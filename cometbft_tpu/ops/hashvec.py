"""Lane-vectorized batch hashing for the host staging fast path.

BENCH_r05 put the 10k-row mixed mega-commit at ~2 ms of device compute
under ~48 ms of host staging — ~5.4 us/row of per-row hashing (SHA-512
challenges for ed25519, Merlin/STROBE transcripts for sr25519). This
module turns that per-row work into batch-axis work:

  sha512_many / sha512_rows   N digests per call, inputs grouped by padded
                              block count (commit sign-bytes are near-
                              uniform length, so one group dominates)
  keccak_f1600_many           N Keccak states advanced under ONE
                              permutation call — the engine behind the
                              batch STROBE transcript in
                              crypto/sr25519_math.py
  reduce512_mod_l             vectorized Barrett reduction of N 512-bit
                              digests mod the ed25519 group order L,
                              emitting the (N, 8) uint32 word layout the
                              device kernels consume (no per-row
                              int.from_bytes/%/to_bytes round trip)

Rung ladder (per core, measured on the dev box, selected per call):

  native   8-lane SIMD C (native/hashvec.c, GCC vector extensions,
           ISA picked from /proc/cpuinfo): 92 ns/row/permutation,
           166 ns/row for a 2-block SHA-512 — the production rung.
  numpy    the batch-axis numpy uint64 implementation in this file —
           bit-for-bit equal, always available. For Keccak it is ~40x
           the pure-Python per-row path (the no-toolchain rung); for
           SHA-512 OpenSSL's serial hashlib outruns it on small hosts,
           so auto mode prefers serial there.
  serial   per-row hashlib / Strobe128 — ragged stragglers and tiny
           groups, and the reference the equality tests pin against.

CBFT_HASHVEC=auto|native|numpy|serial forces a rung (tests use this to
pin the numpy reference); auto is measurement-driven as above. Every
rung is bit-for-bit identical — tests/test_hashvec.py fuzzes all three
against hashlib.sha512 and the serial Keccak over randomized lengths and
batch sizes.
"""

from __future__ import annotations

import ctypes
import hashlib
import os
import threading

import numpy as np

# below this many rows a group takes the serial rung: per-row native hash
# calls beat numpy/ctypes call overhead for a handful of stragglers
VEC_MIN_ROWS = 8

_M64 = np.uint64(0xFFFFFFFFFFFFFFFF)

# ---------------------------------------------------------------- native rung


def _isa_cflags() -> tuple:
    """Compiler-flag ladder for native/hashvec.c, widest ISA first. The
    ISA is read from /proc/cpuinfo (not -march=native: virtualized hosts
    hide the model and gcc then picks a narrow baseline)."""
    flags = ""
    try:
        with open("/proc/cpuinfo") as f:
            for line in f:
                if line.startswith("flags"):
                    flags = line
                    break
    except OSError:
        pass
    ladder = []
    if " avx512f" in flags and " avx512dq" in flags:
        ladder.append(("-O3", "-mavx512f", "-mavx512dq"))
    if " avx2" in flags:
        ladder.append(("-O3", "-mavx2"))
    ladder.append(("-O3",))
    return tuple(ladder)


def _load_native():
    from cometbft_tpu import native

    lib = native.load("hashvec", cflags_ladder=_isa_cflags())
    if lib is None:
        return None
    try:
        lib.keccak_many.argtypes = [ctypes.c_void_p, ctypes.c_long]
        lib.sha512_many.argtypes = [
            ctypes.c_void_p, ctypes.c_long, ctypes.c_long, ctypes.c_void_p]
        lib.reduce512_mod_l_many.argtypes = [
            ctypes.c_void_p, ctypes.c_long, ctypes.c_void_p]
    except AttributeError:
        return None
    return lib


_NATIVE = _load_native()

# ------------------------------------------------------------- rung selection

_VALID_MODES = ("auto", "native", "numpy", "serial")


def _mode() -> str:
    m = os.environ.get("CBFT_HASHVEC", "auto")
    return m if m in _VALID_MODES else "auto"


# path-taken counters (the tier-1 smoke asserts the vectorized path is
# actually taken for a uniform-length commit; microbench reads them too)
_stats_lock = threading.Lock()
_stats: dict[str, int] = {}


def _count(core: str, rung: str, rows: int) -> None:
    with _stats_lock:
        key = f"{core}_{rung}_rows"
        _stats[key] = _stats.get(key, 0) + rows


def stats() -> dict[str, int]:
    with _stats_lock:
        return dict(_stats)


def reset_stats() -> None:
    with _stats_lock:
        _stats.clear()


def native_available() -> bool:
    return _NATIVE is not None


def active_rung() -> str:
    """The rung the next batch hash will ride given the forced mode and
    what loaded: "native" (SIMD C core), "numpy" (batch-axis), or
    "serial" (hashlib/strobe stragglers). Stamped onto staging trace
    spans (libs/trace.py) so a trace shows WHICH hash ladder produced a
    given stage_us."""
    m = _mode()
    if m == "auto":
        return "native" if _NATIVE is not None else "numpy"
    if m == "native" and _NATIVE is None:
        return "numpy"
    return m


# ---------------------------------------------------------------- keccak rung
#
# State layout matches crypto/sr25519_math.keccak_f1600: lane i = x + 5*y,
# little-endian uint64 lanes, as an (N, 25) uint64 array (one row per
# independent sponge).

_KECCAK_RC = np.array([
    0x0000000000000001, 0x0000000000008082, 0x800000000000808A,
    0x8000000080008000, 0x000000000000808B, 0x0000000080000001,
    0x8000000080008081, 0x8000000000008009, 0x000000000000008A,
    0x0000000000000088, 0x0000000080008009, 0x000000008000000A,
    0x000000008000808B, 0x800000000000008B, 0x8000000000008089,
    0x8000000000008003, 0x8000000000008002, 0x8000000000000080,
    0x000000000000800A, 0x800000008000000A, 0x8000000080008081,
    0x8000000000008080, 0x0000000080000001, 0x8000000080008008,
], dtype=np.uint64)

_ROTC = [[0, 36, 3, 41, 18], [1, 44, 10, 45, 2], [62, 6, 43, 15, 61],
         [28, 55, 25, 21, 56], [27, 20, 39, 8, 14]]

# rho+pi fused as one gather + one vector rotate: out[j] = rotl(in[SRC[j]])
_PI_SRC = np.zeros(25, dtype=np.intp)
_RHO = np.zeros((25, 1), dtype=np.uint64)
for _x in range(5):
    for _y in range(5):
        _PI_SRC[_y + 5 * ((2 * _x + 3 * _y) % 5)] = _x + 5 * _y
        _RHO[_y + 5 * ((2 * _x + 3 * _y) % 5), 0] = _ROTC[_x][_y]
# (64 - r) & 63 keeps the r == 0 lane shift-safe: t<<0 | t>>0 == t
_RHO_INV = (np.uint64(64) - _RHO) & np.uint64(63)
_CHI1 = np.array([(i % 5 + 1) % 5 + 5 * (i // 5) for i in range(25)],
                 dtype=np.intp)
_CHI2 = np.array([(i % 5 + 2) % 5 + 5 * (i // 5) for i in range(25)],
                 dtype=np.intp)
_D_IDX = np.array([i % 5 for i in range(25)], dtype=np.intp)
_C_L = np.array([(x - 1) % 5 for x in range(5)], dtype=np.intp)
_C_R = np.array([(x + 1) % 5 for x in range(5)], dtype=np.intp)
_U1 = np.uint64(1)
_U63 = np.uint64(63)


def _keccak_batch_numpy(states: np.ndarray) -> None:
    """In-place Keccak-f[1600] over (N, 25) uint64 states — the batch-axis
    numpy rung (all N sponges advance under one permutation)."""
    a = states.T.copy()  # (25, N): lane-major for whole-lane vector ops
    for r in range(24):
        c = np.bitwise_xor.reduce(a.reshape(5, 5, -1), axis=0)  # theta: (5,N)
        cr = c[_C_R]
        d = c[_C_L] ^ ((cr << _U1) | (cr >> _U63))
        a ^= d[_D_IDX]
        t = a[_PI_SRC]  # rho + pi
        t = (t << _RHO) | (t >> _RHO_INV)
        a = t ^ (~t[_CHI1] & t[_CHI2])  # chi
        a[0] ^= _KECCAK_RC[r]  # iota
    states[:] = a.T


def keccak_f1600_many(states: np.ndarray) -> None:
    """Advance N independent Keccak-f[1600] states (one (N, 25) uint64
    array, modified in place) under one permutation call — native SIMD
    when available, else the numpy batch rung. Bit-for-bit equal to the
    serial crypto/sr25519_math.keccak_f1600 on every state."""
    assert states.dtype == np.uint64 and states.ndim == 2 \
        and states.shape[1] == 25
    n = states.shape[0]
    if n == 0:
        return
    mode = _mode()
    if _NATIVE is not None and mode in ("auto", "native"):
        buf = np.ascontiguousarray(states)
        _NATIVE.keccak_many(buf.ctypes.data, n)
        if buf is not states:
            states[:] = buf
        _count("keccak", "native", n)
        return
    _keccak_batch_numpy(states)
    _count("keccak", "numpy", n)


# --------------------------------------------------------------- SHA-512 rung

_SHA_K = np.array([
    0x428a2f98d728ae22, 0x7137449123ef65cd, 0xb5c0fbcfec4d3b2f,
    0xe9b5dba58189dbbc, 0x3956c25bf348b538, 0x59f111f1b605d019,
    0x923f82a4af194f9b, 0xab1c5ed5da6d8118, 0xd807aa98a3030242,
    0x12835b0145706fbe, 0x243185be4ee4b28c, 0x550c7dc3d5ffb4e2,
    0x72be5d74f27b896f, 0x80deb1fe3b1696b1, 0x9bdc06a725c71235,
    0xc19bf174cf692694, 0xe49b69c19ef14ad2, 0xefbe4786384f25e3,
    0x0fc19dc68b8cd5b5, 0x240ca1cc77ac9c65, 0x2de92c6f592b0275,
    0x4a7484aa6ea6e483, 0x5cb0a9dcbd41fbd4, 0x76f988da831153b5,
    0x983e5152ee66dfab, 0xa831c66d2db43210, 0xb00327c898fb213f,
    0xbf597fc7beef0ee4, 0xc6e00bf33da88fc2, 0xd5a79147930aa725,
    0x06ca6351e003826f, 0x142929670a0e6e70, 0x27b70a8546d22ffc,
    0x2e1b21385c26c926, 0x4d2c6dfc5ac42aed, 0x53380d139d95b3df,
    0x650a73548baf63de, 0x766a0abb3c77b2a8, 0x81c2c92e47edaee6,
    0x92722c851482353b, 0xa2bfe8a14cf10364, 0xa81a664bbc423001,
    0xc24b8b70d0f89791, 0xc76c51a30654be30, 0xd192e819d6ef5218,
    0xd69906245565a910, 0xf40e35855771202a, 0x106aa07032bbd1b8,
    0x19a4c116b8d2d0c8, 0x1e376c085141ab53, 0x2748774cdf8eeb99,
    0x34b0bcb5e19b48a8, 0x391c0cb3c5c95a63, 0x4ed8aa4ae3418acb,
    0x5b9cca4f7763e373, 0x682e6ff3d6b2b8a3, 0x748f82ee5defb2fc,
    0x78a5636f43172f60, 0x84c87814a1f0ab72, 0x8cc702081a6439ec,
    0x90befffa23631e28, 0xa4506cebde82bde9, 0xbef9a3f7b2c67915,
    0xc67178f2e372532b, 0xca273eceea26619c, 0xd186b8c721c0c207,
    0xeada7dd6cde0eb1e, 0xf57d4f7fee6ed178, 0x06f067aa72176fba,
    0x0a637dc5a2c898a6, 0x113f9804bef90dae, 0x1b710b35131c471b,
    0x28db77f523047d84, 0x32caab7b40c72493, 0x3c9ebe0a15c9bebc,
    0x431d67c49c100d4c, 0x4cc5d4becb3e42b6, 0x597f299cfc657e2a,
    0x5fcb6fab3ad6faec, 0x6c44198c4a475817], dtype=np.uint64)

_SHA_H0 = np.array([
    0x6a09e667f3bcc908, 0xbb67ae8584caa73b, 0x3c6ef372fe94f82b,
    0xa54ff53a5f1d36f1, 0x510e527fade682d1, 0x9b05688c2b3e6c1f,
    0x1f83d9abfb41bd6b, 0x5be0cd19137e2179], dtype=np.uint64)


def _rotr(x: np.ndarray, n: int) -> np.ndarray:
    n = np.uint64(n)
    return (x >> n) | (x << (np.uint64(64) - n))


def _sha512_blocks_numpy(w_in: np.ndarray) -> np.ndarray:
    """(N, nb, 16) uint64 big-endian message words -> (N, 8) uint64 final
    state — the batch-axis numpy compression (FIPS 180-4, all N messages
    through each round together)."""
    n, nb, _ = w_in.shape
    h = [np.full(n, _SHA_H0[i], dtype=np.uint64) for i in range(8)]
    for bi in range(nb):
        w = [w_in[:, bi, t].copy() for t in range(16)]
        a, b, c, d, e, f, g, hh = h
        for t in range(80):
            if t >= 16:
                w15 = w[(t - 15) % 16]
                w2 = w[(t - 2) % 16]
                s0 = _rotr(w15, 1) ^ _rotr(w15, 8) ^ (w15 >> np.uint64(7))
                s1 = _rotr(w2, 19) ^ _rotr(w2, 61) ^ (w2 >> np.uint64(6))
                w[t % 16] = w[t % 16] + s0 + w[(t - 7) % 16] + s1
            s1e = _rotr(e, 14) ^ _rotr(e, 18) ^ _rotr(e, 41)
            ch = g ^ (e & (f ^ g))
            t1 = hh + s1e + ch + _SHA_K[t] + w[t % 16]
            s0a = _rotr(a, 28) ^ _rotr(a, 34) ^ _rotr(a, 39)
            mj = (a & (b | c)) | (b & c)
            t2 = s0a + mj
            hh = g; g = f; f = e; e = d + t1  # noqa: E702 - round rotation
            d = c; c = b; b = a; a = t1 + t2  # noqa: E702
        h = [h[0] + a, h[1] + b, h[2] + c, h[3] + d,
             h[4] + e, h[5] + f, h[6] + g, h[7] + hh]
    return np.stack(h, axis=1)


def _sha512_pad(rows: np.ndarray) -> tuple[np.ndarray, int]:
    """(N, L) uint8 same-length messages -> ((N, nb*128) padded buffer,
    nb). FIPS 180-4 padding vectorized across the batch."""
    n, ln = rows.shape
    nb = (ln + 17 + 127) // 128
    buf = np.zeros((n, nb * 128), dtype=np.uint8)
    buf[:, :ln] = rows
    buf[:, ln] = 0x80
    buf[:, -16:] = np.frombuffer((ln * 8).to_bytes(16, "big"), dtype=np.uint8)
    return buf, nb


def _batch_sha512_active() -> bool:
    """Is a batch compression rung (native SIMD or forced numpy) in play?
    In auto mode without the native library, serial OpenSSL is the fastest
    correct rung (the un-fused numpy compression loses to a native serial
    core on memory-traffic amplification — measured on the dev box), so
    batch grouping is skipped entirely."""
    mode = _mode()
    if mode == "native":
        return _NATIVE is not None
    if mode == "numpy":
        return True
    if mode == "serial":
        return False
    return _NATIVE is not None


def _sha512_compress(buf: np.ndarray, nb: int) -> np.ndarray:
    """Padded (N, nb*128) buffer -> (N, 64) uint8 digests via a batch
    rung: native SIMD when available (and not overridden), else the numpy
    batch-axis compression. Callers gate on _batch_sha512_active()."""
    n = buf.shape[0]
    if _NATIVE is not None and _mode() != "numpy":
        buf = np.ascontiguousarray(buf)
        out = np.empty((n, 64), dtype=np.uint8)
        _NATIVE.sha512_many(buf.ctypes.data, n, nb, out.ctypes.data)
        _count("sha512", "native", n)
        return out
    w = buf.reshape(n, nb, 16, 8).view(">u8")[..., 0].astype(np.uint64)
    h = _sha512_blocks_numpy(w)
    _count("sha512", "numpy", n)
    return np.ascontiguousarray(h).astype(">u8").view(np.uint8).reshape(n, 64)


def _sha512_serial(datas, out: np.ndarray, idxs) -> None:
    for i in idxs:
        out[i] = np.frombuffer(
            hashlib.sha512(datas[i]).digest(), dtype=np.uint8)
    _count("sha512", "serial", len(idxs))


def assemble_prefixed_rows(msgs, mlen: int) -> np.ndarray:
    """Reassemble uniform-length messages on the batch axis into an
    (N, mlen) uint8 matrix — the staging-side consumer of the
    shared-prefix wire protocol (libs/prefixrows.py). Runs of
    PrefixedMsg rows sharing the SAME prefix object write the prefix
    ONCE as a broadcast column block and join only their short
    suffixes; plain bytes rows join as before. For a vote flush this
    cuts the host copy from ~122 B/row to ~17 B/row of suffix plus one
    ~105-byte prefix per commit."""
    from cometbft_tpu.libs.prefixrows import PrefixedMsg

    n = len(msgs)
    out = np.empty((n, mlen), dtype=np.uint8)
    i = 0
    while i < n:
        m = msgs[i]
        if isinstance(m, PrefixedMsg):
            p = m.prefix
            j = i
            while (j < n and isinstance(msgs[j], PrefixedMsg)
                   and msgs[j].prefix is p):
                j += 1
            plen = len(p)
            out[i:j, :plen] = np.frombuffer(p, dtype=np.uint8)
            sfx = b"".join(msgs[k].suffix for k in range(i, j))
            out[i:j, plen:] = np.frombuffer(
                sfx, dtype=np.uint8).reshape(j - i, mlen - plen)
        else:
            j = i
            while j < n and not isinstance(msgs[j], PrefixedMsg):
                j += 1
            blob = b"".join(msgs[i:j])
            out[i:j] = np.frombuffer(
                blob, dtype=np.uint8).reshape(j - i, mlen)
        i = j
    return out


def sha512_rows(rows: np.ndarray) -> np.ndarray:
    """(N, L) uint8 same-length messages -> (N, 64) uint8 digests,
    bit-for-bit hashlib.sha512. The uniform-length fast entry used by the
    staging paths (vote sign-bytes within a commit share one length)."""
    n = rows.shape[0]
    if n == 0:
        return np.zeros((0, 64), dtype=np.uint8)
    if not _batch_sha512_active() or n < VEC_MIN_ROWS:
        out = np.empty((n, 64), dtype=np.uint8)
        blob = np.ascontiguousarray(rows).tobytes()
        ln = rows.shape[1]
        for i in range(n):
            out[i] = np.frombuffer(
                hashlib.sha512(blob[i * ln:(i + 1) * ln]).digest(),
                dtype=np.uint8)
        _count("sha512", "serial", n)
        return out
    buf, nb = _sha512_pad(rows)
    return _sha512_compress(buf, nb)


def sha512_many(datas: list[bytes]) -> np.ndarray:
    """N messages of any lengths -> (N, 64) uint8 digests. Rows are
    grouped by padded block count and each group compressed in one
    batch call; groups below VEC_MIN_ROWS (ragged stragglers) take the
    serial hashlib rung."""
    n = len(datas)
    out = np.empty((n, 64), dtype=np.uint8)
    if n == 0:
        return out
    if not _batch_sha512_active():
        _sha512_serial(datas, out, range(n))
        return out
    lens = set(map(len, datas))
    if len(lens) == 1:  # the dominant commit shape: skip per-row grouping
        ln = lens.pop()
        rows = np.frombuffer(b"".join(datas), dtype=np.uint8)
        return sha512_rows(rows.reshape(n, ln) if ln else
                           np.zeros((n, 0), dtype=np.uint8))
    by_nb: dict[int, dict[int, list[int]]] = {}
    for i, d in enumerate(datas):
        nb = (len(d) + 17 + 127) // 128
        by_nb.setdefault(nb, {}).setdefault(len(d), []).append(i)
    for nb, by_len in by_nb.items():
        group_rows = sum(len(v) for v in by_len.values())
        if group_rows < VEC_MIN_ROWS:
            for idxs in by_len.values():
                _sha512_serial(datas, out, idxs)
            continue
        bufs, order = [], []
        for ln, idxs in by_len.items():
            flat = np.frombuffer(
                b"".join(datas[i] for i in idxs), dtype=np.uint8)
            buf, _ = _sha512_pad(flat.reshape(len(idxs), ln))
            bufs.append(buf)
            order.extend(idxs)
        digests = _sha512_compress(
            bufs[0] if len(bufs) == 1 else np.concatenate(bufs), nb)
        out[np.asarray(order, dtype=np.intp)] = digests
    return out


# --------------------------------------------------- Barrett reduction mod L
#
# k = digest mod L for N 512-bit little-endian digests at once, emitting
# the packed (N, 8) uint32 little-endian word layout the device kernels
# consume. Base-2^16 limbs in uint64 (products < 2^32, 17-term
# accumulations < 2^37 — no overflow), HAC Algorithm 14.42 with k = 16
# limbs: q3 = floor(floor(x / b^15) * mu / b^17), r = x - q3*L mod b^17,
# then at most two conditional subtractions of L.

from cometbft_tpu.crypto.ed25519_math import L as L_ED25519  # noqa: E402

_BARRETT_MU = (1 << 512) // L_ED25519  # 261 bits -> 17 base-2^16 limbs


def _to_limbs16(x: int, n: int) -> np.ndarray:
    return np.array([(x >> (16 * i)) & 0xFFFF for i in range(n)],
                    dtype=np.uint64)


_MU17 = _to_limbs16(_BARRETT_MU, 17)
_L17 = _to_limbs16(L_ED25519, 17)
_U16MASK = np.uint64(0xFFFF)
_U16 = np.uint64(16)
_U63SIGN = np.uint64(63)


def _carry16(acc: np.ndarray) -> np.ndarray:
    """Propagate base-2^16 carries along the limb axis of a limb-major
    (limbs, N) accumulator (values < 2^48 per limb on entry; canonical
    < 2^16 limbs on exit; overflow off the top limb dropped — callers
    size the array so it cannot occur or want mod-b^n semantics)."""
    c = np.zeros(acc.shape[1], dtype=np.uint64)
    for j in range(acc.shape[0]):
        t = acc[j] + c
        acc[j] = t & _U16MASK
        c = t >> _U16
    return acc


def _reduce512_mod_l_numpy(digests: np.ndarray) -> np.ndarray:
    """The batch-axis numpy Barrett rung (limb-major (17, N) layout so
    every per-limb op runs on a contiguous row)."""
    n = digests.shape[0]
    x = np.ascontiguousarray(digests).view("<u2").astype(np.uint64).T  # (32,N)
    q1 = x[15:]  # floor(x / b^15): 17 limbs
    q2 = np.zeros((34, n), dtype=np.uint64)
    for i in range(17):
        q2[i:i + 17] += q1 * _MU17[i]
    _carry16(q2)
    q3 = q2[17:]  # floor(q2 / b^17): 17 limbs
    r2 = np.zeros((17, n), dtype=np.uint64)  # q3*L mod b^17
    for i in range(17):
        if _L17[i]:
            r2[i:] += q3[:17 - i] * _L17[i]
    _carry16(r2)
    # r = x - r2 mod b^17 (limb-wise borrow chain, top borrow dropped);
    # the uint64 sign bit flags a wrapped (negative) limb difference
    r = np.zeros((17, n), dtype=np.uint64)
    borrow = np.zeros(n, dtype=np.uint64)
    for j in range(17):
        t = x[j] - r2[j] - borrow
        r[j] = t & _U16MASK
        borrow = t >> _U63SIGN
    # Barrett guarantees r < 3L: at most two conditional subtractions
    for _ in range(2):
        t = np.zeros_like(r)
        borrow = np.zeros(n, dtype=np.uint64)
        for j in range(17):
            d = r[j] - _L17[j] - borrow
            t[j] = d & _U16MASK
            borrow = d >> _U63SIGN
        ge = borrow == 0  # no final borrow: r >= L, take the difference
        r[:, ge] = t[:, ge]
    return np.ascontiguousarray(
        r[:16].T.astype(np.uint16)).view("<u4").reshape(n, 8)


def reduce512_mod_l(digests: np.ndarray) -> np.ndarray:
    """(N, 64) uint8 little-endian 512-bit values -> (N, 8) uint32
    little-endian words of (value mod L), bit-for-bit equal to
    int.from_bytes(d, "little") % L. Barrett reduction: native __int128
    rung when available, else the vectorized numpy rung."""
    n = digests.shape[0]
    if n == 0:
        return np.zeros((0, 8), dtype=np.uint32)
    if _NATIVE is not None and _mode() in ("auto", "native"):
        buf = np.ascontiguousarray(digests)
        out = np.empty((n, 8), dtype=np.uint32)
        _NATIVE.reduce512_mod_l_many(buf.ctypes.data, n, out.ctypes.data)
        return out
    return _reduce512_mod_l_numpy(digests)


def sha512_mod_l_words(datas: list[bytes]) -> np.ndarray:
    """SHA-512 digests reduced mod L as packed device words: the whole
    ed25519 challenge pipeline (hash -> wide reduction -> wire words) in
    three batch calls."""
    return reduce512_mod_l(sha512_many(datas))


# ------------------------------------------------------------- SHA-256 rung
#
# The BLS hash-to-curve pipeline (ops/bls12381/htc.py expand_message_xmd)
# hashes with SHA-256. Today the only rung is serial hashlib — SHA-256's
# host cost is a rounding error next to the pairing math it feeds, and
# each expand_message round is already batched ACROSS messages by the
# caller (9 sha256_many calls per batch instead of 9*N hashlib calls).
# When profiling ever shows this on a flush's critical path, the
# batch-axis rung follows _sha512_blocks_numpy with 32-bit words and
# K-constants — the structure above is the template.


def sha256_many(datas: list[bytes]) -> np.ndarray:
    """N messages -> (N, 32) uint8 digests, bit-for-bit hashlib.sha256;
    counted on the shared rung-stats surface like the sha512 cores."""
    n = len(datas)
    out = np.empty((n, 32), dtype=np.uint8)
    for i, d in enumerate(datas):
        out[i] = np.frombuffer(hashlib.sha256(d).digest(), dtype=np.uint8)
    if n:
        _count("sha256", "serial", n)
    return out
