"""Shared kernel-dispatch lock.

The Pallas Ed25519 kernel trace temporarily swaps the field/curve module
constants for VMEM refs (pallas_verify._verify_block_kernel). ANY other
trace that reads those module globals — the sr25519 XLA ladder, the
ed25519 XLA fallback — must never interleave with that swap, or it bakes
another kernel's refs/tracers into its compiled program. Every jit
dispatch of a curve kernel therefore serializes on this one lock
(compiled-cache dispatch under the lock is sub-ms; the expensive
host<->device transfers stay outside it).
"""

from __future__ import annotations

import threading

KERNEL_DISPATCH_LOCK = threading.Lock()
