"""Shared kernel-dispatch lock.

The Pallas Ed25519 kernel trace temporarily swaps the field/curve module
constants for VMEM refs (pallas_verify._verify_block_kernel). ANY other
trace that reads those module globals — the sr25519 XLA ladder, the
ed25519 XLA fallback — must never interleave with that swap, or it bakes
another kernel's refs/tracers into its compiled program. Every jit
dispatch of a curve kernel therefore serializes on this one lock
(compiled-cache dispatch under the lock is sub-ms; the expensive
host<->device transfers stay outside it).
"""

from __future__ import annotations

import threading

KERNEL_DISPATCH_LOCK = threading.Lock()


class PallasGate:
    """The one dispatch policy for a Pallas kernel with an XLA fallback:
    lane-aligned batches go to Pallas while it works; the first Mosaic
    failure permanently disables it (a failing trace costs seconds — never
    pay it per batch). Callers hold KERNEL_DISPATCH_LOCK around run()."""

    def __init__(self) -> None:
        self.broken = False

    def run(self, pallas_fn, xla_fn, args, lane_count: int):
        from cometbft_tpu.ops import pallas_verify as PV
        from cometbft_tpu.ops.ed25519_kernel import _pallas_available

        if (not self.broken and _pallas_available()
                and lane_count % PV.LANES == 0):
            try:
                return pallas_fn(*args)
            except Exception:  # noqa: BLE001 - Mosaic/backend failure
                self.broken = True
        return xla_fn(*args)
