"""Shared kernel-dispatch lock + the device-fault supervision layer.

The Pallas Ed25519 kernel trace temporarily swaps the field/curve module
constants for VMEM refs (pallas_verify._verify_block_kernel). ANY other
trace that reads those module globals — the sr25519 XLA ladder, the
ed25519 XLA fallback — must never interleave with that swap, or it bakes
another kernel's refs/tracers into its compiled program. Every jit
dispatch of a curve kernel therefore serializes on this one lock
(compiled-cache dispatch under the lock is sub-ms; the expensive
host<->device transfers stay outside it).

Supervision (the device-fault resilience layer): the node's hot path lives
on an accelerator that can time out, OOM, lose its Mosaic compile, or
vanish behind a contended tunnel. Instead of the old one-way `broken`
latch, every device operation runs under a DeviceSupervisor:

  classify   transient (XlaRuntimeError RESOURCE_EXHAUSTED/UNAVAILABLE,
             timeouts) vs permanent (Mosaic/lowering death)
  retry      transients retry with capped exponential backoff + jitter
  break      N consecutive failed operations (or one permanent) open a
             circuit breaker — new batches skip the device entirely
  re-probe   after `cooldown` the breaker half-opens and ONE batch probes
             the device; success closes the breaker and reclaims the
             device, failure re-opens it

The supervisor only decides *whether* the device is used; the verify
ladder TPU (Pallas) -> XLA -> CPU (exact host oracle) does the falling
back, in ops/ed25519_kernel.py / ops/sr25519_kernel.py and
crypto/batch.resolve_backend. Fault injection for all of this lives in
libs/chaos.py.
"""

from __future__ import annotations

import random
import threading
import time

from cometbft_tpu.libs import trace as _trace

KERNEL_DISPATCH_LOCK = threading.Lock()

# failure classes
TRANSIENT = "transient"
PERMANENT = "permanent"
TIMEOUT = "timeout"

# breaker states (gauge encoding: the wire values are part of the
# metrics/RPC contract, keep in sync with README)
CLOSED = "closed"
HALF_OPEN = "half_open"
OPEN = "open"
_STATE_GAUGE = {CLOSED: 0, HALF_OPEN: 1, OPEN: 2}


class DeviceUnavailable(Exception):
    """Breaker open: the device is sidelined until the next re-probe."""


class DeviceOpFailed(Exception):
    """A supervised device operation failed (after retries). The original
    exception rides __cause__; the supervisor has already recorded it —
    catchers fall back without double-counting."""


# transient markers in XlaRuntimeError/RuntimeError text (gRPC-style codes
# the PJRT client surfaces for contended/hung/OOM devices)
_TRANSIENT_MARKERS = (
    "RESOURCE_EXHAUSTED", "DEADLINE_EXCEEDED", "UNAVAILABLE", "ABORTED",
    "CANCELLED", "connection reset", "timed out", "temporarily",
)
# permanent markers: a failing Mosaic trace/lowering costs seconds and
# will fail the same way every time for this program shape
_PERMANENT_MARKERS = (
    "Mosaic", "mosaic", "lowering", "Unsupported", "NOT_FOUND",
    "UNIMPLEMENTED", "INVALID_ARGUMENT",
)


def classify_failure(exc: BaseException) -> str:
    """Map a device-op exception to a failure class. Unknown errors count
    as transient: a flapping tunnel produces novel error text, and the
    breaker bounds how long we keep trying."""
    from cometbft_tpu.libs import chaos

    if isinstance(exc, chaos.ChaosPermanentError):
        return PERMANENT
    if isinstance(exc, chaos.ChaosTransientError):
        return TRANSIENT
    if isinstance(exc, (chaos.ChaosTimeout, TimeoutError)):
        return TIMEOUT
    try:  # concurrent.futures.TimeoutError is TimeoutError on 3.11+, not 3.10
        import concurrent.futures as _cf

        if isinstance(exc, _cf.TimeoutError):
            return TIMEOUT
    except ImportError:  # pragma: no cover
        pass
    text = f"{type(exc).__name__}: {exc}"
    if any(m in text for m in _PERMANENT_MARKERS):
        return PERMANENT
    if any(m in text for m in _TRANSIENT_MARKERS):
        return TRANSIENT
    return TRANSIENT


def _metrics():
    """Lazy process-global CryptoMetrics; never raises (metrics must not
    break verification)."""
    try:
        from cometbft_tpu.libs import metrics as m

        return m.crypto_metrics()
    except Exception:  # noqa: BLE001
        return None


class CircuitBreaker:
    """closed -> (N consecutive failures | 1 permanent) -> open ->
    (cooldown elapses) -> half_open -> one probe -> closed | open."""

    def __init__(self, name: str, failure_threshold: int = 3,
                 cooldown: float = 30.0, clock=time.monotonic):
        self.name = name
        self.failure_threshold = failure_threshold
        self.cooldown = cooldown
        self._clock = clock
        self._lock = threading.Lock()
        self._state = CLOSED
        self._consecutive = 0
        self._opened_at = 0.0
        self._probe_inflight = False
        self._publish(CLOSED, transition=False)

    def _publish(self, state: str, transition: bool = True) -> None:
        if transition:
            # breaker flips land in the flight recorder as instant events:
            # a trace showing a fetch stall next to `breaker.open` answers
            # "did the device die or did the wire?" without log archaeology
            _trace.event(f"breaker.{state}", cat="device", breaker=self.name)
        m = _metrics()
        if m is None:
            return
        try:
            m.breaker_state.labels(self.name).set(_STATE_GAUGE[state])
            if transition:
                m.breaker_transitions.labels(self.name, state).inc()
        except Exception:  # noqa: BLE001
            pass

    @property
    def state(self) -> str:
        with self._lock:
            return self._state

    @property
    def consecutive_failures(self) -> int:
        with self._lock:
            return self._consecutive

    def allow(self) -> bool:
        """Claim permission for a device operation. An OPEN breaker whose
        cooldown has elapsed half-opens and admits the caller as THE probe;
        while that probe is in flight every other caller is refused — one
        batch tests a possibly-dead device, not a whole blocksync window.
        Read-only callers (health snapshots, backend resolution at staging
        time) must use peek() instead: allow() is a state transition."""
        with self._lock:
            if self._state == OPEN:
                if self._clock() - self._opened_at < self.cooldown:
                    return False
                self._state = HALF_OPEN
                self._probe_inflight = True
                self._publish(HALF_OPEN)
                return True
            if self._state == HALF_OPEN:
                if self._probe_inflight:
                    return False
                self._probe_inflight = True
            return True

    def peek(self) -> bool:
        """Would a device operation be admitted now? No transitions, no
        probe claim — safe for health snapshots and staging decisions."""
        with self._lock:
            if self._state == OPEN:
                return self._clock() - self._opened_at >= self.cooldown
            if self._state == HALF_OPEN:
                return not self._probe_inflight
            return True

    def record_success(self) -> None:
        with self._lock:
            self._consecutive = 0
            self._probe_inflight = False
            if self._state != CLOSED:
                self._state = CLOSED
                self._publish(CLOSED)

    def record_failure(self, fclass: str) -> None:
        """A failed operation (retries exhausted). Permanent failures and a
        failed half-open probe open immediately; transients open at the
        threshold."""
        with self._lock:
            self._consecutive += 1
            self._probe_inflight = False
            opens = (
                fclass == PERMANENT
                or self._state == HALF_OPEN
                or self._consecutive >= self.failure_threshold
            )
            if opens and self._state != OPEN:
                self._state = OPEN
                self._opened_at = self._clock()
                self._publish(OPEN)
            elif self._state == OPEN:
                self._opened_at = self._clock()  # failed probe: restart timer

    def health(self) -> dict:
        with self._lock:
            out = {
                "state": self._state,
                "consecutive_failures": self._consecutive,
                "failure_threshold": self.failure_threshold,
                "cooldown_seconds": self.cooldown,
            }
            if self._state == OPEN:
                out["reprobe_in_seconds"] = round(
                    max(0.0, self.cooldown - (self._clock() - self._opened_at)), 3)
            return out


class DeviceSupervisor:
    """Retry/backoff + breaker + bookkeeping around one class of device
    operation. `sleep`/`clock` are injectable so chaos tests run on a fake
    timeline."""

    def __init__(self, name: str, failure_threshold: int = 3,
                 cooldown: float = 30.0, retry_attempts: int = 2,
                 retry_base: float = 0.05, retry_cap: float = 1.0,
                 sleep=time.sleep, clock=time.monotonic):
        self.name = name
        self.breaker = CircuitBreaker(
            name, failure_threshold=failure_threshold, cooldown=cooldown,
            clock=clock)
        self.retry_attempts = retry_attempts
        self.retry_base = retry_base
        self.retry_cap = retry_cap
        self._sleep = sleep
        self._lock = threading.Lock()
        self.retries = 0
        self.failures = 0
        self.successes = 0
        self.last_error: str | None = None

    # ------------------------------------------------------------ stats

    def _count_retry(self) -> None:
        with self._lock:
            self.retries += 1
        _trace.event("device.retry", cat="device", supervisor=self.name)
        m = _metrics()
        if m is not None:
            try:
                m.device_retries.labels(self.name).inc()
            except Exception:  # noqa: BLE001
                pass

    def _count_failure(self, fclass: str, exc: BaseException) -> None:
        with self._lock:
            self.failures += 1
            self.last_error = f"{fclass}: {type(exc).__name__}: {exc}"
        m = _metrics()
        if m is not None:
            try:
                m.device_failures.labels(self.name, fclass).inc()
            except Exception:  # noqa: BLE001
                pass

    # -------------------------------------------------------------- run

    def run(self, fn, *args, **kwargs):
        """Run fn under supervision. Raises DeviceUnavailable (breaker open,
        nothing attempted) or DeviceOpFailed (attempted and failed; already
        recorded). Success resets the breaker."""
        if not self.breaker.allow():
            raise DeviceUnavailable(self.name)
        attempt = 0
        while True:
            try:
                out = fn(*args, **kwargs)
            except Exception as exc:  # noqa: BLE001 - classified below
                fclass = classify_failure(exc)
                if fclass == TRANSIENT and attempt < self.retry_attempts:
                    self._count_retry()
                    delay = min(self.retry_cap, self.retry_base * (2 ** attempt))
                    self._sleep(delay * (0.5 + random.random() / 2))
                    attempt += 1
                    continue
                self._count_failure(fclass, exc)
                self.breaker.record_failure(fclass)
                try:
                    from cometbft_tpu.libs import log as _log

                    _log.default().error(
                        "supervised device operation failed",
                        supervisor=self.name, failure_class=fclass,
                        attempts=str(attempt + 1),
                        breaker=self.breaker.state, err=str(exc))
                except Exception:  # noqa: BLE001
                    pass
                raise DeviceOpFailed(
                    f"{self.name}: {fclass} device failure "
                    f"after {attempt + 1} attempt(s)") from exc
            with self._lock:
                self.successes += 1
            self.breaker.record_success()
            return out

    def record_op_failure(self, exc: BaseException) -> str:
        """Record a failure observed outside run() (e.g. a watchdog timeout
        on the fetch side). Returns the failure class."""
        fclass = classify_failure(exc)
        self._count_failure(fclass, exc)
        self.breaker.record_failure(fclass)
        return fclass

    def health(self) -> dict:
        with self._lock:
            out = {
                "retries": self.retries,
                "failures": self.failures,
                "successes": self.successes,
                "last_error": self.last_error,
            }
        out["breaker"] = self.breaker.health()
        return out


# ---------------------------------------------------------------------------
# process-global supervisor registry + knobs (configured from
# config.crypto at node boot; tests poke configure() directly)
# ---------------------------------------------------------------------------

_config = {
    "failure_threshold": 3,
    "cooldown": 30.0,
    "retry_attempts": 2,
    "retry_base": 0.05,
    "retry_cap": 1.0,
    # must comfortably cover a COLD first-dispatch compile (Mosaic traces
    # run tens of seconds; the per-call watchdog cannot tell compile from
    # hang) while still bounding a wedged fetch to well under a blocksync
    # window retry
    "watchdog_timeout": 120.0,
    # Pallas gets a longer leash: a failed Mosaic trace costs seconds, so
    # re-probe it an order of magnitude less often than the XLA/device path
    "pallas_cooldown": 300.0,
}

_registry_lock = threading.Lock()
_supervisors: dict[str, DeviceSupervisor] = {}


def configure(**kwargs) -> None:
    """Set supervision knobs (unknown keys rejected). Existing supervisors
    pick up the new values in place so a node reconfig (or a test) does not
    orphan live breakers."""
    with _registry_lock:
        for k, v in kwargs.items():
            if k not in _config:
                raise ValueError(f"unknown supervision knob {k!r}")
            _config[k] = v
        for name, sup in _supervisors.items():
            pallas = name.startswith("pallas")
            sup.breaker.failure_threshold = _config["failure_threshold"]
            sup.breaker.cooldown = (
                _config["pallas_cooldown"] if pallas else _config["cooldown"])
            # pallas rungs never retry in place: a transient re-runs as XLA
            # now and Pallas is re-probed on the next aligned batch
            sup.retry_attempts = 0 if pallas else _config["retry_attempts"]
            sup.retry_base = _config["retry_base"]
            sup.retry_cap = _config["retry_cap"]


def watchdog_timeout() -> float:
    return _config["watchdog_timeout"]


def supervisor(name: str) -> DeviceSupervisor:
    with _registry_lock:
        sup = _supervisors.get(name)
        if sup is None:
            pallas = name.startswith("pallas")
            sup = DeviceSupervisor(
                name,
                failure_threshold=_config["failure_threshold"],
                cooldown=(_config["pallas_cooldown"] if pallas
                          else _config["cooldown"]),
                retry_attempts=0 if pallas else _config["retry_attempts"],
                retry_base=_config["retry_base"],
                retry_cap=_config["retry_cap"],
            )
            _supervisors[name] = sup
        return sup


def device_allowed() -> bool:
    """May a NEW batch target the device? Side-effect-free peek: False
    while the device breaker is open or another probe is mid-flight
    (crypto/batch.resolve_backend degrades to the CPU ladder on this).
    The authoritative probe CLAIM happens inside DeviceSupervisor.run via
    breaker.allow() — health snapshots and staging decisions polling this
    never change failover state."""
    return supervisor("device").breaker.peek()


def reset_supervision() -> None:
    """Forget breakers/counters (tests; a fresh process state)."""
    with _registry_lock:
        _supervisors.clear()
    with _doublebuf_lock:
        _doublebufs.clear()


# ---------------------------------------------------------------------------
# double-buffered dispatch gate
# ---------------------------------------------------------------------------


def _release_once(fn):
    lock = threading.Lock()
    state = {"done": False}

    def release() -> None:
        with lock:
            if state["done"]:
                return
            state["done"] = True
        fn()

    return release


class DoubleBuffer:
    """Two-slot in-flight gate per fault domain — the dispatch-side half of
    the StagingPool double-buffer contract (ops/limbs.py). A batch acquires
    a slot BEFORE its h2d transfer and releases it as soon as its verify
    dispatch is enqueued (the slot is scoped inside the dispatch closure,
    never held to batch resolution — an abandoned thunk must not wedge the
    gate), so with two slots batch N's host->device transfer overlaps
    batch N-1's compute while batch N+2 queues behind the gate: bounded
    in-flight staging, overlap by construction, no unbounded donated-buffer
    growth.

    Fault seam: chaos site `dispatch.doublebuf` fires at acquire. An
    injected fault (a poisoned donated buffer) records against the domain's
    `doublebuf.<domain>` supervisor and degrades the gate to SERIALIZED
    single-buffer dispatch (one batch in flight end-to-end) while the
    breaker is not admitting — overlap lost, verdicts untouched — and the
    normal half-open schedule restores double-buffering. acquire() never
    raises: a buffer-gate fault must degrade, not fail the batch."""

    def __init__(self, domain: str, slots: int = 2) -> None:
        self.domain = domain
        self.slots = slots
        self._sem = threading.BoundedSemaphore(slots)
        self._serial = threading.Lock()
        self._lock = threading.Lock()
        self.acquires = 0
        self.waits = 0
        self.degraded = 0

    def acquire(self):
        """Block until a slot is free; returns a one-shot release callable
        (safe to call from any thread, extra calls are no-ops)."""
        from cometbft_tpu.libs import chaos

        sup = supervisor(f"doublebuf.{self.domain}")
        degraded = False
        try:
            chaos.fire("dispatch.doublebuf")
            if sup.breaker.allow():
                sup.breaker.record_success()
            else:
                degraded = True
        except Exception as exc:  # noqa: BLE001 - injected/poisoned buffer
            sup.record_op_failure(exc)
            degraded = True
        with self._lock:
            self.acquires += 1
            if degraded:
                self.degraded += 1
        if degraded:
            self._serial.acquire()
            return _release_once(self._serial.release)
        if not self._sem.acquire(blocking=False):
            with self._lock:
                self.waits += 1
            self._sem.acquire()
        return _release_once(self._sem.release)

    def stats(self) -> dict:
        with self._lock:
            return {"slots": self.slots, "acquires": self.acquires,
                    "waits": self.waits, "degraded": self.degraded}


_doublebuf_lock = threading.Lock()
_doublebufs: dict[str, DoubleBuffer] = {}


def doublebuffer(domain: str = "dev0") -> DoubleBuffer:
    """The per-fault-domain dispatch gate (single-chip kernels use dev0;
    the mesh keys one per chip)."""
    with _doublebuf_lock:
        db = _doublebufs.get(domain)
        if db is None:
            db = DoubleBuffer(domain)
            _doublebufs[domain] = db
        return db


def doublebuffer_stats() -> dict:
    with _doublebuf_lock:
        return {d: db.stats() for d, db in _doublebufs.items()}


def _mesh_health() -> dict:
    """The mesh section of crypto_health; never raises (health must
    render even when jax/device discovery is mid-import or broken)."""
    try:
        from cometbft_tpu.parallel import mesh as _mesh

        return _mesh.health_snapshot()
    except Exception:  # noqa: BLE001
        return {"enabled": False, "built": False}


def health_snapshot() -> dict:
    """The RPC-visible crypto-health snapshot (rpc crypto_health route)."""
    from cometbft_tpu import sched
    from cometbft_tpu.crypto import batch as crypto_batch
    from cometbft_tpu.libs import chaos

    from cometbft_tpu.libs import linkmodel as _linkmodel

    with _registry_lock:
        sups = dict(_supervisors)
    snap = {
        "configured_backend": crypto_batch.get_backend(),
        "active_backend": crypto_batch.resolve_backend(),
        "watchdog_timeout_seconds": _config["watchdog_timeout"],
        "supervisors": {name: sup.health() for name, sup in sups.items()},
        "chaos": chaos.snapshot(),
        # the verify plane's batching layer: producers feed the global
        # scheduler, the scheduler feeds these supervisors
        "verify_sched": sched.health_snapshot(),
        # the multi-chip plane (parallel/mesh.py): live mesh size,
        # per-chip fault-domain breaker states, eviction/readmission/
        # redispatch churn, all-chips-dead fallback count
        "mesh": _mesh_health(),
        # rolling per-batch wall-time attribution (libs/trace.py): stage-
        # share percentages + measured bytes-per-sig — the number the
        # mesh / reduced-send PRs are judged against
        "attribution": _trace.attribution(),
        # live host<->device link model (libs/linkmodel.py): EWMA
        # bandwidth/RTT fed by the kernels' measured h2d/d2h transfers —
        # replaces the hand-measured "~22 MB/s, ~89 ms" tunnel constants
        "tunnel": _linkmodel.tunnel().snapshot(),
    }
    try:
        # staging plane: hash rung usage, reduced-fetch happy/full split,
        # pubkey cache hit rates, staging-buffer pool reuse
        from cometbft_tpu.ops import ed25519_kernel as _ek
        from cometbft_tpu.ops import hashvec as _hv
        from cometbft_tpu.ops import limbs as _limbs
        from cometbft_tpu.ops import residency as _residency

        snap["staging"] = {
            "hashvec_native": _hv.native_available(),
            "hashvec_rows": _hv.stats(),
            "fetch": _ek.fetch_stats(),
            # send-side twin of `fetch` (reduced-send protocol): per-path
            # wire accounting + steady-state bytes/sig + per-replica
            # validator-table counters
            "wire": _residency.stats(),
            "pubkey_cache": _ek.cache_stats(),
            "staging_pool": _limbs.POOL.stats(),
            # the dispatch-side half of the double-buffer contract:
            # per-fault-domain slot acquires/waits/degraded counts
            "doublebuf": doublebuffer_stats(),
        }
        # device-challenge plane (ops/challenge.py): plans, per-lane
        # device/host split, degradation reasons, prefix-table churn
        from cometbft_tpu.ops import challenge as _challenge

        snap["staging"]["challenge"] = {
            "enabled": _challenge.enabled(),
            "counters": _challenge.stats(),
            "tables": _challenge.table_stats(),
        }
    except Exception:  # noqa: BLE001 - health must render even mid-import
        pass
    return snap


class PallasGate:
    """Dispatch policy for a Pallas kernel with an XLA fallback: lane-aligned
    batches go to Pallas while its breaker is closed; a Mosaic failure opens
    the breaker (a failing trace costs seconds — never pay it per batch) and
    the half-open schedule re-probes, so a recovered device is reclaimed
    instead of abandoned for the process lifetime. Callers hold
    KERNEL_DISPATCH_LOCK around run()."""

    def __init__(self, name: str = "pallas") -> None:
        self.name = name

    @property
    def supervisor(self) -> DeviceSupervisor:
        return supervisor(self.name)

    @property
    def broken(self) -> bool:
        """Back-compat view of the old one-way latch (bench.py reads it):
        True while the breaker is sidelining Pallas — open, or half-open
        with the probe already claimed."""
        return not self.supervisor.breaker.peek()

    def run(self, pallas_fn, xla_fn, args, lane_count: int):
        from cometbft_tpu.libs import chaos
        from cometbft_tpu.ops import pallas_verify as PV
        from cometbft_tpu.ops.ed25519_kernel import _pallas_available

        if _pallas_available() and lane_count % PV.LANES == 0:
            def _probe():
                chaos.fire("pallas.trace")
                return pallas_fn(*args)

            try:
                # pallas supervisors are created with retry_attempts=0 (see
                # supervisor()): a transient re-runs as XLA below and
                # Pallas is re-probed on the next aligned batch
                return self.supervisor.run(_probe)
            except (DeviceUnavailable, DeviceOpFailed):
                pass
        return xla_fn(*args)
