"""Pallas TPU kernel for the Ed25519 ZIP-215 batch-verify ladder.

Same math as ed25519_kernel.verify_math, but executed as ONE fused device
program per 128-lane block with every intermediate held in VMEM. The
XLA-compiled ladder materializes each field-op result to HBM (a (20, B)
int32 array per op, ~2.6k field muls per verify), which makes the kernel
HBM-bound ~20x off the VPU roofline; the Pallas version streams each block
of signatures through VMEM once: reads 4x(20,128) A-coords, one (8,128)
packed R block and 2x(51,128) signed window digits, writes a (1,128) mask,
and does the entire signed-window double-scalar ladder + R decompression
in on-chip memory.

Ladder: 51 windows of signed 5-bit digits — 5 doublings (4 of them
skipping the unused T output) + a mixed premultiplied-T base add + a
premultiplied-T point add per window (curve.windowed_double_scalar_signed
is the shape-polymorphic source of truth; the kernel body inlines its loop
so Mosaic sees a flat fori_loop).

The kernel body reuses the shape-polymorphic field/curve jnp code
(field.py, curve.py) — Pallas traces it onto Mosaic. Pallas forbids
closing over device constants, so the field constants (M_SUB, D2, the
17-entry [d]B window table, ...) enter as broadcast kernel inputs and are
swapped into the field/curve modules for the duration of the
(single-threaded) kernel trace. Signed digit recoding runs as a tiny XLA
prelude (unpack.words_to_digits5_signed) — its 51-step carry scan is
hostile to the fused kernel but trivial for XLA.

Reference seam: crypto/ed25519/ed25519.go:208-241 (curve25519-voi batch
verifier) — this is its device replacement.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from cometbft_tpu.ops import curve
from cometbft_tpu.ops import field as F
from cometbft_tpu.ops import unpack as U

LANES = 128  # one VPU lane row per block; VMEM use ~3 MB/block
NDIG = U.NDIGITS5

# Constants the traced field/curve code needs, pre-broadcast to the lane
# width so they're ordinary VMEM blocks (index_map pins them to block 0).
_FIELD_CONST_NAMES = ("M_SUB", "P_LIMBS", "D", "D2", "SQRT_M1", "ONE")


def _const_args() -> tuple[np.ndarray, ...]:
    out = [
        np.ascontiguousarray(
            np.broadcast_to(np.asarray(getattr(F, n)), (F.NLIMBS, LANES))
        )
        for n in _FIELD_CONST_NAMES
    ]
    for t in curve._BASE_TABLE17:
        out.append(
            np.ascontiguousarray(
                np.broadcast_to(np.asarray(t), (curve.TABLE17, F.NLIMBS, LANES))
            )
        )
    return tuple(out)


_N_CONSTS = len(_FIELD_CONST_NAMES) + 4


def _verify_block_kernel(*refs, n_windows: int = 0, stages: str = "full",
                         scheme: str = "ed25519"):
    """consts..., A-coords (20, L) int32, packed R words (8, L) uint32,
    signed digits s/k (51, L) int32, out (1, L) int32 mask.

    scheme selects the decode + cofactor pair: "ed25519" = ZIP-215
    decompression + [8] coset check; "sr25519" = ristretto255 decode + [4]
    coset check (ristretto equality). The ladder between them is byte-for-
    byte the same program.

    n_windows/stages are microbench bisection knobs (ops/microbench.py):
    n_windows truncates the ladder, stages="nodecomp" skips the R
    decompression — both produce WRONG masks and exist only to slope out
    per-stage in-context device cost. Production callers use the defaults.

    A second (1, 1) SMEM output accumulates the batch-wide all-ok scalar
    across grid blocks (TPU grid iterations run sequentially, so the
    revisited block is a running AND) — the reduced-fetch header
    (ed25519_kernel._integrity_parts) rides on it without materializing a
    separate mask reduction."""
    consts = refs[:_N_CONSTS]
    ax, ay, az, at, rw, sdig_ref, kdig_ref, out, ok_out = refs[_N_CONSTS:]

    saved_f = {n: getattr(F, n) for n in _FIELD_CONST_NAMES}
    saved_table = curve._BASE_TABLE17
    saved_sqn = F.SQN_UNROLL_LIMIT
    try:
        for n, ref in zip(_FIELD_CONST_NAMES, consts):
            setattr(F, n, ref[:])
        curve._BASE_TABLE17 = tuple(
            r[:] for r in consts[len(_FIELD_CONST_NAMES):]
        )
        # fully unroll squaring runs: Mosaic loop overhead per iteration is
        # comparable to one squaring (see field.SQN_UNROLL_LIMIT)
        F.SQN_UNROLL_LIMIT = 1 << 30
        table_b = curve._BASE_TABLE17

        a = curve.Point(ax[:], ay[:], az[:], at[:])
        if stages == "nodecomp":
            ok_r, r = jnp.ones(a.x.shape[1:], dtype=bool), a
        elif scheme == "sr25519":
            from cometbft_tpu.ops import sr25519_kernel as SRK

            ok_r, r = SRK.ristretto_decode_device(rw[:])
        else:
            r_words = rw[:]
            y_r = U.words_to_y_limbs(r_words)
            sign_r = U.words_sign(r_words)
            ok_r, r = curve.decompress_zip215(y_r, sign_r)

        neg_a = curve.neg(a)
        table_a = curve.build_point_table17(neg_a)

        zero = jnp.zeros_like(neg_a.x)
        one = zero + F.ONE
        init = curve.Point(zero, one, one, zero)

        nw = n_windows or NDIG

        def body(j, acc):
            # most-significant digit first: index nw-1-j
            i = nw - 1 - j
            ds = sdig_ref[pl.ds(i, 1), :][0]
            dk = kdig_ref[pl.ds(i, 1), :][0]
            return curve.window_step(acc, ds, dk, table_b, table_a, out_t=False)

        acc = jax.lax.fori_loop(0, nw - 1, body, init)
        # final (LSB) window outside the loop: the only one whose A-add must
        # materialize T (the add of -R below reads it)
        sb_ka = curve.window_step(
            acc, sdig_ref[pl.ds(0, 1), :][0], kdig_ref[pl.ds(0, 1), :][0],
            table_b, table_a, out_t=True,
        )
        diff = curve.add(sb_ka, curve.neg(r))
        if scheme == "sr25519":  # cofactor 4: ristretto equality
            coset = curve.double(curve.double(diff))
        else:  # cofactor 8: ZIP-215
            coset = curve.mul_by_cofactor(diff)
        valid = curve.is_identity(coset)
        blk = (valid & ok_r).astype(jnp.int32)
        out[0, :] = blk
        blk_ok = blk.min()  # 1 iff every lane in this 128-lane block passed

        @pl.when(pl.program_id(0) == 0)
        def _init_ok():
            ok_out[0, 0] = blk_ok

        @pl.when(pl.program_id(0) != 0)
        def _and_ok():
            ok_out[0, 0] = jnp.minimum(ok_out[0, 0], blk_ok)
    finally:
        for n, v in saved_f.items():
            setattr(F, n, v)
        curve._BASE_TABLE17 = saved_table
        F.SQN_UNROLL_LIMIT = saved_sqn


@functools.partial(
    jax.jit, static_argnames=("interpret", "n_windows", "stages", "scheme")
)
def _verify_pallas_bench(
    ax, ay, az, at, r_words, s_words, k_words, interpret=False,
    n_windows=0, stages="full", scheme="ed25519",
):
    """Internal entry with microbench bisection knobs (n_windows/stages,
    see _verify_block_kernel) — non-default knob values produce WRONG
    masks. Production code uses verify_pallas, which cannot express them."""
    b = ax.shape[1]
    assert b % LANES == 0, f"batch {b} not a multiple of {LANES}"
    s_dig = U.words_to_digits5_signed(s_words)
    k_dig = U.words_to_digits5_signed(k_words)
    grid = (b // LANES,)
    const_specs = [
        pl.BlockSpec((F.NLIMBS, LANES), lambda i: (0, 0), memory_space=pltpu.VMEM)
    ] * len(_FIELD_CONST_NAMES) + [
        pl.BlockSpec(
            (curve.TABLE17, F.NLIMBS, LANES), lambda i: (0, 0, 0),
            memory_space=pltpu.VMEM,
        )
    ] * 4
    limb_spec = pl.BlockSpec((F.NLIMBS, LANES), lambda i: (0, i), memory_space=pltpu.VMEM)
    word_spec = pl.BlockSpec((U.WORDS, LANES), lambda i: (0, i), memory_space=pltpu.VMEM)
    dig_spec = pl.BlockSpec((NDIG, LANES), lambda i: (0, i), memory_space=pltpu.VMEM)
    out_spec = pl.BlockSpec((1, LANES), lambda i: (0, i), memory_space=pltpu.VMEM)
    ok_spec = pl.BlockSpec((1, 1), lambda i: (0, 0), memory_space=pltpu.SMEM)
    mask, allok = pl.pallas_call(
        functools.partial(
            _verify_block_kernel, n_windows=n_windows, stages=stages,
            scheme=scheme,
        ),
        grid=grid,
        in_specs=const_specs + [limb_spec] * 4 + [word_spec] + [dig_spec] * 2,
        out_specs=(out_spec, ok_spec),
        out_shape=(
            jax.ShapeDtypeStruct((1, b), jnp.int32),
            jax.ShapeDtypeStruct((1, 1), jnp.int32),
        ),
        interpret=interpret,
    )(*_const_args(), ax, ay, az, at, r_words, s_dig, k_dig)
    return mask[0] != 0, allok[0, 0] != 0


def verify_pallas(ax, ay, az, at, r_words, s_words, k_words, interpret=False):
    """(20, B) int32 A-coords + (8, B) uint32 packed r/s/k words ->
    (B,) bool mask (ed25519 ZIP-215). B must be a multiple of LANES
    (callers fall back to the XLA path for smaller buckets)."""
    return _verify_pallas_bench(
        ax, ay, az, at, r_words, s_words, k_words, interpret=interpret
    )[0]


def verify_pallas_ok(ax, ay, az, at, r_words, s_words, k_words,
                     interpret=False):
    """verify_pallas plus the fused all-ok scalar — the reduced-fetch
    header's device-side reduction (kernel-accumulated, see
    _verify_block_kernel). Pairs with ed25519_kernel.verify_math_ok as the
    PallasGate (pallas_fn, xla_fn) couple."""
    return _verify_pallas_bench(
        ax, ay, az, at, r_words, s_words, k_words, interpret=interpret
    )


def verify_pallas_sr(ax, ay, az, at, r_words, s_words, k_words,
                     interpret=False):
    """sr25519 (schnorrkel/ristretto) variant of verify_pallas: same
    ladder, ristretto decode, cofactor-4 coset check."""
    return _verify_pallas_bench(
        ax, ay, az, at, r_words, s_words, k_words, interpret=interpret,
        scheme="sr25519",
    )[0]


def verify_pallas_sr_ok(ax, ay, az, at, r_words, s_words, k_words,
                        interpret=False):
    """sr25519 variant of verify_pallas_ok (mask, all-ok scalar)."""
    return _verify_pallas_bench(
        ax, ay, az, at, r_words, s_words, k_words, interpret=interpret,
        scheme="sr25519",
    )
