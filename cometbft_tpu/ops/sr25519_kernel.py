"""Batched sr25519 (schnorrkel) verification on TPU lanes.

Reference seam: crypto/sr25519/batch.go:45-78 (curve25519-voi's
sr25519.BatchVerifier). Device design: the schnorrkel verification equation
over ristretto255 reduces to edwards25519 arithmetic —

    accept  iff  [4]( [s]B - [k]A - R ) == O

— because two edwards points map to the same ristretto255 element exactly
when they differ by a 4-torsion point, so the cofactor-4 coset check IS
ristretto equality. That makes the heavy path identical to the ed25519
kernel: the same signed 5-bit double-scalar ladder (curve.py), the same
limb layout and packed wire format; only the point DECODING differs
(ristretto255 decode instead of ZIP-215 decompression) and the final
cofactor is 4 instead of 8.

Host side stays host-shaped: Merlin transcript challenges (STROBE/Keccak,
64-bit word arithmetic — hostile to the VPU) come from
crypto/sr25519_math, and the schnorrkel marker bit / s < L checks never
reach the device.
"""

from __future__ import annotations

import time as _time

import jax
import jax.numpy as jnp
import numpy as np

from cometbft_tpu.crypto import sr25519_math as srm
from cometbft_tpu.libs import linkmodel as _linkmodel
from cometbft_tpu.libs import trace as _trace
from cometbft_tpu.ops import curve
from cometbft_tpu.ops import field as F
from cometbft_tpu.ops import limbs as L
from cometbft_tpu.ops import unpack as U
from cometbft_tpu.ops.ed25519_kernel import bucket_size

# the 32-byte encoding of the ristretto identity (all zeros) — padding lanes
_ID_ENC32 = bytes(32)


def _words_to_full_limbs(w: jnp.ndarray) -> tuple[jnp.ndarray, jnp.ndarray]:
    """(8, B) uint32 -> ((20, B) int32 limbs of the low 255 bits, (B,) bit
    255). Ristretto encodings must have bit 255 clear; the caller folds the
    flag into validity."""
    return U.words_to_y_limbs(w), U.words_sign(w)


def _is_canonical_even(limbs: jnp.ndarray, hi_bit: jnp.ndarray) -> jnp.ndarray:
    """ristretto255 DECODE preconditions: s < p, s nonnegative (even),
    bit 255 clear."""
    canon = F.canonicalize(limbs)
    is_canon = jnp.all(canon == limbs, axis=0)
    even = (limbs[0] & 1) == 0
    return is_canon & even & (hi_bit == 0)


def sqrt_ratio_m1(u: jnp.ndarray, v: jnp.ndarray) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Vectorized SQRT_RATIO_M1: (was_square (B,), nonnegative root (20, B)).
    Reads F.SQRT_M1 at trace time (NOT a captured module constant) so the
    Pallas kernel's constant swap applies."""
    v3 = F.mul(F.sq(v), v)
    v7 = F.mul(F.sq(v3), v)
    r = F.mul(F.mul(u, v3), F.pow22523(F.mul(u, v7)))
    check = F.mul(v, F.sq(r))
    correct = F.is_zero(F.sub(check, u))
    flipped = F.is_zero(F.add(check, u))
    flipped_i = F.is_zero(F.add(check, F.mul(u, F.SQRT_M1)))
    r = jnp.where((flipped | flipped_i)[None], F.mul(r, F.SQRT_M1), r)
    was_square = correct | flipped
    # CT_ABS: take the even root
    odd = F.parity(r) == 1
    r = jnp.where(odd[None], F.neg(r), r)
    return was_square, r


def ristretto_decode_device(w: jnp.ndarray) -> tuple[jnp.ndarray, curve.Point]:
    """(8, B) packed encodings -> (ok (B,), extended Point (20, B) coords).
    Mirrors sr25519_math.ristretto_decode lane-parallel."""
    s, hi = _words_to_full_limbs(w)
    pre_ok = _is_canonical_even(s, hi)
    one = jnp.broadcast_to(F.ONE, s.shape).astype(jnp.int32)
    ss = F.sq(s)
    u1 = F.sub(one, ss)
    u2 = F.add(one, ss)
    u2_sqr = F.sq(u2)
    v = F.sub(F.neg(F.mul(F.mul(F.D, u1), u1)), u2_sqr)
    was_square, invsqrt = sqrt_ratio_m1(one, F.mul(v, u2_sqr))
    den_x = F.mul(invsqrt, u2)
    den_y = F.mul(F.mul(invsqrt, den_x), v)
    x = F.mul(F.add(s, s), den_x)
    x = jnp.where((F.parity(x) == 1)[None], F.neg(x), x)
    y = F.mul(u1, den_y)
    t = F.mul(x, y)
    ok = pre_ok & was_square & (F.parity(t) == 0) & ~F.is_zero(y)
    z = jnp.broadcast_to(F.ONE, s.shape).astype(jnp.int32)
    return ok, curve.Point(x, y, z, t)


@jax.jit
def _decompress_kernel(words: jnp.ndarray):
    ok, p = ristretto_decode_device(words)
    return ok, p.x, p.y, p.z, p.t


def verify_math_sr(ax, ay, az, at, r_words, s_words, k_words) -> jnp.ndarray:
    """Per-chip sr25519 verify program: A coords (20, B) (ristretto-decoded,
    cached), packed R encodings + s/k scalars (8, B). Lanes with undecodable
    R reject; undecodable A is masked host-side by the cache."""
    ok_r, r = ristretto_decode_device(r_words)
    neg_a = curve.neg(curve.Point(ax, ay, az, at))
    sb_ka = curve.windowed_double_scalar_signed(
        U.words_to_digits5_signed(s_words), U.words_to_digits5_signed(k_words), neg_a
    )
    diff = curve.add(sb_ka, curve.neg(r))
    quad = curve.double(curve.double(diff))  # cofactor 4: ristretto equality
    valid = curve.is_identity(quad)
    return valid & ok_r


_verify_kernel = jax.jit(verify_math_sr)


def verify_math_sr_ok(ax, ay, az, at, r_words, s_words, k_words):
    """verify_math_sr plus the all-ok reduction for the reduced-fetch
    header (padding lanes are zero encodings with zero scalars — the
    identity verifies valid — so all() over the padded batch equals all()
    over the live lanes)."""
    mask = verify_math_sr(ax, ay, az, at, r_words, s_words, k_words)
    return mask, mask.all()


_verify_kernel_ok = jax.jit(verify_math_sr_ok)

from cometbft_tpu.ops.dispatch import PallasGate  # noqa: E402

_pallas_gate = PallasGate("pallas.sr25519")


def decompress_points(enc: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
    """(N, 32) ristretto encodings -> (ok (N,), coords (N, 4, 20))."""
    n = enc.shape[0]
    b = bucket_size(n)
    words = L.bytes_to_words(enc)
    if b > n:
        words = np.concatenate([words, np.zeros((b - n, 8), dtype=np.uint32)])
    from cometbft_tpu.ops.dispatch import KERNEL_DISPATCH_LOCK

    with KERNEL_DISPATCH_LOCK:
        ok, x, y, z, t = _decompress_kernel(jnp.asarray(words.T))
    coords = np.stack(
        [np.asarray(x).T, np.asarray(y).T, np.asarray(z).T, np.asarray(t).T], axis=1
    )
    return np.asarray(ok)[:n], coords[:n]


from cometbft_tpu.ops.ed25519_kernel import PubKeyCache  # noqa: E402


class SrPubKeyCache(PubKeyCache):
    """Two-level ristretto-decoded pubkey cache: the ed25519 cache with this
    module's decompressor — the device-level digest cache means a repeating
    sr25519 valset's A-coordinates (2 MB at 5k lanes) upload once, not once
    per commit."""

    _decompress = staticmethod(lambda enc: decompress_points(enc))
    scheme = "sr25519"  # reduced-send residency table key (ops/residency)


_default_cache = SrPubKeyCache()


def stage_rows_sr(
    pubs: list[bytes],
    msgs: list[bytes],
    sigs: list[bytes],
    bucket: int,
    out: np.ndarray | None = None,
) -> tuple[np.ndarray, list[bytes], np.ndarray, np.ndarray, np.ndarray]:
    """Host-only sr25519 staging, the scheme's analog of
    ed25519_kernel.stage_batch (the mesh path shards it per chip):
    vectorized length/marker/s<L checks, the whole batch's Merlin
    challenges through the batch STROBE transcript
    (srm.batch_challenge_words_rows — N sponges under one Keccak
    permutation per duplex boundary), r/s/k packed batch-minor
    (8, bucket) into `out` (a leased StagingPool block) when given.
    Returns (pre_ok, safe_pubs, r_words, s_words, k_words) — no device
    arrays; pubkey staging is the dispatcher's (per-chip) concern."""
    n = len(sigs)
    from cometbft_tpu.ops import ed25519_kernel as EK

    ok_len = np.fromiter(map(len, sigs), np.int64, n) == 64
    ok_len &= np.fromiter(map(len, pubs), np.int64, n) == 32
    if ok_len.all():
        sig_rows = np.frombuffer(b"".join(sigs), dtype=np.uint8).reshape(n, 64)
        safe_pubs = list(pubs)
    else:  # ragged stragglers: per-row placeholder substitution
        sig_rows = np.zeros((n, 64), dtype=np.uint8)
        safe_pubs = [_ID_ENC32] * n
        for i in np.flatnonzero(ok_len):
            sig_rows[i] = np.frombuffer(sigs[i], dtype=np.uint8)
            safe_pubs[i] = pubs[i]
    # schnorrkel signature parse, vectorized (mirrors srm.parse_signature):
    # marker bit 255 must be set; s (with the marker cleared) must be < L
    marker = (sig_rows[:, 63] & 128) != 0
    s_rows = np.ascontiguousarray(sig_rows[:, 32:])
    s_rows[:, 31] &= 127
    pre_ok = ok_len & marker & EK.scalars_lt_l(s_rows)
    bad = np.flatnonzero(~pre_ok)
    if bad.size:
        if not sig_rows.flags.writeable:
            sig_rows = sig_rows.copy()
        sig_rows[bad, :32] = 0  # ristretto identity encoding
        s_rows[bad] = 0
        safe_pubs = [p if pre_ok[i] else _ID_ENC32
                     for i, p in enumerate(safe_pubs)]
    r_rows = sig_rows[:, :32]
    # Merlin transcripts absorb the exact message bytes: materialize any
    # shared-prefix factored rows here (the batch STROBE sponge keeps its
    # own per-mlen transcript-prefix snapshots, so the prefix work is
    # still shared inside srm)
    from cometbft_tpu.libs.prefixrows import as_bytes

    k_rows = srm.batch_challenge_words_rows(
        safe_pubs, r_rows, [as_bytes(m) for m in msgs])
    k_rows[~pre_ok] = 0

    if out is None:
        out = np.empty((3, 8, bucket), dtype=np.uint32)
    r_words, s_words, k_words = out[0], out[1], out[2]
    r_words[:, :n] = np.ascontiguousarray(r_rows).view("<u4").T
    s_words[:, :n] = s_rows.view("<u4").T
    k_words[:, :n] = k_rows.T
    if bucket > n:
        r_words[:, n:] = 0
        s_words[:, n:] = 0
        k_words[:, n:] = 0
    return pre_ok, safe_pubs, r_words, s_words, k_words


def stage_batch_sr(
    pubs: list[bytes],
    msgs: list[bytes],
    sigs: list[bytes],
    cache: SrPubKeyCache | None = None,
    out: np.ndarray | None = None,
):
    """Full staging for the single-chip dispatch path: stage_rows_sr host
    staging plus ristretto pubkey decode and device residency. Returns
    (pre_ok, ok_a, n, a_dev, r_words, s_words, k_words) with the word
    arrays still host-resident — verify_batch dispatches them; the
    bench harness rep-differences verify_math_sr over them."""
    n = len(sigs)
    assert len(pubs) == n and len(msgs) == n
    cache = cache or _default_cache

    b = bucket_size(n)
    pre_ok, safe_pubs, r_words, s_words, k_words = stage_rows_sr(
        pubs, msgs, sigs, b, out=out)
    # device-resident A-coordinate staging: digest cache over the UNIQUE
    # key set + device-side gather (a stable sr25519 valset uploads its
    # decoded coords once; repeated/tiled keys cost 4 bytes/lane)
    from cometbft_tpu.ops.ed25519_kernel import _stage_gather

    with _trace.span("sr25519.stage_pubkeys", cat="transfer", lanes=b):
        ok_a, a_dev, _path, _tx = _stage_gather(
            cache, safe_pubs, b, put_key="sr")
    # r/s/k stay HOST arrays (batch-minor (8, B)): the dispatcher checksums
    # them before the transfer and re-transfers on an integrity retry
    return pre_ok, ok_a, n, a_dev, r_words, s_words, k_words


def verify_batch_async(
    pubs: list[bytes],
    msgs: list[bytes],
    sigs: list[bytes],
    cache: SrPubKeyCache | None = None,
):
    """Stage + dispatch without blocking on the device (mirror of
    ed25519_kernel.verify_batch_async): returns a thunk materializing the
    (N,) bool mask, with .device_parts for the shared single-fetch resolver
    (ed25519_kernel.resolve_batches) — the mixed mega-commit dispatches both
    schemes' sub-batches and pays ONE device round trip."""
    n = len(sigs)
    assert len(pubs) == n and len(msgs) == n
    if n == 0:
        empty = lambda: np.zeros(0, dtype=bool)  # noqa: E731
        empty.device_parts = lambda: (
            None, 0, np.zeros(0, bool), np.zeros(0, bool), ([], [], []),
            (srm.verify, "sr25519", None), None)
        return empty
    from cometbft_tpu.ops import dispatch as D
    from cometbft_tpu.ops import ed25519_kernel as EK
    from cometbft_tpu.ops.dispatch import KERNEL_DISPATCH_LOCK

    rows = (list(pubs), list(msgs), list(sigs))
    info = (srm.verify, "sr25519", None)
    sup = D.supervisor("device")

    b = bucket_size(n)
    staged = None
    stage_counted = False
    block = L.POOL.lease(b)
    if D.device_allowed():
        try:
            # sig_rows: THE attribution row-counting site for this batch
            # (mirrors ed25519_kernel.verify_batch_async). Host-only
            # staging: pubkey residency/upload moved into the dispatch
            # closure (reduced-send overlap — the caller thread never
            # blocks on a device round trip).
            with _trace.span("sr25519.stage", cat="stage", sig_rows=n,
                             lanes=b, hash_rung=EK._staging_rung()):
                stage_counted = True  # span finishes (and counts) even
                staged = stage_rows_sr(pubs, msgs, sigs, b, out=block)
        except Exception as exc:  # noqa: BLE001 - hashvec died in staging
            sup.record_op_failure(exc)
    if staged is None:
        L.POOL.release(block)
        # structural pre-checks still run host-side so pre_ok keeps the
        # identity-placeholder semantics of the device path. On the
        # fully-degraded route (breaker open: the stage span above never
        # ran) this is the row-counting site — otherwise degraded
        # batches would grow compute_us with flat rows and inflate
        # bytes-per-sig exactly during the episodes the flight recorder
        # exists to diagnose
        with _trace.span("sr25519.host_precheck", cat="stage",
                         sig_rows=0 if stage_counted else n):
            pre_ok = np.fromiter(
                (len(p) == 32 and srm.parse_signature(s) is not None
                 for p, s in zip(pubs, sigs)), dtype=bool, count=n)
        return EK.make_host_thunk(n, pre_ok, rows, info)
    pre_ok, safe_pubs, r_np, s_np, k_np = staged
    expected = np.uint32(EK._host_checksum(r_np, s_np, k_np))
    ok_cell = EK._LateOkA(n)

    def _dispatch():
        from cometbft_tpu.libs import chaos
        from cometbft_tpu.ops import residency as _residency

        chaos.fire("sr25519.dispatch")
        # ristretto pubkey staging on the transfer pool: indexed
        # reduced-send when the resident table covers the keys, the
        # digest-cached full-key path otherwise
        with _trace.span("sr25519.stage_pubkeys", cat="transfer",
                         lanes=b):
            ok_a, a_dev, path, staging_tx = EK._stage_gather(
                cache, safe_pubs, b, put_key="sr")
        ok_cell.value = ok_a
        # any curve-kernel trace swaps field/curve module constants under
        # this lock (ops/dispatch.py); never trace concurrently
        with _trace.span("sr25519.h2d", cat="transfer", lanes=b) as sp:
            t0 = _time.perf_counter()
            # one transfer for the (3, 8, B) staged block (was three
            # separate puts); planes sliced apart on device. Block
            # before t1: async dispatch would record enqueue time, not
            # wire time (the kernel needs the words resident anyway).
            dev_block = jnp.asarray(block)
            jax.block_until_ready(dev_block)
            nbytes = block.nbytes
            _linkmodel.tunnel().observe_transfer(
                nbytes, _time.perf_counter() - t0)
            sp.add_bytes(tx=nbytes)
        _residency.record_send(path, staging_tx + nbytes, sigs=n)
        r_w, s_w, k_w = dev_block[0], dev_block[1], dev_block[2]
        with _trace.span("sr25519.dispatch", cat="compute", lanes=b,
                         device=EK.default_device_index()):
            with KERNEL_DISPATCH_LOCK:
                from cometbft_tpu.ops import pallas_verify as PV

                mask, allok = _pallas_gate.run(
                    PV.verify_pallas_sr_ok, _verify_kernel_ok,
                    (*a_dev, r_w, s_w, k_w), r_w.shape[1])
            parts = EK._integrity_parts(mask, allok, r_w, s_w, k_w, expected)
        EK._count_device_batch("sr25519", b)
        return parts

    return EK.supervised_device_thunk(
        "sr25519", sup, _dispatch, "sr25519.fetch",
        n, pre_ok, ok_cell, rows, info, expected=expected, lease=block)


def verify_batch(
    pubs: list[bytes],
    msgs: list[bytes],
    sigs: list[bytes],
    cache: SrPubKeyCache | None = None,
) -> tuple[bool, list[bool]]:
    """Schnorrkel batch verification with a per-signature mask."""
    if len(sigs) == 0:
        return True, []
    mask = verify_batch_async(pubs, msgs, sigs, cache=cache)()
    return bool(mask.all()), mask.tolist()
