"""Optimal-ate pairing on the batch axis: one lane = one pairing.

Miller variable T walks the twist E'(Fp2) in PROJECTIVE coordinates; the
evaluated line lands in the same three sparse Fp12 slots as the oracle's
affine derivation (fallback.py bls_miller_loop), scaled per step by the
Fp2 factor 2YZ^2 (tangent) / X - xQ Z (chord) — Fp2 scalings are killed
by the final exponentiation, so the affine oracle and this projective
pipeline agree exactly after it (tested bit-for-bit).

The loop is a lax.scan over the 64 baked bits of |x|, so the HLO holds
ONE doubling+conditional-add body. The final exponentiation mirrors the
oracle's easy part + (x-1)^2 (x+p) (x^2+p^2-1) + 3 addition chain."""

from __future__ import annotations

import jax
import jax.numpy as jnp

from cometbft_tpu.crypto import fallback as _oracle
from cometbft_tpu.ops.bls12381 import fp
from cometbft_tpu.ops.bls12381 import fp2
from cometbft_tpu.ops.bls12381 import points as pts
from cometbft_tpu.ops.bls12381 import tower
from cometbft_tpu.ops.bls12381.fp2 import Fp2
from cometbft_tpu.ops.bls12381.tower import Fp6, Fp12

_X_BITS = [int(c) for c in bin(-_oracle.BLS_X)[2:]]


def _line_f12(c0: Fp2, c_vw: Fp2, c_v2w: Fp2, bshape) -> Fp12:
    """Assemble the sparse line (c0 + c_vw * v w + c_v2w * v^2 w)."""
    xi_inv = fp2.broadcast_const(_oracle._XI_INV, bshape)
    z = fp2.zero(bshape)
    return Fp12(Fp6(c0, z, z),
                Fp6(z, fp2.mul(c_vw, xi_inv), fp2.mul(c_v2w, xi_inv)))


def miller_loop(px: jnp.ndarray, py: jnp.ndarray,
                qx: Fp2, qy: Fp2) -> Fp12:
    """f_{|x|,Q}(P) conjugated (x < 0). px/py: (35, B) Montgomery Fp
    affine G1 coordinates; qx/qy: affine twist coordinates. Identity
    lanes must be masked by the caller (the pairing with infinity is
    rejected upstream, matching the oracle's semantics)."""
    bshape = px.shape
    t0 = pts.from_affine(pts.G2Field, qx, qy)
    f0 = tower.f12_one(bshape)
    bits = jnp.asarray(_X_BITS[1:], dtype=jnp.int32)

    state0 = (f0, t0)
    flat0, tree = jax.tree_util.tree_flatten(state0)

    def body(flat, bit):
        f, t = jax.tree_util.tree_unflatten(tree, flat)
        X, Y, Z = t.x, t.y, t.z
        # tangent line at T, scaled by 2YZ^2
        xx = fp2.sq(X)
        yz = fp2.mul(Y, Z)
        c0 = fp2.mul_fp(fp2.mul_small(fp2.mul(yz, Z), 2), py)
        c_vw = fp2.sub(fp2.mul(xx, fp2.mul_small(X, 3)),
                       fp2.mul_small(fp2.mul(fp2.sq(Y), Z), 2))
        c_v2w = fp2.neg(fp2.mul_fp(fp2.mul_small(fp2.mul(xx, Z), 3), px))
        f = tower.f12_mul(tower.f12_sq(f),
                          _line_f12(c0, c_vw, c_v2w, bshape))
        t = pts.dbl(pts.G2Field, t)
        # chord through (new) T and Q, scaled by X - xQ Z — computed
        # every step, selected by the bit (lockstep lanes)
        X, Y, Z = t.x, t.y, t.z
        s = fp2.sub(X, fp2.mul(qx, Z))
        a_c0 = fp2.mul_fp(s, py)
        a_v2w = fp2.neg(fp2.mul_fp(fp2.sub(Y, fp2.mul(qy, Z)), px))
        a_vw = fp2.sub(fp2.mul(Y, qx), fp2.mul(X, qy))
        f_add = tower.f12_mul(f, _line_f12(a_c0, a_vw, a_v2w, bshape))
        t_add = pts.add(pts.G2Field, t, pts.from_affine(pts.G2Field, qx, qy))
        taken = jnp.broadcast_to(bit == 1, bshape[1:])
        f = tower.f12_select(taken, f_add, f)
        t = jax.tree_util.tree_map(
            lambda a, b: fp.select(taken, a, b), t_add, t)
        return jax.tree_util.tree_flatten((f, t))[0], None

    out, _ = jax.lax.scan(body, flat0, bits)
    f, _t = jax.tree_util.tree_unflatten(tree, out)
    return tower.f12_conj(f)


def _cyclo_exp(a: Fp12, e: int) -> Fp12:
    if e < 0:
        return tower.f12_exp_const(tower.f12_conj(a), -e)
    return tower.f12_exp_const(a, e)


def final_exp(f: Fp12) -> Fp12:
    """Mirror of fallback.bls_final_exp (same cubed-pairing chain, so
    device and oracle values compare equal, not just both-roots)."""
    f = tower.f12_mul(tower.f12_conj(f), tower.f12_inv(f))
    f = tower.f12_mul(tower.f12_frob(f, 2), f)
    x = _oracle.BLS_X
    y = _cyclo_exp(_cyclo_exp(f, x - 1), x - 1)
    y = tower.f12_mul(_cyclo_exp(y, x), tower.f12_frob(y, 1))
    y2 = _cyclo_exp(_cyclo_exp(y, x), x)
    y = tower.f12_mul(tower.f12_mul(y2, tower.f12_frob(y, 2)),
                      tower.f12_conj(y))
    return tower.f12_mul(y, tower.f12_mul(tower.f12_sq(f), f))


def product_lanes(f: Fp12) -> Fp12:
    """Multiply all lanes of a batched Fp12 down to one lane (tree
    fold) — the aggregate check multiplies its Miller values before the
    single shared final exponentiation."""
    def lanes(x):
        return jax.tree_util.tree_leaves(x)[0].shape[-1]

    while lanes(f) > 1:
        n = lanes(f)
        half = (n + 1) // 2
        lo = jax.tree_util.tree_map(lambda a: a[..., :half], f)
        if n % 2:
            hi_tail = jax.tree_util.tree_map(lambda a: a[..., half:], f)
            one = tower.f12_one(
                jax.tree_util.tree_leaves(lo)[0].shape[:-1] + (1,))
            hi = jax.tree_util.tree_map(
                lambda a, b: jnp.concatenate([a, b], axis=-1), hi_tail, one)
        else:
            hi = jax.tree_util.tree_map(lambda a: a[..., half:], f)
        f = tower.f12_mul(lo, hi)
    return f


# ---- host-composed final exponentiation --------------------------------
#
# The monolithic final_exp above inlines five 64-bit exponentiation scans
# — five compiled copies of the same body. The kernel path (ops/
# bls_kernel.py) composes jitted pieces at host level instead: ONE
# compiled exp-by-64-bits program (bits are a traced input) serves all
# five chain steps, roughly halving the pipeline's cold-compile cost.
# Intermediate values stay device-resident between calls.

import jax as _jax


@_jax.jit
def _jit_easy(f: Fp12) -> Fp12:
    f = tower.f12_mul(tower.f12_conj(f), tower.f12_inv(f))
    return tower.f12_mul(tower.f12_frob(f, 2), f)


@_jax.jit
def _jit_exp64(f: Fp12, bits: jnp.ndarray) -> Fp12:
    return tower.f12_exp_bits(f, bits)


@_jax.jit
def _jit_xplusp_step(y: Fp12, yx: Fp12) -> Fp12:
    return tower.f12_mul(yx, tower.f12_frob(y, 1))


@_jax.jit
def _jit_tail(y2: Fp12, y: Fp12, f: Fp12) -> Fp12:
    y = tower.f12_mul(tower.f12_mul(y2, tower.f12_frob(y, 2)),
                      tower.f12_conj(y))
    return tower.f12_mul(y, tower.f12_mul(tower.f12_sq(f), f))


def _bits64(e: int) -> jnp.ndarray:
    """|e| as exactly 64 MSB-first bits (leading zeros are exp no-ops)."""
    s = bin(abs(e))[2:].rjust(64, "0")
    assert len(s) == 64
    return jnp.asarray([int(c) for c in s], dtype=jnp.int32)


_XM1_BITS = _bits64(_oracle.BLS_X - 1)
_X_BITS64 = _bits64(_oracle.BLS_X)


def _cyclo_exp_host(a: Fp12, e: int) -> Fp12:
    if e < 0:
        a = tower.f12_conj(a)
    return _jit_exp64(a, _bits64(e))


def final_exp_composed(f: Fp12) -> Fp12:
    """final_exp as a host-level composition of shared jitted pieces —
    bit-identical to final_exp (and to the oracle)."""
    x = _oracle.BLS_X
    f = _jit_easy(f)
    y = _cyclo_exp_host(_cyclo_exp_host(f, x - 1), x - 1)
    y = _jit_xplusp_step(y, _cyclo_exp_host(y, x))
    y2 = _cyclo_exp_host(_cyclo_exp_host(y, x), x)
    return _jit_tail(y2, y, f)
