"""G1/G2 point arithmetic on the batch axis.

Complete projective formulas (Renes-Costello-Batina 2015, algorithms 7/9
for a = 0) over a generic field adapter — branch-free by construction,
which is exactly what lockstep vector lanes need: identity, doubling and
adversarial inputs take the same instruction path (the ops/curve.py
design note, ported to short Weierstrass). One instantiation per group:
G1 over fp arrays, G2 over fp2 pairs.

The only scalars multiplied on device are FIXED public constants (the
subgroup order r, the G2 cofactor) — per-lane secret scalars never reach
this plane (signing is host-side), so every ladder is a baked-bits scan.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from cometbft_tpu.crypto import fallback as _oracle
from cometbft_tpu.ops.bls12381 import fp
from cometbft_tpu.ops.bls12381 import fp2
from cometbft_tpu.ops.bls12381.fp2 import Fp2


class G1Field:
    """Field adapter: fp module over (35, B) arrays."""

    add = staticmethod(fp.add)
    sub = staticmethod(fp.sub)
    mul = staticmethod(fp.mul)
    sq = staticmethod(fp.sq)
    neg = staticmethod(fp.neg)
    select = staticmethod(fp.select)
    is_zero = staticmethod(fp.is_zero)
    stack = staticmethod(fp.stack)
    split = staticmethod(fp.split)
    mul_small = staticmethod(fp.mul_small)

    @staticmethod
    def mul_b3(a):  # 3 * b = 12: a cheap limb scaling, never a field mul
        return fp.mul_small(a, 12)

    @staticmethod
    def zero_like(a):
        return jnp.zeros_like(a)

    @staticmethod
    def one_like(a):
        return jnp.broadcast_to(fp.ONE, a.shape).astype(jnp.int32)


_B3_G2 = _oracle.f2_mul_fp(_oracle._B2, 3)  # 12 * (1 + u)


class G2Field:
    """Field adapter: fp2 module over Fp2 pairs."""

    add = staticmethod(fp2.add)
    sub = staticmethod(fp2.sub)
    mul = staticmethod(fp2.mul)
    sq = staticmethod(fp2.sq)
    neg = staticmethod(fp2.neg)
    select = staticmethod(fp2.select)
    is_zero = staticmethod(fp2.is_zero)
    stack = staticmethod(fp2.stack)
    split = staticmethod(fp2.split)
    mul_small = staticmethod(fp2.mul_small)

    @staticmethod
    def mul_b3(a: Fp2):  # 3 * b = 12(1 + u): limb scaling + xi rotation
        return fp2.mul_xi(fp2.mul_small(a, 12))

    @staticmethod
    def zero_like(a: Fp2):
        return fp2.zero(a.a.shape)

    @staticmethod
    def one_like(a: Fp2):
        return fp2.one(a.a.shape)


class Point(NamedTuple):
    """Projective (X : Y : Z); identity = (0 : 1 : 0)."""

    x: object
    y: object
    z: object


def identity_like(F, coord) -> Point:
    return Point(F.zero_like(coord), F.one_like(coord), F.zero_like(coord))


def from_affine(F, x, y) -> Point:
    return Point(x, y, F.one_like(y))


def neg_point(F, p: Point) -> Point:
    return Point(p.x, F.neg(p.y), p.z)


def is_identity(F, p: Point) -> jnp.ndarray:
    return F.is_zero(p.z)


def add(F, p: Point, q: Point) -> Point:
    """RCB 2015 algorithm 7 (complete, a = 0), multiplies stacked in two
    dependency layers of six."""
    l1 = F.mul(
        F.stack([p.x, p.y, p.z, F.add(p.x, p.y), F.add(p.y, p.z),
                 F.add(p.x, p.z)]),
        F.stack([q.x, q.y, q.z, F.add(q.x, q.y), F.add(q.y, q.z),
                 F.add(q.x, q.z)]))
    t0, t1, t2, mxy, myz, mxz = F.split(l1, 6)
    t3 = F.sub(mxy, F.add(t0, t1))
    t4 = F.sub(myz, F.add(t1, t2))
    y3 = F.sub(mxz, F.add(t0, t2))
    x3 = F.mul_small(t0, 3)
    t2b = F.mul_b3(t2)
    z3 = F.add(t1, t2b)
    t1b = F.sub(t1, t2b)
    y3b = F.mul_b3(y3)
    l2 = F.mul(F.stack([t3, t4, y3b, t1b, z3, x3]),
               F.stack([t1b, y3b, x3, z3, t4, t3]))
    p1, p2, p3, p4, p5, p6 = F.split(l2, 6)
    return Point(F.sub(p1, p2), F.add(p3, p4), F.add(p5, p6))


def dbl(F, p: Point) -> Point:
    """RCB 2015 algorithm 9 (complete doubling, a = 0), two stacked
    multiply layers of four."""
    l1 = F.mul(F.stack([p.y, p.y, p.z, p.x]),
               F.stack([p.y, p.z, p.z, p.y]))
    t0, t1, zz, txy = F.split(l1, 4)
    t2 = F.mul_b3(zz)
    z8 = F.mul_small(t0, 8)
    y3 = F.add(t0, t2)
    t0b = F.sub(t0, F.mul_small(t2, 3))
    l2 = F.mul(F.stack([t2, t1, t0b, t0b]),
               F.stack([z8, z8, y3, txy]))
    x3, z3, q3, q4 = F.split(l2, 4)
    return Point(F.mul_small(q4, 2), F.add(x3, q3), z3)


def mul_const(F, p: Point, e: int) -> Point:
    """[e]P for a fixed public scalar: baked-bits double-and-add scan
    (complete formulas — no special-casing along the ladder)."""
    assert e >= 0
    bits = fp._bits_desc(e)
    acc0 = identity_like(F, p.y)
    flat_p, tree = jax.tree_util.tree_flatten(p)

    def body(acc_flat, bit):
        acc = jax.tree_util.tree_unflatten(tree, acc_flat)
        acc = dbl(F, acc)
        cand = add(F, acc, jax.tree_util.tree_unflatten(tree, flat_p))
        bshape = bit == 1
        nxt = jax.tree_util.tree_map(
            lambda a, b: jnp.where(jnp.broadcast_to(
                bshape, a.shape[1:])[None, :], a, b),
            cand, acc)
        return jax.tree_util.tree_flatten(nxt)[0], None

    out, _ = jax.lax.scan(body, jax.tree_util.tree_flatten(acc0)[0], bits)
    return jax.tree_util.tree_unflatten(tree, out)


def in_subgroup(F, p: Point) -> jnp.ndarray:
    """[r]P == O (identity itself counts — callers mask infinity
    separately where the draft rejects it)."""
    return is_identity(F, mul_const(F, p, _oracle.BLS_R))


def on_curve(F, p: Point) -> jnp.ndarray:
    """Projective membership via 3*(Y^2 Z) == 3*X^3 + b3*Z^3 (only the
    baked b3 constant is needed). Identity (0:1:0) satisfies it."""
    cubes = F.mul(F.stack([F.sq(p.x), F.sq(p.z), F.sq(p.y)]),
                  F.stack([p.x, p.z, p.z]))
    x3, z3, yyz = F.split(cubes, 3)
    lhs = F.mul_small(yyz, 3)
    rhs = F.add(F.mul_small(x3, 3), F.mul_b3(z3))
    return F.is_zero(F.sub(lhs, rhs))


def to_affine(F, p: Point):
    """(x, y, is_identity): identity lanes read (0, 0)."""
    import cometbft_tpu.ops.bls12381.fp as _fp  # noqa: F401

    zi = _field_inv(F, p.z)
    return F.mul(p.x, zi), F.mul(p.y, zi), is_identity(F, p)


def _field_inv(F, a):
    if F is G1Field:
        return fp.inv(a)
    return fp2.inv(a)


def sum_tree(F, p: Point, width: int) -> Point:
    """Reduce a batch of points to lane 0 by halving adds: lanes past
    `width` must already hold the identity. Returns a 1-lane Point.
    log2(B) jitted adds at shrinking shapes — shapes walk the same
    power-of-two ladder every call, so compilation is bounded."""
    del width

    def lanes(q: Point) -> int:
        leaf = jax.tree_util.tree_leaves(q)[0]
        return leaf.shape[-1]

    while lanes(p) > 1:
        n = lanes(p)
        half = (n + 1) // 2
        lo = jax.tree_util.tree_map(lambda a: a[..., :half], p)
        if n % 2:  # odd: pad the high half with one identity lane
            hi = jax.tree_util.tree_map(lambda a: a[..., half - 1:], p)
            ident = identity_like(F, lo.y)
            hi = jax.tree_util.tree_map(
                lambda a, b: jnp.concatenate(
                    [a[..., 1:], b[..., :1]], axis=-1), hi, ident)
        else:
            hi = jax.tree_util.tree_map(lambda a: a[..., half:], p)
        p = add(F, lo, hi)
    return p


# ---- compressed-point staging (host <-> device) -------------------------


def g1_decompress(x_raw: jnp.ndarray, sign_bit: jnp.ndarray
                  ) -> tuple[jnp.ndarray, Point]:
    """(35, B) raw x limbs (+ per-lane sign flags) -> (ok, affine-Z1
    projective Point). Structural flag/range/infinity checks are the
    host's job (ops/bls_kernel staging) — this is the field math half:
    y = sqrt(x^3 + 4), sign-selected. ok = sqrt exists."""
    x = fp.to_mont(x_raw)
    four = jnp.broadcast_to(
        fp._const(4 * fp.R_MOD_P % fp.P_INT), x.shape).astype(jnp.int32)
    ok, y = fp.sqrt(fp.add(fp.mul(fp.sq(x), x), four))
    flip = _lexi_larger_fp(y) != (sign_bit != 0)
    y = fp.select(flip, fp.neg(y), y)
    return ok, from_affine(G1Field, x, y)


def g2_decompress(x0_raw: jnp.ndarray, x1_raw: jnp.ndarray,
                  sign_bit: jnp.ndarray) -> tuple[jnp.ndarray, Point]:
    """G2 analog: x = (x0, x1) raw limb planes, y via Fp2 sqrt."""
    x = Fp2(fp.to_mont(x0_raw), fp.to_mont(x1_raw))
    b2 = fp2.broadcast_const(_oracle._B2, x.a.shape)
    ok, y = fp2.sqrt(fp2.add(fp2.mul(fp2.sq(x), x), b2))
    flip = _lexi_larger_fp2(y) != (sign_bit != 0)
    y = fp2.select(flip, fp2.neg(y), y)
    return ok, from_affine(G2Field, x, y)


_HALF = (fp.P_INT - 1) // 2


def _gt_half(raw: jnp.ndarray) -> jnp.ndarray:
    """(35, B) canonical raw limbs -> (B,) bool of value > (p-1)/2,
    via a borrow sweep against the constant."""
    half = jnp.broadcast_to(fp._const(_HALF), raw.shape).astype(jnp.int32)

    def body(i, borrow):
        v = (jax.lax.dynamic_slice_in_dim(half, i, 1, axis=0)
             - jax.lax.dynamic_slice_in_dim(raw, i, 1, axis=0) - borrow)
        return (v < 0).astype(jnp.int32)

    borrow = jax.lax.fori_loop(
        0, fp.NLIMBS, body, jnp.zeros_like(raw[:1]))
    return borrow[0] != 0


def _lexi_larger_fp(y_mont: jnp.ndarray) -> jnp.ndarray:
    return _gt_half(fp.from_mont(y_mont))


def _lexi_larger_fp2(y: Fp2) -> jnp.ndarray:
    ra, rb = fp.from_mont(y.a), fp.from_mont(y.b)
    b_zero = jnp.all(rb == 0, axis=0)
    return jnp.where(b_zero, _gt_half(ra), _gt_half(rb))


def g1_compress_host(pt_affine_raw: np.ndarray, y_larger: np.ndarray,
                     inf: np.ndarray) -> np.ndarray:
    """(35, B) canonical raw x limbs + per-lane sign/infinity -> (B, 48)
    compressed encodings (host-side assembly)."""
    out = fp.limbs_to_bytes_be(pt_affine_raw)
    out = out.copy()
    out[:, 0] |= 0x80
    out[y_larger.astype(bool), 0] |= 0x20
    if inf.any():
        out[inf.astype(bool)] = 0
        out[inf.astype(bool), 0] = 0xC0
    return out
