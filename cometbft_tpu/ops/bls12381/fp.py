"""The BLS12-381 base field on TPU vector lanes.

Representation: radix-2^11, 35 limbs (385 bits), little-endian, int32,
LIMB-AXIS FIRST — an Fp batch is shape (35, B), one vector lane per
element, mirroring ops/field.py. Unlike 2^255 - 19 the BLS prime is not
pseudo-Mersenne, so reduction is MONTGOMERY (R = 2^385): every stored
element is in the Montgomery domain and mul() is a schoolbook limb
convolution followed by a CIOS-style REDC sweep (fori_loop bodies, so
the Miller-loop scan's HLO stays bounded).

Invariant ("carried"): limbs in [0, ~2^12), value REDUNDANT mod p. The
2^385 overflow of carries folds back through the constant R mod p — the
general-modulus analog of field.py's FOLD = 608 wrap; a residual top
carry of 1 can persist across rounds (R mod p has full-size limbs), which
is why the carried bound is 2^12, not 2^11. canon() produces the unique
representative for comparisons; from_mont() leaves the Montgomery domain.

int32 safety (radix-11 is the headroom choice; radix-12 is one carry away
from overflow):
  conv columns:     <= 35 * (2^12)^2            ~= 5.9e8
  REDC m*N columns: <= 35 * 2047^2              ~= 1.5e8
  worst REDC col:   conv + m*N + carries        <  7.5e8  <  2^31
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from cometbft_tpu.crypto import fallback as _oracle

P_INT = _oracle.BLS_P
RADIX = 11
NLIMBS = 35
MASK = (1 << RADIX) - 1
R_INT = 1 << (RADIX * NLIMBS)  # 2^385, the Montgomery radix
R_MOD_P = R_INT % P_INT
R2_MOD_P = R_INT * R_INT % P_INT
N0INV = (-pow(P_INT, -1, 1 << RADIX)) % (1 << RADIX)
# subtraction bias: a multiple of p dominating any carried value
# (value < 2^387); its top limb overflows 11 bits by design
M_SUB_INT = P_INT * (-(-(1 << 388) // P_INT))


def int_to_limbs(x: int) -> np.ndarray:
    out = np.zeros(NLIMBS, dtype=np.int64)
    for i in range(NLIMBS - 1):
        out[i] = x & MASK
        x >>= RADIX
    out[NLIMBS - 1] = x
    assert x < 2**17, "constant too large for the loose top limb"
    return out.astype(np.int32)


def ints_to_limbs(xs) -> np.ndarray:
    """list[int] -> (35, B) int32 canonical limbs."""
    out = np.zeros((NLIMBS, len(xs)), dtype=np.int32)
    for j, x in enumerate(xs):
        for i in range(NLIMBS):
            out[i, j] = x & MASK
            x >>= RADIX
    return out


def limbs_to_ints(a: np.ndarray) -> list[int]:
    """(35, B) limbs (any carried representation) -> list[int] values."""
    a = np.asarray(a, dtype=object)
    out = []
    for j in range(a.shape[1]):
        v = 0
        for i in range(NLIMBS - 1, -1, -1):
            v = (v << RADIX) + int(a[i, j])
        out.append(v)
    return out


def _const(x: int) -> jnp.ndarray:
    return jnp.asarray(int_to_limbs(x))[:, None]


P_LIMBS = _const(P_INT)
R_MOD_P_LIMBS = _const(R_MOD_P)
R2_LIMBS = _const(R2_MOD_P)
M_SUB = _const(M_SUB_INT)
ONE = _const(R_MOD_P)       # 1 in the Montgomery domain
ONE_RAW = _const(1)         # the raw integer 1 (for from_mont)
_NPAD = jnp.concatenate(
    [jnp.asarray(int_to_limbs(P_INT)), jnp.zeros(NLIMBS, jnp.int32)])[:, None]


def zeros(b: int) -> jnp.ndarray:
    return jnp.zeros((NLIMBS, b), dtype=jnp.int32)


def _carry_fold(x: jnp.ndarray, rounds: int = 2) -> jnp.ndarray:
    """Carry rounds with the 2^385 overflow folded back via R mod p (the
    whole 35-limb constant — a top carry re-enters as c * (R mod p)).
    Convergence: the fold's top limb is ~2^9, so top carries shrink ~4x
    per round; a residual carry of 1 keeps limbs under 2^12."""
    for _ in range(rounds):
        c = x >> RADIX
        r = x & MASK
        x = r + jnp.concatenate(
            [jnp.zeros_like(c[:1]), c[: NLIMBS - 1]], axis=0)
        x = x + c[NLIMBS - 1:] * R_MOD_P_LIMBS
    return x


def add(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    return _carry_fold(a + b, rounds=2)


def sub(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    return _carry_fold(a + M_SUB - b, rounds=3)


def neg(a: jnp.ndarray) -> jnp.ndarray:
    return _carry_fold(M_SUB - a, rounds=3)


# bias for the fused a - b - c (dominates two carried operands)
M_SUB2 = _const(P_INT * (-(-(1 << 389) // P_INT)))


def sub2(a: jnp.ndarray, b: jnp.ndarray, c: jnp.ndarray) -> jnp.ndarray:
    """a - b - c in one carry chain (tower-mul glue)."""
    return _carry_fold(a + M_SUB2 - b - c, rounds=3)


def stack(parts) -> jnp.ndarray:
    """Concatenate operands on the LANE axis — the tower's multiply
    batching: k independent Fp muls become one k-wide mul, so the HLO op
    count stays flat while lanes fill (the whole point on a VPU)."""
    return jnp.concatenate(parts, axis=1)


def split(x: jnp.ndarray, k: int):
    """Undo stack(): split k equal lane groups."""
    return jnp.split(x, k, axis=1)


# the full-width Montgomery constant N' = -p^-1 mod 2^385 (3-conv REDC)
NPRIME_INT = (-pow(P_INT, -1, R_INT)) % R_INT


def _conv(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """(35, B) x (35, B) -> (70, B) schoolbook product columns: 35
    statically-rolled multiply-adds (the field.py _conv idiom — plain
    elementwise HLO compiles and runs an order of magnitude faster here
    than gather/einsum or grouped-conv formulations; the fori_loop
    variant compiled the FULL pairing in ~9 minutes, this one in
    seconds). Callers batch independent multiplies onto the lane axis
    (fp2/tower stacking) so op count, not op width, stays the budget."""
    bz = jnp.concatenate([b, jnp.zeros_like(b)], axis=0)
    acc = a[0:1] * bz
    for i in range(1, NLIMBS):
        acc = acc + a[i:i + 1] * jnp.roll(bz, i, axis=0)
    return acc


def _carry_nodrop(x: jnp.ndarray, rounds: int) -> jnp.ndarray:
    """Partial carry rounds on a full-width column array (no top wrap —
    the value bound guarantees no carry ever leaves the top column)."""
    for _ in range(rounds):
        c = x >> RADIX
        x = (x & MASK) + jnp.concatenate(
            [jnp.zeros_like(c[:1]), c[:-1]], axis=0)
    return x


_NPRIME_LIMBS = jnp.asarray(
    np.stack([int_to_limbs(NPRIME_INT)]).T)  # (35, 1)
_N_LIMBS_C = P_LIMBS


def _redc(t: jnp.ndarray) -> jnp.ndarray:
    """Montgomery reduction in convolution form: m = (t mod R) * N'
    mod R, result = (t + m*p) / R. Whole-array partial carries only —
    the exact division's carry bit falls out of a reduction: after the
    carries, the low half's value is a multiple of 2^385 bounded below
    2 * 2^385, i.e. exactly 0 or 2^385, so the carry into the high half
    is any(low != 0)."""
    # one spill column: redundant inputs can push the product a hair
    # past 70 limbs (2^770 * 1.001); its carry must not drop
    t = jnp.concatenate([t, jnp.zeros_like(t[:1])], axis=0)
    t = _carry_nodrop(t, 3)
    m = _conv(t[:NLIMBS],
              jnp.broadcast_to(_NPRIME_LIMBS, t[:NLIMBS].shape)
              .astype(jnp.int32))[:NLIMBS]
    # drop-top carries are multiples of 2^385 — m only matters mod R
    for _ in range(3):
        c = m >> RADIX
        m = (m & MASK) + jnp.concatenate(
            [jnp.zeros_like(c[:1]), c[:-1]], axis=0)
    mp = _conv(m, jnp.broadcast_to(_N_LIMBS_C, m.shape).astype(jnp.int32))
    t = t + jnp.concatenate([mp, jnp.zeros_like(mp[:1])], axis=0)
    t = _carry_nodrop(t, 3)
    carry = jnp.any(t[:NLIMBS] != 0, axis=0).astype(jnp.int32)
    res = t[NLIMBS: 2 * NLIMBS]
    res = jnp.concatenate([res[:1] + carry[None, :], res[1:]], axis=0)
    # the spill column (weight 2^385 relative to res) folds via R mod p
    res = res + t[2 * NLIMBS:] * R_MOD_P_LIMBS
    return _carry_fold(res, rounds=2)


def mul(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    return _redc(_conv(a, b))


def sq(a: jnp.ndarray) -> jnp.ndarray:
    return _redc(_conv(a, a))


def mul_small(a: jnp.ndarray, k: int) -> jnp.ndarray:
    """a * k for tiny non-Montgomery integers (k <= ~2^6): plain limb
    scaling, no domain factor involved."""
    return _carry_fold(a * jnp.int32(k), rounds=2)


def to_mont(raw: jnp.ndarray) -> jnp.ndarray:
    """Raw integer limbs -> Montgomery domain (mont-mul by R^2)."""
    return mul(raw, jnp.broadcast_to(R2_LIMBS, raw.shape))


def from_mont(a: jnp.ndarray) -> jnp.ndarray:
    """Montgomery -> raw integer limbs in [0, p), canonical."""
    return _canon_raw(mul(a, jnp.broadcast_to(ONE_RAW, a.shape)))


def _cond_sub_p(x: jnp.ndarray) -> jnp.ndarray:
    """One conditional subtract of p with a sequential borrow sweep;
    input limbs canonical-carried, value < 2p."""
    def body(i, st):
        borrow, out = st
        v = jax.lax.dynamic_slice_in_dim(x, i, 1, axis=0) \
            - jax.lax.dynamic_slice_in_dim(
                jnp.broadcast_to(P_LIMBS, x.shape), i, 1, axis=0) - borrow
        borrow = (v < 0).astype(jnp.int32)
        return borrow, jax.lax.dynamic_update_slice_in_dim(
            out, v + (borrow << RADIX), i, axis=0)

    borrow, sub_x = jax.lax.fori_loop(
        0, NLIMBS, body, (jnp.zeros_like(x[:1]), jnp.zeros_like(x)))
    return jnp.where(borrow == 0, sub_x, x)


def _strict_carry(x: jnp.ndarray) -> jnp.ndarray:
    """Full sequential carry: limbs -> canonical digits (value must
    already be < 2^385 so no top carry escapes)."""
    def body(i, st):
        carry, out = st
        v = jax.lax.dynamic_slice_in_dim(x, i, 1, axis=0) + carry
        return v >> RADIX, jax.lax.dynamic_update_slice_in_dim(
            out, v & MASK, i, axis=0)

    _, out = jax.lax.fori_loop(
        0, NLIMBS, body, (jnp.zeros_like(x[:1]), jnp.zeros_like(x)))
    return out


def _canon_raw(x: jnp.ndarray) -> jnp.ndarray:
    """Carried limbs, value < 3p -> canonical [0, p)."""
    x = _strict_carry(_carry_fold(x, rounds=2))
    return _cond_sub_p(_cond_sub_p(x))


def canon(a: jnp.ndarray) -> jnp.ndarray:
    """Canonical Montgomery representative in [0, p) — the read path for
    comparisons. A mont-mul by ONE tightens the redundant value below
    ~2p before the conditional subtracts."""
    return _canon_raw(mul(a, jnp.broadcast_to(ONE, a.shape)))


def is_zero(a: jnp.ndarray) -> jnp.ndarray:
    return jnp.all(canon(a) == 0, axis=0)


def eq(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    return is_zero(sub(a, b))


def select(m: jnp.ndarray, a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """Per-lane select: m (B,) bool -> a where true else b."""
    return jnp.where(m[None, :], a, b)


def _bits_desc(e: int) -> jnp.ndarray:
    return jnp.asarray([int(c) for c in bin(e)[2:]], dtype=jnp.int32)


def pow_const(a: jnp.ndarray, e: int) -> jnp.ndarray:
    """a^e for a fixed public exponent, square-and-multiply over the
    baked bit array via lax.scan (one compiled body per call site)."""
    bits = _bits_desc(e)
    one = jnp.broadcast_to(ONE, a.shape).astype(jnp.int32)

    def body(acc, bit):
        acc = sq(acc)
        return select(jnp.broadcast_to(bit == 1, a.shape[1:]),
                      mul(acc, a), acc), None

    out, _ = jax.lax.scan(body, one, bits)
    return out


def inv(a: jnp.ndarray) -> jnp.ndarray:
    """Fermat inverse (inv(0) = 0, branch-free)."""
    return pow_const(a, P_INT - 2)


def sqrt(a: jnp.ndarray) -> tuple[jnp.ndarray, jnp.ndarray]:
    """(ok (B,), root): p = 3 mod 4 so the candidate is a^((p+1)/4);
    ok is the was-square check."""
    r = pow_const(a, (P_INT + 1) // 4)
    return eq(sq(r), a), r


def sgn0(a: jnp.ndarray) -> jnp.ndarray:
    """Parity of the canonical integer value (RFC 9380 sgn0 for m=1)."""
    return from_mont(a)[0] & 1


# ---- host packing -------------------------------------------------------


def bytes_be_to_limbs(rows: np.ndarray) -> np.ndarray:
    """(B, 48) uint8 big-endian field elements -> (35, B) int32 raw
    limbs: unpack to 384 LE bits, pad to 385, regroup by 11."""
    le = np.ascontiguousarray(rows[:, ::-1])
    bits = np.unpackbits(le, axis=1, bitorder="little")  # (B, 384)
    bits = np.concatenate(
        [bits, np.zeros((rows.shape[0], 1), dtype=np.uint8)], axis=1)
    weights = (1 << np.arange(RADIX, dtype=np.int32))
    limbs = (bits.reshape(rows.shape[0], NLIMBS, RADIX)
             * weights[None, None, :]).sum(axis=2, dtype=np.int32)
    return np.ascontiguousarray(limbs.T)


def limbs_to_bytes_be(limbs: np.ndarray) -> np.ndarray:
    """(35, B) canonical raw limbs -> (B, 48) uint8 big-endian."""
    limbs = np.asarray(limbs).T.astype(np.int64)  # (B, 35)
    shifts = np.arange(RADIX, dtype=np.int64)
    bits = ((limbs[:, :, None] >> shifts[None, None, :]) & 1).astype(np.uint8)
    bits = bits.reshape(limbs.shape[0], NLIMBS * RADIX)[:, :384]
    le = np.packbits(bits, axis=1, bitorder="little")
    return np.ascontiguousarray(le[:, ::-1])
