"""Fp2 = Fp[u]/(u^2 + 1) on the batch axis: an element is a NamedTuple of
two (35, B) Montgomery limb arrays. Mirrors crypto/fallback.py's f2_*
oracle functions one-for-one (tests assert bit-consistency)."""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp

from cometbft_tpu.crypto import fallback as _oracle
from cometbft_tpu.ops.bls12381 import fp


class Fp2(NamedTuple):
    a: jnp.ndarray  # real component, (35, B) Montgomery limbs
    b: jnp.ndarray  # u component


def broadcast_const(c, shape) -> Fp2:
    """Python-int pair (oracle Fp2) -> broadcast Montgomery constant."""
    p = fp.P_INT
    ca = fp._const(c[0] % p * fp.R_MOD_P % p)
    cb = fp._const(c[1] % p * fp.R_MOD_P % p)
    return Fp2(jnp.broadcast_to(ca, shape).astype(jnp.int32),
               jnp.broadcast_to(cb, shape).astype(jnp.int32))


def zero(bshape) -> Fp2:
    z = jnp.zeros(bshape, dtype=jnp.int32)
    return Fp2(z, z)


def one(bshape) -> Fp2:
    return Fp2(jnp.broadcast_to(fp.ONE, bshape).astype(jnp.int32),
               jnp.zeros(bshape, dtype=jnp.int32))


def add(x: Fp2, y: Fp2) -> Fp2:
    return Fp2(fp.add(x.a, y.a), fp.add(x.b, y.b))


def sub(x: Fp2, y: Fp2) -> Fp2:
    return Fp2(fp.sub(x.a, y.a), fp.sub(x.b, y.b))


def neg(x: Fp2) -> Fp2:
    return Fp2(fp.neg(x.a), fp.neg(x.b))


def stack(parts) -> Fp2:
    """k independent Fp2 values -> one k-wide value (lane batching)."""
    return Fp2(fp.stack([p.a for p in parts]),
               fp.stack([p.b for p in parts]))


def split(x: Fp2, k: int):
    return [Fp2(a, b) for a, b in zip(fp.split(x.a, k), fp.split(x.b, k))]


def mul(x: Fp2, y: Fp2) -> Fp2:
    """Karatsuba with the three Fp products STACKED into one 3-wide
    fp.mul — one conv instead of three (the plain-sum third lane has
    limbs <= 2^12, inside the conv's proven bound)."""
    prod = fp.mul(fp.stack([x.a, x.b, x.a + x.b]),
                  fp.stack([y.a, y.b, y.a + y.b]))
    t0, t1, t2 = fp.split(prod, 3)
    return Fp2(fp.sub(t0, t1), fp.sub2(t2, t0, t1))


def sq(x: Fp2) -> Fp2:
    prod = fp.mul(fp.stack([x.a + x.b, x.a]),
                  fp.stack([fp.sub(x.a, x.b), x.b]))
    u, v = fp.split(prod, 2)
    return Fp2(u, fp.mul_small(v, 2))


def conj(x: Fp2) -> Fp2:
    return Fp2(x.a, fp.neg(x.b))


def mul_fp(x: Fp2, k: jnp.ndarray) -> Fp2:
    return Fp2(fp.mul(x.a, k), fp.mul(x.b, k))


def mul_small(x: Fp2, k: int) -> Fp2:
    return Fp2(fp.mul_small(x.a, k), fp.mul_small(x.b, k))


def mul_xi(x: Fp2) -> Fp2:
    """(1 + u) * x — the tower non-residue."""
    return Fp2(fp.sub(x.a, x.b), fp.add(x.a, x.b))


def inv(x: Fp2) -> Fp2:
    """Fermat through the norm; inv(0) = 0 (branch-free inv0)."""
    n = fp.inv(fp.add(fp.sq(x.a), fp.sq(x.b)))
    return Fp2(fp.mul(x.a, n), fp.neg(fp.mul(x.b, n)))


def is_zero(x: Fp2) -> jnp.ndarray:
    return fp.is_zero(x.a) & fp.is_zero(x.b)


def eq(x: Fp2, y: Fp2) -> jnp.ndarray:
    return is_zero(sub(x, y))


def select(m: jnp.ndarray, x: Fp2, y: Fp2) -> Fp2:
    return Fp2(fp.select(m, x.a, y.a), fp.select(m, x.b, y.b))


def pow_const(x: Fp2, e: int) -> Fp2:
    bits = fp._bits_desc(e)
    acc0 = one(x.a.shape)

    def body(acc, bit):
        acc = sq(Fp2(*acc))
        nxt = select(jnp.broadcast_to(bit == 1, x.a.shape[1:]),
                     mul(acc, x), acc)
        return tuple(nxt), None

    out, _ = jax.lax.scan(body, tuple(acc0), bits)
    return Fp2(*out)


def is_square(x: Fp2) -> jnp.ndarray:
    """norm(x)^((p-1)/2) != p-1 (zero counts as square)."""
    n = fp.add(fp.sq(x.a), fp.sq(x.b))
    leg = fp.pow_const(n, (fp.P_INT - 1) // 2)
    return ~fp.eq(leg, _minus_one_mont(leg.shape))


def _minus_one_mont(shape):
    c = fp._const((fp.P_INT - 1) * fp.R_MOD_P % fp.P_INT)
    return jnp.broadcast_to(c, shape).astype(jnp.int32)


def sqrt(x: Fp2) -> tuple[jnp.ndarray, Fp2]:
    """(ok, root) — algorithm 9 of eprint 2012/685 for p = 3 mod 4,
    branch-free; ok is the final root check (False for non-squares)."""
    a1 = pow_const(x, (fp.P_INT - 3) // 4)
    alpha = mul(sq(a1), x)
    x0 = mul(a1, x)
    minus1 = Fp2(_minus_one_mont(x.a.shape),
                 jnp.zeros_like(x.a))
    is_m1 = eq(alpha, minus1)
    # u * x0 branch vs (1 + alpha)^((p-1)/2) * x0 branch
    ux0 = Fp2(fp.neg(x0.b), x0.a)
    b = pow_const(add(one(x.a.shape), alpha), (fp.P_INT - 1) // 2)
    cand = select(is_m1, ux0, mul(b, x0))
    ok = eq(sq(cand), x)
    return ok, cand


def sgn0(x: Fp2) -> jnp.ndarray:
    """RFC 9380 sgn0 for m = 2."""
    ra = fp.from_mont(x.a)
    rb = fp.from_mont(x.b)
    s0 = ra[0] & 1
    z0 = jnp.all(ra == 0, axis=0)
    return s0 | (z0 & (rb[0] & 1))


def canon_ints(x: Fp2):
    """Host read: -> (a_limbs, b_limbs) canonical raw (non-Montgomery)."""
    return fp.from_mont(x.a), fp.from_mont(x.b)


def from_oracle_ints(pairs, b: int | None = None) -> Fp2:
    """Host stage: list of oracle (a, b) int pairs -> device Fp2 batch."""
    import numpy as np

    p = fp.P_INT
    a = fp.ints_to_limbs([int(c[0]) % p * fp.R_MOD_P % p for c in pairs])
    bb = fp.ints_to_limbs([int(c[1]) % p * fp.R_MOD_P % p for c in pairs])
    return Fp2(jnp.asarray(np.ascontiguousarray(a)),
               jnp.asarray(np.ascontiguousarray(bb)))


def to_oracle_ints(x: Fp2) -> list:
    """Host read: device Fp2 batch -> list of oracle (a, b) int pairs."""
    import numpy as np

    a, b = canon_ints(x)
    av = fp.limbs_to_ints(np.asarray(a))
    bv = fp.limbs_to_ints(np.asarray(b))
    return [(x0, x1) for x0, x1 in zip(av, bv)]
