"""Fp6/Fp12 towers on the batch axis — mirrors fallback.py's f6_*/f12_*
oracle functions (Fp6 = Fp2[v]/(v^3 - xi), Fp12 = Fp6[w]/(w^2 - v)).
Frobenius constants are lifted from the oracle's computed gammas, never
transcribed."""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp

from cometbft_tpu.crypto import fallback as _oracle
from cometbft_tpu.ops.bls12381 import fp
from cometbft_tpu.ops.bls12381 import fp2
from cometbft_tpu.ops.bls12381.fp2 import Fp2


class Fp6(NamedTuple):
    c0: Fp2
    c1: Fp2
    c2: Fp2


class Fp12(NamedTuple):
    d0: Fp6
    d1: Fp6


def f6_zero(bshape) -> Fp6:
    z = fp2.zero(bshape)
    return Fp6(z, z, z)


def f6_one(bshape) -> Fp6:
    return Fp6(fp2.one(bshape), fp2.zero(bshape), fp2.zero(bshape))


def f6_add(x: Fp6, y: Fp6) -> Fp6:
    return Fp6(fp2.add(x.c0, y.c0), fp2.add(x.c1, y.c1), fp2.add(x.c2, y.c2))


def f6_sub(x: Fp6, y: Fp6) -> Fp6:
    return Fp6(fp2.sub(x.c0, y.c0), fp2.sub(x.c1, y.c1), fp2.sub(x.c2, y.c2))


def f6_neg(x: Fp6) -> Fp6:
    return Fp6(fp2.neg(x.c0), fp2.neg(x.c1), fp2.neg(x.c2))


def f6_mul(x: Fp6, y: Fp6) -> Fp6:
    """Toom-style interpolation with all six Fp2 products stacked into
    ONE 6-wide fp2.mul (18 Fp muls -> one 18-wide conv)."""
    xs = fp2.stack([x.c0, x.c1, x.c2, fp2.add(x.c1, x.c2),
                    fp2.add(x.c0, x.c1), fp2.add(x.c0, x.c2)])
    ys = fp2.stack([y.c0, y.c1, y.c2, fp2.add(y.c1, y.c2),
                    fp2.add(y.c0, y.c1), fp2.add(y.c0, y.c2)])
    t0, t1, t2, m12, m01, m02 = fp2.split(fp2.mul(xs, ys), 6)
    c0 = fp2.add(t0, fp2.mul_xi(fp2.sub(m12, fp2.add(t1, t2))))
    c1 = fp2.add(fp2.sub(m01, fp2.add(t0, t1)), fp2.mul_xi(t2))
    c2 = fp2.add(fp2.sub(m02, fp2.add(t0, t2)), t1)
    return Fp6(c0, c1, c2)


def f6_stack(parts) -> Fp6:
    return Fp6(fp2.stack([p.c0 for p in parts]),
               fp2.stack([p.c1 for p in parts]),
               fp2.stack([p.c2 for p in parts]))


def f6_split(x: Fp6, k: int):
    return [Fp6(a, b, c) for a, b, c in zip(
        fp2.split(x.c0, k), fp2.split(x.c1, k), fp2.split(x.c2, k))]


def f6_mul_v(x: Fp6) -> Fp6:
    return Fp6(fp2.mul_xi(x.c2), x.c0, x.c1)


def f6_inv(x: Fp6) -> Fp6:
    c0 = fp2.sub(fp2.sq(x.c0), fp2.mul_xi(fp2.mul(x.c1, x.c2)))
    c1 = fp2.sub(fp2.mul_xi(fp2.sq(x.c2)), fp2.mul(x.c0, x.c1))
    c2 = fp2.sub(fp2.sq(x.c1), fp2.mul(x.c0, x.c2))
    t = fp2.inv(fp2.add(fp2.mul(x.c0, c0), fp2.mul_xi(
        fp2.add(fp2.mul(x.c2, c1), fp2.mul(x.c1, c2)))))
    return Fp6(fp2.mul(c0, t), fp2.mul(c1, t), fp2.mul(c2, t))


def f12_one(bshape) -> Fp12:
    return Fp12(f6_one(bshape), f6_zero(bshape))


def f12_mul(x: Fp12, y: Fp12) -> Fp12:
    """Karatsuba with the three Fp6 products stacked (one 54-wide conv
    per Fp12 multiply — the lane-batching that keeps the Miller scan
    body's HLO small enough to compile in seconds)."""
    xs = f6_stack([x.d0, x.d1, f6_add(x.d0, x.d1)])
    ys = f6_stack([y.d0, y.d1, f6_add(y.d0, y.d1)])
    t0, t1, t3 = f6_split(f6_mul(xs, ys), 3)
    d1 = f6_sub(f6_sub(t3, t0), t1)
    return Fp12(f6_add(t0, f6_mul_v(t1)), d1)


def f12_sq(x: Fp12) -> Fp12:
    """Complex squaring: the two Fp6 muls stacked into one."""
    xs = f6_stack([x.d0, f6_add(x.d0, x.d1)])
    ys = f6_stack([x.d1, f6_add(x.d0, f6_mul_v(x.d1))])
    t0, a = f6_split(f6_mul(xs, ys), 2)
    d0 = f6_sub(f6_sub(a, t0), f6_mul_v(t0))
    return Fp12(d0, f6_add(t0, t0))


def f12_conj(x: Fp12) -> Fp12:
    return Fp12(x.d0, f6_neg(x.d1))


def f12_inv(x: Fp12) -> Fp12:
    t = f6_inv(f6_sub(f6_mul(x.d0, x.d0), f6_mul_v(f6_mul(x.d1, x.d1))))
    return Fp12(f6_mul(x.d0, t), f6_neg(f6_mul(x.d1, t)))


def f12_select(m: jnp.ndarray, x: Fp12, y: Fp12) -> Fp12:
    return jax.tree_util.tree_map(
        lambda a, b: fp.select(m, a, b), x, y)


def f12_eq_one(x: Fp12) -> jnp.ndarray:
    """(B,) mask: x == 1."""
    bshape = x.d0.c0.a.shape
    ok = fp2.eq(x.d0.c0, fp2.one(bshape))
    for c in (x.d0.c1, x.d0.c2, x.d1.c0, x.d1.c1, x.d1.c2):
        ok = ok & fp2.is_zero(c)
    return ok


# Frobenius p^n: coefficients conjugated n-odd, times the oracle gammas.
def _gamma(n: int, k: int):
    g1 = _oracle._FROB_G1
    if n == 1:
        return g1[k]
    # compose: gamma_{n,k} = xi^(k (p^n - 1)/6) computed via oracle pow
    return _oracle.f2_pow(_oracle.BLS_XI,
                          k * (_oracle.BLS_P ** n - 1) // 6)


def f12_frob(x: Fp12, n: int = 1) -> Fp12:
    """x^(p^n) via coefficient conjugation + computed gamma constants."""
    bshape = x.d0.c0.a.shape
    odd = n % 2 == 1

    def coef(c: Fp2, k: int) -> Fp2:
        cc = fp2.conj(c) if odd else c
        return fp2.mul(cc, fp2.broadcast_const(_gamma(n, k), bshape))

    d0 = Fp6(coef(x.d0.c0, 0), coef(x.d0.c1, 2), coef(x.d0.c2, 4))
    d1 = Fp6(coef(x.d1.c0, 1), coef(x.d1.c1, 3), coef(x.d1.c2, 5))
    return Fp12(d0, d1)


def f12_exp_bits(x: Fp12, bits: jnp.ndarray) -> Fp12:
    """x^e with e's MSB-first bits as a traced array — ONE compiled scan
    serves every fixed exponent of the same bit length (the final-exp
    chain reuses it for |x| and |x-1|)."""
    bshape = x.d0.c0.a.shape
    acc0 = f12_one(bshape)
    flat_x, tree = jax.tree_util.tree_flatten(x)

    def body(acc_flat, bit):
        acc = jax.tree_util.tree_unflatten(tree, acc_flat)
        acc = f12_sq(acc)
        nxt = f12_select(jnp.broadcast_to(bit == 1, bshape[1:]),
                         f12_mul(acc, jax.tree_util.tree_unflatten(
                             tree, flat_x)), acc)
        return jax.tree_util.tree_flatten(nxt)[0], None

    out, _ = jax.lax.scan(body, jax.tree_util.tree_flatten(acc0)[0], bits)
    return jax.tree_util.tree_unflatten(tree, out)


def f12_exp_const(x: Fp12, e: int) -> Fp12:
    """x^e for a fixed nonnegative exponent (bits baked)."""
    assert e >= 0
    return f12_exp_bits(x, fp._bits_desc(e))


def from_oracle(el, b: int) -> Fp12:
    """Oracle nested-tuple Fp12 -> broadcast device batch of width b."""
    shape = (fp.NLIMBS, b)

    def c2(c):
        return fp2.broadcast_const(c, shape)

    return Fp12(Fp6(c2(el[0][0]), c2(el[0][1]), c2(el[0][2])),
                Fp6(c2(el[1][0]), c2(el[1][1]), c2(el[1][2])))


def to_oracle(x: Fp12) -> list:
    """Device Fp12 batch -> list of oracle nested tuples (host read)."""
    comps = [fp2.to_oracle_ints(c) for c in
             (x.d0.c0, x.d0.c1, x.d0.c2, x.d1.c0, x.d1.c1, x.d1.c2)]
    b = len(comps[0])
    return [((comps[0][j], comps[1][j], comps[2][j]),
             (comps[3][j], comps[4][j], comps[5][j])) for j in range(b)]
