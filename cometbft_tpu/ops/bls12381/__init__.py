"""Vectorized BLS12-381 arithmetic on the batch axis.

The scheme's device plane, built in the style of ops/field.py /
ops/curve.py: packed-limb Fp (fp.py), the Fp2/Fp6/Fp12 towers
(fp2.py, tower.py), G1/G2 in complete projective coordinates with batch
add/double/fixed-scalar ladders (curve.py), the optimal-ate Miller loop
and final exponentiation (pairing.py), and the hash-to-curve pipeline
(htc.py). One lane = one field element / point / pairing; the limb axis
is major so the batch axis lands on vector lanes, exactly like the
ed25519 kernel's layout.

The host twin for every function here is the pure-Python oracle in
crypto/fallback.py — tests/test_ops_bls.py asserts bit-consistency.
"""
