"""Vectorized hash-to-curve for G2 (draft-irtf-cfrg-hash-to-curve
pipeline, generic SvdW map — the oracle twin is fallback.bls_hash_to_g2).

Split host/device the way the ed25519 kernel splits SHA-512 from curve
math: expand_message_xmd is 32-bit SHA-256 word arithmetic (host, riding
ops/hashvec.sha256_many for rung accounting, batched ACROSS messages —
the per-message chaining is sequential by construction), while
hash_to_field reduction, the SvdW map, and cofactor clearing are batch
field arithmetic (device)."""

from __future__ import annotations

import numpy as np

import jax.numpy as jnp

from cometbft_tpu.crypto import fallback as _oracle
from cometbft_tpu.ops.bls12381 import fp
from cometbft_tpu.ops.bls12381 import fp2
from cometbft_tpu.ops.bls12381 import points as pts
from cometbft_tpu.ops.bls12381.fp2 import Fp2

_LEN = 2 * 2 * _oracle._H2F_L  # 256 uniform bytes per message


def expand_messages(msgs: list[bytes], dst: bytes) -> list[bytes]:
    """expand_message_xmd over a batch of messages: 9 hashvec.sha256_many
    calls of B rows each instead of 9*B hashlib calls."""
    from cometbft_tpu.ops import hashvec

    if len(dst) > 255:
        import hashlib

        dst = hashlib.sha256(b"H2C-OVERSIZE-DST-" + dst).digest()
    ell = -(-_LEN // 32)
    dst_prime = dst + bytes([len(dst)])
    z_pad = bytes(64)
    l_i_b = _LEN.to_bytes(2, "big")
    b0 = hashvec.sha256_many(
        [z_pad + m + l_i_b + b"\x00" + dst_prime for m in msgs])
    prev = hashvec.sha256_many(
        [bytes(b0[j]) + b"\x01" + dst_prime for j in range(len(msgs))])
    chunks = [prev]
    b0b = [bytes(b0[j]) for j in range(len(msgs))]
    for i in range(2, ell + 1):
        prev = hashvec.sha256_many(
            [bytes(x ^ y for x, y in zip(b0b[j], bytes(prev[j])))
             + bytes([i]) + dst_prime for j in range(len(msgs))])
        chunks.append(prev)
    return [b"".join(bytes(c[j]) for c in chunks)[:_LEN]
            for j in range(len(msgs))]


def hash_to_field_limbs(msgs: list[bytes], dst: bytes):
    """B messages -> two Fp2 element batches as RAW (non-Montgomery)
    limb planes (u0a, u0b, u1a, u1b), each (35, B) — host staging; the
    512-bit-to-Fp reduction happens in exact host integers (cheap and
    bit-identical to the oracle by construction)."""
    uniform = expand_messages(msgs, dst)
    planes = [[], [], [], []]
    for u in uniform:
        for k in range(4):
            off = _oracle._H2F_L * k
            planes[k].append(
                int.from_bytes(u[off:off + _oracle._H2F_L], "big")
                % _oracle.BLS_P)
    return tuple(fp.ints_to_limbs(p) for p in planes)


def svdw_map(u: Fp2) -> pts.Point:
    """Branch-free map_to_curve_svdw on the twist (constants baked from
    the oracle's self-validated setup)."""
    z, c1, c2, c3, c4 = _oracle._bls_setup()["svdw"]
    bshape = u.a.shape
    Z = fp2.broadcast_const(z, bshape)
    C1 = fp2.broadcast_const(c1, bshape)
    C2 = fp2.broadcast_const(c2, bshape)
    C3 = fp2.broadcast_const(c3, bshape)
    C4 = fp2.broadcast_const(c4, bshape)
    B2 = fp2.broadcast_const(_oracle._B2, bshape)

    def g(x):
        return fp2.add(fp2.mul(fp2.sq(x), x), B2)

    tv1 = fp2.mul(fp2.sq(u), C1)
    tv2 = fp2.add(fp2.one(bshape), tv1)
    tv1 = fp2.sub(fp2.one(bshape), tv1)
    tv3 = fp2.inv(fp2.mul(tv1, tv2))  # inv0 built in
    tv4 = fp2.mul(fp2.mul(u, tv1), fp2.mul(tv3, C3))
    x1 = fp2.sub(C2, tv4)
    x2 = fp2.add(C2, tv4)
    x3 = fp2.add(fp2.mul(fp2.sq(fp2.mul(fp2.sq(tv2), tv3)), C4), Z)
    e1 = fp2.is_square(g(x1))
    e2 = fp2.is_square(g(x2)) & ~e1
    x = fp2.select(e1, x1, fp2.select(e2, x2, x3))
    _, y = fp2.sqrt(g(x))
    flip = fp2.sgn0(u) != fp2.sgn0(y)
    y = fp2.select(flip, fp2.neg(y), y)
    return pts.from_affine(pts.G2Field, x, y)


def map_to_g2(u0: Fp2, u1: Fp2) -> pts.Point:
    """SvdW both field elements, add, clear the (calibrated) cofactor —
    projective output in the r-order subgroup."""
    h2 = _oracle._bls_setup()["h2"]
    q = pts.add(pts.G2Field, svdw_map(u0), svdw_map(u1))
    return pts.mul_const(pts.G2Field, q, h2)


def hash_to_g2_device(msgs: list[bytes], dst: bytes) -> pts.Point:
    """Full pipeline for a batch of messages (host expand + device map)."""
    u0a, u0b, u1a, u1b = hash_to_field_limbs(msgs, dst)
    u0 = Fp2(fp.to_mont(jnp.asarray(np.ascontiguousarray(u0a))),
             fp.to_mont(jnp.asarray(np.ascontiguousarray(u0b))))
    u1 = Fp2(fp.to_mont(jnp.asarray(np.ascontiguousarray(u1a))),
             fp.to_mont(jnp.asarray(np.ascontiguousarray(u1b))))
    return map_to_g2(u0, u1)
