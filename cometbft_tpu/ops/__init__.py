"""TPU device kernels (JAX/XLA) — the framework's dense-compute layer.

The only data-parallel compute in a BFT consensus engine is signature
verification (reference: types/validation.go:153-257, the batch verifier at
crypto/ed25519/ed25519.go:208-241). Here it becomes a lane-parallel device
program: each TPU vector lane verifies one Ed25519 signature under ZIP-215
semantics, producing a per-lane validity mask (the reference needs a serial
re-verify fallback to pinpoint bad signatures; on TPU the mask is free).

Layout:
  limbs.py            host-side numpy packing: bytes/ints <-> limb arrays
  field.py            GF(2^255-19) arithmetic, radix-2^13 x 20 limbs, int32
  curve.py            edwards25519 point ops, decompression, Straus ladder
  ed25519_kernel.py   jitted batch-verify entry + host glue (hashing, padding)
  batch_verifier.py   crypto.BatchVerifier implementation backed by the kernel
"""
