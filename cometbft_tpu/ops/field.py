"""GF(2^255 - 19) arithmetic on TPU vector lanes.

Representation: radix-2^13, 20 limbs (260 bits), little-endian, int32,
LIMB-AXIS FIRST: a field element batch is shape (20, B). The batch axis is
minor-most so it lands on the TPU's 128-wide vector lanes (one lane = one
element); the 20-limb axis sits on sublanes. The transposed layout is worth
~6x utilization over (B, 20), where the limb axis would waste 108/128 lanes.
Chosen so every intermediate of a schoolbook 20x20 limb convolution fits
signed int32 — the TPU VPU's native integer width (no int64, no widening
multiply): carried limbs are <= CARRIED_MAX, so each product is < 2^26.3 and
a 20-term column sum is < 2^31.

Invariant ("carried"): limbs in [0, CARRIED_MAX]. add/sub/mul/sq take and
return carried values. Values are redundant mod p (anywhere in [0, ~2^260));
canonicalize() produces the unique representative in [0, p) for comparisons,
parity checks, and re-compression.

Reference seam: this replaces the 64-bit limb arithmetic inside
curve25519-voi that the Go reference leans on (crypto/ed25519/ed25519.go:37);
the design here is TPU-native, not a translation.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from cometbft_tpu.ops import limbs as L

RADIX = L.RADIX
NLIMBS = L.NLIMBS
MASK = L.MASK

P = 2**255 - 19
# 2^260 mod p = 2^5 * 19: the fold multiplier for carry-out of limb 19.
FOLD = 19 << (NLIMBS * RADIX - 255)  # 608

# d, 2d, sqrt(-1) as limb constants.
_D_INT = (-121665 * pow(121666, P - 2, P)) % P
_SQRT_M1_INT = pow(2, (P - 1) // 4, P)


def _const(x: int) -> jnp.ndarray:
    """(20, 1) so constants broadcast over the trailing batch axis."""
    return jnp.asarray(L.int_to_limbs(x), dtype=jnp.int32)[:, None]


def _const_loose(x: int) -> jnp.ndarray:
    """Constant whose top limb may exceed 13 bits (used for the subtraction
    bias M = 33p, which is 261 bits)."""
    out = np.zeros(NLIMBS, dtype=np.int64)
    for i in range(NLIMBS - 1):
        out[i] = x & MASK
        x >>= RADIX
    out[NLIMBS - 1] = x
    assert x < 2**15
    return jnp.asarray(out, dtype=jnp.int32)[:, None]


P_LIMBS = _const(P)
D = _const(_D_INT)
D2 = _const((2 * _D_INT) % P)
SQRT_M1 = _const(_SQRT_M1_INT)
ONE = _const(1)
# Subtraction bias: smallest multiple of p that dominates any carried value
# (carried max ~ 2^260 + 2^251 < 33p), keeping a + M - b positive.
M_SUB = _const_loose(33 * P)


def zeros_like(a: jnp.ndarray) -> jnp.ndarray:
    return jnp.zeros_like(a)


# Carried-limb invariant: limbs in [0, CARRIED_MAX]. The parallel carry
# rounds below converge to this bound (not to a strict 13 bits) — sized so a
# 20-term product column still fits int32: 20 * 8800^2 = 1.55e9 < 2^31.
CARRIED_MAX = 8800


def _carry_round20(x: jnp.ndarray) -> jnp.ndarray:
    """One parallel carry round on 20 limbs with top wrap (2^260 = FOLD):
    whole-array shift/mask/roll — no sequential limb chain, so the HLO stays
    tiny and XLA vectorizes across the batch AND limb axes. Arithmetic
    right-shift floors, so negative intermediates (from sub) carry
    correctly."""
    c = x >> RADIX
    r = x & MASK
    shifted = jnp.concatenate([c[NLIMBS - 1:] * FOLD, c[: NLIMBS - 1]], axis=0)
    return r + shifted


def weak_carry(x: jnp.ndarray) -> jnp.ndarray:
    """Reduce limbs to the carried range. Three rounds handle any input with
    |limb| <= ~2^15 (add/sub magnitudes); post-convolution values go through
    _conv_reduce which runs more rounds."""
    for _ in range(3):
        x = _carry_round20(x)
    return x


def add(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    return weak_carry(a + b)


def sub(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    return weak_carry(a + M_SUB - b)


def neg(a: jnp.ndarray) -> jnp.ndarray:
    return weak_carry(M_SUB - a)


_NCONV = 2 * NLIMBS  # 39 product columns + 1 carry headroom column


def _carry_round40(x: jnp.ndarray) -> jnp.ndarray:
    """Parallel carry round on the 40-column product vector. Carry out of
    column 39 (value 2^(13*40) = 2^260 * 2^260) wraps to column 20 with
    factor FOLD, keeping the ring closed without a sequential chain."""
    c = x >> RADIX
    r = x & MASK
    shifted = jnp.concatenate(
        [
            jnp.zeros_like(c[:1]),
            c[: NLIMBS - 1],
            c[NLIMBS - 1: NLIMBS] + c[_NCONV - 1:] * FOLD,
            c[NLIMBS: _NCONV - 1],
        ],
        axis=0,
    )
    return r + shifted


def _conv_reduce(conv: jnp.ndarray) -> jnp.ndarray:
    """(..., 40) product columns (col 39 zero) -> carried (..., 20):
    4 parallel carry rounds, fold 2^260 = FOLD, 3 more rounds."""
    for _ in range(4):
        conv = _carry_round40(conv)
    folded = conv[:NLIMBS] + FOLD * conv[NLIMBS:]
    return weak_carry(folded)


def _conv(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """Schoolbook polynomial product as one outer product + shifted row
    sums: row i of the (20, 20) product tensor lands at columns i..i+19."""
    prods = a[:, None] * b[None, :]  # (20, 20, ...)
    acc = None
    for i in range(NLIMBS):
        row = jnp.pad(prods[i], [(i, _NCONV - NLIMBS - i)] + [(0, 0)] * (prods.ndim - 2))
        acc = row if acc is None else acc + row
    return acc


def mul(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    return _conv_reduce(_conv(a, b))


def sq(a: jnp.ndarray) -> jnp.ndarray:
    return _conv_reduce(_conv(a, a))


def _sqn(x: jnp.ndarray, n: int) -> jnp.ndarray:
    """x^(2^n) via n squarings. Uses fori_loop so the HLO stays small for
    the long runs inside the inversion/sqrt addition chains."""
    if n <= 4:
        for _ in range(n):
            x = sq(x)
        return x
    return jax.lax.fori_loop(0, n, lambda _, v: sq(v), x)


def pow22523(z: jnp.ndarray) -> jnp.ndarray:
    """z^((p-5)/8) = z^(2^252 - 3) — the exponentiation at the heart of
    modular sqrt / point decompression. Standard ref10 addition chain
    (254 squarings + 11 multiplies), expressed with fori_loop squaring runs."""
    z2 = sq(z)
    z9 = mul(_sqn(z2, 2), z)
    z11 = mul(z9, z2)
    z_5_0 = mul(sq(z11), z9)  # 2^5 - 2^0
    z_10_0 = mul(_sqn(z_5_0, 5), z_5_0)
    z_20_0 = mul(_sqn(z_10_0, 10), z_10_0)
    z_40_0 = mul(_sqn(z_20_0, 20), z_20_0)
    z_50_0 = mul(_sqn(z_40_0, 10), z_10_0)
    z_100_0 = mul(_sqn(z_50_0, 50), z_50_0)
    z_200_0 = mul(_sqn(z_100_0, 100), z_100_0)
    z_250_0 = mul(_sqn(z_200_0, 50), z_50_0)
    return mul(_sqn(z_250_0, 2), z)


def canonicalize(x: jnp.ndarray) -> jnp.ndarray:
    """Unique representative mod p, limbs canonical, value in [0, p)."""
    x = weak_carry(x)
    top_shift = 255 - (NLIMBS - 1) * RADIX  # bit 255 within limb 19
    top_mask = (1 << top_shift) - 1
    for _ in range(3):  # fold bits >= 255 (2^255 = 19 mod p) + re-carry
        hi = x[NLIMBS - 1] >> top_shift
        x = jnp.concatenate(
            [
                (x[0] + 19 * hi)[None],
                x[1: NLIMBS - 1],
                (x[NLIMBS - 1] & top_mask)[None],
            ],
            axis=0,
        )
        x = _carry_round20(x)
    l = [x[i] for i in range(NLIMBS)]
    # value now < 2^255 + eps < 2p: one conditional subtract of p.
    pl = [P_LIMBS[i, 0] for i in range(NLIMBS)]
    borrow = jnp.zeros_like(l[0])
    sub_l = []
    for i in range(NLIMBS):
        v = l[i] - pl[i] - borrow
        borrow = (v < 0).astype(jnp.int32)
        sub_l.append(v + (borrow << RADIX))
    ge_p = borrow == 0
    out = [jnp.where(ge_p, sub_l[i], l[i]) for i in range(NLIMBS)]
    return jnp.stack(out, axis=0)


def is_zero(x: jnp.ndarray) -> jnp.ndarray:
    """(20, ...) -> (...,) bool: x == 0 mod p."""
    return jnp.all(canonicalize(x) == 0, axis=0)


def eq(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    return is_zero(sub(a, b))


def parity(x: jnp.ndarray) -> jnp.ndarray:
    """LSB of the canonical representative (the compressed sign bit)."""
    return canonicalize(x)[0] & 1
