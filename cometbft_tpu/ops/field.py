"""GF(2^255 - 19) arithmetic on TPU vector lanes.

Representation: radix-2^13, 20 limbs (260 bits), little-endian, int32,
LIMB-AXIS FIRST: a field element batch is shape (20, B). The batch axis is
minor-most so it lands on the TPU's 128-wide vector lanes (one lane = one
element); the 20-limb axis sits on sublanes. The transposed layout is worth
~6x utilization over (B, 20), where the limb axis would waste 108/128 lanes.
Chosen so every intermediate of a schoolbook 20x20 limb convolution fits
signed int32 — the TPU VPU's native integer width (no int64, no widening
multiply).

Invariant ("carried"): per-limb SIGNED intervals — the least fixpoint of
{mul, sq, add, sub, neg} over their own outputs, computed and proved int32-
safe by tests/test_field_intervals.py (see the block comment above
CARRIED_MAX; the naive "every limb small enough for any column sum" bound
does NOT hold). add/sub/mul/sq take and return carried values. Values are
redundant mod p (anywhere in [0, ~2^260)); canonicalize() produces the
unique representative in [0, p) for comparisons, parity checks, and
re-compression.

Reference seam: this replaces the 64-bit limb arithmetic inside
curve25519-voi that the Go reference leans on (crypto/ed25519/ed25519.go:37);
the design here is TPU-native, not a translation.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from cometbft_tpu.ops import limbs as L

RADIX = L.RADIX
NLIMBS = L.NLIMBS
MASK = L.MASK

P = 2**255 - 19
# 2^260 mod p = 2^5 * 19: the fold multiplier for carry-out of limb 19.
FOLD = 19 << (NLIMBS * RADIX - 255)  # 608

# d, 2d, sqrt(-1) as limb constants.
_D_INT = (-121665 * pow(121666, P - 2, P)) % P
_SQRT_M1_INT = pow(2, (P - 1) // 4, P)


def _const(x: int) -> jnp.ndarray:
    """(20, 1) so constants broadcast over the trailing batch axis."""
    return jnp.asarray(L.int_to_limbs(x), dtype=jnp.int32)[:, None]


def _const_loose(x: int) -> jnp.ndarray:
    """Constant whose top limb may exceed 13 bits (used for the subtraction
    bias M = 33p, which is 261 bits)."""
    out = np.zeros(NLIMBS, dtype=np.int64)
    for i in range(NLIMBS - 1):
        out[i] = x & MASK
        x >>= RADIX
    out[NLIMBS - 1] = x
    assert x < 2**15
    return jnp.asarray(out, dtype=jnp.int32)[:, None]


P_LIMBS = _const(P)
D = _const(_D_INT)
D2 = _const((2 * _D_INT) % P)
SQRT_M1 = _const(_SQRT_M1_INT)
ONE = _const(1)
# Subtraction bias: smallest multiple of p that dominates any carried value
# (carried max ~ 2^260 + 2^251 < 33p), keeping a + M - b positive.
M_SUB = _const_loose(33 * P)


def zeros_like(a: jnp.ndarray) -> jnp.ndarray:
    return jnp.zeros_like(a)


# Carried-limb invariant ("C"): per-limb signed intervals, the least
# fixpoint of {mul, sq, add, sub, neg} over their own outputs, mechanically
# verified by tests/test_field_intervals.py, which mirrors every op below in
# exact interval arithmetic and proves (a) closure, (b) every intermediate —
# conv columns included — fits int32, (c) the value bound stays under the
# subtraction bias M = 33p. The fixpoint's shape: limbs 0 and 1 reach ~25.5k
# (the 2^260 wrap concentrates carry mass there), limbs 2..19 stay ~8.2k —
# the naive "every limb below sqrt(2^31/20)" bound is FALSE, and only the
# per-limb exact analysis shows the conv columns still fit int32 (columns
# pair at most two oversized limbs). CARRIED_MAX is the checker-proved
# per-limb ceiling.
CARRIED_MAX = 25600

# Carry-round counts per op, tuned on-device (ops/microbench.py) and proved
# sufficient by the interval checker. One round is a whole-array
# shift/mask/roll; each extra round costs ~20 ns per 128-lane block inside
# the Pallas ladder, and the ladder runs ~2.6k reduced ops per signature —
# round counts are THE device-time knob of the whole kernel.
ADD_ROUNDS = 1
SUB_ROUNDS = 1
HI_ROUNDS = 1
CONV20_ROUNDS = 2


def _carry_round20(x: jnp.ndarray) -> jnp.ndarray:
    """One parallel carry round on 20 limbs with top wrap (2^260 = FOLD):
    whole-array shift/mask/roll — no sequential limb chain, so the HLO stays
    tiny and XLA vectorizes across the batch AND limb axes. Arithmetic
    right-shift floors, so negative intermediates (from sub) carry
    correctly."""
    c = x >> RADIX
    r = x & MASK
    shifted = jnp.concatenate([c[NLIMBS - 1:] * FOLD, c[: NLIMBS - 1]], axis=0)
    return r + shifted


def weak_carry(x: jnp.ndarray) -> jnp.ndarray:
    """Reduce limbs to the carried range. Three rounds handle any input with
    |limb| <= ~2^15 (add/sub magnitudes); canonicalize and the comparison
    entry points call this before interpreting limbs."""
    for _ in range(3):
        x = _carry_round20(x)
    return x


def add(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    x = a + b
    for _ in range(ADD_ROUNDS):
        x = _carry_round20(x)
    return x


def sub(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    x = a + M_SUB - b
    for _ in range(SUB_ROUNDS):
        x = _carry_round20(x)
    return x


def neg(a: jnp.ndarray) -> jnp.ndarray:
    x = M_SUB - a
    for _ in range(SUB_ROUNDS):
        x = _carry_round20(x)
    return x


_NCONV = 2 * NLIMBS  # 39 product columns + 1 carry headroom column


def _carry_round20_nowrap(x: jnp.ndarray) -> tuple[jnp.ndarray, jnp.ndarray]:
    """One carry round WITHOUT the 2^260 wrap: returns (rounded, top carry
    (1, B)). Used on the high half of the product, whose own wrap factor
    would be FOLD^2 — the top carry is folded in exactly once at the end."""
    c = x >> RADIX
    r = x & MASK
    shifted = jnp.concatenate([jnp.zeros_like(c[:1]), c[: NLIMBS - 1]], axis=0)
    return r + shifted, c[NLIMBS - 1:]


def _conv_reduce(conv: jnp.ndarray) -> jnp.ndarray:
    """(..., 40) product columns (col 39 zero) -> carried (..., 20).

    Split form: lo = cols 0..19, hi = cols 20..39 (weight 2^260 = FOLD per
    lo-column). hi is carried on 20 columns only (no 40-wide vector ever
    materializes — measured faster than carry rounds on the (40, B) array,
    ops/microbench.py), its top carries (weight 2^520 = FOLD^2 at column 0)
    are accumulated separately, then everything folds into lo and two
    20-column rounds restore the carried invariant. Round counts proved by
    tests/test_field_intervals.py."""
    lo, hi = conv[:NLIMBS], conv[NLIMBS:]
    top = None
    for _ in range(HI_ROUNDS):
        hi, t = _carry_round20_nowrap(hi)
        top = t if top is None else top + t
    folded = lo + FOLD * hi
    folded = jnp.concatenate(
        [folded[:1] + (FOLD * FOLD) * top, folded[1:]], axis=0
    )
    for _ in range(CONV20_ROUNDS):
        folded = _carry_round20(folded)
    return folded


def _conv(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """Schoolbook polynomial product, pre-rolled form: row i (a_i * b) lands
    at columns i..i+19 of the zero-extended accumulator via a sublane roll
    of the zero-padded b — Mosaic turns each roll into cheap vreg funnel
    shifts, measured 3x faster per conv than materializing jnp.pad'ed rows
    (ops/microbench.py)."""
    pad_shape = list(b.shape)
    pad_shape[0] = _NCONV - NLIMBS
    bz = jnp.concatenate([b, jnp.zeros(pad_shape, dtype=b.dtype)], axis=0)
    acc = a[0:1] * bz
    for i in range(1, NLIMBS):
        acc = acc + a[i: i + 1] * jnp.roll(bz, i, axis=0)
    return acc


def mul(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    return _conv_reduce(_conv(a, b))


def sq(a: jnp.ndarray) -> jnp.ndarray:
    return _conv_reduce(_conv(a, a))


# Squaring-run unroll threshold. Default keeps the XLA HLO small (runs of
# up to 100 squarings become fori_loops). The Pallas kernel raises it for
# the duration of its trace (pallas_verify._verify_block_kernel's
# constant-swap try/finally): inside Mosaic a fori_loop whose body is ONE
# squaring pays per-iteration loop overhead comparable to the squaring
# itself — unrolling the pow22523 chain cut the R-decompression stage ~3x
# on device (ops/microbench.py bisect probe).
SQN_UNROLL_LIMIT = 4


def _sqn(x: jnp.ndarray, n: int) -> jnp.ndarray:
    """x^(2^n) via n squarings."""
    if n <= SQN_UNROLL_LIMIT:
        for _ in range(n):
            x = sq(x)
        return x
    return jax.lax.fori_loop(0, n, lambda _, v: sq(v), x)


def pow22523(z: jnp.ndarray) -> jnp.ndarray:
    """z^((p-5)/8) = z^(2^252 - 3) — the exponentiation at the heart of
    modular sqrt / point decompression. Standard ref10 addition chain
    (254 squarings + 11 multiplies), expressed with fori_loop squaring runs."""
    z2 = sq(z)
    z9 = mul(_sqn(z2, 2), z)
    z11 = mul(z9, z2)
    z_5_0 = mul(sq(z11), z9)  # 2^5 - 2^0
    z_10_0 = mul(_sqn(z_5_0, 5), z_5_0)
    z_20_0 = mul(_sqn(z_10_0, 10), z_10_0)
    z_40_0 = mul(_sqn(z_20_0, 20), z_20_0)
    z_50_0 = mul(_sqn(z_40_0, 10), z_10_0)
    z_100_0 = mul(_sqn(z_50_0, 50), z_50_0)
    z_200_0 = mul(_sqn(z_100_0, 100), z_100_0)
    z_250_0 = mul(_sqn(z_200_0, 50), z_50_0)
    return mul(_sqn(z_250_0, 2), z)


def canonicalize(x: jnp.ndarray) -> jnp.ndarray:
    """Unique representative mod p, limbs canonical, value in [0, p)."""
    x = weak_carry(x)
    top_shift = 255 - (NLIMBS - 1) * RADIX  # bit 255 within limb 19
    top_mask = (1 << top_shift) - 1
    for _ in range(3):  # fold bits >= 255 (2^255 = 19 mod p) + re-carry
        hi = x[NLIMBS - 1] >> top_shift
        x = jnp.concatenate(
            [
                (x[0] + 19 * hi)[None],
                x[1: NLIMBS - 1],
                (x[NLIMBS - 1] & top_mask)[None],
            ],
            axis=0,
        )
        x = _carry_round20(x)
    l = [x[i] for i in range(NLIMBS)]
    # value now < 2^255 + eps < 2p: one conditional subtract of p.
    pl = [P_LIMBS[i, 0] for i in range(NLIMBS)]
    borrow = jnp.zeros_like(l[0])
    sub_l = []
    for i in range(NLIMBS):
        v = l[i] - pl[i] - borrow
        borrow = (v < 0).astype(jnp.int32)
        sub_l.append(v + (borrow << RADIX))
    ge_p = borrow == 0
    out = [jnp.where(ge_p, sub_l[i], l[i]) for i in range(NLIMBS)]
    return jnp.stack(out, axis=0)


def is_zero(x: jnp.ndarray) -> jnp.ndarray:
    """(20, ...) -> (...,) bool: x == 0 mod p."""
    return jnp.all(canonicalize(x) == 0, axis=0)


def eq(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    return is_zero(sub(a, b))


def parity(x: jnp.ndarray) -> jnp.ndarray:
    """LSB of the canonical representative (the compressed sign bit)."""
    return canonicalize(x)[0] & 1
