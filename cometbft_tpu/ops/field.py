"""GF(2^255 - 19) arithmetic on TPU vector lanes.

Representation: radix-2^13, 20 limbs (260 bits), little-endian, int32.
Chosen so every intermediate of a schoolbook 20x20 limb convolution fits
signed int32 — the TPU VPU's native integer width (no int64, no widening
multiply): carried limbs are <= 2^13 + eps, so each product is < 2^26 and a
20-term column sum is < 2^31. All ops are elementwise over arbitrary leading
batch dims: one TPU lane = one field element = one signature being verified.

Invariant ("carried"): limbs in [0, 2^13 + 16]. add/sub/mul/sq take and
return carried values. Values are redundant mod p (anywhere in [0, ~2^260));
canonicalize() produces the unique representative in [0, p) for comparisons,
parity checks, and re-compression.

Reference seam: this replaces the 64-bit limb arithmetic inside
curve25519-voi that the Go reference leans on (crypto/ed25519/ed25519.go:37);
the design here is TPU-native, not a translation.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from cometbft_tpu.ops import limbs as L

RADIX = L.RADIX
NLIMBS = L.NLIMBS
MASK = L.MASK

P = 2**255 - 19
# 2^260 mod p = 2^5 * 19: the fold multiplier for carry-out of limb 19.
FOLD = 19 << (NLIMBS * RADIX - 255)  # 608

# d, 2d, sqrt(-1) as limb constants.
_D_INT = (-121665 * pow(121666, P - 2, P)) % P
_SQRT_M1_INT = pow(2, (P - 1) // 4, P)


def _const(x: int) -> jnp.ndarray:
    return jnp.asarray(L.int_to_limbs(x), dtype=jnp.int32)


def _const_loose(x: int) -> jnp.ndarray:
    """Constant whose top limb may exceed 13 bits (used for the subtraction
    bias M = 33p, which is 261 bits)."""
    out = np.zeros(NLIMBS, dtype=np.int64)
    for i in range(NLIMBS - 1):
        out[i] = x & MASK
        x >>= RADIX
    out[NLIMBS - 1] = x
    assert x < 2**15
    return jnp.asarray(out, dtype=jnp.int32)


P_LIMBS = _const(P)
D = _const(_D_INT)
D2 = _const((2 * _D_INT) % P)
SQRT_M1 = _const(_SQRT_M1_INT)
ONE = _const(1)
# Subtraction bias: smallest multiple of p that dominates any carried value
# (carried max ~ 2^260 + 2^251 < 33p), keeping a + M - b positive.
M_SUB = _const_loose(33 * P)


def zeros_like(a: jnp.ndarray) -> jnp.ndarray:
    return jnp.zeros_like(a)


def _chain(limbs_list: list[jnp.ndarray]) -> tuple[list[jnp.ndarray], jnp.ndarray]:
    """One sequential carry pass. Arithmetic right-shift handles negative
    intermediates (from sub) correctly: v>>13 floors, v&MASK is nonneg."""
    out = []
    c = jnp.zeros_like(limbs_list[0])
    for v in limbs_list:
        v = v + c
        c = v >> RADIX
        out.append(v & MASK)
    return out, c


def weak_carry(x: jnp.ndarray) -> jnp.ndarray:
    """Reduce limbs to carried range. Two full passes + top fold: handles
    any input with |limb| < ~2^30 (covers post-convolution magnitudes)."""
    l = [x[..., i] for i in range(NLIMBS)]
    l, c = _chain(l)
    l[0] = l[0] + c * FOLD
    l, c = _chain(l)
    l[0] = l[0] + c * FOLD  # c <= 1 here
    c2 = l[0] >> RADIX
    l[0] = l[0] & MASK
    l[1] = l[1] + c2
    return jnp.stack(l, axis=-1)


def add(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    return weak_carry(a + b)


def sub(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    return weak_carry(a + M_SUB - b)


def neg(a: jnp.ndarray) -> jnp.ndarray:
    return weak_carry(M_SUB - a)


def _conv_reduce(conv: list[jnp.ndarray]) -> jnp.ndarray:
    """Carry the 39-column product convolution, fold 2^260 = FOLD, carry."""
    conv, c = _chain(conv)  # each column <= 8191, carry-out < 2^18
    lo = conv[:NLIMBS]
    hi = conv[NLIMBS:] + [c]
    out = [lo[i] + FOLD * hi[i] for i in range(NLIMBS)]
    return weak_carry(jnp.stack(out, axis=-1))


def mul(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    al = [a[..., i] for i in range(NLIMBS)]
    bl = [b[..., i] for i in range(NLIMBS)]
    conv: list = [None] * (2 * NLIMBS - 1)
    for i in range(NLIMBS):
        for j in range(NLIMBS):
            t = al[i] * bl[j]
            k = i + j
            conv[k] = t if conv[k] is None else conv[k] + t
    return _conv_reduce(conv)


def sq(a: jnp.ndarray) -> jnp.ndarray:
    al = [a[..., i] for i in range(NLIMBS)]
    conv: list = [None] * (2 * NLIMBS - 1)
    for i in range(NLIMBS):
        t = al[i] * al[i]
        conv[2 * i] = t if conv[2 * i] is None else conv[2 * i] + t
        for j in range(i + 1, NLIMBS):
            t = 2 * (al[i] * al[j])
            k = i + j
            conv[k] = t if conv[k] is None else conv[k] + t
    return _conv_reduce(conv)


def _sqn(x: jnp.ndarray, n: int) -> jnp.ndarray:
    """x^(2^n) via n squarings. Uses fori_loop so the HLO stays small for
    the long runs inside the inversion/sqrt addition chains."""
    if n <= 4:
        for _ in range(n):
            x = sq(x)
        return x
    return jax.lax.fori_loop(0, n, lambda _, v: sq(v), x)


def pow22523(z: jnp.ndarray) -> jnp.ndarray:
    """z^((p-5)/8) = z^(2^252 - 3) — the exponentiation at the heart of
    modular sqrt / point decompression. Standard ref10 addition chain
    (254 squarings + 11 multiplies), expressed with fori_loop squaring runs."""
    z2 = sq(z)
    z9 = mul(_sqn(z2, 2), z)
    z11 = mul(z9, z2)
    z_5_0 = mul(sq(z11), z9)  # 2^5 - 2^0
    z_10_0 = mul(_sqn(z_5_0, 5), z_5_0)
    z_20_0 = mul(_sqn(z_10_0, 10), z_10_0)
    z_40_0 = mul(_sqn(z_20_0, 20), z_20_0)
    z_50_0 = mul(_sqn(z_40_0, 10), z_10_0)
    z_100_0 = mul(_sqn(z_50_0, 50), z_50_0)
    z_200_0 = mul(_sqn(z_100_0, 100), z_100_0)
    z_250_0 = mul(_sqn(z_200_0, 50), z_50_0)
    return mul(_sqn(z_250_0, 2), z)


def canonicalize(x: jnp.ndarray) -> jnp.ndarray:
    """Unique representative mod p, limbs canonical, value in [0, p)."""
    x = weak_carry(x)
    l = [x[..., i] for i in range(NLIMBS)]
    for _ in range(2):  # fold bits >= 255: 2^255 = 19 mod p
        hi = l[NLIMBS - 1] >> (255 - (NLIMBS - 1) * RADIX)
        l[NLIMBS - 1] = l[NLIMBS - 1] & ((1 << (255 - (NLIMBS - 1) * RADIX)) - 1)
        l[0] = l[0] + 19 * hi
        l, c = _chain(l)
        l[0] = l[0] + c * FOLD  # c == 0 in fact; keep for safety
    # value now < 2^255 + 19 < 2p: one conditional subtract of p.
    pl = [P_LIMBS[i] for i in range(NLIMBS)]
    borrow = jnp.zeros_like(l[0])
    sub_l = []
    for i in range(NLIMBS):
        v = l[i] - pl[i] - borrow
        borrow = (v < 0).astype(jnp.int32)
        sub_l.append(v + (borrow << RADIX))
    ge_p = borrow == 0
    out = [jnp.where(ge_p, sub_l[i], l[i]) for i in range(NLIMBS)]
    return jnp.stack(out, axis=-1)


def is_zero(x: jnp.ndarray) -> jnp.ndarray:
    """(..., 20) -> (...,) bool: x == 0 mod p."""
    return jnp.all(canonicalize(x) == 0, axis=-1)


def eq(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    return is_zero(sub(a, b))


def parity(x: jnp.ndarray) -> jnp.ndarray:
    """LSB of the canonical representative (the compressed sign bit)."""
    return canonicalize(x)[..., 0] & 1
