"""Batched Ed25519 ZIP-215 verification — the jitted device entry points.

Two kernels, split so decompressed validator pubkeys can be cached across
calls (the device-resident analog of the reference's LRU expanded-key cache,
crypto/ed25519/ed25519.go:44,63-69 — a validator set re-verifies every
height, but its keys decompress once):

  decompress(words)                  -> (ok, X, Y, Z, T)
  verify(A-coords, rW, sW, kW)       -> per-lane validity mask

verify computes, per lane:  [8]([s]B - [k]A - R) == O   (cofactored,
ZIP-215), via a signed 5-bit windowed double-scalar ladder (curve.py), one
add of -R, three doublings, and a projective identity test. The mask
pinpoints bad signatures directly; the few lanes it rejects are
double-checked against the host oracle before being reported (see
_recheck_failed_lanes — the narrow analog of the reference's
fallback-to-serial re-verify, types/validation.go:266).

Wire layout (the perf-critical design point): R / s / k cross the host link
as packed (8, B) uint32 words — 96 B per signature — and are unpacked to
limbs/digits on device (ops/unpack.py). Validator pubkey coordinates live
in a device-resident batch cache keyed by the pubkey-set digest, so the
steady-state commit-verification path transfers ~1 MB per 10k-signature
batch instead of ~25 MB.

Batch sizes are bucketed to powers of two (min 8) to bound recompilation;
padding lanes carry the identity encoding (y=1) with zero scalars, which
verify as valid and are sliced off.
"""

from __future__ import annotations

import hashlib
import threading
import time as _time

import jax
import jax.numpy as jnp
import numpy as np

from cometbft_tpu.crypto import ed25519_math as oracle
from cometbft_tpu.libs import linkmodel as _linkmodel
from cometbft_tpu.libs import trace as _trace
from cometbft_tpu.ops import curve
from cometbft_tpu.ops import limbs as L
from cometbft_tpu.ops import unpack as U

MIN_BUCKET = 8
MAX_BUCKET_LOG2 = 17  # 128k lanes


def _staging_rung() -> str:
    """hashvec rung label for staging trace spans (never raises)."""
    try:
        from cometbft_tpu.ops import hashvec

        return hashvec.active_rung()
    except Exception:  # noqa: BLE001 - tracing must never break staging
        return "unknown"

_ID_ENC32 = (1).to_bytes(32, "little")  # y=1: the identity point encoding

_default_dev_id: int | None = None


def default_device_index() -> int:
    """Index of the chip the single-chip dispatch path targets — stamped
    on dispatch trace spans so a flight-recorder tree names its fault
    domain even off the mesh path (the mesh stamps its own shard index)."""
    global _default_dev_id
    if _default_dev_id is None:
        try:
            _default_dev_id = int(jax.devices()[0].id)
        except Exception:  # noqa: BLE001 - tracing must never break dispatch
            _default_dev_id = 0
    return _default_dev_id


_POW2_CAP = 2048  # above this, buckets are multiples of _POW2_CAP


def bucket_size(n: int) -> int:
    """Power-of-two buckets up to 2048, then multiples of 2048: bounds the
    number of compiled shapes (9 + 63) while capping padding waste at 20%
    for large batches (a 10240-sig mega-commit runs at exactly 10240 lanes,
    not 16384)."""
    if n > (1 << MAX_BUCKET_LOG2):
        raise ValueError(f"batch of {n} exceeds max bucket {1 << MAX_BUCKET_LOG2}")
    b = MIN_BUCKET
    while b < n and b < _POW2_CAP:
        b *= 2
    if b >= n:
        return b
    return (n + _POW2_CAP - 1) // _POW2_CAP * _POW2_CAP


@jax.jit
def _decompress_kernel(words: jnp.ndarray):
    """(8, B) uint32 packed encodings -> (ok, X, Y, Z, T) each (20, B)."""
    y = U.words_to_y_limbs(words)
    sign = U.words_sign(words)
    ok, p = curve.decompress_zip215(y, sign)
    return ok, p.x, p.y, p.z, p.t


def verify_math(ax, ay, az, at, r_words, s_words, k_words) -> jnp.ndarray:
    """The per-chip verify program (also the shard_map body, parallel/mesh).
    A-coords (20, B) int32; r/s/k packed (8, B) uint32. Lanes whose pubkey
    failed decompression produce garbage — the caller masks with ok_a."""
    y_r = U.words_to_y_limbs(r_words)
    sign_r = U.words_sign(r_words)
    ok_r, r = curve.decompress_zip215(y_r, sign_r)
    neg_a = curve.neg(curve.Point(ax, ay, az, at))
    sb_ka = curve.windowed_double_scalar_signed(
        U.words_to_digits5_signed(s_words), U.words_to_digits5_signed(k_words), neg_a
    )
    diff = curve.add(sb_ka, curve.neg(r))
    valid = curve.is_identity(curve.mul_by_cofactor(diff))
    return valid & ok_r


_verify_kernel = jax.jit(verify_math)


def verify_math_ok(ax, ay, az, at, r_words, s_words, k_words):
    """verify_math plus the device-side all-ok reduction the reduced-fetch
    header rides on (padding lanes carry the identity encoding and verify
    valid, so all() over the padded batch equals all() over the live
    lanes). XLA counterpart of pallas_verify.verify_pallas_ok."""
    mask = verify_math(ax, ay, az, at, r_words, s_words, k_words)
    return mask, mask.all()


_verify_kernel_ok = jax.jit(verify_math_ok)

# Pallas path: the fused-VMEM ladder (pallas_verify.py) is ~2.5x the
# XLA-compiled program on real TPU (HBM-bound vs VMEM-resident). Enabled
# for TPU backends on lane-aligned buckets; CPU (tests) and small buckets
# use the XLA program. CBFT_NO_PALLAS=1 forces the XLA path.
_use_pallas: bool | None = None


def _pallas_available() -> bool:
    global _use_pallas
    if _use_pallas is None:
        import os

        _use_pallas = (
            os.environ.get("CBFT_NO_PALLAS") != "1"
            and jax.devices()[0].platform == "tpu"
        )
    return _use_pallas


_donate_staging: bool | None = None


def _donate_ok() -> bool:
    """Donate the staged wire block through the challenge-derive program
    only on TPU: the identity pass-through output aliases the h2d buffer
    straight into the verify dispatch. CPU jit donation is unsupported
    (XLA warns and copies on every batch)."""
    global _donate_staging
    if _donate_staging is None:
        try:
            _donate_staging = jax.devices()[0].platform == "tpu"
        except Exception:  # noqa: BLE001
            _donate_staging = False
    return _donate_staging


# Serializes jit dispatch (and therefore tracing) across ALL curve kernels
# and threads — see ops/dispatch.py for why the Pallas constant swap makes
# this mandatory.
from cometbft_tpu.ops.dispatch import KERNEL_DISPATCH_LOCK as _dispatch_lock

# ---------------------------------------------------------------------------
# Transfer integrity. The axon tunnel has produced isolated single-lane
# corruption under load (observed twice across ~10 bench runs); the
# reference trusts in-process memory (types/validation.go:235) — a
# tunnel-attached device must earn that trust explicitly:
#   host->device: a position-weighted checksum of the staged r/s/k words is
#     recomputed ON DEVICE and compared to the host's value; the verdict
#     rides back inside the verify payload (no extra round trip).
#   device->host: the mask travels twice (mask + bitwise complement); an
#     echo mismatch flags fetch-path corruption.
# A failed check is counted, logged, retried once with a fresh transfer,
# and — if still failing — the batch falls back to the exact host oracle,
# so corruption is *detected and contained*, never silently tolerated.
# ---------------------------------------------------------------------------

_CHK_MULT = np.uint64(2654435761)  # Knuth multiplicative-hash odd constant


def _host_checksum(*arrs: np.ndarray) -> int:
    """Position-weighted sum mod 2^32 over the arrays' uint32 views, in
    ravel order — bit-identical to _device_checksum."""
    acc = 0
    off = 0
    for a in arrs:
        flat = np.ascontiguousarray(a).view(np.uint32).ravel().astype(np.uint64)
        idx = np.arange(off, off + flat.size, dtype=np.uint64)
        w = (idx * _CHK_MULT + 1) & 0xFFFFFFFF
        acc = (acc + int(((flat * w) & 0xFFFFFFFF).sum() & 0xFFFFFFFF)) & 0xFFFFFFFF
        off += flat.size
    return acc


def _device_checksum_expr(arrs) -> jnp.ndarray:
    """The device-side mirror of _host_checksum (traced inside the payload
    jit)."""
    acc = jnp.uint32(0)
    off = 0
    for a in arrs:
        if a.dtype == jnp.int32:
            flat = jax.lax.bitcast_convert_type(a, jnp.uint32).ravel()
        else:
            flat = a.astype(jnp.uint32).ravel()
        idx = jax.lax.iota(jnp.uint32, flat.size) + jnp.uint32(off)
        w = idx * jnp.uint32(2654435761) + jnp.uint32(1)
        acc = acc + (flat * w).sum(dtype=jnp.uint32)
        off += flat.size
    return acc


_device_checksum = jax.jit(_device_checksum_expr)


# ---------------------------------------------------------------------------
# Reduced-fetch protocol. The happy-path mask fetch used to pull the full
# (2B+1,) payload — ~20 KB and a full tunnel RTT for bytes that are almost
# always all-true. The kernels now additionally emit a (2,) uint32 HEADER
# folding the all-ok verdict into the staging checksum:
#
#   token = device_checksum ^ (OK_MAGIC if every lane verified AND the
#           staged bytes checksummed else BAD_MAGIC);   header = [token, ~token]
#
# The host knows the expected checksum, so 8 fetched bytes prove "staged
# bytes arrived intact and every lane verified" — the full per-lane payload
# is pulled only when the header says otherwise (a failing lane, a staging
# checksum mismatch, or a mangled header fetch, each distinguished by
# decode_header). The complement echo gives the header the same
# corruption-detection plane as the full mask fetch; a corrupted header
# degrades to the full fetch, never to a wrong verdict.
# ---------------------------------------------------------------------------

OK_MAGIC = np.uint32(0x600DFA57)
_BAD_MAGIC = np.uint32(~0x600DFA57 & 0xFFFFFFFF)


def _integrity_parts_expr(mask, allok, rw, sw, kw, expected):
    """-> ((2,) uint32 reduced-fetch header, (2B+1,) bool full payload
    [mask, ~mask (echo), staging-checksum ok])."""
    chk = _device_checksum_expr((rw, sw, kw))
    ok = chk == expected.astype(jnp.uint32)
    payload = jnp.concatenate([mask, ~mask, ok[None]])
    tok = chk ^ jnp.where(allok & ok, OK_MAGIC, _BAD_MAGIC)
    return jnp.stack([tok, ~tok]), payload


# NOT donated: the header/payload outputs are tiny (2 words + 2B+1
# bools), so no donated staged-word buffer could ever be reused for an
# output — XLA would warn "donated buffers were not usable" on every
# batch and copy anyway. Device-buffer recycling comes instead from the
# staged block dying with the dispatch closure (one (3,8,B) array per
# in-flight batch, freed at resolution) and the host-side StagingPool
# reuse underneath it.
_integrity_parts = jax.jit(_integrity_parts_expr)


def _integrity_parts_arrs_expr(mask, allok, expected, *arrs):
    """_integrity_parts_expr generalized over arbitrary checksummed array
    sets: the device-challenge wire is a flat block (+ optional fallback-k
    scatter arrays), not three fixed r/s/k planes, and the checksummed set
    differs per degradation rung. Same header/payload contract."""
    chk = _device_checksum_expr(arrs)
    ok = chk == expected.astype(jnp.uint32)
    payload = jnp.concatenate([mask, ~mask, ok[None]])
    tok = chk ^ jnp.where(allok & ok, OK_MAGIC, _BAD_MAGIC)
    return jnp.stack([tok, ~tok]), payload


_integrity_parts_arrs = jax.jit(_integrity_parts_arrs_expr)


class _LateExpected:
    """Host staging checksum resolved ON THE TRANSFER POOL: the
    device-challenge dispatch closure picks its degradation rung (device
    derive vs host-batch k) inside the closure, and each rung checksums a
    different array set — so the expected value decode_header compares
    against is a cell the closure fills before the header can be fetched
    (the same late-binding contract as _LateOkA). int(cell) is what
    decode_header and resolve_batches consume."""

    __slots__ = ("value",)

    def __init__(self, value: int):
        self.value = value

    def __int__(self) -> int:
        return int(self.value)


def decode_header(header: np.ndarray, expected) -> str:
    """Header verdicts: "happy" (staging intact, every lane valid — the
    per-lane mask need not cross the tunnel), "full" (device and staging
    fine, some lane failed: pull the mask), "chk_mismatch" (the device saw
    different staged bytes than the host sent), "echo_corrupt" (the header
    itself was mangled on the fetch — its complement disagreed)."""
    h0, h1 = int(header[0]), int(header[1])
    if h1 != (~h0 & 0xFFFFFFFF):
        return "echo_corrupt"
    exp = int(expected)
    if h0 == exp ^ int(OK_MAGIC):
        return "happy"
    if h0 == exp ^ int(_BAD_MAGIC):
        return "full"
    return "chk_mismatch"


# happy/full fetch accounting (bench emits fetch_bytes_happy_path from
# this; crypto_health surfaces it next to the hashvec rung counters)
_fetch_lock = threading.Lock()
_fetch_stats = {"happy_fetches": 0, "full_fetches": 0,
                "happy_bytes": 0, "full_bytes": 0}


def _count_fetch(happy: bool, nbytes: int) -> None:
    key = "happy" if happy else "full"
    with _fetch_lock:
        _fetch_stats[key + "_fetches"] += 1
        _fetch_stats[key + "_bytes"] += nbytes
    try:
        from cometbft_tpu.libs import metrics as _metrics

        cm = _metrics.crypto_metrics()
        cm.verify_fetches.labels(key).inc()
        cm.verify_fetch_bytes.labels(key).inc(nbytes)
    except Exception:  # noqa: BLE001 - metrics must never break verification
        pass


def fetch_stats() -> dict:
    with _fetch_lock:
        return dict(_fetch_stats)


def reset_fetch_stats() -> None:
    with _fetch_lock:
        for k in _fetch_stats:
            _fetch_stats[k] = 0


def host_oracle_mask(n, pre_ok, ok_a, rows, info) -> np.ndarray:
    """The CPU rung of the verify ladder: the scheme's exact host oracle
    over the batch rows. Counts the lanes as fallback verifies."""
    from cometbft_tpu.libs.prefixrows import as_bytes

    verify_fn = info[0]
    ok_a = _ok_arr(ok_a)  # may be a _LateOkA cell (pooled pubkey staging)
    pubs, msgs, sigs = rows
    with _trace.span("host_oracle", cat="compute", scheme=info[1], rows=n):
        host = np.fromiter(
            (verify_fn(p, as_bytes(m), s)
             for p, m, s in zip(pubs, msgs, sigs)),
            dtype=bool, count=n)
    _count_fallback(info[1], n)
    return host & pre_ok & ok_a


def decode_payload(payload: np.ndarray, n, pre_ok, ok_a, rows, info,
                   redo=None) -> np.ndarray:
    """Validate the integrity payload and produce the final (N,) mask.
    On checksum/echo failure: count, log, retry once with a fresh transfer
    (redo), then fall back to the exact host oracle for the whole batch."""
    ok_a = _ok_arr(ok_a)  # may be a _LateOkA cell (pooled pubkey staging)
    b = (payload.shape[0] - 1) // 2
    mask = payload[:b].copy()
    echo = payload[b:2 * b]
    chk_ok = bool(payload[2 * b])
    echo_ok = bool((mask != echo).all())  # echo is the complement
    if not (chk_ok and echo_ok):
        from cometbft_tpu.libs import log as _log

        _count_integrity(
            "transfer_checksum_mismatch" if not chk_ok else "mask_echo_mismatch")
        _log.default().error(
            "device transfer integrity check failed",
            scheme=info[1], staging_checksum_ok=str(chk_ok),
            mask_echo_ok=str(echo_ok),
            action="retry" if redo is not None else "host-oracle fallback")
        if redo is not None:
            try:
                fresh = np.asarray(redo())
            except Exception:  # noqa: BLE001 - device died during the retry
                fresh = None
            if fresh is not None:
                return decode_payload(
                    fresh, n, pre_ok, ok_a, rows, info, redo=None)
        return host_oracle_mask(n, pre_ok, ok_a, rows, info)
    mask = mask[:n] & pre_ok & ok_a
    return apply_recheck(mask, pre_ok & ok_a, rows, info)


def _count_integrity(kind: str, n: int = 1) -> None:
    try:
        from cometbft_tpu.libs import metrics as _metrics

        getattr(_metrics.crypto_metrics(), kind).inc(n)
    except Exception:  # noqa: BLE001 - metrics must never break verification
        pass


def _count_fallback(scheme: str, n: int) -> None:
    """Count lanes that fell off the device onto the CPU ladder."""
    try:
        from cometbft_tpu.libs import metrics as _metrics

        _metrics.crypto_metrics().fallback_verifies.labels(scheme).inc(n)
    except Exception:  # noqa: BLE001
        pass


def _count_device_batch(scheme: str, lanes: int) -> None:
    """Count a successfully dispatched device batch (the TPU-path-is-alive
    signal the chaos tests assert on)."""
    try:
        from cometbft_tpu.libs import metrics as _metrics

        cm = _metrics.crypto_metrics()
        cm.device_batches.labels(scheme).inc()
        cm.device_lanes.labels(scheme).inc(lanes)
    except Exception:  # noqa: BLE001
        pass


from cometbft_tpu.ops import dispatch as _dispatch
from cometbft_tpu.ops.dispatch import PallasGate

_pallas_gate = PallasGate("pallas.ed25519")


# Device trace-count instrumentation: every lane count dispatched this
# process is a shape XLA/Pallas compiled a program for. The scheduler's
# bucket soak asserts len(dispatched_shapes()) stays <= the bucket-ladder
# length — continuous batching must bound compilation, not multiply it.
_dispatched_shapes: set[int] = set()


def dispatched_shapes() -> list[int]:
    return sorted(_dispatched_shapes)


def reset_shape_log() -> None:
    _dispatched_shapes.clear()


def _dispatch_verify(a_dev, r_words, s_words, k_words):
    """-> ((B,) mask, () all-ok scalar), both device-resident."""
    from cometbft_tpu.ops import pallas_verify as PV

    _dispatched_shapes.add(int(r_words.shape[1]))
    with _dispatch_lock:
        return _pallas_gate.run(
            PV.verify_pallas_ok, _verify_kernel_ok,
            (*a_dev, r_words, s_words, k_words), r_words.shape[1])


def decompress_points(enc: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
    """(N, 32) uint8 encodings -> (ok (N,) bool, coords (N, 4, 20) int32),
    padding internally to a bucket. Host-facing; used to fill the pubkey
    cache and by tests. Device arrays are limb-axis-first (20, B); the host
    cache keeps batch-major (N, 4, 20) for cheap per-key gathers."""
    n = enc.shape[0]
    b = bucket_size(n)
    words = L.bytes_to_words(enc)
    if b > n:
        pad = np.zeros((b - n, 8), dtype=np.uint32)
        pad[:, 0] = 1  # y = 1: the identity point, always decompressible
        words = np.concatenate([words, pad])
    with _dispatch_lock:
        ok, x, yy, z, t = _decompress_kernel(jnp.asarray(words.T))
    coords = np.stack(
        [np.asarray(x).T, np.asarray(yy).T, np.asarray(z).T, np.asarray(t).T], axis=1
    )
    return np.asarray(ok)[:n], coords[:n]


def pad_coords_batch_minor(coords: np.ndarray, bucket: int) -> tuple:
    """(N, 4, 20) int32 coords -> identity-padded, batch-minor
    (ax, ay, az, at) host arrays, each (20, bucket). THE one place the
    identity-point pad encoding (Y=1, Z=1) and the device layout
    transpose live — PubKeyCache.stage and the mesh's direct staging
    path share it."""
    pad = bucket - coords.shape[0]
    if pad:
        id_coords = np.zeros((pad, 4, L.NLIMBS), dtype=np.int32)
        id_coords[:, 1, 0] = 1  # Y = 1
        id_coords[:, 2, 0] = 1  # Z = 1
        coords = np.concatenate([coords, id_coords])
    return tuple(np.ascontiguousarray(coords[:, i].T) for i in range(4))


class PubKeyCache:
    """Two-level decompressed-pubkey cache.

    Host level: pubkey bytes -> (ok, (4, 20) int32 coords), bounded FIFO —
    absorbs validator-set churn and partial overlap between batches.
    Device level: digest of the padded pubkey batch -> coords already
    resident on device as (20, B) arrays — the steady-state hit for commit
    verification, where the same validator set re-verifies every height and
    the A-coordinate upload (3.3 MB at 10k lanes) drops to zero.
    """

    # subclasses (sr25519) swap in their scheme's device decompressor;
    # staticmethod so instances share one slot
    _decompress = staticmethod(lambda enc: decompress_points(enc))
    # scheme tag consumed by the reduced-send residency layer
    # (ops/residency.py) to key device validator tables per scheme
    scheme = "ed25519"

    def __init__(self, capacity: int = 65536, device_slots: int = 8):
        self.capacity = capacity
        self.device_slots = device_slots
        # reentrant (stage -> lookup_or_decompress): the cache is shared
        # by scheduler inline drains, blocksync staging threads, and mesh
        # shard workers — a concurrent FIFO eviction racing a reader must
        # not KeyError an honest batch onto the fallback ladder
        self._tlock = threading.RLock()
        self._map: dict[bytes, tuple[bool, np.ndarray]] = {}
        self._dev: dict[bytes, tuple] = {}
        # hit/miss/eviction counters per level (host bytes->coords FIFO vs
        # device-resident digest slots), mirrored onto /metrics
        # (crypto_pubkey_cache_events) and the crypto_health RPC section
        self.counters = {
            "host_hits": 0, "host_misses": 0, "host_evictions": 0,
            "device_hits": 0, "device_misses": 0, "device_evictions": 0,
        }

    def _count(self, level: str, event: str, n: int = 1) -> None:
        self.counters[f"{level}_{event}"] += n
        try:
            from cometbft_tpu.libs import metrics as _metrics

            _metrics.crypto_metrics().pubkey_cache_events.labels(
                level, event).inc(n)
        except Exception:  # noqa: BLE001 - metrics must never break staging
            pass

    def stats(self) -> dict:
        return dict(self.counters,
                    host_entries=len(self._map), device_slots=len(self._dev))

    def lookup_or_decompress(self, pubs: list[bytes]) -> tuple[np.ndarray, np.ndarray]:
        """Host-level: (ok (N,) bool, coords (N, 4, 20) int32)."""
        with self._tlock:
            return self._lookup_locked(pubs)

    def _lookup_locked(self, pubs: list[bytes]) -> tuple[np.ndarray, np.ndarray]:
        uniq = dict.fromkeys(pubs)
        missing = [p for p in uniq if p not in self._map]
        self._count("host", "misses", len(missing))
        self._count("host", "hits", len(uniq) - len(missing))
        if missing:
            enc = np.frombuffer(b"".join(missing), dtype=np.uint8).reshape(-1, 32)
            ok, coords = self._decompress(enc)
            evict = min(len(self._map), len(self._map) + len(missing) - self.capacity)
            for _ in range(max(0, evict)):
                self._map.pop(next(iter(self._map)))
            if evict > 0:
                self._count("host", "evictions", evict)
            for i, p in enumerate(missing):
                self._map[p] = (bool(ok[i]), coords[i])
        oks = np.empty(len(pubs), dtype=bool)
        coords = np.empty((len(pubs), 4, L.NLIMBS), dtype=np.int32)
        for i, p in enumerate(pubs):
            o, c = self._map[p]
            oks[i] = o
            coords[i] = c
        return oks, coords

    def stage(
        self, pubs: list[bytes], bucket: int, put=None, put_key: str = ""
    ) -> tuple[np.ndarray, tuple]:
        """(ok_a (N,) host bool, (ax, ay, az, at) device arrays (20, bucket)).
        `put` overrides jax.device_put (the mesh path passes a sharded put;
        put_key disambiguates cache entries across shardings/meshes).
        Serialized on the cache lock: a device-level miss pays its
        checksummed upload under it, which is the price of never caching a
        half-written entry a concurrent stager could read."""
        with self._tlock:
            return self._stage_locked(pubs, bucket, put, put_key)

    def _stage_locked(self, pubs, bucket, put, put_key):
        digest = hashlib.sha256(put_key.encode() + b"".join(pubs)).digest() + bytes(
            [bucket.bit_length()]
        )
        hit = self._dev.get(digest)
        if hit is not None:
            self._count("device", "hits")
            return hit[0], hit[1]
        self._count("device", "misses")
        ok_a, coords = self.lookup_or_decompress(pubs)
        put = put or jax.device_put
        host_arrs = pad_coords_batch_minor(coords, bucket)
        expected = _host_checksum(*host_arrs)
        dev = None
        for attempt in (1, 2):
            t0 = _time.perf_counter()
            dev = tuple(put(a) for a in host_arrs)
            # block before t1 (async dispatch would record enqueue time,
            # not wire time); the checksum read below forces residency
            # immediately after anyway
            jax.block_until_ready(dev)
            # coordinate-table upload bytes (per attempt: a retry really
            # re-crosses the wire) against the enclosing transfer span
            nbytes = sum(a.nbytes for a in host_arrs)
            _linkmodel.tunnel().observe_transfer(
                nbytes, _time.perf_counter() - t0)
            _trace.add_bytes(tx=nbytes)
            # full-key-path wire accounting: the coordinate-table upload
            # the reduced-send residency exists to amortize away
            from cometbft_tpu.ops import residency as _residency

            _residency.record_send("full", nbytes)
            # upload-time integrity check: a corrupted coordinate table
            # would poison EVERY batch against this valset until eviction,
            # so the one extra round trip per cache miss is paid here
            got = int(np.asarray(_device_checksum(dev)))
            if got == expected:
                break
            _count_integrity("transfer_checksum_mismatch")
            from cometbft_tpu.libs import log as _log

            _log.default().error(
                "pubkey coordinate upload failed integrity check",
                attempt=str(attempt))
            if attempt == 2:
                raise RuntimeError(
                    "pubkey coordinate upload corrupted twice; refusing to "
                    "cache a poisoned table")
        if len(self._dev) >= self.device_slots:
            self._dev.pop(next(iter(self._dev)))
            self._count("device", "evictions")
        self._dev[digest] = (ok_a, dev)
        return ok_a, dev


@jax.jit
def _gather_coords(dev_u, idx):
    """Device-side gather: unique-pubkey coordinate table (20, U) -> per-lane
    A-coordinates (20, B). Runs as a plain XLA op enqueued before the verify
    kernel — no host round trip."""
    return tuple(jnp.take(c, idx, axis=1) for c in dev_u)


def _stage_gather(cache: "PubKeyCache", pubs: list[bytes], bucket: int,
                  put_key: str = "", device=None, want_enc: bool = False
                  ) -> tuple:
    """(ok_a (N,), (ax, ay, az, at) device arrays (20, bucket), send
    path, pubkey-staging wire bytes). With want_enc the tuple gains the
    (8, bucket) resident pubkey-encoding words between a_dev and path —
    served only by the indexed path (None otherwise), since only the
    residency tables keep raw key bytes on device; a None enc is one of
    the device-challenge degradation rungs (non-resident A).

    Indexed path first (ops/residency.py): when the batch's keys fit the
    device-resident validator table, the wire carries a 2-byte uint16
    row index per lane (unseen keys delta-insert, counted separately) —
    the reduced-send steady state. path="indexed".

    Full-key path otherwise: a device-side gather from the UNIQUE pubkey
    table. A batch that repeats a validator set W times (the coalesced
    blocksync window) uploads ONE copy of the coordinates (digest-cached
    across windows, since the unique set is stable even when window
    composition changes) plus a 4-byte/lane index vector — not W copies
    keyed on the exact concatenation. path="full".

    `device` targets a specific chip (the mesh path stages each shard's
    coordinate table on its own fault domain; put_key must then carry the
    chip index so cache/table entries never alias across devices)."""
    from cometbft_tpu.ops import residency as _residency

    got = _residency.stage(cache, pubs, bucket, put_key=put_key,
                           device=device, want_enc=want_enc)
    if got is not None:
        if want_enc:
            ok_a, a_dev, enc_dev, staging_tx = got
            return ok_a, a_dev, enc_dev, "indexed", staging_tx
        ok_a, a_dev, staging_tx = got
        return ok_a, a_dev, "indexed", staging_tx
    uniq = list(dict.fromkeys(pubs))
    # an identity pad slot is needed only when padding lanes exist; when the
    # batch fills its bucket exactly (n == bucket == cap is legal) the +1
    # would overflow the lane cap
    need_pad = bucket > len(pubs)
    bu = bucket_size(len(uniq) + 1 if need_pad else len(uniq))
    put = None
    if device is not None:
        import functools as _functools

        put = _functools.partial(jax.device_put, device=device)
    ok_u, dev_u = cache.stage(uniq, bu, put=put, put_key=put_key)
    pos = {p: i for i, p in enumerate(uniq)}
    idx = np.full(bucket, len(uniq), dtype=np.int32)  # padding -> identity
    idx[: len(pubs)] = [pos[p] for p in pubs]
    ok_a = np.asarray(ok_u)[idx[: len(pubs)]]
    t0 = _time.perf_counter()
    idx_dev = (jax.device_put(idx) if device is None
               else jax.device_put(idx, device))
    # the 4 B/lane index vector is the steady-state small upload — the
    # tunnel model's h2d RTT probe (no pending compute to entangle with;
    # blocked before t1 so async dispatch can't record enqueue time)
    jax.block_until_ready(idx_dev)
    _linkmodel.tunnel().observe_transfer(
        idx.nbytes, _time.perf_counter() - t0)
    _trace.add_bytes(tx=idx.nbytes)
    a_dev = _gather_coords(dev_u, idx_dev)
    if want_enc:
        return ok_a, a_dev, None, "full", idx.nbytes
    return ok_a, a_dev, "full", idx.nbytes


_default_cache = PubKeyCache()


def cache_stats() -> dict:
    """Default PubKeyCache counters per scheme — the crypto_health RPC's
    pubkey_cache section (next to verify_sched)."""
    out = {"ed25519": _default_cache.stats()}
    try:
        from cometbft_tpu.ops import sr25519_kernel as SRK

        out["sr25519"] = SRK._default_cache.stats()
    except Exception:  # noqa: BLE001 - sr kernel may be unimportable (deps)
        pass
    return out


def compute_challenges(pubs: list[bytes], msgs: list[bytes], sigs: list[bytes]) -> list[int]:
    """k_i = SHA-512(R_i || A_i || M_i) mod L — host-side (SHA-512 is 64-bit
    word arithmetic, hostile to the TPU VPU). Batch-vectorized via
    ops/hashvec (lane-SIMD native core / batch-axis numpy / hashlib rung
    ladder, bit-for-bit hashlib); this list[int] entry is the compat shim —
    the staging path consumes packed words directly (stage_batch)."""
    from cometbft_tpu.ops import hashvec

    words = hashvec.sha512_mod_l_words(
        [sig[:32] + pub + msg for pub, msg, sig in zip(pubs, msgs, sigs)])
    blob = words.tobytes()
    return [int.from_bytes(blob[32 * i: 32 * i + 32], "little")
            for i in range(len(sigs))]


# L as 4 little-endian 64-bit words, most significant last — the vectorized
# s < L comparison reads these
_L_WORDS64 = np.frombuffer(oracle.L.to_bytes(32, "little"), dtype="<u8")


def scalars_lt_l(s_rows: np.ndarray) -> np.ndarray:
    """(N, 32) uint8 little-endian scalars -> (N,) bool of (s < L),
    vectorized lexicographic compare over the four 64-bit words from the
    most significant down — replaces the per-row int.from_bytes round trip
    in staging."""
    w = np.ascontiguousarray(s_rows).view("<u8")
    lt = np.zeros(w.shape[0], dtype=bool)
    decided = np.zeros(w.shape[0], dtype=bool)
    for i in (3, 2, 1, 0):
        lt |= ~decided & (w[:, i] < _L_WORDS64[i])
        decided |= w[:, i] != _L_WORDS64[i]
    return lt


_ID_ROW32 = np.frombuffer(_ID_ENC32, dtype=np.uint8)


def _challenge_words(r_rows, pub_rows, msgs, mlens, pre_ok) -> np.ndarray:
    """(N, 8) uint32 packed challenge words k = SHA-512(R||A||M) mod L.
    Uniform-length messages (every commit: sign-bytes share one length)
    hash as ONE (N, 64+mlen) batch call; ragged messages group inside
    sha512_many. Rows with pre_ok False get k = 0 (their placeholder
    R/A content is hashed but discarded)."""
    from cometbft_tpu.libs.prefixrows import as_bytes
    from cometbft_tpu.ops import hashvec

    n = r_rows.shape[0]
    if n and (mlens == mlens[0]).all():
        # batch-axis reassembly: shared-prefix vote rows broadcast their
        # per-commit prefix once instead of joining N full copies
        msg_rows = hashvec.assemble_prefixed_rows(msgs, int(mlens[0]))
        data = np.concatenate([r_rows, pub_rows, msg_rows], axis=1)
        digests = hashvec.sha512_rows(data)
    else:
        r_blob, p_blob = r_rows.tobytes(), pub_rows.tobytes()
        digests = hashvec.sha512_many(
            [r_blob[32 * i:32 * i + 32] + p_blob[32 * i:32 * i + 32]
             + as_bytes(m) for i, m in enumerate(msgs)])
    k_words = hashvec.reduce512_mod_l(digests)
    k_words[~pre_ok] = 0
    return k_words


def _structural_stage(
    pubs: list[bytes], sigs: list[bytes],
) -> tuple[np.ndarray, list[bytes], np.ndarray, np.ndarray]:
    """The host-side structural checks every staging path shares (lengths,
    s < L — never reach the device), with placeholder substitution for the
    failing rows. Returns (pre_ok, safe_pubs, sig_rows, pub_rows) — the
    row matrices feed challenge computation (host or the device fallback
    lanes) and the word packing."""
    n = len(sigs)
    ok_len = np.fromiter(map(len, sigs), np.int64, n) == 64
    ok_len &= np.fromiter(map(len, pubs), np.int64, n) == 32
    if ok_len.all():
        sig_rows = np.frombuffer(b"".join(sigs), dtype=np.uint8).reshape(n, 64)
        pub_rows = np.frombuffer(b"".join(pubs), dtype=np.uint8).reshape(n, 32)
        safe_pubs = list(pubs)
    else:  # ragged stragglers: per-row placeholder substitution
        sig_rows = np.zeros((n, 64), dtype=np.uint8)
        pub_rows = np.zeros((n, 32), dtype=np.uint8)
        sig_rows[:, :32] = _ID_ROW32
        pub_rows[:] = _ID_ROW32
        safe_pubs = [_ID_ENC32] * n
        for i in np.flatnonzero(ok_len):
            sig_rows[i] = np.frombuffer(sigs[i], dtype=np.uint8)
            pub_rows[i] = np.frombuffer(pubs[i], dtype=np.uint8)
            safe_pubs[i] = pubs[i]
    pre_ok = ok_len & scalars_lt_l(sig_rows[:, 32:])
    bad = np.flatnonzero(ok_len & ~pre_ok)  # s >= L rows need placeholders
    if bad.size:
        if not sig_rows.flags.writeable:
            sig_rows = sig_rows.copy()
        sig_rows[bad, :32] = _ID_ROW32
        sig_rows[bad, 32:] = 0
        safe_pubs = [p if pre_ok[i] else _ID_ENC32
                     for i, p in enumerate(safe_pubs)]
    return pre_ok, safe_pubs, sig_rows, pub_rows


def stage_batch(
    pubs: list[bytes], msgs: list[bytes], sigs: list[bytes], bucket: int,
    out: np.ndarray | None = None,
) -> tuple[np.ndarray, list[bytes], np.ndarray, np.ndarray, np.ndarray]:
    """Host staging shared by the single-chip and mesh paths: structural
    checks (lengths, s < L — never reach the device), SHA-512 challenges,
    packed-word arrays padded to `bucket`, batch-minor (8, bucket) uint32.
    Returns (pre_ok, safe_pubs, r_words, s_words, k_words).

    All batch-axis numpy: vectorized length/s<L checks, one hashvec batch
    call for the challenges, r/s/k packed in place into `out` — a leased
    (3, 8, bucket) StagingPool block (limbs.POOL) — when given, else fresh
    arrays (mesh/bench callers that keep the words). This is the
    host-challenge path; the device-challenge twin (verify_batch_async's
    ops/challenge.py branch) stages the same structural rows but ships
    descriptors instead of k words."""
    pre_ok, safe_pubs, sig_rows, pub_rows = _structural_stage(pubs, sigs)
    r_words, s_words, k_words = _pack_host_words(
        pre_ok, sig_rows, pub_rows, msgs, bucket, out=out)
    return pre_ok, safe_pubs, r_words, s_words, k_words


def _pack_host_words(pre_ok, sig_rows, pub_rows, msgs, bucket,
                     out=None) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Host-challenge word packing: SHA-512 challenges plus the r/s/k
    planes, identity-padded to `bucket`."""
    n = sig_rows.shape[0]
    mlens = np.fromiter(map(len, msgs), np.int64, n)
    k_rows = _challenge_words(
        sig_rows[:, :32], pub_rows, msgs, mlens, pre_ok)

    sig_u4 = sig_rows.view("<u4")  # (n, 16): words 0-7 = R, 8-15 = s
    if out is None:
        out = np.empty((3, 8, bucket), dtype=np.uint32)
    r_words, s_words, k_words = out[0], out[1], out[2]
    r_words[:, :n] = sig_u4[:, :8].T
    s_words[:, :n] = sig_u4[:, 8:].T
    k_words[:, :n] = k_rows.T
    if bucket > n:  # identity encoding + zero scalars: verifies valid
        r_words[:, n:] = 0
        r_words[0, n:] = 1
        s_words[:, n:] = 0
        k_words[:, n:] = 0
    return r_words, s_words, k_words


def _pack_device_block(sig_rows: np.ndarray, bucket: int, plan,
                       block: np.ndarray) -> None:
    """Pack a leased FLAT block for the device-challenge wire: R words,
    s words (word-major (8, bucket) planes, identity-padded), then the
    descriptor stream (challenge.fill_stream). No k words — that is the
    point."""
    n = sig_rows.shape[0]
    sig_u4 = sig_rows.view("<u4")
    rw = block[:8 * bucket].reshape(8, bucket)
    sw = block[8 * bucket:16 * bucket].reshape(8, bucket)
    rw[:, :n] = sig_u4[:, :8].T
    sw[:, :n] = sig_u4[:, 8:].T
    if bucket > n:
        rw[:, n:] = 0
        rw[0, n:] = 1
        sw[:, n:] = 0
    from cometbft_tpu.ops import challenge as _challenge

    _challenge.fill_stream(block, bucket, plan)


def verify_batch(
    pubs: list[bytes],
    msgs: list[bytes],
    sigs: list[bytes],
    cache: PubKeyCache | None = None,
) -> tuple[bool, list[bool]]:
    """ZIP-215 batch verification with per-signature mask. Agrees with
    oracle.verify_zip215 on every input (tested bit-for-bit)."""
    mask = verify_batch_async(pubs, msgs, sigs, cache=cache)()
    return bool(mask.all()), mask.tolist()


# Failed lanes are re-verified on host with the exact ZIP-215 oracle before
# being reported invalid (bounded count — a batch with many failures is
# genuinely bad). The reference batch verifier falls back to serial
# re-verify on failure too (types/validation.go:266); here the motivation
# is also defensive: the dev tunnel transport has produced isolated
# single-lane corruption under load, and an honest signature must never be
# condemned by a flipped transfer bit.
_RECHECK_MAX = 32


def recheck_failed_lanes(mask, eligible, pubs, msgs, sigs,
                         verify_fn, scheme: str):
    """eligible: lanes that passed the host-side structural checks — a
    pre-failed lane carries a placeholder encoding (the identity, which
    being small-order validly signs ANYTHING under ZIP-215) and must never
    be flipped back to valid. Shared by the ed25519 and sr25519 paths;
    verify_fn is the scheme's exact host oracle."""
    import numpy as _np

    from cometbft_tpu.libs.prefixrows import as_bytes

    bad = _np.flatnonzero(~mask & eligible)
    if len(bad) == 0 or len(bad) > _RECHECK_MAX:
        return mask
    flipped = []
    for i in bad:
        if verify_fn(pubs[i], as_bytes(msgs[i]), sigs[i]):
            mask[i] = True
            flipped.append(int(i))
    if flipped:
        from cometbft_tpu.libs import log as _log

        _count_integrity("mask_oracle_disagreement", len(flipped))
        _log.default().error(
            "device verify mask disagreed with host oracle; honoring host",
            scheme=scheme, lanes=str(flipped))
    return mask


def _recheck_failed_lanes(mask, eligible, pubs, msgs, sigs):
    return recheck_failed_lanes(
        mask, eligible, pubs, msgs, sigs, oracle.verify_zip215, "ed25519")


def apply_recheck(mask, eligible, rows, info):
    """Host-oracle recheck with optional per-group budgets: info is
    (verify_fn, scheme, groups). A coalesced window passes its per-commit
    row boundaries as groups so each commit keeps its own _RECHECK_MAX
    budget — one genuinely-bad commit must not suppress the
    transfer-corruption recheck for its window-mates."""
    verify_fn, scheme, groups = info
    pubs, msgs, sigs = rows
    if not groups:
        return recheck_failed_lanes(
            mask, eligible, pubs, msgs, sigs, verify_fn, scheme)
    for a, b in groups:
        mask[a:b] = recheck_failed_lanes(
            mask[a:b], eligible[a:b], pubs[a:b], msgs[a:b], sigs[a:b],
            verify_fn, scheme)
    return mask


def make_host_thunk(n, pre_ok, rows, info):
    """A verify thunk that never touches the device — the CPU rung of the
    ladder, used when the breaker has sidelined the device or staging
    failed. Same thunk contract as verify_batch_async (device_parts with a
    None payload acquirer and n > 0 routes resolve_batches here too)."""
    ones = np.ones(n, dtype=bool)
    cached: dict = {}

    def result() -> np.ndarray:
        if "m" not in cached:
            cached["m"] = host_oracle_mask(n, pre_ok, ones, rows, info)
        return cached["m"]

    result.device_parts = lambda: (None, n, pre_ok, ones, rows, info, None)
    return result


class _LateOkA:
    """Pubkey-validity mask resolved ON THE TRANSFER POOL: the
    reduced-send pipeline moved pubkey staging (residency/index upload)
    off the caller thread into the dispatch closure, so batch N+1's
    host staging overlaps batch N's pubkey RTT instead of serializing
    behind it. The cell is set by the closure before dispatch returns;
    a read before that only happens on ladder paths that already failed
    device dispatch — there the host oracle is ground truth and needs
    no device decompress mask, so the all-eligible default is exact."""

    __slots__ = ("n", "value")

    def __init__(self, n: int):
        self.n = n
        self.value = None

    def resolve(self) -> np.ndarray:
        v = self.value
        return v if v is not None else np.ones(self.n, dtype=bool)


def _ok_arr(ok_a) -> np.ndarray:
    return ok_a.resolve() if isinstance(ok_a, _LateOkA) else ok_a


def supervised_device_thunk(scheme: str, sup, submit_fn, fetch_site: str,
                            n, pre_ok, ok_a, rows, info,
                            expected=0, lease=None):
    """The shared thunk shape for a supervised device batch (ed25519 and
    sr25519 build their dispatch closure, this builds the rest): dispatch
    runs on the transfer pool under the supervisor; fetches are
    watchdog-bounded; every failure drops the batch onto the host oracle
    instead of raising into the verify seam.

    submit_fn returns (header_dev, payload_dev) — the reduced-fetch pair
    from _integrity_parts. The thunk fetches the 8-byte header first and
    pulls the full per-lane payload only on a non-happy verdict. `expected`
    is the host staging checksum the header is decoded against; `lease` is
    the StagingPool block backing the staged words, returned to the pool
    once the batch resolves (the _redo retry re-reads it, so release waits
    for resolution, not dispatch). The DoubleBuffer in-flight slot is NOT
    released here: the dispatch closure scopes it (acquire before h2d,
    release in a finally after the verify dispatch), so an abandoned thunk
    — a caller that takes device_parts() and never resolves, exactly like
    an unreleased pool block — can never leak a slot and wedge the gate."""
    # wrap_ctx carries the caller's trace context onto the pool thread so
    # the dispatch's transfer/compute spans land inside this batch's tree
    fut = _xfer_pool().submit(_trace.wrap_ctx(sup.run), submit_fn)
    _lease = [lease]

    def _release() -> None:
        blk, _lease[0] = _lease[0], None
        if blk is not None:
            L.POOL.release(blk)

    def _acquire():
        """Block until dispatch completes; returns the device-resident
        (header, payload) pair. Raises DeviceOpFailed/DeviceUnavailable
        (recorded)."""
        try:
            return fut.result(timeout=_dispatch.watchdog_timeout())
        except (_dispatch.DeviceOpFailed, _dispatch.DeviceUnavailable):
            raise
        except Exception as exc:  # noqa: BLE001 - watchdog timeout etc.
            sup.record_op_failure(exc)
            raise _dispatch.DeviceOpFailed(f"{scheme} dispatch wait") from exc

    _acquire.expected = expected  # resolve_batches decodes headers itself

    def _fetch_np(dev_arr, pure_transfer: bool = False) -> np.ndarray:
        """Device->host fetch (header or full payload): chaos site +
        watchdog + injected lane corruption (the integrity echo plane must
        catch it). Only a `pure_transfer` fetch feeds the link model: the
        FIRST fetch of a batch blocks until the kernel finishes, so its
        wall time is compute + wire — feeding that into the tunnel
        estimator would inflate RTT by the kernel time. Once the header
        has been read the device result is materialized, and the payload
        fetch is pure wire."""
        from cometbft_tpu.libs import chaos

        with _trace.span(f"{scheme}.d2h", cat="fetch") as sp:
            try:
                chaos.fire(fetch_site)
                t0 = _time.perf_counter()
                out = _fetch_pool().submit(
                    lambda: np.asarray(dev_arr)).result(
                        timeout=_dispatch.watchdog_timeout())
                if pure_transfer:
                    _linkmodel.tunnel().observe_transfer(
                        out.nbytes, _time.perf_counter() - t0)
            except Exception as exc:  # noqa: BLE001
                sup.record_op_failure(exc)
                raise _dispatch.DeviceOpFailed(
                    f"{scheme} payload fetch") from exc
            sp.add_bytes(rx=out.nbytes)
        return chaos.corrupt_mask(fetch_site, out)

    def _redo():
        """Integrity-retry path: full fresh transfer+dispatch+fetch of the
        FULL payload (the header already said unhappy), supervised AND
        watchdog-bounded like every other device wait — a device that
        hangs during the retry must not stall the verify seam
        (decode_payload catches and falls to the host oracle), and the
        hang/failure is recorded so the breaker and crypto_health see it."""
        try:
            return _fetch_pool().submit(
                lambda: np.asarray(sup.run(submit_fn)[1])).result(
                    timeout=_dispatch.watchdog_timeout())
        except (_dispatch.DeviceOpFailed, _dispatch.DeviceUnavailable):
            raise  # sup.run already recorded it
        except Exception as exc:  # noqa: BLE001 - watchdog timeout etc.
            sup.record_op_failure(exc)
            raise

    def result() -> np.ndarray:
        try:
            header_dev, payload_dev = _acquire()
            header = _fetch_np(header_dev)
        except (_dispatch.DeviceOpFailed, _dispatch.DeviceUnavailable):
            _release()
            return host_oracle_mask(n, pre_ok, _ok_arr(ok_a), rows, info)
        ok = _ok_arr(ok_a)  # staging completed: the cell is resolved
        verdict = decode_header(header, expected)
        if verdict == "happy":
            _count_fetch(True, header.nbytes)
            _release()
            return pre_ok & ok  # no failed lanes -> nothing to recheck
        if verdict == "echo_corrupt":
            _count_integrity("mask_echo_mismatch")
            from cometbft_tpu.libs import log as _log

            _log.default().error(
                "reduced-fetch header failed its complement echo; pulling "
                "the full payload", scheme=info[1])
        try:
            payload = _fetch_np(payload_dev, pure_transfer=True)
        except (_dispatch.DeviceOpFailed, _dispatch.DeviceUnavailable):
            _release()
            return host_oracle_mask(n, pre_ok, ok, rows, info)
        _count_fetch(False, header.nbytes + payload.nbytes)
        try:
            with _trace.span(f"{scheme}.decode", cat="resolve", rows=n):
                return decode_payload(
                    payload, n, pre_ok, ok, rows, info, redo=_redo)
        finally:
            _release()

    result.device_parts = lambda: (
        _acquire, n, pre_ok, ok_a, rows, info, _redo)
    result.release_staging = _release
    return result


def verify_batch_async(
    pubs: list[bytes],
    msgs: list[bytes],
    sigs: list[bytes],
    cache: PubKeyCache | None = None,
    recheck_groups: list[tuple[int, int]] | None = None,
):
    """Stage + dispatch without blocking on the device: returns a thunk that
    materializes the (N,) bool mask. Lets callers (blocksync streaming,
    VoteSet flush) overlap host staging of batch N+1 with device compute of
    batch N. recheck_groups: per-commit row boundaries of a coalesced
    window (see apply_recheck).

    Device faults never escape the thunk: dispatch runs under the "device"
    supervisor (transient retry + breaker, ops/dispatch.py), fetches are
    watchdog-bounded, and any failure resolves the batch on the exact host
    oracle — a hung or dead device costs latency, not a consensus round."""
    n = len(sigs)
    assert len(pubs) == n and len(msgs) == n
    if n == 0:
        empty = lambda: np.zeros(0, dtype=bool)  # noqa: E731
        empty.device_parts = lambda: (
            None, 0, np.zeros(0, bool), np.zeros(0, bool), ([], [], []),
            (oracle.verify_zip215, "ed25519", None), None)
        return empty
    cache = cache or _default_cache

    b = bucket_size(n)
    # sig_rows: THE attribution row-counting site for this batch (one
    # stage span per dispatched batch; everything else is informational)
    with _trace.span("ed25519.stage", cat="stage", sig_rows=n, lanes=b,
                     hash_rung=_staging_rung()):
        pre_ok, safe_pubs, sig_rows, pub_rows = _structural_stage(pubs, sigs)
        plan = None
        if _dispatch.device_allowed():
            try:
                from cometbft_tpu.ops import challenge as _challenge

                plan = _challenge.plan_batch(msgs, pre_ok)
            except Exception:  # noqa: BLE001 - planning never breaks staging
                plan = None
        if plan is None:
            block = L.POOL.lease(b)
            r_words, s_words, k_words = _pack_host_words(
                pre_ok, sig_rows, pub_rows, msgs, b, out=block)
        else:
            from cometbft_tpu.ops import challenge as _challenge

            block = L.POOL.lease_flat(_challenge.block_words(b, plan.var))
            _pack_device_block(sig_rows, b, plan, block)
    rows = (safe_pubs, list(msgs), list(sigs))
    info = (oracle.verify_zip215, "ed25519", recheck_groups)
    sup = _dispatch.supervisor("device")

    if not _dispatch.device_allowed():
        L.POOL.release(block)
        return make_host_thunk(n, pre_ok, rows, info)
    ok_cell = _LateOkA(n)

    if plan is None:
        expected = np.uint32(_host_checksum(r_words, s_words, k_words))

        def _transfer_and_dispatch():
            from cometbft_tpu.libs import chaos
            from cometbft_tpu.ops import residency as _residency

            chaos.fire("ed25519.dispatch")
            # pubkey staging rides the transfer pool too (reduced-send
            # pipeline): the caller thread never blocks on the index/table
            # round trip, so host staging of batch N+1 overlaps batch N's
            # transfers instead of serializing behind the tunnel RTT. A
            # staging failure here feeds the supervisor/breaker exactly
            # like a dispatch failure (the batch lands on the host oracle).
            with _trace.span("ed25519.stage_pubkeys", cat="transfer",
                             lanes=b):
                ok_a, a_dev, path, staging_tx = _stage_gather(
                    cache, safe_pubs, b)
            ok_cell.value = ok_a
            # in-flight slot, scoped to h2d THROUGH the verify dispatch
            # (a _redo retry or an abandoned thunk can never leak it):
            # batch N's h2d overlaps batch N-1's compute, batch N+1
            # queues until a slot frees
            with _trace.span("ed25519.slot", cat="queue", lanes=b):
                rel = _dispatch.doublebuffer(
                    f"dev{default_device_index()}").acquire()
            try:
                with _trace.span("ed25519.h2d", cat="transfer",
                                 lanes=b) as sp:
                    t0 = _time.perf_counter()
                    # ONE transfer for the whole (3, 8, B) staged block —
                    # the r/s/k planes were three separate puts (three
                    # tunnel round trips) before the reduced-send
                    # protocol; the planes are sliced apart on device
                    # where the copy is HBM-cheap. Blocking before t1
                    # keeps the link-model sample honest (async dispatch
                    # would record enqueue time, not wire time); the
                    # verify dispatch below needs the words resident
                    # anyway, and this thread is the transfer pool —
                    # blocking it is the design.
                    dev_block = jnp.asarray(block)
                    jax.block_until_ready(dev_block)
                    nbytes = block.nbytes
                    _linkmodel.tunnel().observe_transfer(
                        nbytes, _time.perf_counter() - t0)
                    sp.add_bytes(tx=nbytes)
                _residency.record_send(path, staging_tx + nbytes, sigs=n)
                rw, sw, kw = dev_block[0], dev_block[1], dev_block[2]
                with _trace.span("ed25519.dispatch", cat="compute", lanes=b,
                                 device=default_device_index()):
                    mask, allok = _dispatch_verify(a_dev, rw, sw, kw)
                    parts = _integrity_parts(
                        mask, allok, rw, sw, kw, expected)
            finally:
                rel()
            _count_device_batch("ed25519", b)
            return parts

        # The host->device copy blocks the calling thread for the wire time
        # (~45 ms/MB through the axon tunnel), so it runs on a small pool:
        # the caller can stage batch i+1 while batch i's bytes are in
        # flight, and parallel puts multiplex the tunnel.
        return supervised_device_thunk(
            "ed25519", sup, _transfer_and_dispatch, "ed25519.fetch",
            n, pre_ok, ok_cell, rows, info, expected=expected, lease=block)

    # ---- device-challenge path: the wire carries R/s + descriptors; k is
    # derived on-chip (ops/challenge.py) with per-lane host fallbacks for
    # the Plan's ineligible lanes, and a whole-batch host-k rung when the
    # derive itself fails or A is not table-resident.
    fb_lanes = np.flatnonzero(pre_ok & ~plan.eligible)
    fb = 0
    fkw = fidx = None
    if fb_lanes.size:
        with _trace.span("ed25519.challenge", cat="challenge",
                         lanes=int(fb_lanes.size), rung="lane_fallback"):
            mlens_fb = np.fromiter((len(msgs[i]) for i in fb_lanes),
                                   np.int64, fb_lanes.size)
            k_fb = _challenge_words(
                np.ascontiguousarray(sig_rows[fb_lanes, :32]),
                np.ascontiguousarray(pub_rows[fb_lanes]),
                [msgs[i] for i in fb_lanes], mlens_fb,
                np.ones(fb_lanes.size, dtype=bool))
            fb = bucket_size(int(fb_lanes.size))
            # pad by repeating the last real lane: the device scatter is
            # idempotent, so the repeated index just rewrites the same
            # value
            fidx = np.full(fb, int(fb_lanes[-1]), dtype=np.int32)
            fidx[:fb_lanes.size] = fb_lanes
            fkw = np.tile(k_fb[-1:].T, (1, fb)).astype(np.uint32)
            fkw[:, :fb_lanes.size] = k_fb.T
    expected_cell = _LateExpected(
        _host_checksum(block, fkw, fidx) if fb else _host_checksum(block))

    def _transfer_and_dispatch_dc():
        from cometbft_tpu.libs import chaos

        chaos.fire("ed25519.dispatch")
        with _trace.span("ed25519.stage_pubkeys", cat="transfer", lanes=b):
            ok_a, a_dev, enc_dev, path, staging_tx = _stage_gather(
                cache, safe_pubs, b, want_enc=True)
        ok_cell.value = ok_a
        with _trace.span("ed25519.slot", cat="queue", lanes=b):
            rel = _dispatch.doublebuffer(
                f"dev{default_device_index()}").acquire()
        try:
            return _challenge_rungs_and_dispatch(a_dev, enc_dev, path,
                                                 staging_tx)
        finally:
            rel()

    def _challenge_rungs_and_dispatch(a_dev, enc_dev, path, staging_tx):
        from cometbft_tpu.libs import chaos
        from cometbft_tpu.ops import challenge as _challenge
        from cometbft_tpu.ops import residency as _residency

        with _trace.span("ed25519.h2d", cat="transfer", lanes=b) as sp:
            t0 = _time.perf_counter()
            dev_block = jnp.asarray(block)
            fkw_dev = fidx_dev = None
            if fb:
                fkw_dev = jnp.asarray(fkw)
                fidx_dev = jnp.asarray(fidx)
                jax.block_until_ready((dev_block, fkw_dev, fidx_dev))
                nbytes = block.nbytes + fkw.nbytes + fidx.nbytes
            else:
                jax.block_until_ready(dev_block)
                nbytes = block.nbytes
            _linkmodel.tunnel().observe_transfer(
                nbytes, _time.perf_counter() - t0)
            sp.add_bytes(tx=nbytes)
        _residency.record_send(path, staging_tx + nbytes, sigs=n)
        kw = None
        if enc_dev is not None:
            sup_ch = _dispatch.supervisor(_challenge.SITE)

            def _derive():
                chaos.fire(_challenge.SITE)
                run = _challenge.derive_fn(
                    b, plan.var, plan.plen, plan.tlen, fb, _donate_ok())
                args = (dev_block, enc_dev, plan.dev_tab)
                if fb:
                    args = args + (fkw_dev, fidx_dev)
                with _trace.span("ed25519.challenge", cat="challenge",
                                 lanes=b, device=default_device_index()):
                    with _dispatch_lock:
                        return run(*args)

            try:
                dev_out, kw = sup_ch.run(_derive)
                if chaos.should_corrupt(_challenge.SITE):
                    # perturbed device k: the failing lane must be caught
                    # by the recheck plane, never reported as invalid
                    kw = kw.at[0, 0].add(np.uint32(1))
            except (_dispatch.DeviceUnavailable, _dispatch.DeviceOpFailed):
                kw = None
                _challenge.count("derive_failed")
        else:
            _challenge.count("enc_not_resident")
        if kw is None:
            # whole-batch host-k rung: compute k here on the transfer
            # pool, re-upload the block (a donated derive may have
            # consumed the first transfer) and the k plane
            with _trace.span("ed25519.challenge", cat="challenge", lanes=b,
                             rung="host_fallback"):
                mlens = np.fromiter(map(len, msgs), np.int64, n)
                k_rows = _challenge_words(
                    sig_rows[:, :32], pub_rows, msgs, mlens, pre_ok)
                kw_host = np.zeros((8, b), dtype=np.uint32)
                kw_host[:, :n] = k_rows.T
            t0 = _time.perf_counter()
            dev_out = jnp.asarray(block)
            kw = jnp.asarray(kw_host)
            jax.block_until_ready((dev_out, kw))
            fb_bytes = block.nbytes + kw_host.nbytes
            _linkmodel.tunnel().observe_transfer(
                fb_bytes, _time.perf_counter() - t0)
            _trace.add_bytes(tx=fb_bytes)
            _residency.record_send(path, fb_bytes)
            expected_cell.value = _host_checksum(block, kw_host)
            chk_arrs = (dev_out, kw)
            _challenge.count("batch_host_fallback")
        elif fb:
            chk_arrs = (dev_out, fkw_dev, fidx_dev)
        else:
            chk_arrs = (dev_out,)
        rw = dev_out[:8 * b].reshape(8, b)
        sw = dev_out[8 * b:16 * b].reshape(8, b)
        with _trace.span("ed25519.dispatch", cat="compute", lanes=b,
                         device=default_device_index()):
            mask, allok = _dispatch_verify(a_dev, rw, sw, kw)
            parts = _integrity_parts_arrs(
                mask, allok, np.uint32(int(expected_cell)), *chk_arrs)
        _count_device_batch("ed25519", b)
        return parts

    return supervised_device_thunk(
        "ed25519", sup, _transfer_and_dispatch_dc, "ed25519.fetch",
        n, pre_ok, ok_cell, rows, info, expected=expected_cell, lease=block)


def resolve_batches(thunks) -> list[np.ndarray]:
    """Materialize many verify_batch_async results with a two-phase
    reduced fetch (device-side concat): phase 1 pulls every batch's 8-byte
    header in ONE device->host fetch — over the axon tunnel every fetch
    pays an ~89 ms round trip, so a happy window (the steady state) costs
    one tiny transfer instead of the full masks; phase 2 pulls the full
    per-lane payloads, again concatenated into one fetch, only for batches
    whose header said unhappy. Thunks may mix schemes (the mixed
    mega-commit resolves its ed25519 and sr25519 sub-batches together) —
    each carries its own host re-check oracle.

    Device-fault behavior: a batch whose dispatch failed (or that was
    staged host-side because the breaker was open) resolves on the host
    oracle; a failed/hung combined fetch (watchdog) drops every device
    batch still depending on it onto the host oracle. The function never
    raises on device trouble — blocksync's pool routine awaits it from an
    executor."""
    parts = [t.device_parts() for t in thunks]
    pairs: list = []  # per thunk: (header_dev, payload_dev) | None | False
    for p in parts:
        acquire = p[0]
        if acquire is None:
            pairs.append(None)
            continue
        try:
            pairs.append(acquire())
        except Exception:  # noqa: BLE001 - recorded by the thunk's supervisor
            pairs.append(False)
    live = [pr for pr in pairs if pr is not None and pr is not False]

    def _pull(arrs):
        from cometbft_tpu.libs import chaos

        chaos.fire("mixed.resolve")
        return np.asarray(jnp.concatenate(arrs))

    headers = None
    if live:
        sup = _dispatch.supervisor("device")
        try:
            with _trace.span("resolve.header_fetch", cat="fetch",
                             batches=len(live)) as sp:
                # NOT fed to the link model: this fetch blocks until every
                # batch's kernel finishes, so its wall time is compute-
                # entangled (the post-header payload pull below is pure)
                headers = _fetch_pool().submit(
                    _pull, [h for h, _ in live]).result(
                        timeout=_dispatch.watchdog_timeout())
                sp.add_bytes(rx=headers.nbytes)
        except Exception as exc:  # noqa: BLE001 - window falls to the CPU rung
            sup.record_op_failure(exc)
    verdicts: list[str | None] = []  # parallel to pairs; None = host oracle
    need_payload = []
    li = 0
    for pr, p in zip(pairs, parts):
        if pr is None or pr is False or headers is None:
            verdicts.append(None)
            continue
        v = decode_header(headers[2 * li:2 * li + 2], p[0].expected)
        li += 1
        if v == "echo_corrupt":
            _count_integrity("mask_echo_mismatch")
        if v != "happy":
            need_payload.append(pr[1])
        verdicts.append(v)
    flat = None
    if need_payload:
        sup = _dispatch.supervisor("device")
        try:
            t0 = _time.perf_counter()
            flat = _fetch_pool().submit(_pull, need_payload).result(
                timeout=_dispatch.watchdog_timeout())
            _linkmodel.tunnel().observe_transfer(
                flat.nbytes, _time.perf_counter() - t0)
        except Exception as exc:  # noqa: BLE001 - those batches go host-side
            sup.record_op_failure(exc)
    if headers is not None:
        if not need_payload:
            _count_fetch(True, headers.nbytes)
        else:
            _count_fetch(False, headers.nbytes
                         + (flat.nbytes if flat is not None else 0))
    out = []
    off = 0
    for pr, p, v in zip(pairs, parts, verdicts):
        acquire, n, pre_ok, ok_a, rows, info, redo = p
        ok_a = _ok_arr(ok_a)  # late cell: resolved once dispatch ran
        if pr is None and acquire is None and n == 0:
            out.append(np.zeros(0, dtype=bool))
        elif pr is None or pr is False or v is None:
            out.append(host_oracle_mask(n, pre_ok, ok_a, rows, info))
        elif v == "happy":
            out.append(pre_ok & ok_a)
        elif flat is None:
            out.append(host_oracle_mask(n, pre_ok, ok_a, rows, info))
        else:
            b = pr[1].shape[0]
            out.append(decode_payload(
                flat[off:off + b], n, pre_ok, ok_a, rows, info, redo=redo))
            off += b
    for t in thunks:
        rel = getattr(t, "release_staging", None)
        if rel is not None:
            rel()
    return out


_pool = None
_fpool = None


def _xfer_pool():
    global _pool
    if _pool is None:
        import concurrent.futures

        _pool = concurrent.futures.ThreadPoolExecutor(
            max_workers=4, thread_name_prefix="ed25519-xfer"
        )
    return _pool


def _fetch_pool():
    """Separate pool for watchdog-bounded device->host fetches: a fetch
    abandoned by the watchdog keeps its thread until jax gives up, and it
    must not starve the dispatch pool. If a hung device clogs both workers,
    subsequent fetches time out too — which is the truth — and the breaker
    stops new device batches after the threshold."""
    global _fpool
    if _fpool is None:
        import concurrent.futures

        _fpool = concurrent.futures.ThreadPoolExecutor(
            max_workers=2, thread_name_prefix="device-fetch"
        )
    return _fpool
