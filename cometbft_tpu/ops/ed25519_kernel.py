"""Batched Ed25519 ZIP-215 verification — the jitted device entry points.

Two kernels, split so decompressed validator pubkeys can be cached across
calls (the device-resident analog of the reference's LRU expanded-key cache,
crypto/ed25519/ed25519.go:44,63-69 — a validator set re-verifies every
height, but its keys decompress once):

  decompress(y, sign)                 -> (ok, X, Y, Z, T)
  verify(A..., okA, yR, signR, s, k)  -> per-lane validity mask

verify computes, per lane:  [8]([s]B - [k]A - R) == O   (cofactored,
ZIP-215), via one Straus double-scalar ladder for [s]B + [k](-A), one add of
-R, three doublings, and a projective identity test. The mask pinpoints bad
signatures directly — the reference's fallback-to-serial re-verify
(types/validation.go:266) has no analog here.

Batch sizes are bucketed to powers of two (min 8) to bound recompilation;
padding lanes carry the identity encoding (y=1) with zero scalars, which
verify as valid and are sliced off.
"""

from __future__ import annotations

import functools
import hashlib

import jax
import jax.numpy as jnp
import numpy as np

from cometbft_tpu.crypto import ed25519_math as oracle
from cometbft_tpu.ops import curve
from cometbft_tpu.ops import limbs as L

MIN_BUCKET = 8
MAX_BUCKET_LOG2 = 17  # 128k lanes


def bucket_size(n: int) -> int:
    b = MIN_BUCKET
    while b < n:
        b *= 2
    if b > (1 << MAX_BUCKET_LOG2):
        raise ValueError(f"batch of {n} exceeds max bucket {1 << MAX_BUCKET_LOG2}")
    return b


@functools.partial(jax.jit, static_argnames=())
def _decompress_kernel(y: jnp.ndarray, sign: jnp.ndarray):
    ok, p = curve.decompress_zip215(y, sign)
    return ok, p.x, p.y, p.z, p.t


@jax.jit
def _verify_kernel(
    ax: jnp.ndarray,
    ay: jnp.ndarray,
    az: jnp.ndarray,
    at: jnp.ndarray,
    ok_a: jnp.ndarray,
    y_r: jnp.ndarray,
    sign_r: jnp.ndarray,
    s_bits: jnp.ndarray,
    k_bits: jnp.ndarray,
) -> jnp.ndarray:
    ok_r, r = curve.decompress_zip215(y_r, sign_r)
    neg_a = curve.neg(curve.Point(ax, ay, az, at))
    sb_ka = curve.straus_base_and_point(s_bits, k_bits, neg_a)
    diff = curve.add(sb_ka, curve.neg(r))
    valid = curve.is_identity(curve.mul_by_cofactor(diff))
    return valid & ok_a & ok_r


def decompress_points(enc: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
    """(N, 32) uint8 encodings -> (ok (N,) bool, coords (N, 4, 20) int32),
    padding internally to a bucket. Host-facing; used to fill the pubkey
    cache and by tests. Device arrays are limb-axis-first (20, B); the host
    cache keeps batch-major (N, 4, 20) for cheap per-key gathers."""
    n = enc.shape[0]
    b = bucket_size(n)
    y, sign = L.encodings_to_point_inputs(enc)
    if b > n:
        pad_y = np.zeros((b - n, L.NLIMBS), dtype=np.int32)
        pad_y[:, 0] = 1  # y = 1: the identity point, always decompressible
        y = np.concatenate([y, pad_y])
        sign = np.concatenate([sign, np.zeros(b - n, dtype=np.int32)])
    ok, x, yy, z, t = _decompress_kernel(jnp.asarray(y.T), jnp.asarray(sign))
    coords = np.stack(
        [np.asarray(x).T, np.asarray(yy).T, np.asarray(z).T, np.asarray(t).T], axis=1
    )
    return np.asarray(ok)[:n], coords[:n]


class PubKeyCache:
    """Decompressed-pubkey cache: pubkey bytes -> (ok, (4, 20) int32 coords).
    Bounded FIFO (validator sets churn slowly; 64k entries ~ 20 MB)."""

    def __init__(self, capacity: int = 65536):
        self.capacity = capacity
        self._map: dict[bytes, tuple[bool, np.ndarray]] = {}

    def lookup_or_decompress(self, pubs: list[bytes]) -> tuple[np.ndarray, np.ndarray]:
        missing = [p for p in dict.fromkeys(pubs) if p not in self._map]
        if missing:
            enc = np.frombuffer(b"".join(missing), dtype=np.uint8).reshape(-1, 32)
            ok, coords = decompress_points(enc)
            for i, p in enumerate(missing):
                if len(self._map) >= self.capacity:
                    self._map.pop(next(iter(self._map)))
                self._map[p] = (bool(ok[i]), coords[i])
        oks = np.empty(len(pubs), dtype=bool)
        coords = np.empty((len(pubs), 4, L.NLIMBS), dtype=np.int32)
        for i, p in enumerate(pubs):
            o, c = self._map[p]
            oks[i] = o
            coords[i] = c
        return oks, coords


_default_cache = PubKeyCache()


def compute_challenges(pubs: list[bytes], msgs: list[bytes], sigs: list[bytes]) -> list[int]:
    """k_i = SHA-512(R_i || A_i || M_i) mod L — host-side (SHA-512 is 64-bit
    word arithmetic, hostile to the TPU VPU; ~1 us/item via OpenSSL)."""
    out = []
    for pub, msg, sig in zip(pubs, msgs, sigs):
        h = hashlib.sha512()
        h.update(sig[:32])
        h.update(pub)
        h.update(msg)
        out.append(int.from_bytes(h.digest(), "little") % oracle.L)
    return out


def verify_batch(
    pubs: list[bytes],
    msgs: list[bytes],
    sigs: list[bytes],
    cache: PubKeyCache | None = None,
) -> tuple[bool, list[bool]]:
    """ZIP-215 batch verification with per-signature mask. Agrees with
    oracle.verify_zip215 on every input (tested bit-for-bit); structural
    rejects (bad lengths, s >= L) are filtered host-side and never reach
    the device."""
    n = len(sigs)
    assert len(pubs) == n and len(msgs) == n
    if n == 0:
        return True, []
    cache = cache or _default_cache

    pre_ok = np.ones(n, dtype=bool)
    s_vals = [0] * n
    for i, (pub, sig) in enumerate(zip(pubs, sigs)):
        if len(pub) != 32 or len(sig) != 64:
            pre_ok[i] = False
            continue
        s = int.from_bytes(sig[32:], "little")
        if s >= oracle.L:
            pre_ok[i] = False
            continue
        s_vals[i] = s

    safe_pubs = [p if pre_ok[i] else b"\x01" + b"\x00" * 31 for i, p in enumerate(pubs)]
    safe_rs = [sigs[i][:32] if pre_ok[i] else b"\x01" + b"\x00" * 31 for i in range(n)]
    ok_a, a_coords = cache.lookup_or_decompress(safe_pubs)
    ks = compute_challenges(safe_pubs, msgs, sigs)
    for i in range(n):
        if not pre_ok[i]:
            ks[i] = 0

    b = bucket_size(n)
    pad = b - n
    r_enc = np.frombuffer(b"".join(safe_rs), dtype=np.uint8).reshape(n, 32)
    y_r, sign_r = L.encodings_to_point_inputs(r_enc)
    s_bits = L.scalars_to_bits(s_vals)
    k_bits = L.scalars_to_bits(ks)

    if pad:
        id_y = np.zeros((pad, L.NLIMBS), dtype=np.int32)
        id_y[:, 0] = 1
        id_coords = np.zeros((pad, 4, L.NLIMBS), dtype=np.int32)
        id_coords[:, 1, 0] = 1  # Y = 1
        id_coords[:, 2, 0] = 1  # Z = 1
        a_coords = np.concatenate([a_coords, id_coords])
        ok_a = np.concatenate([ok_a, np.ones(pad, dtype=bool)])
        y_r = np.concatenate([y_r, id_y])
        sign_r = np.concatenate([sign_r, np.zeros(pad, dtype=np.int32)])
        zbits = np.zeros((pad, L.SCALAR_BITS), dtype=np.int32)
        s_bits = np.concatenate([s_bits, zbits])
        k_bits = np.concatenate([k_bits, zbits])

    mask_dev = _verify_kernel(
        jnp.asarray(np.ascontiguousarray(a_coords[:, 0].T)),
        jnp.asarray(np.ascontiguousarray(a_coords[:, 1].T)),
        jnp.asarray(np.ascontiguousarray(a_coords[:, 2].T)),
        jnp.asarray(np.ascontiguousarray(a_coords[:, 3].T)),
        jnp.asarray(ok_a),
        jnp.asarray(np.ascontiguousarray(y_r.T)),
        jnp.asarray(sign_r),
        jnp.asarray(np.ascontiguousarray(s_bits.T)),
        jnp.asarray(np.ascontiguousarray(k_bits.T)),
    )
    mask = np.asarray(mask_dev)[:n] & pre_ok
    return bool(mask.all()), mask.tolist()
