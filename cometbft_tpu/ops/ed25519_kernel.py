"""Batched Ed25519 ZIP-215 verification — the jitted device entry points.

Two kernels, split so decompressed validator pubkeys can be cached across
calls (the device-resident analog of the reference's LRU expanded-key cache,
crypto/ed25519/ed25519.go:44,63-69 — a validator set re-verifies every
height, but its keys decompress once):

  decompress(words)                  -> (ok, X, Y, Z, T)
  verify(A-coords, rW, sW, kW)       -> per-lane validity mask

verify computes, per lane:  [8]([s]B - [k]A - R) == O   (cofactored,
ZIP-215), via a signed 5-bit windowed double-scalar ladder (curve.py), one
add of -R, three doublings, and a projective identity test. The mask
pinpoints bad signatures directly; the few lanes it rejects are
double-checked against the host oracle before being reported (see
_recheck_failed_lanes — the narrow analog of the reference's
fallback-to-serial re-verify, types/validation.go:266).

Wire layout (the perf-critical design point): R / s / k cross the host link
as packed (8, B) uint32 words — 96 B per signature — and are unpacked to
limbs/digits on device (ops/unpack.py). Validator pubkey coordinates live
in a device-resident batch cache keyed by the pubkey-set digest, so the
steady-state commit-verification path transfers ~1 MB per 10k-signature
batch instead of ~25 MB.

Batch sizes are bucketed to powers of two (min 8) to bound recompilation;
padding lanes carry the identity encoding (y=1) with zero scalars, which
verify as valid and are sliced off.
"""

from __future__ import annotations

import hashlib

import jax
import jax.numpy as jnp
import numpy as np

from cometbft_tpu.crypto import ed25519_math as oracle
from cometbft_tpu.ops import curve
from cometbft_tpu.ops import limbs as L
from cometbft_tpu.ops import unpack as U

MIN_BUCKET = 8
MAX_BUCKET_LOG2 = 17  # 128k lanes

_ID_ENC32 = (1).to_bytes(32, "little")  # y=1: the identity point encoding


_POW2_CAP = 2048  # above this, buckets are multiples of _POW2_CAP


def bucket_size(n: int) -> int:
    """Power-of-two buckets up to 2048, then multiples of 2048: bounds the
    number of compiled shapes (9 + 63) while capping padding waste at 20%
    for large batches (a 10240-sig mega-commit runs at exactly 10240 lanes,
    not 16384)."""
    if n > (1 << MAX_BUCKET_LOG2):
        raise ValueError(f"batch of {n} exceeds max bucket {1 << MAX_BUCKET_LOG2}")
    b = MIN_BUCKET
    while b < n and b < _POW2_CAP:
        b *= 2
    if b >= n:
        return b
    return (n + _POW2_CAP - 1) // _POW2_CAP * _POW2_CAP


@jax.jit
def _decompress_kernel(words: jnp.ndarray):
    """(8, B) uint32 packed encodings -> (ok, X, Y, Z, T) each (20, B)."""
    y = U.words_to_y_limbs(words)
    sign = U.words_sign(words)
    ok, p = curve.decompress_zip215(y, sign)
    return ok, p.x, p.y, p.z, p.t


def verify_math(ax, ay, az, at, r_words, s_words, k_words) -> jnp.ndarray:
    """The per-chip verify program (also the shard_map body, parallel/mesh).
    A-coords (20, B) int32; r/s/k packed (8, B) uint32. Lanes whose pubkey
    failed decompression produce garbage — the caller masks with ok_a."""
    y_r = U.words_to_y_limbs(r_words)
    sign_r = U.words_sign(r_words)
    ok_r, r = curve.decompress_zip215(y_r, sign_r)
    neg_a = curve.neg(curve.Point(ax, ay, az, at))
    sb_ka = curve.windowed_double_scalar_signed(
        U.words_to_digits5_signed(s_words), U.words_to_digits5_signed(k_words), neg_a
    )
    diff = curve.add(sb_ka, curve.neg(r))
    valid = curve.is_identity(curve.mul_by_cofactor(diff))
    return valid & ok_r


_verify_kernel = jax.jit(verify_math)

# Pallas path: the fused-VMEM ladder (pallas_verify.py) is ~2.5x the
# XLA-compiled program on real TPU (HBM-bound vs VMEM-resident). Enabled
# for TPU backends on lane-aligned buckets; CPU (tests) and small buckets
# use the XLA program. CBFT_NO_PALLAS=1 forces the XLA path.
_use_pallas: bool | None = None


def _pallas_available() -> bool:
    global _use_pallas
    if _use_pallas is None:
        import os

        _use_pallas = (
            os.environ.get("CBFT_NO_PALLAS") != "1"
            and jax.devices()[0].platform == "tpu"
        )
    return _use_pallas


# Serializes jit dispatch (and therefore tracing) across ALL curve kernels
# and threads — see ops/dispatch.py for why the Pallas constant swap makes
# this mandatory.
from cometbft_tpu.ops.dispatch import KERNEL_DISPATCH_LOCK as _dispatch_lock

# ---------------------------------------------------------------------------
# Transfer integrity. The axon tunnel has produced isolated single-lane
# corruption under load (observed twice across ~10 bench runs); the
# reference trusts in-process memory (types/validation.go:235) — a
# tunnel-attached device must earn that trust explicitly:
#   host->device: a position-weighted checksum of the staged r/s/k words is
#     recomputed ON DEVICE and compared to the host's value; the verdict
#     rides back inside the verify payload (no extra round trip).
#   device->host: the mask travels twice (mask + bitwise complement); an
#     echo mismatch flags fetch-path corruption.
# A failed check is counted, logged, retried once with a fresh transfer,
# and — if still failing — the batch falls back to the exact host oracle,
# so corruption is *detected and contained*, never silently tolerated.
# ---------------------------------------------------------------------------

_CHK_MULT = np.uint64(2654435761)  # Knuth multiplicative-hash odd constant


def _host_checksum(*arrs: np.ndarray) -> int:
    """Position-weighted sum mod 2^32 over the arrays' uint32 views, in
    ravel order — bit-identical to _device_checksum."""
    acc = 0
    off = 0
    for a in arrs:
        flat = np.ascontiguousarray(a).view(np.uint32).ravel().astype(np.uint64)
        idx = np.arange(off, off + flat.size, dtype=np.uint64)
        w = (idx * _CHK_MULT + 1) & 0xFFFFFFFF
        acc = (acc + int(((flat * w) & 0xFFFFFFFF).sum() & 0xFFFFFFFF)) & 0xFFFFFFFF
        off += flat.size
    return acc


def _device_checksum_expr(arrs) -> jnp.ndarray:
    """The device-side mirror of _host_checksum (traced inside the payload
    jit)."""
    acc = jnp.uint32(0)
    off = 0
    for a in arrs:
        if a.dtype == jnp.int32:
            flat = jax.lax.bitcast_convert_type(a, jnp.uint32).ravel()
        else:
            flat = a.astype(jnp.uint32).ravel()
        idx = jax.lax.iota(jnp.uint32, flat.size) + jnp.uint32(off)
        w = idx * jnp.uint32(2654435761) + jnp.uint32(1)
        acc = acc + (flat * w).sum(dtype=jnp.uint32)
        off += flat.size
    return acc


_device_checksum = jax.jit(_device_checksum_expr)


@jax.jit
def _integrity_payload(mask, rw, sw, kw, expected):
    """(2B+1,) bool payload: [mask, ~mask (echo), staging-checksum ok]."""
    chk = _device_checksum_expr((rw, sw, kw))
    ok = (chk == expected.astype(jnp.uint32))
    return jnp.concatenate([mask, ~mask, ok[None]])


def host_oracle_mask(n, pre_ok, ok_a, rows, info) -> np.ndarray:
    """The CPU rung of the verify ladder: the scheme's exact host oracle
    over the batch rows. Counts the lanes as fallback verifies."""
    verify_fn = info[0]
    pubs, msgs, sigs = rows
    host = np.fromiter(
        (verify_fn(p, m, s) for p, m, s in zip(pubs, msgs, sigs)),
        dtype=bool, count=n)
    _count_fallback(info[1], n)
    return host & pre_ok & ok_a


def decode_payload(payload: np.ndarray, n, pre_ok, ok_a, rows, info,
                   redo=None) -> np.ndarray:
    """Validate the integrity payload and produce the final (N,) mask.
    On checksum/echo failure: count, log, retry once with a fresh transfer
    (redo), then fall back to the exact host oracle for the whole batch."""
    b = (payload.shape[0] - 1) // 2
    mask = payload[:b].copy()
    echo = payload[b:2 * b]
    chk_ok = bool(payload[2 * b])
    echo_ok = bool((mask != echo).all())  # echo is the complement
    if not (chk_ok and echo_ok):
        from cometbft_tpu.libs import log as _log

        _count_integrity(
            "transfer_checksum_mismatch" if not chk_ok else "mask_echo_mismatch")
        _log.default().error(
            "device transfer integrity check failed",
            scheme=info[1], staging_checksum_ok=str(chk_ok),
            mask_echo_ok=str(echo_ok),
            action="retry" if redo is not None else "host-oracle fallback")
        if redo is not None:
            try:
                fresh = np.asarray(redo())
            except Exception:  # noqa: BLE001 - device died during the retry
                fresh = None
            if fresh is not None:
                return decode_payload(
                    fresh, n, pre_ok, ok_a, rows, info, redo=None)
        return host_oracle_mask(n, pre_ok, ok_a, rows, info)
    mask = mask[:n] & pre_ok & ok_a
    return apply_recheck(mask, pre_ok & ok_a, rows, info)


def _count_integrity(kind: str, n: int = 1) -> None:
    try:
        from cometbft_tpu.libs import metrics as _metrics

        getattr(_metrics.crypto_metrics(), kind).inc(n)
    except Exception:  # noqa: BLE001 - metrics must never break verification
        pass


def _count_fallback(scheme: str, n: int) -> None:
    """Count lanes that fell off the device onto the CPU ladder."""
    try:
        from cometbft_tpu.libs import metrics as _metrics

        _metrics.crypto_metrics().fallback_verifies.labels(scheme).inc(n)
    except Exception:  # noqa: BLE001
        pass


def _count_device_batch(scheme: str, lanes: int) -> None:
    """Count a successfully dispatched device batch (the TPU-path-is-alive
    signal the chaos tests assert on)."""
    try:
        from cometbft_tpu.libs import metrics as _metrics

        cm = _metrics.crypto_metrics()
        cm.device_batches.labels(scheme).inc()
        cm.device_lanes.labels(scheme).inc(lanes)
    except Exception:  # noqa: BLE001
        pass


from cometbft_tpu.ops import dispatch as _dispatch
from cometbft_tpu.ops.dispatch import PallasGate

_pallas_gate = PallasGate("pallas.ed25519")


# Device trace-count instrumentation: every lane count dispatched this
# process is a shape XLA/Pallas compiled a program for. The scheduler's
# bucket soak asserts len(dispatched_shapes()) stays <= the bucket-ladder
# length — continuous batching must bound compilation, not multiply it.
_dispatched_shapes: set[int] = set()


def dispatched_shapes() -> list[int]:
    return sorted(_dispatched_shapes)


def reset_shape_log() -> None:
    _dispatched_shapes.clear()


def _dispatch_verify(a_dev, r_words, s_words, k_words):
    from cometbft_tpu.ops import pallas_verify as PV

    _dispatched_shapes.add(int(r_words.shape[1]))
    with _dispatch_lock:
        return _pallas_gate.run(
            PV.verify_pallas, _verify_kernel,
            (*a_dev, r_words, s_words, k_words), r_words.shape[1])


def decompress_points(enc: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
    """(N, 32) uint8 encodings -> (ok (N,) bool, coords (N, 4, 20) int32),
    padding internally to a bucket. Host-facing; used to fill the pubkey
    cache and by tests. Device arrays are limb-axis-first (20, B); the host
    cache keeps batch-major (N, 4, 20) for cheap per-key gathers."""
    n = enc.shape[0]
    b = bucket_size(n)
    words = L.bytes_to_words(enc)
    if b > n:
        pad = np.zeros((b - n, 8), dtype=np.uint32)
        pad[:, 0] = 1  # y = 1: the identity point, always decompressible
        words = np.concatenate([words, pad])
    with _dispatch_lock:
        ok, x, yy, z, t = _decompress_kernel(jnp.asarray(words.T))
    coords = np.stack(
        [np.asarray(x).T, np.asarray(yy).T, np.asarray(z).T, np.asarray(t).T], axis=1
    )
    return np.asarray(ok)[:n], coords[:n]


class PubKeyCache:
    """Two-level decompressed-pubkey cache.

    Host level: pubkey bytes -> (ok, (4, 20) int32 coords), bounded FIFO —
    absorbs validator-set churn and partial overlap between batches.
    Device level: digest of the padded pubkey batch -> coords already
    resident on device as (20, B) arrays — the steady-state hit for commit
    verification, where the same validator set re-verifies every height and
    the A-coordinate upload (3.3 MB at 10k lanes) drops to zero.
    """

    # subclasses (sr25519) swap in their scheme's device decompressor;
    # staticmethod so instances share one slot
    _decompress = staticmethod(lambda enc: decompress_points(enc))

    def __init__(self, capacity: int = 65536, device_slots: int = 8):
        self.capacity = capacity
        self.device_slots = device_slots
        self._map: dict[bytes, tuple[bool, np.ndarray]] = {}
        self._dev: dict[bytes, tuple] = {}

    def lookup_or_decompress(self, pubs: list[bytes]) -> tuple[np.ndarray, np.ndarray]:
        """Host-level: (ok (N,) bool, coords (N, 4, 20) int32)."""
        missing = [p for p in dict.fromkeys(pubs) if p not in self._map]
        if missing:
            enc = np.frombuffer(b"".join(missing), dtype=np.uint8).reshape(-1, 32)
            ok, coords = self._decompress(enc)
            evict = min(len(self._map), len(self._map) + len(missing) - self.capacity)
            for _ in range(max(0, evict)):
                self._map.pop(next(iter(self._map)))
            for i, p in enumerate(missing):
                self._map[p] = (bool(ok[i]), coords[i])
        oks = np.empty(len(pubs), dtype=bool)
        coords = np.empty((len(pubs), 4, L.NLIMBS), dtype=np.int32)
        for i, p in enumerate(pubs):
            o, c = self._map[p]
            oks[i] = o
            coords[i] = c
        return oks, coords

    def stage(
        self, pubs: list[bytes], bucket: int, put=None, put_key: str = ""
    ) -> tuple[np.ndarray, tuple]:
        """(ok_a (N,) host bool, (ax, ay, az, at) device arrays (20, bucket)).
        `put` overrides jax.device_put (the mesh path passes a sharded put;
        put_key disambiguates cache entries across shardings/meshes)."""
        digest = hashlib.sha256(put_key.encode() + b"".join(pubs)).digest() + bytes(
            [bucket.bit_length()]
        )
        hit = self._dev.get(digest)
        if hit is not None:
            return hit[0], hit[1]
        ok_a, coords = self.lookup_or_decompress(pubs)
        pad = bucket - len(pubs)
        if pad:
            id_coords = np.zeros((pad, 4, L.NLIMBS), dtype=np.int32)
            id_coords[:, 1, 0] = 1  # Y = 1
            id_coords[:, 2, 0] = 1  # Z = 1
            coords = np.concatenate([coords, id_coords])
        put = put or jax.device_put
        host_arrs = tuple(np.ascontiguousarray(coords[:, i].T) for i in range(4))
        expected = _host_checksum(*host_arrs)
        dev = None
        for attempt in (1, 2):
            dev = tuple(put(a) for a in host_arrs)
            # upload-time integrity check: a corrupted coordinate table
            # would poison EVERY batch against this valset until eviction,
            # so the one extra round trip per cache miss is paid here
            got = int(np.asarray(_device_checksum(dev)))
            if got == expected:
                break
            _count_integrity("transfer_checksum_mismatch")
            from cometbft_tpu.libs import log as _log

            _log.default().error(
                "pubkey coordinate upload failed integrity check",
                attempt=str(attempt))
            if attempt == 2:
                raise RuntimeError(
                    "pubkey coordinate upload corrupted twice; refusing to "
                    "cache a poisoned table")
        if len(self._dev) >= self.device_slots:
            self._dev.pop(next(iter(self._dev)))
        self._dev[digest] = (ok_a, dev)
        return ok_a, dev


@jax.jit
def _gather_coords(dev_u, idx):
    """Device-side gather: unique-pubkey coordinate table (20, U) -> per-lane
    A-coordinates (20, B). Runs as a plain XLA op enqueued before the verify
    kernel — no host round trip."""
    return tuple(jnp.take(c, idx, axis=1) for c in dev_u)


def _stage_gather(cache: "PubKeyCache", pubs: list[bytes], bucket: int,
                  put_key: str = "") -> tuple[np.ndarray, tuple]:
    """(ok_a (N,), (ax, ay, az, at) device arrays (20, bucket)) via a
    device-side gather from the UNIQUE pubkey table. A batch that repeats a
    validator set W times (the coalesced blocksync window) uploads ONE copy
    of the coordinates (digest-cached across windows, since the unique set
    is stable even when window composition changes) plus a 4-byte/lane index
    vector — not W copies keyed on the exact concatenation."""
    uniq = list(dict.fromkeys(pubs))
    # an identity pad slot is needed only when padding lanes exist; when the
    # batch fills its bucket exactly (n == bucket == cap is legal) the +1
    # would overflow the lane cap
    need_pad = bucket > len(pubs)
    bu = bucket_size(len(uniq) + 1 if need_pad else len(uniq))
    ok_u, dev_u = cache.stage(uniq, bu, put_key=put_key)
    pos = {p: i for i, p in enumerate(uniq)}
    idx = np.full(bucket, len(uniq), dtype=np.int32)  # padding -> identity
    idx[: len(pubs)] = [pos[p] for p in pubs]
    ok_a = np.asarray(ok_u)[idx[: len(pubs)]]
    idx_dev = jax.device_put(idx)
    return ok_a, _gather_coords(dev_u, idx_dev)


_default_cache = PubKeyCache()


def compute_challenges(pubs: list[bytes], msgs: list[bytes], sigs: list[bytes]) -> list[int]:
    """k_i = SHA-512(R_i || A_i || M_i) mod L — host-side (SHA-512 is 64-bit
    word arithmetic, hostile to the TPU VPU; ~1 us/item via OpenSSL)."""
    sha = hashlib.sha512
    ell = oracle.L
    return [
        int.from_bytes(sha(sig[:32] + pub + msg).digest(), "little") % ell
        for pub, msg, sig in zip(pubs, msgs, sigs)
    ]


def stage_batch(
    pubs: list[bytes], msgs: list[bytes], sigs: list[bytes], bucket: int
) -> tuple[np.ndarray, list[bytes], np.ndarray, np.ndarray, np.ndarray]:
    """Host staging shared by the single-chip and mesh paths: structural
    checks (lengths, s < L — never reach the device), SHA-512 challenges,
    packed-word arrays padded to `bucket`, batch-minor (8, bucket) uint32.
    Returns (pre_ok, safe_pubs, r_words, s_words, k_words)."""
    n = len(sigs)
    pre_ok = np.ones(n, dtype=bool)
    s_vals = [0] * n
    for i, (pub, sig) in enumerate(zip(pubs, sigs)):
        if len(pub) != 32 or len(sig) != 64:
            pre_ok[i] = False
            continue
        s = int.from_bytes(sig[32:], "little")
        if s >= oracle.L:
            pre_ok[i] = False
            continue
        s_vals[i] = s

    safe_pubs = [p if pre_ok[i] else _ID_ENC32 for i, p in enumerate(pubs)]
    safe_rs = [sigs[i][:32] if pre_ok[i] else _ID_ENC32 for i in range(n)]
    ks = compute_challenges(safe_pubs, msgs, sigs)
    for i in range(n):
        if not pre_ok[i]:
            ks[i] = 0

    pad = bucket - n
    r_enc = np.frombuffer(b"".join(safe_rs), dtype=np.uint8).reshape(n, 32)
    r_words = L.bytes_to_words(r_enc)
    s_words = L.scalars_to_words(s_vals)
    k_words = L.scalars_to_words(ks)
    if pad:
        id_words = np.zeros((pad, 8), dtype=np.uint32)
        id_words[:, 0] = 1
        zwords = np.zeros((pad, 8), dtype=np.uint32)
        r_words = np.concatenate([r_words, id_words])
        s_words = np.concatenate([s_words, zwords])
        k_words = np.concatenate([k_words, zwords])
    return (
        pre_ok,
        safe_pubs,
        np.ascontiguousarray(r_words.T),
        np.ascontiguousarray(s_words.T),
        np.ascontiguousarray(k_words.T),
    )


def verify_batch(
    pubs: list[bytes],
    msgs: list[bytes],
    sigs: list[bytes],
    cache: PubKeyCache | None = None,
) -> tuple[bool, list[bool]]:
    """ZIP-215 batch verification with per-signature mask. Agrees with
    oracle.verify_zip215 on every input (tested bit-for-bit)."""
    mask = verify_batch_async(pubs, msgs, sigs, cache=cache)()
    return bool(mask.all()), mask.tolist()


# Failed lanes are re-verified on host with the exact ZIP-215 oracle before
# being reported invalid (bounded count — a batch with many failures is
# genuinely bad). The reference batch verifier falls back to serial
# re-verify on failure too (types/validation.go:266); here the motivation
# is also defensive: the dev tunnel transport has produced isolated
# single-lane corruption under load, and an honest signature must never be
# condemned by a flipped transfer bit.
_RECHECK_MAX = 32


def recheck_failed_lanes(mask, eligible, pubs, msgs, sigs,
                         verify_fn, scheme: str):
    """eligible: lanes that passed the host-side structural checks — a
    pre-failed lane carries a placeholder encoding (the identity, which
    being small-order validly signs ANYTHING under ZIP-215) and must never
    be flipped back to valid. Shared by the ed25519 and sr25519 paths;
    verify_fn is the scheme's exact host oracle."""
    import numpy as _np

    bad = _np.flatnonzero(~mask & eligible)
    if len(bad) == 0 or len(bad) > _RECHECK_MAX:
        return mask
    flipped = []
    for i in bad:
        if verify_fn(pubs[i], msgs[i], sigs[i]):
            mask[i] = True
            flipped.append(int(i))
    if flipped:
        from cometbft_tpu.libs import log as _log

        _count_integrity("mask_oracle_disagreement", len(flipped))
        _log.default().error(
            "device verify mask disagreed with host oracle; honoring host",
            scheme=scheme, lanes=str(flipped))
    return mask


def _recheck_failed_lanes(mask, eligible, pubs, msgs, sigs):
    return recheck_failed_lanes(
        mask, eligible, pubs, msgs, sigs, oracle.verify_zip215, "ed25519")


def apply_recheck(mask, eligible, rows, info):
    """Host-oracle recheck with optional per-group budgets: info is
    (verify_fn, scheme, groups). A coalesced window passes its per-commit
    row boundaries as groups so each commit keeps its own _RECHECK_MAX
    budget — one genuinely-bad commit must not suppress the
    transfer-corruption recheck for its window-mates."""
    verify_fn, scheme, groups = info
    pubs, msgs, sigs = rows
    if not groups:
        return recheck_failed_lanes(
            mask, eligible, pubs, msgs, sigs, verify_fn, scheme)
    for a, b in groups:
        mask[a:b] = recheck_failed_lanes(
            mask[a:b], eligible[a:b], pubs[a:b], msgs[a:b], sigs[a:b],
            verify_fn, scheme)
    return mask


def make_host_thunk(n, pre_ok, rows, info):
    """A verify thunk that never touches the device — the CPU rung of the
    ladder, used when the breaker has sidelined the device or staging
    failed. Same thunk contract as verify_batch_async (device_parts with a
    None payload acquirer and n > 0 routes resolve_batches here too)."""
    ones = np.ones(n, dtype=bool)
    cached: dict = {}

    def result() -> np.ndarray:
        if "m" not in cached:
            cached["m"] = host_oracle_mask(n, pre_ok, ones, rows, info)
        return cached["m"]

    result.device_parts = lambda: (None, n, pre_ok, ones, rows, info, None)
    return result


def supervised_device_thunk(scheme: str, sup, submit_fn, fetch_site: str,
                            n, pre_ok, ok_a, rows, info):
    """The shared thunk shape for a supervised device batch (ed25519 and
    sr25519 build their dispatch closure, this builds the rest): dispatch
    runs on the transfer pool under the supervisor; the payload fetch is
    watchdog-bounded; every failure drops the batch onto the host oracle
    instead of raising into the verify seam."""
    fut = _xfer_pool().submit(sup.run, submit_fn)

    def _acquire():
        """Block until dispatch completes; returns the device-resident
        payload. Raises DeviceOpFailed/DeviceUnavailable (recorded)."""
        try:
            return fut.result(timeout=_dispatch.watchdog_timeout())
        except (_dispatch.DeviceOpFailed, _dispatch.DeviceUnavailable):
            raise
        except Exception as exc:  # noqa: BLE001 - watchdog timeout etc.
            sup.record_op_failure(exc)
            raise _dispatch.DeviceOpFailed(f"{scheme} dispatch wait") from exc

    def _fetch_np(payload_dev) -> np.ndarray:
        """Device->host payload fetch: chaos site + watchdog + injected
        lane corruption (the integrity echo plane must catch it)."""
        from cometbft_tpu.libs import chaos

        try:
            chaos.fire(fetch_site)
            out = _fetch_pool().submit(
                lambda: np.asarray(payload_dev)).result(
                    timeout=_dispatch.watchdog_timeout())
        except Exception as exc:  # noqa: BLE001
            sup.record_op_failure(exc)
            raise _dispatch.DeviceOpFailed(f"{scheme} payload fetch") from exc
        return chaos.corrupt_mask(fetch_site, out)

    def _redo():
        """Integrity-retry path: full fresh transfer+dispatch+fetch,
        supervised AND watchdog-bounded like every other device wait — a
        device that hangs during the retry must not stall the verify seam
        (decode_payload catches and falls to the host oracle), and the
        hang/failure is recorded so the breaker and crypto_health see it."""
        try:
            return _fetch_pool().submit(
                lambda: np.asarray(sup.run(submit_fn))).result(
                    timeout=_dispatch.watchdog_timeout())
        except (_dispatch.DeviceOpFailed, _dispatch.DeviceUnavailable):
            raise  # sup.run already recorded it
        except Exception as exc:  # noqa: BLE001 - watchdog timeout etc.
            sup.record_op_failure(exc)
            raise

    def result() -> np.ndarray:
        try:
            payload = _fetch_np(_acquire())
        except (_dispatch.DeviceOpFailed, _dispatch.DeviceUnavailable):
            return host_oracle_mask(n, pre_ok, ok_a, rows, info)
        return decode_payload(
            payload, n, pre_ok, ok_a, rows, info, redo=_redo)

    result.device_parts = lambda: (
        _acquire, n, pre_ok, ok_a, rows, info, _redo)
    return result


def verify_batch_async(
    pubs: list[bytes],
    msgs: list[bytes],
    sigs: list[bytes],
    cache: PubKeyCache | None = None,
    recheck_groups: list[tuple[int, int]] | None = None,
):
    """Stage + dispatch without blocking on the device: returns a thunk that
    materializes the (N,) bool mask. Lets callers (blocksync streaming,
    VoteSet flush) overlap host staging of batch N+1 with device compute of
    batch N. recheck_groups: per-commit row boundaries of a coalesced
    window (see apply_recheck).

    Device faults never escape the thunk: dispatch runs under the "device"
    supervisor (transient retry + breaker, ops/dispatch.py), fetches are
    watchdog-bounded, and any failure resolves the batch on the exact host
    oracle — a hung or dead device costs latency, not a consensus round."""
    n = len(sigs)
    assert len(pubs) == n and len(msgs) == n
    if n == 0:
        empty = lambda: np.zeros(0, dtype=bool)  # noqa: E731
        empty.device_parts = lambda: (
            None, 0, np.zeros(0, bool), np.zeros(0, bool), ([], [], []),
            (oracle.verify_zip215, "ed25519", None), None)
        return empty
    cache = cache or _default_cache

    b = bucket_size(n)
    pre_ok, safe_pubs, r_words, s_words, k_words = stage_batch(pubs, msgs, sigs, b)
    rows = (safe_pubs, list(msgs), list(sigs))
    info = (oracle.verify_zip215, "ed25519", recheck_groups)
    sup = _dispatch.supervisor("device")

    a_dev = None
    if _dispatch.device_allowed():
        try:
            ok_a, a_dev = _stage_gather(cache, safe_pubs, b)
        except Exception as exc:  # noqa: BLE001 - device died in staging
            sup.record_op_failure(exc)
    if a_dev is None:
        return make_host_thunk(n, pre_ok, rows, info)
    expected = np.uint32(_host_checksum(r_words, s_words, k_words))

    def _transfer_and_dispatch():
        from cometbft_tpu.libs import chaos

        chaos.fire("ed25519.dispatch")
        rw = jnp.asarray(r_words)
        sw = jnp.asarray(s_words)
        kw = jnp.asarray(k_words)
        mask = _dispatch_verify(a_dev, rw, sw, kw)
        payload = _integrity_payload(mask, rw, sw, kw, expected)
        _count_device_batch("ed25519", b)
        return payload

    # The host->device copy blocks the calling thread for the wire time
    # (~45 ms/MB through the axon tunnel), so it runs on a small pool:
    # the caller can stage batch i+1 while batch i's bytes are in flight,
    # and parallel puts multiplex the tunnel.
    return supervised_device_thunk(
        "ed25519", sup, _transfer_and_dispatch, "ed25519.fetch",
        n, pre_ok, ok_a, rows, info)


def resolve_batches(thunks) -> list[np.ndarray]:
    """Materialize many verify_batch_async results with ONE device->host
    fetch (device-side concat): over the axon tunnel every fetch pays an
    ~89 ms round trip, so streaming callers (blocksync, bench) resolve a
    window of batches at once. Thunks may mix schemes (the mixed
    mega-commit resolves its ed25519 and sr25519 sub-batches together) —
    each carries its own host re-check oracle.

    Device-fault behavior: a batch whose dispatch failed (or that was
    staged host-side because the breaker was open) resolves on the host
    oracle; a failed/hung combined fetch (watchdog) drops every device
    batch in the window onto the host oracle. The function never raises on
    device trouble — blocksync's pool routine awaits it from an executor."""
    parts = [t.device_parts() for t in thunks]
    payloads: list = []
    for p in parts:
        acquire = p[0]
        if acquire is None:
            payloads.append(None)
            continue
        try:
            payloads.append(acquire())
        except Exception:  # noqa: BLE001 - recorded by the thunk's supervisor
            payloads.append(False)
    nonempty = [p for p in payloads if p is not None and p is not False]
    flat = np.zeros(0, dtype=bool)
    if nonempty:
        sup = _dispatch.supervisor("device")

        def _pull():
            from cometbft_tpu.libs import chaos

            chaos.fire("mixed.resolve")
            return np.asarray(jnp.concatenate(nonempty))

        try:
            flat = _fetch_pool().submit(_pull).result(
                timeout=_dispatch.watchdog_timeout())
        except Exception as exc:  # noqa: BLE001 - window falls to the CPU rung
            sup.record_op_failure(exc)
            flat = None
    out = []
    off = 0
    for payload_dev, (acquire, n, pre_ok, ok_a, rows, info, redo) in zip(
            payloads, parts):
        if payload_dev is None and acquire is None and n == 0:
            out.append(np.zeros(0, dtype=bool))
            continue
        if payload_dev is None or payload_dev is False or flat is None:
            out.append(host_oracle_mask(n, pre_ok, ok_a, rows, info))
            continue
        b = payload_dev.shape[0]
        out.append(decode_payload(
            flat[off : off + b], n, pre_ok, ok_a, rows, info, redo=redo))
        off += b
    return out


_pool = None
_fpool = None


def _xfer_pool():
    global _pool
    if _pool is None:
        import concurrent.futures

        _pool = concurrent.futures.ThreadPoolExecutor(
            max_workers=4, thread_name_prefix="ed25519-xfer"
        )
    return _pool


def _fetch_pool():
    """Separate pool for watchdog-bounded device->host fetches: a fetch
    abandoned by the watchdog keeps its thread until jax gives up, and it
    must not starve the dispatch pool. If a hung device clogs both workers,
    subsequent fetches time out too — which is the truth — and the breaker
    stops new device batches after the threshold."""
    global _fpool
    if _fpool is None:
        import concurrent.futures

        _fpool = concurrent.futures.ThreadPoolExecutor(
            max_workers=2, thread_name_prefix="device-fetch"
        )
    return _fpool
