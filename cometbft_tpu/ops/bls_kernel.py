"""Batched BLS12-381 verification — staging/dispatch/reduced-fetch glue
shaped like ed25519_kernel.py, so the VerifyScheduler, the supervisor/
breaker ladder, resolve_batches' two-phase reduced fetch, and the
VerifyMesh's per-chip fault domains all carry the scheme untouched.

Two verify modes:

  verify_batch_async      batched SINGLE-verify (mempool admission,
                          evidence checks, mixed-scheme commits): per
                          lane i the pairing-product check
                          e(-g1, sig_i) * e(pk_i, H(m_i)) == 1, with the
                          two Miller loops of every lane batched into one
                          2B-wide loop and the final exponentiations
                          vectorized across lanes.
  aggregate_verify        one-pairing-product COMMIT verify: signatures
                          sum to one G2 point, pubkeys aggregate per
                          distinct sign-bytes (PoP semantics — identical
                          vote bytes aggregate their signers), and the
                          whole commit decides with D+1 Miller lanes and
                          ONE final exponentiation, any committee size.

Device layout: the staged block is (7, 35, bucket) int32 raw limb planes
[pk_x, sig_x0, sig_x1, u00, u01, u10, u11] plus a (3, bucket) flag plane
(pk sign, sig sign, lane-is-padding); SHA-256 message expansion is host
work (ops/hashvec.sha256_many), everything downstream — decompression,
subgroup checks, SvdW mapping, cofactor clearing, Miller loops, final
exponentiation — runs on the batch axis (ops/bls12381/).

The device program is a HOST-COMPOSED pipeline of jitted pieces (shared
exp/scan programs) rather than one monolithic jit: the monolithic form
compiled ~3x slower for zero runtime gain, and piece reuse means the
single-verify and aggregate paths share most of their compiled code.
Staged blocks do not ride limbs.StagingPool — its (3, 8, B) r/s/k block
shape is ed25519's wire format; BLS blocks are 7 limb planes and get
fresh arrays (pooling them is a later perf PR if profiles ever show it).

Degradation: identical to the other schemes — TPU (or XLA-on-CPU) device
path under the DeviceSupervisor, host-oracle fallback
(crypto/fallback.bls_verify) on any device fault, breaker-open routing,
reduced-fetch happy path of 8 B/batch via the shared header protocol.
"""

from __future__ import annotations

import time as _time

import jax
import jax.numpy as jnp
import numpy as np

from cometbft_tpu.crypto import fallback as _oracle
from cometbft_tpu.libs import linkmodel as _linkmodel
from cometbft_tpu.libs import trace as _trace
from cometbft_tpu.ops import dispatch as _dispatch
from cometbft_tpu.ops import ed25519_kernel as EK
from cometbft_tpu.ops.dispatch import KERNEL_DISPATCH_LOCK
from cometbft_tpu.ops.ed25519_kernel import bucket_size

SCHEME = "bls12381"
PUB_KEY_SIZE = 48
SIGNATURE_SIZE = 96


def _dst() -> bytes:
    from cometbft_tpu.crypto import bls12381

    return bls12381.DST


def oracle_verify(pub: bytes, msg: bytes, sig: bytes) -> bool:
    """The exact host oracle behind the recheck/fallback ladder."""
    return _oracle.bls_verify(pub, msg, sig, _dst())


# generator encodings: the structural-reject / padding placeholder rows
# (decompressable, in-subgroup; their verify verdict is masked anyway)
_G1_GEN_ENC = _oracle.bls_g1_compress(_oracle.BLS_G1)
_G2_GEN_ENC = _oracle.bls_g2_compress(_oracle.BLS_G2)

_NEG_G1_LIMBS: tuple | None = None  # memoized (35,1) Montgomery -g1


def _neg_g1_coords(b: int):
    """(-g1) affine coordinates broadcast to b lanes (Montgomery)."""
    global _NEG_G1_LIMBS
    from cometbft_tpu.ops.bls12381 import fp

    if _NEG_G1_LIMBS is None:
        x, y = _oracle._NEG_G1
        _NEG_G1_LIMBS = (fp._const(x * fp.R_MOD_P % fp.P_INT),
                         fp._const(y * fp.R_MOD_P % fp.P_INT))
    xs, ys = _NEG_G1_LIMBS
    shape = (fp.NLIMBS, b)
    return (jnp.broadcast_to(xs, shape).astype(jnp.int32),
            jnp.broadcast_to(ys, shape).astype(jnp.int32))


# ------------------------------------------------------------------ staging


def _structural_check(pubs, sigs, n):
    """Host structural pass: lengths, compression flags, infinity
    rejection, x < p canonicality — everything the oracle rejects before
    field math. Returns (pre_ok, pk_rows (n, 48) uint8, sig_rows
    (n, 96) uint8) with placeholder substitution on bad rows."""
    pre_ok = np.ones(n, dtype=bool)
    pk_rows = np.empty((n, PUB_KEY_SIZE), dtype=np.uint8)
    sig_rows = np.empty((n, SIGNATURE_SIZE), dtype=np.uint8)
    p = _oracle.BLS_P
    for i in range(n):
        pk, sg = pubs[i], sigs[i]
        ok = len(pk) == PUB_KEY_SIZE and len(sg) == SIGNATURE_SIZE
        if ok:
            ok = bool(pk[0] & 0x80) and not (pk[0] & 0x40)
            ok = ok and bool(sg[0] & 0x80) and not (sg[0] & 0x40)
        if ok:
            ok = int.from_bytes(bytes([pk[0] & 0x1F]) + pk[1:], "big") < p
            ok = (ok
                  and int.from_bytes(bytes([sg[0] & 0x1F]) + sg[1:48],
                                     "big") < p
                  and int.from_bytes(sg[48:], "big") < p)
        pre_ok[i] = ok
        pk_rows[i] = np.frombuffer(pk if ok else _G1_GEN_ENC, dtype=np.uint8)
        sig_rows[i] = np.frombuffer(sg if ok else _G2_GEN_ENC, dtype=np.uint8)
    return pre_ok, pk_rows, sig_rows


def stage_batch_bls(pubs, msgs, sigs, bucket: int):
    """Host staging: structural checks, SHA-256 message expansion
    (hashvec rung), limb packing. Returns (pre_ok (n,), block
    (7, 35, bucket) int32, flags (3, bucket) int32) — flags rows are
    [pk sign, sig sign, is_pad]. msgs=None zero-fills the u-planes
    (3..6): the aggregate path hashes only the DISTINCT messages in
    their own small bucket, so per-lane hash-to-field here would be
    O(n) dead work on the path whose point is committee-size-independent
    cost."""
    from cometbft_tpu.libs.prefixrows import as_bytes
    from cometbft_tpu.ops.bls12381 import fp
    from cometbft_tpu.ops.bls12381 import htc

    n = len(sigs)
    pre_ok, pk_rows, sig_rows = _structural_check(pubs, sigs, n)
    pad = bucket - n
    if pad:
        pk_rows = np.concatenate([pk_rows, np.broadcast_to(
            np.frombuffer(_G1_GEN_ENC, np.uint8), (pad, 48))])
        sig_rows = np.concatenate([sig_rows, np.broadcast_to(
            np.frombuffer(_G2_GEN_ENC, np.uint8), (pad, 96))])
    flags = np.zeros((3, bucket), dtype=np.int32)
    flags[0] = (pk_rows[:, 0] & 0x20) != 0
    flags[1] = (sig_rows[:, 0] & 0x20) != 0
    flags[2, n:] = 1
    pk_x = pk_rows.copy()
    pk_x[:, 0] &= 0x1F
    sg_x = sig_rows.copy()
    sg_x[:, 0] &= 0x1F
    block = np.empty((7, fp.NLIMBS, bucket), dtype=np.int32)
    block[0] = fp.bytes_be_to_limbs(pk_x)
    # G2 wire order is x_c1 || x_c0 — plane 1 is c0, plane 2 is c1
    block[1] = fp.bytes_be_to_limbs(np.ascontiguousarray(sg_x[:, 48:]))
    block[2] = fp.bytes_be_to_limbs(np.ascontiguousarray(sg_x[:, :48]))
    if msgs is None:
        block[3:] = 0
    else:
        msg_bytes = [as_bytes(m) for m in msgs]
        if pad:
            msg_bytes = msg_bytes + [b""] * pad
        u00, u01, u10, u11 = htc.hash_to_field_limbs(msg_bytes, _dst())
        block[3], block[4], block[5], block[6] = u00, u01, u10, u11
    return pre_ok, block, flags


# ------------------------------------------------------------ device pieces
#
# Host-composed jitted pipeline. Each piece is compiled once per bucket
# shape and shared by the single-verify, aggregate and mesh paths.


@jax.jit
def _jit_decompress(block, flags):
    """-> (ok_pk, ok_sig, pk Point coords, sig Point coords) — curve
    membership falls out of the sqrt existence check."""
    from cometbft_tpu.ops.bls12381 import points as pts

    okp, pk = pts.g1_decompress(block[0], flags[0])
    oks, sig = pts.g2_decompress(block[1], block[2], flags[1])
    return okp, oks, tuple(pk), tuple(sig)


@jax.jit
def _jit_subgroup_g1(x, y, z):
    from cometbft_tpu.ops.bls12381 import points as pts

    return pts.in_subgroup(pts.G1Field, pts.Point(x, y, z))


@jax.jit
def _jit_subgroup_g2(p):
    from cometbft_tpu.ops.bls12381 import points as pts

    return pts.in_subgroup(pts.G2Field, pts.Point(*p))


@jax.jit
def _jit_hash_msgs(u00, u01, u10, u11):
    """Raw hash_to_field limb planes -> G2 points (projective), then
    affine for the Miller input."""
    from cometbft_tpu.ops.bls12381 import fp
    from cometbft_tpu.ops.bls12381 import htc
    from cometbft_tpu.ops.bls12381 import points as pts
    from cometbft_tpu.ops.bls12381.fp2 import Fp2

    u0 = Fp2(fp.to_mont(u00), fp.to_mont(u01))
    u1 = Fp2(fp.to_mont(u10), fp.to_mont(u11))
    h = htc.map_to_g2(u0, u1)
    hx, hy, _hid = pts.to_affine(pts.G2Field, h)
    return tuple(hx), tuple(hy)


@jax.jit
def _jit_miller(px, py, qxa, qxb, qya, qyb):
    from cometbft_tpu.ops.bls12381 import pairing
    from cometbft_tpu.ops.bls12381.fp2 import Fp2

    return pairing.miller_loop(px, py, Fp2(qxa, qxb), Fp2(qya, qyb))


@jax.jit
def _jit_pair_halves(f):
    """(2B,) Miller lanes -> per-lane product of halves (B,)."""
    from cometbft_tpu.ops.bls12381 import tower

    lo = jax.tree_util.tree_map(lambda a: a[..., : a.shape[-1] // 2], f)
    hi = jax.tree_util.tree_map(lambda a: a[..., a.shape[-1] // 2:], f)
    return tower.f12_mul(lo, hi)


@jax.jit
def _jit_eq_one(f):
    from cometbft_tpu.ops.bls12381 import tower

    return tower.f12_eq_one(f)


@jax.jit
def _jit_mask_header(mask, pad, block, flags, expected):
    """Final per-lane mask (padding lanes forced valid so the all-ok
    reduction mirrors the identity-padding of the other kernels) plus
    the reduced-fetch header/payload pair (shared protocol)."""
    mask = mask | (pad != 0)
    allok = mask.all()
    chk = EK._device_checksum_expr((block, flags))
    ok = chk == expected.astype(jnp.uint32)
    payload = jnp.concatenate([mask, ~mask, ok[None]])
    tok = chk ^ jnp.where(allok & ok, EK.OK_MAGIC, EK._BAD_MAGIC)
    return jnp.stack([tok, ~tok]), payload


def _affine_points(block_dev, flags_dev):
    """Shared front half: decompress + subgroup-validate + hash msgs.
    Returns (eligible (B,), pk affine coords, sig affine coords,
    H(m) affine coords) — all device-resident."""
    okp, oks, pk, sig = _jit_decompress(block_dev, flags_dev)
    sub1 = _jit_subgroup_g1(*pk)
    sub2 = _jit_subgroup_g2(sig)
    hx, hy = _jit_hash_msgs(block_dev[3], block_dev[4],
                            block_dev[5], block_dev[6])
    eligible = okp & oks & sub1 & sub2
    return eligible, pk, sig, (hx, hy)


def _concat_lanes(arrs):
    return jnp.concatenate(arrs, axis=-1)


def _verify_device(block_dev, flags_dev, expected):
    """The full single-verify pipeline -> (header, payload) devices."""
    from cometbft_tpu.ops.bls12381 import pairing
    from cometbft_tpu.ops.bls12381.fp2 import Fp2

    b = block_dev.shape[-1]
    eligible, pk, sig, (hx, hy) = _affine_points(block_dev, flags_dev)
    ng1x, ng1y = _neg_g1_coords(b)
    # one 2B-wide Miller loop: lanes [0, B) = e(-g1, sig),
    # lanes [B, 2B) = e(pk, H(m))
    px = _concat_lanes([ng1x, pk[0]])
    py = _concat_lanes([ng1y, pk[1]])
    qxa = _concat_lanes([sig[0].a, jnp.asarray(hx[0])])
    qxb = _concat_lanes([sig[0].b, hx[1]])
    qya = _concat_lanes([sig[1].a, hy[0]])
    qyb = _concat_lanes([sig[1].b, hy[1]])
    f = _jit_miller(px, py, qxa, qxb, qya, qyb)
    f = _jit_pair_halves(f)
    e = pairing.final_exp_composed(f)
    mask = _jit_eq_one(e) & eligible
    return _jit_mask_header(mask, flags_dev[2], block_dev, flags_dev,
                            expected)


# ------------------------------------------------------- batched single-verify


def verify_batch_async(pubs, msgs, sigs, cache=None,
                       recheck_groups=None):
    """Stage + dispatch without blocking (mirror of
    sr25519_kernel.verify_batch_async): returns a thunk with
    .device_parts for the shared single-fetch resolver
    (ed25519_kernel.resolve_batches) — a mixed ed25519+sr25519+BLS
    window still pays ONE device round trip. Device faults degrade to
    the exact host oracle under the supervisor/breaker, identically to
    the other schemes."""
    del cache  # BLS has no decompressed-pubkey device cache yet
    n = len(sigs)
    assert len(pubs) == n and len(msgs) == n
    if n == 0:
        empty = lambda: np.zeros(0, dtype=bool)  # noqa: E731
        empty.device_parts = lambda: (
            None, 0, np.zeros(0, bool), np.zeros(0, bool), ([], [], []),
            (oracle_verify, SCHEME, None), None)
        return empty

    rows = (list(pubs), list(msgs), list(sigs))
    info = (oracle_verify, SCHEME, recheck_groups)
    sup = _dispatch.supervisor("device")
    b = bucket_size(n)

    staged = None
    stage_counted = False
    if _dispatch.device_allowed():
        try:
            with _trace.span("bls12381.stage", cat="stage", sig_rows=n,
                             lanes=b, hash_rung=EK._staging_rung()):
                stage_counted = True
                staged = stage_batch_bls(pubs, msgs, sigs, b)
        except Exception as exc:  # noqa: BLE001 - staging died: host rung
            sup.record_op_failure(exc)
    if staged is None:
        with _trace.span("bls12381.host_precheck", cat="stage",
                         sig_rows=0 if stage_counted else n):
            pre_ok, _, _ = _structural_check(pubs, sigs, n)
        return EK.make_host_thunk(n, pre_ok, rows, info)
    pre_ok, block, flags = staged
    expected = np.uint32(EK._host_checksum(block, flags))

    def _transfer_and_dispatch():
        from cometbft_tpu.libs import chaos

        chaos.fire("bls12381.dispatch")
        with _trace.span("bls12381.h2d", cat="transfer", lanes=b) as sp:
            t0 = _time.perf_counter()
            block_dev = jnp.asarray(block)
            flags_dev = jnp.asarray(flags)
            jax.block_until_ready((block_dev, flags_dev))
            nbytes = block.nbytes + flags.nbytes
            _linkmodel.tunnel().observe_transfer(
                nbytes, _time.perf_counter() - t0)
            sp.add_bytes(tx=nbytes)
        try:
            from cometbft_tpu.ops import residency as _residency

            _residency.record_send("full", nbytes, sigs=n)
        except Exception:  # noqa: BLE001 - accounting never breaks verify
            pass
        with _trace.span("bls12381.dispatch", cat="compute", lanes=b,
                         device=EK.default_device_index()):
            with KERNEL_DISPATCH_LOCK:
                parts = _verify_device(
                    block_dev, flags_dev, np.uint32(expected))
        EK._count_device_batch(SCHEME, b)
        return parts

    return EK.supervised_device_thunk(
        SCHEME, sup, _transfer_and_dispatch, "bls12381.fetch",
        n, pre_ok, np.ones(n, dtype=bool), rows, info, expected=expected)


def verify_batch(pubs, msgs, sigs, cache=None):
    """Batched single-verify with a per-signature mask."""
    if len(sigs) == 0:
        return True, []
    mask = verify_batch_async(pubs, msgs, sigs, cache=cache)()
    return bool(mask.all()), mask.tolist()


# ------------------------------------------------------------ aggregate path


def aggregate_verify(pubs, msgs, sigs) -> bool:
    """The one-pairing-product commit check over per-vote rows: every
    signature subgroup-validated and summed, pubkeys aggregated per
    distinct sign-bytes, D+1 Miller lanes, ONE final exponentiation —
    commit verify cost ~independent of committee size. Device path when
    the ladder allows it; the exact oracle otherwise (bit-consistent
    semantics either way, tested on every rung)."""
    n = len(sigs)
    if n == 0 or len(pubs) != n or len(msgs) != n:
        return False
    from cometbft_tpu.crypto import batch as crypto_batch

    if (crypto_batch.resolve_backend() != "tpu"
            or not _dispatch.device_allowed()):
        return _oracle_aggregate(pubs, msgs, sigs)
    sup = _dispatch.supervisor("device")
    try:
        return sup.run(lambda: _aggregate_device(pubs, msgs, sigs))
    except Exception:  # noqa: BLE001 - device fault: exact host oracle
        EK._count_fallback(SCHEME, n)
        return _oracle_aggregate(pubs, msgs, sigs)


def aggregate_signatures(sigs) -> bytes:
    """Sum per-vote G2 signature points into the one 96 B aggregate a
    CommitCertificate carries. Host-side point adds (production runs
    once per commit; the pairing work all lives on the verify side).
    Raises ValueError on undecodable/infinity inputs."""
    return _oracle.bls_aggregate([bytes(s) for s in sigs])


def aggregate_verify_agg(pubs, msgs, agg_sig) -> bool:
    """The certificate-verify entry: the same one-pairing-product check
    as aggregate_verify, but the G2 side arrives ALREADY aggregated (a
    CommitCertificate's signature) so the per-vote summing stage is
    skipped. Device path when the ladder allows it; exact oracle
    otherwise — bit-consistent semantics either way."""
    n = len(pubs)
    if n == 0 or len(msgs) != n or len(agg_sig) != SIGNATURE_SIZE:
        return False
    from cometbft_tpu.crypto import batch as crypto_batch

    if (crypto_batch.resolve_backend() != "tpu"
            or not _dispatch.device_allowed()):
        return _oracle_aggregate_agg(pubs, msgs, agg_sig)
    sup = _dispatch.supervisor("device")
    try:
        # every staged lane carries the same aggregate so structural and
        # decompress checks run unchanged; the device path slices lane 0
        # instead of summing
        return sup.run(lambda: _aggregate_device(
            pubs, msgs, [bytes(agg_sig)] * n, presummed_sig=True))
    except Exception:  # noqa: BLE001 - device fault: exact host oracle
        EK._count_fallback(SCHEME, n)
        return _oracle_aggregate_agg(pubs, msgs, agg_sig)


def _oracle_aggregate_agg(pubs, msgs, agg_sig) -> bool:
    from cometbft_tpu.libs.prefixrows import as_bytes

    return _oracle.bls_aggregate_verify(
        [bytes(p) for p in pubs], [as_bytes(m) for m in msgs],
        bytes(agg_sig), _dst())


# validator-set subgroup-check cache: sha256(pk bytes) -> (N,) bool.
# A validator set re-verifies every height; its KeyValidate subgroup
# scans run once per set, not once per commit (the BLS analog of the
# ed25519 decompressed-pubkey cache). Bounded FIFO.
_VALSET_OK: dict[bytes, np.ndarray] = {}
_VALSET_CAP = 64


def _valset_subgroup_ok(pubs, pk_points) -> np.ndarray:
    import hashlib

    key = hashlib.sha256(b"".join(bytes(p) for p in pubs)).digest()
    hit = _VALSET_OK.get(key)
    if hit is not None:
        return hit
    ok = np.asarray(_jit_subgroup_g1(*pk_points))
    if len(_VALSET_OK) >= _VALSET_CAP:
        _VALSET_OK.pop(next(iter(_VALSET_OK)))
    _VALSET_OK[key] = ok
    return ok


def _oracle_aggregate(pubs, msgs, sigs) -> bool:
    from cometbft_tpu.libs.prefixrows import as_bytes

    try:
        agg = _oracle.bls_aggregate([bytes(s) for s in sigs])
    except ValueError:
        return False
    return _oracle.bls_aggregate_verify(
        [bytes(p) for p in pubs], [as_bytes(m) for m in msgs], agg, _dst())


def _aggregate_device(pubs, msgs, sigs, presummed_sig: bool = False) -> bool:
    from cometbft_tpu.libs.prefixrows import as_bytes
    from cometbft_tpu.ops.bls12381 import pairing
    from cometbft_tpu.ops.bls12381 import points as pts

    n = len(sigs)
    b = bucket_size(n)
    with _trace.span("bls12381.stage", cat="stage", sig_rows=n, lanes=b,
                     hash_rung=EK._staging_rung()):
        # distinct-message grouping (PoP: identical vote bytes
        # aggregate); the staged block's u-planes hash the DISTINCT
        # messages padded to their own small bucket
        msg_b = [as_bytes(m) for m in msgs]
        distinct = list(dict.fromkeys(msg_b))
        group_of = {m: i for i, m in enumerate(distinct)}
        lane_group = np.asarray([group_of[m] for m in msg_b],
                                dtype=np.int64)
        pre_ok, block, flags = stage_batch_bls(
            pubs, None, sigs, b)  # u-planes unused on this path
        if not pre_ok.all():
            return False
    chaos_ok = True
    try:
        from cometbft_tpu.libs import chaos

        chaos.fire("bls12381.dispatch")
    except Exception:  # noqa: BLE001 - injected fault: oracle rung
        chaos_ok = False
    if not chaos_ok:
        raise _dispatch.DeviceOpFailed("bls12381 aggregate chaos")
    with _trace.span("bls12381.h2d", cat="transfer", lanes=b) as sp:
        t0 = _time.perf_counter()
        block_dev = jnp.asarray(block)
        flags_dev = jnp.asarray(flags)
        jax.block_until_ready((block_dev, flags_dev))
        _linkmodel.tunnel().observe_transfer(
            block.nbytes, _time.perf_counter() - t0)
        sp.add_bytes(tx=block.nbytes + flags.nbytes)
    try:
        from cometbft_tpu.ops import residency as _residency

        _residency.record_send("full", block.nbytes + flags.nbytes, sigs=n)
    except Exception:  # noqa: BLE001
        pass
    with _trace.span("bls12381.dispatch", cat="compute", lanes=b,
                     device=EK.default_device_index()):
        with KERNEL_DISPATCH_LOCK:
            okp, oks, pk, sig = _jit_decompress(block_dev, flags_dev)
            # per-pubkey KeyValidate subgroup scans are CACHED by
            # validator-set content (a valset re-verifies every height);
            # per-signature subgroup membership is NOT re-checked here —
            # only the SUM enters the pairing equation and the sum is
            # subgroup-checked below (single-verify admission covers
            # individuals), which is what keeps the aggregate path free
            # of n scalar-mul scans per commit
            pk_sub = _valset_subgroup_ok(pubs, pk)
            ok_rows = (np.asarray(okp) & np.asarray(oks))[:n] \
                & pk_sub[:n]
            if not ok_rows.all():
                return False
            # signature sum (padding lanes hold the generator — slice
            # the live lanes and pad with identity instead)
            sig_pts = pts.Point(*sig)
            if presummed_sig:
                # certificate path: every lane holds the SAME
                # pre-aggregated signature — lane 0 IS the sum (summing
                # would scale the point by n)
                sig_sum = jax.tree_util.tree_map(
                    lambda a: a[..., :1], sig_pts)
            else:
                live = jax.tree_util.tree_map(
                    lambda a: a[..., :n], sig_pts)
                sig_sum = pts.sum_tree(pts.G2Field, live, n)
            # per-group pubkey sums (group masks padded to the bucket)
            pk_pts = pts.Point(*pk)
            pk_sums = []
            for gi in range(len(distinct)):
                sel_np = np.zeros(b, dtype=bool)
                sel_np[:n] = lane_group == gi
                sel = jnp.asarray(sel_np)
                ident = pts.identity_like(pts.G1Field, pk_pts.y)
                masked = jax.tree_util.tree_map(
                    lambda a, i: jnp.where(sel[None, :], a, i),
                    pk_pts, ident)
                pk_sums.append(pts.sum_tree(pts.G1Field, masked, n))
            # hash the distinct messages (their own small bucket)
            from cometbft_tpu.ops.bls12381 import htc

            d = len(distinct)
            db = bucket_size(d)
            u00, u01, u10, u11 = htc.hash_to_field_limbs(
                distinct + [b""] * (db - d), _dst())
            hx, hy = _jit_hash_msgs(
                jnp.asarray(u00), jnp.asarray(u01),
                jnp.asarray(u10), jnp.asarray(u11))
            # reject cancelled pubkey groups / infinity signature sum
            # (oracle semantics) and assemble the D+1 Miller lanes
            if not bool(np.asarray(_jit_subgroup_g2(tuple(sig_sum)))[0]):
                return False
            sig_aff = pts.to_affine(pts.G2Field, sig_sum)
            if bool(np.asarray(sig_aff[2])[0]):
                return False
            pk_affs = [pts.to_affine(pts.G1Field, s) for s in pk_sums]
            if any(bool(np.asarray(a[2])[0]) for a in pk_affs):
                return False
            mb = bucket_size(d + 1)
            ng1x, ng1y = _neg_g1_coords(1)
            px = _concat_lanes(
                [a[0] for a in pk_affs] + [ng1x]
                + [ng1x] * (mb - d - 1))
            py = _concat_lanes(
                [a[1] for a in pk_affs] + [ng1y]
                + [ng1y] * (mb - d - 1))
            qxa = _concat_lanes(
                [hx[0][:, gi:gi + 1] for gi in range(d)]
                + [sig_aff[0].a]
                + [sig_aff[0].a] * (mb - d - 1))
            qxb = _concat_lanes(
                [hx[1][:, gi:gi + 1] for gi in range(d)]
                + [sig_aff[0].b] + [sig_aff[0].b] * (mb - d - 1))
            qya = _concat_lanes(
                [hy[0][:, gi:gi + 1] for gi in range(d)]
                + [sig_aff[1].a] + [sig_aff[1].a] * (mb - d - 1))
            qyb = _concat_lanes(
                [hy[1][:, gi:gi + 1] for gi in range(d)]
                + [sig_aff[1].b] + [sig_aff[1].b] * (mb - d - 1))
            f = _jit_miller(px, py, qxa, qxb, qya, qyb)
            # mask the pad lanes to one, multiply down, one final exp
            pad_mask = np.zeros(mb, dtype=bool)
            pad_mask[d + 1:] = True
            from cometbft_tpu.ops.bls12381 import tower

            f = tower.f12_select(
                jnp.asarray(pad_mask),
                tower.f12_one((_oracle_nlimbs(), mb)), f)
            f = pairing.product_lanes(f)
            e = pairing.final_exp_composed(f)
            ok = bool(np.asarray(_jit_eq_one(e))[0])
    EK._count_device_batch(SCHEME, b)
    return ok


def _oracle_nlimbs() -> int:
    from cometbft_tpu.ops.bls12381 import fp

    return fp.NLIMBS


# ----------------------------------------------------------- mesh shard seam


def mesh_shard_verify(chip_device, pubs, msgs, sigs):
    """One mesh chip's BLS shard (parallel/mesh.py ops["shard_verify"]):
    stage host-side, place the block on the chip, run the shared pieces,
    fetch the mask. Returns (mask (n,), eligible (n,))."""
    n = len(sigs)
    b = bucket_size(n)
    pre_ok, block, flags = stage_batch_bls(pubs, msgs, sigs, b)
    expected = np.uint32(EK._host_checksum(block, flags))

    def _round() -> np.ndarray:
        t0 = _time.perf_counter()
        block_dev = jax.device_put(block, chip_device)
        flags_dev = jax.device_put(flags, chip_device)
        jax.block_until_ready((block_dev, flags_dev))
        _linkmodel.tunnel().observe_transfer(
            block.nbytes + flags.nbytes, _time.perf_counter() - t0)
        with KERNEL_DISPATCH_LOCK:
            _header, payload = _verify_device(
                block_dev, flags_dev, expected)
        return np.asarray(payload)

    # same transfer-integrity contract as the single-chip resolver
    # (ed25519_kernel.decode_payload): checksum + mask/echo complement,
    # one fresh-transfer retry, then the shard FAILS so the mesh
    # redispatches it across surviving fault domains — a flipped bit in
    # the tunnel must never become an accepted signature
    for _attempt in range(2):
        payload_np = _round()
        mask = payload_np[:b]
        echo = payload_np[b:2 * b]
        chk_ok = bool(payload_np[2 * b])
        if chk_ok and bool((mask != echo).all()):
            return mask[:n] & pre_ok, pre_ok.copy()
        EK._count_integrity(
            "transfer_checksum_mismatch" if not chk_ok
            else "mask_echo_mismatch")
    raise _dispatch.DeviceOpFailed(
        "bls12381 mesh shard transfer integrity check failed twice")
