"""Device-resident validator sets — the reduced-send wire protocol.

PR 5 shrank the FETCH side to 8 B/batch; this module is the SEND-side
twin. The measured ceiling since r04 is the host<->device wire (the dev
box tunnel runs ~22 MB/s, ~89 ms RTT), and the dominant recurring send
is key material that barely changes: the same validator set re-verifies
every height, yet the digest-keyed PubKeyCache re-uploads its whole
decompressed coordinate table whenever the exact unique-key
concatenation of a batch changes — which under the scheduler's
continuous batching (mempool riders coalesced into consensus flushes)
is nearly every flush. The FPGA verification-engine literature
(PAPERS.md, arXiv:2112.02229) makes the same move this module does:
keep the slowly-changing key material resident on the accelerator and
stream only the per-item deltas.

Design:

  KeyTable     one per (scheme, placement key): a fixed-capacity
               (20, cap) x 4 coordinate table resident on ONE device,
               plus a host-side key->row map. Rows are CONTENT-keyed
               (exact pubkey bytes), so a row can never serve stale
               coordinates — correctness never depends on the epoch
               bookkeeping below.
  indexed send a batch whose keys are all resident ships a 2-byte
               uint16 row index per lane instead of a 32-byte key (or a
               320-byte decompressed-coordinate row); the device
               gathers per-lane A-coordinates from the table with no
               host round trip.
  delta update unseen keys (validator-set churn, mempool riders) are
               decompressed host-side and scattered into free/LRU rows
               — the wire carries only the NEW rows, never the table.
               Scatters are FUNCTIONAL (jnp .at[].set returns a fresh
               array): an in-flight batch keeps gathering from its own
               immutable snapshot, so concurrent churn can never
               corrupt a dispatched batch.
  epoch pins   validation.py announces the active validator set(s)
               (keyed by ValidatorSet.hash()); tables pin those rows so
               rider churn can never evict the hot set, and a new epoch
               re-pins by shipping only the evict/insert delta. An
               announced hash whose key content changed (set-hash
               mismatch) drops the pin and re-uploads the set in full —
               counted, and never a wrong verdict, because rows were
               content-keyed all along.
  replicas     placement keys carry the chip index on the multi-chip
               mesh ("dev3"), so each fault domain holds its own
               replica; invalidate_device() drops exactly one chip's
               replicas (mesh readmission re-seeds only the healed
               chip).
  degradation  anything the table cannot serve (capacity overflow, a
               poisoned delta upload, the module disabled) returns the
               batch to the classic full-key path
               (ed25519_kernel._stage_gather's digest cache) — the
               reduced-send protocol is an optimization layer, never a
               correctness dependency.

Send accounting: every host->device staging transfer is recorded under
a path label — "indexed" (steady state: index vector + staged r/s/k
words), "delta" (churn row uploads), "full" (full-key fallback:
coordinate-table uploads + 4-byte indices + staged words) — mirrored to
the crypto_verify_send_bytes{path} Prometheus counters and the
crypto_health staging.wire section, next to PR 5's fetch-side
verify_fetch_bytes{path}.
"""

from __future__ import annotations

import functools
import hashlib
import threading
import time as _time

import numpy as np

# ---------------------------------------------------------------- config

_cfg = {
    "enabled": True,
    # per-table row capacity: bounds device memory (320 B/row) and the
    # uint16 index width. One row is reserved for the identity padding
    # encoding.
    "rows": 16384,
}

_cfg_lock = threading.Lock()


def configure(enabled: bool | None = None, rows: int | None = None) -> None:
    """Apply config.crypto wire knobs (wire_indexed_sends,
    wire_table_rows). A capacity change applies to tables built after
    the call; live tables keep their allocation (a process-lifetime
    device buffer is not resized under in-flight batches)."""
    with _cfg_lock:
        if enabled is not None:
            _cfg["enabled"] = bool(enabled)
        if rows is not None:
            if not 64 <= rows <= 65536:
                raise ValueError("wire_table_rows must be in [64, 65536]")
            _cfg["rows"] = int(rows)


def enabled() -> bool:
    return _cfg["enabled"]


# ------------------------------------------------------- send accounting

_send_lock = threading.Lock()
_PATHS = ("indexed", "full", "delta")
_send_stats: dict[str, dict] = {
    p: {"sends": 0, "bytes": 0, "sigs": 0} for p in _PATHS
}


def record_send(path: str, nbytes: int, sigs: int = 0) -> None:
    """Account a host->device verify staging transfer under its send
    path. `sigs` counts live signature rows ONLY for the batch-carrying
    transfer (the staged-words + index send), so bytes/sig divides by
    real rows, not padding or table maintenance."""
    with _send_lock:
        s = _send_stats[path]
        s["sends"] += 1
        s["bytes"] += nbytes
        s["sigs"] += sigs
    try:
        from cometbft_tpu.libs import metrics as _metrics

        cm = _metrics.crypto_metrics()
        cm.verify_sends.labels(path).inc()
        cm.verify_send_bytes.labels(path).inc(nbytes)
    except Exception:  # noqa: BLE001 - metrics must never break staging
        pass


def send_stats() -> dict:
    """The crypto_health staging `wire` subsection and the scheduler's
    live bytes-per-sig planning source. steady_state_bytes_per_sig is
    the indexed path's measured rate — what one more signature costs on
    the wire once the validator set is resident."""
    with _send_lock:
        out = {p: dict(v) for p, v in _send_stats.items()}
    idx = out["indexed"]
    out["steady_state_bytes_per_sig"] = (
        round(idx["bytes"] / idx["sigs"], 2) if idx["sigs"] else None)
    full = out["full"]
    out["full_path_bytes_per_sig"] = (
        round(full["bytes"] / full["sigs"], 2) if full["sigs"] else None)
    return out


def measured_bytes_per_sig() -> float | None:
    """Live wire cost of one signature on the dominant send path: the
    indexed rate when the reduced-send path carries traffic, else the
    full-key rate. None until any batch has been sent."""
    stats = send_stats()
    return (stats["steady_state_bytes_per_sig"]
            or stats["full_path_bytes_per_sig"])


def reset_send_stats() -> None:
    with _send_lock:
        for p in _PATHS:
            _send_stats[p] = {"sends": 0, "bytes": 0, "sigs": 0}


# ------------------------------------------------------- device programs


def _jax():
    import jax

    return jax


def _jnp():
    import jax.numpy as jnp

    return jnp


@functools.lru_cache(maxsize=1)
def _init_table_fn():
    jax = _jax()
    jnp = _jnp()

    @functools.partial(jax.jit, static_argnums=(0,))
    def init(cap: int):
        """Fresh (20, cap) x 4 coordinate table plus the (8, cap)
        compressed-encoding plane, built ON DEVICE (no wire bytes):
        every row the extended identity (X=0, Y=1, Z=1, T=0) — the
        padding encoding for BOTH schemes (ed25519's y=1 point and the
        ristretto identity decode to the same extended coords). The enc
        plane holds each row's 32 raw key bytes as 8 LE uint32 words
        (identity: y=1 -> word0=1) — the A half of the on-device
        challenge preimage SHA-512(R||A||M), so the device-challenge
        path (ops/challenge.py) never re-ships key bytes it already has
        resident as coordinates."""
        zero = jnp.zeros((20, cap), jnp.int32)
        one = zero.at[0, :].set(1)
        enc = jnp.zeros((8, cap), jnp.uint32).at[0, :].set(1)
        return zero, one, one, zero, enc

    return init


@functools.lru_cache(maxsize=1)
def _scatter_fn():
    jax = _jax()
    jnp = _jnp()

    @jax.jit
    def scatter(tx, ty, tz, tt, te, idx, vals, enc):
        i = idx.astype(jnp.int32)
        return (tx.at[:, i].set(vals[0]), ty.at[:, i].set(vals[1]),
                tz.at[:, i].set(vals[2]), tt.at[:, i].set(vals[3]),
                te.at[:, i].set(enc))

    return scatter


@functools.lru_cache(maxsize=1)
def _gather_enc_fn():
    jax = _jax()
    jnp = _jnp()

    @jax.jit
    def gather(te, idx):
        return jnp.take(te, idx.astype(jnp.int32), axis=1)

    return gather


class _NoRoom(Exception):
    """The table cannot serve this batch/set — caller degrades to the
    full-key path."""


# -------------------------------------------------------------- KeyTable


class KeyTable:
    """One device-resident validator table (see module docstring). All
    public methods are serialized on the table lock; device arrays are
    replaced functionally, so readers that captured a snapshot stay
    consistent."""

    def __init__(self, scheme: str, cache, rows: int, put_key: str = "",
                 device=None):
        self.scheme = scheme
        self.cache = cache  # the scheme's PubKeyCache (host decompressor)
        self.cap = int(rows)
        self.id_row = self.cap - 1  # identity encoding for padding lanes
        self.put_key = put_key
        self.device = device
        self._lock = threading.RLock()
        self._rows: dict[bytes, int] = {}  # key -> row (dict order = LRU)
        self._ok: dict[bytes, bool] = {}
        self._free: list[int] = list(range(self.cap - 1))
        # pinned epoch sets: set_hash -> (content_digest, tuple(keys));
        # bounded — interleaved valsets (light-client bisection across
        # churn epochs) must not thrash each other's pins
        self._pinned_sets: dict[bytes, tuple[bytes, tuple]] = {}
        self._pin_count: dict[bytes, int] = {}  # key -> pinning sets
        self._dev: tuple | None = None
        self.counters = {
            "indexed_batches": 0, "delta_updates": 0, "delta_rows": 0,
            "full_set_uploads": 0, "evictions": 0, "hash_mismatches": 0,
            "checksum_retries": 0,
        }

    _MAX_PINNED_SETS = 4

    # ------------------------------------------------------------ device

    def _build(self):
        if self._dev is None:
            jax = _jax()
            init = _init_table_fn()
            if self.device is not None:
                with jax.default_device(self.device):
                    self._dev = tuple(init(self.cap))
            else:
                self._dev = tuple(init(self.cap))
        return self._dev

    def _put(self, arr: np.ndarray):
        jax = _jax()
        return (jax.device_put(arr) if self.device is None
                else jax.device_put(arr, self.device))

    # ---------------------------------------------------------- eviction

    def _evict_one(self, protect: frozenset = frozenset()) -> int:
        """Free the least-recently-used unpinned row outside `protect`
        (the current batch's resident keys — room-making for a delta
        must never evict a row the very batch is about to index).
        Raises _NoRoom when nothing is evictable."""
        for key in self._rows:  # dict order: oldest first
            if self._pin_count.get(key, 0) == 0 and key not in protect:
                row = self._rows.pop(key)
                self._ok.pop(key, None)
                self.counters["evictions"] += 1
                return row
        raise _NoRoom("all resident rows pinned or staged by this batch")

    def _alloc_rows(self, n: int,
                    protect: frozenset = frozenset()) -> list[int]:
        """Take n free rows (evicting LRU unpinned keys as needed). On
        _NoRoom the partially-allocated rows return to the free list —
        an aborted allocation must not leak capacity."""
        out: list[int] = []
        try:
            while len(out) < n:
                if self._free:
                    out.append(self._free.pop())
                else:
                    out.append(self._evict_one(protect))
        except _NoRoom:
            self._free.extend(out)
            raise
        return out

    # ------------------------------------------------------------ deltas

    def _insert_keys(self, missing: list[bytes], path: str = "delta",
                     protect: frozenset = frozenset()) -> int:
        """Decompress + scatter `missing` keys into free/LRU rows.
        Returns the wire bytes shipped. The delta upload is integrity-
        checked like the full-table path (a corrupted row would poison
        one validator until eviction): checksum mismatch retries once
        with a fresh transfer, then raises — the caller degrades to the
        full-key path rather than caching a poisoned row."""
        if not missing:
            return 0
        if len(missing) > self.cap - 1:
            raise _NoRoom(f"{len(missing)} keys exceed table capacity")
        ok, coords = self.cache.lookup_or_decompress(missing)
        rows = self._alloc_rows(len(missing), protect=protect)
        try:
            return self._upload_rows(missing, ok, coords, rows, path)
        except Exception:
            # a failed upload (double checksum mismatch, device death)
            # must hand its allocated rows back: repeated failures would
            # otherwise permanently drain the table's capacity
            self._free.extend(rows)
            raise

    def _upload_rows(self, missing, ok, coords, rows, path) -> int:
        from cometbft_tpu.libs import linkmodel as _linkmodel
        from cometbft_tpu.libs import trace as _trace
        from cometbft_tpu.ops import ed25519_kernel as EK

        db = EK.bucket_size(len(missing))
        # batch-minor (4, 20, db) upload block, identity-padded; padding
        # scatters rewrite the identity row with identity coords — a
        # deliberate idempotent no-op that keeps the scatter on the
        # shared bucket ladder (bounded compiled shapes)
        vals = np.zeros((4, 20, db), dtype=np.int32)
        vals[1, 0, :] = 1  # Y = 1
        vals[2, 0, :] = 1  # Z = 1
        vals[:, :, :len(missing)] = coords.transpose(1, 2, 0)
        # the compressed-encoding plane rides the same delta: the rows'
        # raw 32 key bytes as 8 LE words (identity word0=1 padding)
        enc = np.zeros((8, db), dtype=np.uint32)
        enc[0, :] = 1
        enc[:, :len(missing)] = np.frombuffer(
            b"".join(missing), dtype=np.uint8).reshape(-1, 32).view("<u4").T
        idx = np.full(db, self.id_row, dtype=np.int32)
        idx[:len(missing)] = rows
        expected = EK._host_checksum(vals, enc)
        dev = self._build()
        scatter = _scatter_fn()
        for attempt in (1, 2):
            t0 = _time.perf_counter()
            vals_dev = self._put(vals)
            enc_dev = self._put(enc)
            idx_dev = self._put(idx)
            _jax().block_until_ready((vals_dev, enc_dev, idx_dev))
            nbytes = vals.nbytes + enc.nbytes + idx.nbytes
            _linkmodel.tunnel().observe_transfer(
                nbytes, _time.perf_counter() - t0)
            _trace.add_bytes(tx=nbytes)
            got = int(np.asarray(EK._device_checksum((vals_dev, enc_dev))))
            if got == expected:
                break
            self.counters["checksum_retries"] += 1
            EK._count_integrity("transfer_checksum_mismatch")
            if attempt == 2:
                raise RuntimeError(
                    "validator-table delta upload corrupted twice; "
                    "refusing to cache a poisoned row")
        self._dev = tuple(scatter(*dev, idx_dev, vals_dev, enc_dev))
        for i, key in enumerate(missing):
            self._rows[key] = rows[i]
            self._ok[key] = bool(ok[i])
        self.counters["delta_updates"] += 1
        self.counters["delta_rows"] += len(missing)
        nbytes = vals.nbytes + enc.nbytes + idx.nbytes
        record_send(path, nbytes)
        return nbytes

    # --------------------------------------------------------- epoch pins

    def _sync_sets(self, announced: dict[bytes, tuple[bytes, tuple]]) -> None:
        """Reconcile the table's pinned sets with the announced epoch
        sets: new hashes delta-insert and pin, content mismatches under
        a known hash re-upload the set in full (counted), vanished
        hashes unpin (rows stay resident as plain LRU entries)."""
        for h in list(self._pinned_sets):
            if h not in announced:
                self._unpin(h)
        for h, (digest, keys) in announced.items():
            cur = self._pinned_sets.get(h)
            if cur is not None:
                if cur[0] == digest:
                    continue
                # set-hash mismatch: the epoch key no longer names the
                # content we pinned. Rows are content-keyed so no wrong
                # verdict is possible — but the pin bookkeeping is void:
                # drop it and re-upload the set in full.
                self.counters["hash_mismatches"] += 1
                self._unpin(h)
                missing = [k for k in dict.fromkeys(keys)
                           if k not in self._rows]
                self._insert_keys(missing, path="full")
                self.counters["full_set_uploads"] += 1
                self._pin(h, digest, keys)
                continue
            uniq = list(dict.fromkeys(keys))
            if len(uniq) > self.cap - 1:
                continue  # set larger than the table: serve unpinned
            while (len(self._pinned_sets) >= self._MAX_PINNED_SETS
                   or sum(len(v[1]) for v in self._pinned_sets.values())
                   + len(uniq) > self.cap - 1):
                if not self._pinned_sets:
                    break
                self._unpin(next(iter(self._pinned_sets)))
            missing = [k for k in uniq if k not in self._rows]
            self._insert_keys(missing)
            self._pin(h, digest, uniq)

    def _pin(self, set_hash: bytes, digest: bytes, keys) -> None:
        keys = tuple(dict.fromkeys(keys))
        self._pinned_sets[set_hash] = (digest, keys)
        for k in keys:
            self._pin_count[k] = self._pin_count.get(k, 0) + 1

    def _unpin(self, set_hash: bytes) -> None:
        _, keys = self._pinned_sets.pop(set_hash)
        for k in keys:
            c = self._pin_count.get(k, 0) - 1
            if c <= 0:
                self._pin_count.pop(k, None)
            else:
                self._pin_count[k] = c

    # ------------------------------------------------------------ staging

    def stage(self, pubs: list[bytes], bucket: int,
              announced: dict | None = None, want_enc: bool = False):
        """The indexed send: (ok_a (N,), (ax, ay, az, at) device arrays
        (20, bucket), index-vector wire bytes) — plus, with want_enc,
        the (8, bucket) gathered compressed-encoding words between the
        coords and the byte count (the device-challenge path's A rows).
        Unseen keys delta-insert first (counted separately); raises
        _NoRoom when the batch cannot fit, which returns the caller to
        the full-key path."""
        from cometbft_tpu.libs import linkmodel as _linkmodel
        from cometbft_tpu.libs import trace as _trace
        from cometbft_tpu.ops import ed25519_kernel as EK

        with self._lock:
            if announced:
                self._sync_sets(announced)
            uniq = dict.fromkeys(pubs)
            if len(uniq) > self.cap - 1:
                raise _NoRoom(f"{len(uniq)} unique keys exceed table")
            missing = [k for k in uniq if k not in self._rows]
            # LRU-touch the batch's RESIDENT keys, and PROTECT them from
            # room-making eviction: the delta insert must never evict a
            # row this very batch is about to index (a crowded table
            # degrades via _NoRoom to the full-key path instead)
            for k in uniq:
                row = self._rows.pop(k, None)
                if row is not None:
                    self._rows[k] = row
            self._insert_keys(missing, protect=frozenset(uniq))
            idx = np.full(bucket, self.id_row, dtype=np.uint16)
            idx[:len(pubs)] = [self._rows[p] for p in pubs]
            ok_a = np.fromiter((self._ok[p] for p in pubs), dtype=bool,
                               count=len(pubs))
            dev = self._build()
            self.counters["indexed_batches"] += 1
        # the 2 B/lane index vector is the steady-state send — also the
        # tunnel model's h2d RTT probe (blocked before t1 so async
        # dispatch can't record enqueue time; same contract as the full
        # path's 4-byte index upload)
        t0 = _time.perf_counter()
        idx_dev = self._put(idx)
        _jax().block_until_ready(idx_dev)
        _linkmodel.tunnel().observe_transfer(
            idx.nbytes, _time.perf_counter() - t0)
        _trace.add_bytes(tx=idx.nbytes)
        coords = EK._gather_coords(dev[:4], idx_dev)
        if want_enc:
            return ok_a, coords, _gather_enc_fn()(dev[4], idx_dev), idx.nbytes
        return ok_a, coords, idx.nbytes

    def stats(self) -> dict:
        with self._lock:
            return dict(
                self.counters, rows=len(self._rows), capacity=self.cap,
                pinned_sets=len(self._pinned_sets),
                pinned_rows=len(self._pin_count),
                free_rows=len(self._free),
            )


# ------------------------------------------------------ process registry

_reg_lock = threading.Lock()
_tables: dict[tuple[str, str], KeyTable] = {}
# announced epoch sets per scheme: set_hash -> (content_digest, keys),
# bounded (interleaved valsets across light-client churn epochs)
_announced: dict[str, dict[bytes, tuple[bytes, tuple]]] = {}
_MAX_ANNOUNCED = 4
_last_announced_hash: bytes | None = None


def announce_validator_set(vals) -> None:
    """Register the active validator set for epoch-keyed residency
    (validation.py calls this on every commit verification). Never
    raises — residency is an optimization layer. A per-object stamp
    makes repeat announcements of the same ValidatorSet object free
    (ValidatorSet.hash() is an uncached O(N) merkle root); a set
    mutated after stamping just pins one epoch late, which costs delta
    bytes, never correctness (rows are content-keyed)."""
    global _last_announced_hash
    try:
        if getattr(vals, "_wire_announced", False):
            return
        h = vals.hash()
        if h == _last_announced_hash:
            return
        by_scheme: dict[str, list[bytes]] = {}
        for v in vals.validators:
            by_scheme.setdefault(v.pub_key.type_(), []).append(
                v.pub_key.bytes_())
        with _reg_lock:
            for scheme, keys in by_scheme.items():
                if scheme not in ("ed25519", "sr25519"):
                    continue
                sets = _announced.setdefault(scheme, {})
                if h in sets:
                    continue
                digest = hashlib.sha256(b"".join(keys)).digest()
                while len(sets) >= _MAX_ANNOUNCED:
                    sets.pop(next(iter(sets)))
                sets[h] = (digest, tuple(keys))
            _last_announced_hash = h
        try:
            vals._wire_announced = True
        except Exception:  # noqa: BLE001 - slotted/frozen sets re-hash
            pass
    except Exception:  # noqa: BLE001 - residency must never break verify
        pass


def register_set(scheme: str, set_hash: bytes, keys: list[bytes]) -> None:
    """Direct epoch registration (tests, callers that know the set hash
    without a ValidatorSet object)."""
    global _last_announced_hash
    with _reg_lock:
        sets = _announced.setdefault(scheme, {})
        digest = hashlib.sha256(b"".join(keys)).digest()
        sets.pop(set_hash, None)
        while len(sets) >= _MAX_ANNOUNCED:
            sets.pop(next(iter(sets)))
        sets[set_hash] = (digest, tuple(keys))
        _last_announced_hash = None


def table_for(cache, put_key: str = "", device=None) -> KeyTable | None:
    """The (scheme, placement-key) replica, built lazily. None when the
    cache carries no scheme tag (a custom cache from tests)."""
    scheme = getattr(cache, "scheme", None)
    if scheme is None:
        return None
    with _reg_lock:
        tbl = _tables.get((scheme, put_key))
        if tbl is None:
            tbl = KeyTable(scheme, cache, _cfg["rows"], put_key=put_key,
                           device=device)
            _tables[(scheme, put_key)] = tbl
        return tbl


def stage(cache, pubs: list[bytes], bucket: int, put_key: str = "",
          device=None, want_enc: bool = False):
    """Try the reduced-send indexed path for a batch. Returns
    (ok_a, a_dev, index_bytes) — or (ok_a, a_dev, enc_dev, index_bytes)
    with want_enc — or None when the full-key path must serve (disabled,
    untagged cache, capacity overflow, or a failed delta upload)."""
    if not _cfg["enabled"]:
        return None
    tbl = table_for(cache, put_key=put_key, device=device)
    if tbl is None:
        return None
    scheme = tbl.scheme
    with _reg_lock:
        announced = dict(_announced.get(scheme, {}))
    try:
        return tbl.stage(pubs, bucket, announced=announced,
                         want_enc=want_enc)
    except _NoRoom:
        return None
    except Exception:  # noqa: BLE001 - degraded, never a wrong verdict
        from cometbft_tpu.libs import log as _log

        try:
            _log.default().error(
                "reduced-send residency failed; falling back to the "
                "full-key path", scheme=scheme, put_key=put_key)
        except Exception:  # noqa: BLE001
            pass
        return None


def invalidate_device(index: int) -> int:
    """Drop every replica placed on mesh fault domain `index` (put_key
    "devN"): called on chip readmission so exactly that chip's tables
    re-seed on the next shard — a healed device must not serve arrays
    from before its fault. Returns the number of tables dropped."""
    key = f"dev{index}"
    with _reg_lock:
        drop = [k for k in _tables if k[1] == key]
        for k in drop:
            del _tables[k]
    return len(drop)


def stats() -> dict:
    """The crypto_health staging `wire` subsection: send-path
    accounting plus per-replica table counters."""
    with _reg_lock:
        tables = {f"{s}/{pk}" if pk else s: t.stats()
                  for (s, pk), t in _tables.items()}
    out = send_stats()
    out["enabled"] = _cfg["enabled"]
    out["tables"] = tables
    return out


def reset() -> None:
    """Forget every table, announcement, and send counter (tests)."""
    global _last_announced_hash
    with _reg_lock:
        _tables.clear()
        _announced.clear()
        _last_announced_hash = None
    reset_send_stats()
