"""TimeoutTicker — schedules consensus step timeouts.

Reference: consensus/ticker.go:15-36. One pending timeout at a time; a newer
schedule replaces an older one (timeouts for earlier H/R/S are stale by
construction). Injectable for tests, like the reference's mock ticker.
"""

from __future__ import annotations

import asyncio
from dataclasses import dataclass

from cometbft_tpu.consensus.round_state import RoundStepType


@dataclass(frozen=True)
class TimeoutInfo:
    duration: float
    height: int
    round_: int
    step: RoundStepType

    def __str__(self) -> str:
        return f"{self.duration:.3f}s@{self.height}/{self.round_}/{self.step.name}"


class TimeoutTicker:
    """schedule_timeout() arms (replacing any pending); fired timeouts are
    pushed to out_queue as TimeoutInfo."""

    def __init__(self, out_queue: asyncio.Queue):
        self.out_queue = out_queue
        self._task: asyncio.Task | None = None

    def schedule_timeout(self, ti: TimeoutInfo) -> None:
        if self._task is not None and not self._task.done():
            self._task.cancel()
        self._task = asyncio.get_running_loop().create_task(self._fire(ti))

    async def _fire(self, ti: TimeoutInfo) -> None:
        try:
            await asyncio.sleep(ti.duration)
            await self.out_queue.put(ti)
        except asyncio.CancelledError:
            pass

    def stop(self) -> None:
        if self._task is not None and not self._task.done():
            self._task.cancel()
            self._task = None
