"""ConsensusState — the Tendermint round state machine.

Reference: consensus/state.go. Structure mirrors the reference's transition
graph exactly (SURVEY.md §3.2):

  enterNewRound -> enterPropose -> [complete proposal] -> enterPrevote
  -> [+2/3 prevotes] -> enterPrecommit (lock/unlock rules)
  -> [+2/3 precommits] -> enterCommit -> finalizeCommit -> next height

Concurrency: ONE asyncio task (`_receive_routine`) consumes a queue of
peer/internal messages and timeout events; every transition happens on that
task, so the round state needs no locks (the reference pins everything to
one goroutine for the same reason, state.go:774). Messages are WAL-logged
before processing; EndHeightMessage is fsynced before ApplyBlock
(state.go:1810), making crash-replay exact.

Vote ingestion: serial add_vote by default; with
config.batch_vote_verification the VoteSet's staged/batched path carries
gossip votes to the TPU kernel (SURVEY.md §3.3, the north-star hot path).
"""

from __future__ import annotations

import asyncio
import traceback
from typing import Callable, Optional

from cometbft_tpu.consensus import messages as M
from cometbft_tpu.consensus import timeline
from cometbft_tpu.consensus.config import ConsensusConfig
from cometbft_tpu.consensus.height_vote_set import HeightVoteSet
from cometbft_tpu.consensus.round_state import RoundState, RoundStepType
from cometbft_tpu.consensus.ticker import TimeoutInfo, TimeoutTicker
from cometbft_tpu.consensus.wal import WAL, EndHeightMessage
from cometbft_tpu.libs import fail
from cometbft_tpu.libs import log as cmtlog
from cometbft_tpu.libs import trace
from cometbft_tpu.libs.service import BaseService, TaskRunner
from cometbft_tpu.privval.file_pv import PrivValidator
from cometbft_tpu.state import BlockExecutor, State
from cometbft_tpu.store.blockstore import BlockStore
from cometbft_tpu.types.basic import BlockID, SignedMsgType
from cometbft_tpu.types.block import Block
from cometbft_tpu.types.commit import Commit, ExtendedCommit, ExtendedCommitSig
from cometbft_tpu.types.part_set import PartSet
from cometbft_tpu.types.proposal import Proposal
from cometbft_tpu.types.vote import Vote
from cometbft_tpu.types.vote_set import (
    ErrVoteConflictingVotes,
    ErrVoteInvalidSignature,
    VoteSet,
    commit_to_vote_set,
    extended_commit_to_vote_set,
)
from cometbft_tpu.utils import cmttime

BLOCK_PART_SIZE = 65536


def _vote_key(v: Vote) -> tuple:
    """Identity of a staged vote for batched-path peer attribution."""
    return (v.height, v.round_, int(v.type_), v.validator_index,
            v.block_id.key())


class _TaggedQueue:
    """Adapter: the TimeoutTicker puts bare TimeoutInfo; the state queue
    carries (from_peer, msg) pairs."""

    def __init__(self, inner: asyncio.Queue):
        self._inner = inner

    async def put(self, ti) -> None:
        await self._inner.put((False, ti))


class ConsensusState(BaseService):
    def __init__(
        self,
        config: ConsensusConfig,
        state: State,
        block_exec: BlockExecutor,
        block_store: BlockStore,
        wal: WAL | None = None,
        priv_validator: PrivValidator | None = None,
        event_switch=None,
        logger: cmtlog.Logger | None = None,
        metrics=None,
    ):
        super().__init__("ConsensusState", logger)
        self.config = config
        self.metrics = metrics  # libs.metrics.ConsensusMetrics | None
        self.block_exec = block_exec
        self.block_store = block_store
        self.wal = wal
        self.priv_validator = priv_validator
        self.priv_validator_pub_key = (
            priv_validator.get_pub_key() if priv_validator else None
        )
        self.event_switch = event_switch  # libs.events.EventSwitch (reactor fast path)

        self.rs = RoundState()
        self.state: State | None = None
        # set by _contain_failure when the receive routine dies; surfaced
        # through /health and /status (rpc/core.py)
        self.failed = False

        # One multiplexed queue of (from_peer, msg) — the analog of the
        # reference's select over peerMsgQueue/internalMsgQueue/tockChan.
        self.msg_queue: asyncio.Queue = asyncio.Queue(maxsize=5000)
        self.timeout_queue = _TaggedQueue(self.msg_queue)
        self.timeout_ticker = TimeoutTicker(self.timeout_queue)
        self._tasks = TaskRunner("consensus")
        self._wait_sync = False
        self.n_steps = 0  # transition counter (test instrumentation)

        # Outbound tap: called with every self-produced gossipable message
        # (ProposalMessage / BlockPartMessage / VoteMessage). The consensus
        # reactor (and the in-process test net) subscribes here — the state
        # machine itself never touches sockets (SURVEY §1).
        self.outbound_hook: Optional[Callable] = None

        # injectable decision hooks (reference: state.go:122-124, the seam
        # that makes byzantine tests possible)
        self.decide_proposal: Callable = self._default_decide_proposal
        self.do_prevote: Callable = self._default_do_prevote
        self.set_proposal_fn: Callable = self._default_set_proposal

        # misbehavior tap: (peer_id, reason) -> None, wired to
        # Switch.report_misbehavior by the node. A vote with a forged
        # signature is unforgeable proof the SENDER misbehaves (honest
        # peers only relay verified votes), so consensus reports it here
        # instead of silently dropping it.
        self.misbehavior_hook: Optional[Callable] = None
        # batched-path attribution: staged vote -> staging peer, so a
        # FLUSH_INVALID result can still be pinned on its sender
        self._staged_peer: dict[tuple, str] = {}

        # flight-recorder height timeline (libs/trace.py): one begin()-span
        # per consensus height — propose/flush/commit-verify/ABCI-exec
        # spans and step events hang off it, so a slow height keeps its
        # whole tree in the slow capture ring
        self._height_span = None
        self._height_span_h = 0

        # heightline event ring (consensus/timeline.py): per-height
        # critical-path marks + per-peer vote lag. Every hook is gated on
        # the module _enabled flag, so the disabled consensus path pays
        # one call + one bool test. Node boot labels it with the node id
        # and installs the postmortem context collector.
        self.timeline = timeline.Recorder()

        self.sync_to_state(state)

    # ------------------------------------------------------------ lifecycle

    async def on_start(self) -> None:
        if self.wal is not None:
            await self._catchup_replay()
        self._tasks.spawn(self._receive_routine(), name="cs-receive")
        self._schedule_round_0(self.rs)

    async def on_stop(self) -> None:
        self.timeout_ticker.stop()
        await self._tasks.cancel_all()
        if self.wal is not None:
            self.wal.close()

    # ---------------------------------------------------------- state setup

    def update_to_state(self, state: State) -> None:
        """state.go:1842 updateToState: prepare RoundState for the height
        after state.last_block_height."""
        if self.rs.commit_round > -1 and 0 < self.rs.height != state.last_block_height:
            raise RuntimeError(
                f"updateToState expected state height {self.rs.height}, got {state.last_block_height}"
            )
        if (
            self.state is not None
            and self.state.last_block_height > 0
            and state.last_block_height <= self.state.last_block_height
        ):
            # reference state.go updateToState: a non-advancing state (e.g.
            # the post-handshake re-sync on restart) must not reset the
            # RoundState — it would wipe the reconstructed LastCommit.
            self.logger.debug(
                "ignoring update_to_state; state height not greater",
                new=state.last_block_height, old=self.state.last_block_height,
            )
            # still signal the step: peers depend on an up-to-date view
            # (reference updateToState calls newStep() in this branch)
            if self.event_switch is not None:
                self.event_switch.fire("NewRoundStep", self.rs)
            return
        validators = state.validators
        last_precommits: VoteSet | None = None
        if self.rs.commit_round > -1 and self.rs.votes is not None:
            pcs = self.rs.votes.precommits(self.rs.commit_round)
            if pcs is None or not pcs.has_two_thirds_majority():
                raise RuntimeError("updateToState called with no +2/3 precommits")
            last_precommits = pcs

        height = state.last_block_height + 1
        if height == 1:
            height = state.initial_height

        self.rs = RoundState(
            height=height,
            round_=0,
            step=RoundStepType.NEW_HEIGHT,
            start_time=cmttime.now().add_seconds(self.config.timeout_commit),
            validators=validators.copy() if validators else None,
            votes=HeightVoteSet(
                state.chain_id, height, validators,
                extensions_enabled=state.consensus_params.abci.vote_extensions_enabled(height),
                batch_flush_size=self.config.vote_batch_flush_size,
            ),
            last_commit=last_precommits,
            last_validators=state.last_validators.copy() if state.last_validators else None,
            locked_round=-1,
            valid_round=-1,
            commit_round=-1,
        )
        self.state = state
        self._staged_peer.clear()  # stale attribution dies with the height
        if self.event_switch is not None:
            # announce the height transition (reference updateToState ->
            # newStep -> EventNewRoundStep) so peers learn we moved on
            self.event_switch.fire("NewRoundStep", self.rs)

    def sync_to_state(self, state: State) -> None:
        """Boot-time state sync (NewState / post-handshake): update the
        RoundState and, if the resulting height needs a LastCommit the
        RoundState doesn't carry, reconstruct it from the block store.
        The single entry point for constructor, node handshake, and the
        blocksync handoff."""
        self.update_to_state(state)
        if self.rs.last_commit is None and self.state.last_block_height > 0:
            self._reconstruct_last_commit(self.state)

    def _reconstruct_last_commit(self, state: State) -> None:
        """state.go reconstructLastCommit: on restart, rebuild the LastCommit
        precommit VoteSet from the block store's seen (extended) commit so the
        proposer can build height last_block_height+1."""
        h = state.last_block_height
        ext_enabled = state.consensus_params.abci.vote_extensions_enabled(h)
        if ext_enabled:
            ec = self.block_store.load_block_extended_commit(h)
            if ec is None:
                raise RuntimeError(
                    f"failed to reconstruct last extended commit; commit for height {h} not found"
                )
            votes = extended_commit_to_vote_set(state.chain_id, ec, state.last_validators)
        else:
            sc = self.block_store.load_seen_commit(h)
            if sc is None:
                raise RuntimeError(
                    f"failed to reconstruct last commit; seen commit for height {h} not found"
                )
            votes = commit_to_vote_set(state.chain_id, sc, state.last_validators)
        if not votes.has_two_thirds_majority():
            raise RuntimeError("failed to reconstruct last commit; does not have +2/3 maj")
        self.rs.last_commit = votes

    def _schedule_round_0(self, rs: RoundState) -> None:
        sleep = max(0.0, (rs.start_time.unix_ns() - cmttime.now().unix_ns()) / 1e9)
        self.timeout_ticker.schedule_timeout(
            TimeoutInfo(sleep, rs.height, 0, RoundStepType.NEW_HEIGHT)
        )

    def _schedule_timeout(self, duration: float, height: int, round_: int, step: RoundStepType) -> None:
        self.timeout_ticker.schedule_timeout(TimeoutInfo(duration, height, round_, step))

    # --------------------------------------------------------- public input

    async def add_vote_from_peer(self, vote: Vote, peer_id: str) -> None:
        if timeline.enabled() and vote.height == self.rs.height:
            # arrival lag against the vote's signing timestamp; recorded at
            # enqueue so queue depth doesn't read as network lag
            self.timeline.vote_arrival(
                vote.height, vote.round_, int(vote.type_), peer_id,
                vote.timestamp.unix_ns())
        await self.msg_queue.put((True, M.VoteMessage(vote=vote, peer_id=peer_id)))

    async def add_proposal_from_peer(self, proposal: Proposal, peer_id: str) -> None:
        await self.msg_queue.put((True, M.ProposalMessage(proposal=proposal, peer_id=peer_id)))

    async def add_block_part_from_peer(self, height: int, round_: int, part, peer_id: str) -> None:
        await self.msg_queue.put(
            (True, M.BlockPartMessage(height=height, round_=round_, part=part, peer_id=peer_id))
        )

    # --------------------------------------------------------- receive loop

    def _gossip(self, msg) -> None:
        if self.outbound_hook is None:
            return
        try:
            self.outbound_hook(msg)
        except Exception as e:  # noqa: BLE001 - gossip must not kill consensus
            self.logger.error("outbound hook failed", err=str(e))

    async def _receive_routine(self) -> None:
        """state.go:774-862: the single serialization point."""
        while True:
            try:
                from_peer, msg = await self.msg_queue.get()
                if isinstance(msg, TimeoutInfo):
                    if self.wal is not None:
                        self.wal.write(msg)
                    await self._handle_timeout(msg)
                else:
                    if self.wal is not None:
                        if from_peer:
                            # a failed peer-message WAL write is logged,
                            # not fatal (reference state.go:822): the
                            # message is DROPPED un-WALed — as if never
                            # received — and gossip redelivers; a full or
                            # failing disk degrades, it does not halt
                            try:
                                self.wal.write(msg)
                            except OSError as e:
                                self.logger.error(
                                    "failed writing peer msg to WAL; "
                                    "dropping msg", err=str(e))
                                continue
                        else:
                            # own messages MUST be durable before they
                            # act (state.go:829 fsync): failure here is a
                            # consensus failure, handled by containment
                            self.wal.write_sync(msg)
                    await self._handle_msg(msg)
            except asyncio.CancelledError:
                raise
            except Exception:  # noqa: BLE001 - CONSENSUS FAILURE (state.go:789)
                self.logger.error(
                    "CONSENSUS FAILURE!!!", err=traceback.format_exc()
                )
                self._contain_failure()
                return

    def _contain_failure(self) -> None:
        """state.go:789-802 containment, made observable: a node whose
        consensus routine died must not keep looking healthy. Flush+fsync
        the WAL (evidence of what was seen survives the crash), mark the
        service failed — /health and /status report it (rpc/core.py) — and
        let operators decide whether to kill the process; the reference
        likewise keeps the process up so the WAL/evidence can be pulled."""
        self.failed = True
        try:
            if self.wal is not None:
                self.wal.flush()
        except Exception as e:  # noqa: BLE001 - best effort on the way down
            self.logger.error("WAL flush on consensus failure", err=str(e))

    async def _handle_msg(self, msg) -> None:
        if isinstance(msg, M.ProposalMessage):
            self._set_proposal(msg.proposal, msg.peer_id)
        elif isinstance(msg, M.BlockPartMessage):
            await self._add_proposal_block_part(msg)
        elif isinstance(msg, M.VoteMessage):
            await self._try_add_vote(msg.vote, msg.peer_id)
        else:
            self.logger.error("unknown msg type", type=str(type(msg)))

    async def _handle_timeout(self, ti: TimeoutInfo) -> None:
        """state.go:930-980."""
        if self.config.batch_vote_verification:
            await self._flush_all_pending_votes()
        rs = self.rs
        if ti.height != rs.height or ti.round_ < rs.round_ or (
            ti.round_ == rs.round_ and ti.step < rs.step
        ):
            return  # stale
        if ti.step == RoundStepType.NEW_HEIGHT:
            await self._enter_new_round(ti.height, 0)
        elif ti.step == RoundStepType.NEW_ROUND:
            await self._enter_propose(ti.height, 0)
        elif ti.step == RoundStepType.PROPOSE:
            await self._enter_prevote(ti.height, ti.round_)
        elif ti.step == RoundStepType.PREVOTE_WAIT:
            await self._enter_precommit(ti.height, ti.round_)
        elif ti.step == RoundStepType.PRECOMMIT_WAIT:
            await self._enter_precommit(ti.height, ti.round_)
            await self._enter_new_round(ti.height, ti.round_ + 1)
        else:
            self.logger.error("invalid timeout step", step=ti.step.name)

    # ------------------------------------------------------------- rounds

    def _new_step(self, step: RoundStepType) -> None:
        self.rs.step = step
        self.n_steps += 1
        # stamp height/round into every log record this task emits from
        # here on (libs/log.py context — grep-by-height works node-wide)
        cmtlog.set_height_round(self.rs.height, self.rs.round_)
        trace.event(f"consensus.step.{step.name.lower()}", cat="consensus",
                    parent=self._height_span, height=self.rs.height,
                    round=self.rs.round_)
        if self.event_switch is not None:
            self.event_switch.fire("NewRoundStep", self.rs)

    async def _enter_new_round(self, height: int, round_: int) -> None:
        """state.go:1042-1127."""
        rs = self.rs
        if rs.height != height or round_ < rs.round_ or (
            rs.round_ == round_ and rs.step != RoundStepType.NEW_HEIGHT
        ):
            return
        if trace.enabled() and self._height_span_h != height:
            # new height: roll the timeline span (the previous one closed
            # in _finalize_commit; this also covers replay/catch-up jumps)
            if self._height_span is not None:
                self._height_span.finish()
            # the height budget rides on top of the unavoidable protocol
            # waits (propose window + commit delay): with the bare global
            # slow_ms, every ordinary height would be "slow" and the
            # capture ring would hold nothing but routine heights
            cfg = self.config
            wait_ms = (cfg.timeout_propose + cfg.timeout_commit) * 1e3
            self._height_span = trace.begin(
                "consensus.height", cat="consensus", height=height,
                slow_ms=trace.slow_budget_ms() + wait_ms)
            self._height_span_h = height
        self.timeline.mark(height, timeline.NEW_HEIGHT, round_=round_)
        validators = rs.validators
        if rs.round_ < round_:
            validators = validators.copy()
            validators.increment_proposer_priority(round_ - rs.round_)
        rs.validators = validators
        rs.round_ = round_
        self._new_step(RoundStepType.NEW_ROUND)
        if round_ != 0:
            # round catchup resets proposal state (state.go:1092-1100)
            rs.proposal = None
            rs.proposal_block = None
            rs.proposal_block_parts = None
        rs.votes.set_round(round_)
        rs.triggered_timeout_precommit = False

        wait_for_txs = (
            self.config.create_empty_blocks_interval > 0
            and not self.config.create_empty_blocks
        )
        if wait_for_txs:
            self._schedule_timeout(
                self.config.create_empty_blocks_interval, height, round_,
                RoundStepType.NEW_ROUND,
            )
        await self._enter_propose(height, round_)

    def _is_proposer(self) -> bool:
        if self.priv_validator_pub_key is None:
            return False
        proposer = self.rs.validators.get_proposer()
        return proposer is not None and proposer.address == self.priv_validator_pub_key.address()

    async def _enter_propose(self, height: int, round_: int) -> None:
        """state.go:1129-1192."""
        rs = self.rs
        if rs.height != height or round_ < rs.round_ or (
            rs.round_ == round_ and rs.step >= RoundStepType.PROPOSE
        ):
            return
        rs.round_ = round_
        # backstop for vote-driven height entries that skip enter_new_round
        # (first-wins: a no-op when enter_new_round already stamped it)
        self.timeline.mark(height, timeline.NEW_HEIGHT, round_=round_)
        self._new_step(RoundStepType.PROPOSE)
        self._schedule_timeout(
            self.config.propose_timeout(round_), height, round_, RoundStepType.PROPOSE
        )
        if self._is_proposer():
            with trace.span("consensus.propose", cat="consensus",
                            parent=self._height_span, height=height,
                            round=round_):
                await self.decide_proposal(height, round_)
        if self._is_proposal_complete():
            await self._enter_prevote(height, rs.round_)

    async def _default_decide_proposal(self, height: int, round_: int) -> None:
        """state.go:1193-1266."""
        rs = self.rs
        if rs.valid_block is not None:
            block, block_parts = rs.valid_block, rs.valid_block_parts
        else:
            block = await self._create_proposal_block()
            if block is None:
                return
            block_parts = block.make_part_set(BLOCK_PART_SIZE)
        block_id = BlockID(hash=block.hash(), part_set_header=block_parts.header())
        proposal = Proposal(
            height=height, round_=round_, pol_round=rs.valid_round,
            block_id=block_id, timestamp=cmttime.now(),
        )
        try:
            self.priv_validator.sign_proposal(self.state.chain_id, proposal)
        except Exception as e:  # noqa: BLE001
            self.logger.error("propose step; failed signing proposal", err=str(e))
            return
        await self.msg_queue.put((False, M.ProposalMessage(proposal=proposal)))
        self._gossip(M.ProposalMessage(proposal=proposal))
        for i in range(block_parts.total):
            part_msg = M.BlockPartMessage(height=rs.height, round_=rs.round_, part=block_parts.get_part(i))
            await self.msg_queue.put((False, part_msg))
            self._gossip(part_msg)
        self.timeline.mark(height, timeline.PROPOSAL_SENT, round_=round_)
        self.logger.info("signed proposal", height=height, round=round_, proposal=str(proposal.block_id))

    async def _create_proposal_block(self) -> Block | None:
        """state.go:1268-1309."""
        if self.priv_validator_pub_key is None:
            return None
        rs = self.rs
        if rs.height == self.state.initial_height:
            last_ext_commit = ExtendedCommit(
                height=0, round_=0, block_id=BlockID(), extended_signatures=[]
            )
        elif rs.last_commit is not None and rs.last_commit.has_two_thirds_majority():
            last_ext_commit = rs.last_commit.make_extended_commit()
        else:
            self.logger.error("propose step; cannot propose anything without commit for the previous block")
            return None
        return await self.block_exec.create_proposal_block(
            rs.height, self.state, last_ext_commit, self.priv_validator_pub_key.address()
        )

    def _is_proposal_complete(self) -> bool:
        """state.go:1311-1330."""
        rs = self.rs
        if rs.proposal is None or rs.proposal_block is None:
            return False
        if rs.proposal.pol_round < 0:
            return True
        prevotes = rs.votes.prevotes(rs.proposal.pol_round)
        return prevotes is not None and prevotes.has_two_thirds_majority()

    # ------------------------------------------------------------ proposal

    def _set_proposal(self, proposal: Proposal, peer_id: str = "") -> None:
        self.set_proposal_fn(proposal, peer_id)

    def _default_set_proposal(self, proposal: Proposal, peer_id: str = "") -> None:
        """state.go:1960-1993 defaultSetProposal."""
        rs = self.rs
        if rs.proposal is not None:
            return
        if proposal.height != rs.height or proposal.round_ != rs.round_:
            return
        if proposal.pol_round < -1 or (
            proposal.pol_round >= 0 and proposal.pol_round >= proposal.round_
        ):
            raise ValueError("error invalid proposal POL round")
        proposer = rs.validators.get_proposer()
        if not proposal.verify(self.state.chain_id, proposer.pub_key):
            raise ValueError("error invalid proposal signature")
        rs.proposal = proposal
        if rs.proposal_block_parts is None:
            rs.proposal_block_parts = PartSet.from_header(proposal.block_id.part_set_header)
        self.timeline.mark(rs.height, timeline.PROPOSAL_RECEIVED,
                           round_=rs.round_, peer=peer_id)
        self.logger.info("received proposal", proposal=str(proposal.block_id), peer=peer_id)

    async def _add_proposal_block_part(self, msg: M.BlockPartMessage) -> bool:
        """state.go:1994-2073."""
        rs = self.rs
        if msg.height != rs.height:
            return False
        if rs.proposal_block_parts is None:
            return False
        added = rs.proposal_block_parts.add_part(msg.part)
        if not added:
            return False
        self.timeline.mark(msg.height, timeline.FIRST_BLOCK_PART,
                           round_=msg.round_, peer=msg.peer_id)
        if rs.proposal_block_parts.is_complete():
            block = Block.from_proto(rs.proposal_block_parts.get_reader())
            rs.proposal_block = block
            self.timeline.mark(msg.height, timeline.PROPOSAL_COMPLETE,
                               round_=msg.round_)
            self.logger.info("received complete proposal block",
                             height=block.header.height, hash=block.hash().hex()[:12])
            await self._handle_complete_proposal(msg.height)
        return True

    async def _handle_complete_proposal(self, height: int) -> None:
        """state.go:2074-2108."""
        rs = self.rs
        prevotes = rs.votes.prevotes(rs.round_)
        block_id, has_maj = (prevotes.two_thirds_majority() if prevotes else (None, False))
        if has_maj and not block_id.is_nil() and rs.valid_round < rs.round_:
            if rs.proposal_block.hash() == block_id.hash:
                rs.valid_round = rs.round_
                rs.valid_block = rs.proposal_block
                rs.valid_block_parts = rs.proposal_block_parts
                if self.event_switch is not None:
                    self.event_switch.fire("ValidBlock", rs)
        if rs.step <= RoundStepType.PROPOSE and self._is_proposal_complete():
            await self._enter_prevote(height, rs.round_)
            if has_maj:
                await self._enter_precommit(height, rs.round_)
        elif rs.step == RoundStepType.COMMIT:
            await self._try_finalize_commit(height)

    # ------------------------------------------------------------- prevote

    async def _enter_prevote(self, height: int, round_: int) -> None:
        """state.go:1311-1336."""
        rs = self.rs
        if rs.height != height or round_ < rs.round_ or (
            rs.round_ == round_ and rs.step >= RoundStepType.PREVOTE
        ):
            return
        rs.round_ = round_
        self._new_step(RoundStepType.PREVOTE)
        await self.do_prevote(height, round_)

    async def _default_do_prevote(self, height: int, round_: int) -> None:
        """state.go:1337-1410."""
        rs = self.rs
        if rs.locked_block is not None:
            await self._sign_add_vote(SignedMsgType.PREVOTE, rs.locked_block.hash(),
                                      rs.locked_block_parts.header())
            return
        if rs.proposal_block is None:
            await self._sign_add_vote(SignedMsgType.PREVOTE, b"", None)
            return
        try:
            self.block_exec.validate_block(self.state, rs.proposal_block)
            accepted = await self.block_exec.process_proposal(rs.proposal_block, self.state)
        except Exception as e:  # noqa: BLE001
            self.logger.error("prevote step: invalid proposal block", err=str(e))
            accepted = False
        if accepted:
            await self._sign_add_vote(
                SignedMsgType.PREVOTE, rs.proposal_block.hash(),
                rs.proposal_block_parts.header(),
            )
        else:
            await self._sign_add_vote(SignedMsgType.PREVOTE, b"", None)

    async def _enter_prevote_wait(self, height: int, round_: int) -> None:
        """state.go:1478-1510."""
        rs = self.rs
        if rs.height != height or round_ < rs.round_ or (
            rs.round_ == round_ and rs.step >= RoundStepType.PREVOTE_WAIT
        ):
            return
        prevotes = rs.votes.prevotes(round_)
        if prevotes is None or not prevotes.has_two_thirds_any():
            raise RuntimeError("enterPrevoteWait without +2/3 prevotes")
        rs.round_ = round_
        self._new_step(RoundStepType.PREVOTE_WAIT)
        self._schedule_timeout(
            self.config.prevote_timeout(round_), height, round_, RoundStepType.PREVOTE_WAIT
        )

    # ----------------------------------------------------------- precommit

    async def _enter_precommit(self, height: int, round_: int) -> None:
        """state.go:1513-1645 — the locking rules."""
        rs = self.rs
        if rs.height != height or round_ < rs.round_ or (
            rs.round_ == round_ and rs.step >= RoundStepType.PRECOMMIT
        ):
            return
        rs.round_ = round_
        self._new_step(RoundStepType.PRECOMMIT)
        prevotes = rs.votes.prevotes(round_)
        block_id, has_maj = (prevotes.two_thirds_majority() if prevotes else (None, False))
        if not has_maj:
            # no +2/3 prevotes: precommit nil (no unlock)
            await self._sign_add_vote(SignedMsgType.PRECOMMIT, b"", None)
            return
        # +2/3 nil: unlock and precommit nil
        if block_id.is_nil():
            if rs.locked_block is not None:
                rs.locked_round = -1
                rs.locked_block = None
                rs.locked_block_parts = None
            await self._sign_add_vote(SignedMsgType.PRECOMMIT, b"", None)
            return
        # +2/3 for our locked block: relock
        if rs.locked_block is not None and rs.locked_block.hash() == block_id.hash:
            rs.locked_round = round_
            await self._sign_add_vote(SignedMsgType.PRECOMMIT, block_id.hash,
                                      block_id.part_set_header)
            return
        # +2/3 for the proposal block: lock it
        if rs.proposal_block is not None and rs.proposal_block.hash() == block_id.hash:
            self.block_exec.validate_block(self.state, rs.proposal_block)
            rs.locked_round = round_
            rs.locked_block = rs.proposal_block
            rs.locked_block_parts = rs.proposal_block_parts
            await self._sign_add_vote(SignedMsgType.PRECOMMIT, block_id.hash,
                                      block_id.part_set_header)
            return
        # +2/3 for a block we don't have: unlock, fetch it, precommit nil
        rs.locked_round = -1
        rs.locked_block = None
        rs.locked_block_parts = None
        if rs.proposal_block is None or rs.proposal_block.hash() != block_id.hash:
            rs.proposal_block = None
            rs.proposal_block_parts = PartSet.from_header(block_id.part_set_header)
        await self._sign_add_vote(SignedMsgType.PRECOMMIT, b"", None)

    async def _enter_precommit_wait(self, height: int, round_: int) -> None:
        """state.go:1646-1676."""
        rs = self.rs
        if rs.height != height or round_ < rs.round_ or (
            rs.round_ == round_ and rs.triggered_timeout_precommit
        ):
            return
        precommits = rs.votes.precommits(round_)
        if precommits is None or not precommits.has_two_thirds_any():
            raise RuntimeError("enterPrecommitWait without +2/3 precommits")
        rs.triggered_timeout_precommit = True
        self._new_step(RoundStepType.PRECOMMIT_WAIT)
        self._schedule_timeout(
            self.config.precommit_timeout(round_), height, round_, RoundStepType.PRECOMMIT_WAIT
        )

    # -------------------------------------------------------------- commit

    async def _enter_commit(self, height: int, commit_round: int) -> None:
        """state.go:1648-1709."""
        rs = self.rs
        if rs.height != height or rs.step >= RoundStepType.COMMIT:
            return
        precommits = rs.votes.precommits(commit_round)
        block_id, has_maj = precommits.two_thirds_majority()
        if not has_maj or block_id.is_nil():
            raise RuntimeError("RunActionCommit expected +2/3 precommits for a block")
        rs.commit_round = commit_round
        rs.commit_time = cmttime.now()
        self._new_step(RoundStepType.COMMIT)
        self.timeline.mark(height, timeline.COMMIT, round_=commit_round)
        if rs.locked_block is not None and rs.locked_block.hash() == block_id.hash:
            rs.proposal_block = rs.locked_block
            rs.proposal_block_parts = rs.locked_block_parts
        if rs.proposal_block is None or rs.proposal_block.hash() != block_id.hash:
            rs.proposal_block = None
            rs.proposal_block_parts = PartSet.from_header(block_id.part_set_header)
        if self.event_switch is not None:
            # announce the committed block's part-set so lagging peers fetch
            # the right parts (reference EventValidBlock in enterCommit)
            self.event_switch.fire("ValidBlock", rs)
        await self._try_finalize_commit(height)

    async def _try_finalize_commit(self, height: int) -> None:
        """state.go:1711-1737."""
        rs = self.rs
        if rs.height != height:
            raise RuntimeError("tryFinalizeCommit at wrong height")
        precommits = rs.votes.precommits(rs.commit_round)
        block_id, has_maj = precommits.two_thirds_majority()
        if not has_maj or block_id.is_nil():
            return
        if rs.proposal_block is None or rs.proposal_block.hash() != block_id.hash:
            return  # waiting for block parts
        await self._finalize_commit(height)

    async def _finalize_commit(self, height: int) -> None:
        """state.go:1739-1852."""
        rs = self.rs
        block, block_parts = rs.proposal_block, rs.proposal_block_parts
        precommits = rs.votes.precommits(rs.commit_round)
        block_id, _ = precommits.two_thirds_majority()
        with trace.span("consensus.commit_verify", cat="consensus",
                        parent=self._height_span, height=height):
            self.block_exec.validate_block(self.state, block)

        fail.fail_point("blockstore.save")  # state.go:1777 (legacy index 0)
        if self.block_store.height() < block.header.height:
            seen_extended = rs.votes.precommits(rs.commit_round).make_extended_commit()
            if self.state.consensus_params.abci.vote_extensions_enabled(block.header.height):
                self.block_store.save_block_with_extended_commit(block, block_parts, seen_extended)
            else:
                self.block_store.save_block(block, block_parts, seen_extended.to_commit())

        fail.fail_point("wal.endheight")  # state.go:1794 (legacy index 1)
        if self.wal is not None:
            self.wal.write_sync(EndHeightMessage(height))  # state.go:1810 fsync
        # state.go:1817 (legacy index 2) — the committed-but-unapplied
        # crash window: EndHeight is durable, ApplyBlock has not run
        fail.fail_point("abci.apply")

        with trace.span("consensus.abci_exec", cat="consensus",
                        parent=self._height_span, height=height,
                        txs=len(block.data.txs)):
            new_state = await self.block_exec.apply_block(
                self.state, block_id, block)
        if self._height_span is not None and self._height_span_h == height:
            self._height_span.set(
                rounds=rs.commit_round, txs=len(block.data.txs))
            self._height_span.finish()
            self._height_span = None
        self.timeline.mark(height, timeline.APPLY_DONE, round_=rs.commit_round)
        self.timeline.height_done(height)
        self.logger.info(
            "finalized block", height=height, hash=block.hash().hex()[:12],
            txs=len(block.data.txs), app_hash=new_state.app_hash.hex()[:12],
        )
        if self.metrics is not None:
            m = self.metrics
            m.height.set(height)
            m.rounds.set(rs.commit_round)
            m.num_txs.set(len(block.data.txs))
            m.total_txs.inc(len(block.data.txs))
            if block_parts is not None:
                m.block_size.set(sum(
                    len(p.bytes_) for p in block_parts.parts if p is not None))
            if self.state is not None and not self.state.last_block_time.is_zero():
                m.block_interval.observe(
                    (block.header.time.unix_ns() - self.state.last_block_time.unix_ns())
                    / 1e9)
            if rs.validators is not None:
                m.validators.set(len(rs.validators))
                m.validators_power.set(rs.validators.total_voting_power())
        self.update_to_state(new_state)
        self._schedule_round_0(self.rs)

    # --------------------------------------------------------------- votes

    async def _sign_add_vote(self, type_: SignedMsgType, hash_: bytes, psh) -> Optional[Vote]:
        """state.go:2452-2490 signAddVote."""
        rs = self.rs
        if self.priv_validator is None or self.priv_validator_pub_key is None:
            return None
        addr = self.priv_validator_pub_key.address()
        if not rs.validators.has_address(addr):
            return None
        idx, _ = rs.validators.get_by_address(addr)
        vote = Vote(
            type_=type_,
            height=rs.height,
            round_=rs.round_,
            block_id=BlockID(hash=hash_, part_set_header=psh) if hash_ else BlockID(),
            timestamp=cmttime.canonical_now_ms(),
            validator_address=addr,
            validator_index=idx,
        )
        ext_enabled = self.state.consensus_params.abci.vote_extensions_enabled(rs.height)
        if ext_enabled and type_ == SignedMsgType.PRECOMMIT and hash_:
            from cometbft_tpu.abci import types as abci

            resp = await self.block_exec.app_conn.extend_vote(
                abci.RequestExtendVote(hash=hash_, height=rs.height, round_=rs.round_)
            )
            vote.extension = resp.vote_extension
        try:
            self.priv_validator.sign_vote(self.state.chain_id, vote, sign_extension=ext_enabled)
        except Exception as e:  # noqa: BLE001
            self.logger.error("failed signing vote", err=str(e))
            return None
        await self.msg_queue.put((False, M.VoteMessage(vote=vote)))
        self._gossip(M.VoteMessage(vote=vote))
        return vote

    async def _try_add_vote(self, vote: Vote, peer_id: str) -> bool:
        """state.go:2110-2159: tolerate expected errors, detect equivocation."""
        try:
            return await self._add_vote(vote, peer_id)
        except ErrVoteConflictingVotes as e:
            if vote.validator_address == (
                self.priv_validator_pub_key.address() if self.priv_validator_pub_key else b""
            ):
                self.logger.error("found conflicting vote from ourselves; did you unsafe_reset a validator?")
                raise
            self._conflicts_to_evidence(getattr(e, "conflicts", None) or [e])
            return False
        except ErrVoteInvalidSignature as e:
            self._report_misbehavior(peer_id, "invalid-vote-signature")
            self.logger.info("rejected vote with invalid signature",
                             err=str(e), peer=peer_id)
            return False
        except Exception as e:  # noqa: BLE001 - bad votes are logged, not fatal
            self.logger.info("failed attempting to add vote", err=str(e))
            return False

    def _report_misbehavior(self, peer_id: str, reason: str) -> None:
        if not peer_id or self.misbehavior_hook is None:
            return
        try:
            self.misbehavior_hook(peer_id, reason)
        except Exception as e:  # noqa: BLE001 - scoring must not kill consensus
            self.logger.error("misbehavior hook failed", err=str(e))

    def _conflicts_to_evidence(self, conflicts) -> None:
        """Equivocations -> the pool's consensus buffer (state.go:2117-2146
        ReportConflictingVotes). The pool materializes DuplicateVoteEvidence
        once the header at the vote height commits, stamping the BLOCK time
        — the timestamp other pools cross-check against. Takes a list so one
        batched flush can report every conflicting pair it found."""
        for e in conflicts:
            if self.block_exec.evidence_pool is not None:
                self.block_exec.evidence_pool.report_conflicting_votes(
                    e.vote_a, e.vote_b
                )
            self.logger.info(
                "found and sent conflicting vote to evidence pool",
                vote=str(e.vote_b),
            )

    async def _add_vote(self, vote: Vote, peer_id: str) -> bool:
        """state.go:2161-2450."""
        rs = self.rs
        # precommit for previous height (LastCommit catchup, state.go:2176)
        if vote.height + 1 == rs.height and vote.type_ == SignedMsgType.PRECOMMIT:
            if rs.step != RoundStepType.NEW_HEIGHT or rs.last_commit is None:
                return False
            added = rs.last_commit.add_vote(vote)
            if added and self.event_switch is not None:
                self.event_switch.fire("Vote", vote)
            return added
        if vote.height != rs.height:
            return False

        # Extension check on every peer precommit (state.go:2219-2240):
        # the extension signature is verified FIRST so the app only ever
        # sees authenticated payloads (ref vote.VerifyExtension before
        # blockExec.VerifyVoteExtension — a forged vote must not buy an
        # ABCI round-trip), then the app judges the payload. Skipped for
        # our own votes — we produced the extension via ExtendVote.
        if (
            vote.type_ == SignedMsgType.PRECOMMIT
            and not vote.block_id.is_nil()
            and self.state.consensus_params.abci.vote_extensions_enabled(vote.height)
            and vote.validator_address
            != (self.priv_validator_pub_key.address() if self.priv_validator_pub_key else b"")
        ):
            _, val = rs.validators.get_by_index(vote.validator_index)
            if val is None:
                return False
            if not vote.verify_extension(self.state.chain_id, val.pub_key):
                self.logger.info("invalid vote extension signature", vote=str(vote))
                return False
            try:
                await self.block_exec.verify_vote_extension(vote)
            except Exception:
                if self.metrics is not None:
                    self.metrics.vote_extension_received.labels("rejected").inc()
                raise
            if self.metrics is not None:
                self.metrics.vote_extension_received.labels("accepted").inc()

        if self.config.batch_vote_verification and peer_id:
            return await self._add_vote_batched(vote, peer_id)

        added = rs.votes.add_vote(vote, peer_id)
        if not added:
            return False
        if self.event_switch is not None:
            self.event_switch.fire("Vote", vote)

        if vote.type_ == SignedMsgType.PREVOTE:
            await self._on_prevote_added(vote.round_)
        else:
            await self._on_precommit_added(vote.round_)
        if self.config.batch_vote_verification:
            # a serially-added vote (our own) can be the one that pushes the
            # speculative tally over quorum: recheck the staged batch or
            # peer votes staged earlier would never flush (liveness)
            vs = (
                rs.votes.prevotes(vote.round_)
                if vote.type_ == SignedMsgType.PREVOTE
                else rs.votes.precommits(vote.round_)
            )
            if vs is not None and vs.should_flush():
                await self._flush_vote_set(vs)
        return True

    # ---------------------------------------------------- batched vote path

    async def _add_vote_batched(self, vote: Vote, peer_id: str) -> bool:
        """THE hot path, batch-first (SURVEY §3.3): gossip votes are staged
        with cheap structural checks; signatures verify in coalesced device
        batches. Pending votes are invisible to every threshold read (the
        tally only counts verified votes), so 'never count an unverified
        vote' holds by construction; the speculative quorum boundary inside
        should_flush guarantees a staged majority is flushed immediately."""
        rs = self.rs
        staged = rs.votes.add_pending(vote, peer_id)
        if not staged:
            return False
        self._staged_peer[_vote_key(vote)] = peer_id
        vs = (
            rs.votes.prevotes(vote.round_)
            if vote.type_ == SignedMsgType.PREVOTE
            else rs.votes.precommits(vote.round_)
        )
        if vs is not None and vs.should_flush():
            await self._flush_vote_set(vs)
        return True

    async def _flush_vote_set(self, vs: VoteSet) -> None:
        """One device batch for a VoteSet's staged votes; then events +
        threshold hooks for what got added, evidence for equivocations.
        The flush runs consensus-class through the global verify
        scheduler: it drains immediately (never queued behind sync or
        mempool work) and coalesces whatever compatible queued rows fit
        the bucket as filler — the device sees one fuller batch instead
        of a fragment."""
        from cometbft_tpu import sched

        n_pending = len(vs._pending)
        if self.metrics is not None and n_pending > 0:
            self.metrics.batch_flushes.inc()
            self.metrics.batch_lanes.inc(n_pending)
        kind = ("prevote" if vs.signed_msg_type == SignedMsgType.PREVOTE
                else "precommit")
        flush_sp = trace.span(
            f"consensus.{kind}_flush", cat="consensus",
            parent=self._height_span, height=self.rs.height,
            round=vs.round_, rows=n_pending)
        try:
            with flush_sp, sched.work_class(sched.CONSENSUS):
                results = vs.flush_pending()
        except ErrVoteConflictingVotes as e:
            results = getattr(e, "results", [])
            own_addr = (
                self.priv_validator_pub_key.address()
                if self.priv_validator_pub_key
                else b""
            )
            conflicts = getattr(e, "conflicts", None) or [e]
            if any(c.vote_b.validator_address == own_addr for c in conflicts):
                self.logger.error("found conflicting vote from ourselves; did you unsafe_reset a validator?")
                raise
            self._conflicts_to_evidence(conflicts)
        added_any = False
        from cometbft_tpu.types import vote_set as VS

        for v, status in results:
            staging_peer = self._staged_peer.pop(_vote_key(v), "")
            if status == VS.FLUSH_ADDED:
                added_any = True
                if self.event_switch is not None:
                    self.event_switch.fire("Vote", v)
            elif status == VS.FLUSH_INVALID:
                self._report_misbehavior(staging_peer, "invalid-vote-signature")
        if added_any:
            if vs.signed_msg_type == SignedMsgType.PREVOTE:
                await self._on_prevote_added(vs.round_)
            else:
                await self._on_precommit_added(vs.round_)

    async def _flush_all_pending_votes(self) -> None:
        """Flush every staged vote batch for the current height — called
        before timeout-driven threshold decisions so liveness never waits
        on an unflushed batch."""
        if self.rs.votes is None:
            return
        for vs in self.rs.votes.pending_sets():
            await self._flush_vote_set(vs)

    async def _on_prevote_added(self, round_: int) -> None:
        """state.go:2270-2366 (parameterized by round: the batched path
        folds many votes of one round at once)."""
        rs = self.rs
        vote_round = round_
        prevotes = rs.votes.prevotes(vote_round)
        if timeline.enabled() and prevotes is not None:
            # threshold crossings (first-wins marks): rs.height read before
            # any enter_* below can advance it
            self.timeline.mark(rs.height, timeline.PREVOTE_FIRST,
                               round_=vote_round)
            if prevotes.has_one_third_any():
                self.timeline.mark(rs.height, timeline.PREVOTE_THIRD,
                                   round_=vote_round)
            if prevotes.has_two_thirds_any():
                self.timeline.mark(rs.height, timeline.PREVOTE_QUORUM,
                                   round_=vote_round)
        block_id, has_maj = prevotes.two_thirds_majority()
        if has_maj:
            # unlock on POL for a different block (state.go:2290-2305)
            if (
                rs.locked_block is not None
                and rs.locked_round < vote_round <= rs.round_
                and rs.locked_block.hash() != block_id.hash
            ):
                rs.locked_round = -1
                rs.locked_block = None
                rs.locked_block_parts = None
            # update valid block (state.go:2307-2330)
            if not block_id.is_nil() and rs.valid_round < vote_round <= rs.round_:
                if rs.proposal_block is not None and rs.proposal_block.hash() == block_id.hash:
                    rs.valid_round = vote_round
                    rs.valid_block = rs.proposal_block
                    rs.valid_block_parts = rs.proposal_block_parts
                else:
                    rs.proposal_block = None
                    rs.proposal_block_parts = PartSet.from_header(block_id.part_set_header)

        if rs.round_ < vote_round and prevotes.has_two_thirds_any():
            await self._enter_new_round(rs.height, vote_round)
        elif rs.round_ == vote_round and rs.step >= RoundStepType.PREVOTE:
            if has_maj and (self._is_proposal_complete() or block_id.is_nil()):
                await self._enter_precommit(rs.height, vote_round)
            elif prevotes.has_two_thirds_any():
                await self._enter_prevote_wait(rs.height, vote_round)
        elif rs.proposal is not None and 0 <= rs.proposal.pol_round == vote_round:
            if self._is_proposal_complete():
                await self._enter_prevote(rs.height, rs.round_)

    async def _on_precommit_added(self, round_: int) -> None:
        """state.go:2368-2416 (parameterized by round)."""
        rs = self.rs
        vote_round = round_
        precommits = rs.votes.precommits(vote_round)
        if timeline.enabled() and precommits is not None:
            self.timeline.mark(rs.height, timeline.PRECOMMIT_FIRST,
                               round_=vote_round)
            if precommits.has_two_thirds_any():
                self.timeline.mark(rs.height, timeline.PRECOMMIT_QUORUM,
                                   round_=vote_round)
        block_id, has_maj = precommits.two_thirds_majority()
        if has_maj:
            await self._enter_new_round(rs.height, vote_round)
            await self._enter_precommit(rs.height, vote_round)
            if not block_id.is_nil():
                await self._enter_commit(rs.height, vote_round)
                if self.config.skip_timeout_commit and precommits.has_all():
                    await self._enter_new_round(rs.height, 0)
            else:
                await self._enter_precommit_wait(rs.height, vote_round)
        elif rs.round_ <= vote_round and precommits.has_two_thirds_any():
            await self._enter_new_round(rs.height, vote_round)
            await self._enter_precommit_wait(rs.height, vote_round)

    # -------------------------------------------------------------- replay

    async def _catchup_replay(self) -> None:
        """Replay WAL messages recorded after the last EndHeight
        (consensus/replay.go:94): re-feed them through the handlers with
        WAL writes disabled."""
        # replay.go:99-115: the WAL must NOT already contain EndHeight for
        # the height we are about to run — that means the block committed
        # (crash between the EndHeight fsync and the state-store save) and
        # re-feeding its messages would double-execute it against the app.
        # Recovery for that window is handshake block replay, not WAL
        # replay.
        if self.wal.search_for_end_height(self.rs.height):
            raise RuntimeError(
                f"WAL should not contain EndHeight {self.rs.height}: block "
                "already committed; requires handshake block replay"
            )
        msgs = self.wal.replay_after_height(self.rs.height - 1)
        if not msgs:
            return
        self.logger.info("catchup replay", height=self.rs.height, msgs=len(msgs))
        wal, self.wal = self.wal, None
        try:
            for msg in msgs:
                if isinstance(msg, TimeoutInfo):
                    await self._handle_timeout(msg)
                elif isinstance(msg, EndHeightMessage):
                    continue
                else:
                    await self._handle_msg(msg)
        finally:
            self.wal = wal
