"""RoundState — the consensus-internal state snapshot.

Reference: consensus/types/round_state.go. Everything the gossip reactor
reads (via events / shared snapshot) and the step functions mutate.
"""

from __future__ import annotations

import enum
from dataclasses import dataclass, field

from cometbft_tpu.types.basic import BlockID
from cometbft_tpu.types.block import Block
from cometbft_tpu.types.part_set import PartSet
from cometbft_tpu.types.proposal import Proposal
from cometbft_tpu.types.validator import ValidatorSet
from cometbft_tpu.utils import cmttime


class RoundStepType(enum.IntEnum):
    """consensus/types/round_state.go:12-40."""

    NEW_HEIGHT = 1
    NEW_ROUND = 2
    PROPOSE = 3
    PREVOTE = 4
    PREVOTE_WAIT = 5
    PRECOMMIT = 6
    PRECOMMIT_WAIT = 7
    COMMIT = 8


@dataclass
class RoundState:
    height: int = 0
    round_: int = 0
    step: RoundStepType = RoundStepType.NEW_HEIGHT
    start_time: cmttime.Timestamp = field(default_factory=cmttime.Timestamp.zero)
    commit_time: cmttime.Timestamp = field(default_factory=cmttime.Timestamp.zero)
    validators: ValidatorSet | None = None
    proposal: Proposal | None = None
    proposal_block: Block | None = None
    proposal_block_parts: PartSet | None = None
    locked_round: int = -1
    locked_block: Block | None = None
    locked_block_parts: PartSet | None = None
    valid_round: int = -1
    valid_block: Block | None = None
    valid_block_parts: PartSet | None = None
    votes: "object" = None  # HeightVoteSet
    commit_round: int = -1
    last_commit: "object" = None  # VoteSet of precommits for height-1
    last_validators: ValidatorSet | None = None
    triggered_timeout_precommit: bool = False

    def height_round_step(self) -> str:
        return f"{self.height}/{self.round_}/{self.step.name}"
