"""Consensus timing/behavior knobs (reference: config/config.go:1090-1230).

Defaults mirror the reference (propose 3s + 500ms/round, prevote/precommit
1s + 500ms/round, commit 1s); tests shrink them to drive rounds in
milliseconds — the injectable analog of the reference's mock TimeoutTicker.
"""

from __future__ import annotations

from dataclasses import dataclass


@dataclass
class ConsensusConfig:
    timeout_propose: float = 3.0
    timeout_propose_delta: float = 0.5
    timeout_prevote: float = 1.0
    timeout_prevote_delta: float = 0.5
    timeout_precommit: float = 1.0
    timeout_precommit_delta: float = 0.5
    timeout_commit: float = 1.0
    skip_timeout_commit: bool = False
    create_empty_blocks: bool = True
    create_empty_blocks_interval: float = 0.0
    peer_gossip_sleep_duration: float = 0.1
    peer_query_maj23_sleep_duration: float = 2.0
    double_sign_check_height: int = 0
    # batch-first vote verification: stage gossip votes into device batches
    # (VoteSet.add_pending/flush) instead of serial per-vote verification
    batch_vote_verification: bool = False
    # flush a staged batch once it reaches this many votes (flushes also
    # happen at speculative quorum boundaries and on timeouts)
    vote_batch_flush_size: int = 128
    # compact vote-set reconciliation (consensus/reactor.py RECON channel):
    # periodically send peers one VoteSummary frame (both vote bitmaps for
    # the current height/round) so per-vote HasVote announcements lost to
    # drops/full queues/churn are repaired in bulk and peers stop sending
    # votes we already have. Negotiated per peer (a peer that never
    # advertises the channel just gets classic full gossip) and checksum-
    # guarded (a corrupt summary is ignored and counted, never applied).
    gossip_vote_summaries: bool = True
    # summary send cadence per peer; summaries are skipped while the vote
    # view is unchanged, so a short interval costs little on a quiet net
    vote_summary_interval: float = 0.5
    # TEST/E2E ONLY: run this validator adversarially (consensus/byzantine.py
    # behaviors: equivocation | amnesia | silence | flood). The node swaps
    # its privval for an unguarded signer — never set this in production.
    byzantine: str = ""

    def propose_timeout(self, round_: int) -> float:
        return self.timeout_propose + self.timeout_propose_delta * round_

    def prevote_timeout(self, round_: int) -> float:
        return self.timeout_prevote + self.timeout_prevote_delta * round_

    def precommit_timeout(self, round_: int) -> float:
        return self.timeout_precommit + self.timeout_precommit_delta * round_


def test_consensus_config() -> ConsensusConfig:
    """Millisecond-scale timeouts for in-process multi-validator tests
    (reference: config.TestConsensusConfig)."""
    return ConsensusConfig(
        timeout_propose=0.12,
        timeout_propose_delta=0.05,
        timeout_prevote=0.06,
        timeout_prevote_delta=0.03,
        timeout_precommit=0.06,
        timeout_precommit_delta=0.03,
        timeout_commit=0.03,
        skip_timeout_commit=True,
        peer_gossip_sleep_duration=0.005,
        peer_query_maj23_sleep_duration=0.25,
        vote_summary_interval=0.02,
    )
