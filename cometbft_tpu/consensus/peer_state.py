"""Per-peer consensus state tracking.

Reference: consensus/reactor.go:1069 PeerState +
consensus/types/peer_round_state.go. The gossip routines consult this to
decide what the peer still needs (parts, votes, proposal); Receive handlers
update it from the peer's own announcements. Single event loop — no locks
(the reference needs a mutex because goroutines race; reactor.go:1075).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

from cometbft_tpu.consensus import messages as M
from cometbft_tpu.consensus.round_state import RoundStepType
from cometbft_tpu.libs.bits import BitArray
from cometbft_tpu.types.basic import PartSetHeader, SignedMsgType
from cometbft_tpu.types.proposal import Proposal
from cometbft_tpu.types.vote import Vote
from cometbft_tpu.utils import cmttime


@dataclass
class PeerRoundState:
    """consensus/types/peer_round_state.go:9-42."""

    height: int = 0
    round_: int = -1
    step: RoundStepType = RoundStepType.NEW_HEIGHT
    start_time: cmttime.Timestamp = field(default_factory=cmttime.Timestamp.zero)
    proposal: bool = False
    proposal_block_part_set_header: PartSetHeader = field(default_factory=PartSetHeader)
    proposal_block_parts: BitArray | None = None
    proposal_pol_round: int = -1
    proposal_pol: BitArray | None = None
    prevotes: BitArray | None = None
    precommits: BitArray | None = None
    last_commit_round: int = -1
    last_commit: BitArray | None = None
    catchup_commit_round: int = -1
    catchup_commit: BitArray | None = None


class PeerState:
    def __init__(self, peer_id: str):
        self.peer_id = peer_id
        self.prs = PeerRoundState()

    # -------------------------------------------------------------- queries

    def get_height(self) -> int:
        return self.prs.height

    def get_vote_bit_array(
        self, height: int, round_: int, type_: SignedMsgType
    ) -> Optional[BitArray]:
        """reactor.go:1220 getVoteBitArray."""
        prs = self.prs
        if height == prs.height:
            if round_ == prs.round_:
                return prs.prevotes if type_ == SignedMsgType.PREVOTE else prs.precommits
            if round_ == prs.catchup_commit_round and type_ == SignedMsgType.PRECOMMIT:
                return prs.catchup_commit
            if round_ == prs.proposal_pol_round and type_ == SignedMsgType.PREVOTE:
                return prs.proposal_pol
            return None
        if height == prs.height - 1:
            if round_ == prs.last_commit_round and type_ == SignedMsgType.PRECOMMIT:
                return prs.last_commit
            return None
        return None

    # -------------------------------------------------------------- updates

    def set_has_proposal(self, proposal: Proposal) -> None:
        """reactor.go:1127."""
        prs = self.prs
        if prs.height != proposal.height or prs.round_ != proposal.round_:
            return
        if prs.proposal:
            return
        prs.proposal = True
        if prs.proposal_block_parts is not None:
            return  # NewValidBlock already set it
        prs.proposal_block_part_set_header = proposal.block_id.part_set_header
        prs.proposal_block_parts = BitArray(proposal.block_id.part_set_header.total)
        prs.proposal_pol_round = proposal.pol_round
        prs.proposal_pol = None  # until ProposalPOLMessage arrives

    def init_proposal_block_parts(self, header: PartSetHeader) -> None:
        """reactor.go:1147."""
        if self.prs.proposal_block_parts is not None:
            return
        self.prs.proposal_block_part_set_header = header
        self.prs.proposal_block_parts = BitArray(header.total)

    def set_has_proposal_block_part(self, height: int, round_: int, index: int) -> None:
        """reactor.go:1159."""
        prs = self.prs
        if prs.height != height or prs.round_ != round_:
            return
        if prs.proposal_block_parts is None:
            prs.proposal_block_parts = BitArray(index + 1)
        if index < prs.proposal_block_parts.size():
            prs.proposal_block_parts.set_index(index, True)

    def set_has_vote(self, height: int, round_: int, type_: SignedMsgType, index: int) -> None:
        """reactor.go:1288 setHasVote."""
        ba = self.get_vote_bit_array(height, round_, type_)
        if ba is not None and 0 <= index < ba.size():
            ba.set_index(index, True)

    def ensure_vote_bit_arrays(self, height: int, num_validators: int) -> None:
        """reactor.go:1249 EnsureVoteBitArrays."""
        prs = self.prs
        if prs.height == height:
            if prs.prevotes is None:
                prs.prevotes = BitArray(num_validators)
            if prs.precommits is None:
                prs.precommits = BitArray(num_validators)
            if prs.catchup_commit is None:
                prs.catchup_commit = BitArray(num_validators)
            if prs.proposal_pol is None:
                prs.proposal_pol = BitArray(num_validators)
        elif prs.height == height + 1:
            if prs.last_commit is None:
                prs.last_commit = BitArray(num_validators)

    def ensure_catchup_commit_round(self, height: int, round_: int, num_validators: int) -> None:
        """reactor.go:1233."""
        prs = self.prs
        if prs.height != height:
            return
        if prs.catchup_commit_round == round_:
            return
        prs.catchup_commit_round = round_
        if round_ == prs.round_:
            prs.catchup_commit = prs.precommits
        else:
            prs.catchup_commit = BitArray(num_validators)

    # ------------------------------------------------------- vote picking

    def pick_vote_to_send(self, votes) -> Optional[Vote]:
        """reactor.go:1185 PickVoteToSend: a random verified vote the peer
        does not have. `votes` is any vote-set reader: size() +
        bit_array() + get_by_index() + .height/.round_/.signed_msg_type."""
        prs = self.prs
        if votes.size() == 0:
            return None
        height, round_, type_ = votes.height, votes.round_, votes.signed_msg_type
        # lazily init the peer's bit arrays from the vote set's shape
        # (reactor.go:1185-1204: ensureCatchupCommitRound + ensureVoteBitArrays)
        if type_ == SignedMsgType.PRECOMMIT and height == prs.height and round_ != prs.round_:
            self.ensure_catchup_commit_round(height, round_, votes.size())
        self.ensure_vote_bit_arrays(height, votes.size())
        ps_votes = self.get_vote_bit_array(height, round_, type_)
        if ps_votes is None:
            return None
        gap = votes.bit_array().sub(ps_votes)
        idx, ok = gap.pick_random()
        if not ok:
            return None
        return votes.get_by_index(idx)

    # ------------------------------------------------- message application

    def apply_new_round_step(self, msg: M.NewRoundStepMessage) -> None:
        """reactor.go:1313 ApplyNewRoundStepMessage."""
        prs = self.prs
        # ignore stale announcements
        if (
            msg.height < prs.height
            or (msg.height == prs.height and msg.round_ < prs.round_)
            or (
                msg.height == prs.height
                and msg.round_ == prs.round_
                and msg.step < int(prs.step)
            )
        ):
            return
        psh_round = prs.round_
        ps_catchup_round = prs.catchup_commit_round
        ps_precommits = prs.precommits
        start_height, start_round = prs.height, prs.round_

        prs.height = msg.height
        prs.round_ = msg.round_
        prs.step = RoundStepType(msg.step)
        prs.start_time = cmttime.now().add_seconds(-msg.seconds_since_start_time)

        if start_height != msg.height or start_round != msg.round_:
            prs.proposal = False
            prs.proposal_block_part_set_header = PartSetHeader()
            prs.proposal_block_parts = None
            prs.proposal_pol_round = -1
            prs.proposal_pol = None
            prs.prevotes = None
            prs.precommits = None
        if start_height == msg.height and start_round != msg.round_ and msg.round_ == ps_catchup_round:
            # peer caught up to the round we tracked as its catchup commit
            prs.precommits = prs.catchup_commit
        if start_height != msg.height:
            # shift precommits to last_commit
            if start_height == msg.height - 1 and psh_round == msg.last_commit_round:
                prs.last_commit_round = msg.last_commit_round
                prs.last_commit = ps_precommits
            else:
                prs.last_commit_round = msg.last_commit_round
                prs.last_commit = None
            prs.catchup_commit_round = -1
            prs.catchup_commit = None

    def apply_new_valid_block(self, msg: M.NewValidBlockMessage) -> None:
        """reactor.go:1370."""
        prs = self.prs
        if prs.height != msg.height:
            return
        if prs.round_ != msg.round_ and not msg.is_commit:
            return
        prs.proposal_block_part_set_header = msg.block_part_set_header
        prs.proposal_block_parts = msg.block_parts

    def apply_proposal_pol(self, msg: M.ProposalPOLMessage) -> None:
        """reactor.go:1389."""
        prs = self.prs
        if prs.height != msg.height:
            return
        if prs.proposal_pol_round != msg.proposal_pol_round:
            return
        prs.proposal_pol = msg.proposal_pol

    def apply_has_vote(self, msg: M.HasVoteMessage) -> None:
        """reactor.go:1402."""
        if self.prs.height != msg.height:
            return
        self.set_has_vote(msg.height, msg.round_, msg.type_, msg.index)

    def apply_vote_set_bits(self, msg: M.VoteSetBitsMessage, our_votes: BitArray | None) -> None:
        """reactor.go:1412: if we know our votes for that block id, the
        peer's claimed bits are OR'd restricted to what it can prove;
        otherwise taken as-is."""
        ba = self.get_vote_bit_array(msg.height, msg.round_, msg.type_)
        if ba is None or msg.votes is None:
            return
        if our_votes is not None:
            other_votes = ba.sub(our_votes)
            has_votes = other_votes.or_(msg.votes)
            ba.update(has_votes)
        else:
            ba.update(msg.votes)
