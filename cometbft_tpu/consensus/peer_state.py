"""Per-peer consensus state tracking.

Reference: consensus/reactor.go:1069 PeerState +
consensus/types/peer_round_state.go. The gossip routines consult this to
decide what the peer still needs (parts, votes, proposal); Receive handlers
update it from the peer's own announcements. Single event loop — no locks
(the reference needs a mutex because goroutines race; reactor.go:1075).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

from cometbft_tpu.consensus import messages as M
from cometbft_tpu.consensus.round_state import RoundStepType
from cometbft_tpu.libs.bits import BitArray
from cometbft_tpu.types.basic import PartSetHeader, SignedMsgType
from cometbft_tpu.types.proposal import Proposal
from cometbft_tpu.types.vote import Vote
from cometbft_tpu.utils import cmttime


@dataclass
class PeerRoundState:
    """consensus/types/peer_round_state.go:9-42."""

    height: int = 0
    round_: int = -1
    step: RoundStepType = RoundStepType.NEW_HEIGHT
    start_time: cmttime.Timestamp = field(default_factory=cmttime.Timestamp.zero)
    proposal: bool = False
    proposal_block_part_set_header: PartSetHeader = field(default_factory=PartSetHeader)
    proposal_block_parts: BitArray | None = None
    proposal_pol_round: int = -1
    proposal_pol: BitArray | None = None
    prevotes: BitArray | None = None
    precommits: BitArray | None = None
    last_commit_round: int = -1
    last_commit: BitArray | None = None
    catchup_commit_round: int = -1
    catchup_commit: BitArray | None = None


class PeerState:
    def __init__(self, peer_id: str):
        self.peer_id = peer_id
        self.prs = PeerRoundState()
        # gossip accounting (fleet dimension): what this peer link cost.
        # votes_sent = votes our gossip routines pushed at the peer;
        # votes_recv_* = receiver-side classification of the peer's sends
        # (needed / already_had / stale); summaries_* = the reconciliation
        # plane. Rolled up by ConsensusReactor.gossip_accounting() into
        # net_telemetry — bounded by live peers, no metric labels.
        self.gossip: dict[str, int] = {
            "votes_sent": 0,
            "votes_recv": 0, "votes_recv_needed": 0,
            "votes_recv_already_had": 0, "votes_recv_stale": 0,
            "summaries_sent": 0, "summaries_applied": 0,
            "summaries_degraded": 0,
        }
        # set once when the peer turns out not to speak the RECON channel
        self.summary_unsupported = False
        # last summary signature sent, so an unchanged vote view is not
        # re-sent every interval: (height, round, prevote bytes, precommit
        # bytes)
        self.last_summary_sent: tuple | None = None

    # -------------------------------------------------------------- queries

    def get_height(self) -> int:
        return self.prs.height

    def get_vote_bit_array(
        self, height: int, round_: int, type_: SignedMsgType
    ) -> Optional[BitArray]:
        """reactor.go:1220 getVoteBitArray."""
        prs = self.prs
        if height == prs.height:
            if round_ == prs.round_:
                return prs.prevotes if type_ == SignedMsgType.PREVOTE else prs.precommits
            if round_ == prs.catchup_commit_round and type_ == SignedMsgType.PRECOMMIT:
                return prs.catchup_commit
            if round_ == prs.proposal_pol_round and type_ == SignedMsgType.PREVOTE:
                return prs.proposal_pol
            return None
        if height == prs.height - 1:
            if round_ == prs.last_commit_round and type_ == SignedMsgType.PRECOMMIT:
                return prs.last_commit
            return None
        return None

    # -------------------------------------------------------------- updates

    def set_has_proposal(self, proposal: Proposal) -> None:
        """reactor.go:1127."""
        prs = self.prs
        if prs.height != proposal.height or prs.round_ != proposal.round_:
            return
        if prs.proposal:
            return
        prs.proposal = True
        if prs.proposal_block_parts is not None:
            return  # NewValidBlock already set it
        prs.proposal_block_part_set_header = proposal.block_id.part_set_header
        prs.proposal_block_parts = BitArray(proposal.block_id.part_set_header.total)
        prs.proposal_pol_round = proposal.pol_round
        prs.proposal_pol = None  # until ProposalPOLMessage arrives

    def init_proposal_block_parts(self, header: PartSetHeader) -> None:
        """reactor.go:1147."""
        if self.prs.proposal_block_parts is not None:
            return
        self.prs.proposal_block_part_set_header = header
        self.prs.proposal_block_parts = BitArray(header.total)

    def set_has_proposal_block_part(self, height: int, round_: int, index: int) -> None:
        """reactor.go:1159."""
        prs = self.prs
        if prs.height != height or prs.round_ != round_:
            return
        if prs.proposal_block_parts is None:
            prs.proposal_block_parts = BitArray(index + 1)
        if index < prs.proposal_block_parts.size():
            prs.proposal_block_parts.set_index(index, True)

    def set_has_vote(self, height: int, round_: int, type_: SignedMsgType, index: int) -> None:
        """reactor.go:1288 setHasVote."""
        ba = self.get_vote_bit_array(height, round_, type_)
        if ba is not None and 0 <= index < ba.size():
            ba.set_index(index, True)

    def ensure_vote_bit_arrays(self, height: int, num_validators: int) -> None:
        """reactor.go:1249 EnsureVoteBitArrays."""
        prs = self.prs
        if prs.height == height:
            if prs.prevotes is None:
                prs.prevotes = BitArray(num_validators)
            if prs.precommits is None:
                prs.precommits = BitArray(num_validators)
            if prs.catchup_commit is None:
                prs.catchup_commit = BitArray(num_validators)
            if prs.proposal_pol is None:
                prs.proposal_pol = BitArray(num_validators)
        elif prs.height == height + 1:
            if prs.last_commit is None:
                prs.last_commit = BitArray(num_validators)

    def ensure_catchup_commit_round(self, height: int, round_: int, num_validators: int) -> None:
        """reactor.go:1233."""
        prs = self.prs
        if prs.height != height:
            return
        if prs.catchup_commit_round == round_:
            return
        prs.catchup_commit_round = round_
        if round_ == prs.round_:
            prs.catchup_commit = prs.precommits
        else:
            prs.catchup_commit = BitArray(num_validators)

    # ------------------------------------------------------- vote picking

    def pick_vote_to_send(self, votes) -> Optional[Vote]:
        """reactor.go:1185 PickVoteToSend: a random verified vote the peer
        does not have. `votes` is any vote-set reader: size() +
        bit_array() + get_by_index() + .height/.round_/.signed_msg_type."""
        prs = self.prs
        if votes.size() == 0:
            return None
        height, round_, type_ = votes.height, votes.round_, votes.signed_msg_type
        # lazily init the peer's bit arrays from the vote set's shape
        # (reactor.go:1185-1204: ensureCatchupCommitRound + ensureVoteBitArrays)
        if type_ == SignedMsgType.PRECOMMIT and height == prs.height and round_ != prs.round_:
            self.ensure_catchup_commit_round(height, round_, votes.size())
        self.ensure_vote_bit_arrays(height, votes.size())
        ps_votes = self.get_vote_bit_array(height, round_, type_)
        if ps_votes is None:
            return None
        gap = votes.bit_array().sub(ps_votes)
        idx, ok = gap.pick_random()
        if not ok:
            return None
        return votes.get_by_index(idx)

    # ------------------------------------------------- message application

    def apply_new_round_step(self, msg: M.NewRoundStepMessage) -> None:
        """reactor.go:1313 ApplyNewRoundStepMessage."""
        prs = self.prs
        # ignore stale announcements
        if (
            msg.height < prs.height
            or (msg.height == prs.height and msg.round_ < prs.round_)
            or (
                msg.height == prs.height
                and msg.round_ == prs.round_
                and msg.step < int(prs.step)
            )
        ):
            return
        psh_round = prs.round_
        ps_catchup_round = prs.catchup_commit_round
        ps_precommits = prs.precommits
        start_height, start_round = prs.height, prs.round_

        prs.height = msg.height
        prs.round_ = msg.round_
        prs.step = RoundStepType(msg.step)
        prs.start_time = cmttime.now().add_seconds(-msg.seconds_since_start_time)

        if start_height != msg.height or start_round != msg.round_:
            # RE-ARM the vote-summary send (PR 12 residual): the
            # send-first routine suppresses resends while OUR view is
            # unchanged, but a summary sent while this peer was on an
            # earlier round was dropped as "stale" on its side — when
            # the peer arrives at a new (height, round) the next summary
            # tick must send again so a multi-round height repairs the
            # peer's vote view for the CURRENT round, not just the round
            # it happened to be on at connect time.
            self.last_summary_sent = None
            prs.proposal = False
            prs.proposal_block_part_set_header = PartSetHeader()
            prs.proposal_block_parts = None
            prs.proposal_pol_round = -1
            prs.proposal_pol = None
            prs.prevotes = None
            prs.precommits = None
        if start_height == msg.height and start_round != msg.round_ and msg.round_ == ps_catchup_round:
            # peer caught up to the round we tracked as its catchup commit
            prs.precommits = prs.catchup_commit
        if start_height != msg.height:
            # shift precommits to last_commit
            if start_height == msg.height - 1 and psh_round == msg.last_commit_round:
                prs.last_commit_round = msg.last_commit_round
                prs.last_commit = ps_precommits
            else:
                prs.last_commit_round = msg.last_commit_round
                prs.last_commit = None
            prs.catchup_commit_round = -1
            prs.catchup_commit = None

    def apply_new_valid_block(self, msg: M.NewValidBlockMessage) -> None:
        """reactor.go:1370."""
        prs = self.prs
        if prs.height != msg.height:
            return
        if prs.round_ != msg.round_ and not msg.is_commit:
            return
        prs.proposal_block_part_set_header = msg.block_part_set_header
        prs.proposal_block_parts = msg.block_parts

    def apply_proposal_pol(self, msg: M.ProposalPOLMessage) -> None:
        """reactor.go:1389."""
        prs = self.prs
        if prs.height != msg.height:
            return
        if prs.proposal_pol_round != msg.proposal_pol_round:
            return
        prs.proposal_pol = msg.proposal_pol

    def apply_has_vote(self, msg: M.HasVoteMessage) -> None:
        """reactor.go:1402."""
        if self.prs.height != msg.height:
            return
        self.set_has_vote(msg.height, msg.round_, msg.type_, msg.index)

    def apply_vote_summary(self, msg: M.VoteSummaryMessage,
                           expected_size: int | None = None) -> str:
        """Compact vote-set reconciliation: merge the peer's whole vote
        view for (height, round) into its bit arrays in ONE step — the
        batch form of apply_has_vote. Returns "applied", "stale" (the
        summary is for a height/round we no longer track for this peer —
        ignored, not an error), or "shape" (bit sizes disagree with the
        arrays we track or with `expected_size`, the caller's validator
        count — degraded, ignored). Merging is a monotonic in-place OR:
        a reordered older summary can never erase has-vote knowledge,
        and aliases (catchup_commit may be the same object as
        precommits) stay consistent.

        `expected_size` guards the None-array window right after a round
        change: without it a peer could install an arbitrary-size bitmap
        (the crc32 is integrity, not authentication) that poisons this
        peer's bookkeeping for the whole height — later correct-size
        summaries would read as shape mismatches and set_has_vote would
        silently drop out-of-range indices."""
        prs = self.prs
        if prs.height != msg.height or prs.round_ != msg.round_:
            return "stale"
        pairs = [(bits, attr) for bits, attr in
                 ((msg.prevotes, "prevotes"), (msg.precommits, "precommits"))
                 if bits is not None]
        # validate every shape BEFORE mutating anything: a half-applied
        # summary would be a new corruption mode of its own
        for bits, attr in pairs:
            if expected_size is not None and bits.size() != expected_size:
                return "shape"
            cur = getattr(prs, attr)
            if cur is not None and cur.size() != bits.size():
                return "shape"
        for bits, attr in pairs:
            cur = getattr(prs, attr)
            if cur is None:
                setattr(prs, attr, bits.copy())
            else:
                cur.or_update(bits)
        self.gossip["summaries_applied"] += 1
        return "applied"

    def apply_vote_set_bits(self, msg: M.VoteSetBitsMessage, our_votes: BitArray | None) -> None:
        """reactor.go:1412: if we know our votes for that block id, the
        peer's claimed bits are OR'd restricted to what it can prove;
        otherwise taken as-is."""
        ba = self.get_vote_bit_array(msg.height, msg.round_, msg.type_)
        if ba is None or msg.votes is None:
            return
        if our_votes is not None:
            other_votes = ba.sub(our_votes)
            has_votes = other_votes.or_(msg.votes)
            ba.update(has_votes)
        else:
            ba.update(msg.votes)
