"""Consensus message types flowing through the state-machine queue.

Reference: consensus/reactor.go:1576-1592 message taxonomy; the subset the
state machine consumes (Proposal/BlockPart/Vote) plus the gossip-control
messages the reactor exchanges (NewRoundStep, HasVote, VoteSetMaj23, ...).
"""

from __future__ import annotations

from dataclasses import dataclass, field

from cometbft_tpu.libs.bits import BitArray
from cometbft_tpu.types.basic import BlockID, SignedMsgType
from cometbft_tpu.types.part_set import Part
from cometbft_tpu.types.proposal import Proposal
from cometbft_tpu.types.vote import Vote


@dataclass
class ProposalMessage:
    proposal: Proposal
    peer_id: str = ""


@dataclass
class BlockPartMessage:
    height: int
    round_: int
    part: Part
    peer_id: str = ""


@dataclass
class VoteMessage:
    vote: Vote
    peer_id: str = ""


# ---- reactor-level gossip control messages (consensus/reactor.go) ----


@dataclass
class NewRoundStepMessage:
    height: int
    round_: int
    step: int
    seconds_since_start_time: int = 0
    last_commit_round: int = -1


@dataclass
class NewValidBlockMessage:
    height: int
    round_: int
    block_part_set_header: object = None
    block_parts: BitArray | None = None
    is_commit: bool = False


@dataclass
class ProposalPOLMessage:
    height: int
    proposal_pol_round: int
    proposal_pol: BitArray | None = None


@dataclass
class HasVoteMessage:
    height: int
    round_: int
    type_: SignedMsgType = SignedMsgType.UNKNOWN
    index: int = -1


@dataclass
class VoteSetMaj23Message:
    height: int
    round_: int
    type_: SignedMsgType
    block_id: BlockID = field(default_factory=BlockID)


@dataclass
class VoteSetBitsMessage:
    height: int
    round_: int
    type_: SignedMsgType
    block_id: BlockID = field(default_factory=BlockID)
    votes: BitArray | None = None


@dataclass
class VoteSummaryMessage:
    """Compact vote-set reconciliation (no reference analog): one frame
    carrying BOTH vote-presence bitmaps for (height, round) — the batch
    form of per-vote HasVote announcements, so a peer whose HasVotes were
    lost (drops, full queues, churn) re-learns our whole vote view in one
    message and stops re-sending votes we already have. Rides its own
    channel (reactor.RECON_CHANNEL) so nodes that never negotiated it
    simply never see it, and carries an end-to-end checksum so a
    corrupted summary degrades to plain full gossip instead of poisoning
    the peer's bookkeeping."""

    height: int
    round_: int
    prevotes: BitArray | None = None
    precommits: BitArray | None = None
    checksum: int = 0
