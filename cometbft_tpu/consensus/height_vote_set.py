"""HeightVoteSet — prevote/precommit VoteSets for every round of one height.

Reference: consensus/types/height_vote_set.go. Tracks which peers claim
catch-up rounds (peer_catchup_rounds, max 2 per peer) so Byzantine peers
can't force unbounded round allocations.
"""

from __future__ import annotations

from cometbft_tpu.types.basic import SignedMsgType
from cometbft_tpu.types.validator import ValidatorSet
from cometbft_tpu.types.vote import Vote
from cometbft_tpu.types.vote_set import VoteSet


class ErrGotVoteFromUnwantedRound(Exception):
    pass


class HeightVoteSet:
    def __init__(self, chain_id: str, height: int, val_set: ValidatorSet,
                 extensions_enabled: bool = False, batch_flush_size: int = 128):
        self.chain_id = chain_id
        self.height = height
        self.val_set = val_set
        self.extensions_enabled = extensions_enabled
        self.batch_flush_size = batch_flush_size
        self.round_ = 0
        self._sets: dict[int, dict[str, VoteSet]] = {}
        self._peer_catchup_rounds: dict[str, list[int]] = {}
        self._add_round(0)

    def _add_round(self, round_: int) -> None:
        if round_ in self._sets:
            return
        # auto_flush off: ConsensusState drives flushes so it can observe
        # the per-vote results (events, threshold hooks, evidence)
        self._sets[round_] = {
            "prevote": VoteSet(self.chain_id, self.height, round_,
                               SignedMsgType.PREVOTE, self.val_set,
                               batch_flush_size=self.batch_flush_size,
                               auto_flush=False),
            "precommit": VoteSet(self.chain_id, self.height, round_,
                                 SignedMsgType.PRECOMMIT, self.val_set,
                                 extensions_enabled=self.extensions_enabled,
                                 batch_flush_size=self.batch_flush_size,
                                 auto_flush=False),
        }

    def set_round(self, round_: int) -> None:
        """Create vote sets up to round_+1 (catchup; height_vote_set.go:104)."""
        new_round = self.round_
        for r in range(self.round_, round_ + 2):
            self._add_round(r)
        self.round_ = round_

    def add_vote(self, vote: Vote, peer_id: str = "") -> bool:
        """height_vote_set.go:126-160: non-current rounds only allowed from
        peers with catchup quota."""
        if not self._is_wanted(vote.round_, peer_id):
            raise ErrGotVoteFromUnwantedRound(
                f"peer {peer_id} has sent a vote for round {vote.round_} != current {self.round_}"
            )
        self._add_round(vote.round_)
        vs = self._get(vote.round_, vote.type_)
        return vs.add_vote(vote)

    def add_pending(self, vote: Vote, peer_id: str = "") -> bool:
        """Batch-path analog of add_vote: same round gating, then stage the
        vote in the round's VoteSet for deferred device verification (the
        SURVEY §3.3 hot path)."""
        if not self._is_wanted(vote.round_, peer_id):
            raise ErrGotVoteFromUnwantedRound(
                f"peer {peer_id} has sent a vote for round {vote.round_} != current {self.round_}"
            )
        self._add_round(vote.round_)
        vs = self._get(vote.round_, vote.type_)
        return vs.add_pending(vote)

    def pending_sets(self) -> list[VoteSet]:
        """All VoteSets with staged (unflushed) votes, every round/type."""
        out = []
        for sets in self._sets.values():
            for vs in sets.values():
                if vs._pending:
                    out.append(vs)
        return out

    def _is_wanted(self, round_: int, peer_id: str) -> bool:
        if self.round_ <= round_ <= self.round_ + 1:
            return True
        if round_ in self._sets:
            return True
        rounds = self._peer_catchup_rounds.setdefault(peer_id, [])
        if round_ in rounds:
            return True
        if len(rounds) < 2:
            rounds.append(round_)
            return True
        return False

    def _get(self, round_: int, type_: SignedMsgType) -> VoteSet | None:
        sets = self._sets.get(round_)
        if sets is None:
            return None
        return sets["prevote" if type_ == SignedMsgType.PREVOTE else "precommit"]

    def prevotes(self, round_: int) -> VoteSet | None:
        return self._get(round_, SignedMsgType.PREVOTE)

    def precommits(self, round_: int) -> VoteSet | None:
        return self._get(round_, SignedMsgType.PRECOMMIT)

    def pol_info(self) -> tuple[int, object]:
        """Highest round with a prevote +2/3 majority (POLRound, POLBlockID)."""
        for r in sorted(self._sets.keys(), reverse=True):
            vs = self.prevotes(r)
            if vs is not None:
                bid, ok = vs.two_thirds_majority()
                if ok:
                    return r, bid
        return -1, None
