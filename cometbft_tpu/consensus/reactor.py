"""Consensus reactor: gossips proposals, block parts, and votes.

Reference: consensus/reactor.go. Four p2p channels (reactor.go:154-192):
  State 0x20        round-step announcements, HasVote, VoteSetMaj23
  Data 0x21         proposals + block parts (+ catchup parts)
  Vote 0x22         votes
  VoteSetBits 0x23  vote-presence bitmap exchange

Per peer, three routines (reactor.go:208-218): gossip_data (parts +
proposal), gossip_votes, query_maj23. Broadcasts ride the consensus
EventSwitch: every step change -> NewRoundStep (reactor.go:421), every
added vote -> HasVote (reactor.go:466).

The state machine itself never touches the network (SURVEY §1): inbound
messages go through cs.add_*_from_peer queues; outbound gossip reads the
shared RoundState + per-peer PeerState.
"""

from __future__ import annotations

import asyncio

from cometbft_tpu.consensus import messages as M
from cometbft_tpu.consensus import reactor_codec as codec
from cometbft_tpu.consensus import timeline
from cometbft_tpu.libs import linkmodel
from cometbft_tpu.consensus.peer_state import PeerState
from cometbft_tpu.consensus.round_state import RoundStepType
from cometbft_tpu.consensus.state import ConsensusState
from cometbft_tpu.libs import log as cmtlog
from cometbft_tpu.libs.bits import BitArray
from cometbft_tpu.p2p.base_reactor import Envelope, Reactor
from cometbft_tpu.p2p.conn.connection import ChannelDescriptor
from cometbft_tpu.types.basic import SignedMsgType
from cometbft_tpu.utils import cmttime

STATE_CHANNEL = 0x20
DATA_CHANNEL = 0x21
VOTE_CHANNEL = 0x22
VOTE_SET_BITS_CHANNEL = 0x23
# compact vote-set reconciliation (framework extension, no reference
# analog): VoteSummary frames ride their OWN channel so support is
# negotiated in the p2p handshake's channel list — a peer that never
# advertises 0x24 simply never receives a summary and gets classic full
# gossip (the mixed-fleet degradation path; an unknown frame would
# otherwise cost the peer its connection)
RECON_CHANNEL = 0x24

PEER_STATE_KEY = "consensus.peer_state"


class _CommitVoteSource:
    """Adapter letting pick_vote_to_send serve votes out of a stored Commit
    (the reference's Commit-implements-VoteSetReader, types/block.go:846)."""

    def __init__(self, commit):
        self.commit = commit
        self.height = commit.height
        self.round_ = commit.round_
        self.signed_msg_type = SignedMsgType.PRECOMMIT

    def size(self) -> int:
        return len(self.commit.signatures)

    def bit_array(self) -> BitArray:
        ba = BitArray(len(self.commit.signatures))
        for i, cs in enumerate(self.commit.signatures):
            ba.set_index(i, bool(cs.signature))
        return ba

    def get_by_index(self, idx: int):
        cs = self.commit.signatures[idx]
        if not cs.signature:
            return None
        return self.commit.get_vote(idx)


class ConsensusReactor(Reactor):
    def __init__(
        self,
        cs: ConsensusState,
        wait_sync: bool = False,
        logger: cmtlog.Logger | None = None,
    ):
        super().__init__("Consensus", logger)
        self.cs = cs
        self.wait_sync = wait_sync
        # keyed by peer OBJECT: a replaced duplicate conn's teardown must
        # not cancel the replacement's routines (same node id)
        self._peer_tasks: dict[object, list[asyncio.Task]] = {}
        self._subscribed = False

    # ------------------------------------------------------------- channels

    def get_channels(self) -> list[ChannelDescriptor]:
        """reactor.go:154-192 (+ the negotiated reconciliation channel)."""
        chans = [
            ChannelDescriptor(id=STATE_CHANNEL, priority=6, send_queue_capacity=64),
            ChannelDescriptor(id=DATA_CHANNEL, priority=10, send_queue_capacity=64,
                              recv_message_capacity=1 << 22),
            ChannelDescriptor(id=VOTE_CHANNEL, priority=7, send_queue_capacity=256),
            ChannelDescriptor(id=VOTE_SET_BITS_CHANNEL, priority=1, send_queue_capacity=8),
        ]
        if getattr(self.cs.config, "gossip_vote_summaries", False):
            # advertising the channel IS the capability announcement
            chans.append(ChannelDescriptor(
                id=RECON_CHANNEL, priority=2, send_queue_capacity=16))
        return chans

    # ------------------------------------------------------------ lifecycle

    async def on_start(self) -> None:
        self._subscribe_events()
        if not self.wait_sync:
            await self.cs.start()

    async def on_stop(self) -> None:
        for tasks in self._peer_tasks.values():
            for t in tasks:
                t.cancel()
        self._peer_tasks.clear()
        if self.cs.is_running:
            await self.cs.stop()

    async def switch_to_consensus(self, state) -> None:
        """blocksync -> consensus handoff (reactor.go:115 SwitchToConsensus).
        sync_to_state also reconstructs LastCommit so this node can propose
        (reference calls reconstructLastCommit here)."""
        self.cs.sync_to_state(state)
        self.wait_sync = False
        await self.cs.start()

    def _subscribe_events(self) -> None:
        """reactor.go:390 subscribeToBroadcastEvents."""
        if self._subscribed or self.cs.event_switch is None:
            return
        self._subscribed = True
        es = self.cs.event_switch
        es.add_listener("cons-reactor", "NewRoundStep",
                        lambda rs: self._broadcast_new_round_step(rs))
        es.add_listener("cons-reactor", "Vote",
                        lambda vote: self._broadcast_has_vote(vote))
        es.add_listener("cons-reactor", "ValidBlock",
                        lambda rs: self._broadcast_new_valid_block(rs))

    # ----------------------------------------------------------- broadcasts

    def _broadcast(self, chan_id: int, msg_bytes: bytes) -> None:
        if self.switch is not None:
            self.switch.broadcast(chan_id, msg_bytes)

    def _new_round_step_msg(self, rs) -> M.NewRoundStepMessage:
        elapsed = max(0, (cmttime.now().unix_ns() - rs.start_time.unix_ns()) // 10**9)
        return M.NewRoundStepMessage(
            height=rs.height,
            round_=rs.round_,
            step=int(rs.step),
            seconds_since_start_time=int(elapsed),
            last_commit_round=rs.last_commit.round_ if rs.last_commit is not None else -1,
        )

    def _broadcast_new_round_step(self, rs) -> None:
        """reactor.go:421 broadcastNewRoundStepMessage."""
        self._broadcast(STATE_CHANNEL, codec.encode(self._new_round_step_msg(rs)))

    def _broadcast_new_valid_block(self, rs) -> None:
        """reactor.go:434."""
        if rs.proposal_block_parts is None:
            return
        msg = M.NewValidBlockMessage(
            height=rs.height,
            round_=rs.round_,
            block_part_set_header=rs.proposal_block_parts.header(),
            block_parts=rs.proposal_block_parts.bit_array(),
            is_commit=rs.step == RoundStepType.COMMIT,
        )
        self._broadcast(STATE_CHANNEL, codec.encode(msg))

    def _broadcast_has_vote(self, vote) -> None:
        """reactor.go:466."""
        msg = M.HasVoteMessage(
            height=vote.height, round_=vote.round_, type_=vote.type_,
            index=vote.validator_index,
        )
        self._broadcast(STATE_CHANNEL, codec.encode(msg))

    # ------------------------------------------------------- peer lifecycle

    def init_peer(self, peer) -> None:
        peer.set(PEER_STATE_KEY, PeerState(peer.id))

    async def add_peer(self, peer) -> None:
        """reactor.go:208-230: start gossip routines + announce our step."""
        ps: PeerState = peer.get(PEER_STATE_KEY)
        loop = asyncio.get_running_loop()
        tasks = [
            loop.create_task(self._gossip_data_routine(peer, ps)),
            loop.create_task(self._gossip_votes_routine(peer, ps)),
            loop.create_task(self._query_maj23_routine(peer, ps)),
        ]
        if getattr(self.cs.config, "gossip_vote_summaries", False):
            tasks.append(
                loop.create_task(self._gossip_summary_routine(peer, ps)))
        self._peer_tasks[peer] = tasks
        if not self.wait_sync:
            peer.try_send(
                STATE_CHANNEL, codec.encode(self._new_round_step_msg(self.cs.rs))
            )

    async def remove_peer(self, peer, reason) -> None:
        for t in self._peer_tasks.pop(peer, []):
            t.cancel()

    # --------------------------------------------------------------- receive

    async def receive(self, e: Envelope) -> None:
        """reactor.go:241-385."""
        peer = e.src
        ps: PeerState = peer.get(PEER_STATE_KEY)
        if ps is None:
            return
        if e.channel_id == RECON_CHANNEL:
            # the reconciliation channel is advisory: any malformed frame
            # (codec mismatch, truncation, checksum failure) is COUNTED
            # and ignored — full gossip continues untouched, never a
            # liveness loss and never a banned peer
            self._receive_vote_summary(e.message, ps)
            return
        msg = codec.decode(e.message)
        rs = self.cs.rs

        if e.channel_id == STATE_CHANNEL:
            if isinstance(msg, M.NewRoundStepMessage):
                if msg.height < self.cs.state.initial_height:
                    raise ValueError("peer claims height below initial height")
                ps.apply_new_round_step(msg)
            elif isinstance(msg, M.NewValidBlockMessage):
                ps.apply_new_valid_block(msg)
            elif isinstance(msg, M.HasVoteMessage):
                ps.apply_has_vote(msg)
            elif isinstance(msg, M.VoteSetMaj23Message):
                await self._handle_vote_set_maj23(peer, ps, msg)
            else:
                raise ValueError(f"unexpected message on state channel: {type(msg)}")

        elif e.channel_id == DATA_CHANNEL:
            if self.wait_sync:
                return
            if isinstance(msg, M.ProposalMessage):
                ps.set_has_proposal(msg.proposal)
                await self.cs.add_proposal_from_peer(msg.proposal, peer.id)
            elif isinstance(msg, M.ProposalPOLMessage):
                ps.apply_proposal_pol(msg)
            elif isinstance(msg, M.BlockPartMessage):
                ps.set_has_proposal_block_part(msg.height, msg.round_, msg.part.index)
                await self.cs.add_block_part_from_peer(
                    msg.height, msg.round_, msg.part, peer.id
                )
            else:
                raise ValueError(f"unexpected message on data channel: {type(msg)}")

        elif e.channel_id == VOTE_CHANNEL:
            if self.wait_sync:
                return
            if isinstance(msg, M.VoteMessage):
                height = rs.height
                valsize = len(rs.validators) if rs.validators else 0
                last_size = rs.last_commit.size() if rs.last_commit is not None else 0
                ps.ensure_vote_bit_arrays(height, valsize)
                ps.ensure_vote_bit_arrays(height - 1, last_size)
                self._account_vote_received(ps, rs, msg.vote)
                if timeline.enabled() and msg.vote.height == rs.height:
                    # vote-timestamp delta cross-check for the skew model:
                    # only current-height votes (a gossiped old vote's age
                    # would read as clock offset)
                    linkmodel.skew().observe_vote(
                        peer.id, msg.vote.timestamp.unix_ns(),
                        cmttime.now().unix_ns(),
                        getattr(peer.mconn, "_ping_rtt_s", 0.0))
                ps.set_has_vote(
                    msg.vote.height, msg.vote.round_, msg.vote.type_,
                    msg.vote.validator_index,
                )
                await self.cs.add_vote_from_peer(msg.vote, peer.id)
            else:
                raise ValueError(f"unexpected message on vote channel: {type(msg)}")

        elif e.channel_id == VOTE_SET_BITS_CHANNEL:
            if self.wait_sync:
                return
            if isinstance(msg, M.VoteSetBitsMessage):
                our_votes = None
                if rs.height == msg.height and rs.votes is not None:
                    vs = (
                        rs.votes.prevotes(msg.round_)
                        if msg.type_ == SignedMsgType.PREVOTE
                        else rs.votes.precommits(msg.round_)
                    )
                    if vs is not None:
                        our_votes = vs.bit_array_by_block_id(msg.block_id)
                ps.apply_vote_set_bits(msg, our_votes)
            else:
                raise ValueError(f"unexpected message on vote-set-bits channel: {type(msg)}")

    # ------------------------------------------------- gossip accounting

    def _gossip_metric(self, name: str, *labels) -> None:
        m = getattr(self.cs, "metrics", None)
        if m is None:
            return
        counter = getattr(m, name, None)
        if counter is None:
            return
        if labels:
            counter.labels(*labels).inc()
        else:
            counter.inc()

    def _account_vote_received(self, ps: PeerState, rs, vote) -> None:
        """Receiver-side gossip accounting: did we NEED this vote?
        needed = it can still advance our view; already_had = the
        matching vote set already holds this validator's vote (the peer
        wasted a send); stale = for a height we committed past. The
        ratio of received to needed IS the vote-amplification number the
        fleet metrics grade."""
        status = "needed"
        if vote.height == rs.height:
            vs = None
            if rs.votes is not None:
                vs = (rs.votes.prevotes(vote.round_)
                      if vote.type_ == SignedMsgType.PREVOTE
                      else rs.votes.precommits(vote.round_))
            # get_by_index, not bit_array(): the latter copies the whole
            # array per received vote on the hottest p2p path. Bounds
            # guarded here — a malformed index is add_vote's problem to
            # reject, not classification's to crash on (raw list
            # indexing would wrap negatives and raise past the end)
            idx = vote.validator_index
            if (vs is not None and 0 <= idx < vs.size()
                    and vs.get_by_index(idx) is not None):
                status = "already_had"
        elif vote.height == rs.height - 1:
            lc = rs.last_commit
            idx = vote.validator_index
            if (lc is not None and vote.type_ == SignedMsgType.PRECOMMIT
                    and vote.round_ == lc.round_ and 0 <= idx < lc.size()
                    and lc.get_by_index(idx) is not None):
                status = "already_had"
        elif vote.height < rs.height - 1:
            status = "stale"
        g = ps.gossip
        g["votes_recv"] += 1
        g[f"votes_recv_{status}"] += 1
        self._gossip_metric("gossip_votes_received", status)

    def gossip_accounting(self) -> dict:
        """The vote-amplification rollup net_telemetry serves: per-peer
        sent/received/needed splits (bounded by live peers) plus totals
        and the headline `votes_per_vote_needed` ratio — received votes
        per vote that actually advanced this node's view (1.0 = perfect
        reconciliation; the gap above 1.0 is pure amplification)."""
        per_peer: dict[str, dict] = {}
        totals = {"votes_sent": 0, "votes_recv": 0, "votes_recv_needed": 0,
                  "votes_recv_already_had": 0, "votes_recv_stale": 0,
                  "summaries_sent": 0, "summaries_applied": 0,
                  "summaries_degraded": 0}
        sw = self.switch
        peers = list(sw.peers.values()) if sw is not None else []
        for peer in peers:
            ps = peer.get(PEER_STATE_KEY)
            if ps is None:
                continue
            row = dict(ps.gossip)
            row["summary_unsupported"] = ps.summary_unsupported
            per_peer[peer.id[:10]] = row
            for k in totals:
                totals[k] += ps.gossip.get(k, 0)
        needed = totals["votes_recv_needed"]
        return {
            "per_peer": per_peer,
            "totals": totals,
            "votes_per_vote_needed": (
                round(totals["votes_recv"] / needed, 3) if needed else None),
        }

    # ------------------------------------------- vote-set reconciliation

    def _receive_vote_summary(self, raw: bytes, ps: PeerState) -> None:
        """Apply one reconciliation frame with the full degradation
        ladder: codec error -> degraded_codec, wrong message type ->
        degraded_codec, checksum mismatch -> degraded_checksum, bit-size
        disagreement -> degraded_shape; stale summaries are ignored
        silently. Degradation NEVER raises — the worst outcome of a bad
        summary is the full gossip we already run."""
        try:
            msg = codec.decode(raw)
        except Exception:  # noqa: BLE001 - corrupt frame, count and drop
            ps.gossip["summaries_degraded"] += 1
            self._gossip_metric("gossip_summaries", "degraded_codec")
            return
        if not isinstance(msg, M.VoteSummaryMessage):
            ps.gossip["summaries_degraded"] += 1
            self._gossip_metric("gossip_summaries", "degraded_codec")
            return
        want = codec.vote_summary_checksum(
            msg.height, msg.round_, msg.prevotes, msg.precommits)
        if msg.checksum != want:
            ps.gossip["summaries_degraded"] += 1
            self._gossip_metric("gossip_summaries", "degraded_checksum")
            return
        # when the summary is for OUR height we know the validator count
        # and pin the bitmap size to it (crc32 is integrity, not
        # authentication — a forged size must not install); for other
        # heights the peer's existing arrays gate the shape
        rs = self.cs.rs
        expected = (len(rs.validators)
                    if msg.height == rs.height and rs.validators else None)
        verdict = ps.apply_vote_summary(msg, expected_size=expected)
        if verdict == "applied":
            self._gossip_metric("gossip_summaries", "applied")
        elif verdict == "shape":
            ps.gossip["summaries_degraded"] += 1
            self._gossip_metric("gossip_summaries", "degraded_shape")

    async def _gossip_summary_routine(self, peer, ps: PeerState) -> None:
        """Periodically push one VoteSummary frame at the peer: both vote
        bitmaps for our current (height, round). Skips resends while the
        view is unchanged. A peer that never advertised RECON_CHANNEL is
        detected once and the routine exits — that peer runs on classic
        full gossip (the mixed-fleet path)."""
        interval = getattr(self.cs.config, "vote_summary_interval", 0.5)
        if RECON_CHANNEL not in (peer.node_info.channels or b""):
            ps.summary_unsupported = True
            self._gossip_metric("gossip_summaries", "peer_unsupported")
            return
        try:
            while peer.is_running:
                # send-first, THEN sleep: a freshly (re)connected peer —
                # a churn storm makes many — learns our whole vote view
                # in its first gossip exchange instead of re-sending us
                # ~2 vote sets during the first interval
                if self.wait_sync:
                    await asyncio.sleep(interval)
                    continue
                rs = self.cs.rs
                if rs.votes is None:
                    await asyncio.sleep(interval)
                    continue
                pv = rs.votes.prevotes(rs.round_)
                pc = rs.votes.precommits(rs.round_)
                if pv is None and pc is None:
                    await asyncio.sleep(interval)
                    continue
                pv_bits = pv.bit_array() if pv is not None else None
                pc_bits = pc.bit_array() if pc is not None else None
                sig = (rs.height, rs.round_,
                       pv_bits.to_bytes() if pv_bits is not None else b"",
                       pc_bits.to_bytes() if pc_bits is not None else b"")
                if sig != ps.last_summary_sent:
                    msg = M.VoteSummaryMessage(
                        height=rs.height, round_=rs.round_,
                        prevotes=pv_bits, precommits=pc_bits,
                        checksum=codec.vote_summary_checksum(
                            rs.height, rs.round_, pv_bits, pc_bits),
                    )
                    if peer.try_send(RECON_CHANNEL, codec.encode(msg)):
                        ps.last_summary_sent = sig
                        ps.gossip["summaries_sent"] += 1
                        self._gossip_metric("gossip_summaries", "sent")
                await asyncio.sleep(interval)
        except asyncio.CancelledError:
            raise
        except Exception as e:  # noqa: BLE001 - reconciliation is advisory
            self.logger.error("gossip_summary routine failed",
                              peer=peer.id[:10], err=str(e))

    async def _handle_vote_set_maj23(self, peer, ps: PeerState, msg: M.VoteSetMaj23Message) -> None:
        """reactor.go:316-361: record the peer's +2/3 claim, answer with our
        vote bits for that BlockID."""
        rs = self.cs.rs
        if rs.height != msg.height or rs.votes is None:
            return
        vs = (
            rs.votes.prevotes(msg.round_)
            if msg.type_ == SignedMsgType.PREVOTE
            else rs.votes.precommits(msg.round_)
        )
        if vs is None:
            return
        vs.set_peer_maj23(peer.id, msg.block_id)
        our_votes = vs.bit_array_by_block_id(msg.block_id)
        resp = M.VoteSetBitsMessage(
            height=msg.height, round_=msg.round_, type_=msg.type_,
            block_id=msg.block_id, votes=our_votes,
        )
        peer.try_send(VOTE_SET_BITS_CHANNEL, codec.encode(resp))

    # ------------------------------------------------------- gossip routines

    async def _gossip_data_routine(self, peer, ps: PeerState) -> None:
        """reactor.go:569-650."""
        sleep = self.cs.config.peer_gossip_sleep_duration
        try:
            while peer.is_running:
                if self.wait_sync:
                    await asyncio.sleep(sleep)
                    continue
                rs = self.cs.rs
                prs = ps.prs

                # 1. send a block part for the current proposal
                if (
                    rs.proposal_block_parts is not None
                    and prs.proposal_block_parts is not None
                    and rs.proposal_block_parts.has_header(prs.proposal_block_part_set_header)
                ):
                    gap = rs.proposal_block_parts.bit_array().sub(prs.proposal_block_parts)
                    index, ok = gap.pick_random()
                    if ok:
                        part = rs.proposal_block_parts.get_part(index)
                        sent = await peer.send(
                            DATA_CHANNEL,
                            codec.encode(M.BlockPartMessage(
                                height=rs.height, round_=rs.round_, part=part)),
                        )
                        if sent:
                            ps.set_has_proposal_block_part(prs.height, prs.round_, index)
                        else:
                            # a stopped mconn fails the send WITHOUT ever
                            # suspending; continuing unthrottled would spin
                            # the (cooperative) event loop and starve every
                            # other task — observed as a whole-node freeze
                            # in the restart-all e2e perturbation
                            await asyncio.sleep(sleep)
                        continue

                # 2. peer is on an older height: serve committed-block parts
                if (
                    prs.height != 0
                    and rs.height != prs.height
                    and self.cs.block_store.base() <= prs.height <= self.cs.block_store.height()
                ):
                    await self._gossip_catchup_part(peer, ps)
                    await asyncio.sleep(sleep)
                    continue

                # 3. different height/round: nothing to send
                if rs.height != prs.height or rs.round_ != prs.round_:
                    await asyncio.sleep(sleep)
                    continue

                # 4. send the proposal (+POL)
                if rs.proposal is not None and not prs.proposal:
                    await peer.send(
                        DATA_CHANNEL, codec.encode(M.ProposalMessage(proposal=rs.proposal))
                    )
                    ps.set_has_proposal(rs.proposal)
                    if 0 <= rs.proposal.pol_round:
                        pol = rs.votes.prevotes(rs.proposal.pol_round)
                        if pol is not None:
                            await peer.send(
                                DATA_CHANNEL,
                                codec.encode(M.ProposalPOLMessage(
                                    height=rs.height,
                                    proposal_pol_round=rs.proposal.pol_round,
                                    proposal_pol=pol.bit_array(),
                                )),
                            )
                    continue
                await asyncio.sleep(sleep)
        except asyncio.CancelledError:
            raise
        except Exception as e:  # noqa: BLE001 - gossip must not crash the reactor
            self.logger.error("gossip_data routine failed", peer=peer.id[:10], err=str(e))

    async def _gossip_catchup_part(self, peer, ps: PeerState) -> None:
        """reactor.go:652-735 gossipDataForCatchup."""
        prs = ps.prs
        meta = self.cs.block_store.load_block_meta(prs.height)
        if meta is None:
            return
        # make sure the peer's part-set header matches the stored block
        if prs.proposal_block_parts is None:
            ps.init_proposal_block_parts(meta.block_id.part_set_header)
            return
        if prs.proposal_block_part_set_header != meta.block_id.part_set_header:
            return
        # any part index the peer lacks
        index, ok = prs.proposal_block_parts.not_().pick_random()
        if not ok:
            return
        part = self.cs.block_store.load_block_part(prs.height, index)
        if part is None:
            return
        sent = await peer.send(
            DATA_CHANNEL,
            codec.encode(M.BlockPartMessage(height=prs.height, round_=prs.round_, part=part)),
        )
        if sent:
            ps.set_has_proposal_block_part(prs.height, prs.round_, index)

    async def _gossip_votes_routine(self, peer, ps: PeerState) -> None:
        """reactor.go:737-830."""
        sleep = self.cs.config.peer_gossip_sleep_duration
        try:
            while peer.is_running:
                if self.wait_sync:
                    await asyncio.sleep(sleep)
                    continue
                rs = self.cs.rs
                prs = ps.prs

                if rs.height == prs.height:
                    if await self._gossip_votes_for_height(peer, ps):
                        continue
                # peer one height behind: our last commit has what it needs
                elif prs.height != 0 and rs.height == prs.height + 1 and rs.last_commit is not None:
                    if await self._pick_send_vote(peer, ps, rs.last_commit):
                        continue
                # peer further behind: serve the stored commit at its height
                elif (
                    prs.height != 0
                    and rs.height >= prs.height + 2
                    and self.cs.block_store.base() <= prs.height
                ):
                    commit = self.cs.block_store.load_block_commit(prs.height)
                    if commit is not None and await self._pick_send_vote(
                        peer, ps, _CommitVoteSource(commit)
                    ):
                        continue
                await asyncio.sleep(sleep)
        except asyncio.CancelledError:
            raise
        except Exception as e:  # noqa: BLE001
            self.logger.error("gossip_votes routine failed", peer=peer.id[:10], err=str(e))

    async def _gossip_votes_for_height(self, peer, ps: PeerState) -> bool:
        """reactor.go:832-894."""
        rs = self.cs.rs
        prs = ps.prs
        # peer still in NewHeight: needs our last commit
        if prs.step == RoundStepType.NEW_HEIGHT and rs.last_commit is not None:
            if await self._pick_send_vote(peer, ps, rs.last_commit):
                return True
        # peer in Propose, has declared a POL round: send those prevotes
        if (
            prs.step <= RoundStepType.PROPOSE
            and prs.round_ != -1
            and prs.round_ <= rs.round_
            and prs.proposal_pol_round != -1
        ):
            pol = rs.votes.prevotes(prs.proposal_pol_round)
            if pol is not None and await self._pick_send_vote(peer, ps, pol):
                return True
        # prevotes for the peer's round
        if prs.step <= RoundStepType.PREVOTE_WAIT and -1 != prs.round_ <= rs.round_:
            vs = rs.votes.prevotes(prs.round_)
            if vs is not None and await self._pick_send_vote(peer, ps, vs):
                return True
        # precommits for the peer's round
        if prs.step <= RoundStepType.PRECOMMIT_WAIT and -1 != prs.round_ <= rs.round_:
            vs = rs.votes.precommits(prs.round_)
            if vs is not None and await self._pick_send_vote(peer, ps, vs):
                return True
        # any round's prevotes the peer can use
        if prs.round_ != -1 and prs.round_ <= rs.round_:
            vs = rs.votes.prevotes(prs.round_)
            if vs is not None and await self._pick_send_vote(peer, ps, vs):
                return True
        if prs.proposal_pol_round != -1:
            pol = rs.votes.prevotes(prs.proposal_pol_round)
            if pol is not None and await self._pick_send_vote(peer, ps, pol):
                return True
        return False

    async def _pick_send_vote(self, peer, ps: PeerState, votes) -> bool:
        """reactor.go:1171 PickSendVote."""
        vote = ps.pick_vote_to_send(votes)
        if vote is None:
            return False
        sent = await peer.send(VOTE_CHANNEL, codec.encode(M.VoteMessage(vote=vote)))
        if sent:
            ps.set_has_vote(vote.height, vote.round_, vote.type_, vote.validator_index)
            ps.gossip["votes_sent"] += 1
            self._gossip_metric("gossip_votes_sent")
        return sent

    async def _query_maj23_routine(self, peer, ps: PeerState) -> None:
        """reactor.go:896-1000: periodically tell peers about our +2/3
        majorities so they can return any votes we miss."""
        sleep = self.cs.config.peer_query_maj23_sleep_duration
        try:
            while peer.is_running:
                await asyncio.sleep(sleep)
                if self.wait_sync:
                    continue
                rs = self.cs.rs
                prs = ps.prs
                if rs.height != prs.height or rs.votes is None:
                    continue
                for type_, vs in (
                    (SignedMsgType.PREVOTE, rs.votes.prevotes(prs.round_)),
                    (SignedMsgType.PRECOMMIT, rs.votes.precommits(prs.round_)),
                ):
                    if vs is None:
                        continue
                    block_id, ok = vs.two_thirds_majority()
                    if ok:
                        peer.try_send(
                            STATE_CHANNEL,
                            codec.encode(M.VoteSetMaj23Message(
                                height=prs.height, round_=prs.round_,
                                type_=type_, block_id=block_id,
                            )),
                        )
        except asyncio.CancelledError:
            raise
        except Exception as e:  # noqa: BLE001
            self.logger.error("query_maj23 routine failed", peer=peer.id[:10], err=str(e))
