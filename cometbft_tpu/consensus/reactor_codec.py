"""Wire codec for consensus reactor messages.

Reference: proto/tendermint/consensus/types.proto + consensus/reactor.go
message taxonomy (reactor.go:1576-1592). Each channel carries a Message
envelope with a oneof keyed by field number:

  1 NewRoundStep  2 NewValidBlock  3 Proposal  4 ProposalPOL  5 BlockPart
  6 Vote          7 HasVote        8 VoteSetMaj23  9 VoteSetBits
  10 VoteSummary (framework extension: compact vote-set reconciliation,
     only ever sent on the negotiated RECON channel)

BitArrays ride as {1: bits varint, 2: packed little-endian bytes}.
"""

from __future__ import annotations

import zlib

from cometbft_tpu.consensus import messages as M
from cometbft_tpu.libs.bits import BitArray
from cometbft_tpu.types.basic import BlockID, PartSetHeader, SignedMsgType
from cometbft_tpu.types.part_set import Part
from cometbft_tpu.types.proposal import Proposal
from cometbft_tpu.types.vote import Vote
from cometbft_tpu.utils.protobuf import Reader, Writer


def _bits_bytes(ba: BitArray | None) -> bytes | None:
    if ba is None:
        return None
    return Writer().varint_i64(1, ba.size()).bytes(2, ba.to_bytes()).output()


def _read_bits(r: Reader) -> BitArray:
    br = r.read_message()
    bits, data = 0, b""
    while not br.at_end():
        f, w = br.read_tag()
        if f == 1:
            bits = br.read_varint_i64()
        elif f == 2:
            data = br.read_bytes()
        else:
            br.skip(w)
    return BitArray.from_bytes(bits, data)


def vote_summary_checksum(height: int, round_: int,
                          prevotes: BitArray | None,
                          precommits: BitArray | None) -> int:
    """End-to-end integrity word for a VoteSummaryMessage: crc32 over the
    canonical payload. Transport framing already checks lengths; this
    catches a summary whose BITS were corrupted in flight or by a buggy
    peer — an invalid summary must degrade to full gossip, never update
    the peer's vote bookkeeping."""
    pv = prevotes.to_bytes() if prevotes is not None else b""
    pc = precommits.to_bytes() if precommits is not None else b""
    body = b"%d|%d|%d|%d|" % (
        height, round_,
        prevotes.size() if prevotes is not None else -1,
        precommits.size() if precommits is not None else -1) + pv + b"|" + pc
    return zlib.crc32(body) & 0xFFFFFFFF


def encode(msg) -> bytes:
    w = Writer()
    if isinstance(msg, M.NewRoundStepMessage):
        inner = (
            Writer()
            .varint_i64(1, msg.height)
            .varint_i64(2, msg.round_)
            .uvarint(3, msg.step)
            .varint_i64(4, msg.seconds_since_start_time)
            .varint_i64(5, msg.last_commit_round)
            .output()
        )
        w.message(1, inner, always=True)
    elif isinstance(msg, M.NewValidBlockMessage):
        inner = Writer().varint_i64(1, msg.height).varint_i64(2, msg.round_)
        psh = msg.block_part_set_header
        inner.message(3, psh.to_proto() if psh else None)
        inner.message(4, _bits_bytes(msg.block_parts))
        inner.bool(5, msg.is_commit)
        w.message(2, inner.output(), always=True)
    elif isinstance(msg, M.ProposalMessage):
        w.message(3, Writer().message(1, msg.proposal.to_proto(), always=True).output(), always=True)
    elif isinstance(msg, M.ProposalPOLMessage):
        inner = (
            Writer()
            .varint_i64(1, msg.height)
            .varint_i64(2, msg.proposal_pol_round)
            .message(3, _bits_bytes(msg.proposal_pol))
            .output()
        )
        w.message(4, inner, always=True)
    elif isinstance(msg, M.BlockPartMessage):
        inner = (
            Writer()
            .varint_i64(1, msg.height)
            .varint_i64(2, msg.round_)
            .message(3, msg.part.to_proto(), always=True)
            .output()
        )
        w.message(5, inner, always=True)
    elif isinstance(msg, M.VoteMessage):
        w.message(6, Writer().message(1, msg.vote.to_proto(), always=True).output(), always=True)
    elif isinstance(msg, M.HasVoteMessage):
        inner = (
            Writer()
            .varint_i64(1, msg.height)
            .varint_i64(2, msg.round_)
            .uvarint(3, int(msg.type_))
            .varint_i64(4, msg.index)
            .output()
        )
        w.message(7, inner, always=True)
    elif isinstance(msg, M.VoteSetMaj23Message):
        inner = (
            Writer()
            .varint_i64(1, msg.height)
            .varint_i64(2, msg.round_)
            .uvarint(3, int(msg.type_))
            .message(4, msg.block_id.to_proto(), always=True)
            .output()
        )
        w.message(8, inner, always=True)
    elif isinstance(msg, M.VoteSetBitsMessage):
        inner = (
            Writer()
            .varint_i64(1, msg.height)
            .varint_i64(2, msg.round_)
            .uvarint(3, int(msg.type_))
            .message(4, msg.block_id.to_proto(), always=True)
            .message(5, _bits_bytes(msg.votes))
            .output()
        )
        w.message(9, inner, always=True)
    elif isinstance(msg, M.VoteSummaryMessage):
        inner = (
            Writer()
            .varint_i64(1, msg.height)
            .varint_i64(2, msg.round_)
            .message(3, _bits_bytes(msg.prevotes))
            .message(4, _bits_bytes(msg.precommits))
            .uvarint(5, msg.checksum)
            .output()
        )
        w.message(10, inner, always=True)
    else:
        raise TypeError(f"cannot encode consensus message {type(msg)}")
    return w.output()


def decode(data: bytes):
    r = Reader(data)
    f, w = r.read_tag()
    if f == 1:
        mr = r.read_message()
        msg = M.NewRoundStepMessage(height=0, round_=0, step=0)
        while not mr.at_end():
            mf, mw = mr.read_tag()
            if mf == 1:
                msg.height = mr.read_varint_i64()
            elif mf == 2:
                msg.round_ = mr.read_varint_i64()
            elif mf == 3:
                msg.step = mr.read_uvarint()
            elif mf == 4:
                msg.seconds_since_start_time = mr.read_varint_i64()
            elif mf == 5:
                msg.last_commit_round = mr.read_varint_i64()
            else:
                mr.skip(mw)
        return msg
    if f == 2:
        mr = r.read_message()
        msg = M.NewValidBlockMessage(height=0, round_=0)
        while not mr.at_end():
            mf, mw = mr.read_tag()
            if mf == 1:
                msg.height = mr.read_varint_i64()
            elif mf == 2:
                msg.round_ = mr.read_varint_i64()
            elif mf == 3:
                msg.block_part_set_header = PartSetHeader.from_proto(mr.read_bytes())
            elif mf == 4:
                msg.block_parts = _read_bits(mr)
            elif mf == 5:
                msg.is_commit = mr.read_uvarint() != 0
            else:
                mr.skip(mw)
        return msg
    if f == 3:
        mr = r.read_message()
        proposal = None
        while not mr.at_end():
            mf, mw = mr.read_tag()
            if mf == 1:
                proposal = Proposal.from_proto(mr.read_bytes())
            else:
                mr.skip(mw)
        return M.ProposalMessage(proposal=proposal)
    if f == 4:
        mr = r.read_message()
        msg = M.ProposalPOLMessage(height=0, proposal_pol_round=0)
        while not mr.at_end():
            mf, mw = mr.read_tag()
            if mf == 1:
                msg.height = mr.read_varint_i64()
            elif mf == 2:
                msg.proposal_pol_round = mr.read_varint_i64()
            elif mf == 3:
                msg.proposal_pol = _read_bits(mr)
            else:
                mr.skip(mw)
        return msg
    if f == 5:
        mr = r.read_message()
        height = round_ = 0
        part = None
        while not mr.at_end():
            mf, mw = mr.read_tag()
            if mf == 1:
                height = mr.read_varint_i64()
            elif mf == 2:
                round_ = mr.read_varint_i64()
            elif mf == 3:
                part = Part.from_proto(mr.read_bytes())
            else:
                mr.skip(mw)
        return M.BlockPartMessage(height=height, round_=round_, part=part)
    if f == 6:
        mr = r.read_message()
        vote = None
        while not mr.at_end():
            mf, mw = mr.read_tag()
            if mf == 1:
                vote = Vote.from_proto(mr.read_bytes())
            else:
                mr.skip(mw)
        return M.VoteMessage(vote=vote)
    if f == 7:
        mr = r.read_message()
        msg = M.HasVoteMessage(height=0, round_=0)
        while not mr.at_end():
            mf, mw = mr.read_tag()
            if mf == 1:
                msg.height = mr.read_varint_i64()
            elif mf == 2:
                msg.round_ = mr.read_varint_i64()
            elif mf == 3:
                msg.type_ = SignedMsgType(mr.read_uvarint())
            elif mf == 4:
                msg.index = mr.read_varint_i64()
            else:
                mr.skip(mw)
        return msg
    if f == 8:
        mr = r.read_message()
        msg = M.VoteSetMaj23Message(height=0, round_=0, type_=SignedMsgType.UNKNOWN)
        while not mr.at_end():
            mf, mw = mr.read_tag()
            if mf == 1:
                msg.height = mr.read_varint_i64()
            elif mf == 2:
                msg.round_ = mr.read_varint_i64()
            elif mf == 3:
                msg.type_ = SignedMsgType(mr.read_uvarint())
            elif mf == 4:
                msg.block_id = BlockID.from_proto(mr.read_bytes())
            else:
                mr.skip(mw)
        return msg
    if f == 9:
        mr = r.read_message()
        msg = M.VoteSetBitsMessage(height=0, round_=0, type_=SignedMsgType.UNKNOWN)
        while not mr.at_end():
            mf, mw = mr.read_tag()
            if mf == 1:
                msg.height = mr.read_varint_i64()
            elif mf == 2:
                msg.round_ = mr.read_varint_i64()
            elif mf == 3:
                msg.type_ = SignedMsgType(mr.read_uvarint())
            elif mf == 4:
                msg.block_id = BlockID.from_proto(mr.read_bytes())
            elif mf == 5:
                msg.votes = _read_bits(mr)
            else:
                mr.skip(mw)
        return msg
    if f == 10:
        mr = r.read_message()
        msg = M.VoteSummaryMessage(height=0, round_=0)
        while not mr.at_end():
            mf, mw = mr.read_tag()
            if mf == 1:
                msg.height = mr.read_varint_i64()
            elif mf == 2:
                msg.round_ = mr.read_varint_i64()
            elif mf == 3:
                msg.prevotes = _read_bits(mr)
            elif mf == 4:
                msg.precommits = _read_bits(mr)
            elif mf == 5:
                msg.checksum = mr.read_uvarint()
            else:
                mr.skip(mw)
        return msg
    raise ValueError(f"unknown consensus message field {f}")
