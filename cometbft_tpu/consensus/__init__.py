"""Consensus core — the Tendermint state machine (reference: consensus/).

One asyncio task per node serializes ALL state transitions (the analog of
the reference's single receiveRoutine goroutine, consensus/state.go:774);
peer messages, self-generated messages, and timeouts are queue items. Gossip
lives in the reactor (p2p-land); this package never touches sockets
(SURVEY.md §1 control relationships).
"""

from cometbft_tpu.consensus.config import ConsensusConfig  # noqa: F401
from cometbft_tpu.consensus.round_state import RoundState, RoundStepType  # noqa: F401
from cometbft_tpu.consensus.state import ConsensusState  # noqa: F401
