"""ABCI handshake: sync the application to the block store on boot.

Reference: consensus/replay.go:200-530. On start the node asks the app
its height (ABCI Info), compares with the state store and block store,
and replays whatever the app missed:

  app at 0                -> InitChain from genesis (replay.go:308-360)
  app < state height      -> re-execute stored blocks against the app only
  state = store height -1 -> the crash window between the WAL EndHeight
                             fsync and the state-store save: apply the
                             stored last block through the BlockExecutor,
                             which re-saves state (replay.go:414-460)
  app ahead of state by 1 -> state update only, using the stored
                             FinalizeBlockResponse as a mock app
                             (replay.go:462-480)

This is the recovery path the round-2 WAL replay guard punts to
(state.py _catchup_replay raising on found EndHeight).
"""

from __future__ import annotations

from cometbft_tpu.abci import types as abci
from cometbft_tpu.libs import log as cmtlog
from cometbft_tpu.state import BlockExecutor, State
from cometbft_tpu.state.store import StateStore
from cometbft_tpu.store.blockstore import BlockStore
from cometbft_tpu.types.genesis import GenesisDoc


class ErrAppBlockHeightTooHigh(Exception):
    pass


class _NullMempool:
    """Handshake executes without a live mempool (replay.go:472
    emptyMempool)."""

    def reap_max_bytes_max_gas(self, max_bytes: int, max_gas: int) -> list[bytes]:
        return []

    async def update(self, height: int, txs, tx_results) -> None:
        return None


class _StoredResponseClient:
    """Mock consensus conn answering FinalizeBlock from the state store's
    saved response — used when the app already ran the block but the state
    save was lost (replay.go:462 mockProxyApp)."""

    def __init__(self, resp):
        self._resp = resp

    async def finalize_block(self, req):
        return self._resp

    async def commit(self, req):
        return abci.ResponseCommit()


class Handshaker:
    def __init__(
        self,
        state_store: StateStore,
        block_store: BlockStore,
        genesis_doc: GenesisDoc,
        logger: cmtlog.Logger | None = None,
    ):
        self.state_store = state_store
        self.block_store = block_store
        self.genesis_doc = genesis_doc
        self.logger = logger or cmtlog.nop()
        self.n_blocks_replayed = 0

    async def handshake(self, proxy_app) -> State:
        """replay.go:241-280 Handshake: Info -> ReplayBlocks."""
        res = await proxy_app.query.info(abci.RequestInfo(version="", block_version=11))
        app_height = res.last_block_height
        app_hash = res.last_block_app_hash
        if app_height < 0:
            raise ValueError(f"got negative last block height {app_height} from app")
        self.logger.info(
            "ABCI handshake", app_height=app_height, app_hash=app_hash.hex()[:12]
        )
        state = self.state_store.load()
        if state is None:
            state = State.from_genesis(self.genesis_doc)
            self.state_store.bootstrap(state)
        state = await self.replay_blocks(state, app_hash, app_height, proxy_app)
        self.logger.info(
            "completed ABCI handshake", height=state.last_block_height,
            replayed=self.n_blocks_replayed,
        )
        return state

    async def replay_blocks(
        self, state: State, app_hash: bytes, app_height: int, proxy_app
    ) -> State:
        """replay.go:283-460 ReplayBlocks."""
        store_height = self.block_store.height()
        state_height = state.last_block_height

        # 1. fresh app: InitChain (replay.go:308-360)
        if app_height == 0:
            gdoc = self.genesis_doc
            req = abci.RequestInitChain(
                time=gdoc.genesis_time,
                chain_id=gdoc.chain_id,
                consensus_params=None,
                validators=[
                    abci.ValidatorUpdate(
                        power=v.power,
                        pub_key_type=v.pub_key.type_(),
                        pub_key_bytes=v.pub_key.bytes_(),
                    )
                    for v in gdoc.validators
                ],
                app_state_bytes=gdoc.app_state,
                initial_height=gdoc.initial_height,
            )
            resp = await proxy_app.consensus.init_chain(req)
            if state_height == 0:  # only a genesis state may be amended
                if resp.app_hash:
                    state.app_hash = resp.app_hash
                    app_hash = resp.app_hash
                if resp.validators:
                    from cometbft_tpu.state.execution import _validator_updates_to_vals
                    from cometbft_tpu.types.validator import ValidatorSet

                    vals = _validator_updates_to_vals(resp.validators)
                    state.validators = ValidatorSet(vals)
                    nxt = ValidatorSet(vals)
                    nxt.increment_proposer_priority(1)
                    state.next_validators = nxt
                if resp.consensus_params is not None:
                    state.consensus_params = state.consensus_params.update(resp.consensus_params)
                self.state_store.save(state)

        # 2. nothing stored yet
        if store_height == 0:
            self._assert_app_hash(state, app_hash)
            return state

        if app_height > store_height:
            raise ErrAppBlockHeightTooHigh(
                f"app height {app_height} exceeds store height {store_height}"
            )
        # truncated-store guards (replay.go:364-370): blocks the app would
        # need to replay have been pruned away
        store_base = self.block_store.base()
        if app_height == 0 and state.initial_height < store_base:
            raise RuntimeError(
                f"app has no state and the block store is truncated above "
                f"the initial height (store base {store_base}, initial "
                f"height {state.initial_height})")
        if 0 < app_height < store_base - 1:
            raise RuntimeError(
                f"app height {app_height} is below the truncated store "
                f"base {store_base}")
        # the height the state expects to apply next: the chain's FIRST
        # block is initial_height, not 1 (a crash between saving block
        # initial_height and the state save must remain recoverable)
        next_height = (state.initial_height if state_height == 0
                       else state_height + 1)
        if store_height > next_height:
            raise RuntimeError(
                f"block store height {store_height} is more than one ahead of "
                f"state height {state_height}"
            )

        if store_height == state_height:
            if app_height == store_height:
                # nothing to replay: the app must already match
                # (replay.go checkAppHash on the Info response)
                self._assert_app_hash(state, app_hash)
                return state
            # happy path: replay to the app only (replay.go:399-412)
            return await self._replay_to_app(state, app_height, store_height, proxy_app)

        # store_height == next_height: the crash window (one block saved
        # beyond the last applied state)
        if app_height < state_height:
            # app missed earlier blocks too: catch it up, then apply the last
            state = await self._replay_to_app(state, app_height, state_height, proxy_app)
            app_height = state_height
        if app_height == state_height:
            # app and state agree; the final stored block goes through the
            # full executor so the state store is rewritten (replay.go:414)
            return await self._apply_stored_block(state, store_height, proxy_app.consensus)
        if app_height == store_height:
            # app ran the block; rebuild state from the saved response
            resp = self.state_store.load_finalize_block_response(store_height)
            if resp is None:
                raise RuntimeError(
                    f"app is at height {app_height} but no saved FinalizeBlock "
                    f"response for it; cannot resync state"
                )
            return await self._apply_stored_block(
                state, store_height, _StoredResponseClient(resp)
            )
        raise RuntimeError(
            f"uncovered handshake case: app {app_height}, state {state_height}, "
            f"store {store_height}"
        )

    async def _replay_to_app(
        self, state: State, app_height: int, final_height: int, proxy_app
    ) -> State:
        """replay.go:500-530 applyBlock loop: FinalizeBlock+Commit only —
        state is NOT re-saved (it is already correct)."""
        from cometbft_tpu.state.execution import _abci_commit_info, _abci_misbehavior

        app_hash = b""
        # a freshly-InitChained app starts at the chain's initial height,
        # which need not be 1 (replay.go:465-468)
        first = app_height + 1
        if first == 1:
            first = state.initial_height
        for h in range(first, final_height + 1):
            block = self.block_store.load_block(h)
            if block is None:
                raise RuntimeError(f"missing block {h} in store during replay")
            self.logger.info("replaying block to app", height=h)
            # signers of block h's LastCommit = validator set at h-1; the app
            # must see the same CommitInfo it saw live (execution.py:249)
            last_vals = self.state_store.load_validators(h - 1) if h > 1 else None
            req = abci.RequestFinalizeBlock(
                txs=block.data.txs,
                decided_last_commit=_abci_commit_info(block, last_vals),
                # the app must re-see the block's evidence exactly as it did
                # live, or a misbehavior-sensitive app forks its own hash
                misbehavior=_abci_misbehavior(block.evidence.evidence),
                hash=block.hash(),
                height=h,
                time=block.header.time,
                next_validators_hash=block.header.next_validators_hash,
                proposer_address=block.header.proposer_address,
            )
            resp = await proxy_app.consensus.finalize_block(req)
            await proxy_app.consensus.commit(abci.RequestCommit())
            app_hash = resp.app_hash
            self.n_blocks_replayed += 1
        if app_hash:
            self._assert_app_hash(state, app_hash)
        return state

    async def _apply_stored_block(self, state: State, height: int, conn) -> State:
        """replay.go:414-460: run the stored block through a BlockExecutor
        (null mempool/evidence) so updateState + state save happen."""
        block = self.block_store.load_block(height)
        meta = self.block_store.load_block_meta(height)
        if block is None or meta is None:
            raise RuntimeError(f"missing block {height} during handshake apply")
        exec_ = BlockExecutor(
            self.state_store, conn, _NullMempool(), evidence_pool=None,
            logger=self.logger,
        )
        self.n_blocks_replayed += 1
        return await exec_.apply_block(state, meta.block_id, block)

    def _assert_app_hash(self, state: State, app_hash: bytes) -> None:
        if state.app_hash != app_hash:
            raise RuntimeError(
                f"app hash mismatch after replay: state {state.app_hash.hex()[:12]} "
                f"vs app {app_hash.hex()[:12]}"
            )
