"""Write-ahead log for consensus messages.

Reference: consensus/wal.go over libs/autofile. Every message is written
BEFORE it is processed (consensus/state.go:821,829) so a crashed node
replays to the exact pre-crash state. Records are CRC32C+length framed;
EndHeightMessage sentinels mark completed heights (wal.go:42) and are the
replay anchors (SearchForEndHeight, wal.go:64). A corrupted tail (torn
write at crash) is detected by CRC/length and truncated, mirroring the
reference's WAL repair (consensus/state.go:2579).

Record body is a compact JSON envelope {"t": type, ...} — vote/proposal
payloads ride their canonical proto encodings in hex.
"""

from __future__ import annotations

import json
import os
import struct
import zlib
from dataclasses import dataclass
from typing import Iterator

from cometbft_tpu.consensus.round_state import RoundStepType
from cometbft_tpu.consensus.ticker import TimeoutInfo

MAX_RECORD_SIZE = 4 * 1024 * 1024


@dataclass
class EndHeightMessage:
    height: int


@dataclass
class EventDataRoundState:
    height: int
    round_: int
    step: str


class WAL:
    def __init__(self, path: str, chunk_size: int | None = None,
                 total_size: int | None = None):
        from cometbft_tpu.libs import autofile

        self.path = path
        self.group = autofile.Group(
            path,
            chunk_size=chunk_size or autofile.DEFAULT_CHUNK_SIZE,
            total_size=total_size or autofile.DEFAULT_TOTAL_SIZE,
        )

    # ------------------------------------------------------------- write

    def write(self, msg) -> None:
        self._write_record(_encode_msg(msg))

    def write_sync(self, msg) -> None:
        self._write_record(_encode_msg(msg))
        self.group.fsync()

    def _write_record(self, body: bytes) -> None:
        crc = zlib.crc32(body) & 0xFFFFFFFF
        self.group.write(struct.pack(">II", crc, len(body)) + body)
        self.group.maybe_rotate()  # record boundary: safe rotation point

    def flush(self) -> None:
        self.group.fsync()

    def close(self) -> None:
        self.group.close()

    # -------------------------------------------------------------- read

    def iter_records(self) -> Iterator[object]:
        """Yield decoded messages across every chunk in stream order;
        stops at a corrupted record. Torn tails are repaired by truncation
        only in the FINAL file (a mid-group corruption means real damage,
        not a crash artifact — reference wal.go repair semantics)."""
        paths = [p for p in self.group.chunk_paths() if os.path.exists(p)]
        for pi, path in enumerate(paths):
            good_end = 0
            corrupted = False
            with open(path, "rb") as f:
                while True:
                    hdr = f.read(8)
                    if len(hdr) < 8:
                        break
                    crc, n = struct.unpack(">II", hdr)
                    if n > MAX_RECORD_SIZE:
                        corrupted = True
                        break
                    body = f.read(n)
                    if len(body) < n or (zlib.crc32(body) & 0xFFFFFFFF) != crc:
                        corrupted = True
                        break
                    good_end = f.tell()
                    yield _decode_msg(body)
            size = os.path.getsize(path)
            if good_end < size:
                if pi == len(paths) - 1:
                    # torn tail: repair by truncation (reference auto-repair)
                    with open(path, "r+b") as f:
                        f.truncate(good_end)
                else:
                    raise OSError(f"corrupted WAL chunk {path} (not the tail)")
            if corrupted:
                return

    def search_for_end_height(self, height: int) -> bool:
        """True if EndHeightMessage(height) exists (wal.go:64)."""
        for msg in self.iter_records():
            if isinstance(msg, EndHeightMessage) and msg.height == height:
                return True
        return False

    def replay_after_height(self, height: int) -> list[object]:
        """Messages recorded after EndHeight(height) — the catchup-replay
        input (consensus/replay.go:94). Collection stops at any LATER
        EndHeight sentinel: messages past it belong to an already-committed
        height and replaying them would re-execute the block against the
        app (replay.go:99-115 semantics)."""
        out: list[object] = []
        found = height == -1
        for msg in self.iter_records():
            if isinstance(msg, EndHeightMessage):
                if msg.height == height:
                    found = True
                    out = []
                elif found and msg.height > height:
                    break
                continue
            if found:
                out.append(msg)
        return out if found else []


def _encode_msg(msg) -> bytes:
    from cometbft_tpu.consensus import messages as M

    if isinstance(msg, EndHeightMessage):
        doc = {"t": "eh", "h": msg.height}
    elif isinstance(msg, TimeoutInfo):
        doc = {"t": "to", "d": msg.duration, "h": msg.height, "r": msg.round_, "s": int(msg.step)}
    elif isinstance(msg, EventDataRoundState):
        doc = {"t": "rs", "h": msg.height, "r": msg.round_, "s": msg.step}
    elif isinstance(msg, M.VoteMessage):
        doc = {"t": "v", "d": msg.vote.to_proto().hex(), "p": msg.peer_id}
    elif isinstance(msg, M.ProposalMessage):
        doc = {"t": "p", "d": msg.proposal.to_proto().hex(), "p": msg.peer_id}
    elif isinstance(msg, M.BlockPartMessage):
        doc = {
            "t": "bp", "h": msg.height, "r": msg.round_,
            "d": msg.part.to_proto().hex(), "p": msg.peer_id,
        }
    else:
        raise TypeError(f"cannot WAL-encode {type(msg)}")
    return json.dumps(doc, separators=(",", ":")).encode()


def _decode_msg(body: bytes):
    from cometbft_tpu.consensus import messages as M
    from cometbft_tpu.types.part_set import Part
    from cometbft_tpu.types.proposal import Proposal
    from cometbft_tpu.types.vote import Vote

    doc = json.loads(body)
    t = doc["t"]
    if t == "eh":
        return EndHeightMessage(height=doc["h"])
    if t == "to":
        return TimeoutInfo(duration=doc["d"], height=doc["h"], round_=doc["r"],
                           step=RoundStepType(doc["s"]))
    if t == "rs":
        return EventDataRoundState(height=doc["h"], round_=doc["r"], step=doc["s"])
    if t == "v":
        return M.VoteMessage(vote=Vote.from_proto(bytes.fromhex(doc["d"])), peer_id=doc.get("p", ""))
    if t == "p":
        return M.ProposalMessage(proposal=Proposal.from_proto(bytes.fromhex(doc["d"])), peer_id=doc.get("p", ""))
    if t == "bp":
        return M.BlockPartMessage(
            height=doc["h"], round_=doc["r"],
            part=Part.from_proto(bytes.fromhex(doc["d"])), peer_id=doc.get("p", ""),
        )
    raise ValueError(f"unknown WAL record type {t!r}")
