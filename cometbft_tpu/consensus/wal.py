"""Write-ahead log for consensus messages.

Reference: consensus/wal.go over libs/autofile. Every message is written
BEFORE it is processed (consensus/state.go:821,829) so a crashed node
replays to the exact pre-crash state. Records are CRC32C+length framed;
EndHeightMessage sentinels mark completed heights (wal.go:42) and are the
replay anchors (SearchForEndHeight, wal.go:64). A corrupted tail (torn
write at crash) is detected by CRC/length and truncated, mirroring the
reference's WAL repair (consensus/state.go:2579).

Record body is a compact JSON envelope {"t": type, ...} — vote/proposal
payloads ride their canonical proto encodings in hex.
"""

from __future__ import annotations

import json
import os
import shutil
import struct
import time
import zlib
from dataclasses import dataclass, field
from typing import Iterator

from cometbft_tpu.consensus.round_state import RoundStepType
from cometbft_tpu.consensus.ticker import TimeoutInfo
from cometbft_tpu.libs import diskchaos, fail

MAX_RECORD_SIZE = 4 * 1024 * 1024


class WALCorruptionError(OSError):
    """Mid-group WAL corruption: a chunk that is NOT the stream tail
    failed its CRC/length framing — real disk damage, not a crash
    artifact, so replay refuses to guess. The message names the chunk,
    the byte offset of the first bad record, and the repair knob, so a
    node that cannot boot tells the operator exactly what to run."""

    def __init__(self, chunk: str, offset: int, detail: str):
        super().__init__(
            f"corrupted WAL chunk {chunk} at byte offset {offset} "
            f"({detail}); this chunk is not the stream tail, so it is "
            f"disk damage, not a torn crash-write. Repair: run "
            f"`cometbft wal-repair --home <home>` — it quarantines the "
            f"damaged chunk (kept as {os.path.basename(chunk)}.corrupt "
            f"for forensics) and the unreplayable records after it, "
            f"then the node boots and recovers the rest over "
            f"handshake/blocksync.")
        self.chunk = chunk
        self.offset = offset
        self.detail = detail


@dataclass
class RepairReport:
    """What `WAL.repair()` (the `cometbft wal-repair` surface) did."""

    corrupt_chunk: str | None = None
    offset: int = 0
    quarantined: list[str] = field(default_factory=list)
    truncated_bytes: int = 0


@dataclass
class EndHeightMessage:
    height: int


@dataclass
class EventDataRoundState:
    height: int
    round_: int
    step: str


class WAL:
    def __init__(self, path: str, chunk_size: int | None = None,
                 total_size: int | None = None):
        from cometbft_tpu.libs import autofile

        self.path = path
        self.group = autofile.Group(
            path,
            chunk_size=chunk_size or autofile.DEFAULT_CHUNK_SIZE,
            total_size=total_size or autofile.DEFAULT_TOTAL_SIZE,
        )

    # ------------------------------------------------------------- write

    def write(self, msg) -> None:
        self._write_record(_encode_msg(msg))

    def write_sync(self, msg) -> None:
        self._write_record(_encode_msg(msg))
        self._timed_fsync()

    def _write_record(self, body: bytes) -> None:
        fail.fail_point("wal.write")
        crc = zlib.crc32(body) & 0xFFFFFFFF
        self.group.write(struct.pack(">II", crc, len(body)) + body)
        self.group.maybe_rotate()  # record boundary: safe rotation point

    def _timed_fsync(self) -> None:
        from cometbft_tpu.libs import metrics as cmtmetrics

        t0 = time.perf_counter()
        self.group.fsync()
        cmtmetrics.storage_metrics().observe_wal_fsync(time.perf_counter() - t0)

    def flush(self) -> None:
        self._timed_fsync()

    def close(self) -> None:
        self.group.close()

    # -------------------------------------------------------------- read

    @staticmethod
    def _scan_chunk(path: str):
        """Scan one chunk: yields (good_end, body) per valid record, then
        returns via StopIteration.value a (good_end, detail|None) pair —
        detail is None when the chunk is clean to its last byte."""
        good_end = 0
        with open(path, "rb") as f:
            while True:
                hdr = f.read(8)
                if len(hdr) < 8:
                    detail = "torn record header" if hdr else None
                    return good_end, detail
                crc, n = struct.unpack(">II", hdr)
                if n == 0:
                    # crc32(b"") == 0, so an all-zero header would parse
                    # as a "valid" empty record — but no encoded message
                    # is empty; zeroed regions are damage
                    return good_end, "zero-length record"
                if n > MAX_RECORD_SIZE:
                    return good_end, f"record length {n} exceeds {MAX_RECORD_SIZE}"
                body = f.read(n)
                body = diskchaos.fault_read("wal.read", body)
                if len(body) < n:
                    return good_end, f"torn record body ({len(body)} of {n} bytes)"
                if (zlib.crc32(body) & 0xFFFFFFFF) != crc:
                    return good_end, "crc32 mismatch"
                good_end = f.tell()
                yield good_end, body

    def iter_records(self) -> Iterator[object]:
        """Yield decoded messages across every chunk in stream order;
        stops at a corrupted record. Torn tails are repaired by truncation
        only in the FINAL file (a mid-group corruption means real damage,
        not a crash artifact — reference wal.go repair semantics) and the
        truncation is counted on the storage metrics plane. Mid-group
        corruption raises the TYPED WALCorruptionError naming the chunk,
        the byte offset, and the `cometbft wal-repair` knob — never a
        bare stack trace, and never a corrupt message."""
        paths = [p for p in self.group.chunk_paths() if os.path.exists(p)]
        for pi, path in enumerate(paths):
            scan = self._scan_chunk(path)
            good_end, detail = 0, None
            while True:
                try:
                    good_end, body = next(scan)
                except StopIteration as stop:
                    good_end, detail = stop.value
                    break
                yield _decode_msg(body)
            size = os.path.getsize(path)
            if good_end < size:
                if pi == len(paths) - 1:
                    # torn tail: repair by truncation (reference auto-repair)
                    with open(path, "r+b") as f:
                        f.truncate(good_end)
                    from cometbft_tpu.libs import metrics as cmtmetrics

                    cmtmetrics.storage_metrics().wal_truncations.inc()
                else:
                    raise WALCorruptionError(
                        path, good_end, detail or "trailing garbage")
            if detail is not None:
                return

    def repair(self) -> RepairReport:
        """The `cometbft wal-repair` surface: make the group replayable
        again after mid-group corruption. The damaged chunk keeps its
        good prefix (the original is preserved as `<chunk>.corrupt` for
        forensics) and every LATER chunk — records that cannot be safely
        replayed across the gap — is quarantined as `<chunk>.quarantined`.
        Sound because losing WAL tail records is equivalent to having
        crashed slightly earlier: block/state stores and the privval
        sign-state still guarantee no lost committed height and no
        double-sign; the node recovers the gap over handshake/blocksync."""
        report = RepairReport()
        # quarantining may rename the head out from under the group's
        # open handle — close first, reopen a fresh head after
        self.group.close()
        paths = [p for p in self.group.chunk_paths() if os.path.exists(p)]
        for pi, path in enumerate(paths):
            scan = self._scan_chunk(path)
            while True:
                try:
                    next(scan)
                except StopIteration as stop:
                    good_end, detail = stop.value
                    break
            size = os.path.getsize(path)
            if good_end >= size and detail is None:
                continue
            # first damage in stream order: truncate here, quarantine rest
            report.corrupt_chunk, report.offset = path, good_end
            report.truncated_bytes = size - good_end
            shutil.copyfile(path, path + ".corrupt")
            with open(path, "r+b") as f:
                f.truncate(good_end)
            for later in paths[pi + 1:]:
                os.replace(later, later + ".quarantined")
                report.quarantined.append(later)
            from cometbft_tpu.libs import metrics as cmtmetrics

            cmtmetrics.storage_metrics().wal_repairs.inc()
            break
        self.group._head = open(self.group.head_path, "ab", buffering=0)
        diskchaos.track_open(self.group.head_path, fresh=True)
        return report

    def search_for_end_height(self, height: int) -> bool:
        """True if EndHeightMessage(height) exists (wal.go:64)."""
        for msg in self.iter_records():
            if isinstance(msg, EndHeightMessage) and msg.height == height:
                return True
        return False

    def replay_after_height(self, height: int) -> list[object]:
        """Messages recorded after EndHeight(height) — the catchup-replay
        input (consensus/replay.go:94). Collection stops at any LATER
        EndHeight sentinel: messages past it belong to an already-committed
        height and replaying them would re-execute the block against the
        app (replay.go:99-115 semantics)."""
        out: list[object] = []
        found = height == -1
        for msg in self.iter_records():
            if isinstance(msg, EndHeightMessage):
                if msg.height == height:
                    found = True
                    out = []
                elif found and msg.height > height:
                    break
                continue
            if found:
                out.append(msg)
        return out if found else []


def _encode_msg(msg) -> bytes:
    from cometbft_tpu.consensus import messages as M

    if isinstance(msg, EndHeightMessage):
        doc = {"t": "eh", "h": msg.height}
    elif isinstance(msg, TimeoutInfo):
        doc = {"t": "to", "d": msg.duration, "h": msg.height, "r": msg.round_, "s": int(msg.step)}
    elif isinstance(msg, EventDataRoundState):
        doc = {"t": "rs", "h": msg.height, "r": msg.round_, "s": msg.step}
    elif isinstance(msg, M.VoteMessage):
        doc = {"t": "v", "d": msg.vote.to_proto().hex(), "p": msg.peer_id}
    elif isinstance(msg, M.ProposalMessage):
        doc = {"t": "p", "d": msg.proposal.to_proto().hex(), "p": msg.peer_id}
    elif isinstance(msg, M.BlockPartMessage):
        doc = {
            "t": "bp", "h": msg.height, "r": msg.round_,
            "d": msg.part.to_proto().hex(), "p": msg.peer_id,
        }
    else:
        raise TypeError(f"cannot WAL-encode {type(msg)}")
    return json.dumps(doc, separators=(",", ":")).encode()


def _decode_msg(body: bytes):
    from cometbft_tpu.consensus import messages as M
    from cometbft_tpu.types.part_set import Part
    from cometbft_tpu.types.proposal import Proposal
    from cometbft_tpu.types.vote import Vote

    doc = json.loads(body)
    t = doc["t"]
    if t == "eh":
        return EndHeightMessage(height=doc["h"])
    if t == "to":
        return TimeoutInfo(duration=doc["d"], height=doc["h"], round_=doc["r"],
                           step=RoundStepType(doc["s"]))
    if t == "rs":
        return EventDataRoundState(height=doc["h"], round_=doc["r"], step=doc["s"])
    if t == "v":
        return M.VoteMessage(vote=Vote.from_proto(bytes.fromhex(doc["d"])), peer_id=doc.get("p", ""))
    if t == "p":
        return M.ProposalMessage(proposal=Proposal.from_proto(bytes.fromhex(doc["d"])), peer_id=doc.get("p", ""))
    if t == "bp":
        return M.BlockPartMessage(
            height=doc["h"], round_=doc["r"],
            part=Part.from_proto(bytes.fromhex(doc["d"])), peer_id=doc.get("p", ""),
        )
    raise ValueError(f"unknown WAL record type {t!r}")
