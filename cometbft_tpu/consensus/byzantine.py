"""Byzantine validator harness: drive the REAL reactor stack adversarially.

Reference: consensus/byzantine_test.go — the byzantine node keeps its whole
production stack (reactors, switch, encrypted mconns) and only its decision
seams are replaced, so the honest majority is attacked over the same wire
it uses for everything else. Behaviors:

  equivocation   double-sign: every non-nil vote is shadowed by a second,
                 validly-signed vote for a fabricated block, gossiped to
                 peers (never enqueued locally — the liar believes its own
                 first story). Honest nodes must detect the conflict,
                 report it to the evidence pool, and commit
                 DuplicateVoteEvidence into a block.
  amnesia        vote, then forget: locks are wiped right after each
                 precommit, so later rounds can prevote a different block
                 (arXiv:2010.07031's amnesia attack shape).
  silence        a crashed-but-connected validator: gossip keeps flowing,
                 votes never come. Costs one validator of liveness margin,
                 never safety.
  flood          invalid-signature flooding: bursts of votes carrying the
                 byzantine validator's real address but forged signatures.
                 The batch verifier must reject every lane and the peer
                 scorer must ban the sender (p2p/switch.py).

The double-sign is only possible because the harness signs with the raw
key via UnsafeSigner — FilePV's HRS guard exists precisely to refuse this,
which is why the reference's byzantine tests also swap the signer.
"""

from __future__ import annotations

import asyncio
import os

from cometbft_tpu import crypto
from cometbft_tpu.consensus import messages as M
from cometbft_tpu.consensus.state import ConsensusState
from cometbft_tpu.crypto import tmhash
from cometbft_tpu.privval.file_pv import PrivValidator
from cometbft_tpu.types.basic import BlockID, PartSetHeader, SignedMsgType
from cometbft_tpu.types.proposal import Proposal
from cometbft_tpu.types.vote import Vote

BEHAVIORS = ("equivocation", "amnesia", "silence", "flood")

FLOOD_INTERVAL = 0.05   # seconds between bursts
FLOOD_BURST = 4         # forged votes per burst


class UnsafeSigner(PrivValidator):
    """A privval with NO double-sign protection — the byzantine analog of
    handing an attacker the raw key. Never use outside tests/harnesses."""

    def __init__(self, priv_key: crypto.PrivKey):
        self.priv_key = priv_key

    def get_pub_key(self) -> crypto.PubKey:
        return self.priv_key.pub_key()

    def sign_vote(self, chain_id: str, vote: Vote, sign_extension: bool = False) -> None:
        vote.signature = self.priv_key.sign(vote.sign_bytes(chain_id))
        if sign_extension and vote.type_ == SignedMsgType.PRECOMMIT and not vote.block_id.is_nil():
            vote.extension_signature = self.priv_key.sign(
                vote.extension_sign_bytes(chain_id))

    def sign_proposal(self, chain_id: str, proposal: Proposal) -> None:
        proposal.signature = self.priv_key.sign(proposal.sign_bytes(chain_id))


class ByzantineHarness:
    """Installed over a live ConsensusState by make_byzantine()."""

    def __init__(self, cs: ConsensusState, behavior: str, send=None):
        if behavior not in BEHAVIORS:
            raise ValueError(
                f"unknown byzantine behavior {behavior!r} (behaviors: {BEHAVIORS})")
        self.cs = cs
        self.behavior = behavior
        # outbound channel for adversarial messages; defaults to the state
        # machine's gossip tap (in-proc nets). Reactor stacks pass
        # switch_vote_sender(switch) so evil votes ride the real wire.
        self._send = send if send is not None else cs._gossip
        self._priv = cs.priv_validator.priv_key
        self._orig_sign_add_vote = cs._sign_add_vote
        self._flood_task: asyncio.Task | None = None
        self.equivocations = 0
        self.floods = 0
        self._install()

    # ------------------------------------------------------------ behaviors

    def _install(self) -> None:
        cs = self.cs
        if self.behavior == "equivocation":
            cs._sign_add_vote = self._equivocating_sign_add_vote
        elif self.behavior == "amnesia":
            cs._sign_add_vote = self._amnesiac_sign_add_vote
        elif self.behavior == "silence":
            cs._sign_add_vote = self._silent_sign_add_vote

    async def start(self) -> None:
        if self.behavior == "flood" and self._flood_task is None:
            self._flood_task = asyncio.get_running_loop().create_task(
                self._flood_routine())

    async def stop(self) -> None:
        if self._flood_task is not None:
            self._flood_task.cancel()
            try:
                await self._flood_task
            except (asyncio.CancelledError, Exception):  # noqa: BLE001
                pass
            self._flood_task = None
        self.cs._sign_add_vote = self._orig_sign_add_vote

    # ---------------------------------------------------------- equivocation

    def _conflicting_vote(self, vote: Vote, chain_id: str) -> Vote:
        """A validly-signed vote at the same H/R/type for a fabricated
        block — exactly the pair DuplicateVoteEvidence punishes."""
        fake = tmhash.sum_(b"byzantine-fork|" + vote.block_id.hash)
        evil = Vote(
            type_=vote.type_,
            height=vote.height,
            round_=vote.round_,
            block_id=BlockID(hash=fake,
                             part_set_header=PartSetHeader(total=1, hash=fake)),
            timestamp=vote.timestamp,
            validator_address=vote.validator_address,
            validator_index=vote.validator_index,
        )
        evil.signature = self._priv.sign(evil.sign_bytes(chain_id))
        return evil

    async def _equivocating_sign_add_vote(self, type_, hash_, psh):
        vote = await self._orig_sign_add_vote(type_, hash_, psh)
        if vote is None or not hash_:
            return vote
        if self.cs.state.consensus_params.abci.vote_extensions_enabled(vote.height) \
                and type_ == SignedMsgType.PRECOMMIT:
            # an extension-carrying double-sign needs a second extension
            # round-trip; equivocating on prevotes already yields evidence
            return vote
        evil = self._conflicting_vote(vote, self.cs.state.chain_id)
        self.equivocations += 1
        # gossip only — enqueueing it locally would trip our own
        # "conflicting vote from ourselves" containment
        self._send(M.VoteMessage(vote=evil))
        return vote

    # --------------------------------------------------------------- amnesia

    async def _amnesiac_sign_add_vote(self, type_, hash_, psh):
        vote = await self._orig_sign_add_vote(type_, hash_, psh)
        if vote is not None and type_ == SignedMsgType.PRECOMMIT and hash_:
            rs = self.cs.rs
            rs.locked_round = -1
            rs.locked_block = None
            rs.locked_block_parts = None
        return vote

    # --------------------------------------------------------------- silence

    async def _silent_sign_add_vote(self, type_, hash_, psh):
        return None

    # ----------------------------------------------------------------- flood

    def _forged_vote(self, rs) -> Vote:
        fake = os.urandom(32)
        return Vote(
            type_=SignedMsgType.PREVOTE,
            height=rs.height,
            round_=rs.round_,
            block_id=BlockID(hash=fake,
                             part_set_header=PartSetHeader(total=1, hash=fake)),
            timestamp=self.cs.rs.start_time,
            validator_address=self._priv.pub_key().address(),
            validator_index=self._own_index(rs),
            signature=os.urandom(64),
        )

    def _own_index(self, rs) -> int:
        if rs.validators is None:
            return 0
        idx, _ = rs.validators.get_by_address(self._priv.pub_key().address())
        return max(idx, 0)

    async def _flood_routine(self) -> None:
        while True:
            await asyncio.sleep(FLOOD_INTERVAL)
            rs = self.cs.rs
            if rs.validators is None or rs.height == 0:
                continue
            for _ in range(FLOOD_BURST):
                self.floods += 1
                self._send(M.VoteMessage(vote=self._forged_vote(rs)))


def switch_vote_sender(switch):
    """Adapter: broadcast adversarial VoteMessages over the real p2p switch
    (the consensus reactor's vote channel)."""
    from cometbft_tpu.consensus import reactor_codec as codec
    from cometbft_tpu.consensus.reactor import VOTE_CHANNEL

    def send(msg) -> None:
        switch.broadcast(VOTE_CHANNEL, codec.encode(msg))

    return send


def make_byzantine(cs: ConsensusState, behavior: str, send=None) -> ByzantineHarness:
    """Turn a live ConsensusState adversarial. Swaps the privval for an
    UnsafeSigner (double-signing requires bypassing FilePV's HRS guard)
    and installs the behavior's decision seams. Returns the harness;
    call start()/stop() around the node's lifetime for flood mode."""
    cs.priv_validator = UnsafeSigner(cs.priv_validator.priv_key)
    return ByzantineHarness(cs, behavior, send=send)
