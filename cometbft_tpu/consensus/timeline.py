"""Consensus heightline: bounded per-height event ring + fleet aggregation.

Every node records monotonic+wall timestamps for the consensus critical
path (proposal sent/received, first block part, proposal complete,
first/1/3/2/3 prevote, 2/3 precommit, commit, ABCI apply done) plus
per-peer vote-arrival lag.  The hooks in consensus/state.py and
consensus/reactor.py follow the span-tracer idiom (libs/trace.py): a
module-global ``_enabled`` bool guards every recording call, so the
disabled cost on the consensus path is one attribute load, one call and
one bool test — asserted <3% of a 1k-row batch verify in tier-1.

Phase anatomy is contiguous by construction: each phase ends exactly
where the next begins (new_height -> proposal_complete -> prevote_quorum
-> precommit_quorum -> commit -> apply_done), so the five durations tile
the height wall time and their sum covers >=95% of it whenever all marks
landed.

``aggregate()`` fuses the rings pulled from N nodes (the
``consensus_timeline`` RPC route) onto one fleet clock axis using the
per-peer skew model (libs/linkmodel.SkewEstimator), attributing proposal
propagation per node, naming the straggler and the slowest vote link.
``chrome_spans()`` renders the fused timeline into span records accepted
by libs/trace.chrome_trace for Perfetto export.

Slow heights (total above ``instrumentation.height_slow_ms``) auto-capture
a bounded postmortem bundle — the local timeline plus whatever the
node-installed collector contributes (span captures, gossip accounting,
wire-counter deltas, scheduler/mesh health) — served by the
``postmortems`` RPC route.
"""

from __future__ import annotations

import time
from collections import deque
from typing import Callable

# Critical-path marks, in nominal order.
NEW_HEIGHT = "new_height"
PROPOSAL_SENT = "proposal_sent"
PROPOSAL_RECEIVED = "proposal_received"
FIRST_BLOCK_PART = "first_block_part"
PROPOSAL_COMPLETE = "proposal_complete"
PREVOTE_FIRST = "prevote_first"
PREVOTE_THIRD = "prevote_third"
PREVOTE_QUORUM = "prevote_quorum"
PRECOMMIT_FIRST = "precommit_first"
PRECOMMIT_QUORUM = "precommit_quorum"
COMMIT = "commit"
APPLY_DONE = "apply_done"

MARKS = (
    NEW_HEIGHT, PROPOSAL_SENT, PROPOSAL_RECEIVED, FIRST_BLOCK_PART,
    PROPOSAL_COMPLETE, PREVOTE_FIRST, PREVOTE_THIRD, PREVOTE_QUORUM,
    PRECOMMIT_FIRST, PRECOMMIT_QUORUM, COMMIT, APPLY_DONE,
)

# Contiguous phase edges: (phase, start mark, end mark).
PHASES = ("propose", "prevote", "precommit", "commit", "apply")
_PHASE_EDGES = (
    ("propose", NEW_HEIGHT, PROPOSAL_COMPLETE),
    ("prevote", PROPOSAL_COMPLETE, PREVOTE_QUORUM),
    ("precommit", PREVOTE_QUORUM, PRECOMMIT_QUORUM),
    ("commit", PRECOMMIT_QUORUM, COMMIT),
    ("apply", COMMIT, APPLY_DONE),
)

_DEF_HEIGHTS = 64
_DEF_SLOW_MS = 0.0  # <= 0: slow-height postmortems off
_DEF_POSTMORTEMS = 8
_VOTE_PEER_CAP = 64  # per-height bound on distinct vote-lag peers

_enabled = False
_def_heights = _DEF_HEIGHTS
_def_slow_ms = _DEF_SLOW_MS
_def_postmortems = _DEF_POSTMORTEMS
_clock_mono: Callable[[], int] = time.monotonic_ns
_clock_wall: Callable[[], int] = time.time_ns


def enabled() -> bool:
    return _enabled


def configure(
    enabled: bool | None = None,
    heights: int | None = None,
    slow_ms: float | None = None,
    postmortems: int | None = None,
    clock_mono: Callable[[], int] | None = None,
    clock_wall: Callable[[], int] | None = None,
) -> None:
    """Set the global arm flag and the defaults new Recorders pick up.
    Injectable clocks keep the unit tests deterministic."""
    global _enabled, _def_heights, _def_slow_ms, _def_postmortems
    global _clock_mono, _clock_wall
    if enabled is not None:
        _enabled = bool(enabled)
    if heights is not None:
        _def_heights = max(1, int(heights))
    if slow_ms is not None:
        _def_slow_ms = float(slow_ms)
    if postmortems is not None:
        _def_postmortems = max(1, int(postmortems))
    if clock_mono is not None:
        _clock_mono = clock_mono
    if clock_wall is not None:
        _clock_wall = clock_wall


def reset() -> None:
    global _enabled, _def_heights, _def_slow_ms, _def_postmortems
    global _clock_mono, _clock_wall
    _enabled = False
    _def_heights = _DEF_HEIGHTS
    _def_slow_ms = _DEF_SLOW_MS
    _def_postmortems = _DEF_POSTMORTEMS
    _clock_mono = time.monotonic_ns
    _clock_wall = time.time_ns


# ----------------------------------------------------------- recorder


class Recorder:
    """Bounded per-height event ring for one node.

    Single-writer: every mark comes from the consensus receive task (the
    in-proc harness and the reactor both funnel through it), so the hot
    path takes no lock; snapshot()/postmortem reads copy plain dicts.
    """

    __slots__ = ("node", "heights", "slow_ms", "postmortem_cap",
                 "collector", "_ring", "_by_height", "_postmortems")

    def __init__(self, node: str = "", heights: int | None = None,
                 slow_ms: float | None = None,
                 postmortem_cap: int | None = None):
        self.node = node
        self.heights = int(heights if heights is not None else _def_heights)
        self.slow_ms = float(slow_ms if slow_ms is not None else _def_slow_ms)
        self.postmortem_cap = int(
            postmortem_cap if postmortem_cap is not None else _def_postmortems)
        # collector(height) -> dict of node context for postmortem bundles;
        # installed by node boot, absent in bare-harness runs.
        self.collector: Callable[[int], dict] | None = None
        self._ring: deque[int] = deque()
        self._by_height: dict[int, dict] = {}
        self._postmortems: deque[dict] = deque(maxlen=self.postmortem_cap)

    # -- write side (consensus task) ----------------------------------

    def _rec(self, height: int) -> dict:
        r = self._by_height.get(height)
        if r is None:
            r = {"height": height, "rounds": 0, "events": {}, "votes": {}}
            self._by_height[height] = r
            self._ring.append(height)
            while len(self._ring) > self.heights:
                self._by_height.pop(self._ring.popleft(), None)
        return r

    def mark(self, height: int, name: str, *, round_: int = 0,
             peer: str = "") -> None:
        """First-wins critical-path mark with monotonic+wall stamps."""
        if not _enabled:
            return
        r = self._rec(height)
        if round_ > r["rounds"]:
            r["rounds"] = round_
        ev = r["events"]
        if name in ev:
            return
        ev[name] = {"mono_ns": _clock_mono(), "wall_ns": _clock_wall(),
                    "round": round_, "peer": peer}

    def vote_arrival(self, height: int, round_: int, type_: int, peer: str,
                     vote_wall_ns: int) -> None:
        """Per-peer vote-arrival lag: local arrival wall clock minus the
        vote's signing timestamp (skew-uncorrected; aggregate() corrects
        with the fleet skew model)."""
        if not _enabled:
            return
        votes = self._rec(height)["votes"]
        now_wall = _clock_wall()
        lag = (now_wall - vote_wall_ns) / 1e6
        v = votes.get(peer)
        if v is None:
            if len(votes) >= _VOTE_PEER_CAP:
                return
            votes[peer] = {"n": 1, "lag_ms_sum": lag, "lag_ms_max": lag,
                           "first_wall_ns": now_wall, "last_wall_ns": now_wall}
            return
        v["n"] += 1
        v["lag_ms_sum"] += lag
        if lag > v["lag_ms_max"]:
            v["lag_ms_max"] = lag
        v["last_wall_ns"] = now_wall

    def height_done(self, height: int) -> None:
        """Close out a height; capture a postmortem if it ran slow.  At
        most one bundle per height regardless of how often this fires."""
        if not _enabled:
            return
        r = self._by_height.get(height)
        if r is None:
            return
        ev = r["events"]
        a, b = ev.get(NEW_HEIGHT), ev.get(APPLY_DONE)
        if a is None or b is None:
            return
        total = max(0.0, (b["mono_ns"] - a["mono_ns"]) / 1e6)
        r["total_ms"] = total
        if self.slow_ms > 0 and total > self.slow_ms and not any(
                p["height"] == height for p in self._postmortems):
            self._capture(height, r, total)

    def _capture(self, height: int, r: dict, total: float) -> None:
        bundle = {
            "height": height,
            "node": self.node,
            "total_ms": round(total, 3),
            "slow_ms": self.slow_ms,
            "captured_wall_ns": _clock_wall(),
            "timeline": self._render(r),
        }
        if self.collector is not None:
            # The collector gathers node context (span captures, gossip
            # accounting, wire deltas, scheduler health); it must never
            # take the consensus path down with it.
            try:
                bundle["context"] = self.collector(height)
            except Exception as exc:  # noqa: BLE001
                bundle["context_error"] = repr(exc)
        self._postmortems.append(bundle)

    # -- read side ----------------------------------------------------

    def _render(self, r: dict) -> dict:
        votes = {}
        for peer, v in r["votes"].items():
            votes[peer] = {
                "n": v["n"],
                "lag_ms_mean": round(v["lag_ms_sum"] / v["n"], 3),
                "lag_ms_max": round(v["lag_ms_max"], 3),
                "first_wall_ns": v["first_wall_ns"],
                "last_wall_ns": v["last_wall_ns"],
            }
        out = {
            "height": r["height"],
            "node": self.node,
            "rounds": r["rounds"],
            "events": {k: dict(v) for k, v in r["events"].items()},
            "votes": votes,
            "phases": phases_of(r["events"]),
        }
        if "total_ms" in r:
            out["total_ms"] = round(r["total_ms"], 3)
        return out

    def snapshot(self, min_height: int = 0, limit: int = 0) -> list[dict]:
        """Rendered height records, ascending by height."""
        hs = [h for h in self._ring if h >= min_height]
        if limit > 0:
            hs = hs[-limit:]
        return [self._render(self._by_height[h]) for h in hs
                if h in self._by_height]

    def postmortems(self) -> list[dict]:
        """Bounded list of bundle summaries (newest last)."""
        return [{"height": p["height"], "total_ms": p["total_ms"],
                 "slow_ms": p["slow_ms"],
                 "captured_wall_ns": p["captured_wall_ns"]}
                for p in self._postmortems]

    def postmortem(self, height: int) -> dict | None:
        for p in self._postmortems:
            if p["height"] == height:
                return p
        return None

    def clear(self) -> None:
        self._ring.clear()
        self._by_height.clear()
        self._postmortems.clear()


# ------------------------------------------------------------- phases


def phases_of(events: dict) -> dict:
    """Contiguous phase durations (ms) from one height's event marks;
    a phase whose edge marks are missing is None.  Durations use the
    monotonic stamps, so local clock steps cannot corrupt them."""
    out = {}
    for phase, start, end in _PHASE_EDGES:
        a, b = events.get(start), events.get(end)
        out[phase] = (None if a is None or b is None
                      else round(max(0.0, (b["mono_ns"] - a["mono_ns"]) / 1e6), 3))
    return out


def _quantile(vals: list[float], q: float) -> float | None:
    if not vals:
        return None
    s = sorted(vals)
    idx = min(len(s) - 1, max(0, int(round(q * (len(s) - 1)))))
    return s[idx]


# ---------------------------------------------------------- aggregate


def _doc_id(doc: dict) -> str:
    return str(doc.get("node_id") or doc.get("moniker") or doc.get("node") or "")


def aggregate(docs: list[dict]) -> dict:
    """Fuse per-node ``consensus_timeline`` documents onto one fleet axis.

    The first doc's clock is the reference axis.  Every other node's wall
    stamps are shifted by the reference node's skew estimate for it
    (offset = peer_clock - ref_clock), falling back to the negated
    reverse estimate, else zero.  Emits per-height phase anatomy with
    per-node proposal-propagation lag, the straggler (slowest node to
    assemble the proposal) and the slowest vote link.
    """
    docs = [d for d in docs if d]
    if not docs:
        return {"ref": "", "offsets_ms": {}, "heights": [], "summary": {}}
    ref = docs[0]
    ref_id = _doc_id(ref)
    ref_skew = ref.get("skew") or {}
    offsets: dict[str, float] = {ref_id: 0.0}
    for d in docs[1:]:
        nid = _doc_id(d)
        ent = ref_skew.get(nid)
        if ent is not None and ent.get("offset_ms") is not None:
            offsets[nid] = float(ent["offset_ms"])
            continue
        back = (d.get("skew") or {}).get(ref_id)
        if back is not None and back.get("offset_ms") is not None:
            offsets[nid] = -float(back["offset_ms"])
        else:
            offsets[nid] = 0.0

    heights: dict[int, dict] = {}
    for d in docs:
        nid = _doc_id(d)
        off_ns = offsets.get(nid, 0.0) * 1e6
        for rec in d.get("heights", []):
            h = int(rec["height"])
            hh = heights.setdefault(h, {})
            events = {}
            for name, ev in (rec.get("events") or {}).items():
                ev = dict(ev)
                ev["fleet_wall_ns"] = ev["wall_ns"] - off_ns
                events[name] = ev
            hh[nid] = {
                "events": events,
                "phases": rec.get("phases") or {},
                "votes": rec.get("votes") or {},
                "total_ms": rec.get("total_ms"),
                "rounds": rec.get("rounds", 0),
            }

    out_heights = []
    prop_all: list[float] = []
    straggler_counts: dict[str, int] = {}
    phase_series: dict[str, list[float]] = {p: [] for p in PHASES}
    for h in sorted(heights):
        nodes = heights[h]
        proposer = None
        t_sent = None
        for nid, n in nodes.items():
            ev = n["events"].get(PROPOSAL_SENT)
            if ev is not None:
                proposer, t_sent = nid, ev["fleet_wall_ns"]
                break
        propagation: dict[str, float] = {}
        if t_sent is not None:
            for nid, n in nodes.items():
                pc = n["events"].get(PROPOSAL_COMPLETE)
                if pc is not None:
                    propagation[nid] = round(
                        max(0.0, (pc["fleet_wall_ns"] - t_sent) / 1e6), 3)
        straggler = max(propagation, key=propagation.get) if propagation else None
        if straggler is not None:
            straggler_counts[straggler] = straggler_counts.get(straggler, 0) + 1
            prop_all.extend(propagation.values())

        fleet_phases = {}
        for phase in PHASES:
            vals = {nid: n["phases"].get(phase) for nid, n in nodes.items()
                    if n["phases"].get(phase) is not None}
            if not vals:
                fleet_phases[phase] = None
                continue
            slowest = max(vals, key=vals.get)
            fleet_phases[phase] = {
                "max_ms": round(vals[slowest], 3),
                "mean_ms": round(sum(vals.values()) / len(vals), 3),
                "slowest": slowest,
            }
            phase_series[phase].append(vals[slowest])

        slowest_link = None
        worst = -1.0
        for nid, n in nodes.items():
            for peer, v in n["votes"].items():
                mean = v.get("lag_ms_mean")
                if mean is None and v.get("n"):
                    mean = v["lag_ms_sum"] / v["n"]
                if mean is None:
                    continue
                # raw lag = arrival (nid's clock) - signing stamp (peer's
                # clock); on the ref axis arrival loses off_nid and the
                # stamp loses off_peer, so the true link lag is
                # lag - off_nid + off_peer
                adj = mean - offsets.get(nid, 0.0) + offsets.get(peer, 0.0)
                if adj > worst:
                    worst = adj
                    slowest_link = {"from": peer, "to": nid,
                                    "lag_ms": round(adj, 3), "votes": v["n"]}

        totals = {nid: n["total_ms"] for nid, n in nodes.items()
                  if n["total_ms"] is not None}
        out_heights.append({
            "height": h,
            "proposer": proposer,
            "proposal_propagation_ms": propagation,
            "straggler": straggler,
            "phases": fleet_phases,
            "slowest_link": slowest_link,
            "total_ms": {nid: round(t, 3) for nid, t in totals.items()},
        })

    phase_mean = {p: (round(sum(v) / len(v), 3) if v else None)
                  for p, v in phase_series.items()}
    known = [v for v in phase_mean.values() if v is not None]
    summary = {
        "heights": len(out_heights),
        "nodes": sorted(offsets),
        "phase_ms": phase_mean,
        "phase_total_ms": round(sum(known), 3) if known else None,
        "proposal_propagation_p50_ms": _quantile(prop_all, 0.50),
        "proposal_propagation_p99_ms": _quantile(prop_all, 0.99),
        "straggler_heights": straggler_counts,
        "top_straggler": (max(straggler_counts, key=straggler_counts.get)
                          if straggler_counts else None),
    }
    return {"ref": ref_id, "offsets_ms": {k: round(v, 3) for k, v in offsets.items()},
            "heights": out_heights, "summary": summary}


# ------------------------------------------------------- chrome export


def chrome_spans(agg: dict, docs: list[dict]) -> list[dict]:
    """Render a fleet aggregate back into span records accepted by
    libs/trace.chrome_trace: one lane (tid) per node, an X span per
    height plus per-phase child spans on the common fleet axis, and an
    instant per raw event mark."""
    offsets = agg.get("offsets_ms") or {}
    spans: list[dict] = []
    t_min = None
    per_node: dict[str, list[tuple[int, dict]]] = {}
    for d in docs:
        if not d:
            continue
        nid = _doc_id(d)
        off_ns = offsets.get(nid, 0.0) * 1e6
        for rec in d.get("heights", []):
            evs = rec.get("events") or {}
            aligned = {k: v["wall_ns"] - off_ns for k, v in evs.items()}
            if aligned:
                lo = min(aligned.values())
                t_min = lo if t_min is None else min(t_min, lo)
            per_node.setdefault(nid, []).append((int(rec["height"]), {
                "aligned": aligned, "events": evs}))
    if t_min is None:
        return []
    next_id = 1
    for tid, nid in enumerate(sorted(per_node), start=1):
        for h, rec in per_node[nid]:
            al = rec["aligned"]
            a, b = al.get(NEW_HEIGHT), al.get(APPLY_DONE)
            parent = None
            if a is not None and b is not None and b >= a:
                parent = next_id
                next_id += 1
                spans.append({
                    "id": parent, "parent_id": None, "trace_id": h,
                    "name": f"height {h} [{nid}]", "cat": "heightline",
                    "t0_ns": int(a - t_min), "dur_ns": int(b - a),
                    "tid": tid, "attrs": {"height": h, "node": nid},
                })
            for phase, start, end in _PHASE_EDGES:
                pa, pb = al.get(start), al.get(end)
                if pa is None or pb is None or pb < pa:
                    continue
                sid = next_id
                next_id += 1
                spans.append({
                    "id": sid, "parent_id": parent, "trace_id": h,
                    "name": phase, "cat": "heightline",
                    "t0_ns": int(pa - t_min), "dur_ns": int(pb - pa),
                    "tid": tid, "attrs": {"height": h, "node": nid},
                })
            for name, t in al.items():
                sid = next_id
                next_id += 1
                spans.append({
                    "id": sid, "parent_id": parent, "trace_id": h,
                    "name": name, "cat": "heightline",
                    "t0_ns": int(t - t_min), "dur_ns": 0, "tid": tid,
                    "attrs": {"height": h, "node": nid, "instant": True,
                              "peer": rec["events"][name].get("peer", "")},
                })
    return spans
