"""BlockPool: parallel height requesters for catch-up sync.

Reference: blocksync/pool.go — up to 600 in-flight height requesters
(pool.go:22-26), <=20 pending per peer, each requester owning one height:
pick a peer, send the request, wait for the block (retry elsewhere on
timeout/redo). The pool exposes peek_two_blocks/pop_request/redo_request
to the reactor's apply loop.

asyncio redesign: one task per requester (goroutine analog); peer pick
waits on a condition instead of the reference's retry ticker.
"""

from __future__ import annotations

import asyncio
import time
from dataclasses import dataclass, field
from typing import Callable, Optional

from cometbft_tpu.libs import log as cmtlog
from cometbft_tpu.libs.service import BaseService, TaskRunner
from cometbft_tpu.types.block import Block
from cometbft_tpu.types.commit import ExtendedCommit

MAX_TOTAL_REQUESTERS = 600  # pool.go:36-42
MAX_PENDING_REQUESTS_PER_PEER = 20
REQUEST_TIMEOUT = 15.0
POOL_SPAWN_INTERVAL = 0.01

# a peer that hasn't sent us anything for this long while owing blocks is
# considered stalled (pool.go minRecvRate analog, simplified to a deadline)


@dataclass
class _BPPeer:
    peer_id: str
    base: int
    height: int
    num_pending: int = 0


@dataclass
class _BPRequester:
    height: int
    peer_id: str = ""
    block: Optional[Block] = None
    ext_commit: Optional[ExtendedCommit] = None
    banned: set = field(default_factory=set)  # peers tried and failed
    got_block: asyncio.Event = field(default_factory=asyncio.Event)
    task: Optional[asyncio.Task] = None


class BlockPool(BaseService):
    """pool.go:63 BlockPool."""

    def __init__(
        self,
        start_height: int,
        send_request: Callable[[int, str], "asyncio.Future | object"],
        on_peer_error: Callable[[str, str], None],
        logger: cmtlog.Logger | None = None,
    ):
        super().__init__("BlockPool", logger)
        self.height = start_height  # next height to process
        self.start_height = start_height
        self._send_request = send_request  # async fn(height, peer_id) -> bool
        self._on_peer_error = on_peer_error  # fn(reason, peer_id)
        self.peers: dict[str, _BPPeer] = {}
        self.requesters: dict[int, _BPRequester] = {}
        self.max_peer_height = 0
        self._tasks = TaskRunner("blockpool")
        self._peer_cond: asyncio.Event = asyncio.Event()
        self._started_at = 0.0
        self.blocks_synced = 0

    # ----------------------------------------------------------- lifecycle

    async def on_start(self) -> None:
        self._started_at = time.monotonic()
        self._tasks.spawn(self._make_requesters_routine(), name="bp-spawner")

    async def on_stop(self) -> None:
        for r in self.requesters.values():
            if r.task is not None:
                r.task.cancel()
        await self._tasks.cancel_all()

    # --------------------------------------------------------------- peers

    def set_peer_range(self, peer_id: str, base: int, height: int) -> None:
        """pool.go SetPeerRange: called on StatusResponse."""
        p = self.peers.get(peer_id)
        if p is not None:
            p.base, p.height = base, height
        else:
            self.peers[peer_id] = _BPPeer(peer_id, base, height)
        if height > self.max_peer_height:
            self.max_peer_height = height
        self._peer_cond.set()

    def remove_peer(self, peer_id: str) -> None:
        """pool.go RemovePeer: redo its requesters elsewhere."""
        self.peers.pop(peer_id, None)
        for r in self.requesters.values():
            if r.peer_id == peer_id and r.block is None:
                r.banned.add(peer_id)
                r.got_block.set()  # wake the task; it will retry
        self.max_peer_height = max((p.height for p in self.peers.values()), default=0)

    # -------------------------------------------------------------- blocks

    def add_block(self, peer_id: str, block: Block, ext_commit: ExtendedCommit | None,
                  _size: int) -> None:
        """pool.go AddBlock: only the assigned requester may deliver."""
        r = self.requesters.get(block.header.height)
        if r is None:
            # late/unsolicited block: height already processed or never asked
            if block.header.height > self.height:
                self._on_peer_error("unsolicited block", peer_id)
            return
        if r.peer_id != peer_id or r.block is not None:
            self._on_peer_error("block from wrong peer or duplicate", peer_id)
            return
        r.block = block
        r.ext_commit = ext_commit
        p = self.peers.get(peer_id)
        if p is not None:
            p.num_pending = max(0, p.num_pending - 1)
        r.got_block.set()

    def peek_two_blocks(self):
        """pool.go PeekTwoBlocks: (first, first_ext, second) or Nones."""
        r1 = self.requesters.get(self.height)
        r2 = self.requesters.get(self.height + 1)
        first = r1.block if r1 is not None else None
        first_ext = r1.ext_commit if r1 is not None else None
        second = r2.block if r2 is not None else None
        return first, first_ext, second

    def block_at(self, height: int):
        r = self.requesters.get(height)
        return (r.block, r.ext_commit) if r is not None else (None, None)

    def peer_of(self, height: int) -> str:
        r = self.requesters.get(height)
        return r.peer_id if r is not None else ""

    def pop_request(self) -> None:
        """pool.go PopRequest: height verified + applied."""
        r = self.requesters.pop(self.height, None)
        if r is not None and r.task is not None:
            r.task.cancel()
        self.height += 1
        self.blocks_synced += 1

    def redo_request(self, height: int) -> str:
        """pool.go RedoRequest: bad block — drop it and retry elsewhere.
        Returns the peer that served it (for punishment)."""
        r = self.requesters.get(height)
        if r is None:
            return ""
        bad_peer = r.peer_id
        r.banned.add(bad_peer)
        r.block = None
        r.ext_commit = None
        r.got_block.set()  # wake task to re-request
        return bad_peer

    # -------------------------------------------------------------- status

    def is_caught_up(self) -> bool:
        """pool.go IsCaughtUp: never claims caught-up with zero peers —
        a node that is behind must keep waiting for its peers to appear
        rather than limp into consensus."""
        if not self.peers:
            return False
        return self.height >= self.max_peer_height

    def sync_rate(self) -> float:
        dt = time.monotonic() - self._started_at
        return self.blocks_synced / dt if dt > 0 else 0.0

    # ----------------------------------------------------------- requesters

    async def _make_requesters_routine(self) -> None:
        """pool.go:108 makeRequestersRoutine."""
        while True:
            next_h = self.height + len(self.requesters)
            if (
                len(self.requesters) < MAX_TOTAL_REQUESTERS
                and next_h <= self.max_peer_height
            ):
                r = _BPRequester(height=next_h)
                self.requesters[next_h] = r
                r.task = self._tasks.spawn(
                    self._requester_routine(r), name=f"bp-req-{next_h}"
                )
            else:
                await asyncio.sleep(POOL_SPAWN_INTERVAL)

    def _pick_peer(self, r: _BPRequester) -> Optional[_BPPeer]:
        best = None
        for p in self.peers.values():
            if p.peer_id in r.banned or p.num_pending >= MAX_PENDING_REQUESTS_PER_PEER:
                continue
            if not (p.base <= r.height <= p.height):
                continue
            if best is None or p.num_pending < best.num_pending:
                best = p
        return best

    async def _requester_routine(self, r: _BPRequester) -> None:
        """pool.go:394 requestRoutine: acquire a block, hold it until the
        pool pops the height (task cancelled) or redoes it (loop back)."""
        while True:
            while r.block is None:
                peer = self._pick_peer(r)
                if peer is None:
                    if r.banned and self.peers and len(r.banned) >= len(self.peers):
                        r.banned.clear()  # every peer failed once: forgive, retry
                    self._peer_cond.clear()
                    try:
                        await asyncio.wait_for(self._peer_cond.wait(), 0.25)
                    except asyncio.TimeoutError:
                        pass
                    continue
                r.peer_id = peer.peer_id
                peer.num_pending += 1
                r.got_block.clear()
                try:
                    await self._send_request(r.height, peer.peer_id)
                    await asyncio.wait_for(r.got_block.wait(), REQUEST_TIMEOUT)
                except asyncio.TimeoutError:
                    peer.num_pending = max(0, peer.num_pending - 1)
                    r.banned.add(peer.peer_id)
                    self._on_peer_error("block request timed out", peer.peer_id)
                except Exception as e:  # noqa: BLE001 - send failure: try another peer
                    peer.num_pending = max(0, peer.num_pending - 1)
                    r.banned.add(peer.peer_id)
                    self.logger.debug("request send failed", height=r.height, err=str(e))
                # got_block fired (block / redo / remove) or timed out: re-check
            while r.block is not None:
                r.got_block.clear()
                await r.got_block.wait()
            # redo_request dropped the block: acquire again
