"""Blocksync reactor: catch up by downloading blocks from peers.

Reference: blocksync/reactor.go — channel 0x40, pool-driven parallel
requesters, VerifyCommitLight per height (reactor.go:463), ValidateBlock,
SaveBlock, ApplyBlock, then SwitchToConsensus (reactor.go:286-330).

TPU-first redesign of the verify loop (SURVEY §7 step 8, BASELINE config 3):
instead of one synchronous commit verification at a time, a WINDOW of
consecutive ready heights is staged through verify_batch_async — host
staging of commit N+1 overlaps device compute of commit N, and the whole
window's masks come back in one device fetch (resolve_batches). Each commit
is verified ONCE on device with full verify_commit semantics (covering both
the reference's VerifyCommitLight pre-check and validateBlock's re-check,
which ApplyBlock then skips via last_commit_verified).
"""

from __future__ import annotations

import asyncio
import time

from cometbft_tpu.blocksync import messages as bm
from cometbft_tpu.blocksync.pool import BlockPool
from cometbft_tpu.libs import log as cmtlog
from cometbft_tpu.libs import trace
from cometbft_tpu.libs.service import TaskRunner
from cometbft_tpu.p2p.base_reactor import Envelope, Reactor
from cometbft_tpu.p2p.conn.connection import ChannelDescriptor
from cometbft_tpu.types import validation
from cometbft_tpu.types.basic import BlockID

BLOCKSYNC_CHANNEL = 0x40
# commit-certificate exchange (framework extension, no reference analog):
# cert frames ride their OWN channel so support is negotiated in the p2p
# handshake's channel list, exactly like the consensus VoteSummary channel
# (0x24) — a peer that never advertises 0x25 never sees a cert frame and
# syncs through the classic per-vote window path
CERT_CHANNEL = 0x25
BLOCK_PART_SIZE = 65536
STATUS_UPDATE_INTERVAL = 10.0
VERIFY_WINDOW = 8  # heights staged on device concurrently
TRY_SYNC_INTERVAL = 0.01
MAX_HELD_CERTS = 1024  # peer-served certs awaiting their window


class _CertStaged:
    """Stand-in for a StagedCommitVerification when a commit certificate
    already proved the height: nothing to prefetch, finish() is a no-op.
    The pool routine treats it like any staged entry."""

    def finish(self) -> None:
        return None


class BlocksyncReactor(Reactor):
    def __init__(
        self,
        block_exec,
        block_store,
        active: bool,
        consensus_reactor=None,
        window: int = VERIFY_WINDOW,
        cert_plane=None,
        cert_serve: bool = True,
        logger: cmtlog.Logger | None = None,
    ):
        super().__init__("Blocksync", logger)
        self.block_exec = block_exec
        self.block_store = block_store
        self.active = active  # syncing (vs serving only)
        self.consensus_reactor = consensus_reactor
        self.window = window
        self.state = None  # set via set_state before start
        self.pool: BlockPool | None = None
        self._tasks = TaskRunner("blocksync")
        self._verified_commits: set[bytes] = set()
        self._status_task = None
        self.synced_at: float = 0.0
        self.device_busy_s: float = 0.0  # time spent waiting on device masks
        # commit-certificate plane (None = cert exchange off; the 0x25
        # channel is then never advertised and peers treat us as classic)
        self.cert_plane = cert_plane
        self.cert_serve = cert_serve and cert_plane is not None
        self._held_certs: dict = {}  # height -> CommitCertificate
        self.certs_requested = 0  # CertRequests sent to 0x25-capable peers
        self.certs_received = 0   # well-formed certs accepted into holding
        self.certs_served = 0     # CertResponses answered with a cert
        self.cert_heights = 0     # window heights proved by a certificate
        self.certs_rejected = 0   # corrupt/mismatched/failed certs (no ban)

    def set_state(self, state) -> None:
        self.state = state

    # ------------------------------------------------------------- channels

    def get_channels(self) -> list[ChannelDescriptor]:
        chans = [
            ChannelDescriptor(
                id=BLOCKSYNC_CHANNEL, priority=5, send_queue_capacity=1000,
                recv_message_capacity=1 << 22,
            )
        ]
        if self.cert_plane is not None:
            # advertising the channel IS the capability announcement
            # (the VoteSummary 0x24 idiom)
            chans.append(ChannelDescriptor(
                id=CERT_CHANNEL, priority=2, send_queue_capacity=64))
        return chans

    # ------------------------------------------------------------ lifecycle

    async def on_start(self) -> None:
        if self.active:
            await self._start_sync()

    async def _start_sync(self) -> None:
        if self.state is None:
            raise RuntimeError("BlocksyncReactor.set_state before start")
        self.pool = BlockPool(
            self.state.last_block_height + 1 if self.state.last_block_height
            else self.state.initial_height,
            self._send_block_request,
            self._on_pool_peer_error,
            logger=self.logger,
        )
        await self.pool.start()
        self._tasks.spawn(self._pool_routine(), name="bcs-pool")
        self._status_task = self._tasks.spawn(
            self._status_broadcast_routine(), name="bcs-status")

    async def activate(self, state) -> None:
        """Start syncing AFTER boot — the statesync handoff (node.go
        stateSync -> blockSync switch): the pool begins at the restored
        state's height + 1."""
        if self.active:
            return
        self.active = True
        self.state = state
        await self._start_sync()
        # peers that connected while we were state-syncing: ask for their
        # ranges right away (the broadcast routine also fires immediately,
        # this is belt-and-braces for a changed interval)
        if self.switch is not None:
            self.switch.broadcast(BLOCKSYNC_CHANNEL, bm.encode(bm.StatusRequest()))

    async def on_stop(self) -> None:
        await self._tasks.cancel_all()
        if self.pool is not None and self.pool.is_running:
            await self.pool.stop()

    # ----------------------------------------------------------------- p2p

    async def add_peer(self, peer) -> None:
        """reactor.go AddPeer: advertise our range."""
        await peer.send(BLOCKSYNC_CHANNEL, bm.encode(
            bm.StatusResponse(self.block_store.height(), self.block_store.base())))

    async def remove_peer(self, peer, reason) -> None:
        if self.pool is not None:
            self.pool.remove_peer(peer.id)

    async def receive(self, e: Envelope) -> None:
        try:
            msg = bm.decode(e.message)
        except Exception as err:  # noqa: BLE001
            if e.channel_id == CERT_CHANNEL:
                # certificates are an accept-only optimization: a garbled
                # frame costs the peer nothing but the shortcut (never a
                # ban — contrast the block channel below, where garbage
                # stalls the sync itself)
                self.certs_rejected += 1
                self.logger.debug("bad cert frame", err=str(err), peer=e.src.id)
                return
            self.logger.error("bad blocksync message", err=str(err), peer=e.src.id)
            await self._punish(e.src.id, f"undecodable message: {err}")
            return
        if isinstance(msg, (bm.CertRequest, bm.CertResponse, bm.NoCertResponse)):
            await self._receive_cert_message(msg, e.src)
            return
        if isinstance(msg, bm.StatusRequest):
            await e.src.send(BLOCKSYNC_CHANNEL, bm.encode(
                bm.StatusResponse(self.block_store.height(), self.block_store.base())))
        elif isinstance(msg, bm.StatusResponse):
            if self.active and self.pool is not None:
                self.pool.set_peer_range(e.src.id, msg.base, msg.height)
        elif isinstance(msg, bm.BlockRequest):
            await self._respond_to_block_request(msg, e.src)
        elif isinstance(msg, bm.NoBlockResponse):
            self.logger.debug("peer has no block", height=msg.height, peer=e.src.id)
        elif isinstance(msg, bm.BlockResponse):
            if self.active and self.pool is not None:
                self.pool.add_block(e.src.id, msg.block, msg.ext_commit, len(e.message))

    async def _receive_cert_message(self, msg, peer) -> None:
        """Commit-certificate exchange on 0x25. Serving reads straight off
        the cert plane; received certs are parked until their height's
        window stages (where they substitute ONE pairing for the per-vote
        batch). Every failure path here degrades to classic verification —
        a certificate can only ever remove work, never add risk."""
        from cometbft_tpu.cert import CommitCertificate

        if isinstance(msg, bm.CertRequest):
            raw = self.cert_plane.serve(msg.height) if self.cert_serve else None
            if raw is None:
                await peer.send(CERT_CHANNEL, bm.encode(bm.NoCertResponse(msg.height)))
            else:
                self.certs_served += 1
                await peer.send(CERT_CHANNEL, bm.encode(bm.CertResponse(msg.height, raw)))
        elif isinstance(msg, bm.CertResponse):
            try:
                cert = CommitCertificate.decode(msg.cert)
                if cert.height != msg.height:
                    raise ValueError(
                        f"cert height {cert.height} != response height {msg.height}")
            except Exception as err:  # noqa: BLE001 - corrupt cert: count, no ban
                self.certs_rejected += 1
                self.logger.debug("undecodable cert", height=msg.height,
                                  err=str(err), peer=peer.id)
                return
            if len(self._held_certs) < MAX_HELD_CERTS:
                self._held_certs[cert.height] = cert
                self.certs_received += 1
        # NoCertResponse: peer simply has no cert — classic path runs

    async def _respond_to_block_request(self, msg: bm.BlockRequest, peer) -> None:
        """reactor.go respondToPeer."""
        block = self.block_store.load_block(msg.height)
        if block is None:
            await peer.send(BLOCKSYNC_CHANNEL, bm.encode(bm.NoBlockResponse(msg.height)))
            return
        ext = self.block_store.load_block_extended_commit(msg.height)
        await peer.send(BLOCKSYNC_CHANNEL, bm.encode(bm.BlockResponse(block, ext)))

    # ------------------------------------------------------------ pool glue

    async def _send_block_request(self, height: int, peer_id: str) -> None:
        peer = self.switch.get_peer(peer_id) if self.switch else None
        if peer is None:
            raise ConnectionError(f"peer {peer_id} gone")
        ok = await peer.send(BLOCKSYNC_CHANNEL, bm.encode(bm.BlockRequest(height)))
        if not ok:
            raise ConnectionError(f"send to {peer_id} failed")
        # opportunistically ask a 0x25-capable peer for the height's commit
        # certificate alongside the block: if it lands before the window
        # stages, the height verifies with one pairing instead of a
        # per-vote batch; if not, nothing changes
        if (self.cert_plane is not None
                and height not in self._held_certs
                and CERT_CHANNEL in (peer.node_info.channels or b"")):
            self.certs_requested += 1
            await peer.send(CERT_CHANNEL, bm.encode(bm.CertRequest(height)))

    def _on_pool_peer_error(self, reason: str, peer_id: str) -> None:
        task = self._punish(peer_id, reason)
        self._tasks.spawn(task, name="bcs-punish")

    async def _punish(self, peer_id: str, reason: str) -> None:
        if self.switch is None:
            return
        peer = self.switch.get_peer(peer_id)
        if peer is not None:
            await self.switch.stop_peer_for_error(peer, reason)

    # --------------------------------------------------------- status bcast

    async def _status_broadcast_routine(self) -> None:
        while True:
            if self.switch is not None:
                self.switch.broadcast(BLOCKSYNC_CHANNEL, bm.encode(bm.StatusRequest()))
            await asyncio.sleep(STATUS_UPDATE_INTERVAL)

    # ------------------------------------------------- the TPU apply loop

    async def _pool_routine(self) -> None:
        """reactor.go:286 poolRoutine, windowed AND pipelined: while window
        N's masks are in flight on the device, window N+1 is staged (the
        host-heavy part: sign-bytes + SHA-512 challenges + dispatch), so
        the device never idles between windows. The staged-ahead window is
        used only if the pool height after applying window N lands exactly
        on its first height; any redo/invalid-block path discards it (the
        staging is speculative work, never speculative state)."""
        chain_id = self.state.chain_id
        staged_ahead: list | None = None
        while True:
            if self.pool.is_caught_up():
                await self._switch_to_consensus()
                return
            if staged_ahead and staged_ahead[0][0] == self.pool.height:
                entries = staged_ahead
            else:
                entries = self._stage_window(chain_id, self.pool.height)
            staged_ahead = None
            if not entries:
                await asyncio.sleep(TRY_SYNC_INTERVAL)
                continue
            # device->host mask fetch must not stall the p2p event loop;
            # timing runs INSIDE the worker so device_busy_s measures the
            # fetch alone, not the overlapped staging below
            # sync-class: the window yields the device to consensus-
            # critical flushes in the global verify scheduler, and queued
            # mempool-admission rows ride the window batch as filler
            def _timed_prefetch(batch=[e[-1] for e in entries
                                       if not isinstance(e[-1], _CertStaged)],
                                h0=entries[0][0]):
                if not batch:  # whole window proved by certificates
                    return 0.0
                t0 = time.monotonic()
                # root span per verify window (fresh context on the
                # executor thread): a slow window keeps its full tree —
                # header fetch, payload pulls, host re-checks — in the
                # slow-batch capture ring
                with trace.span("sync.window", cat="sync", height=h0,
                                heights=len(batch)):
                    validation.prefetch_staged(batch, klass="sync")
                return time.monotonic() - t0

            fetch = asyncio.get_running_loop().run_in_executor(
                None, _timed_prefetch)
            # overlap: stage the next window while the fetch is in flight
            # (same valset assumption — _stage_window stops at a change)
            staged_ahead = self._stage_window(chain_id, entries[-1][0] + 1)
            self.device_busy_s += await fetch
            for h, first, first_ext, second, parts, first_id, staged in entries:
                if h != self.pool.height:
                    break  # an earlier redo shifted the window
                try:
                    staged.finish()
                    self._check_extensions(first, first_ext)
                    lc_ok = (
                        first.last_commit is not None
                        and first.last_commit.hash() in self._verified_commits
                    )
                    self.block_exec.validate_block(
                        self.state, first, last_commit_verified=lc_ok)
                except Exception as err:  # noqa: BLE001 - bad block: redo + punish
                    self.logger.error("invalid block in sync", height=h, err=str(err))
                    p1 = self.pool.redo_request(h)
                    p2 = self.pool.redo_request(h + 1)
                    for pid in {p1, p2} - {""}:
                        await self._punish(pid, f"sent invalid block {h}: {err}")
                    break
                # commit for height h (second.last_commit) is device-verified
                self._remember_verified(second.last_commit.hash())
                self.pool.pop_request()
                if self.state.consensus_params.abci.vote_extensions_enabled(h):
                    self.block_store.save_block_with_extended_commit(
                        first, parts, first_ext)
                else:
                    self.block_store.save_block(first, parts, second.last_commit)
                self.state = await self.block_exec.apply_block(
                    self.state, first_id, first, validated=True)
                if self.pool.blocks_synced % 100 == 0:
                    self.logger.info(
                        "block sync rate", height=self.pool.height,
                        max_peer=self.pool.max_peer_height,
                        bps=round(self.pool.sync_rate(), 1))

    def _stage_window(self, chain_id: str, start_height: int):
        """Stage up to `window` consecutive verifications from
        start_height. Stops at a valset change boundary (staged batches
        assume the current valset)."""
        entries = []
        h = start_height
        # certs that arrived after their height was already applied
        # would otherwise pin holding slots forever
        for k in [k for k in self._held_certs if k < start_height]:
            del self._held_certs[k]
        vals = self.state.validators
        vals_hash = vals.hash()
        with trace.span("sync.stage_window", cat="sync",
                        height=start_height) as stage_sp:
            try:
                self._stage_window_inner(chain_id, vals, vals_hash, h,
                                         entries)
            finally:
                stage_sp.set(heights=len(entries))
        return entries

    def _stage_window_inner(self, chain_id: str, vals, vals_hash,
                            h: int, entries: list) -> None:
        while len(entries) < self.window:
            first, first_ext = self.pool.block_at(h)
            second, _ = self.pool.block_at(h + 1)
            if first is None or second is None:
                break
            if first.header.validators_hash != vals_hash:
                # valset changes at h: process what we have; the rest after
                # state catches up (next loop uses the updated valset)
                break
            parts = first.make_part_set(BLOCK_PART_SIZE)
            first_id = BlockID(hash=first.hash(), part_set_header=parts.header())
            if self._cert_proves(chain_id, vals, h, first_id, second.last_commit):
                entries.append((h, first, first_ext, second, parts, first_id,
                                _CertStaged()))
                h += 1
                continue
            try:
                staged = validation.stage_verify_commit(
                    chain_id, vals, first_id, h, second.last_commit)
            except Exception as err:  # noqa: BLE001 - structurally bad: redo now
                self.logger.error("commit rejected in staging", height=h, err=str(err))
                p1 = self.pool.redo_request(h)
                p2 = self.pool.redo_request(h + 1)
                for pid in {p1, p2} - {""}:
                    self._on_pool_peer_error(f"bad commit for {h}: {err}", pid)
                break
            entries.append((h, first, first_ext, second, parts, first_id, staged))
            h += 1

    def _cert_proves(self, chain_id: str, vals, h: int, first_id,
                     commit) -> bool:
        """True iff a held certificate fully proves height h's commit:
        it names this exact block, attests THIS commit's signature set
        (matching bitmap/timestamps AND an aggregate-sum equal to the
        cert's — so a mauled commit can't hide behind an honest cert),
        and its one pairing-product check passes against the current
        valset. Any failure is counted and falls through to the classic
        per-vote staging — bit-identical verdicts, never a peer ban."""
        cert = self._held_certs.pop(h, None)
        if cert is None or self.cert_plane is None:
            return False
        from cometbft_tpu import cert as certmod

        try:
            if cert.block_id != first_id or not certmod.attests_commit(cert, commit):
                raise certmod.ErrCertInvalid("certificate does not attest synced commit")
            certmod.verify_certificate(cert, chain_id, vals)
        except certmod.ErrCertInvalid as err:
            self.certs_rejected += 1
            self.cert_plane.count_verify_failure()
            self.logger.debug("cert rejected; classic verification",
                              height=h, err=str(err))
            return False
        self.cert_heights += 1
        self.cert_plane.count_verified()
        return True

    def _check_extensions(self, first, first_ext) -> None:
        """reactor.go:471-480."""
        if self.state.consensus_params.abci.vote_extensions_enabled(first.header.height):
            if first_ext is None:
                raise ValueError(
                    f"no extended commit for height {first.header.height} "
                    "(extensions enabled)")
            first_ext.ensure_extensions(True)
        elif first_ext is not None:
            raise ValueError(
                f"non-nil extended commit for height {first.header.height} "
                "(extensions disabled)")

    def _remember_verified(self, commit_hash: bytes) -> None:
        if len(self._verified_commits) > 4096:
            self._verified_commits.clear()
        self._verified_commits.add(commit_hash)

    # ----------------------------------------------------------- handoff

    async def _switch_to_consensus(self) -> None:
        """reactor.go:286-330 SwitchToConsensus."""
        self.synced_at = time.monotonic()
        if self._status_task is not None:
            self._status_task.cancel()
            self._status_task = None
        self.logger.info(
            "caught up; switching to consensus",
            height=self.pool.height, synced=self.pool.blocks_synced,
            device_busy_s=round(self.device_busy_s, 3))
        await self.pool.stop()
        self.active = False
        if self.consensus_reactor is not None:
            await self.consensus_reactor.switch_to_consensus(self.state)
