"""Blocksync reactor: catch up by downloading blocks from peers.

Reference: blocksync/reactor.go — channel 0x40, pool-driven parallel
requesters, VerifyCommitLight per height (reactor.go:463), ValidateBlock,
SaveBlock, ApplyBlock, then SwitchToConsensus (reactor.go:286-330).

TPU-first redesign of the verify loop (SURVEY §7 step 8, BASELINE config 3):
instead of one synchronous commit verification at a time, a WINDOW of
consecutive ready heights is staged through verify_batch_async — host
staging of commit N+1 overlaps device compute of commit N, and the whole
window's masks come back in one device fetch (resolve_batches). Each commit
is verified ONCE on device with full verify_commit semantics (covering both
the reference's VerifyCommitLight pre-check and validateBlock's re-check,
which ApplyBlock then skips via last_commit_verified).
"""

from __future__ import annotations

import asyncio
import time

from cometbft_tpu.blocksync import messages as bm
from cometbft_tpu.blocksync.pool import BlockPool
from cometbft_tpu.libs import log as cmtlog
from cometbft_tpu.libs import trace
from cometbft_tpu.libs.service import TaskRunner
from cometbft_tpu.p2p.base_reactor import Envelope, Reactor
from cometbft_tpu.p2p.conn.connection import ChannelDescriptor
from cometbft_tpu.types import validation
from cometbft_tpu.types.basic import BlockID

BLOCKSYNC_CHANNEL = 0x40
BLOCK_PART_SIZE = 65536
STATUS_UPDATE_INTERVAL = 10.0
VERIFY_WINDOW = 8  # heights staged on device concurrently
TRY_SYNC_INTERVAL = 0.01


class BlocksyncReactor(Reactor):
    def __init__(
        self,
        block_exec,
        block_store,
        active: bool,
        consensus_reactor=None,
        window: int = VERIFY_WINDOW,
        logger: cmtlog.Logger | None = None,
    ):
        super().__init__("Blocksync", logger)
        self.block_exec = block_exec
        self.block_store = block_store
        self.active = active  # syncing (vs serving only)
        self.consensus_reactor = consensus_reactor
        self.window = window
        self.state = None  # set via set_state before start
        self.pool: BlockPool | None = None
        self._tasks = TaskRunner("blocksync")
        self._verified_commits: set[bytes] = set()
        self._status_task = None
        self.synced_at: float = 0.0
        self.device_busy_s: float = 0.0  # time spent waiting on device masks

    def set_state(self, state) -> None:
        self.state = state

    # ------------------------------------------------------------- channels

    def get_channels(self) -> list[ChannelDescriptor]:
        return [
            ChannelDescriptor(
                id=BLOCKSYNC_CHANNEL, priority=5, send_queue_capacity=1000,
                recv_message_capacity=1 << 22,
            )
        ]

    # ------------------------------------------------------------ lifecycle

    async def on_start(self) -> None:
        if self.active:
            await self._start_sync()

    async def _start_sync(self) -> None:
        if self.state is None:
            raise RuntimeError("BlocksyncReactor.set_state before start")
        self.pool = BlockPool(
            self.state.last_block_height + 1 if self.state.last_block_height
            else self.state.initial_height,
            self._send_block_request,
            self._on_pool_peer_error,
            logger=self.logger,
        )
        await self.pool.start()
        self._tasks.spawn(self._pool_routine(), name="bcs-pool")
        self._status_task = self._tasks.spawn(
            self._status_broadcast_routine(), name="bcs-status")

    async def activate(self, state) -> None:
        """Start syncing AFTER boot — the statesync handoff (node.go
        stateSync -> blockSync switch): the pool begins at the restored
        state's height + 1."""
        if self.active:
            return
        self.active = True
        self.state = state
        await self._start_sync()
        # peers that connected while we were state-syncing: ask for their
        # ranges right away (the broadcast routine also fires immediately,
        # this is belt-and-braces for a changed interval)
        if self.switch is not None:
            self.switch.broadcast(BLOCKSYNC_CHANNEL, bm.encode(bm.StatusRequest()))

    async def on_stop(self) -> None:
        await self._tasks.cancel_all()
        if self.pool is not None and self.pool.is_running:
            await self.pool.stop()

    # ----------------------------------------------------------------- p2p

    async def add_peer(self, peer) -> None:
        """reactor.go AddPeer: advertise our range."""
        await peer.send(BLOCKSYNC_CHANNEL, bm.encode(
            bm.StatusResponse(self.block_store.height(), self.block_store.base())))

    async def remove_peer(self, peer, reason) -> None:
        if self.pool is not None:
            self.pool.remove_peer(peer.id)

    async def receive(self, e: Envelope) -> None:
        try:
            msg = bm.decode(e.message)
        except Exception as err:  # noqa: BLE001
            self.logger.error("bad blocksync message", err=str(err), peer=e.src.id)
            await self._punish(e.src.id, f"undecodable message: {err}")
            return
        if isinstance(msg, bm.StatusRequest):
            await e.src.send(BLOCKSYNC_CHANNEL, bm.encode(
                bm.StatusResponse(self.block_store.height(), self.block_store.base())))
        elif isinstance(msg, bm.StatusResponse):
            if self.active and self.pool is not None:
                self.pool.set_peer_range(e.src.id, msg.base, msg.height)
        elif isinstance(msg, bm.BlockRequest):
            await self._respond_to_block_request(msg, e.src)
        elif isinstance(msg, bm.NoBlockResponse):
            self.logger.debug("peer has no block", height=msg.height, peer=e.src.id)
        elif isinstance(msg, bm.BlockResponse):
            if self.active and self.pool is not None:
                self.pool.add_block(e.src.id, msg.block, msg.ext_commit, len(e.message))

    async def _respond_to_block_request(self, msg: bm.BlockRequest, peer) -> None:
        """reactor.go respondToPeer."""
        block = self.block_store.load_block(msg.height)
        if block is None:
            await peer.send(BLOCKSYNC_CHANNEL, bm.encode(bm.NoBlockResponse(msg.height)))
            return
        ext = self.block_store.load_block_extended_commit(msg.height)
        await peer.send(BLOCKSYNC_CHANNEL, bm.encode(bm.BlockResponse(block, ext)))

    # ------------------------------------------------------------ pool glue

    async def _send_block_request(self, height: int, peer_id: str) -> None:
        peer = self.switch.get_peer(peer_id) if self.switch else None
        if peer is None:
            raise ConnectionError(f"peer {peer_id} gone")
        ok = await peer.send(BLOCKSYNC_CHANNEL, bm.encode(bm.BlockRequest(height)))
        if not ok:
            raise ConnectionError(f"send to {peer_id} failed")

    def _on_pool_peer_error(self, reason: str, peer_id: str) -> None:
        task = self._punish(peer_id, reason)
        self._tasks.spawn(task, name="bcs-punish")

    async def _punish(self, peer_id: str, reason: str) -> None:
        if self.switch is None:
            return
        peer = self.switch.get_peer(peer_id)
        if peer is not None:
            await self.switch.stop_peer_for_error(peer, reason)

    # --------------------------------------------------------- status bcast

    async def _status_broadcast_routine(self) -> None:
        while True:
            if self.switch is not None:
                self.switch.broadcast(BLOCKSYNC_CHANNEL, bm.encode(bm.StatusRequest()))
            await asyncio.sleep(STATUS_UPDATE_INTERVAL)

    # ------------------------------------------------- the TPU apply loop

    async def _pool_routine(self) -> None:
        """reactor.go:286 poolRoutine, windowed AND pipelined: while window
        N's masks are in flight on the device, window N+1 is staged (the
        host-heavy part: sign-bytes + SHA-512 challenges + dispatch), so
        the device never idles between windows. The staged-ahead window is
        used only if the pool height after applying window N lands exactly
        on its first height; any redo/invalid-block path discards it (the
        staging is speculative work, never speculative state)."""
        chain_id = self.state.chain_id
        staged_ahead: list | None = None
        while True:
            if self.pool.is_caught_up():
                await self._switch_to_consensus()
                return
            if staged_ahead and staged_ahead[0][0] == self.pool.height:
                entries = staged_ahead
            else:
                entries = self._stage_window(chain_id, self.pool.height)
            staged_ahead = None
            if not entries:
                await asyncio.sleep(TRY_SYNC_INTERVAL)
                continue
            # device->host mask fetch must not stall the p2p event loop;
            # timing runs INSIDE the worker so device_busy_s measures the
            # fetch alone, not the overlapped staging below
            # sync-class: the window yields the device to consensus-
            # critical flushes in the global verify scheduler, and queued
            # mempool-admission rows ride the window batch as filler
            def _timed_prefetch(batch=[e[-1] for e in entries],
                                h0=entries[0][0]):
                t0 = time.monotonic()
                # root span per verify window (fresh context on the
                # executor thread): a slow window keeps its full tree —
                # header fetch, payload pulls, host re-checks — in the
                # slow-batch capture ring
                with trace.span("sync.window", cat="sync", height=h0,
                                heights=len(batch)):
                    validation.prefetch_staged(batch, klass="sync")
                return time.monotonic() - t0

            fetch = asyncio.get_running_loop().run_in_executor(
                None, _timed_prefetch)
            # overlap: stage the next window while the fetch is in flight
            # (same valset assumption — _stage_window stops at a change)
            staged_ahead = self._stage_window(chain_id, entries[-1][0] + 1)
            self.device_busy_s += await fetch
            for h, first, first_ext, second, parts, first_id, staged in entries:
                if h != self.pool.height:
                    break  # an earlier redo shifted the window
                try:
                    staged.finish()
                    self._check_extensions(first, first_ext)
                    lc_ok = (
                        first.last_commit is not None
                        and first.last_commit.hash() in self._verified_commits
                    )
                    self.block_exec.validate_block(
                        self.state, first, last_commit_verified=lc_ok)
                except Exception as err:  # noqa: BLE001 - bad block: redo + punish
                    self.logger.error("invalid block in sync", height=h, err=str(err))
                    p1 = self.pool.redo_request(h)
                    p2 = self.pool.redo_request(h + 1)
                    for pid in {p1, p2} - {""}:
                        await self._punish(pid, f"sent invalid block {h}: {err}")
                    break
                # commit for height h (second.last_commit) is device-verified
                self._remember_verified(second.last_commit.hash())
                self.pool.pop_request()
                if self.state.consensus_params.abci.vote_extensions_enabled(h):
                    self.block_store.save_block_with_extended_commit(
                        first, parts, first_ext)
                else:
                    self.block_store.save_block(first, parts, second.last_commit)
                self.state = await self.block_exec.apply_block(
                    self.state, first_id, first, validated=True)
                if self.pool.blocks_synced % 100 == 0:
                    self.logger.info(
                        "block sync rate", height=self.pool.height,
                        max_peer=self.pool.max_peer_height,
                        bps=round(self.pool.sync_rate(), 1))

    def _stage_window(self, chain_id: str, start_height: int):
        """Stage up to `window` consecutive verifications from
        start_height. Stops at a valset change boundary (staged batches
        assume the current valset)."""
        entries = []
        h = start_height
        vals = self.state.validators
        vals_hash = vals.hash()
        with trace.span("sync.stage_window", cat="sync",
                        height=start_height) as stage_sp:
            try:
                self._stage_window_inner(chain_id, vals, vals_hash, h,
                                         entries)
            finally:
                stage_sp.set(heights=len(entries))
        return entries

    def _stage_window_inner(self, chain_id: str, vals, vals_hash,
                            h: int, entries: list) -> None:
        while len(entries) < self.window:
            first, first_ext = self.pool.block_at(h)
            second, _ = self.pool.block_at(h + 1)
            if first is None or second is None:
                break
            if first.header.validators_hash != vals_hash:
                # valset changes at h: process what we have; the rest after
                # state catches up (next loop uses the updated valset)
                break
            parts = first.make_part_set(BLOCK_PART_SIZE)
            first_id = BlockID(hash=first.hash(), part_set_header=parts.header())
            try:
                staged = validation.stage_verify_commit(
                    chain_id, vals, first_id, h, second.last_commit)
            except Exception as err:  # noqa: BLE001 - structurally bad: redo now
                self.logger.error("commit rejected in staging", height=h, err=str(err))
                p1 = self.pool.redo_request(h)
                p2 = self.pool.redo_request(h + 1)
                for pid in {p1, p2} - {""}:
                    self._on_pool_peer_error(f"bad commit for {h}: {err}", pid)
                break
            entries.append((h, first, first_ext, second, parts, first_id, staged))
            h += 1

    def _check_extensions(self, first, first_ext) -> None:
        """reactor.go:471-480."""
        if self.state.consensus_params.abci.vote_extensions_enabled(first.header.height):
            if first_ext is None:
                raise ValueError(
                    f"no extended commit for height {first.header.height} "
                    "(extensions enabled)")
            first_ext.ensure_extensions(True)
        elif first_ext is not None:
            raise ValueError(
                f"non-nil extended commit for height {first.header.height} "
                "(extensions disabled)")

    def _remember_verified(self, commit_hash: bytes) -> None:
        if len(self._verified_commits) > 4096:
            self._verified_commits.clear()
        self._verified_commits.add(commit_hash)

    # ----------------------------------------------------------- handoff

    async def _switch_to_consensus(self) -> None:
        """reactor.go:286-330 SwitchToConsensus."""
        self.synced_at = time.monotonic()
        if self._status_task is not None:
            self._status_task.cancel()
            self._status_task = None
        self.logger.info(
            "caught up; switching to consensus",
            height=self.pool.height, synced=self.pool.blocks_synced,
            device_busy_s=round(self.device_busy_s, 3))
        await self.pool.stop()
        self.active = False
        if self.consensus_reactor is not None:
            await self.consensus_reactor.switch_to_consensus(self.state)
