from cometbft_tpu.blocksync.pool import BlockPool
from cometbft_tpu.blocksync.reactor import BlocksyncReactor

__all__ = ["BlockPool", "BlocksyncReactor"]
