"""Blocksync wire messages + codec.

Reference: proto/tendermint/blocksync/types.proto and blocksync/msgs.go.
One Message envelope, oneof by field number:

  1 BlockRequest{1:height}
  2 NoBlockResponse{1:height}
  3 BlockResponse{1:block, 2:ext_commit}
  4 StatusRequest{}
  5 StatusResponse{1:height, 2:base}

Framework extension (no reference analog) — commit-certificate exchange,
negotiated via its own channel (0x25, reactor.py CERT_CHANNEL) exactly
like the consensus VoteSummary idiom, so non-supporting peers never see
these frames:

  6 CertRequest{1:height}
  7 CertResponse{1:height, 2:cert}   (cert = encoded CommitCertificate)
  8 NoCertResponse{1:height}
"""

from __future__ import annotations

from dataclasses import dataclass

from cometbft_tpu.types.block import Block
from cometbft_tpu.types.commit import ExtendedCommit
from cometbft_tpu.utils.protobuf import Reader, Writer


@dataclass
class BlockRequest:
    height: int


@dataclass
class NoBlockResponse:
    height: int


@dataclass
class BlockResponse:
    block: Block
    ext_commit: ExtendedCommit | None = None


@dataclass
class StatusRequest:
    pass


@dataclass
class CertRequest:
    height: int


@dataclass
class CertResponse:
    height: int
    cert: bytes  # encoded CommitCertificate (opaque at this layer)


@dataclass
class NoCertResponse:
    height: int


@dataclass
class StatusResponse:
    height: int
    base: int


def encode(msg) -> bytes:
    w = Writer()
    if isinstance(msg, BlockRequest):
        w.message(1, Writer().varint_i64(1, msg.height).output(), always=True)
    elif isinstance(msg, NoBlockResponse):
        w.message(2, Writer().varint_i64(1, msg.height).output(), always=True)
    elif isinstance(msg, BlockResponse):
        inner = Writer().message(1, msg.block.to_proto(), always=True)
        if msg.ext_commit is not None:
            from cometbft_tpu.store.blockstore import _extended_to_proto

            inner.message(2, _extended_to_proto(msg.ext_commit))
        w.message(3, inner.output(), always=True)
    elif isinstance(msg, StatusRequest):
        w.message(4, b"", always=True)
    elif isinstance(msg, StatusResponse):
        w.message(
            5,
            Writer().varint_i64(1, msg.height).varint_i64(2, msg.base).output(),
            always=True,
        )
    elif isinstance(msg, CertRequest):
        w.message(6, Writer().varint_i64(1, msg.height).output(), always=True)
    elif isinstance(msg, CertResponse):
        inner = Writer().varint_i64(1, msg.height).bytes(2, msg.cert)
        w.message(7, inner.output(), always=True)
    elif isinstance(msg, NoCertResponse):
        w.message(8, Writer().varint_i64(1, msg.height).output(), always=True)
    else:
        raise TypeError(f"cannot encode blocksync message {type(msg)}")
    return w.output()


def decode(data: bytes):
    r = Reader(data)
    f, _w = r.read_tag()
    body = r.read_bytes()
    br = Reader(body)
    if f == 1 or f == 2:
        height = 0
        while not br.at_end():
            g, w2 = br.read_tag()
            if g == 1:
                height = br.read_varint_i64()
            else:
                br.skip(w2)
        return BlockRequest(height) if f == 1 else NoBlockResponse(height)
    if f == 3:
        block, ec = None, None
        while not br.at_end():
            g, w2 = br.read_tag()
            if g == 1:
                block = Block.from_proto(br.read_bytes())
            elif g == 2:
                from cometbft_tpu.store.blockstore import _extended_from_proto

                ec = _extended_from_proto(br.read_bytes())
            else:
                br.skip(w2)
        if block is None:
            raise ValueError("BlockResponse without block")
        return BlockResponse(block, ec)
    if f == 4:
        return StatusRequest()
    if f == 5:
        height, base = 0, 0
        while not br.at_end():
            g, w2 = br.read_tag()
            if g == 1:
                height = br.read_varint_i64()
            elif g == 2:
                base = br.read_varint_i64()
            else:
                br.skip(w2)
        return StatusResponse(height, base)
    if f == 6 or f == 8:
        height = 0
        while not br.at_end():
            g, w2 = br.read_tag()
            if g == 1:
                height = br.read_varint_i64()
            else:
                br.skip(w2)
        return CertRequest(height) if f == 6 else NoCertResponse(height)
    if f == 7:
        height, cert = 0, b""
        while not br.at_end():
            g, w2 = br.read_tag()
            if g == 1:
                height = br.read_varint_i64()
            elif g == 2:
                cert = br.read_bytes()
            else:
                br.skip(w2)
        return CertResponse(height, cert)
    raise ValueError(f"unknown blocksync message field {f}")
