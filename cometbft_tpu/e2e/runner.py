"""Config-matrix runner: set up, start, perturb, and verify one manifest's
testnet of real OS processes over real TCP.

Reference: test/e2e/runner (main.go Setup/Start/Perturb/Test/Cleanup;
perturb.go:44-100). Differences are environmental: nodes are processes on
one host (no docker network, so "disconnect" lives in the in-proc
perturbation matrix instead), and out-of-process ABCI apps are one
`abci-cli kvstore` server per node on the manifest's transport."""

from __future__ import annotations

import concurrent.futures
import glob
import json
import os
import signal
import subprocess
import sys
import time
import urllib.parse
import urllib.request
from dataclasses import dataclass, field

from cometbft_tpu.e2e.manifest import Manifest

REPO = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


class RunError(Exception):
    pass


# ---------------------------------------------------------------- fleet
# Named netchaos link-profile bodies (p2p/netchaos.py profile syntax) for
# the regional topology's cross-region links: intra-region links stay
# clean, cross-region links pay WAN latency (and, for lossy-wan, loss).
LINK_PROFILES = {
    "wan": "latency:0.03;jitter:0.01",
    "lossy-wan": "latency:0.05;jitter:0.02;drop:0.005",
}

# resource-guard knobs (env-overridable; the error message names them):
# estimated per-node cost of one OS-process node on this host
NODE_RSS_MB = int(os.environ.get("CBFT_E2E_NODE_RSS_MB", "400"))
NODE_FDS = int(os.environ.get("CBFT_E2E_NODE_FDS", "96"))


def _ephemeral_port_range() -> tuple[int, int]:
    try:
        with open("/proc/sys/net/ipv4/ip_local_port_range") as f:
            lo, hi = (int(x) for x in f.read().split())
            return lo, hi
    except (OSError, ValueError):
        return 32768, 60999  # the Linux default


def _resource_guard(n_nodes: int, base_port: int | None = None) -> None:
    """Refuse to launch a fleet the host cannot hold — BEFORE node 0
    spawns, with an error naming the knob, instead of wedging mid-boot at
    node 70. Estimates are deliberately conservative; operators with
    bigger boxes override via env (CBFT_E2E_NODE_RSS_MB /
    CBFT_E2E_NODE_FDS) or disable with CBFT_E2E_RESOURCE_GUARD=0."""
    if os.environ.get("CBFT_E2E_RESOURCE_GUARD", "1") == "0":
        return
    # Listen ports colliding with the kernel's EPHEMERAL range is the
    # classic wedge-at-node-48: another node's outbound conn grabs the
    # port a later node was about to bind (found the hard way at 50
    # nodes — ~750 outbound conns vs. 150 pending listens is a birthday
    # problem). The net spans [base, base+2000+n] (p2p/rpc/abci
    # strides). Small nets keep their historical ports: a handful of
    # listens against a handful of conns is a negligible exposure.
    if base_port is not None and n_nodes >= 16:
        eph_lo, eph_hi = _ephemeral_port_range()
        span_hi = base_port + 2000 + n_nodes
        if base_port <= eph_hi and span_hi >= eph_lo:
            raise RunError(
                f"refusing to launch {n_nodes} nodes on base_port "
                f"{base_port}: the net's port span [{base_port}, {span_hi}]"
                f" overlaps the kernel ephemeral range [{eph_lo}, {eph_hi}]"
                f" — a peer's outbound conn can steal a listen port "
                f"mid-boot; pick base_port so the span ends below "
                f"{eph_lo} (or set CBFT_E2E_RESOURCE_GUARD=0)")
    # file descriptors: every node holds sockets to its peers + stores
    try:
        import resource

        soft, _ = resource.getrlimit(resource.RLIMIT_NOFILE)
    except Exception:  # noqa: BLE001 - exotic platform: skip the fd check
        soft = 0
    need_fds = n_nodes * NODE_FDS
    if soft and need_fds > soft:
        raise RunError(
            f"refusing to launch {n_nodes} nodes: estimated {need_fds} fds "
            f"(~{NODE_FDS}/node, knob CBFT_E2E_NODE_FDS) exceeds the "
            f"RLIMIT_NOFILE soft limit {soft}; raise `ulimit -n` or set "
            f"CBFT_E2E_RESOURCE_GUARD=0 to override")
    # memory: each node is a full python+jax process
    avail_mb = 0
    try:
        with open("/proc/meminfo") as f:
            for line in f:
                if line.startswith("MemAvailable:"):
                    avail_mb = int(line.split()[1]) // 1024
                    break
    except OSError:
        return  # no /proc: skip the memory check
    need_mb = n_nodes * NODE_RSS_MB
    if avail_mb and need_mb > avail_mb:
        raise RunError(
            f"refusing to launch {n_nodes} nodes: estimated {need_mb} MB "
            f"(~{NODE_RSS_MB} MB/node, knob CBFT_E2E_NODE_RSS_MB) exceeds "
            f"the {avail_mb} MB available; shrink the fleet or set "
            f"CBFT_E2E_RESOURCE_GUARD=0 to override")


def _topology_peers(manifest: Manifest, names: list[str], i: int) -> list[int]:
    """Which peers node i dials persistently, by topology. "full" is the
    classic everyone-dials-everyone; "hub" meshes the first `hubs` nodes
    and hangs every spoke off ALL hubs; "regional" meshes each region
    internally and meshes the region GATEWAYS (first node per region)
    across regions — cross-region traffic concentrates on the gateway
    links the netchaos profiles degrade. "organic" wires NOTHING: every
    node except the lone seed (node 0) boots with an empty address book
    and grows its peer set through PEX discovery alone."""
    n = len(names)
    others = [j for j in range(n) if j != i]
    if manifest.topology == "organic":
        return []
    if manifest.topology == "hub":
        hubs = list(range(min(manifest.hubs, n)))
        if i in hubs:
            return [j for j in hubs if j != i]
        return hubs
    if manifest.topology == "regional":
        regs = [manifest.nodes[nm].region for nm in names]
        # TWO gateways per region (the first two nodes), meshed across
        # regions: killing one gateway — a churn storm will — must not
        # partition the fleet
        gateways: dict[int, list[int]] = {}
        for j, r in enumerate(regs):
            gateways.setdefault(r, [])
            if len(gateways[r]) < 2:
                gateways[r].append(j)
        peers = [j for j in others if regs[j] == regs[i]]
        if i in gateways.get(regs[i], []):
            peers += [g for r, gs in sorted(gateways.items())
                      if r != regs[i] for g in gs]
        return peers
    return others


def _netchaos_spec(manifest: Manifest, names: list[str],
                   node_ids: list[str]) -> str:
    """The per-node p2p.chaos schedule for a regional fleet: the named
    link profile, every node's region, and one cross-region link mapping
    per region pair. Empty when the manifest asks for a clean wire."""
    if manifest.topology != "regional" or not manifest.link_profile:
        return ""
    prof = manifest.link_profile
    parts = [f"profile.{prof}={LINK_PROFILES[prof]}"]
    parts += [f"region={node_ids[i]}:r{manifest.nodes[nm].region}"
              for i, nm in enumerate(names)]
    regions = sorted({manifest.nodes[nm].region for nm in names})
    parts += [f"link.r{a}-r{b}={prof}"
              for ai, a in enumerate(regions) for b in regions[ai + 1:]]
    return ",".join(parts)


@dataclass
class _Net:
    manifest: Manifest
    dir: str
    base_port: int
    homes: list[str] = field(default_factory=list)
    node_procs: list = field(default_factory=list)
    app_procs: list = field(default_factory=list)

    def rpc_port(self, i: int) -> int:
        return self.base_port + 1000 + i


def _env() -> dict:
    return dict(os.environ, JAX_PLATFORMS="cpu", PYTHONUNBUFFERED="1",
                CBFT_NO_PALLAS="1")


def setup(manifest: Manifest, out_dir: str, base_port: int) -> _Net:
    """testnet homes + per-node config per the manifest (runner/setup.go)."""
    from cometbft_tpu.config import Config
    from cometbft_tpu.node import init_files
    from cometbft_tpu.p2p.key import NodeKey
    from cometbft_tpu.privval.file_pv import FilePV
    from cometbft_tpu.types.genesis import GenesisDoc, GenesisValidator
    from cometbft_tpu.utils import cmttime

    net = _Net(manifest=manifest, dir=out_dir, base_port=base_port)
    names = sorted(manifest.nodes)
    net.homes = [os.path.join(out_dir, name) for name in names]
    pvs, node_keys = [], []
    for home in net.homes:
        cfg = Config(home=home)
        os.makedirs(os.path.join(home, "config"), exist_ok=True)
        os.makedirs(os.path.join(home, "data"), exist_ok=True)
        pvs.append(FilePV.load_or_generate(
            cfg.priv_validator_key_path(), cfg.priv_validator_state_path(),
            key_type=manifest.key_type))
        node_keys.append(NodeKey.load_or_gen(cfg.node_key_path()))

    gdoc = GenesisDoc(
        genesis_time=cmttime.canonical_now_ms(),
        chain_id=manifest.name,
        initial_height=manifest.initial_height,
        validators=[
            GenesisValidator(address=pv.get_pub_key().address(),
                             pub_key=pv.get_pub_key(), power=1, name=nm)
            for nm, pv in zip(names, pvs)
        ],
        app_state=json.dumps(manifest.initial_state).encode(),
    )
    if manifest.vote_extensions_enable_height:
        gdoc.consensus_params.abci.vote_extensions_enable_height = (
            manifest.vote_extensions_enable_height)
    if manifest.key_type != "ed25519":
        gdoc.consensus_params.validator.pub_key_types = [manifest.key_type]
    gdoc.validate_and_complete()

    peer_addrs = [f"{node_keys[i].id()}@127.0.0.1:{base_port + i}"
                  for i in range(len(names))]
    node_ids = [nk.id() for nk in node_keys]
    chaos_spec = _netchaos_spec(manifest, names, node_ids)
    for i, (name, home) in enumerate(zip(names, net.homes)):
        nm = manifest.nodes[name]
        cfg = Config(home=home)
        cfg.base.moniker = name
        cfg.base.db_backend = nm.database
        cfg.p2p.laddr = f"tcp://127.0.0.1:{base_port + i}"
        cfg.rpc.laddr = f"tcp://127.0.0.1:{net.rpc_port(i)}"
        cfg.p2p.persistent_peers = ",".join(
            peer_addrs[j] for j in _topology_peers(manifest, names, i))
        if manifest.topology == "organic" and i > 0:
            # bootstrap = the seed's address and nothing else; the rest
            # of the peer set must be LEARNED over PEX
            cfg.p2p.seeds = peer_addrs[0]
        if manifest.topology == "organic":
            # boot-time convergence rides ensure-peers; the 30 s
            # production cadence would dominate the bootstrap clock
            cfg.p2p.pex_ensure_interval = 2.0
        # a fleet hub/gateway takes far more inbound conns than the
        # 40-peer default allows
        cfg.p2p.max_num_inbound_peers = max(40, len(names) + 8)
        if chaos_spec:
            # every node arms the same region/profile map: partition and
            # profile enforcement is write-side, so each process must
            # throttle its OWN outbound links
            cfg.p2p.chaos = chaos_spec
        cfg.crypto.backend = "cpu"  # N processes cannot share one chip
        cfg.consensus.timeout_commit = 0.1
        # heightline on every node: the run report's consensus anatomy
        # section needs the per-height rings, and the recorder's armed
        # cost is a few dict writes per height
        cfg.instrumentation.timeline = True
        cfg.instrumentation.height_slow_ms = manifest.height_slow_ms
        # reconciliation arm: the manifest picks the protocol (the
        # full-gossip control arm measures amplification WITHOUT it); a
        # fleet repairs vote views on a tighter cadence than the 0.5 s
        # single-digit-net default
        cfg.consensus.gossip_vote_summaries = manifest.vote_summaries
        if manifest.vote_summaries:
            cfg.consensus.vote_summary_interval = 0.1
        # perturbations drive the runtime control routes (partition arm/
        # heal); test-scale ban windows so a flood perturbation's bans
        # decay before the final catch-up deadline
        cfg.rpc.unsafe = True
        cfg.p2p.ban_duration = 5.0
        cfg.p2p.ban_max_duration = 30.0
        if nm.fuzz:
            cfg.p2p.test_fuzz = True
            cfg.p2p.test_fuzz_mode = nm.fuzz
        if nm.abci_protocol == "builtin":
            cfg.base.proxy_app = "kvstore"
        elif nm.abci_protocol == "tcp":
            cfg.base.proxy_app = f"tcp://127.0.0.1:{base_port + 2000 + i}"
        elif nm.abci_protocol == "unix":
            cfg.base.proxy_app = f"unix://{home}/app.sock"
        elif nm.abci_protocol == "grpc":
            cfg.base.proxy_app = f"grpc://127.0.0.1:{base_port + 2000 + i}"
        cfg.save()
        with open(cfg.genesis_path(), "w") as f:
            f.write(gdoc.to_json())
    return net


# device-fault perturbation schedules (libs/chaos.py syntax). The net
# normally pins crypto.backend=cpu (N processes cannot share one real
# chip), so the perturbation rewrites the ONE perturbed node's config to
# backend="tpu" (JAX_PLATFORMS=cpu in _env makes that the XLA-on-CPU
# device path — no chip contention) with the chaos schedule armed from
# config: its supervisor/breaker/fallback paths genuinely run, and the
# node must still rejoin the live head.
DEVICE_KILL_CHAOS = ("ed25519.dispatch=permanent,sr25519.dispatch=permanent,"
                     "pallas.trace=permanent")
DEVICE_FLAP_CHAOS = ("ed25519.dispatch=transient:4,ed25519.fetch=timeout:1,"
                     "sr25519.dispatch=transient:2")

# mesh perturbations (chip-kill[:N] / chip-flap[:N]): the node restarts
# with forced host devices so the verify mesh activates, and ONLY chip
# N's fault domain is scheduled to fail — the run must finalize on the
# SHRUNKEN mesh (kill) or the full mesh after breaker hysteresis absorbs
# the flap, never on the CPU fallback. Asserted via the mesh metrics.
# 4 devices, not 8: instantiating the verify executable costs tens of
# seconds PER CHIP even on a warm compilation cache, and consensus
# placement round-robins through every chip — the catch-up deadline must
# cover all of them
MESH_DEVICE_COUNT = 4
DEFAULT_CHIP_INDEX = 1


def _chip_kill_chaos(dev: int) -> str:
    return (f"ed25519.dispatch.dev{dev}=permanent,"
            f"sr25519.dispatch.dev{dev}=permanent")


def _chip_flap_chaos(dev: int) -> str:
    return (f"ed25519.dispatch.dev{dev}=transient:6,"
            f"sr25519.dispatch.dev{dev}=transient:2")


def _boot_staggered(net: _Net, wave: int = 12, pause: float = 1.0) -> None:
    """Spawn every node in waves: 50 simultaneous jax imports would
    stall every node's dial window (thundering herd). Shared by
    run_manifest and bench_fleet so the curves boot fleets with the
    same herd behavior as the acceptance runs they are compared to."""
    for w in range(0, len(net.homes), wave):
        net.node_procs += [_spawn_node(h) for h in net.homes[w:w + wave]]
        if w + wave < len(net.homes):
            time.sleep(pause)


def _spawn_node(home: str, mesh_devices: int = 0,
                extra_env: dict | None = None):
    env = _env()
    if extra_env:
        env.update(extra_env)
    if mesh_devices:
        # the axon TPU plugin self-registers from PYTHONPATH and ignores
        # JAX_PLATFORMS, which would leave this node with ONE real chip —
        # the shared recipe (parallel/mesh.host_mesh_env) strips it so
        # the forced host-device mesh actually materializes
        from cometbft_tpu.parallel.mesh import host_mesh_env

        env = host_mesh_env(env, mesh_devices)
    return subprocess.Popen(
        [sys.executable, "-m", "cometbft_tpu", "--home", home, "start"],
        cwd=REPO, env=env, stdout=subprocess.DEVNULL,
        stderr=subprocess.STDOUT, start_new_session=True)


def _arm_device_chaos(home: str, spec: str) -> None:
    """Point the node's on-disk config at the device path with `spec`
    armed (survives the respawn; CBFT_CHAOS env would work too but the
    config knob keeps the whole schedule visible in the node's home)."""
    from cometbft_tpu.config import Config

    cfg = Config.load(home)
    cfg.crypto.backend = "tpu"
    cfg.crypto.chaos = spec
    # a dead device should sideline fast in a liveness test
    cfg.crypto.breaker_failure_threshold = 1
    cfg.save()


def _arm_chip_chaos(home: str, spec: str, kill: bool) -> None:
    """Mesh perturbation config: device backend + mesh enabled + the
    per-chip schedule. A killed chip should evict fast (threshold 1); a
    flapping chip must be ABSORBED by hysteresis, so the flap keeps the
    default threshold and in-place transient retries."""
    from cometbft_tpu.config import Config

    cfg = Config.load(home)
    cfg.crypto.backend = "tpu"
    cfg.crypto.chaos = spec
    cfg.crypto.mesh_enabled = True
    cfg.crypto.mesh_min_devices = 2
    if kill:
        cfg.crypto.breaker_failure_threshold = 1
    cfg.save()


def _arm_light_fleet(home: str) -> None:
    """Enable the light-client fleet service (light/fleet.py) on the
    node's on-disk config — the serving plane boots with the node."""
    from cometbft_tpu.config import Config

    cfg = Config.load(home)
    cfg.light.fleet_enabled = True
    cfg.save()


def _fleet_swarm(net: _Net, i: int, requests: int, seed: int = 0) -> list[float]:
    """A simulated light-client swarm against node i's light_verify
    route: `requests` calls over a deterministic spread of committed
    heights. Returns sorted per-request latencies; raises RunError on a
    failed verification (a cache-served header the fleet could not
    produce is a serving-plane bug, not a flake)."""
    lats: list[float] = []
    top = max(1, _height(net, i) - 1)
    for j in range(requests):
        hq = 1 + (seed + j * 7) % top
        t0 = time.time()
        doc = _rpc(net, i, f"light_verify?height={hq}", timeout=15.0)
        if "result" not in doc:
            raise RunError(f"light_verify failed at height {hq}: {doc}")
        lats.append(time.time() - t0)
    lats.sort()
    return lats


def _arm_byzantine(home: str, behavior: str) -> None:
    """Point the node's on-disk config at an adversarial consensus mode
    (consensus/byzantine.py); empty behavior disarms."""
    from cometbft_tpu.config import Config

    cfg = Config.load(home)
    cfg.consensus.byzantine = behavior
    cfg.save()


def _metrics_text(net: _Net, i: int, timeout=3.0) -> str:
    url = f"http://127.0.0.1:{net.rpc_port(i)}/metrics"
    try:
        with urllib.request.urlopen(url, timeout=timeout) as r:
            return r.read().decode()
    except Exception:  # noqa: BLE001 - node not up / metrics not ready
        return ""


def _metric_value(text: str, name: str) -> float:
    """Sum every series of a metric in a Prometheus exposition."""
    total, seen = 0.0, False
    for line in text.splitlines():
        if line.startswith(name) and (len(line) == len(name)
                                      or line[len(name)] in " {"):
            try:
                total += float(line.rsplit(" ", 1)[1])
                seen = True
            except (ValueError, IndexError):
                continue
    return total if seen else 0.0


def _node_ids(net: _Net) -> list[str]:
    from cometbft_tpu.config import Config
    from cometbft_tpu.p2p.key import NodeKey

    return [NodeKey.load_or_gen(Config(home=h).node_key_path()).id()
            for h in net.homes]


def _spawn_app(addr: str):
    return subprocess.Popen(
        [sys.executable, "-m", "cometbft_tpu.abci.cli",
         "--address", addr, "kvstore"],
        cwd=REPO, env=_env(), stdout=subprocess.DEVNULL,
        stderr=subprocess.DEVNULL, start_new_session=True)


def _rpc(net: _Net, i: int, route: str, timeout=2.0):
    url = f"http://127.0.0.1:{net.rpc_port(i)}/{route}"
    with urllib.request.urlopen(url, timeout=timeout) as r:
        return json.load(r)


def _height(net: _Net, i: int) -> int:
    try:
        return int(_rpc(net, i, "status")["result"]["sync_info"]
                   ["latest_block_height"])
    except Exception:  # noqa: BLE001 - node not up yet
        return -1


def _wait(cond, timeout: float, what: str) -> None:
    deadline = time.time() + timeout
    while time.time() < deadline:
        if cond():
            return
        time.sleep(0.3)
    raise RunError(f"timed out waiting for {what}")


def _kill(proc) -> None:
    try:
        os.killpg(proc.pid, signal.SIGKILL)
    except (ProcessLookupError, PermissionError):
        pass
    try:
        proc.wait(timeout=5)
    except subprocess.TimeoutExpired:
        pass


def _fleet_rollup(report: dict, net: _Net, names: list[str]) -> dict:
    """Aggregate per-node net_report forensics into ONE fleet view: wire
    totals, gossip accounting (votes sent vs. needed — the amplification
    headline), heal latency, and per-node heights. Every field degrades
    to None/partial when a node died — the rollup reports, it never
    raises."""
    heights, send_bytes, recv_bytes = {}, 0, 0
    g_tot: dict[str, int] = {}
    heal = []
    reporting = 0
    book_sizes: dict[str, int] = {}
    for i, name in enumerate(names):
        doc = report["nodes"].get(name) or {}
        if "error" in doc:
            continue
        reporting += 1
        heights[name] = _height(net, i)
        totals = doc.get("totals") or {}
        send_bytes += totals.get("send_bytes", 0)
        recv_bytes += totals.get("recv_bytes", 0)
        gossip = doc.get("gossip") or {}
        for k, v in (gossip.get("totals") or {}).items():
            g_tot[k] = g_tot.get(k, 0) + v
        disc = doc.get("discovery") or {}
        if disc:
            book_sizes[name] = disc.get("size", 0)
        hs = (doc.get("net_chaos") or {}).get("last_heal_seconds")
        if hs:
            heal.append(hs)
    hs_vals = [h for h in heights.values() if h > 0]
    span = ((max(hs_vals) - net.manifest.initial_height)
            if hs_vals else 0)
    needed = g_tot.get("votes_recv_needed", 0)
    return {
        "n_nodes": len(names),
        "nodes_reporting": reporting,
        "topology": net.manifest.topology,
        "heights": heights,
        "wire_send_bytes_total": send_bytes,
        "wire_recv_bytes_total": recv_bytes,
        "wire_bytes_per_height_per_node": (
            round(send_bytes / span / max(1, reporting), 1)
            if span > 0 and reporting else None),
        "gossip_totals": g_tot,
        "gossip_votes_per_vote_needed": (
            round(g_tot.get("votes_recv", 0) / needed, 3)
            if needed else None),
        "partition_heal_seconds_max": max(heal) if heal else None,
        # discovery plane: how big each node's PEX book grew — under the
        # organic topology this IS the convergence evidence (every entry
        # was learned over the wire, none were wired by the runner)
        "addrbook_sizes": book_sizes or None,
    }


def _heightline_section(net: _Net, names: list[str]) -> dict:
    """The run report's consensus-anatomy section: each live node's
    `consensus_timeline` ring plus the skew-aligned fleet aggregate
    (consensus/timeline.aggregate) and postmortem summaries.  Per-node
    pull failures are recorded, never raised — like the wire section,
    this is an artifact."""
    from cometbft_tpu.consensus import timeline

    docs, per_node = [], {}
    for i, name in enumerate(names):
        try:
            doc = _rpc(net, i, "consensus_timeline",
                       timeout=5.0).get("result", {})
        except Exception as e:  # noqa: BLE001
            per_node[name] = {"error": str(e)}
            continue
        doc["name"] = name
        docs.append(doc)
        entry = {"node_id": doc.get("node_id", ""),
                 "heights": len(doc.get("heights", [])),
                 "enabled": doc.get("enabled", False)}
        try:
            pm = _rpc(net, i, "postmortems", timeout=5.0).get("result", {})
            entry["postmortems"] = pm.get("captures", [])
        except Exception as e:  # noqa: BLE001
            entry["postmortems_error"] = str(e)
        per_node[name] = entry
    section = {"nodes": per_node}
    try:
        agg = timeline.aggregate(docs)
        # regional manifests read straggler REGIONS, not just node ids
        regions = net.manifest.region_names()
        id_to_name = {d.get("node_id", ""): d["name"] for d in docs}
        top = agg["summary"].get("top_straggler")
        if top is not None and id_to_name.get(top) in regions:
            agg["summary"]["top_straggler_name"] = id_to_name[top]
            agg["summary"]["top_straggler_region"] = regions[id_to_name[top]]
        section["aggregate"] = agg
    except Exception as e:  # noqa: BLE001
        section["aggregate"] = {"error": str(e)}
    return section


def _write_net_report(net: _Net, names: list[str], log=print) -> str | None:
    """Snapshot net_telemetry from every live node into
    <out_dir>/net_report.json (the run report's wire-plane section),
    plus the `fleet` rollup aggregating them into one record and the
    `heightline` consensus-anatomy section. Telemetry failures are
    recorded per node, never raised — the report is an artifact, not an
    assertion, and it must land on FAILED runs too (a perturbation
    assert mid-run reaches here via run_manifest's finally), so every
    section degrades independently instead of losing the whole file."""
    report = {"manifest": net.manifest.name, "nodes": {}}
    for i, name in enumerate(names):
        try:
            report["nodes"][name] = _rpc(net, i, "net_telemetry",
                                         timeout=5.0).get("result", {})
        except Exception as e:  # noqa: BLE001
            report["nodes"][name] = {"error": str(e)}
    try:
        report["fleet"] = _fleet_rollup(report, net, names)
    except Exception as e:  # noqa: BLE001 - the rollup must never cost
        report["fleet"] = {"error": str(e)}  # the per-node forensics
    try:
        report["heightline"] = _heightline_section(net, names)
    except Exception as e:  # noqa: BLE001 - ditto
        report["heightline"] = {"error": str(e)}
    path = os.path.join(net.dir, "net_report.json")
    try:
        with open(path, "w") as f:
            # default=str: one unserializable telemetry value must not
            # cost the failed-run forensics record
            json.dump(report, f, indent=1, default=str)
    except OSError as e:
        log(f"[{net.manifest.name}] net report not written: {e}")
        return None
    ok = sum(1 for v in report["nodes"].values() if "error" not in v)
    log(f"[{net.manifest.name}] wrote {path} "
        f"({ok}/{len(names)} nodes reporting)")
    return path


# ------------------------------------------------- fleet perturbations
# NET-level perturbations (manifest.net_perturb): each one drives the
# WHOLE fleet and asserts through the gossip/heal metrics, where the
# per-node perturbations above drive one node at a time.


def _min_height(net: _Net, idxs) -> int:
    return min(_height(net, j) for j in idxs)


def _max_height(net: _Net, idxs) -> int:
    return max(_height(net, j) for j in idxs)


def _perturb_churn_storm(net: _Net, names: list[str], pct: int, log) -> None:
    """Rolling restarts of pct% of the fleet in quorum-preserving waves:
    at most ~10% of nodes are down at once, and the chain must ADVANCE
    while the storm blows (a churn storm is weather, not an outage)."""
    n = len(names)
    n_churn = max(1, n * pct // 100)
    wave = max(1, min(n // 10, max(1, (n - 1) // 3)))
    victims = list(range(n))[:n_churn]  # deterministic: lowest indices
    log(f"[{net.manifest.name}] churn storm: restarting {n_churn}/{n} "
        f"nodes in waves of {wave}")
    h0 = _max_height(net, range(n))
    for w in range(0, n_churn, wave):
        batch = victims[w:w + wave]
        for j in batch:
            _kill(net.node_procs[j])
        for j in batch:
            net.node_procs[j] = _spawn_node(net.homes[j])
        # the respawned wave must REJOIN before the next wave blows, or
        # waves overlap into an outage
        target = _max_height(net, [j for j in range(n) if j not in batch])
        _wait(lambda: _min_height(net, batch) >= target - 1,
              120 + 4 * len(batch),
              f"churn wave {w // wave} rejoining height {target - 1}")
    # the chain must have kept committing through the storm window (a
    # churn storm is weather, not an outage); a short tail covers a
    # proposer round that died mid-wave
    _wait(lambda: _max_height(net, range(n)) > h0, 60 + 2 * n,
          f"the chain advancing past {h0} through the churn storm")
    h1 = _max_height(net, range(n))
    _wait(lambda: _min_height(net, range(n)) >= h1, 150 + 2 * n,
          "the whole fleet catching up after the churn storm")
    log(f"[{net.manifest.name}] churn storm done: {h0} -> {h1}, all caught up")


def _nudge_dials(net: _Net, names: list[str]) -> None:
    """Ask every node to re-dial its topology peers NOW (the dial_peers
    control route; already-connected peers are no-ops). Best-effort —
    a node that ignores the nudge just rides its own backoff."""
    ids = _node_ids(net)
    for i in range(len(names)):
        if net.manifest.topology == "organic":
            # no persistent wiring to re-dial; point everyone back at the
            # seed so a restarted node re-enters discovery immediately
            peer_idx = [0] if i != 0 else []
        else:
            peer_idx = _topology_peers(net.manifest, names, i)
        peers = ",".join(
            f"{ids[j]}@127.0.0.1:{net.base_port + j}"
            for j in peer_idx)
        if not peers:
            continue
        try:
            _rpc(net, i,
                 f"dial_peers?peers={urllib.parse.quote(peers)}",
                 timeout=10.0)
        except Exception:  # noqa: BLE001
            pass


def _perturb_regional_partition(net: _Net, names: list[str], region: int,
                                log) -> None:
    """Cut one region off through the runtime netchaos route. A minority
    region must STALL while the rest commits (they lost nothing but that
    region's votes); a heal must reconnect it, catch it up, and land on
    the partition-heal metric."""
    m = net.manifest
    n = len(names)
    ids = _node_ids(net)
    cut = [i for i, nm in enumerate(names) if m.nodes[nm].region == region]
    rest = [i for i in range(n) if i not in cut]
    if not cut or not rest:
        raise RunError(f"regional-partition: region {region} is empty or "
                       f"the whole net")
    spec = ("partition=" + ".".join(ids[i] for i in cut) + "|"
            + ".".join(ids[i] for i in rest))
    log(f"[{m.name}] partitioning region r{region} "
        f"({len(cut)} nodes) from the other {len(rest)}")
    arg = urllib.parse.quote(f'"{spec}"')
    for j in range(n):
        _rpc(net, j, f"unsafe_net_chaos?spec={arg}", timeout=10.0)
    time.sleep(2.0)  # in-flight commits land
    cut_h = _max_height(net, cut)
    rest_h = _max_height(net, rest)
    majority_has_quorum = len(rest) * 3 > n * 2
    if majority_has_quorum:
        _wait(lambda: _min_height(net, rest) >= rest_h + 2, 120 + 2 * n,
              "the majority side committing through the partition")
    else:
        time.sleep(6.0)
        if _max_height(net, rest) > rest_h + 1:
            raise RunError("progress on a quorum-less majority side")
    if _max_height(net, cut) > cut_h + 1:
        raise RunError(
            f"cut region r{region} advanced {cut_h} -> "
            f"{_max_height(net, cut)} during its partition")
    for j in range(n):
        _rpc(net, j, "unsafe_net_chaos?heal=true", timeout=10.0)
    # redial nudge: persistent-peer reconnect backoff deepens to 30 s
    # steps during a long partition, which can leave the few
    # cross-region links down for minutes AFTER the heal — the operator
    # move (and this runner's) is to nudge the dials through the
    # control route instead of waiting out the backoff
    _nudge_dials(net, names)
    target = _max_height(net, rest) + 2
    _wait(lambda: _min_height(net, range(n)) >= target, 300 + 6 * n,
          f"region r{region} catching up to {target} after the heal")
    if not any(_metric_value(_metrics_text(net, j),
                             "cometbft_p2p_partition_heal_seconds") > 0
               for j in range(n)):
        raise RunError("regional partition heal not recorded on /metrics")
    log(f"[{m.name}] region r{region} healed and caught up")


def _perturb_minority_partition(net: _Net, names: list[str], k: int,
                                log) -> None:
    """Cut the LAST k nodes off through the runtime netchaos route — the
    topology-agnostic sibling of regional-partition (a hub fleet has no
    regions, and under the hub topology the last nodes are spokes, so
    the hub mesh stays intact). The cut minority must STALL while the
    majority commits; a heal must reconnect it, catch it up, and land
    on the partition-heal metric."""
    m = net.manifest
    n = len(names)
    k = max(1, min(k, (n - 1) // 3))  # the majority keeps a +2/3 quorum
    ids = _node_ids(net)
    cut = list(range(n - k, n))
    rest = list(range(n - k))
    spec = ("partition=" + ".".join(ids[i] for i in cut) + "|"
            + ".".join(ids[i] for i in rest))
    log(f"[{m.name}] minority partition: cutting "
        f"{', '.join(names[i] for i in cut)} from the other {len(rest)}")
    arg = urllib.parse.quote(f'"{spec}"')
    for j in range(n):
        _rpc(net, j, f"unsafe_net_chaos?spec={arg}", timeout=10.0)
    time.sleep(2.0)  # in-flight commits land
    cut_h = _max_height(net, cut)
    rest_h = _max_height(net, rest)
    _wait(lambda: _min_height(net, rest) >= rest_h + 2, 120 + 2 * n,
          "the majority side committing through the minority partition")
    if _max_height(net, cut) > cut_h + 1:
        raise RunError(
            f"cut minority advanced {cut_h} -> {_max_height(net, cut)} "
            f"during its partition")
    for j in range(n):
        _rpc(net, j, "unsafe_net_chaos?heal=true", timeout=10.0)
    # same redial nudge as the regional heal: reconnect backoff deepens
    # during a long partition, the control route shortcuts it
    _nudge_dials(net, names)
    target = _max_height(net, rest) + 2
    _wait(lambda: _min_height(net, range(n)) >= target, 300 + 6 * n,
          f"the cut minority catching up to {target} after the heal")
    if not any(_metric_value(_metrics_text(net, j),
                             "cometbft_p2p_partition_heal_seconds") > 0
               for j in range(n)):
        raise RunError("minority partition heal not recorded on /metrics")
    log(f"[{m.name}] minority healed and caught up")


def _perturb_byzantine_minority(net: _Net, names: list[str], k: int,
                                log) -> None:
    """Restart k nodes equivocating (capped to keep a +2/3 honest
    quorum). The honest fleet must detect (DuplicateVoteEvidence
    committed) while staying live; the culprits are then reformed."""
    n = len(names)
    k = max(1, min(k, (n - 1) // 3))
    byz = list(range(k))
    honest = [j for j in range(n) if j >= k]
    log(f"[{net.manifest.name}] byzantine minority: {k}/{n} equivocating")
    for j in byz:
        _kill(net.node_procs[j])
        _arm_byzantine(net.homes[j], "equivocation")
        net.node_procs[j] = _spawn_node(net.homes[j])
    _wait(lambda: any(
        _metric_value(_metrics_text(net, j), "cometbft_evidence_committed")
        >= 1 for j in honest), 240 + 4 * n,
        "honest nodes committing DuplicateVoteEvidence")
    h0 = _max_height(net, honest)
    _wait(lambda: _max_height(net, honest) >= h0 + 2, 120 + 2 * n,
          "the honest fleet staying live under the byzantine minority")
    for j in byz:
        _kill(net.node_procs[j])
        _arm_byzantine(net.homes[j], "")
        net.node_procs[j] = _spawn_node(net.homes[j])
    target = _max_height(net, honest) + 1
    _wait(lambda: _min_height(net, range(n)) >= target, 200 + 4 * n,
          "reformed nodes rejoining the fleet")
    log(f"[{net.manifest.name}] byzantine minority detected and reformed")


def _run_net_perturbations(net: _Net, names: list[str], log) -> None:
    for p in net.manifest.net_perturb:
        base, _, arg = p.partition(":")
        if base == "churn-storm":
            _perturb_churn_storm(net, names, int(arg) if arg else 30, log)
        elif base == "regional-partition":
            _perturb_regional_partition(net, names,
                                        int(arg) if arg else 0, log)
        elif base == "byzantine-minority":
            _perturb_byzantine_minority(
                net, names, int(arg) if arg else len(names) // 3, log)
        elif base == "minority-partition":
            _perturb_minority_partition(
                net, names, int(arg) if arg else max(1, len(names) // 4),
                log)


def run_manifest(manifest: Manifest, out_dir: str, base_port: int = 29000,
                 log=print) -> None:
    """Setup + start + perturb + verify + cleanup. Raises RunError on any
    violated expectation."""
    manifest.validate()
    _resource_guard(len(manifest.nodes), base_port)
    net = setup(manifest, out_dir, base_port)
    names = sorted(manifest.nodes)
    n = len(names)
    # fleet deadlines scale with size: 50 processes importing jax and
    # dialing a topology do not boot in a 4-node net's 150 s
    boot_deadline = 150 + 4 * n
    try:
        # out-of-process apps first (the node dials them on boot)
        for i, name in enumerate(names):
            proto = manifest.nodes[name].abci_protocol
            if proto == "builtin":
                net.app_procs.append(None)
                continue
            from cometbft_tpu.config import Config

            cfg = Config.load(net.homes[i])
            net.app_procs.append(_spawn_app(cfg.base.proxy_app))
        time.sleep(1.0)
        _boot_staggered(net)

        start_h = manifest.initial_height
        log(f"[{manifest.name}] waiting for height {start_h + 2} on {n} nodes")
        _wait(lambda: all(_height(net, i) >= start_h + 2 for i in range(n)),
              boot_deadline, f"all {n} nodes reaching height {start_h + 2}")

        # perturbations (perturb.go:44-100), one node at a time. A
        # single-node net has no survivors to observe: kill degrades to
        # restart, pause is a fixed-length stop (waiting on the perturbed
        # node's own height would deadlock).
        for i, name in enumerate(names):
            for p in manifest.nodes[name].perturb:
                p, p_arg = manifest.nodes[name].split_perturb(p)
                others = [j for j in range(n) if j != i]
                h0 = max((_height(net, j) for j in others), default=0)
                if p == "kill":
                    log(f"[{manifest.name}] kill {name}")
                    _kill(net.node_procs[i])
                    if others:
                        _wait(lambda: min(_height(net, j) for j in others)
                              >= h0 + 2, 120,
                              "survivors advancing past a kill")
                    net.node_procs[i] = _spawn_node(net.homes[i])
                elif p == "restart":
                    log(f"[{manifest.name}] restart {name}")
                    _kill(net.node_procs[i])
                    net.node_procs[i] = _spawn_node(net.homes[i])
                elif p in ("device-kill", "device-flap"):
                    # restart the node on the device backend with a chaos
                    # schedule armed: its accelerator is dead (permanent)
                    # or flapping (transient) from boot — catching up to
                    # the live head below proves the degraded verify
                    # ladder commits; crypto_health is asserted after
                    chaos = (DEVICE_KILL_CHAOS if p == "device-kill"
                             else DEVICE_FLAP_CHAOS)
                    log(f"[{manifest.name}] {p} {name}")
                    _kill(net.node_procs[i])
                    _arm_device_chaos(net.homes[i], chaos)
                    net.node_procs[i] = _spawn_node(net.homes[i])
                elif p in ("chip-kill", "chip-flap"):
                    # restart the node on a forced host-device mesh with
                    # ONE chip's fault domain scheduled to die (permanent)
                    # or flap (transient): catching up below proves liveness;
                    # the mesh metrics asserted after prove the run
                    # finalized on a shrunken/healed MESH, not on the CPU
                    # fallback ladder
                    dev = int(p_arg) if p_arg else DEFAULT_CHIP_INDEX
                    # the mesh must contain the targeted chip: a manifest
                    # may index up to chaos.MESH_CHAOS_DEVICES-1
                    n_mesh = max(MESH_DEVICE_COUNT, dev + 1)
                    chaos = (_chip_kill_chaos(dev) if p == "chip-kill"
                             else _chip_flap_chaos(dev))
                    log(f"[{manifest.name}] {p} {name} "
                        f"(device {dev} of {n_mesh})")
                    _kill(net.node_procs[i])
                    _arm_chip_chaos(net.homes[i], chaos,
                                    kill=(p == "chip-kill"))
                    net.node_procs[i] = _spawn_node(
                        net.homes[i], mesh_devices=n_mesh)
                elif p == "pause":
                    log(f"[{manifest.name}] pause {name}")
                    os.killpg(net.node_procs[i].pid, signal.SIGSTOP)
                    if others:
                        _wait(lambda: min(_height(net, j) for j in others)
                              >= h0 + 2, 120,
                              "survivors advancing past a pause")
                    else:
                        time.sleep(2.0)
                    os.killpg(net.node_procs[i].pid, signal.SIGCONT)
                elif p == "partition":
                    # 2-2 split through the runtime control route: no side
                    # has quorum, so NO progress until the heal — then the
                    # heal must be observable on /metrics
                    ids = _node_ids(net)
                    side = {i, (i + 1) % n}
                    spec = ("partition="
                            + ".".join(ids[j] for j in sorted(side)) + "|"
                            + ".".join(ids[j] for j in range(n)
                                       if j not in side))
                    log(f"[{manifest.name}] partition {sorted(side)} vs rest")
                    arg = urllib.parse.quote(f'"{spec}"')
                    for j in range(n):
                        _rpc(net, j, f"unsafe_net_chaos?spec={arg}")
                    time.sleep(2.0)  # in-flight commits land
                    hp = max(_height(net, j) for j in range(n))
                    time.sleep(6.0)
                    hq = max(_height(net, j) for j in range(n))
                    if hq > hp + 1:
                        raise RunError(
                            f"progress during a 2-2 partition: {hp} -> {hq}")
                    for j in range(n):
                        _rpc(net, j, "unsafe_net_chaos?heal=true")
                    _wait(lambda: min(_height(net, j) for j in range(n))
                          >= hq + 2, 150, "the net resuming after the heal")
                    if not any(_metric_value(
                            _metrics_text(net, j),
                            "cometbft_p2p_partition_heal_seconds") > 0
                            for j in range(n)):
                        raise RunError("partition_heal_seconds not recorded")
                elif p == "light-fleet":
                    # restart the node with the serving plane enabled,
                    # drive a client swarm at light_verify, partition the
                    # fleet node away MID-SOAK (already-committed heights
                    # must keep serving from the checkpoint cache), heal,
                    # and assert post-heal p99 + the light_fleet metrics
                    log(f"[{manifest.name}] light-fleet {name}")
                    _kill(net.node_procs[i])
                    _arm_light_fleet(net.homes[i])
                    net.node_procs[i] = _spawn_node(net.homes[i])
                    _wait(lambda: _height(net, i) >= h0, 150,
                          "the fleet node serving again")
                    _fleet_swarm(net, i, 40)  # soak phase 1: warm cache
                    ids = _node_ids(net)
                    spec = ("partition=" + ids[i] + "|"
                            + ".".join(ids[j] for j in range(n) if j != i))
                    log(f"[{manifest.name}] partitioning fleet node "
                        f"{name} mid-soak")
                    arg = urllib.parse.quote(f'"{spec}"')
                    for j in range(n):
                        _rpc(net, j, f"unsafe_net_chaos?spec={arg}")
                    time.sleep(2.0)
                    # the cut fleet node still answers for committed
                    # heights — the cache needs no quorum
                    _fleet_swarm(net, i, 15, seed=3)
                    for j in range(n):
                        _rpc(net, j, "unsafe_net_chaos?heal=true")
                    if others:
                        _wait(lambda: _height(net, i)
                              >= max(_height(net, j) for j in others) - 1,
                              150, "the fleet node rejoining after heal")
                    healed = _fleet_swarm(net, i, 60, seed=11)
                    p99 = healed[min(len(healed) - 1,
                                     int(len(healed) * 0.99))]
                    if p99 > 5.0:
                        raise RunError(
                            f"light-fleet on {name}: post-heal p99 "
                            f"{p99:.2f}s (> 5s budget)")
                    text = _metrics_text(net, i, timeout=5.0)
                    served = _metric_value(
                        text, "cometbft_light_fleet_requests_total")
                    if served < 100:
                        raise RunError(
                            f"light-fleet on {name}: only {served} fleet "
                            f"requests on /metrics (swarm ran 115)")
                    hits = _metric_value(
                        text,
                        'cometbft_light_fleet_cache_events{event="hit"}')
                    if hits < 1:
                        raise RunError(
                            f"light-fleet on {name}: checkpoint cache "
                            f"recorded no hits")
                elif p == "crash-storm":
                    # >= 3 kill-at-crash-site / respawn cycles on ONE
                    # node (CBFT_CRASH_SITE, libs/fail.py): each armed
                    # incarnation must die at its site with exit 99, each
                    # clean respawn must serve again; the shared tail
                    # asserts the storm cost the chain nothing
                    sites = ([p_arg] if p_arg else
                             ["wal.endheight", "abci.apply", "state.save"])
                    cycles = max(3, len(sites))
                    for c in range(cycles):
                        site = sites[c % len(sites)]
                        log(f"[{manifest.name}] crash-storm {name} "
                            f"cycle {c + 1}/{cycles} @ {site}")
                        _kill(net.node_procs[i])
                        proc = _spawn_node(
                            net.homes[i],
                            extra_env={"CBFT_CRASH_SITE": f"{site}:2"})
                        net.node_procs[i] = proc
                        t0 = time.time()
                        while proc.poll() is None and time.time() - t0 < 150:
                            time.sleep(0.5)
                        if proc.poll() != 99:
                            _kill(proc)
                            raise RunError(
                                f"crash-storm on {name}: site {site} never "
                                f"fired (exit {proc.poll()})")
                        net.node_procs[i] = _spawn_node(net.homes[i])
                        _wait(lambda: _height(net, i) >= 1, 150,
                              f"{name} serving after crash cycle {c + 1}")
                elif p == "disk-fault":
                    # arm a BOUNDED diskchaos schedule at runtime
                    # (unsafe_disk_chaos): the node must degrade or halt
                    # typed — never serve a block that differs from the
                    # fault-free chain — and every injected fault must be
                    # counted on the storage metrics plane
                    kind = p_arg or "bitrot"
                    spec = {"bitrot": "db.read=bitrot:2",
                            "enospc": "wal.write=enospc:2",
                            "eio": "db.write=eio:2",
                            "fsync_error": "wal.fsync=fsync_error:1",
                            "slow": "wal.fsync=slow:8"}[kind]
                    log(f"[{manifest.name}] disk-fault {name} ({spec})")
                    arg = urllib.parse.quote(f'"{spec}"')
                    _rpc(net, i, f"unsafe_disk_chaos?spec={arg}")
                    hq = manifest.initial_height + 1
                    ref_hash = None
                    if others:
                        ref = _rpc(net, others[0], f"block?height={hq}")
                        ref_hash = ref.get("result", {}).get(
                            "block_id", {}).get("hash")
                    deadline = time.time() + 60
                    fired = 0.0
                    while time.time() < deadline:
                        # poke the read seam: the answer is the typed
                        # error or the IDENTICAL block, never a wrong one
                        try:
                            doc = _rpc(net, i, f"block?height={hq}")
                        except Exception:  # noqa: BLE001 - typed halt
                            doc = {}
                        if "result" in doc and ref_hash is not None:
                            got = doc["result"]["block_id"]["hash"]
                            if got != ref_hash:
                                raise RunError(
                                    f"disk-fault on {name}: served block "
                                    f"{hq} hash {got} differs from fault-"
                                    f"free {ref_hash}")
                        fired = _metric_value(
                            _metrics_text(net, i),
                            "cometbft_storage_disk_faults")
                        if fired >= 1:
                            break
                        time.sleep(1.0)
                    if fired < 1:
                        raise RunError(
                            f"disk-fault on {name}: no injected fault "
                            f"counted on /metrics within 60s")
                    # clear the schedule and respawn: a node that halted
                    # with the typed error must rejoin; a live one just
                    # restarts (the shared tail asserts fork-free)
                    _rpc(net, i, "unsafe_disk_chaos?clear=true")
                    _kill(net.node_procs[i])
                    net.node_procs[i] = _spawn_node(net.homes[i])
                elif p == "cert-backfill":
                    # kill the node, wipe its commit-certificate store,
                    # respawn it mid-fleet while the chain keeps
                    # advancing: the backfill worker must re-certify the
                    # retained range from stored commits, observable on
                    # /metrics and over the commit_certificate route
                    log(f"[{manifest.name}] cert-backfill {name}")
                    _wait(lambda: _metric_value(
                        _metrics_text(net, i),
                        "cometbft_cert_produced_total") >= 1, 150,
                        f"{name} producing certificates before the wipe")
                    _kill(net.node_procs[i])
                    from cometbft_tpu.config import Config

                    cfg = Config.load(net.homes[i])
                    cert_files = glob.glob(cfg.db_path("certs") + "*")
                    if not cert_files:
                        raise RunError(
                            f"cert-backfill on {name}: no certificate "
                            f"store files under {cfg.db_path('certs')}*")
                    for path in cert_files:
                        os.remove(path)
                    net.node_procs[i] = _spawn_node(net.homes[i])
                    _wait(lambda: _metric_value(
                        _metrics_text(net, i),
                        "cometbft_cert_backfilled_total") >= 1, 180,
                        f"{name} backfilling certificates after the wipe")
                    # churn: the fleet must have kept committing while the
                    # node re-certified (backfill under a moving head)
                    if others:
                        _wait(lambda: min(_height(net, j) for j in others)
                              >= h0 + 2, 120,
                              "survivors advancing through the backfill")
                    # a height committed BEFORE the wipe must answer on
                    # the RPC route again — re-certified, not replayed
                    def _recertified(_i=i, _h=max(h0, start_h + 2)):
                        try:
                            doc = _rpc(
                                net, _i, f"commit_certificate?height={_h}")
                        except Exception:  # noqa: BLE001 - retried
                            return False
                        return "certificate" in doc.get("result", {})

                    _wait(_recertified, 120,
                          f"{name} serving a re-certified early height")
                elif p == "mempool-storm":
                    # respawn with a SMALL pool so saturation is reachable
                    # without drowning the host, then drive fire-and-forget
                    # admission waves at the node's RPC: the chain must
                    # ADVANCE through the storm (only admission-plane work
                    # may be shed), the exempt control plane must answer
                    # mid-storm, and the sheds must land on /metrics with
                    # the mempool plane label
                    log(f"[{manifest.name}] mempool-storm {name}")
                    from cometbft_tpu.config import Config

                    cfg = Config.load(net.homes[i])
                    orig_pool = cfg.mempool.size
                    cfg.mempool.size = 128
                    cfg.save()
                    _kill(net.node_procs[i])
                    net.node_procs[i] = _spawn_node(net.homes[i])
                    _wait(lambda: _height(net, i) >= 1, 150,
                          f"{name} serving with a small pool")
                    h1 = _height(net, i)
                    for wave in range(4):
                        for t in range(200):
                            tx = urllib.parse.quote(
                                f'"storm-{name}-{wave:02d}-{t:03d}"')
                            _rpc(net, i, f"broadcast_tx_async?tx={tx}",
                                 timeout=10.0)
                        doc = _rpc(net, i, "health", timeout=10.0)
                        if "overload" not in doc.get("result", {}):
                            raise RunError(
                                f"mempool-storm on {name}: health lost its "
                                f"overload section mid-storm: {doc}")
                    _wait(lambda: _height(net, i) >= h1 + 2, 120,
                          "the chain advancing through the mempool storm")
                    shed = _metric_value(
                        _metrics_text(net, i, timeout=5.0),
                        'cometbft_overload_sheds_total{plane="mempool"}')
                    if shed < 1:
                        raise RunError(
                            f"mempool-storm on {name}: 800 txs into a "
                            f"128-tx pool shed nothing on /metrics")
                    cfg = Config.load(net.homes[i])
                    cfg.mempool.size = orig_pool
                    cfg.save()
                    _kill(net.node_procs[i])
                    net.node_procs[i] = _spawn_node(net.homes[i])
                elif p == "rpc-flood":
                    # respawn with a 1-slot WRITE budget, then flood
                    # concurrent broadcast_tx_commit calls — the route
                    # that holds its slot across a whole commit wait, so
                    # the budget genuinely exhausts (fast read handlers
                    # finish within one event-loop step and never pile
                    # up). Excess requests must shed with the unified
                    # -32005 envelope (plane "rpc" + retry hint) while
                    # the exempt control plane keeps answering — an
                    # operator must always be able to ask a saturated
                    # node how saturated it is
                    log(f"[{manifest.name}] rpc-flood {name}")
                    from cometbft_tpu.config import Config

                    cfg = Config.load(net.homes[i])
                    orig_guard = (cfg.rpc.overload_write_inflight,
                                  cfg.rpc.overload_queue_timeout)
                    cfg.rpc.overload_write_inflight = 1
                    cfg.rpc.overload_queue_timeout = 0.01
                    cfg.save()
                    _kill(net.node_procs[i])
                    net.node_procs[i] = _spawn_node(net.homes[i])
                    _wait(lambda: _height(net, i) >= 1, 150,
                          f"{name} serving with a 1-slot write budget")

                    def _flood_write(_j, _i=i, _nm=name):
                        tx = urllib.parse.quote(f'"flood-{_nm}-{_j:03d}"')
                        try:
                            return _rpc(
                                net, _i, f"broadcast_tx_commit?tx={tx}",
                                timeout=30.0)
                        except Exception:  # noqa: BLE001 - counted below
                            return {}

                    health_ok = False
                    with concurrent.futures.ThreadPoolExecutor(
                            max_workers=24) as tp:
                        futs = [tp.submit(_flood_write, j)
                                for j in range(120)]
                        while not all(f.done() for f in futs):
                            try:
                                doc = _rpc(net, i, "health", timeout=10.0)
                                health_ok = health_ok or "result" in doc
                            except Exception:  # noqa: BLE001
                                pass
                            time.sleep(0.02)
                        docs = [f.result() for f in futs]
                    sheds = 0
                    for doc in docs:
                        err = doc.get("error") or {}
                        if err.get("code") != -32005:
                            continue
                        data = err.get("data") or {}
                        if (data.get("plane") != "rpc"
                                or "retry_after_ms" not in data):
                            raise RunError(
                                f"rpc-flood on {name}: malformed shed "
                                f"envelope {err}")
                        sheds += 1
                    if sheds < 1:
                        raise RunError(
                            f"rpc-flood on {name}: no -32005 sheds out of "
                            f"{len(docs)} concurrent commit-waits on a "
                            f"1-slot budget")
                    if not health_ok:
                        raise RunError(
                            f"rpc-flood on {name}: exempt health route "
                            f"failed during the flood")
                    if _metric_value(
                            _metrics_text(net, i, timeout=5.0),
                            'cometbft_overload_sheds_total{plane="rpc"}') < 1:
                        raise RunError(
                            f"rpc-flood on {name}: sheds not recorded on "
                            f"/metrics with the rpc plane label")
                    cfg = Config.load(net.homes[i])
                    (cfg.rpc.overload_write_inflight,
                     cfg.rpc.overload_queue_timeout) = orig_guard
                    cfg.save()
                    _kill(net.node_procs[i])
                    net.node_procs[i] = _spawn_node(net.homes[i])
                elif p in ("byzantine", "flood"):
                    # restart the node adversarially; the honest majority
                    # must DETECT it: equivocation -> DuplicateVoteEvidence
                    # committed (evidence_committed), invalid-signature
                    # flooding -> the peer is banned (peer_bans)
                    behavior = "equivocation" if p == "byzantine" else "flood"
                    log(f"[{manifest.name}] {p} {name} ({behavior})")
                    _kill(net.node_procs[i])
                    _arm_byzantine(net.homes[i], behavior)
                    net.node_procs[i] = _spawn_node(net.homes[i])
                    metric = ("cometbft_evidence_committed"
                              if p == "byzantine" else "cometbft_p2p_peer_bans")
                    _wait(lambda: any(
                        _metric_value(_metrics_text(net, j), metric) >= 1
                        for j in others), 180,
                        f"honest nodes recording {metric} >= 1")
                    # reform the node so the final agreement checks run
                    # against an honest net
                    _kill(net.node_procs[i])
                    _arm_byzantine(net.homes[i], "")
                    net.node_procs[i] = _spawn_node(net.homes[i])
                # the perturbed node must rejoin the live head (generous
                # deadline: CI shares the host with whatever else runs,
                # and a device perturbation pays cold kernel compiles)
                target = max((_height(net, j) for j in others),
                             default=h0) + 1
                _wait(lambda: _height(net, i) >= target, 240,
                      f"{name} catching up to {target} after {p}")
                if p in ("device-kill", "device-flap"):
                    # the degradation must be OBSERVED, not assumed: the
                    # supervisor recorded device failures and (for a dead
                    # device) the node now serves verifies from the CPU rung
                    h = _rpc(net, i, "crypto_health")["result"]
                    dev = h["supervisors"].get("device", {})
                    if dev.get("failures", 0) < 1:
                        raise RunError(
                            f"{p} on {name}: no supervised device failures "
                            f"recorded (crypto_health: {h})")
                    if (p == "device-kill"
                            and dev.get("breaker", {}).get("state") == "closed"):
                        # only a SUCCESSFUL device op closes the breaker —
                        # impossible with a permanently dead device
                        raise RunError(
                            f"device-kill on {name}: breaker closed, so a "
                            f"device op succeeded (crypto_health: {h})")
                if p in ("chip-kill", "chip-flap"):
                    # the run must have finalized ON THE MESH: shards were
                    # dispatched, and the all-chips-dead CPU fallback was
                    # never engaged
                    text = _metrics_text(net, i, timeout=5.0)
                    size = _metric_value(
                        text, "cometbft_crypto_verify_mesh_size")
                    fallbacks = _metric_value(
                        text, "cometbft_crypto_mesh_fallback_total")
                    shard_lanes = _metric_value(
                        text, "cometbft_crypto_mesh_shard_lanes")
                    if shard_lanes < 1:
                        raise RunError(
                            f"{p} on {name}: no mesh shards dispatched "
                            f"(mesh never engaged)")
                    if fallbacks > 0:
                        raise RunError(
                            f"{p} on {name}: finalized via the CPU "
                            f"fallback ({fallbacks} fallbacks), not the mesh")
                    if p == "chip-kill":
                        evictions = _metric_value(
                            text, "cometbft_crypto_mesh_evictions_total")
                        dead_state = _metric_value(
                            text, "cometbft_crypto_mesh_breaker_state"
                                  f'{{device="{dev}"}}')
                        if evictions < 1:
                            raise RunError(
                                f"chip-kill on {name}: the mesh never "
                                f"evicted the dead chip (size {size})")
                        if dead_state < 1:  # 0 closed: a device op succeeded
                            raise RunError(
                                f"chip-kill on {name}: chip {dev}'s breaker "
                                f"is closed — its fault domain never died")
                        if size < 1:
                            raise RunError(
                                f"chip-kill on {name}: whole mesh died "
                                f"(size {size})")
                    else:  # chip-flap: hysteresis absorbs, mesh stays full
                        if size < n_mesh:
                            raise RunError(
                                f"chip-flap on {name}: flap shrank the mesh "
                                f"(size {size} of {n_mesh}) instead of "
                                f"being absorbed")

        # net-level perturbations (fleet scale): after the per-node loop,
        # so a manifest can compose both planes
        _run_net_perturbations(net, names, log)

        target = max(manifest.initial_height + manifest.target_height_delta,
                     max(_height(net, i) for i in range(n)))
        log(f"[{manifest.name}] waiting for target height {target}")
        _wait(lambda: all(_height(net, i) >= target for i in range(n)),
              150 + 2 * n, f"all nodes reaching target height {target}")

        # no fork: every node agrees on the newest height they all have
        h = min(_height(net, i) for i in range(n)) - 1
        hashes = {
            _rpc(net, i, f"block?height={h}")["result"]["block_id"]["hash"]
            for i in range(n)
        }
        if len(hashes) != 1:
            raise RunError(f"fork at height {h}: {hashes}")

        # genesis app_state visible through every node's app
        for key, want in manifest.initial_state.items():
            q = _rpc(net, 0,
                     f'abci_query?data={key.encode().hex()}&path="/store"')
            if "result" not in q:
                raise RunError(f"abci_query failed: {q}")
            got = q["result"]["response"].get("value")
            import base64 as _b64

            if got is None or _b64.b64decode(got).decode() != want:
                raise RunError(
                    f"initial_state key {key!r} not served by the app "
                    f"(got {got!r})")
        log(f"[{manifest.name}] OK (height {h}, {n} nodes in agreement)")
    finally:
        # wire-plane report: snapshot every node's net_telemetry into the
        # run dir BEFORE teardown — on FAILED runs especially, this is the
        # forensics record of where the wire bytes went (nodes that died
        # are recorded as per-node errors, never raised). A report bug
        # must neither mask the run's real error nor skip the kills below.
        try:
            _write_net_report(net, names, log=log)
        except Exception as e:  # noqa: BLE001
            log(f"[{manifest.name}] net report failed: {e}")
        for p in net.node_procs:
            if p is not None:
                _kill(p)
        for p in net.app_procs:
            if p is not None:
                _kill(p)
