"""Config-matrix runner: set up, start, perturb, and verify one manifest's
testnet of real OS processes over real TCP.

Reference: test/e2e/runner (main.go Setup/Start/Perturb/Test/Cleanup;
perturb.go:44-100). Differences are environmental: nodes are processes on
one host (no docker network, so "disconnect" lives in the in-proc
perturbation matrix instead), and out-of-process ABCI apps are one
`abci-cli kvstore` server per node on the manifest's transport."""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import time
import urllib.parse
import urllib.request
from dataclasses import dataclass, field

from cometbft_tpu.e2e.manifest import Manifest

REPO = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


class RunError(Exception):
    pass


@dataclass
class _Net:
    manifest: Manifest
    dir: str
    base_port: int
    homes: list[str] = field(default_factory=list)
    node_procs: list = field(default_factory=list)
    app_procs: list = field(default_factory=list)

    def rpc_port(self, i: int) -> int:
        return self.base_port + 1000 + i


def _env() -> dict:
    return dict(os.environ, JAX_PLATFORMS="cpu", PYTHONUNBUFFERED="1",
                CBFT_NO_PALLAS="1")


def setup(manifest: Manifest, out_dir: str, base_port: int) -> _Net:
    """testnet homes + per-node config per the manifest (runner/setup.go)."""
    from cometbft_tpu.config import Config
    from cometbft_tpu.node import init_files
    from cometbft_tpu.p2p.key import NodeKey
    from cometbft_tpu.privval.file_pv import FilePV
    from cometbft_tpu.types.genesis import GenesisDoc, GenesisValidator
    from cometbft_tpu.utils import cmttime

    net = _Net(manifest=manifest, dir=out_dir, base_port=base_port)
    names = sorted(manifest.nodes)
    net.homes = [os.path.join(out_dir, name) for name in names]
    pvs, node_keys = [], []
    for home in net.homes:
        cfg = Config(home=home)
        os.makedirs(os.path.join(home, "config"), exist_ok=True)
        os.makedirs(os.path.join(home, "data"), exist_ok=True)
        pvs.append(FilePV.load_or_generate(
            cfg.priv_validator_key_path(), cfg.priv_validator_state_path()))
        node_keys.append(NodeKey.load_or_gen(cfg.node_key_path()))

    gdoc = GenesisDoc(
        genesis_time=cmttime.canonical_now_ms(),
        chain_id=manifest.name,
        initial_height=manifest.initial_height,
        validators=[
            GenesisValidator(address=pv.get_pub_key().address(),
                             pub_key=pv.get_pub_key(), power=1, name=nm)
            for nm, pv in zip(names, pvs)
        ],
        app_state=json.dumps(manifest.initial_state).encode(),
    )
    if manifest.vote_extensions_enable_height:
        gdoc.consensus_params.abci.vote_extensions_enable_height = (
            manifest.vote_extensions_enable_height)
    gdoc.validate_and_complete()

    peer_addrs = [f"{node_keys[i].id()}@127.0.0.1:{base_port + i}"
                  for i in range(len(names))]
    for i, (name, home) in enumerate(zip(names, net.homes)):
        nm = manifest.nodes[name]
        cfg = Config(home=home)
        cfg.base.moniker = name
        cfg.base.db_backend = nm.database
        cfg.p2p.laddr = f"tcp://127.0.0.1:{base_port + i}"
        cfg.rpc.laddr = f"tcp://127.0.0.1:{net.rpc_port(i)}"
        cfg.p2p.persistent_peers = ",".join(
            a for j, a in enumerate(peer_addrs) if j != i)
        cfg.crypto.backend = "cpu"  # N processes cannot share one chip
        cfg.consensus.timeout_commit = 0.1
        # perturbations drive the runtime control routes (partition arm/
        # heal); test-scale ban windows so a flood perturbation's bans
        # decay before the final catch-up deadline
        cfg.rpc.unsafe = True
        cfg.p2p.ban_duration = 5.0
        cfg.p2p.ban_max_duration = 30.0
        if nm.fuzz:
            cfg.p2p.test_fuzz = True
            cfg.p2p.test_fuzz_mode = nm.fuzz
        if nm.abci_protocol == "builtin":
            cfg.base.proxy_app = "kvstore"
        elif nm.abci_protocol == "tcp":
            cfg.base.proxy_app = f"tcp://127.0.0.1:{base_port + 2000 + i}"
        elif nm.abci_protocol == "unix":
            cfg.base.proxy_app = f"unix://{home}/app.sock"
        elif nm.abci_protocol == "grpc":
            cfg.base.proxy_app = f"grpc://127.0.0.1:{base_port + 2000 + i}"
        cfg.save()
        with open(cfg.genesis_path(), "w") as f:
            f.write(gdoc.to_json())
    return net


# device-fault perturbation schedules (libs/chaos.py syntax). The net
# normally pins crypto.backend=cpu (N processes cannot share one real
# chip), so the perturbation rewrites the ONE perturbed node's config to
# backend="tpu" (JAX_PLATFORMS=cpu in _env makes that the XLA-on-CPU
# device path — no chip contention) with the chaos schedule armed from
# config: its supervisor/breaker/fallback paths genuinely run, and the
# node must still rejoin the live head.
DEVICE_KILL_CHAOS = ("ed25519.dispatch=permanent,sr25519.dispatch=permanent,"
                     "pallas.trace=permanent")
DEVICE_FLAP_CHAOS = ("ed25519.dispatch=transient:4,ed25519.fetch=timeout:1,"
                     "sr25519.dispatch=transient:2")

# mesh perturbations (chip-kill[:N] / chip-flap[:N]): the node restarts
# with forced host devices so the verify mesh activates, and ONLY chip
# N's fault domain is scheduled to fail — the run must finalize on the
# SHRUNKEN mesh (kill) or the full mesh after breaker hysteresis absorbs
# the flap, never on the CPU fallback. Asserted via the mesh metrics.
# 4 devices, not 8: instantiating the verify executable costs tens of
# seconds PER CHIP even on a warm compilation cache, and consensus
# placement round-robins through every chip — the catch-up deadline must
# cover all of them
MESH_DEVICE_COUNT = 4
DEFAULT_CHIP_INDEX = 1


def _chip_kill_chaos(dev: int) -> str:
    return (f"ed25519.dispatch.dev{dev}=permanent,"
            f"sr25519.dispatch.dev{dev}=permanent")


def _chip_flap_chaos(dev: int) -> str:
    return (f"ed25519.dispatch.dev{dev}=transient:6,"
            f"sr25519.dispatch.dev{dev}=transient:2")


def _spawn_node(home: str, mesh_devices: int = 0):
    env = _env()
    if mesh_devices:
        # the axon TPU plugin self-registers from PYTHONPATH and ignores
        # JAX_PLATFORMS, which would leave this node with ONE real chip —
        # the shared recipe (parallel/mesh.host_mesh_env) strips it so
        # the forced host-device mesh actually materializes
        from cometbft_tpu.parallel.mesh import host_mesh_env

        env = host_mesh_env(env, mesh_devices)
    return subprocess.Popen(
        [sys.executable, "-m", "cometbft_tpu", "--home", home, "start"],
        cwd=REPO, env=env, stdout=subprocess.DEVNULL,
        stderr=subprocess.STDOUT, start_new_session=True)


def _arm_device_chaos(home: str, spec: str) -> None:
    """Point the node's on-disk config at the device path with `spec`
    armed (survives the respawn; CBFT_CHAOS env would work too but the
    config knob keeps the whole schedule visible in the node's home)."""
    from cometbft_tpu.config import Config

    cfg = Config.load(home)
    cfg.crypto.backend = "tpu"
    cfg.crypto.chaos = spec
    # a dead device should sideline fast in a liveness test
    cfg.crypto.breaker_failure_threshold = 1
    cfg.save()


def _arm_chip_chaos(home: str, spec: str, kill: bool) -> None:
    """Mesh perturbation config: device backend + mesh enabled + the
    per-chip schedule. A killed chip should evict fast (threshold 1); a
    flapping chip must be ABSORBED by hysteresis, so the flap keeps the
    default threshold and in-place transient retries."""
    from cometbft_tpu.config import Config

    cfg = Config.load(home)
    cfg.crypto.backend = "tpu"
    cfg.crypto.chaos = spec
    cfg.crypto.mesh_enabled = True
    cfg.crypto.mesh_min_devices = 2
    if kill:
        cfg.crypto.breaker_failure_threshold = 1
    cfg.save()


def _arm_light_fleet(home: str) -> None:
    """Enable the light-client fleet service (light/fleet.py) on the
    node's on-disk config — the serving plane boots with the node."""
    from cometbft_tpu.config import Config

    cfg = Config.load(home)
    cfg.light.fleet_enabled = True
    cfg.save()


def _fleet_swarm(net: _Net, i: int, requests: int, seed: int = 0) -> list[float]:
    """A simulated light-client swarm against node i's light_verify
    route: `requests` calls over a deterministic spread of committed
    heights. Returns sorted per-request latencies; raises RunError on a
    failed verification (a cache-served header the fleet could not
    produce is a serving-plane bug, not a flake)."""
    lats: list[float] = []
    top = max(1, _height(net, i) - 1)
    for j in range(requests):
        hq = 1 + (seed + j * 7) % top
        t0 = time.time()
        doc = _rpc(net, i, f"light_verify?height={hq}", timeout=15.0)
        if "result" not in doc:
            raise RunError(f"light_verify failed at height {hq}: {doc}")
        lats.append(time.time() - t0)
    lats.sort()
    return lats


def _arm_byzantine(home: str, behavior: str) -> None:
    """Point the node's on-disk config at an adversarial consensus mode
    (consensus/byzantine.py); empty behavior disarms."""
    from cometbft_tpu.config import Config

    cfg = Config.load(home)
    cfg.consensus.byzantine = behavior
    cfg.save()


def _metrics_text(net: _Net, i: int, timeout=3.0) -> str:
    url = f"http://127.0.0.1:{net.rpc_port(i)}/metrics"
    try:
        with urllib.request.urlopen(url, timeout=timeout) as r:
            return r.read().decode()
    except Exception:  # noqa: BLE001 - node not up / metrics not ready
        return ""


def _metric_value(text: str, name: str) -> float:
    """Sum every series of a metric in a Prometheus exposition."""
    total, seen = 0.0, False
    for line in text.splitlines():
        if line.startswith(name) and (len(line) == len(name)
                                      or line[len(name)] in " {"):
            try:
                total += float(line.rsplit(" ", 1)[1])
                seen = True
            except (ValueError, IndexError):
                continue
    return total if seen else 0.0


def _node_ids(net: _Net) -> list[str]:
    from cometbft_tpu.config import Config
    from cometbft_tpu.p2p.key import NodeKey

    return [NodeKey.load_or_gen(Config(home=h).node_key_path()).id()
            for h in net.homes]


def _spawn_app(addr: str):
    return subprocess.Popen(
        [sys.executable, "-m", "cometbft_tpu.abci.cli",
         "--address", addr, "kvstore"],
        cwd=REPO, env=_env(), stdout=subprocess.DEVNULL,
        stderr=subprocess.DEVNULL, start_new_session=True)


def _rpc(net: _Net, i: int, route: str, timeout=2.0):
    url = f"http://127.0.0.1:{net.rpc_port(i)}/{route}"
    with urllib.request.urlopen(url, timeout=timeout) as r:
        return json.load(r)


def _height(net: _Net, i: int) -> int:
    try:
        return int(_rpc(net, i, "status")["result"]["sync_info"]
                   ["latest_block_height"])
    except Exception:  # noqa: BLE001 - node not up yet
        return -1


def _wait(cond, timeout: float, what: str) -> None:
    deadline = time.time() + timeout
    while time.time() < deadline:
        if cond():
            return
        time.sleep(0.3)
    raise RunError(f"timed out waiting for {what}")


def _kill(proc) -> None:
    try:
        os.killpg(proc.pid, signal.SIGKILL)
    except (ProcessLookupError, PermissionError):
        pass
    try:
        proc.wait(timeout=5)
    except subprocess.TimeoutExpired:
        pass


def _write_net_report(net: _Net, names: list[str], log=print) -> str | None:
    """Snapshot net_telemetry from every live node into
    <out_dir>/net_report.json (the run report's wire-plane section).
    Telemetry failures are recorded per node, never raised — the report
    is an artifact, not an assertion."""
    report = {"manifest": net.manifest.name, "nodes": {}}
    for i, name in enumerate(names):
        try:
            report["nodes"][name] = _rpc(net, i, "net_telemetry",
                                         timeout=5.0).get("result", {})
        except Exception as e:  # noqa: BLE001
            report["nodes"][name] = {"error": str(e)}
    path = os.path.join(net.dir, "net_report.json")
    try:
        with open(path, "w") as f:
            json.dump(report, f, indent=1)
    except OSError as e:
        log(f"[{net.manifest.name}] net report not written: {e}")
        return None
    ok = sum(1 for v in report["nodes"].values() if "error" not in v)
    log(f"[{net.manifest.name}] wrote {path} "
        f"({ok}/{len(names)} nodes reporting)")
    return path


def run_manifest(manifest: Manifest, out_dir: str, base_port: int = 29000,
                 log=print) -> None:
    """Setup + start + perturb + verify + cleanup. Raises RunError on any
    violated expectation."""
    manifest.validate()
    net = setup(manifest, out_dir, base_port)
    names = sorted(manifest.nodes)
    n = len(names)
    try:
        # out-of-process apps first (the node dials them on boot)
        for i, name in enumerate(names):
            proto = manifest.nodes[name].abci_protocol
            if proto == "builtin":
                net.app_procs.append(None)
                continue
            from cometbft_tpu.config import Config

            cfg = Config.load(net.homes[i])
            net.app_procs.append(_spawn_app(cfg.base.proxy_app))
        time.sleep(1.0)
        net.node_procs = [_spawn_node(h) for h in net.homes]

        start_h = manifest.initial_height
        log(f"[{manifest.name}] waiting for height {start_h + 2} on {n} nodes")
        _wait(lambda: all(_height(net, i) >= start_h + 2 for i in range(n)),
              150, f"all {n} nodes reaching height {start_h + 2}")

        # perturbations (perturb.go:44-100), one node at a time. A
        # single-node net has no survivors to observe: kill degrades to
        # restart, pause is a fixed-length stop (waiting on the perturbed
        # node's own height would deadlock).
        for i, name in enumerate(names):
            for p in manifest.nodes[name].perturb:
                p, p_arg = manifest.nodes[name].split_perturb(p)
                others = [j for j in range(n) if j != i]
                h0 = max((_height(net, j) for j in others), default=0)
                if p == "kill":
                    log(f"[{manifest.name}] kill {name}")
                    _kill(net.node_procs[i])
                    if others:
                        _wait(lambda: min(_height(net, j) for j in others)
                              >= h0 + 2, 120,
                              "survivors advancing past a kill")
                    net.node_procs[i] = _spawn_node(net.homes[i])
                elif p == "restart":
                    log(f"[{manifest.name}] restart {name}")
                    _kill(net.node_procs[i])
                    net.node_procs[i] = _spawn_node(net.homes[i])
                elif p in ("device-kill", "device-flap"):
                    # restart the node on the device backend with a chaos
                    # schedule armed: its accelerator is dead (permanent)
                    # or flapping (transient) from boot — catching up to
                    # the live head below proves the degraded verify
                    # ladder commits; crypto_health is asserted after
                    chaos = (DEVICE_KILL_CHAOS if p == "device-kill"
                             else DEVICE_FLAP_CHAOS)
                    log(f"[{manifest.name}] {p} {name}")
                    _kill(net.node_procs[i])
                    _arm_device_chaos(net.homes[i], chaos)
                    net.node_procs[i] = _spawn_node(net.homes[i])
                elif p in ("chip-kill", "chip-flap"):
                    # restart the node on a forced host-device mesh with
                    # ONE chip's fault domain scheduled to die (permanent)
                    # or flap (transient): catching up below proves liveness;
                    # the mesh metrics asserted after prove the run
                    # finalized on a shrunken/healed MESH, not on the CPU
                    # fallback ladder
                    dev = int(p_arg) if p_arg else DEFAULT_CHIP_INDEX
                    # the mesh must contain the targeted chip: a manifest
                    # may index up to chaos.MESH_CHAOS_DEVICES-1
                    n_mesh = max(MESH_DEVICE_COUNT, dev + 1)
                    chaos = (_chip_kill_chaos(dev) if p == "chip-kill"
                             else _chip_flap_chaos(dev))
                    log(f"[{manifest.name}] {p} {name} "
                        f"(device {dev} of {n_mesh})")
                    _kill(net.node_procs[i])
                    _arm_chip_chaos(net.homes[i], chaos,
                                    kill=(p == "chip-kill"))
                    net.node_procs[i] = _spawn_node(
                        net.homes[i], mesh_devices=n_mesh)
                elif p == "pause":
                    log(f"[{manifest.name}] pause {name}")
                    os.killpg(net.node_procs[i].pid, signal.SIGSTOP)
                    if others:
                        _wait(lambda: min(_height(net, j) for j in others)
                              >= h0 + 2, 120,
                              "survivors advancing past a pause")
                    else:
                        time.sleep(2.0)
                    os.killpg(net.node_procs[i].pid, signal.SIGCONT)
                elif p == "partition":
                    # 2-2 split through the runtime control route: no side
                    # has quorum, so NO progress until the heal — then the
                    # heal must be observable on /metrics
                    ids = _node_ids(net)
                    side = {i, (i + 1) % n}
                    spec = ("partition="
                            + ".".join(ids[j] for j in sorted(side)) + "|"
                            + ".".join(ids[j] for j in range(n)
                                       if j not in side))
                    log(f"[{manifest.name}] partition {sorted(side)} vs rest")
                    arg = urllib.parse.quote(f'"{spec}"')
                    for j in range(n):
                        _rpc(net, j, f"unsafe_net_chaos?spec={arg}")
                    time.sleep(2.0)  # in-flight commits land
                    hp = max(_height(net, j) for j in range(n))
                    time.sleep(6.0)
                    hq = max(_height(net, j) for j in range(n))
                    if hq > hp + 1:
                        raise RunError(
                            f"progress during a 2-2 partition: {hp} -> {hq}")
                    for j in range(n):
                        _rpc(net, j, "unsafe_net_chaos?heal=true")
                    _wait(lambda: min(_height(net, j) for j in range(n))
                          >= hq + 2, 150, "the net resuming after the heal")
                    if not any(_metric_value(
                            _metrics_text(net, j),
                            "cometbft_p2p_partition_heal_seconds") > 0
                            for j in range(n)):
                        raise RunError("partition_heal_seconds not recorded")
                elif p == "light-fleet":
                    # restart the node with the serving plane enabled,
                    # drive a client swarm at light_verify, partition the
                    # fleet node away MID-SOAK (already-committed heights
                    # must keep serving from the checkpoint cache), heal,
                    # and assert post-heal p99 + the light_fleet metrics
                    log(f"[{manifest.name}] light-fleet {name}")
                    _kill(net.node_procs[i])
                    _arm_light_fleet(net.homes[i])
                    net.node_procs[i] = _spawn_node(net.homes[i])
                    _wait(lambda: _height(net, i) >= h0, 150,
                          "the fleet node serving again")
                    _fleet_swarm(net, i, 40)  # soak phase 1: warm cache
                    ids = _node_ids(net)
                    spec = ("partition=" + ids[i] + "|"
                            + ".".join(ids[j] for j in range(n) if j != i))
                    log(f"[{manifest.name}] partitioning fleet node "
                        f"{name} mid-soak")
                    arg = urllib.parse.quote(f'"{spec}"')
                    for j in range(n):
                        _rpc(net, j, f"unsafe_net_chaos?spec={arg}")
                    time.sleep(2.0)
                    # the cut fleet node still answers for committed
                    # heights — the cache needs no quorum
                    _fleet_swarm(net, i, 15, seed=3)
                    for j in range(n):
                        _rpc(net, j, "unsafe_net_chaos?heal=true")
                    if others:
                        _wait(lambda: _height(net, i)
                              >= max(_height(net, j) for j in others) - 1,
                              150, "the fleet node rejoining after heal")
                    healed = _fleet_swarm(net, i, 60, seed=11)
                    p99 = healed[min(len(healed) - 1,
                                     int(len(healed) * 0.99))]
                    if p99 > 5.0:
                        raise RunError(
                            f"light-fleet on {name}: post-heal p99 "
                            f"{p99:.2f}s (> 5s budget)")
                    text = _metrics_text(net, i, timeout=5.0)
                    served = _metric_value(
                        text, "cometbft_light_fleet_requests_total")
                    if served < 100:
                        raise RunError(
                            f"light-fleet on {name}: only {served} fleet "
                            f"requests on /metrics (swarm ran 115)")
                    hits = _metric_value(
                        text,
                        'cometbft_light_fleet_cache_events{event="hit"}')
                    if hits < 1:
                        raise RunError(
                            f"light-fleet on {name}: checkpoint cache "
                            f"recorded no hits")
                elif p in ("byzantine", "flood"):
                    # restart the node adversarially; the honest majority
                    # must DETECT it: equivocation -> DuplicateVoteEvidence
                    # committed (evidence_committed), invalid-signature
                    # flooding -> the peer is banned (peer_bans)
                    behavior = "equivocation" if p == "byzantine" else "flood"
                    log(f"[{manifest.name}] {p} {name} ({behavior})")
                    _kill(net.node_procs[i])
                    _arm_byzantine(net.homes[i], behavior)
                    net.node_procs[i] = _spawn_node(net.homes[i])
                    metric = ("cometbft_evidence_committed"
                              if p == "byzantine" else "cometbft_p2p_peer_bans")
                    _wait(lambda: any(
                        _metric_value(_metrics_text(net, j), metric) >= 1
                        for j in others), 180,
                        f"honest nodes recording {metric} >= 1")
                    # reform the node so the final agreement checks run
                    # against an honest net
                    _kill(net.node_procs[i])
                    _arm_byzantine(net.homes[i], "")
                    net.node_procs[i] = _spawn_node(net.homes[i])
                # the perturbed node must rejoin the live head (generous
                # deadline: CI shares the host with whatever else runs,
                # and a device perturbation pays cold kernel compiles)
                target = max((_height(net, j) for j in others),
                             default=h0) + 1
                _wait(lambda: _height(net, i) >= target, 240,
                      f"{name} catching up to {target} after {p}")
                if p in ("device-kill", "device-flap"):
                    # the degradation must be OBSERVED, not assumed: the
                    # supervisor recorded device failures and (for a dead
                    # device) the node now serves verifies from the CPU rung
                    h = _rpc(net, i, "crypto_health")["result"]
                    dev = h["supervisors"].get("device", {})
                    if dev.get("failures", 0) < 1:
                        raise RunError(
                            f"{p} on {name}: no supervised device failures "
                            f"recorded (crypto_health: {h})")
                    if (p == "device-kill"
                            and dev.get("breaker", {}).get("state") == "closed"):
                        # only a SUCCESSFUL device op closes the breaker —
                        # impossible with a permanently dead device
                        raise RunError(
                            f"device-kill on {name}: breaker closed, so a "
                            f"device op succeeded (crypto_health: {h})")
                if p in ("chip-kill", "chip-flap"):
                    # the run must have finalized ON THE MESH: shards were
                    # dispatched, and the all-chips-dead CPU fallback was
                    # never engaged
                    text = _metrics_text(net, i, timeout=5.0)
                    size = _metric_value(
                        text, "cometbft_crypto_verify_mesh_size")
                    fallbacks = _metric_value(
                        text, "cometbft_crypto_mesh_fallback_total")
                    shard_lanes = _metric_value(
                        text, "cometbft_crypto_mesh_shard_lanes")
                    if shard_lanes < 1:
                        raise RunError(
                            f"{p} on {name}: no mesh shards dispatched "
                            f"(mesh never engaged)")
                    if fallbacks > 0:
                        raise RunError(
                            f"{p} on {name}: finalized via the CPU "
                            f"fallback ({fallbacks} fallbacks), not the mesh")
                    if p == "chip-kill":
                        evictions = _metric_value(
                            text, "cometbft_crypto_mesh_evictions_total")
                        dead_state = _metric_value(
                            text, "cometbft_crypto_mesh_breaker_state"
                                  f'{{device="{dev}"}}')
                        if evictions < 1:
                            raise RunError(
                                f"chip-kill on {name}: the mesh never "
                                f"evicted the dead chip (size {size})")
                        if dead_state < 1:  # 0 closed: a device op succeeded
                            raise RunError(
                                f"chip-kill on {name}: chip {dev}'s breaker "
                                f"is closed — its fault domain never died")
                        if size < 1:
                            raise RunError(
                                f"chip-kill on {name}: whole mesh died "
                                f"(size {size})")
                    else:  # chip-flap: hysteresis absorbs, mesh stays full
                        if size < n_mesh:
                            raise RunError(
                                f"chip-flap on {name}: flap shrank the mesh "
                                f"(size {size} of {n_mesh}) instead of "
                                f"being absorbed")

        target = max(manifest.initial_height + manifest.target_height_delta,
                     max(_height(net, i) for i in range(n)))
        log(f"[{manifest.name}] waiting for target height {target}")
        _wait(lambda: all(_height(net, i) >= target for i in range(n)),
              150, f"all nodes reaching target height {target}")

        # no fork: every node agrees on the newest height they all have
        h = min(_height(net, i) for i in range(n)) - 1
        hashes = {
            _rpc(net, i, f"block?height={h}")["result"]["block_id"]["hash"]
            for i in range(n)
        }
        if len(hashes) != 1:
            raise RunError(f"fork at height {h}: {hashes}")

        # genesis app_state visible through every node's app
        for key, want in manifest.initial_state.items():
            q = _rpc(net, 0,
                     f'abci_query?data={key.encode().hex()}&path="/store"')
            if "result" not in q:
                raise RunError(f"abci_query failed: {q}")
            got = q["result"]["response"].get("value")
            import base64 as _b64

            if got is None or _b64.b64decode(got).decode() != want:
                raise RunError(
                    f"initial_state key {key!r} not served by the app "
                    f"(got {got!r})")
        log(f"[{manifest.name}] OK (height {h}, {n} nodes in agreement)")
    finally:
        # wire-plane report: snapshot every node's net_telemetry into the
        # run dir BEFORE teardown — on FAILED runs especially, this is the
        # forensics record of where the wire bytes went (nodes that died
        # are recorded as per-node errors, never raised)
        _write_net_report(net, names, log=log)
        for p in net.node_procs:
            if p is not None:
                _kill(p)
        for p in net.app_procs:
            if p is not None:
                _kill(p)
