"""CLI for the manifest generator + config-matrix runner (see package
docstring; reference: test/e2e/generator/main.go + runner/main.go)."""

from __future__ import annotations

import argparse
import os
import sys
import tempfile
import time

from cometbft_tpu.e2e.generator import generate_manifests
from cometbft_tpu.e2e.manifest import Manifest
from cometbft_tpu.e2e.runner import RunError, run_manifest


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="cometbft_tpu.e2e")
    sub = p.add_subparsers(dest="cmd", required=True)

    g = sub.add_parser("generate", help="write random manifest TOMLs")
    g.add_argument("--seed", type=int, default=int(time.time()))
    g.add_argument("--count", type=int, default=5)
    g.add_argument("--dir", default="e2e-manifests")

    r = sub.add_parser("run", help="run one manifest")
    r.add_argument("--manifest", required=True)
    r.add_argument("--dir", default="")
    r.add_argument("--base-port", type=int, default=29000)

    c = sub.add_parser("ci", help="generate + run a sampled matrix")
    c.add_argument("--seed", type=int, default=int(time.time()))
    c.add_argument("--count", type=int, default=5)
    c.add_argument("--base-port", type=int, default=29000)

    f = sub.add_parser(
        "fleet", help="run one deliberate fleet-scale testnet "
                      "(50-100 nodes; gated by the resource guard)")
    f.add_argument("--nodes", type=int, default=50)
    f.add_argument("--topology", default="regional",
                   choices=("full", "hub", "regional"))
    f.add_argument("--regions", type=int, default=4)
    f.add_argument("--link-profile", default=None,
                   choices=("", "wan", "lossy-wan"),
                   help="cross-region link profile (default: wan for the "
                        "regional topology, clean wire otherwise)")
    f.add_argument("--perturb", default="regional-partition:1,churn-storm:30",
                   help="comma-separated net perturbations ('' for none)")
    f.add_argument("--no-summaries", action="store_true",
                   help="full-gossip control arm (reconciliation off)")
    f.add_argument("--dir", default="")
    # span must end below the kernel ephemeral range (guard enforces)
    f.add_argument("--base-port", type=int, default=10000)

    ns = p.parse_args(argv)
    if ns.cmd == "generate":
        os.makedirs(ns.dir, exist_ok=True)
        for m in generate_manifests(ns.seed, ns.count):
            path = os.path.join(ns.dir, f"{m.name}.toml")
            with open(path, "w") as f:
                f.write(m.to_toml())
            print(path)
        return 0
    if ns.cmd == "run":
        with open(ns.manifest, "rb") as f:
            m = Manifest.from_toml(f.read().decode())
        out = ns.dir or tempfile.mkdtemp(prefix=f"e2e-{m.name}-")
        try:
            run_manifest(m, out, base_port=ns.base_port)
        except RunError as e:
            print(f"FAIL {m.name}: {e}", file=sys.stderr)
            return 1
        return 0
    if ns.cmd == "fleet":
        from cometbft_tpu.e2e.generator import generate_fleet_manifest

        profile = ns.link_profile
        if profile is None:
            profile = "wan" if ns.topology == "regional" else ""
        m = generate_fleet_manifest(
            ns.nodes, topology=ns.topology, regions=ns.regions,
            link_profile=profile,
            net_perturb=tuple(x for x in ns.perturb.split(",") if x),
            vote_summaries=not ns.no_summaries)
        out = ns.dir or tempfile.mkdtemp(prefix=f"e2e-{m.name}-")
        try:
            run_manifest(m, out, base_port=ns.base_port)
        except RunError as e:
            print(f"FAIL {m.name}: {e}", file=sys.stderr)
            print(f"(forensics: {os.path.join(out, 'net_report.json')})",
                  file=sys.stderr)
            return 1
        print(f"fleet report: {os.path.join(out, 'net_report.json')}")
        return 0
    # ci
    failures = 0
    for i, m in enumerate(generate_manifests(ns.seed, ns.count)):
        out = tempfile.mkdtemp(prefix=f"e2e-{m.name}-")
        print(f"=== [{i + 1}/{ns.count}] {m.name} "
              f"({len(m.nodes)} nodes, seed {ns.seed}) ===")
        try:
            run_manifest(m, out, base_port=ns.base_port + i * 100)
        except RunError as e:
            failures += 1
            print(f"FAIL {m.name}: {e}", file=sys.stderr)
    print(f"ci: {ns.count - failures}/{ns.count} manifests green")
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main())
