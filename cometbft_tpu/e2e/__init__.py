"""E2E testnet manifests: random generation + a config-matrix runner.

Reference: test/e2e/generator/generate.go (random manifests over the
topology / ABCI-transport / database / perturbation space) +
test/e2e/runner (setup, start, perturb, verify). The runner here launches
real OS processes over real TCP — the same plane as
tests/test_e2e_testnet.py — one net per manifest, sequentially.

CLI (python -m cometbft_tpu.e2e):
  generate --seed S --count K --dir D     write K random manifest TOMLs
  run --manifest M.toml                   set up + run + verify one net
  ci --seed S --count K                   generate and run K nets (the
                                          VERDICT "one command, >=5 random
                                          manifests green" bar)
"""

from cometbft_tpu.e2e.manifest import Manifest, NodeManifest  # noqa: F401
from cometbft_tpu.e2e.generator import generate_manifests  # noqa: F401
from cometbft_tpu.e2e.runner import run_manifest  # noqa: F401
