"""Testnet manifest: the declarative net description the generator emits
and the runner consumes (reference: test/e2e/pkg/manifest.go, TOML shape
mirroring networks/*.toml)."""

from __future__ import annotations

try:
    import tomllib  # 3.11+
except ImportError:  # 3.10: the API-identical backport
    import tomli as tomllib
from dataclasses import dataclass, field


@dataclass
class NodeManifest:
    """One node's options (manifest.go Node)."""

    database: str = "sqlite"        # sqlite | memdb
    abci_protocol: str = "builtin"  # builtin | tcp | unix | grpc
    privval_protocol: str = "file"  # file (remote-signer nets use tests')
    persist_interval: int = 1
    retain_blocks: int = 0
    # p2p stream fuzzing (p2p/fuzz.py FuzzConnConfig via config test_fuzz):
    # "" disabled, else "drop" | "delay"
    fuzz: str = ""
    # process faults: kill | pause | restart (perturb.go:44-100);
    # device faults: device-kill (restart with the accelerator permanently
    # dead via a CBFT_CHAOS schedule — the node must keep committing on
    # the CPU ladder), device-flap (restart with a transient-fault
    # schedule — the supervisor must retry/re-probe back onto the device);
    # mesh faults: chip-kill[:N] (restart on a forced host-device mesh —
    # runner.MESH_DEVICE_COUNT chips, grown to cover N — with chip N's
    # fault domain permanently dead: the node must finalize on the
    # SHRUNKEN mesh, not the CPU fallback), chip-flap[:N] (chip N
    # transiently failing — breaker hysteresis must absorb it without
    # shrinking the mesh); N defaults to 1;
    # network/byzantine faults: partition (runtime 2-2 split through the
    # unsafe_net_chaos route — no progress while split, heal resumes),
    # byzantine (restart equivocating — honest nodes must commit
    # DuplicateVoteEvidence), flood (restart invalid-signature flooding —
    # honest nodes must ban the peer);
    # serving faults: light-fleet (restart with the light-client fleet
    # service enabled, drive a simulated client swarm against
    # light_verify, partition the fleet node away mid-soak, and assert
    # post-heal p99 recovery via the light_fleet metrics);
    # storage faults: crash-storm[:site] (>= 3 kill-at-crash-site /
    # respawn cycles via CBFT_CRASH_SITE — site from libs/fail.SITES,
    # default rotates through the commit-path sites; the chain must
    # commit through the storm and the node rejoin fork-free),
    # disk-fault[:kind] (arm a bounded libs/diskchaos schedule at
    # runtime via unsafe_disk_chaos — kind from the non-crash subset
    # below, default bitrot; every injected fault must be counted in
    # storage_health and the node must degrade or halt typed, never
    # serve a block that differs from the fault-free run);
    # certificate faults (cert/plane.py): cert-backfill (kill the node,
    # wipe its commit-certificate store, respawn mid-fleet while the
    # chain keeps advancing — the backfill worker must re-certify the
    # retained range from stored commits, observable via the
    # cometbft_cert_* /metrics counters and the commit_certificate RPC
    # route; requires an all-BLS net, i.e. manifest key_type bls12381);
    # overload faults (libs/overload.py): mempool-storm (respawn with a
    # SMALL mempool and drive fire-and-forget admission waves at the
    # node's RPC — the chain must keep advancing, the exempt health
    # route must answer mid-storm, and the mempool plane's sheds must
    # land on /metrics), rpc-flood (respawn with a 1-slot write budget
    # and flood concurrent broadcast_tx_commit calls — excess requests
    # must shed with the unified -32005 envelope, plane "rpc", while
    # the exempt control plane keeps serving)
    perturb: list[str] = field(default_factory=list)
    # fleet topologies: which region this node lives in (regional/hub
    # topologies wire peering and netchaos link profiles from this;
    # meaningless under topology "full")
    region: int = 0

    PERTURBATIONS = ("kill", "pause", "restart", "disconnect",
                     "device-kill", "device-flap",
                     "chip-kill", "chip-flap",
                     "partition", "byzantine", "flood", "light-fleet",
                     "crash-storm", "disk-fault", "cert-backfill",
                     "mempool-storm", "rpc-flood")
    # perturbations that take a ":<device-index>" argument
    INDEXED_PERTURBATIONS = ("chip-kill", "chip-flap")
    # disk-fault kinds an OS process can survive to keep serving (the
    # crash kinds torn_write/fsync_lie belong to the in-proc matrix,
    # tests/test_storage_crash_matrix.py, which models the power cut)
    DISK_FAULT_KINDS = ("bitrot", "enospc", "eio", "fsync_error", "slow")

    @staticmethod
    def split_perturb(p: str) -> tuple[str, str]:
        """-> (base, arg); arg is "" when the perturbation is unindexed."""
        base, _, arg = p.partition(":")
        return base, arg

    def validate(self) -> None:
        if self.region < 0:
            raise ValueError("node region cannot be negative")
        if self.database not in ("sqlite", "memdb"):
            raise ValueError(f"unknown database {self.database!r}")
        if self.abci_protocol not in ("builtin", "tcp", "unix", "grpc"):
            raise ValueError(f"unknown abci protocol {self.abci_protocol!r}")
        if self.fuzz not in ("", "drop", "delay"):
            raise ValueError(f"unknown fuzz mode {self.fuzz!r}")
        for p in self.perturb:
            base, arg = self.split_perturb(p)
            if base not in self.PERTURBATIONS:
                raise ValueError(f"unknown perturbation {p!r}")
            if not arg:
                continue
            if base == "crash-storm":
                from cometbft_tpu.libs.fail import SITES

                if arg not in SITES:
                    raise ValueError(
                        f"unknown crash site in {p!r} (sites: {SITES})")
            elif base == "disk-fault":
                if arg not in self.DISK_FAULT_KINDS:
                    raise ValueError(
                        f"unknown disk-fault kind in {p!r} "
                        f"(kinds: {self.DISK_FAULT_KINDS})")
            elif base in self.INDEXED_PERTURBATIONS:
                from cometbft_tpu.libs.chaos import MESH_CHAOS_DEVICES

                try:
                    idx = int(arg)
                except ValueError:
                    raise ValueError(
                        f"bad device index in {p!r}") from None
                if not 0 <= idx < MESH_CHAOS_DEVICES:
                    raise ValueError(
                        f"device index out of range in {p!r} "
                        f"(0..{MESH_CHAOS_DEVICES - 1})")
            else:
                raise ValueError(
                    f"perturbation {base!r} takes no index ({p!r})")


@dataclass
class Manifest:
    """A whole testnet (manifest.go Manifest, the options this framework
    exercises — grown to fleet scale: 50-100 node hub/regional
    topologies, netchaos link profiles, and NET-level perturbations)."""

    name: str = "testnet"
    initial_height: int = 1
    initial_state: dict[str, str] = field(default_factory=dict)
    vote_extensions_enable_height: int = 0
    target_height_delta: int = 4  # heights every node must advance
    # peer-wiring shape (runner.setup): "full" = every node peers with
    # every other (the classic 4-val net); "hub" = the first `hubs` nodes
    # form a hub mesh, spokes peer only with hubs; "regional" = full mesh
    # within a region, region gateways (first node of each region) mesh
    # across regions — the shape production gossip pathologies need;
    # "organic" = NO persistent wiring at all: node 0 is the lone seed,
    # every other node boots with an empty address book knowing only the
    # seed and must GROW its peer set through PEX discovery
    topology: str = "full"
    regions: int = 1    # regional topology: how many regions
    hubs: int = 2       # hub topology: how many hub nodes
    # named netchaos link profile for CROSS-REGION links ("" = clean
    # wire): "wan" = high-latency, "lossy-wan" = high-latency + loss.
    # Intra-region links stay clean — the intra-fast/cross-slow shape.
    link_profile: str = ""
    # NET-level perturbations (runner, after per-node perturbations):
    #   churn-storm[:pct]         rolling restarts of pct% of the fleet
    #                             (default 30), quorum preserved per wave
    #   regional-partition[:r]    cut region r (default 0) off, assert the
    #                             minority stalls while the majority
    #                             commits, heal, assert catch-up + the
    #                             heal metric
    #   byzantine-minority[:k]    restart k nodes (default n//3, capped to
    #                             keep a +2/3 honest quorum) equivocating;
    #                             honest nodes must commit evidence
    #   minority-partition[:k]    cut the LAST k nodes off (default n//4,
    #                             capped to keep a +2/3 majority quorum) —
    #                             the topology-agnostic sibling of
    #                             regional-partition (under the hub
    #                             topology the last nodes are spokes, so
    #                             the hub mesh stays intact); the majority
    #                             must commit, the cut side stall, the
    #                             heal catch it up + land on the metric
    net_perturb: list[str] = field(default_factory=list)
    # compact vote-set reconciliation (consensus.gossip_vote_summaries)
    # for every node: False = the full-gossip baseline, the control arm
    # of the amplification measurement
    vote_summaries: bool = True
    # instrumentation.height_slow_ms for every node: a height whose wall
    # time exceeds this captures a postmortem bundle (consensus/
    # timeline.py) served by the `postmortems` route; <= 0 disables
    height_slow_ms: float = 0.0
    # validator key scheme for the whole net: "ed25519" (default) or
    # "bls12381" (all-BLS — what the commit-certificate plane needs to
    # produce certs; cert-backfill perturbations require it)
    key_type: str = "ed25519"
    nodes: dict[str, NodeManifest] = field(default_factory=dict)

    TOPOLOGIES = ("full", "hub", "regional", "organic")
    KEY_TYPES = ("ed25519", "bls12381")
    NET_PERTURBATIONS = ("churn-storm", "regional-partition",
                         "byzantine-minority", "minority-partition")
    LINK_PROFILES = ("", "wan", "lossy-wan")

    def validate(self) -> None:
        if not self.nodes:
            raise ValueError("manifest has no nodes")
        if self.initial_height < 1:
            raise ValueError("initial_height must be >= 1")
        if self.topology not in self.TOPOLOGIES:
            raise ValueError(f"unknown topology {self.topology!r} "
                             f"(expected one of {self.TOPOLOGIES})")
        if self.regions < 1:
            raise ValueError("regions must be >= 1")
        if self.topology == "hub" and not 1 <= self.hubs <= len(self.nodes):
            raise ValueError(
                f"hub topology needs 1 <= hubs <= nodes, got {self.hubs}")
        if self.link_profile not in self.LINK_PROFILES:
            raise ValueError(f"unknown link_profile {self.link_profile!r} "
                             f"(expected one of {self.LINK_PROFILES})")
        if self.key_type not in self.KEY_TYPES:
            raise ValueError(f"unknown key_type {self.key_type!r} "
                             f"(expected one of {self.KEY_TYPES})")
        if self.key_type != "bls12381" and any(
                NodeManifest.split_perturb(p)[0] == "cert-backfill"
                for n in self.nodes.values() for p in n.perturb):
            raise ValueError(
                "cert-backfill perturbation requires key_type = bls12381 "
                "(certificates only exist on all-BLS validator sets)")
        if self.link_profile and self.topology != "regional":
            raise ValueError("link_profile requires the regional topology")
        for p in self.net_perturb:
            base, _, arg = p.partition(":")
            if base not in self.NET_PERTURBATIONS:
                raise ValueError(f"unknown net perturbation {p!r}")
            if arg:
                try:
                    v = int(arg)
                except ValueError:
                    raise ValueError(
                        f"bad net perturbation arg in {p!r}") from None
                if v < 0:
                    raise ValueError(f"negative arg in {p!r}")
                if base == "churn-storm" and not 1 <= v <= 100:
                    raise ValueError(
                        f"churn-storm percent out of range in {p!r}")
                if (base == "minority-partition"
                        and (v < 1 or 3 * v >= len(self.nodes))):
                    raise ValueError(
                        f"minority-partition must cut a quorum-"
                        f"preserving minority (1 <= k, 3*k < nodes) "
                        f"in {p!r}")
            if (base == "regional-partition"
                    and (self.topology != "regional" or self.regions < 2)):
                raise ValueError(
                    "regional-partition needs topology=regional with "
                    ">= 2 regions")
        for n in self.nodes.values():
            n.validate()
            if self.topology == "regional" and not 0 <= n.region < self.regions:
                raise ValueError(
                    f"node region {n.region} out of range "
                    f"(0..{self.regions - 1})")

    def region_names(self) -> dict[str, int]:
        """node name -> region index (sorted-name order, as the runner
        sees them)."""
        return {name: self.nodes[name].region for name in sorted(self.nodes)}

    # ---------------------------------------------------------- TOML

    def to_toml(self) -> str:
        def q(s: str) -> str:
            return '"' + s.replace("\\", "\\\\").replace('"', '\\"') + '"'

        out = [
            f"name = {q(self.name)}",
            f"initial_height = {self.initial_height}",
            f"vote_extensions_enable_height = {self.vote_extensions_enable_height}",
            f"target_height_delta = {self.target_height_delta}",
            f"topology = {q(self.topology)}",
            f"regions = {self.regions}",
            f"hubs = {self.hubs}",
            f"link_profile = {q(self.link_profile)}",
            "net_perturb = ["
            + ", ".join(q(p) for p in self.net_perturb) + "]",
            f"vote_summaries = {'true' if self.vote_summaries else 'false'}",
            f"height_slow_ms = {float(self.height_slow_ms)}",
            f"key_type = {q(self.key_type)}",
        ]
        if self.initial_state:
            out.append("")
            out.append("[initial_state]")
            for k in sorted(self.initial_state):
                out.append(f"{q(k)} = {q(self.initial_state[k])}")
        for name in sorted(self.nodes):
            n = self.nodes[name]
            out.append("")
            out.append(f"[node.{name}]")
            out.append(f"database = {q(n.database)}")
            out.append(f"abci_protocol = {q(n.abci_protocol)}")
            out.append(f"privval_protocol = {q(n.privval_protocol)}")
            out.append(f"persist_interval = {n.persist_interval}")
            out.append(f"retain_blocks = {n.retain_blocks}")
            out.append(f"fuzz = {q(n.fuzz)}")
            out.append(f"region = {n.region}")
            out.append(
                "perturb = [" + ", ".join(q(p) for p in n.perturb) + "]")
        return "\n".join(out) + "\n"

    @classmethod
    def from_toml(cls, text: str) -> "Manifest":
        doc = tomllib.loads(text)
        m = cls(
            name=doc.get("name", "testnet"),
            initial_height=int(doc.get("initial_height", 1)),
            initial_state={str(k): str(v)
                           for k, v in doc.get("initial_state", {}).items()},
            vote_extensions_enable_height=int(
                doc.get("vote_extensions_enable_height", 0)),
            target_height_delta=int(doc.get("target_height_delta", 4)),
            topology=str(doc.get("topology", "full")),
            regions=int(doc.get("regions", 1)),
            hubs=int(doc.get("hubs", 2)),
            link_profile=str(doc.get("link_profile", "")),
            net_perturb=list(doc.get("net_perturb", [])),
            vote_summaries=bool(doc.get("vote_summaries", True)),
            height_slow_ms=float(doc.get("height_slow_ms", 0.0)),
            key_type=str(doc.get("key_type", "ed25519")),
        )
        for name, nd in doc.get("node", {}).items():
            m.nodes[name] = NodeManifest(
                database=nd.get("database", "sqlite"),
                abci_protocol=nd.get("abci_protocol", "builtin"),
                privval_protocol=nd.get("privval_protocol", "file"),
                persist_interval=int(nd.get("persist_interval", 1)),
                retain_blocks=int(nd.get("retain_blocks", 0)),
                fuzz=str(nd.get("fuzz", "")),
                perturb=list(nd.get("perturb", [])),
                region=int(nd.get("region", 0)),
            )
        m.validate()
        return m
