"""Random manifest generation over the config-matrix space.

Reference: test/e2e/generator/generate.go:20-66 — uniform/weighted/
probabilistic choices over topology, databases, ABCI transports, initial
state, and a perturbation schedule, seeded for reproducibility."""

from __future__ import annotations

import random

from cometbft_tpu.e2e.manifest import Manifest, NodeManifest

TOPOLOGIES = ("single", "quad")  # node counts 1 / 4 (generate.go "topology")
DATABASES = ("sqlite", "memdb")
ABCI_PROTOCOLS = ("builtin", "tcp", "unix", "grpc")
INITIAL_HEIGHTS = (1, 1000)
INITIAL_STATES: tuple[dict, ...] = (
    {},
    {"initial01": "a", "initial02": "b", "initial03": "c"},
)
VOTE_EXT_HEIGHT_OFFSETS = (0, 2)  # 0 = disabled
# perturbation -> probability a node gets it (generate.go nodePerturbations;
# "disconnect" needs a network layer OS processes don't have — the in-proc
# perturbation matrix, tests/test_e2e_perturb.py, covers it). device-kill /
# device-flap restart a node with a CBFT_CHAOS schedule armed (runner.py):
# the accelerator dies or flaps and the verify ladder must keep committing.
# partition splits the net 2-2 at runtime (unsafe_net_chaos route);
# byzantine/flood restart the node adversarially (consensus/byzantine.py)
# and assert detection via evidence_committed / peer_bans metrics.
# light-fleet restarts a node with the serving plane enabled, drives a
# client swarm at light_verify, partitions the fleet node mid-soak, and
# asserts post-heal p99 via the light_fleet metrics.
# crash-storm cycles >= 3 kill-at-crash-site/respawns on one node
# (CBFT_CRASH_SITE); disk-fault arms a bounded diskchaos schedule at
# runtime (unsafe_disk_chaos) and asserts the faults were counted and
# the node degraded or halted typed — never served a differing block.
# mempool-storm respawns the node with a small mempool and drives
# admission waves at its RPC (the chain must keep advancing, sheds must
# land on /metrics); rpc-flood respawns with a 1-slot write budget and
# floods concurrent commit-wait writes (excess must shed -32005 while
# the exempt control plane keeps serving). cert-backfill kills a node,
# wipes its commit-certificate store, and respawns it mid-fleet — the
# backfill worker must re-certify the retained range (needs an all-BLS
# net: drawing it flips the manifest to key_type bls12381).
PERTURBATIONS = {"kill": 0.1, "pause": 0.1, "restart": 0.1,
                 "device-kill": 0.05, "device-flap": 0.05,
                 "chip-kill:1": 0.05, "chip-flap:1": 0.05,
                 "partition": 0.05, "byzantine": 0.05, "flood": 0.05,
                 "light-fleet": 0.05,
                 "crash-storm": 0.05, "crash-storm:abci.apply": 0.03,
                 "disk-fault:bitrot": 0.04, "disk-fault:enospc": 0.03,
                 "disk-fault:slow": 0.03,
                 "mempool-storm": 0.05, "rpc-flood": 0.04,
                 "cert-backfill": 0.05}
# perturbations that kill + respawn the OS process (a memdb node would
# lose its stores while its out-of-process app keeps state); compared by
# BASE name (chip-kill:N respawns too)
RESPAWN_PERTURBATIONS = {"kill", "restart", "device-kill", "device-flap",
                         "chip-kill", "chip-flap", "byzantine", "flood",
                         "light-fleet", "crash-storm", "disk-fault",
                         "mempool-storm", "rpc-flood", "cert-backfill"}


def generate_manifest(rng: random.Random, index: int) -> Manifest:
    topology = rng.choice(TOPOLOGIES)
    n = {"single": 1, "quad": 4}[topology]
    initial_height = rng.choice(INITIAL_HEIGHTS)
    m = Manifest(
        name=f"gen-{index:03d}-{topology}",
        initial_height=initial_height,
        initial_state=dict(rng.choice(INITIAL_STATES)),
        vote_extensions_enable_height=(
            initial_height + rng.choice(VOTE_EXT_HEIGHT_OFFSETS)
            if rng.random() < 0.5 else 0),
    )
    # a slice of the matrix runs the reconciliation-off control arm
    if rng.random() < 0.15:
        m.vote_summaries = False
    # occasionally wire the quad as a small regional net (2 regions, wan
    # cross-links): the matrix keeps the fleet plumbing honest at a size
    # CI can afford — the 50-100 node shapes are deliberate
    # (generate_fleet_manifest), not rolled
    if n == 4 and rng.random() < 0.15:
        m.topology = "regional"
        m.regions = 2
        m.link_profile = rng.choice(("wan", "lossy-wan"))
    for i in range(n):
        node = NodeManifest(
            database=rng.choice(DATABASES),
            abci_protocol=rng.choice(ABCI_PROTOCOLS),
            persist_interval=rng.choice((0, 1, 5)),
            retain_blocks=rng.choice((0, 20)),
            region=(i % 2 if m.topology == "regional" else 0),
        )
        if n >= 4:  # perturbing a 1-node net just halts it
            for p, prob in PERTURBATIONS.items():
                if rng.random() < prob:
                    node.perturb.append(p)
            # occasional always-on stream fuzzing rides alongside
            # (reference generator testFuzz); latency-only so a fuzzed
            # node never costs the quorum its liveness margin
            if rng.random() < 0.05:
                node.fuzz = "delay"
        m.nodes[f"node{i}"] = node
    # at most one perturbed node per net: +2/3 of 4 must stay live while a
    # perturbation is in flight
    perturbed = [name for name, nd in m.nodes.items() if nd.perturb]
    for name in perturbed[1:]:
        m.nodes[name].perturb = []
    # a kill/restart wipes a memdb node's stores while its out-of-process
    # app keeps state -> the ABCI handshake correctly refuses an app ahead
    # of the store. Such nodes need persistent storage (the reference
    # matrix only has persistent engines, generate.go nodeDatabases);
    # pause never loses the process, so memdb+pause stays in the matrix.
    if perturbed:
        nd = m.nodes[perturbed[0]]
        if nd.database == "memdb" and {
                p.partition(":")[0] for p in nd.perturb
        } & RESPAWN_PERTURBATIONS:
            nd.database = "sqlite"
        # certificates only exist on all-BLS validator sets, so drawing
        # cert-backfill flips the whole net's key scheme
        if any(p.partition(":")[0] == "cert-backfill" for p in nd.perturb):
            m.key_type = "bls12381"
    m.validate()
    return m


def generate_manifests(seed: int, count: int) -> list[Manifest]:
    rng = random.Random(seed)
    return [generate_manifest(rng, i) for i in range(count)]


# ------------------------------------------------------------- fleets
# Deterministic fleet-scale manifests (50-100 node nets are booted on
# purpose by tests/bench, not rolled from the random matrix — a 100-node
# net is a deliberate resource commitment, runner._resource_guard gates
# it). Hub/spoke and regional topologies with the intra-region-fast /
# cross-region-slow link shape (runner.LINK_PROFILES).

FLEET_TOPOLOGIES = ("full", "hub", "regional", "organic")


def generate_fleet_manifest(
    n_nodes: int,
    topology: str = "regional",
    regions: int = 4,
    hubs: int = 4,
    link_profile: str = "",
    net_perturb: tuple[str, ...] = (),
    target_height_delta: int = 4,
    name: str = "",
    vote_summaries: bool = True,
    height_slow_ms: float = 0.0,
) -> Manifest:
    """One fleet testnet: `n_nodes` sqlite+builtin validators wired by
    `topology`, regions assigned round-robin, with the given net-level
    perturbation schedule. memdb is excluded (churn storms respawn
    processes) and out-of-process ABCI apps are excluded (they would
    double the fleet's process count for no gossip-plane coverage)."""
    if topology not in FLEET_TOPOLOGIES:
        raise ValueError(f"unknown fleet topology {topology!r}")
    if link_profile and topology != "regional":
        # loudly, not silently: a clean-wire run misread as WAN-resilient
        # is exactly the misconfiguration Manifest.validate exists for
        raise ValueError(
            f"link_profile {link_profile!r} requires the regional "
            f"topology (got {topology!r})")
    regions = regions if topology == "regional" else 1
    m = Manifest(
        name=name or f"fleet-{n_nodes:03d}-{topology}",
        topology=topology,
        regions=regions,
        hubs=min(hubs, n_nodes),
        link_profile=link_profile,
        net_perturb=list(net_perturb),
        target_height_delta=target_height_delta,
        vote_summaries=vote_summaries,
        height_slow_ms=height_slow_ms,
    )
    for i in range(n_nodes):
        m.nodes[f"node{i:03d}"] = NodeManifest(
            database="sqlite", abci_protocol="builtin",
            region=i % regions,
        )
    m.validate()
    return m
