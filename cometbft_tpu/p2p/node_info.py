"""Node handshake info.

Reference: p2p/node_info.go — exchanged over the SecretConnection right
after the crypto handshake; carries protocol versions, the claimed node ID
(must match the SecretConnection-authenticated pubkey), network (chain
id), and the channel list for reactor compatibility checks
(node_info.go:142 CompatibleWith). Wire: the tendermint.p2p
DefaultNodeInfo protobuf (proto/tendermint/p2p/types.proto:14-34),
varint-delimited — the reference's handshake message, byte for byte.
"""

from __future__ import annotations

from dataclasses import dataclass, field

from cometbft_tpu.utils import protobuf as pb


@dataclass
class ProtocolVersion:
    p2p: int = 8
    block: int = 11
    app: int = 0


@dataclass
class NodeInfo:
    node_id: str = ""
    listen_addr: str = ""
    network: str = ""  # chain id
    version: str = ""
    channels: bytes = b""
    moniker: str = ""
    protocol_version: ProtocolVersion = field(default_factory=ProtocolVersion)
    tx_index: str = "on"
    rpc_address: str = ""

    def validate(self) -> None:
        """node_info.go:173 Validate (subset: structural checks)."""
        if not self.node_id:
            raise ValueError("node info: empty node id")
        if len(self.channels) > 64:
            raise ValueError("node info: too many channels")
        if len(set(self.channels)) != len(self.channels):
            raise ValueError("node info: duplicate channel ids")

    def compatible_with(self, other: "NodeInfo") -> None:
        """node_info.go:142: same block protocol + network, >=1 common
        channel."""
        if self.protocol_version.block != other.protocol_version.block:
            raise ValueError(
                f"incompatible block protocol: {self.protocol_version.block} vs "
                f"{other.protocol_version.block}"
            )
        if self.network != other.network:
            raise ValueError(f"different networks: {self.network!r} vs {other.network!r}")
        if self.channels and other.channels and not set(self.channels) & set(other.channels):
            raise ValueError("no common channels")

    # ------------------------------------------------------------- codec

    def encode(self) -> bytes:
        """tendermint.p2p.DefaultNodeInfo (types.proto:20-29)."""
        pv = pb.Writer()
        pv.uvarint(1, self.protocol_version.p2p)
        pv.uvarint(2, self.protocol_version.block)
        pv.uvarint(3, self.protocol_version.app)
        other = pb.Writer()
        other.string(1, self.tx_index)
        other.string(2, self.rpc_address)
        w = pb.Writer()
        w.message(1, pv.output(), always=True)
        w.string(2, self.node_id)
        w.string(3, self.listen_addr)
        w.string(4, self.network)
        w.string(5, self.version)
        w.bytes(6, self.channels)
        w.string(7, self.moniker)
        w.message(8, other.output(), always=True)
        return w.output()

    @classmethod
    def decode(cls, data: bytes) -> "NodeInfo":
        # proto3 zero values, NOT the dataclass defaults: an absent field
        # must decode to zero (a peer omitting protocol_version must not
        # inherit OUR version numbers and sneak past compatible_with)
        out = cls(protocol_version=ProtocolVersion(p2p=0, block=0, app=0),
                  tx_index="")
        r = pb.Reader(data)
        while not r.at_end():
            f, w = r.read_tag()
            if f == 1:
                pvr = pb.Reader(r.read_bytes())
                pv = ProtocolVersion(p2p=0, block=0, app=0)
                while not pvr.at_end():
                    pf, pw = pvr.read_tag()
                    if pf == 1:
                        pv.p2p = pvr.read_uvarint()
                    elif pf == 2:
                        pv.block = pvr.read_uvarint()
                    elif pf == 3:
                        pv.app = pvr.read_uvarint()
                    else:
                        pvr.skip(pw)
                out.protocol_version = pv
            elif f == 2:
                out.node_id = r.read_string()
            elif f == 3:
                out.listen_addr = r.read_string()
            elif f == 4:
                out.network = r.read_string()
            elif f == 5:
                out.version = r.read_string()
            elif f == 6:
                out.channels = r.read_bytes()
            elif f == 7:
                out.moniker = r.read_string()
            elif f == 8:
                orr = pb.Reader(r.read_bytes())
                while not orr.at_end():
                    of, ow = orr.read_tag()
                    if of == 1:
                        out.tx_index = orr.read_string()
                    elif of == 2:
                        out.rpc_address = orr.read_string()
                    else:
                        orr.skip(ow)
            else:
                r.skip(w)
        return out
