"""Node handshake info.

Reference: p2p/node_info.go — exchanged in plaintext-over-SecretConnection
right after the crypto handshake; carries protocol versions, the claimed
node ID (must match the SecretConnection-authenticated pubkey), network
(chain id), and the channel list for reactor compatibility checks
(node_info.go:142 CompatibleWith).
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field


@dataclass
class ProtocolVersion:
    p2p: int = 8
    block: int = 11
    app: int = 0


@dataclass
class NodeInfo:
    node_id: str = ""
    listen_addr: str = ""
    network: str = ""  # chain id
    version: str = ""
    channels: bytes = b""
    moniker: str = ""
    protocol_version: ProtocolVersion = field(default_factory=ProtocolVersion)
    tx_index: str = "on"
    rpc_address: str = ""

    def validate(self) -> None:
        """node_info.go:173 Validate (subset: structural checks)."""
        if not self.node_id:
            raise ValueError("node info: empty node id")
        if len(self.channels) > 64:
            raise ValueError("node info: too many channels")
        if len(set(self.channels)) != len(self.channels):
            raise ValueError("node info: duplicate channel ids")

    def compatible_with(self, other: "NodeInfo") -> None:
        """node_info.go:142: same block protocol + network, >=1 common
        channel."""
        if self.protocol_version.block != other.protocol_version.block:
            raise ValueError(
                f"incompatible block protocol: {self.protocol_version.block} vs "
                f"{other.protocol_version.block}"
            )
        if self.network != other.network:
            raise ValueError(f"different networks: {self.network!r} vs {other.network!r}")
        if self.channels and other.channels and not set(self.channels) & set(other.channels):
            raise ValueError("no common channels")

    # ------------------------------------------------------------- codec

    def encode(self) -> bytes:
        doc = {
            "node_id": self.node_id,
            "listen_addr": self.listen_addr,
            "network": self.network,
            "version": self.version,
            "channels": self.channels.hex(),
            "moniker": self.moniker,
            "protocol_version": {
                "p2p": self.protocol_version.p2p,
                "block": self.protocol_version.block,
                "app": self.protocol_version.app,
            },
            "tx_index": self.tx_index,
            "rpc_address": self.rpc_address,
        }
        return json.dumps(doc, separators=(",", ":")).encode()

    @classmethod
    def decode(cls, data: bytes) -> "NodeInfo":
        doc = json.loads(data)
        pv = doc.get("protocol_version", {})
        return cls(
            node_id=doc.get("node_id", ""),
            listen_addr=doc.get("listen_addr", ""),
            network=doc.get("network", ""),
            version=doc.get("version", ""),
            channels=bytes.fromhex(doc.get("channels", "")),
            moniker=doc.get("moniker", ""),
            protocol_version=ProtocolVersion(
                p2p=pv.get("p2p", 0), block=pv.get("block", 0), app=pv.get("app", 0)
            ),
            tx_index=doc.get("tx_index", "on"),
            rpc_address=doc.get("rpc_address", ""),
        )
