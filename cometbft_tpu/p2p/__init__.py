from cometbft_tpu.p2p.key import NodeKey
from cometbft_tpu.p2p.node_info import NodeInfo
from cometbft_tpu.p2p.base_reactor import Reactor, Envelope
from cometbft_tpu.p2p.peer import Peer
from cometbft_tpu.p2p.switch import Switch
from cometbft_tpu.p2p.transport import Transport

__all__ = [
    "NodeKey",
    "NodeInfo",
    "Reactor",
    "Envelope",
    "Peer",
    "Switch",
    "Transport",
]
