"""PEX reactor (reference: p2p/pex/pex_reactor.go).

Channel 0x00: PexRequest / PexAddrs. Outbound peers get asked for
addresses on connect; inbound requests are rate-limited per peer and
answered with a random book selection. An ensure-peers routine dials from
the address book (biased toward NEW addresses while the node is young)
until max_outbound is reached. Seed mode answers requests and disconnects
(crawler behavior) — pex_reactor.go:54-70.

Discovery-plane hardening (the eclipse defenses the book's hashed-bucket
geometry anchors):

- Gossip intake stamps every learned address with the gossip source's
  SOCKET host (unforgeable) so the book's per-source-group bucket caps
  bind to real network position, not to free-to-mint identities.
- Dial outcomes are AWAITED (Switch.dial_peer), not dropped: a failed
  dial lands on mark_attempt, which feeds bias-aware eviction and the
  per-address failure backoff — a dead address is not re-picked every
  ensure interval, and a flood of unroutable sybil claims burns itself
  out of the book.
- ensure_peers enforces a per-/16-group OUTBOUND cap so one netblock
  cannot own the whole outbound slot budget; persistent peers are
  exempt (operator intent outranks the heuristic).
- The thin-book peer pick rides an injectable RNG so tests are
  deterministic.
"""

from __future__ import annotations

import asyncio
import random
import time

from cometbft_tpu.libs import log as cmtlog
from cometbft_tpu.p2p.base_reactor import Envelope, Reactor
from cometbft_tpu.p2p.conn.connection import ChannelDescriptor
from cometbft_tpu.p2p.pex.addrbook import AddrBook, NetAddress, group16
from cometbft_tpu.utils import protobuf as pb

PEX_CHANNEL = 0x00
ENSURE_PEERS_INTERVAL = 30.0  # pex_reactor.go:33
MIN_REQUEST_INTERVAL = 10.0   # per-peer request rate limit
NEED_ADDRESS_THRESHOLD = 1000  # addrbook.go needAddressThreshold


def encode_request() -> bytes:
    w = pb.Writer()
    w.message(1, b"", always=True)
    return w.output()


def encode_addrs(addrs: list[NetAddress]) -> bytes:
    inner = pb.Writer()
    for a in addrs:
        aw = pb.Writer()
        aw.string(1, a.node_id)
        aw.string(2, a.host)
        aw.uvarint(3, a.port)
        inner.message(1, aw.output(), always=True)
    w = pb.Writer()
    w.message(2, inner.output(), always=True)
    return w.output()


def decode(data: bytes):
    """-> ('request', None) | ('addrs', [NetAddress])."""
    r = pb.Reader(data)
    f, wt = r.read_tag()
    if f == 1:
        return "request", None
    if f == 2:
        out: list[NetAddress] = []
        ir = pb.Reader(r.read_bytes())
        while not ir.at_end():
            jf, jw = ir.read_tag()
            if jf != 1:
                ir.skip(jw)
                continue
            ar = pb.Reader(ir.read_bytes())
            node_id, host, port = "", "", 0
            while not ar.at_end():
                kf, kw = ar.read_tag()
                if kf == 1:
                    node_id = ar.read_string()
                elif kf == 2:
                    host = ar.read_string()
                elif kf == 3:
                    port = ar.read_uvarint()
                else:
                    ar.skip(kw)
            if node_id:
                out.append(NetAddress(node_id=node_id, host=host, port=port))
        return "addrs", out
    raise ValueError(f"unknown pex message field {f}")


class PEXReactor(Reactor):
    """pex_reactor.go:75-520."""

    def __init__(self, book: AddrBook, max_outbound: int = 10,
                 seed_mode: bool = False,
                 ensure_interval: float = ENSURE_PEERS_INTERVAL,
                 max_group_outbound: int = 0,
                 rng: random.Random | None = None,
                 logger: cmtlog.Logger | None = None):
        super().__init__("PEXReactor", logger)
        self.book = book
        self.max_outbound = max_outbound
        self.seed_mode = seed_mode
        self.ensure_interval = ensure_interval
        # per-/16-group outbound cap; 0 = auto (half the outbound budget,
        # never below 2 so a two-group world still fills)
        self.max_group_outbound = (max_group_outbound
                                   or max(2, max_outbound // 2))
        self._rng = rng or random.Random()
        self._last_request: dict[str, float] = {}
        self._requested: set[str] = set()
        # outbound throttle: we must respect the SAME per-peer rate limit
        # we enforce inbound, or a thin address book makes ensure-peers
        # spam requests that the peer rightfully scores as a pex flood
        self._last_sent: dict[str, float] = {}
        # peer id -> /16 group of the host we actually dialed/see; feeds
        # the outbound diversity cap
        self._peer_groups: dict[str, str] = {}
        self._task: asyncio.Task | None = None

    def get_channels(self) -> list[ChannelDescriptor]:
        return [ChannelDescriptor(id=PEX_CHANNEL, priority=1,
                                  send_queue_capacity=10)]

    async def on_start(self) -> None:
        self._task = asyncio.create_task(self._ensure_peers_routine())

    async def on_stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass
            self._task = None
        self.book.save()

    # ------------------------------------------------------------ lifecycle

    async def add_peer(self, peer) -> None:
        """pex_reactor.go:145-165: learn the peer's self-address; ask
        outbound peers for more."""
        addr = self._peer_net_address(peer)
        if addr is not None:
            self.book.add_address(addr)
            if peer.outbound:
                # only addresses WE dialed successfully graduate to OLD —
                # an inbound connect proves nothing about the claimed
                # listen address (a sybil would mint OLD entries for free)
                self.book.mark_good(peer.id)
        self._peer_groups[peer.id] = group16(
            getattr(peer, "remote_host", "") or
            (addr.host if addr is not None else ""))
        if peer.outbound and not self.seed_mode:
            await self._request_addrs(peer)

    async def remove_peer(self, peer, reason) -> None:
        self._last_request.pop(peer.id, None)
        self._last_sent.pop(peer.id, None)
        self._requested.discard(peer.id)
        self._peer_groups.pop(peer.id, None)

    def _peer_net_address(self, peer) -> NetAddress | None:
        listen = getattr(peer.node_info, "listen_addr", "")
        if not listen:
            return None
        try:
            na = NetAddress.parse(f"{peer.id}@{listen.removeprefix('tcp://')}")
            # the source of a self-reported address is the peer itself;
            # the socket host is the unforgeable group key
            na.src_id = peer.id
            na.src_host = getattr(peer, "remote_host", "") or na.host
            return na
        except (ValueError, TypeError):
            return None

    # -------------------------------------------------------------- wire

    async def _request_addrs(self, peer) -> None:
        now = time.time()
        if now - self._last_sent.get(peer.id, 0.0) < MIN_REQUEST_INTERVAL:
            return
        self._last_sent[peer.id] = now
        self._requested.add(peer.id)
        await peer.send(PEX_CHANNEL, encode_request())

    async def receive(self, e: Envelope) -> None:
        try:
            kind, payload = decode(e.message)
        except Exception as err:  # noqa: BLE001
            self.logger.error("bad pex message", err=str(err))
            return
        peer = e.src
        if kind == "request":
            # rate limit (pex_reactor.go:230 receiveRequest)
            now = time.time()
            last = self._last_request.get(peer.id, 0.0)
            if now - last < MIN_REQUEST_INTERVAL:
                self.logger.info("pex request too soon; disconnecting",
                                 peer=peer.id)
                if self.switch is not None:
                    await self.switch.stop_peer_for_error(peer, "pex flood",
                                                          score=1.0)
                return
            self._last_request[peer.id] = now
            await peer.send(PEX_CHANNEL, encode_addrs(self.book.selection()))
            if self.seed_mode and self.switch is not None:
                # seed: serve and hang up (pex_reactor.go:205) — our own
                # doing, so it must not score against the client
                await self.switch.stop_peer_for_error(peer, "seed served",
                                                      score=0.0)
        else:  # addrs
            if peer.id not in self._requested:
                # unsolicited PexAddrs is protocol abuse (pex_reactor.go:260)
                if self.switch is not None:
                    await self.switch.stop_peer_for_error(
                        peer, "unsolicited pex addrs", score=1.0)
                return
            self._requested.discard(peer.id)
            src_host = getattr(peer, "remote_host", "")
            for a in payload or []:
                a.src_id = peer.id
                # bucket attribution binds to the sender's SOCKET host: a
                # sybil swarm behind one /16 shares one source group no
                # matter how many identities it mints
                a.src_host = src_host
                self.book.add_address(a)

    # ------------------------------------------------------------- dialing

    async def _ensure_peers_routine(self) -> None:
        """pex_reactor.go:300 ensurePeersRoutine."""
        while True:
            try:
                await self._ensure_peers()
            except asyncio.CancelledError:
                raise
            except Exception as e:  # noqa: BLE001
                self.logger.error("ensure peers failed", err=str(e))
            await asyncio.sleep(self.ensure_interval)

    def _outbound_group_counts(self) -> dict[str, int]:
        counts: dict[str, int] = {}
        for p in self.switch.peers.values():
            if not p.outbound:
                continue
            g = self._peer_groups.get(
                p.id, group16(getattr(p, "remote_host", "")))
            counts[g] = counts.get(g, 0) + 1
        return counts

    async def _ensure_peers(self) -> None:
        if self.switch is None:
            return
        out_count = sum(1 for p in self.switch.peers.values() if p.outbound)
        needed = self.max_outbound - out_count
        if needed <= 0:
            return
        # young nodes bias toward NEW addresses (pex_reactor.go:330)
        bias = max(30, 100 - 10 * len(self.switch.peers))
        groups = self._outbound_group_counts()
        picks: list[NetAddress] = []
        tried: set[str] = set()
        while len(picks) < needed:
            addr = self.book.pick_address(new_bias_pct=bias)
            if addr is None or addr.node_id in tried:
                break
            tried.add(addr.node_id)
            if (addr.node_id in self.switch.peers
                    or addr.node_id == self.book.our_id):
                continue
            # outbound diversity: one /16 group may not own more than
            # max_group_outbound slots — persistent/protected peers are
            # operator intent and bypass the heuristic
            g = addr.group
            if (groups.get(g, 0) >= self.max_group_outbound
                    and not self.book.is_protected(addr.node_id)):
                continue
            groups[g] = groups.get(g, 0) + 1
            self.book.mark_attempt(addr.node_id)
            picks.append(addr)
        if picks:
            # dial concurrently, AWAITING outcomes: a failure has already
            # been counted by mark_attempt (backoff + eviction bias);
            # a success resets it via add_peer -> mark_good
            results = await asyncio.gather(
                *(self.switch.dial_peer(a.addr) for a in picks),
                return_exceptions=True)
            for a, ok in zip(picks, results):
                if ok is not True:
                    self.logger.info("pex dial failed", addr=a.addr,
                                     attempts=a.attempts)
        # book still wants addresses (addrbook.go needAddressThreshold):
        # ask a RANDOM existing peer — inbound included, which is exactly
        # the surface a sybil swarm floods; the per-peer rate limit and
        # the book's hashed-bucket geometry are the defense, not silence
        if self.book.size() < NEED_ADDRESS_THRESHOLD and self.switch.peers:
            peer = self._rng.choice(list(self.switch.peers.values()))
            await self._request_addrs(peer)
