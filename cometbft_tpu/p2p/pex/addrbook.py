"""Address book (reference: p2p/pex/addrbook.go).

Known peer addresses bucketed NEW (heard about) vs OLD (connected
successfully), with attempt/success bookkeeping, biased random selection,
ban marking, and JSON persistence.

THREAT-MODEL DELTA vs the reference (addrbook.go:70-140): the reference
hashes addresses into 256 NEW / 64 OLD buckets keyed by a random book
nonce and the source's /16 group, capping how much of the book any one
gossip source can occupy — its defense against address poisoning /
eclipse precursors at internet scale. This book keeps the NEW/OLD split,
per-source attribution, ban marking, and selection bias over flat dicts,
plus a total-size cap with bias-aware eviction — sufficient against a
single misbehaving peer at testnet/consortium scale, but an attacker
controlling many source identities can claim a larger fraction of the NEW
set than the hashed-bucket geometry would allow. Deployments on open
internets should front the book with the hashed geometry before relying
on it for eclipse resistance.
"""

from __future__ import annotations

import json
import os
import random
import time
from dataclasses import dataclass, field


@dataclass
class NetAddress:
    """pex/addrbook.go knownAddress + p2p.NetAddress."""

    node_id: str
    host: str
    port: int
    src_id: str = ""
    attempts: int = 0
    last_attempt: float = 0.0
    last_success: float = 0.0
    banned_until: float = 0.0
    is_old: bool = False  # graduated to the OLD (tried) set

    @property
    def addr(self) -> str:
        return f"{self.node_id}@{self.host}:{self.port}"

    @classmethod
    def parse(cls, s: str, src_id: str = "") -> "NetAddress":
        node_id, _, hostport = s.partition("@")
        host, _, port = hostport.rpartition(":")
        return cls(node_id=node_id, host=host or "127.0.0.1",
                   port=int(port), src_id=src_id)

    def is_banned(self, now: float) -> bool:
        return now < self.banned_until


class AddrBook:
    """pex/addrbook.go:70-640 (flat-bucket variant)."""

    MAX_NEW_ADDRS = 1000
    MAX_OLD_ADDRS = 500
    # addrbook.go getSelection: up to 23% of the book, capped
    SELECT_PCT = 23
    MAX_SELECTION = 250

    def __init__(self, file_path: str = "", our_id: str = ""):
        self.file_path = file_path
        self.our_id = our_id
        self._addrs: dict[str, NetAddress] = {}
        self._rng = random.Random()
        if file_path and os.path.exists(file_path):
            self._load()

    # ------------------------------------------------------------- intake

    def add_address(self, addr: NetAddress) -> bool:
        """addrbook.go:178 AddAddress: new addresses land in NEW."""
        if not addr.node_id or addr.node_id == self.our_id:
            return False
        existing = self._addrs.get(addr.node_id)
        if existing is not None:
            # keep the stronger record; refresh the routable address
            existing.host, existing.port = addr.host, addr.port
            return False
        new_count = sum(1 for a in self._addrs.values() if not a.is_old)
        if new_count >= self.MAX_NEW_ADDRS:
            self._evict_worst_new()
        self._addrs[addr.node_id] = addr
        return True

    def _evict_worst_new(self) -> None:
        new = [a for a in self._addrs.values() if not a.is_old]
        if not new:
            return
        worst = max(new, key=lambda a: (a.attempts, -a.last_attempt))
        self._addrs.pop(worst.node_id, None)

    # ----------------------------------------------------------- lifecycle

    def mark_attempt(self, node_id: str) -> None:
        a = self._addrs.get(node_id)
        if a is not None:
            a.attempts += 1
            a.last_attempt = time.time()

    def mark_good(self, node_id: str) -> None:
        """addrbook.go MarkGood: graduate to OLD, reset attempts."""
        a = self._addrs.get(node_id)
        if a is not None:
            a.attempts = 0
            a.last_success = time.time()
            old_count = sum(1 for x in self._addrs.values() if x.is_old)
            if not a.is_old and old_count < self.MAX_OLD_ADDRS:
                a.is_old = True

    def mark_bad(self, node_id: str, ban_seconds: float = 24 * 3600) -> None:
        a = self._addrs.get(node_id)
        if a is not None:
            a.banned_until = time.time() + ban_seconds
            a.is_old = False

    def remove(self, node_id: str) -> None:
        self._addrs.pop(node_id, None)

    # ----------------------------------------------------------- selection

    def pick_address(self, new_bias_pct: int = 50) -> NetAddress | None:
        """addrbook.go:260 PickAddress: choose OLD vs NEW with the given
        bias, then uniformly within the chosen set."""
        now = time.time()
        usable = [a for a in self._addrs.values() if not a.is_banned(now)]
        if not usable:
            return None
        old = [a for a in usable if a.is_old]
        new = [a for a in usable if not a.is_old]
        pick_new = self._rng.randrange(100) < new_bias_pct
        pool = new if (pick_new and new) or not old else old
        return self._rng.choice(pool)

    def selection(self) -> list[NetAddress]:
        """addrbook.go:315 GetSelection: a random ~23% sample (capped) for
        answering a PEX request."""
        now = time.time()
        usable = [a for a in self._addrs.values() if not a.is_banned(now)]
        n = min(self.MAX_SELECTION,
                max(1, len(usable) * self.SELECT_PCT // 100)) if usable else 0
        return self._rng.sample(usable, min(n, len(usable)))

    def is_empty(self) -> bool:
        return not self._addrs

    def has(self, node_id: str) -> bool:
        return node_id in self._addrs

    def size(self) -> int:
        return len(self._addrs)

    # ---------------------------------------------------------- persistence

    def save(self) -> None:
        if not self.file_path:
            return
        doc = [
            {"id": a.node_id, "host": a.host, "port": a.port,
             "src": a.src_id, "attempts": a.attempts,
             "last_success": a.last_success, "old": a.is_old,
             "banned_until": a.banned_until}
            for a in self._addrs.values()
        ]
        tmp = self.file_path + ".tmp"
        os.makedirs(os.path.dirname(self.file_path) or ".", exist_ok=True)
        with open(tmp, "w") as f:
            json.dump(doc, f)
        os.replace(tmp, self.file_path)

    def _load(self) -> None:
        with open(self.file_path) as f:
            doc = json.load(f)
        for d in doc:
            self._addrs[d["id"]] = NetAddress(
                node_id=d["id"], host=d["host"], port=d["port"],
                src_id=d.get("src", ""), attempts=d.get("attempts", 0),
                last_success=d.get("last_success", 0.0),
                banned_until=d.get("banned_until", 0.0),
                is_old=d.get("old", False),
            )
