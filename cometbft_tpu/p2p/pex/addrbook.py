"""Address book (reference: p2p/pex/addrbook.go).

Known peer addresses bucketed NEW (heard about) vs OLD (connected
successfully), with attempt/success bookkeeping, biased random selection,
ban marking, and durable JSON persistence.

HASHED-BUCKET GEOMETRY (addrbook.go:70-140): the NEW set is 256 buckets
of 64 slots, the OLD set 64 buckets of 64 slots. A NEW address's bucket
index is keyed by a PERSISTED RANDOM BOOK NONCE plus the gossip SOURCE's
/16 group: for any one source group only NEW_BUCKETS_PER_GROUP (32) of
the 256 bucket indices are reachable, so an attacker controlling many
source identities behind one /16 can occupy at most 32*64 slots (12.5%
of the NEW bucket space) no matter how many identities or claimed
addresses it floods — the eclipse-precursor defense the flat-dict book
explicitly lacked. OLD bucket indices are keyed by the ADDRESS's own
group (OLD entries were dialed successfully; their host is earned, not
claimed). The nonce persists with the book so bucket placement survives
restarts; a fresh nonce (new book) re-shuffles the geometry, which is
exactly the reference behavior.

Eviction is bias-aware and bucket-local: a full NEW bucket evicts its
worst entry (most failed attempts, oldest attempt), never a PROTECTED
entry (persistent/unconditional peers the operator configured). NEW->OLD
graduation moves the entry between bucket arrays; a full OLD bucket
demotes its worst entry back to NEW to make room.

Persistence rides libs/diskio.atomic_write_durable through the
`addrbook.save` disk-chaos site: a torn/corrupt book file quarantines to
`<path>.corrupt` at load and the node boots with an empty book instead
of bricking (the flat book raised out of _load on one bad byte).
"""

from __future__ import annotations

import hashlib
import json
import os
import random
import time
from dataclasses import dataclass

# geometry (addrbook.go:70-140; bucket counts per the reference, sizes
# shared: 64 slots per bucket either set)
NEW_BUCKET_COUNT = 256
OLD_BUCKET_COUNT = 64
BUCKET_SIZE = 64
# buckets one SOURCE group can reach in the NEW set / one ADDRESS group
# in the OLD set (addrbook.go newBucketsPerGroup / oldBucketsPerGroup)
NEW_BUCKETS_PER_GROUP = 32
OLD_BUCKETS_PER_GROUP = 4

# dial-failure backoff: a failed address is not re-picked until
# BACKOFF_BASE * 2^(attempts-1) (capped) has passed since the attempt —
# ensure-peers must not hammer the same dead address every interval
BACKOFF_BASE = 10.0
BACKOFF_MAX = 600.0
# a NEW address that failed this many consecutive dials is expired from
# the book entirely (protected addresses never expire)
MAX_NEW_FAILURES = 8


def group16(host: str) -> str:
    """The /16 group of a host: 'a.b' for a dotted-quad IPv4, the literal
    host for names (a DNS name is its own routing domain for our
    purposes), 'local' when unknown/empty."""
    if not host:
        return "local"
    parts = host.split(".")
    if len(parts) == 4 and all(p.isdigit() for p in parts):
        return f"{parts[0]}.{parts[1]}"
    return host.lower()


@dataclass
class NetAddress:
    """pex/addrbook.go knownAddress + p2p.NetAddress."""

    node_id: str
    host: str
    port: int
    src_id: str = ""
    # the gossip source's SOCKET host (unforgeable, set by the PEX
    # reactor from the live connection) — the bucket key ingredient
    src_host: str = ""
    attempts: int = 0
    last_attempt: float = 0.0
    last_success: float = 0.0
    banned_until: float = 0.0
    is_old: bool = False  # graduated to the OLD (tried) set

    @property
    def addr(self) -> str:
        return f"{self.node_id}@{self.host}:{self.port}"

    @property
    def group(self) -> str:
        return group16(self.host)

    @property
    def src_group(self) -> str:
        return group16(self.src_host)

    @classmethod
    def parse(cls, s: str, src_id: str = "") -> "NetAddress":
        node_id, _, hostport = s.partition("@")
        host, _, port = hostport.rpartition(":")
        return cls(node_id=node_id, host=host or "127.0.0.1",
                   port=int(port), src_id=src_id)

    def is_banned(self, now: float) -> bool:
        return now < self.banned_until

    def dial_backoff(self) -> float:
        if self.attempts <= 0:
            return 0.0
        return min(BACKOFF_BASE * (2 ** (self.attempts - 1)), BACKOFF_MAX)

    def dial_eligible(self, now: float) -> bool:
        """Not banned and past the failure backoff window."""
        if self.is_banned(now):
            return False
        return now - self.last_attempt >= self.dial_backoff()


class AddrBook:
    """pex/addrbook.go:70-640 (hashed-bucket geometry)."""

    # addrbook.go getSelection: up to 23% of the book, capped
    SELECT_PCT = 23
    MAX_SELECTION = 250

    def __init__(self, file_path: str = "", our_id: str = "",
                 rng: random.Random | None = None):
        self.file_path = file_path
        self.our_id = our_id
        self._rng = rng or random.Random()
        self._nonce = os.urandom(16).hex()
        self._addrs: dict[str, NetAddress] = {}  # id -> record (index)
        self._bucket_of: dict[str, int] = {}     # id -> bucket index
        self._new: list[dict[str, NetAddress]] = [
            {} for _ in range(NEW_BUCKET_COUNT)]
        self._old: list[dict[str, NetAddress]] = [
            {} for _ in range(OLD_BUCKET_COUNT)]
        # persistent/unconditional peers: never evicted, never expired
        self._protected: set[str] = set()
        self.metrics = None  # libs.metrics.P2PMetrics | None (node wires)
        # set when a corrupt book file was quarantined at load (the node
        # logs it; the boot continues with an empty book)
        self.load_error = ""
        self.quarantined_path = ""
        if file_path and os.path.exists(file_path):
            self._load()

    # ------------------------------------------------------------ geometry

    def _hash(self, *parts: str) -> int:
        h = hashlib.sha256("|".join((self._nonce,) + parts).encode())
        return int.from_bytes(h.digest()[:8], "big")

    def new_bucket_index(self, addr: NetAddress) -> int:
        """addrbook.go calcNewBucket: the inner hash (keyed by both the
        address and source groups) picks one of NEW_BUCKETS_PER_GROUP
        slots; the outer hash (keyed by the SOURCE group alone) maps that
        slot to a bucket — so a fixed source group reaches at most
        NEW_BUCKETS_PER_GROUP of the NEW_BUCKET_COUNT buckets."""
        slot = self._hash(addr.group, addr.src_group) % NEW_BUCKETS_PER_GROUP
        return self._hash(addr.src_group, str(slot)) % NEW_BUCKET_COUNT

    def old_bucket_index(self, addr: NetAddress) -> int:
        """addrbook.go calcOldBucket: keyed by the ADDRESS group (an OLD
        entry's host was dialed successfully — earned, not claimed)."""
        slot = self._hash(addr.addr) % OLD_BUCKETS_PER_GROUP
        return self._hash(addr.group, str(slot)) % OLD_BUCKET_COUNT

    def new_buckets_for_group(self, src_group: str) -> set[int]:
        """Every NEW bucket index reachable from one source group — the
        geometric occupancy bound the eclipse tests assert against."""
        return {self._hash(src_group, str(slot)) % NEW_BUCKET_COUNT
                for slot in range(NEW_BUCKETS_PER_GROUP)}

    # ------------------------------------------------------------- intake

    def add_address(self, addr: NetAddress) -> bool:
        """addrbook.go:178 AddAddress: new addresses land in NEW."""
        if not addr.node_id or addr.node_id == self.our_id:
            return False
        existing = self._addrs.get(addr.node_id)
        if existing is not None:
            if existing.is_old:
                # ADDRESS-HIJACK DEFENSE: gossip must not move an address
                # we have successfully dialed — an attacker would redirect
                # the next dial to a host it controls. The tried record
                # wins; the rejection is counted.
                if (addr.host, addr.port) != (existing.host, existing.port):
                    if self.metrics is not None:
                        self.metrics.addrbook_overwrite_rejected.inc()
                return False
            # both NEW: refresh the routable address (a peer moved)
            existing.host, existing.port = addr.host, addr.port
            return False
        b = self.new_bucket_index(addr)
        bucket = self._new[b]
        if len(bucket) >= BUCKET_SIZE and not self._evict_from_new(b):
            return False  # bucket pinned full by protected entries
        bucket[addr.node_id] = addr
        self._addrs[addr.node_id] = addr
        self._bucket_of[addr.node_id] = b
        self._publish_sizes()
        return True

    def _evict_from_new(self, b: int) -> bool:
        """Bias-aware in-bucket eviction: drop the entry with the most
        failed attempts (oldest attempt breaks ties); protected entries
        are never evicted. Returns False when nothing was evictable."""
        victims = [a for a in self._new[b].values()
                   if a.node_id not in self._protected]
        if not victims:
            return False
        worst = max(victims, key=lambda a: (a.attempts, -a.last_attempt))
        self._drop(worst.node_id)
        return True

    def _drop(self, node_id: str) -> None:
        a = self._addrs.pop(node_id, None)
        b = self._bucket_of.pop(node_id, None)
        if a is None or b is None:
            return
        (self._old if a.is_old else self._new)[b].pop(node_id, None)

    # ----------------------------------------------------------- lifecycle

    def mark_protected(self, node_id: str) -> None:
        """Exempt a persistent/unconditional peer from eviction and
        expiry (the id need not be in the book yet)."""
        if node_id:
            self._protected.add(node_id)

    def is_protected(self, node_id: str) -> bool:
        return node_id in self._protected

    def mark_attempt(self, node_id: str) -> None:
        a = self._addrs.get(node_id)
        if a is None:
            return
        a.attempts += 1
        a.last_attempt = time.time()
        # a NEW address that keeps failing is noise an attacker can mint
        # for free — expire it (addrbook.go isBad)
        if (not a.is_old and a.attempts > MAX_NEW_FAILURES
                and node_id not in self._protected):
            self._drop(node_id)
            self._publish_sizes()

    def mark_good(self, node_id: str) -> None:
        """addrbook.go MarkGood: graduate to OLD, reset attempts."""
        a = self._addrs.get(node_id)
        if a is None:
            return
        a.attempts = 0
        a.last_success = time.time()
        if a.is_old:
            return
        ob = self.old_bucket_index(a)
        if len(self._old[ob]) >= BUCKET_SIZE:
            # make room: demote the old bucket's worst entry back to NEW
            # (addrbook.go moveToOld displaces a random OLD entry)
            demotable = [x for x in self._old[ob].values()
                         if x.node_id not in self._protected]
            if not demotable:
                return  # stays NEW; still marked successful
            worst = max(demotable,
                        key=lambda x: (x.attempts, -x.last_success))
            self._demote(worst)
            if len(self._old[ob]) >= BUCKET_SIZE:
                return
        nb = self._bucket_of.get(node_id)
        if nb is not None:
            self._new[nb].pop(node_id, None)
        a.is_old = True
        self._old[ob][node_id] = a
        self._bucket_of[node_id] = ob
        self._publish_sizes()

    def _demote(self, a: NetAddress) -> None:
        """OLD -> NEW (ban, or displaced by a graduation)."""
        ob = self._bucket_of.get(a.node_id)
        if ob is not None:
            self._old[ob].pop(a.node_id, None)
        a.is_old = False
        nb = self.new_bucket_index(a)
        if len(self._new[nb]) >= BUCKET_SIZE and not self._evict_from_new(nb):
            # nowhere to land: the entry leaves the book
            self._addrs.pop(a.node_id, None)
            self._bucket_of.pop(a.node_id, None)
            return
        self._new[nb][a.node_id] = a
        self._bucket_of[a.node_id] = nb

    def mark_bad(self, node_id: str, ban_seconds: float = 24 * 3600) -> None:
        a = self._addrs.get(node_id)
        if a is None:
            return
        a.banned_until = time.time() + ban_seconds
        if a.is_old:
            self._demote(a)
        self._publish_sizes()

    def remove(self, node_id: str) -> None:
        self._drop(node_id)
        self._publish_sizes()

    # ----------------------------------------------------------- selection

    def pick_address(self, new_bias_pct: int = 50) -> NetAddress | None:
        """addrbook.go:260 PickAddress: choose OLD vs NEW with the given
        bias, then walk to a random non-empty bucket and pick uniformly
        within it. Banned and backoff-suppressed addresses are skipped —
        the dial loop never re-picks a freshly failed address."""
        now = time.time()
        pick_new = self._rng.randrange(100) < new_bias_pct
        for want_old in (not pick_new, pick_new):
            found = self._bucket_walk(old=want_old, now=now)
            if found is not None:
                return found
        return None

    def _bucket_walk(self, old: bool, now: float) -> NetAddress | None:
        buckets = self._old if old else self._new
        count = len(buckets)
        start = self._rng.randrange(count)
        for i in range(count):
            bucket = buckets[(start + i) % count]
            if not bucket:
                continue
            usable = [a for a in bucket.values() if a.dial_eligible(now)]
            if usable:
                return self._rng.choice(usable)
        return None

    def selection(self) -> list[NetAddress]:
        """addrbook.go:315 GetSelection: a random ~23% sample (capped) for
        answering a PEX request — collected by a shuffled bucket walk."""
        now = time.time()
        usable = ([a for b in self._new for a in b.values()
                   if not a.is_banned(now)]
                  + [a for b in self._old for a in b.values()
                     if not a.is_banned(now)])
        if not usable:
            return []
        n = min(self.MAX_SELECTION,
                max(1, len(usable) * self.SELECT_PCT // 100))
        return self._rng.sample(usable, min(n, len(usable)))

    def is_empty(self) -> bool:
        return not self._addrs

    def has(self, node_id: str) -> bool:
        return node_id in self._addrs

    def size(self) -> int:
        return len(self._addrs)

    # ---------------------------------------------------------- telemetry

    def _publish_sizes(self) -> None:
        if self.metrics is None:
            return
        new = sum(1 for a in self._addrs.values() if not a.is_old)
        self.metrics.addrbook_size.labels("new").set(new)
        self.metrics.addrbook_size.labels("old").set(len(self._addrs) - new)

    def stats(self) -> dict:
        """The discovery-plane rollup (net_telemetry's `discovery`
        section, bench --discovery, the eclipse tests): sizes, bucket
        occupancy, and the per-source-group NEW share — the number the
        hashed geometry bounds."""
        new_total, old_total = 0, 0
        by_src_group: dict[str, int] = {}
        src_group_buckets: dict[str, set[int]] = {}
        for b, bucket in enumerate(self._new):
            new_total += len(bucket)
            for a in bucket.values():
                g = a.src_group
                by_src_group[g] = by_src_group.get(g, 0) + 1
                src_group_buckets.setdefault(g, set()).add(b)
        for bucket in self._old:
            old_total += len(bucket)
        new_capacity = NEW_BUCKET_COUNT * BUCKET_SIZE
        worst_group = max(by_src_group, key=by_src_group.get) \
            if by_src_group else None
        return {
            "size": len(self._addrs),
            "new": new_total,
            "old": old_total,
            "protected": len(self._protected),
            "new_buckets_nonempty": sum(1 for b in self._new if b),
            "old_buckets_nonempty": sum(1 for b in self._old if b),
            "new_by_src_group": by_src_group,
            "new_buckets_by_src_group": {
                g: len(s) for g, s in src_group_buckets.items()},
            "worst_src_group": worst_group,
            # the eclipse headline: the largest single-source-group claim
            # on the NEW bucket space, vs. the geometric ceiling
            "max_src_group_occupancy_pct": round(
                100.0 * max(by_src_group.values()) / new_capacity, 3)
            if by_src_group else 0.0,
            "src_group_occupancy_bound_pct": round(
                100.0 * NEW_BUCKETS_PER_GROUP * BUCKET_SIZE
                / new_capacity, 3),
            "quarantined": bool(self.quarantined_path),
        }

    # ---------------------------------------------------------- persistence

    def save(self) -> None:
        if not self.file_path:
            return
        doc = {
            "nonce": self._nonce,
            "addrs": [
                {"id": a.node_id, "host": a.host, "port": a.port,
                 "src": a.src_id, "src_host": a.src_host,
                 "attempts": a.attempts,
                 "last_success": a.last_success, "old": a.is_old,
                 "banned_until": a.banned_until}
                for a in self._addrs.values()
            ],
        }
        os.makedirs(os.path.dirname(self.file_path) or ".", exist_ok=True)
        from cometbft_tpu.libs.diskio import atomic_write_durable

        atomic_write_durable(self.file_path,
                             json.dumps(doc).encode(),
                             site="addrbook.save")

    def _load(self) -> None:
        try:
            with open(self.file_path) as f:
                doc = json.load(f)
            if isinstance(doc, dict):
                nonce = doc.get("nonce", "")
                if nonce:
                    self._nonce = str(nonce)
                entries = doc.get("addrs", [])
            else:
                entries = doc  # pre-geometry flat format (a list)
            for d in entries:
                a = NetAddress(
                    node_id=d["id"], host=d["host"], port=int(d["port"]),
                    src_id=d.get("src", ""),
                    src_host=d.get("src_host", ""),
                    attempts=int(d.get("attempts", 0)),
                    last_success=float(d.get("last_success", 0.0)),
                    banned_until=float(d.get("banned_until", 0.0)),
                )
                was_old = bool(d.get("old", False))
                if self.add_address(a) and was_old:
                    self.mark_good(a.node_id)
                    rec = self._addrs.get(a.node_id)
                    if rec is not None:
                        # mark_good stamps now; restore the saved truth
                        rec.last_success = a.last_success
                        rec.attempts = int(d.get("attempts", 0))
        except Exception as e:  # noqa: BLE001 - a corrupt book must not
            # brick the boot: quarantine the file and start empty
            self._addrs.clear()
            self._bucket_of.clear()
            self._new = [{} for _ in range(NEW_BUCKET_COUNT)]
            self._old = [{} for _ in range(OLD_BUCKET_COUNT)]
            self.load_error = str(e)
            quarantine = self.file_path + ".corrupt"
            try:
                os.replace(self.file_path, quarantine)
                self.quarantined_path = quarantine
            except OSError:
                pass
            if self.metrics is not None:
                self.metrics.addrbook_quarantined.inc()
