"""Byzantine PEX harness: drive the REAL discovery stack adversarially.

The discovery-plane sibling of consensus/byzantine.py — one adversary,
many identities, one netblock, attacking a victim's address book over the
same encrypted wire honest PEX uses. Behaviors:

  sybil-flood    the eclipse precursor: the adversary mints N node
                 identities (NodeKeys are free), parks them all behind
                 ONE /16 (in-process that is loopback — exactly the
                 shape of a single hosting-provider swarm), connects
                 each to the victim, and answers every PexAddrs request
                 with bursts of FORGED addresses claiming another /16 it
                 controls. Success for the defense means the book's
                 hashed-bucket geometry confines every claim to the
                 source group's NEW_BUCKETS_PER_GROUP buckets, the
                 victim keeps >= 1 honest outbound peer (protected
                 persistent entries are never evicted), and consensus
                 keeps committing.

Each sybil identity is a full production endpoint (NodeKey, Transport,
Switch, encrypted mconn) whose ONLY reactor is the flood responder — the
victim cannot tell it from an honest peer until it answers a request.
`flood_book` is the socket-free variant of the same intake path for
geometry tests and bench --discovery, where booting 32 transports would
cost seconds for no extra coverage.
"""

from __future__ import annotations

import hashlib

from cometbft_tpu.libs import log as cmtlog
from cometbft_tpu.p2p.base_reactor import Envelope, Reactor
from cometbft_tpu.p2p.conn.connection import ChannelDescriptor
from cometbft_tpu.p2p.key import NodeKey
from cometbft_tpu.p2p.node_info import NodeInfo
from cometbft_tpu.p2p.pex import reactor as pexmod
from cometbft_tpu.p2p.pex.addrbook import AddrBook, NetAddress
from cometbft_tpu.p2p.switch import Switch
from cometbft_tpu.p2p.transport import Transport

PEX_BEHAVIORS = ("sybil-flood",)


def forged_claims(n: int, group: str = "10.66",
                  tag: str = "sybil") -> list[NetAddress]:
    """n deterministic forged addresses, all inside one claimed /16:
    node ids are hashes (indistinguishable from real ids), hosts walk
    the group's address space."""
    out = []
    for k in range(n):
        node_id = hashlib.sha256(f"{tag}:{k}".encode()).hexdigest()[:40]
        out.append(NetAddress(node_id=node_id,
                              host=f"{group}.{k // 200}.{k % 200 + 1}",
                              port=26656))
    return out


class _SybilPexReactor(Reactor):
    """The flood responder: answers every PexRequest with the next burst
    of forged claims (plus the swarm's own real listen addresses, so the
    victim keeps discovering more sybils — the swarm advertises itself).

    `mimic_channels` is the camouflage: a sybil that advertises ONLY the
    PEX channel dies the instant the victim's consensus reactor sends it
    a round-step message (unknown channel = wire error). A real attacker
    advertises whatever the victim speaks and silently drops it — so the
    harness registers the victim's channels as black holes."""

    def __init__(self, harness: "ByzantinePexHarness",
                 mimic_channels: bytes = b"",
                 logger: cmtlog.Logger | None = None):
        super().__init__("SybilPEX", logger)
        self.harness = harness
        self.mimic_channels = mimic_channels

    def get_channels(self) -> list[ChannelDescriptor]:
        chans = [ChannelDescriptor(id=pexmod.PEX_CHANNEL, priority=1,
                                   send_queue_capacity=10)]
        chans += [ChannelDescriptor(id=c, priority=1, send_queue_capacity=10)
                  for c in self.mimic_channels if c != pexmod.PEX_CHANNEL]
        return chans

    async def receive(self, e: Envelope) -> None:
        if e.channel_id != pexmod.PEX_CHANNEL:
            return  # camouflage traffic: swallowed, never answered
        try:
            kind, _ = pexmod.decode(e.message)
        except Exception:  # noqa: BLE001 - an adversary ignores bad input
            return
        if kind != "request":
            return  # the adversary has no use for the victim's addrs
        burst = self.harness.next_burst()
        self.harness.floods_sent += 1
        self.harness.addrs_claimed += len(burst)
        await e.src.send(pexmod.PEX_CHANNEL, pexmod.encode_addrs(burst))


class ByzantinePexHarness:
    """One adversary, `n_identities` NodeKeys, one /16 (the shared source
    host every sybil connects from), flooding forged PexAddrs at a
    victim. start() boots the swarm's endpoints, dial_victim() connects
    every identity (the victim's book learns each sybil's REAL listen
    address from the inbound self-report — that is the hook that later
    makes the victim dial into the swarm and ask it for addresses)."""

    def __init__(self, network: str, n_identities: int = 32,
                 claim_group: str = "10.66", claims_per_reply: int = 100,
                 total_claims: int = 4096, mimic_channels: bytes = b"",
                 logger: cmtlog.Logger | None = None):
        if n_identities < 1:
            raise ValueError("a sybil swarm needs at least one identity")
        self.network = network
        self.n_identities = n_identities
        self.claim_group = claim_group
        self.claims_per_reply = claims_per_reply
        self.mimic_channels = mimic_channels
        self.logger = logger or cmtlog.nop()
        self._claims = forged_claims(total_claims, group=claim_group)
        self._next = 0
        # the swarm: (node_key, transport, switch), one per identity
        self.identities: list[tuple[NodeKey, Transport, Switch]] = []
        self.listen_addrs: list[str] = []
        # counters (harness idiom: every adversarial act is counted)
        self.connects = 0
        self.floods_sent = 0
        self.addrs_claimed = 0

    # ------------------------------------------------------------- swarm

    async def start(self) -> None:
        from cometbft_tpu.crypto import ed25519

        for i in range(self.n_identities):
            nk = NodeKey(ed25519.gen_priv_key())
            info = NodeInfo(node_id=nk.id(), network=self.network,
                            version="dev", moniker=f"sybil-{i}",
                            channels=bytes([pexmod.PEX_CHANNEL]))
            transport = Transport(nk, info, logger=cmtlog.nop())
            switch = Switch(transport, logger=cmtlog.nop())
            switch.add_reactor(
                "PEX", _SybilPexReactor(self, self.mimic_channels))
            addr = await transport.listen("127.0.0.1:0")
            info.listen_addr = addr
            await switch.start()
            self.identities.append((nk, transport, switch))
            self.listen_addrs.append(f"{nk.id()}@{addr}")

    async def dial_victim(self, victim_addr: str) -> int:
        """Connect every identity to the victim (inbound there); returns
        how many connects succeeded."""
        ok = 0
        for _, _, switch in self.identities:
            if await switch.dial_peer(victim_addr):
                ok += 1
        self.connects += ok
        return ok

    async def stop(self) -> None:
        for _, _, switch in self.identities:
            try:
                await switch.stop()
            except Exception:  # noqa: BLE001
                pass
        self.identities.clear()

    def next_burst(self) -> list[NetAddress]:
        """The next claims_per_reply forged claims (wrapping), salted
        with the swarm's own real listen addresses."""
        burst = []
        for _ in range(self.claims_per_reply):
            burst.append(self._claims[self._next % len(self._claims)])
            self._next += 1
        for s in self.listen_addrs[:10]:
            burst.append(NetAddress.parse(s))
        return burst

    def snapshot(self) -> dict:
        return {"identities": self.n_identities,
                "connects": self.connects,
                "floods_sent": self.floods_sent,
                "addrs_claimed": self.addrs_claimed}

    # -------------------------------------------------- socket-free path

    @staticmethod
    def flood_book(book: AddrBook, n_identities: int = 32,
                   claims_per_identity: int = 128,
                   src_group: str = "203.0",
                   claim_groups: int = 64) -> dict:
        """Drive the SAME book-intake path without sockets: n sybil
        identities, all sourced from one /16, each pushing a slab of
        forged claims — the geometry-bound measurement bench --discovery
        and the bucket-invariant tests ride. Claims are spread across
        `claim_groups` forged /16s: claims sharing (claimed group, source
        group) collapse into ONE bucket, so a single-/16 flood would only
        show 64 slots — diverse claims probe the flood's FULL allowance
        (NEW_BUCKETS_PER_GROUP buckets per source group). Returns the
        flood ledger."""
        total = n_identities * claims_per_identity
        per_group = max(1, total // max(1, claim_groups))
        claims: list[NetAddress] = []
        for j in range(max(1, claim_groups)):
            claims.extend(forged_claims(per_group, group=f"10.{j}",
                                        tag=f"sybil:{j}"))
        accepted = 0
        for i in range(n_identities):
            src_id = hashlib.sha256(f"src:{i}".encode()).hexdigest()[:40]
            src_host = f"{src_group}.{i // 200}.{i % 200 + 1}"
            for a in claims[i * claims_per_identity:
                            (i + 1) * claims_per_identity]:
                rec = NetAddress(node_id=a.node_id, host=a.host,
                                 port=a.port, src_id=src_id,
                                 src_host=src_host)
                if book.add_address(rec):
                    accepted += 1
        return {"identities": n_identities,
                "claimed": total,
                "accepted": accepted,
                "src_group": src_group}


def make_pex_byzantine(behavior: str, network: str,
                       **kwargs) -> ByzantinePexHarness:
    """Factory mirroring consensus.byzantine.make_byzantine: behavior
    name -> armed harness."""
    if behavior not in PEX_BEHAVIORS:
        raise ValueError(f"unknown pex behavior {behavior!r} "
                         f"(behaviors: {PEX_BEHAVIORS})")
    return ByzantinePexHarness(network, **kwargs)
