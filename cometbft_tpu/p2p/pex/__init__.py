"""Peer exchange (reference: p2p/pex/)."""

from cometbft_tpu.p2p.pex.addrbook import AddrBook, NetAddress, group16
from cometbft_tpu.p2p.pex.byzantine import ByzantinePexHarness
from cometbft_tpu.p2p.pex.reactor import PEXReactor

__all__ = ["AddrBook", "ByzantinePexHarness", "NetAddress", "PEXReactor",
           "group16"]
