"""Peer exchange (reference: p2p/pex/)."""

from cometbft_tpu.p2p.pex.addrbook import AddrBook, NetAddress
from cometbft_tpu.p2p.pex.reactor import PEXReactor

__all__ = ["AddrBook", "NetAddress", "PEXReactor"]
