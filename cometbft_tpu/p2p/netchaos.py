"""Network-fault injection registry (the transport analog of libs/chaos.py).

chaos.py breaks the DEVICE at named call sites; netchaos breaks the WIRE.
One process-global registry (the network plane, like the device plane, is a
process-global resource) drives two fault families:

  link faults   latency / jitter / drop / duplication / reordering /
                bandwidth caps, applied by a ChaosConn wrapped around every
                peer connection between the MConnection and the
                SecretConnection — frames are already encrypted plaintext
                packets at that seam, so a duplicated or reordered write is
                a duplicated or reordered packet batch on the wire, exactly
                what a lossy network delivers;
  partitions    a partition map keyed by node id: a write across a group
                boundary errors the connection (the RST/timeout a real
                partitioned route eventually produces — silently eating
                bytes would violate the delivered-or-dead contract the
                gossip bookkeeping relies on), and new dials/accepts
                across the boundary are refused until the map is cleared.
                Directed single-link blocks (`block_link`) express
                asymmetric partitions.

Arming, via env (`CBFT_NET_CHAOS`), config (`p2p.chaos`), `arm_spec()`, or
the `unsafe_net_chaos` RPC control route:

  CBFT_NET_CHAOS="latency=0.05,drop=0.01,dup=0.02,reorder=0.05,bandwidth=65536"
  CBFT_NET_CHAOS="partition=<idA>.<idB>|<idC>.<idD>"

Link profiles (the fleet-topology dimension): instead of one global link
config, NAMED profiles apply per region pair — the regional testnets'
"intra-region fast, cross-region high-latency/lossy" shape:

  CBFT_NET_CHAOS="profile.wan=latency:0.04;jitter:0.02;drop:0.005,
                  region=<idA>:r0,region=<idB>:r1,link.r0-r1=wan"

  profile.<name>=k:v;k:v   define a profile (keys = the link-fault keys)
  region=<node_id>:<name>  assign a node to a region (repeatable)
  link.<rA>-<rB>=<name>    profile for traffic between two regions
                           (unordered; rA == rB for intra-region links)
  link.default=<name>      profile for any region pair not mapped above

A write resolves its profile from (local region, remote region); links
with no profile (or nodes with no region) fall back to the global link
config. Profiles compose with partitions unchanged.

`partition=` groups are separated by `|`, members by `.`; node ids are hex
so neither collides. Probabilistic faults use a seeded RNG per connection
(`seed=` in the spec), so a fault schedule replays deterministically like a
fuzz seed. Partition healing is observable: `clear_partition()` starts a
clock that stops at the first write crossing a formerly-blocked link, and
the elapsed seconds land on the process-global
`cometbft_p2p_partition_heal_seconds` gauge (libs/metrics.NetChaosMetrics).

Partition enforcement is write-side: each node's own wrapper drops its own
outbound bytes. In-process nets share this registry so one `set_partition`
cuts every direction at once; OS-process nets must arm the map on every
node that should stop transmitting (the e2e runner arms all of them).
"""

from __future__ import annotations

import asyncio
import os
import random
import threading
import time

_ENV = "CBFT_NET_CHAOS"

# spec keys that arm link faults (all floats except bandwidth/seed)
_LINK_KEYS = ("latency", "jitter", "drop", "dup", "reorder", "bandwidth", "seed")


class NetChaosConfig:
    """Link-fault knobs; all zero means the wire is clean."""

    __slots__ = ("latency", "jitter", "drop", "dup", "reorder", "bandwidth",
                 "seed")

    def __init__(self, latency: float = 0.0, jitter: float = 0.0,
                 drop: float = 0.0, dup: float = 0.0, reorder: float = 0.0,
                 bandwidth: int = 0, seed: int = 0):
        self.latency = latency
        self.jitter = jitter
        self.drop = drop
        self.dup = dup
        self.reorder = reorder
        self.bandwidth = bandwidth
        self.seed = seed

    def any_active(self) -> bool:
        return bool(self.latency or self.jitter or self.drop or self.dup
                    or self.reorder or self.bandwidth)


_lock = threading.Lock()
_cfg: NetChaosConfig | None = None
_groups: dict[str, str] = {}          # node_id -> partition group label
_blocked_links: set[tuple[str, str]] = set()  # directed (src, dst) blocks
# link-profile plane (fleet topologies): named configs + region wiring
_profiles: dict[str, NetChaosConfig] = {}     # profile name -> config
_regions: dict[str, str] = {}                 # node_id -> region name
_region_links: dict[tuple[str, str], str] = {}  # sorted (rA, rB) -> profile
_default_link_profile: str | None = None
_env_loaded = False
# heal observability: set when a partition is cleared, consumed by the first
# write that crosses a formerly-blocked link
_heal_pending = False
_heal_t0 = 0.0
_heal_links: set[tuple[str, str]] = set()
_last_heal_seconds: float | None = None
_stats = {"blocked_writes": 0, "dropped": 0, "duplicated": 0,
          "reordered": 0, "delayed": 0, "blocked_dials": 0}
# fast path: True only while some fault is armed (checked lock-free per write)
_active = False


class ParsedSpec:
    """The parsed form of one CBFT_NET_CHAOS schedule. Attribute access
    only (the old 3-tuple unpack shape predates link profiles)."""

    __slots__ = ("cfg", "groups", "blocks", "profiles", "regions", "links",
                 "default_link")

    def __init__(self):
        self.cfg: NetChaosConfig | None = None
        self.groups: dict[str, str] = {}
        self.blocks: set[tuple[str, str]] = set()
        self.profiles: dict[str, NetChaosConfig] = {}
        self.regions: dict[str, str] = {}
        self.links: dict[tuple[str, str], str] = {}
        self.default_link: str | None = None


def _parse_link_kwargs(value: str, part: str, sep_pair: str = "=") -> dict:
    """Parse link-fault key/value pairs; `value` is `k{sep}v` joined by
    `,` (top level) or `;` (inside a profile definition)."""
    kwargs: dict[str, float | int] = {}
    items = value.split(";") if sep_pair == ":" else [value]
    for item in items:
        key, sep, val = item.partition(sep_pair)
        key, val = key.strip(), val.strip()
        if not sep or not val or key not in _LINK_KEYS:
            raise ValueError(f"bad net-chaos link fault {item!r} in {part!r} "
                             f"(keys: {_LINK_KEYS})")
        try:
            kwargs[key] = (int(val) if key in ("bandwidth", "seed")
                           else float(val))
        except ValueError:
            raise ValueError(
                f"bad net-chaos value {val!r} in {part!r}") from None
        if kwargs[key] < 0:
            raise ValueError(f"negative net-chaos value in {part!r}")
    return kwargs


def parse_spec(spec: str) -> ParsedSpec:
    """Parse a CBFT_NET_CHAOS schedule (link config, partition groups,
    directed blocks, link profiles, region map, region-pair links),
    raising ValueError on any malformed part — config validation uses
    this so a typo'd schedule fails at boot."""
    out = ParsedSpec()
    cfg_kwargs: dict[str, float | int] = {}
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        key, sep, value = part.partition("=")
        key, value = key.strip(), value.strip()
        if not sep or not value:
            raise ValueError(f"malformed net-chaos part {part!r}")
        if key == "partition":
            for gi, group in enumerate(value.split("|")):
                members = [m for m in group.split(".") if m]
                if not members:
                    raise ValueError(f"empty partition group in {part!r}")
                for m in members:
                    out.groups[m] = f"g{gi}"
        elif key == "block":
            src, sep2, dst = value.partition(">")
            if not sep2 or not src or not dst:
                raise ValueError(f"malformed directed block {part!r} "
                                 "(want block=src>dst)")
            out.blocks.add((src, dst))
        elif key.startswith("profile."):
            name = key[len("profile."):]
            if not name:
                raise ValueError(f"empty profile name in {part!r}")
            out.profiles[name] = NetChaosConfig(
                **_parse_link_kwargs(value, part, sep_pair=":"))
        elif key == "region":
            node_id, sep2, region = value.partition(":")
            if not sep2 or not node_id or not region:
                raise ValueError(f"malformed region assignment {part!r} "
                                 "(want region=<node_id>:<region>)")
            out.regions[node_id] = region
        elif key.startswith("link."):
            pair = key[len("link."):]
            if pair == "default":
                out.default_link = value
            else:
                ra, sep2, rb = pair.partition("-")
                if not sep2 or not ra or not rb:
                    raise ValueError(f"malformed link key {part!r} "
                                     "(want link.<rA>-<rB>=<profile>)")
                out.links[tuple(sorted((ra, rb)))] = value
        elif key in _LINK_KEYS:
            cfg_kwargs.update(_parse_link_kwargs(part, part))
        else:
            raise ValueError(
                f"unknown net-chaos key {key!r} (keys: "
                f"{_LINK_KEYS + ('partition', 'block', 'region', 'profile.<name>', 'link.<rA>-<rB>')})")
    if cfg_kwargs:
        out.cfg = NetChaosConfig(**cfg_kwargs)
    # a link mapping naming an undefined profile is a boot-time error,
    # not a silent clean wire at fault time
    for pair, name in list(out.links.items()) + (
            [(("default", "default"), out.default_link)]
            if out.default_link else []):
        if name not in out.profiles:
            raise ValueError(f"link {pair} names unknown profile {name!r}")
    return out


def _recompute_active_locked() -> None:
    global _active
    _active = bool((_cfg is not None and _cfg.any_active()) or _groups
                   or _blocked_links or _heal_pending
                   or (_region_links or _default_link_profile))


def _load_env_locked() -> None:
    global _env_loaded
    if _env_loaded:
        return
    _env_loaded = True
    spec = os.environ.get(_ENV, "")
    if not spec:
        return
    try:
        _arm_spec_locked(spec)
    except ValueError as e:
        # same floor as libs/chaos: a malformed env schedule surfacing as a
        # phantom network fault inside a send routine would be undebuggable
        from cometbft_tpu.libs import log as _log

        _log.default().error(
            "ignoring malformed CBFT_NET_CHAOS schedule", spec=spec, err=str(e))


def _arm_spec_locked(spec: str) -> None:
    global _cfg, _default_link_profile
    parsed = parse_spec(spec)
    if parsed.cfg is not None:
        _cfg = parsed.cfg
    if parsed.groups:
        _set_partition_locked(parsed.groups)
    for link in parsed.blocks:
        _blocked_links.add(link)
    _profiles.update(parsed.profiles)
    _regions.update(parsed.regions)
    _region_links.update(parsed.links)
    if parsed.default_link is not None:
        _default_link_profile = parsed.default_link
    _recompute_active_locked()


def arm(cfg: NetChaosConfig) -> None:
    global _cfg
    with _lock:
        _load_env_locked()
        _cfg = cfg
        _recompute_active_locked()


def arm_spec(spec: str) -> None:
    with _lock:
        _load_env_locked()
        _arm_spec_locked(spec)


def disarm() -> None:
    """Drop the link-fault config; partitions stay (clear_partition heals)."""
    global _cfg
    with _lock:
        _cfg = None
        _recompute_active_locked()


def reset() -> None:
    """Back to a clean wire; forgets the env schedule (tests re-arm)."""
    global _cfg, _env_loaded, _heal_pending, _last_heal_seconds
    global _default_link_profile
    with _lock:
        _cfg = None
        _groups.clear()
        _blocked_links.clear()
        _profiles.clear()
        _regions.clear()
        _region_links.clear()
        _default_link_profile = None
        _heal_pending = False
        _heal_links.clear()
        _last_heal_seconds = None
        _env_loaded = True
        for k in _stats:
            _stats[k] = 0
        _recompute_active_locked()


# ------------------------------------------------------------- partitions


def _set_partition_locked(groups: dict[str, str]) -> None:
    global _heal_pending
    _groups.clear()
    _groups.update({k: str(v) for k, v in groups.items()})
    _heal_pending = False
    _heal_links.clear()


def set_partition(groups: dict[str, str]) -> None:
    """Install a partition map: node_id -> group label. Two known ids in
    different groups cannot exchange traffic; an id absent from the map is
    unrestricted (so a map only needs the nodes it isolates)."""
    with _lock:
        _load_env_locked()
        _set_partition_locked(groups)
        _recompute_active_locked()


def block_link(src: str, dst: str) -> None:
    """Asymmetric partition primitive: src's messages never reach dst."""
    with _lock:
        _load_env_locked()
        _blocked_links.add((src, dst))
        _recompute_active_locked()


def unblock_link(src: str, dst: str) -> None:
    with _lock:
        _blocked_links.discard((src, dst))
        _recompute_active_locked()


def clear_partition() -> None:
    """Heal: drop the partition map and directed blocks, and start the
    heal clock — stopped by the first write across a formerly-cut link."""
    global _heal_pending, _heal_t0
    with _lock:
        cut: set[tuple[str, str]] = set(_blocked_links)
        ids = list(_groups)
        for a in ids:
            for b in ids:
                if a != b and _groups[a] != _groups[b]:
                    cut.add((a, b))
        _groups.clear()
        _blocked_links.clear()
        if cut:
            _heal_pending = True
            _heal_t0 = time.monotonic()
            _heal_links.clear()
            _heal_links.update(cut)
        _recompute_active_locked()


def link_config(src: str, dst: str) -> NetChaosConfig | None:
    """The link-fault config governing traffic src -> dst: a region-pair
    profile when both nodes have regions and the pair (or the default
    link) is mapped, else the global config. None = clean wire."""
    if _region_links or _default_link_profile:
        with _lock:
            ra, rb = _regions.get(src), _regions.get(dst)
            if ra is not None and rb is not None:
                name = _region_links.get(tuple(sorted((ra, rb))),
                                         _default_link_profile)
                if name is not None:
                    prof = _profiles.get(name)
                    if prof is not None:
                        return prof
    return _cfg


def region_of(node_id: str) -> str | None:
    with _lock:
        return _regions.get(node_id)


def link_blocked(src: str, dst: str) -> bool:
    """True when traffic src -> dst is cut (directed block or group split)."""
    if not _active:
        if _env_loaded:
            return False
        # a node armed ONLY via CBFT_NET_CHAOS must enforce the partition
        # on its very first boot-time dial, before any conn was wrapped
        with _lock:
            _load_env_locked()
        if not _active:
            return False
    with _lock:
        if (src, dst) in _blocked_links:
            return True
        ga, gb = _groups.get(src), _groups.get(dst)
        return ga is not None and gb is not None and ga != gb


def dial_blocked(a: str, b: str) -> bool:
    """A dial needs both directions; blocked if either is cut."""
    return link_blocked(a, b) or link_blocked(b, a)


def _note_delivery(src: str, dst: str) -> None:
    """Called on every non-blocked write while a heal is pending; the first
    one across a formerly-cut link records partition_heal_seconds."""
    global _heal_pending, _last_heal_seconds
    with _lock:
        if not _heal_pending or (src, dst) not in _heal_links:
            return
        _heal_pending = False
        _heal_links.clear()
        _last_heal_seconds = time.monotonic() - _heal_t0
        _recompute_active_locked()
        secs = _last_heal_seconds
    from cometbft_tpu.libs import metrics as cmtmetrics

    cmtmetrics.netchaos_metrics().partition_heal_seconds.set(secs)


def last_heal_seconds() -> float | None:
    with _lock:
        return _last_heal_seconds


def snapshot() -> dict:
    """Armed faults + fire counts (surfaced by the unsafe_net_chaos route)."""
    with _lock:
        _load_env_locked()
        cfg = None
        if _cfg is not None:
            cfg = {k: getattr(_cfg, k) for k in _LINK_KEYS}
        return {
            "config": cfg,
            "partition": dict(_groups),
            "blocked_links": sorted(f"{a}>{b}" for a, b in _blocked_links),
            "profiles": {
                name: {k: getattr(p, k) for k in _LINK_KEYS}
                for name, p in _profiles.items()},
            "regions": dict(_regions),
            "region_links": {f"{a}-{b}": name
                             for (a, b), name in _region_links.items()},
            "default_link_profile": _default_link_profile,
            "heal_pending": _heal_pending,
            "last_heal_seconds": _last_heal_seconds,
            "stats": dict(_stats),
        }


def _count(kind: str) -> None:
    with _lock:
        _stats[kind] += 1
    from cometbft_tpu.libs import metrics as cmtmetrics

    cmtmetrics.netchaos_metrics().net_faults.labels(kind).inc()


# ------------------------------------------------------------ conn wrapper


class ChaosConn:
    """Wraps a SecretConnection between the MConnection and the socket.
    Reads the registry on every write, so faults armed mid-connection (the
    runtime partition route) apply to live conns. A held reordered frame is
    flushed by the next write; if the conn goes quiet first the frame is
    lost — indistinguishable from a drop, which is the point."""

    __slots__ = ("_conn", "local_id", "remote_id", "_rng", "_held")

    def __init__(self, conn, local_id: str, remote_id: str):
        self._conn = conn
        self.local_id = local_id
        self.remote_id = remote_id
        self._rng: random.Random | None = None
        self._held: bytes | None = None

    def _link_rng(self, seed: int) -> random.Random:
        if self._rng is None:
            if seed:
                # per-link deterministic stream (hashlib, not hash(): str
                # hashing is salted per process): the same seed + id pair
                # replays the same fault schedule
                import hashlib

                digest = hashlib.sha256(
                    f"{seed}|{self.local_id}|{self.remote_id}".encode()
                ).digest()
                self._rng = random.Random(int.from_bytes(digest[:8], "big"))
            else:
                self._rng = random.Random()
        return self._rng

    async def write(self, data: bytes) -> None:
        if not _active:
            await self._conn.write(data)
            return
        if link_blocked(self.local_id, self.remote_id):
            # a partitioned route must KILL the conn, not silently eat
            # bytes: mconn/reactor bookkeeping assumes TCP's delivered-or-
            # dead contract (PeerState marks gossiped votes as delivered
            # at send time), so a silent black hole wedges gossip forever.
            # The error tears the peer down; redial is then refused at the
            # transport until the partition heals — the TCP-reset analog.
            _count("blocked_writes")
            raise ConnectionResetError(
                f"net chaos: partitioned from {self.remote_id[:10]}")
        cfg = link_config(self.local_id, self.remote_id)
        if cfg is not None and cfg.any_active():
            rng = self._link_rng(cfg.seed)
            if cfg.bandwidth:
                await asyncio.sleep(len(data) / cfg.bandwidth)
            if cfg.latency or cfg.jitter:
                _count("delayed")
                await asyncio.sleep(cfg.latency + cfg.jitter * rng.random())
            r = rng.random()
            if r < cfg.drop:
                _count("dropped")
                return
            if r < cfg.drop + cfg.dup:
                _count("duplicated")
                await self._conn.write(data)
            elif r < cfg.drop + cfg.dup + cfg.reorder and self._held is None:
                _count("reordered")
                self._held = data
                return
        held, self._held = self._held, None
        await self._conn.write(data)
        if held is not None:
            await self._conn.write(held)
        if _heal_pending:
            _note_delivery(self.local_id, self.remote_id)

    async def readexactly(self, n: int) -> bytes:
        return await self._conn.readexactly(n)

    def close(self) -> None:
        self._conn.close()

    def __getattr__(self, name):
        return getattr(self._conn, name)


def wrap(conn, local_id: str, remote_id: str) -> ChaosConn:
    """Wrap a peer connection; cheap when nothing is armed (one flag test
    per write). Always wrapped so faults armed later reach live conns."""
    with _lock:
        _load_env_locked()
    return ChaosConn(conn, local_id, remote_id)
