"""Network-fault injection registry (the transport analog of libs/chaos.py).

chaos.py breaks the DEVICE at named call sites; netchaos breaks the WIRE.
One process-global registry (the network plane, like the device plane, is a
process-global resource) drives two fault families:

  link faults   latency / jitter / drop / duplication / reordering /
                bandwidth caps, applied by a ChaosConn wrapped around every
                peer connection between the MConnection and the
                SecretConnection — frames are already encrypted plaintext
                packets at that seam, so a duplicated or reordered write is
                a duplicated or reordered packet batch on the wire, exactly
                what a lossy network delivers;
  partitions    a partition map keyed by node id: a write across a group
                boundary errors the connection (the RST/timeout a real
                partitioned route eventually produces — silently eating
                bytes would violate the delivered-or-dead contract the
                gossip bookkeeping relies on), and new dials/accepts
                across the boundary are refused until the map is cleared.
                Directed single-link blocks (`block_link`) express
                asymmetric partitions.

Arming, via env (`CBFT_NET_CHAOS`), config (`p2p.chaos`), `arm_spec()`, or
the `unsafe_net_chaos` RPC control route:

  CBFT_NET_CHAOS="latency=0.05,drop=0.01,dup=0.02,reorder=0.05,bandwidth=65536"
  CBFT_NET_CHAOS="partition=<idA>.<idB>|<idC>.<idD>"

`partition=` groups are separated by `|`, members by `.`; node ids are hex
so neither collides. Probabilistic faults use a seeded RNG per connection
(`seed=` in the spec), so a fault schedule replays deterministically like a
fuzz seed. Partition healing is observable: `clear_partition()` starts a
clock that stops at the first write crossing a formerly-blocked link, and
the elapsed seconds land on the process-global
`cometbft_p2p_partition_heal_seconds` gauge (libs/metrics.NetChaosMetrics).

Partition enforcement is write-side: each node's own wrapper drops its own
outbound bytes. In-process nets share this registry so one `set_partition`
cuts every direction at once; OS-process nets must arm the map on every
node that should stop transmitting (the e2e runner arms all of them).
"""

from __future__ import annotations

import asyncio
import os
import random
import threading
import time

_ENV = "CBFT_NET_CHAOS"

# spec keys that arm link faults (all floats except bandwidth/seed)
_LINK_KEYS = ("latency", "jitter", "drop", "dup", "reorder", "bandwidth", "seed")


class NetChaosConfig:
    """Link-fault knobs; all zero means the wire is clean."""

    __slots__ = ("latency", "jitter", "drop", "dup", "reorder", "bandwidth",
                 "seed")

    def __init__(self, latency: float = 0.0, jitter: float = 0.0,
                 drop: float = 0.0, dup: float = 0.0, reorder: float = 0.0,
                 bandwidth: int = 0, seed: int = 0):
        self.latency = latency
        self.jitter = jitter
        self.drop = drop
        self.dup = dup
        self.reorder = reorder
        self.bandwidth = bandwidth
        self.seed = seed

    def any_active(self) -> bool:
        return bool(self.latency or self.jitter or self.drop or self.dup
                    or self.reorder or self.bandwidth)


_lock = threading.Lock()
_cfg: NetChaosConfig | None = None
_groups: dict[str, str] = {}          # node_id -> partition group label
_blocked_links: set[tuple[str, str]] = set()  # directed (src, dst) blocks
_env_loaded = False
# heal observability: set when a partition is cleared, consumed by the first
# write that crosses a formerly-blocked link
_heal_pending = False
_heal_t0 = 0.0
_heal_links: set[tuple[str, str]] = set()
_last_heal_seconds: float | None = None
_stats = {"blocked_writes": 0, "dropped": 0, "duplicated": 0,
          "reordered": 0, "delayed": 0, "blocked_dials": 0}
# fast path: True only while some fault is armed (checked lock-free per write)
_active = False


def parse_spec(spec: str) -> tuple[NetChaosConfig | None, dict[str, str],
                                   set[tuple[str, str]]]:
    """Parse a CBFT_NET_CHAOS schedule into (link config, partition groups,
    directed blocks), raising ValueError on any malformed part — config
    validation uses this so a typo'd schedule fails at boot."""
    cfg_kwargs: dict[str, float | int] = {}
    groups: dict[str, str] = {}
    blocks: set[tuple[str, str]] = set()
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        key, sep, value = part.partition("=")
        key, value = key.strip(), value.strip()
        if not sep or not value:
            raise ValueError(f"malformed net-chaos part {part!r}")
        if key == "partition":
            for gi, group in enumerate(value.split("|")):
                members = [m for m in group.split(".") if m]
                if not members:
                    raise ValueError(f"empty partition group in {part!r}")
                for m in members:
                    groups[m] = f"g{gi}"
        elif key == "block":
            src, sep2, dst = value.partition(">")
            if not sep2 or not src or not dst:
                raise ValueError(f"malformed directed block {part!r} "
                                 "(want block=src>dst)")
            blocks.add((src, dst))
        elif key in _LINK_KEYS:
            try:
                cfg_kwargs[key] = (int(value) if key in ("bandwidth", "seed")
                                   else float(value))
            except ValueError:
                raise ValueError(
                    f"bad net-chaos value {value!r} in {part!r}") from None
            if cfg_kwargs[key] < 0:
                raise ValueError(f"negative net-chaos value in {part!r}")
        else:
            raise ValueError(
                f"unknown net-chaos key {key!r} (keys: "
                f"{_LINK_KEYS + ('partition', 'block')})")
    cfg = NetChaosConfig(**cfg_kwargs) if cfg_kwargs else None
    return cfg, groups, blocks


def _recompute_active_locked() -> None:
    global _active
    _active = bool((_cfg is not None and _cfg.any_active()) or _groups
                   or _blocked_links or _heal_pending)


def _load_env_locked() -> None:
    global _env_loaded
    if _env_loaded:
        return
    _env_loaded = True
    spec = os.environ.get(_ENV, "")
    if not spec:
        return
    try:
        _arm_spec_locked(spec)
    except ValueError as e:
        # same floor as libs/chaos: a malformed env schedule surfacing as a
        # phantom network fault inside a send routine would be undebuggable
        from cometbft_tpu.libs import log as _log

        _log.default().error(
            "ignoring malformed CBFT_NET_CHAOS schedule", spec=spec, err=str(e))


def _arm_spec_locked(spec: str) -> None:
    global _cfg
    cfg, groups, blocks = parse_spec(spec)
    if cfg is not None:
        _cfg = cfg
    if groups:
        _set_partition_locked(groups)
    for link in blocks:
        _blocked_links.add(link)
    _recompute_active_locked()


def arm(cfg: NetChaosConfig) -> None:
    global _cfg
    with _lock:
        _load_env_locked()
        _cfg = cfg
        _recompute_active_locked()


def arm_spec(spec: str) -> None:
    with _lock:
        _load_env_locked()
        _arm_spec_locked(spec)


def disarm() -> None:
    """Drop the link-fault config; partitions stay (clear_partition heals)."""
    global _cfg
    with _lock:
        _cfg = None
        _recompute_active_locked()


def reset() -> None:
    """Back to a clean wire; forgets the env schedule (tests re-arm)."""
    global _cfg, _env_loaded, _heal_pending, _last_heal_seconds
    with _lock:
        _cfg = None
        _groups.clear()
        _blocked_links.clear()
        _heal_pending = False
        _heal_links.clear()
        _last_heal_seconds = None
        _env_loaded = True
        for k in _stats:
            _stats[k] = 0
        _recompute_active_locked()


# ------------------------------------------------------------- partitions


def _set_partition_locked(groups: dict[str, str]) -> None:
    global _heal_pending
    _groups.clear()
    _groups.update({k: str(v) for k, v in groups.items()})
    _heal_pending = False
    _heal_links.clear()


def set_partition(groups: dict[str, str]) -> None:
    """Install a partition map: node_id -> group label. Two known ids in
    different groups cannot exchange traffic; an id absent from the map is
    unrestricted (so a map only needs the nodes it isolates)."""
    with _lock:
        _load_env_locked()
        _set_partition_locked(groups)
        _recompute_active_locked()


def block_link(src: str, dst: str) -> None:
    """Asymmetric partition primitive: src's messages never reach dst."""
    with _lock:
        _load_env_locked()
        _blocked_links.add((src, dst))
        _recompute_active_locked()


def unblock_link(src: str, dst: str) -> None:
    with _lock:
        _blocked_links.discard((src, dst))
        _recompute_active_locked()


def clear_partition() -> None:
    """Heal: drop the partition map and directed blocks, and start the
    heal clock — stopped by the first write across a formerly-cut link."""
    global _heal_pending, _heal_t0
    with _lock:
        cut: set[tuple[str, str]] = set(_blocked_links)
        ids = list(_groups)
        for a in ids:
            for b in ids:
                if a != b and _groups[a] != _groups[b]:
                    cut.add((a, b))
        _groups.clear()
        _blocked_links.clear()
        if cut:
            _heal_pending = True
            _heal_t0 = time.monotonic()
            _heal_links.clear()
            _heal_links.update(cut)
        _recompute_active_locked()


def link_blocked(src: str, dst: str) -> bool:
    """True when traffic src -> dst is cut (directed block or group split)."""
    if not _active:
        if _env_loaded:
            return False
        # a node armed ONLY via CBFT_NET_CHAOS must enforce the partition
        # on its very first boot-time dial, before any conn was wrapped
        with _lock:
            _load_env_locked()
        if not _active:
            return False
    with _lock:
        if (src, dst) in _blocked_links:
            return True
        ga, gb = _groups.get(src), _groups.get(dst)
        return ga is not None and gb is not None and ga != gb


def dial_blocked(a: str, b: str) -> bool:
    """A dial needs both directions; blocked if either is cut."""
    return link_blocked(a, b) or link_blocked(b, a)


def _note_delivery(src: str, dst: str) -> None:
    """Called on every non-blocked write while a heal is pending; the first
    one across a formerly-cut link records partition_heal_seconds."""
    global _heal_pending, _last_heal_seconds
    with _lock:
        if not _heal_pending or (src, dst) not in _heal_links:
            return
        _heal_pending = False
        _heal_links.clear()
        _last_heal_seconds = time.monotonic() - _heal_t0
        _recompute_active_locked()
        secs = _last_heal_seconds
    from cometbft_tpu.libs import metrics as cmtmetrics

    cmtmetrics.netchaos_metrics().partition_heal_seconds.set(secs)


def last_heal_seconds() -> float | None:
    with _lock:
        return _last_heal_seconds


def snapshot() -> dict:
    """Armed faults + fire counts (surfaced by the unsafe_net_chaos route)."""
    with _lock:
        _load_env_locked()
        cfg = None
        if _cfg is not None:
            cfg = {k: getattr(_cfg, k) for k in _LINK_KEYS}
        return {
            "config": cfg,
            "partition": dict(_groups),
            "blocked_links": sorted(f"{a}>{b}" for a, b in _blocked_links),
            "heal_pending": _heal_pending,
            "last_heal_seconds": _last_heal_seconds,
            "stats": dict(_stats),
        }


def _count(kind: str) -> None:
    with _lock:
        _stats[kind] += 1
    from cometbft_tpu.libs import metrics as cmtmetrics

    cmtmetrics.netchaos_metrics().net_faults.labels(kind).inc()


# ------------------------------------------------------------ conn wrapper


class ChaosConn:
    """Wraps a SecretConnection between the MConnection and the socket.
    Reads the registry on every write, so faults armed mid-connection (the
    runtime partition route) apply to live conns. A held reordered frame is
    flushed by the next write; if the conn goes quiet first the frame is
    lost — indistinguishable from a drop, which is the point."""

    __slots__ = ("_conn", "local_id", "remote_id", "_rng", "_held")

    def __init__(self, conn, local_id: str, remote_id: str):
        self._conn = conn
        self.local_id = local_id
        self.remote_id = remote_id
        self._rng: random.Random | None = None
        self._held: bytes | None = None

    def _link_rng(self, seed: int) -> random.Random:
        if self._rng is None:
            if seed:
                # per-link deterministic stream (hashlib, not hash(): str
                # hashing is salted per process): the same seed + id pair
                # replays the same fault schedule
                import hashlib

                digest = hashlib.sha256(
                    f"{seed}|{self.local_id}|{self.remote_id}".encode()
                ).digest()
                self._rng = random.Random(int.from_bytes(digest[:8], "big"))
            else:
                self._rng = random.Random()
        return self._rng

    async def write(self, data: bytes) -> None:
        if not _active:
            await self._conn.write(data)
            return
        if link_blocked(self.local_id, self.remote_id):
            # a partitioned route must KILL the conn, not silently eat
            # bytes: mconn/reactor bookkeeping assumes TCP's delivered-or-
            # dead contract (PeerState marks gossiped votes as delivered
            # at send time), so a silent black hole wedges gossip forever.
            # The error tears the peer down; redial is then refused at the
            # transport until the partition heals — the TCP-reset analog.
            _count("blocked_writes")
            raise ConnectionResetError(
                f"net chaos: partitioned from {self.remote_id[:10]}")
        cfg = _cfg
        if cfg is not None and cfg.any_active():
            rng = self._link_rng(cfg.seed)
            if cfg.bandwidth:
                await asyncio.sleep(len(data) / cfg.bandwidth)
            if cfg.latency or cfg.jitter:
                _count("delayed")
                await asyncio.sleep(cfg.latency + cfg.jitter * rng.random())
            r = rng.random()
            if r < cfg.drop:
                _count("dropped")
                return
            if r < cfg.drop + cfg.dup:
                _count("duplicated")
                await self._conn.write(data)
            elif r < cfg.drop + cfg.dup + cfg.reorder and self._held is None:
                _count("reordered")
                self._held = data
                return
        held, self._held = self._held, None
        await self._conn.write(data)
        if held is not None:
            await self._conn.write(held)
        if _heal_pending:
            _note_delivery(self.local_id, self.remote_id)

    async def readexactly(self, n: int) -> bytes:
        return await self._conn.readexactly(n)

    def close(self) -> None:
        self._conn.close()

    def __getattr__(self, name):
        return getattr(self._conn, name)


def wrap(conn, local_id: str, remote_id: str) -> ChaosConn:
    """Wrap a peer connection; cheap when nothing is armed (one flag test
    per write). Always wrapped so faults armed later reach live conns."""
    with _lock:
        _load_env_locked()
    return ChaosConn(conn, local_id, remote_id)
