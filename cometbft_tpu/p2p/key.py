"""Node identity key.

Reference: p2p/key.go — every node has a persistent ed25519 keypair; the
node ID is the hex-encoded address (first 20 bytes of SHA-256 of the raw
public key), giving an authenticated identity the SecretConnection
handshake proves possession of.
"""

from __future__ import annotations

import json
import os

from cometbft_tpu.crypto import ed25519


def node_id_from_pubkey(pub: ed25519.PubKey) -> str:
    """p2p/key.go:45 PubKeyToID: hex(address)."""
    return pub.address().hex()


class NodeKey:
    def __init__(self, priv_key: ed25519.PrivKey):
        self.priv_key = priv_key

    @property
    def pub_key(self) -> ed25519.PubKey:
        return self.priv_key.pub_key()

    def id(self) -> str:
        return node_id_from_pubkey(self.pub_key)

    # ------------------------------------------------------------ persist

    def save(self, path: str) -> None:
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        doc = {
            "priv_key": {
                "type": "tendermint/PrivKeyEd25519",
                "value": self.priv_key.bytes_().hex(),
            }
        }
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(doc, f, indent=2)
        os.replace(tmp, path)

    @classmethod
    def load(cls, path: str) -> "NodeKey":
        with open(path) as f:
            doc = json.load(f)
        return cls(ed25519.PrivKey(bytes.fromhex(doc["priv_key"]["value"])))

    @classmethod
    def load_or_gen(cls, path: str) -> "NodeKey":
        """p2p/key.go:75 LoadOrGenNodeKey."""
        if os.path.exists(path):
            return cls.load(path)
        nk = cls(ed25519.gen_priv_key())
        nk.save(path)
        return nk
