"""Switch: owns peers and reactors, routes messages between them.

Reference: p2p/switch.go:72 — reactors register channel descriptors; the
switch accepts/dials connections, wraps them in Peers, and dispatches every
received message to the reactor owning that channel. Persistent peers are
redialed with exponential backoff (switch.go:398 reconnectToPeer);
StopPeerForError tears a peer down and triggers the redial.

Misbehavior scoring (framework extension; the reference only disconnects):
every stop-for-error and every reactor-reported offense (invalid vote
signatures, pex floods, bad evidence) adds to a per-peer score with
exponential time decay. Crossing the threshold bans the peer for a window
that doubles on repeat offenses — while banned, inbound conns are refused
and the persistent-peer redial loop waits instead of redialing a byzantine
peer forever.
"""

from __future__ import annotations

import asyncio
import random
import time
from typing import Optional

from cometbft_tpu.libs import log as cmtlog
from cometbft_tpu.libs.service import BaseService, TaskRunner
from cometbft_tpu.p2p import netchaos
from cometbft_tpu.p2p.base_reactor import Envelope, Reactor
from cometbft_tpu.p2p.conn.connection import ChannelDescriptor, MConnConfig
from cometbft_tpu.p2p.peer import Peer
from cometbft_tpu.p2p.transport import Transport, UpgradedConn, parse_addr

RECONNECT_ATTEMPTS = 20
RECONNECT_BASE_DELAY = 0.5
RECONNECT_MAX_DELAY = 30.0


class ErrDuplicatePeer(Exception):
    pass


class ErrBannedPeer(Exception):
    pass


class _PeerRecord:
    __slots__ = ("score", "updated", "banned_until", "ban_count", "last_ban")

    def __init__(self):
        self.score = 0.0
        self.updated = None  # None until the first report (0.0 is a valid time)
        self.banned_until = 0.0
        self.ban_count = 0
        self.last_ban = 0.0


class PeerScorer:
    """Misbehavior score + ban ledger, one record per node id.

    Scores decay exponentially (half_life), so a peer must misbehave
    FASTER than the decay to get banned — a one-off glitch ages out. Ban
    windows double per repeat offense up to ban_max, and the repeat
    counter itself resets after a clean stretch (10x the base window), so
    a long-reformed peer earns back the short first-offense window."""

    def __init__(self, ban_threshold: float = 3.0, ban_base: float = 60.0,
                 ban_max: float = 3600.0, half_life: float = 120.0):
        self.ban_threshold = ban_threshold
        self.ban_base = ban_base
        self.ban_max = ban_max
        self.half_life = half_life
        self._records: dict[str, _PeerRecord] = {}

    def record(self, node_id: str, weight: float = 1.0,
               now: float | None = None) -> bool:
        """Score a misbehavior; returns True when this report trips a ban."""
        now = time.monotonic() if now is None else now
        rec = self._records.setdefault(node_id, _PeerRecord())
        if rec.updated is not None and self.half_life > 0:
            rec.score *= 0.5 ** ((now - rec.updated) / self.half_life)
        rec.updated = now
        rec.score += weight
        if rec.score < self.ban_threshold or now < rec.banned_until:
            return False
        if rec.banned_until and now - rec.banned_until > 10 * self.ban_base:
            # clean stretch measured from ban END, not start: a banned
            # peer can't offend while refused, so measuring from the start
            # would forgive any ban longer than the stretch itself
            rec.ban_count = 0
        window = min(self.ban_base * (2 ** rec.ban_count), self.ban_max)
        rec.banned_until = now + window
        rec.ban_count += 1
        rec.last_ban = now
        rec.score = 0.0
        return True

    def is_banned(self, node_id: str, now: float | None = None) -> bool:
        rec = self._records.get(node_id)
        if rec is None:
            return False
        return (time.monotonic() if now is None else now) < rec.banned_until

    def ban_remaining(self, node_id: str, now: float | None = None) -> float:
        rec = self._records.get(node_id)
        if rec is None:
            return 0.0
        return max(0.0, rec.banned_until - (time.monotonic() if now is None else now))

    def snapshot(self) -> dict:
        now = time.monotonic()
        return {
            nid: {"score": round(rec.score, 3),
                  "banned_for": max(0.0, rec.banned_until - now),
                  "bans": rec.ban_count}
            for nid, rec in self._records.items()
        }


class Switch(BaseService):
    def __init__(
        self,
        transport: Transport,
        mconn_config: MConnConfig | None = None,
        logger: cmtlog.Logger | None = None,
        scorer: PeerScorer | None = None,
    ):
        super().__init__("P2P Switch", logger)
        self.transport = transport
        self.mconn_config = mconn_config or MConnConfig()
        self.reactors: dict[str, Reactor] = {}
        self._chan_to_reactor: dict[int, Reactor] = {}
        self._channels: list[ChannelDescriptor] = []
        self.peers: dict[str, Peer] = {}
        self.metrics = None  # libs.metrics.P2PMetrics | None (node wires it)
        self.persistent_addrs: dict[str, str] = {}  # node_id -> addr
        self._reconnecting: set[str] = set()
        self._tasks = TaskRunner("switch")
        self.scorer = scorer or PeerScorer()
        self.transport.is_banned = self.scorer.is_banned
        self._closing = False
        # ban observer (the node points this at addr_book.mark_bad so PEX
        # stops offering/dialing a banned peer too): (node_id, seconds)
        self.on_ban: Optional[callable] = None

    # ------------------------------------------------------------ reactors

    def add_reactor(self, name: str, reactor: Reactor) -> None:
        """switch.go:206 AddReactor: channel ids must be globally unique."""
        for d in reactor.get_channels():
            if d.id in self._chan_to_reactor:
                raise ValueError(f"channel {d.id:#x} already registered")
            self._chan_to_reactor[d.id] = reactor
            self._channels.append(d)
        self.reactors[name] = reactor
        reactor.set_switch(self)
        # advertise channels in the handshake
        self.transport.node_info.channels = bytes(
            sorted(d.id for d in self._channels)
        )

    # ------------------------------------------------------------ lifecycle

    async def on_start(self) -> None:
        self._closing = False
        for reactor in self.reactors.values():
            await reactor.on_start()
        self._tasks.spawn(self._accept_routine(), name="switch-accept")

    async def on_stop(self) -> None:
        # peer-error callbacks racing the teardown must not spawn fresh
        # reconnect tasks after cancel_all has already run
        self._closing = True
        await self._tasks.cancel_all()
        for peer in list(self.peers.values()):
            await self._stop_peer(peer, "switch stopping")
        for reactor in self.reactors.values():
            await reactor.on_stop()
        self.transport.close()

    # -------------------------------------------------------------- accept

    async def _accept_routine(self) -> None:
        """switch.go:633 acceptRoutine."""
        while True:
            try:
                up = await self.transport.accept()
            except asyncio.CancelledError:
                raise
            except Exception as e:  # noqa: BLE001
                self.logger.error("accept error", err=str(e))
                await asyncio.sleep(0.1)
                continue
            try:
                await self._add_peer(up)
            except Exception as e:  # noqa: BLE001 - bad peer must not kill accepts
                self.logger.info("failed to add inbound peer", err=str(e))
                up.conn.close()

    # ---------------------------------------------------------------- dial

    async def dial_peers_async(self, addrs: list[str], persistent: bool = False) -> None:
        """switch.go:573 DialPeersAsync: fire-and-forget dial attempts."""
        for addr in addrs:
            node_id, _, _ = parse_addr(addr)
            if persistent and node_id:
                self.persistent_addrs[node_id] = addr
            self._tasks.spawn(self._dial_with_retries(addr, persistent),
                              name=f"dial-{addr}")

    async def dial_peer(self, addr: str) -> bool:
        """One AWAITED dial attempt with the outcome returned to the
        caller — the PEX ensure-peers seam: failures must land back on
        the address book's attempt/backoff bookkeeping instead of being
        dropped by a fire-and-forget task (dial_peers_async stays the
        fire-and-forget path for operator/topology dials)."""
        node_id, _, _ = parse_addr(addr)
        if node_id and (node_id in self.peers
                        or self.scorer.is_banned(node_id)):
            return False
        try:
            up = await self.transport.dial(addr)
            await self._add_peer(up)
            return True
        except asyncio.CancelledError:
            raise
        except ErrDuplicatePeer:
            # lost a simultaneous-dial tie-break: the peer IS connected
            return True
        except Exception as e:  # noqa: BLE001
            self.logger.info("dial failed", addr=addr, err=str(e))
            return False

    async def _dial_with_retries(self, addr: str, persistent: bool) -> None:
        node_id, _, _ = parse_addr(addr)
        attempts = RECONNECT_ATTEMPTS if persistent else 1
        delay = RECONNECT_BASE_DELAY
        i = 0
        while i < attempts:
            if node_id and node_id in self.peers:
                return
            if node_id and self.scorer.is_banned(node_id):
                # a banned peer is not redialed — wait out the (finite)
                # ban window WITHOUT consuming dial attempts, or a long
                # ban would permanently abandon a persistent peer
                if not persistent:
                    return
                await asyncio.sleep(
                    min(self.scorer.ban_remaining(node_id), RECONNECT_MAX_DELAY)
                    + RECONNECT_BASE_DELAY)
                continue
            try:
                up = await self.transport.dial(addr)
                await self._add_peer(up, persistent=persistent)
                return
            except asyncio.CancelledError:
                raise
            except Exception as e:  # noqa: BLE001
                self.logger.info("dial failed", addr=addr, attempt=i, err=str(e))
                i += 1
                # exponential backoff + jitter (switch.go:398)
                await asyncio.sleep(delay * (0.5 + random.random()))
                delay = min(delay * 2, RECONNECT_MAX_DELAY)

    # ---------------------------------------------------------------- peers

    async def _add_peer(self, up: UpgradedConn, persistent: bool = False) -> Peer:
        node_id = up.node_info.node_id
        if self.scorer.is_banned(node_id):
            up.conn.close()
            raise ErrBannedPeer(
                f"peer {node_id[:10]} is banned for another "
                f"{self.scorer.ban_remaining(node_id):.1f}s")
        existing = self.peers.get(node_id)
        if existing is not None:
            # Simultaneous-dial tie-break: both sides keep ONLY the
            # connection dialed by the lower node id, so they agree on
            # which TCP conn survives and the mutual-close livelock of
            # naive dedup cannot happen (switch.go addPeer dedup, with a
            # deterministic winner instead of first-wins).
            my_id = self.transport.node_key.id()
            new_is_canonical = (my_id < node_id) == up.outbound
            if not new_is_canonical:
                up.conn.close()
                raise ErrDuplicatePeer(node_id)
            await self._stop_peer(existing, "replaced by canonical duplicate conn")
        persistent = persistent or node_id in self.persistent_addrs
        peer = Peer(
            # every peer conn rides through the net-chaos seam; a clean
            # wire is one flag test per write (p2p/netchaos.py)
            conn=netchaos.wrap(up.conn, self.transport.node_key.id(), node_id),
            node_info=up.node_info,
            channels=self._channels,
            on_receive=self._on_peer_receive,
            on_error=self._on_peer_error,
            outbound=up.outbound,
            persistent=persistent,
            mconn_config=self.mconn_config,
            logger=self.logger.with_fields(peer=node_id[:10]),
            metrics=self.metrics,
            peer_label=(self.metrics.peer_label(node_id)
                        if self.metrics is not None else ""),
        )
        for reactor in self.reactors.values():
            reactor.init_peer(peer)
        await peer.start()
        self.peers[node_id] = peer
        if self.metrics is not None:
            self.metrics.peers.set(len(self.peers))
        for reactor in self.reactors.values():
            await reactor.add_peer(peer)
        self.logger.info("added peer", peer=node_id[:10],
                         outbound=up.outbound, n_peers=len(self.peers))
        return peer

    async def _on_peer_receive(self, chan_id: int, peer: Peer, msg: bytes) -> None:
        reactor = self._chan_to_reactor.get(chan_id)
        if reactor is None:
            await self.stop_peer_for_error(peer, f"unknown channel {chan_id:#x}")
            return
        try:
            await reactor.receive(Envelope(channel_id=chan_id, message=msg, src=peer))
        except Exception as e:  # noqa: BLE001 - a bad message bans the peer
            self.logger.error("reactor receive failed", chan=f"{chan_id:#x}", err=str(e))
            await self.stop_peer_for_error(peer, e)

    async def _on_peer_error(self, peer: Peer, err: Exception) -> None:
        await self.stop_peer_for_error(peer, err)

    def report_misbehavior(self, peer_id: str, reason: str,
                           weight: float = 1.0) -> bool:
        """Score a peer offense (invalid vote signature, bad evidence, pex
        flood, ...). Sync so reactors/consensus can call it inline; a ban
        tears the live conn down on a spawned task. Returns True when this
        report newly banned the peer."""
        if not peer_id:
            return False
        if self.metrics is not None:
            self.metrics.peer_misbehavior.labels(reason).inc()
        banned = self.scorer.record(peer_id, weight)
        if not banned:
            return False
        remaining = self.scorer.ban_remaining(peer_id)
        self.logger.info("banning misbehaving peer", peer=peer_id[:10],
                         reason=reason, seconds=round(remaining, 1))
        if self.metrics is not None:
            self.metrics.peer_bans.inc()
        if self.on_ban is not None:
            try:
                self.on_ban(peer_id, remaining)
            except Exception as e:  # noqa: BLE001 - observer must not break bans
                self.logger.error("on_ban hook failed", err=str(e))
        peer = self.peers.get(peer_id)
        if peer is not None:
            self._tasks.spawn(self.stop_peer_for_error(peer, f"banned: {reason}",
                                                       score=0.0),
                              name=f"ban-{peer_id[:10]}")
        return True

    async def stop_peer_for_error(self, peer: Peer, reason: object,
                                  score: float = 0.4) -> None:
        """switch.go:335: drop the peer; redial if persistent (and not
        banned). `score` feeds the misbehavior ledger — the 0.4 default
        means ~8 conn errors inside one decay half-life before a ban (a
        crashing neighbor is not an attacker); pass 0 for stops that are
        our own doing (seed-mode hangups, operator disconnects, ban
        enforcement) and 1.0 for protocol offenses."""
        if self.peers.get(peer.id) is not peer:
            # a late error from an already-replaced conn (duplicate
            # tie-break) must not tear down the canonical replacement
            return
        self.logger.info("stopping peer for error", peer=peer.id[:10], err=str(reason))
        if score > 0:
            self.report_misbehavior(peer.id, "conn-error", weight=score)
        await self._stop_peer(peer, reason)
        if peer.is_persistent() and not self._closing:
            # banned persistent peers still get a reconnect task — the
            # dial loop waits out the (decaying) ban window instead of
            # hammering dials at a peer we just banned
            addr = self.persistent_addrs.get(peer.id)
            if addr and peer.id not in self._reconnecting:
                self._reconnecting.add(peer.id)
                self._tasks.spawn(self._reconnect(peer.id, addr),
                                  name=f"reconnect-{peer.id[:10]}")

    async def _reconnect(self, node_id: str, addr: str) -> None:
        try:
            await asyncio.sleep(RECONNECT_BASE_DELAY)
            await self._dial_with_retries(addr, persistent=True)
        finally:
            self._reconnecting.discard(node_id)

    async def _stop_peer(self, peer: Peer, reason: object) -> None:
        if self.peers.get(peer.id) is peer:
            self.peers.pop(peer.id, None)
            if self.metrics is not None:
                self.metrics.peers.set(len(self.peers))
                # free the metrics label slot: under a churn storm the
                # label ledger must turn over instead of pinning dead
                # peers' slots forever (a returning peer re-claims its
                # old label — same series, no new cardinality)
                self.metrics.release_peer(peer.id)
        try:
            await peer.stop()
        except Exception:  # noqa: BLE001
            pass
        for reactor in self.reactors.values():
            try:
                await reactor.remove_peer(peer, reason)
            except Exception as e:  # noqa: BLE001
                self.logger.error("remove_peer failed", reactor=reactor.name, err=str(e))

    # ------------------------------------------------------------ broadcast

    def broadcast(self, chan_id: int, msg: bytes) -> None:
        """switch.go:274 Broadcast: try_send to every peer (drops on full
        queues — gossip routines provide reliability). Sync so event-switch
        callbacks can call it inline."""
        for peer in list(self.peers.values()):
            peer.try_send(chan_id, msg)

    def n_peers(self) -> int:
        return len(self.peers)

    def get_peer(self, node_id: str) -> Optional[Peer]:
        return self.peers.get(node_id)

    # ------------------------------------------------------------ telemetry

    def net_telemetry(self) -> dict:
        """The wire-plane accounting rollup the net_telemetry RPC route
        serves: every peer's full MConnection status (per-channel
        bytes/msgs/packets both ways, queue depth + high-water, stall
        split, ping RTT) plus cross-peer totals per channel and for the
        whole switch — 'where do my wire bytes go'."""
        peers = []
        totals = {"send_bytes": 0, "recv_bytes": 0,
                  "send_msgs": 0, "recv_msgs": 0,
                  "send_stall_seconds": 0.0}
        by_channel: dict[str, dict] = {}
        for p in list(self.peers.values()):
            st = p.status()
            peers.append({
                "id": p.id,
                "moniker": p.node_info.moniker,
                "is_outbound": p.outbound,
                "persistent": p.is_persistent(),
                "connection_status": st,
            })
            totals["send_bytes"] += st["send"]["bytes_total"]
            totals["recv_bytes"] += st["recv"]["bytes_total"]
            totals["send_stall_seconds"] += st["send_stall_seconds"]
            for ch_id, ch in st["channels"].items():
                agg = by_channel.setdefault(ch_id, {
                    "send_bytes": 0, "recv_bytes": 0,
                    "send_msgs": 0, "recv_msgs": 0,
                    "send_packets": 0, "recv_packets": 0,
                    "queue_hwm": 0})
                for k in ("send_bytes", "recv_bytes", "send_msgs",
                          "recv_msgs", "send_packets", "recv_packets"):
                    agg[k] += ch[k]
                agg["queue_hwm"] = max(agg["queue_hwm"], ch["queue_hwm"])
                totals["send_msgs"] += ch["send_msgs"]
                totals["recv_msgs"] += ch["recv_msgs"]
        totals["send_stall_seconds"] = round(totals["send_stall_seconds"], 6)
        return {
            "n_peers": len(peers),
            "peers": peers,
            "channels": by_channel,
            "totals": totals,
            "peer_scores": self.scorer.snapshot(),
        }
