"""Reactor interface.

Reference: p2p/base_reactor.go:15-44 — a reactor owns a set of channels and
gets peer lifecycle callbacks from the Switch. Receive is async (runs on the
peer's recv task); long work must be queued internally, mirroring the
reference rule that Receive must not block.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import TYPE_CHECKING, Optional

from cometbft_tpu.libs import log as cmtlog
from cometbft_tpu.p2p.conn.connection import ChannelDescriptor

if TYPE_CHECKING:
    from cometbft_tpu.p2p.peer import Peer
    from cometbft_tpu.p2p.switch import Switch


@dataclass
class Envelope:
    """A routed message (reference p2p/types.go Envelope): raw bytes on a
    channel, plus the sender on receive."""

    channel_id: int
    message: bytes
    src: Optional["Peer"] = None


class Reactor:
    def __init__(self, name: str, logger: cmtlog.Logger | None = None):
        self.name = name
        self.logger = logger or cmtlog.nop()
        self.switch: Optional["Switch"] = None

    def set_switch(self, switch: "Switch") -> None:
        self.switch = switch

    def get_channels(self) -> list[ChannelDescriptor]:
        return []

    async def on_start(self) -> None:
        pass

    async def on_stop(self) -> None:
        pass

    def init_peer(self, peer: "Peer") -> None:
        """Called before the peer starts — attach per-peer state."""

    async def add_peer(self, peer: "Peer") -> None:
        """Called once the peer is running — start per-peer routines."""

    async def remove_peer(self, peer: "Peer", reason: object) -> None:
        """Called on disconnect — tear down per-peer routines."""

    async def receive(self, e: Envelope) -> None:
        """A complete message arrived on one of this reactor's channels."""
