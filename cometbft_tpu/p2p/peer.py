"""Peer: one remote node = one MConnection + its handshake identity.

Reference: p2p/peer.go:23 — wraps the multiplexed connection, carries the
NodeInfo learned in the handshake, a per-peer key/value store reactors hang
their PeerState on (peer.Set/Get, peer.go:356-366), and send helpers that
route by channel id.
"""

from __future__ import annotations

import asyncio
from typing import Optional

from cometbft_tpu.libs import log as cmtlog
from cometbft_tpu.libs.service import BaseService
from cometbft_tpu.p2p.conn.connection import ChannelDescriptor, MConnConfig, MConnection
from cometbft_tpu.p2p.conn.secret_connection import SecretConnection
from cometbft_tpu.p2p.node_info import NodeInfo


class Peer(BaseService):
    def __init__(
        self,
        conn: SecretConnection,
        node_info: NodeInfo,
        channels: list[ChannelDescriptor],
        on_receive,  # async (chan_id, peer, msg_bytes)
        on_error,  # async (peer, err)
        outbound: bool,
        persistent: bool = False,
        mconn_config: MConnConfig | None = None,
        logger: cmtlog.Logger | None = None,
        metrics=None,  # libs.metrics.P2PMetrics | None
        peer_label: str = "",  # pre-capped metrics label (Switch assigns)
    ):
        super().__init__(f"peer-{node_info.node_id[:10]}", logger)
        self.node_info = node_info
        self.outbound = outbound
        self.persistent = persistent
        self._data: dict[str, object] = {}
        self._conn = conn

        async def _mconn_receive(chan_id: int, msg: bytes) -> None:
            await on_receive(chan_id, self, msg)

        async def _mconn_error(err: Exception) -> None:
            await on_error(self, err)

        self.mconn = MConnection(
            conn, channels, _mconn_receive, _mconn_error,
            config=mconn_config, logger=self.logger,
            metrics=metrics, peer_label=peer_label,
            peer_id=node_info.node_id,
        )

    # ------------------------------------------------------------- identity

    @property
    def id(self) -> str:
        return self.node_info.node_id

    @property
    def remote_host(self) -> str:
        """The remote socket host (through the netchaos wrapper's
        attribute forwarding) — the PEX book's source-group key."""
        return getattr(self._conn, "remote_host", "") or ""

    def is_persistent(self) -> bool:
        return self.persistent

    # ------------------------------------------------------------ lifecycle

    async def on_start(self) -> None:
        self.mconn.start()

    async def on_stop(self) -> None:
        await self.mconn.stop()

    # ----------------------------------------------------------------- send

    async def send(self, chan_id: int, msg: bytes) -> bool:
        """Blocking send (peer.go:261)."""
        if not self.is_running:
            return False
        return await self.mconn.send(chan_id, msg)

    def try_send(self, chan_id: int, msg: bytes) -> bool:
        """Non-blocking send; drops when the channel queue is full
        (peer.go:273)."""
        if not self.is_running:
            return False
        return self.mconn.try_send(chan_id, msg)

    # -------------------------------------------------------- per-peer data

    def set(self, key: str, value: object) -> None:
        self._data[key] = value

    def get(self, key: str) -> Optional[object]:
        return self._data.get(key)

    def status(self) -> dict:
        return self.mconn.status()

    def __repr__(self) -> str:
        arrow = "out" if self.outbound else "in"
        return f"Peer{{{self.id[:10]} {arrow}}}"
