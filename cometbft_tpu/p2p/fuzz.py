"""Fuzzed connection wrappers: random drop / delay / kill on p2p streams.

Reference: p2p/fuzz.go:12-67 FuzzedConnection + config.FuzzConnConfig —
wraps the raw conn before the secret-connection upgrade, with fuzzing armed
only after a delay so handshakes complete. Semantics mapped from Go's
net.Conn to asyncio streams:

  - write drop (ProbDropRW): the bytes silently vanish from the stream —
    the peer sees broken framing or a stall and must take its error path;
  - conn drop (ProbDropConn): the transport is closed underneath;
  - sleep (ProbSleep): a uniform random delay up to max_delay;
  - read fuzzing is delay/kill only: an asyncio readexactly() cannot
    "return no data" the way Go's Read returns (0, nil) without breaking
    the stream API, and in Go a dropped read loses nothing anyway (the
    bytes stay in the kernel buffer) — the observable fault there is also
    just latency.

Armed per-connection via Transport(fuzz_config=...), config knobs on the
P2P section (test_fuzz*, config.go:739-740).
"""

from __future__ import annotations

import asyncio
import random
import time
from dataclasses import dataclass


@dataclass
class FuzzConnConfig:
    """config.go FuzzConnConfig. mode="drop" is the reference FuzzModeDrop
    (drops + conn kills + delays); mode="delay" is FuzzModeDelay — latency
    only, the soak profile that must NEVER cost liveness."""

    mode: str = "drop"  # "drop" | "delay"
    prob_drop_rw: float = 0.01
    prob_drop_conn: float = 0.003
    prob_sleep: float = 0.01
    max_delay: float = 0.05  # seconds
    arm_after: float = 3.0   # handshake grace (transport.go:223 uses 10 s)

    def drops_enabled(self) -> bool:
        return self.mode != "delay"


class _FuzzState:
    """Shared between the reader and writer of one connection."""

    def __init__(self, cfg: FuzzConnConfig, writer: asyncio.StreamWriter,
                 rng: random.Random):
        self.cfg = cfg
        self.writer = writer
        self.rng = rng
        self.armed_at = time.monotonic() + cfg.arm_after

    def active(self) -> bool:
        return time.monotonic() >= self.armed_at

    def kill(self) -> None:
        try:
            self.writer.close()
        except Exception:  # noqa: BLE001
            pass


class FuzzedWriter:
    def __init__(self, writer: asyncio.StreamWriter, state: _FuzzState):
        self._writer = writer
        self._state = state
        self._pending_sleep = 0.0

    def write(self, data: bytes) -> None:
        st = self._state
        if st.active():
            r = st.rng.random()
            cfg = st.cfg
            if cfg.drops_enabled():
                if r <= cfg.prob_drop_rw:
                    return  # bytes vanish
                if r < cfg.prob_drop_rw + cfg.prob_drop_conn:
                    st.kill()
                    return
            if r < cfg.prob_drop_rw + cfg.prob_drop_conn + cfg.prob_sleep:
                # write() is sync; the delay lands in the next drain()
                self._pending_sleep = st.rng.uniform(0, cfg.max_delay)
        self._writer.write(data)

    async def drain(self) -> None:
        if self._pending_sleep:
            delay, self._pending_sleep = self._pending_sleep, 0.0
            await asyncio.sleep(delay)
        await self._writer.drain()

    def __getattr__(self, name):
        return getattr(self._writer, name)


class FuzzedReader:
    def __init__(self, reader: asyncio.StreamReader, state: _FuzzState):
        self._reader = reader
        self._state = state

    async def _maybe_fuzz(self) -> None:
        st = self._state
        if not st.active():
            return
        r = st.rng.random()
        cfg = st.cfg
        if cfg.drops_enabled() and r < cfg.prob_drop_conn:
            st.kill()
        elif r < cfg.prob_drop_conn + cfg.prob_sleep:
            await asyncio.sleep(st.rng.uniform(0, cfg.max_delay))

    async def readexactly(self, n: int) -> bytes:
        await self._maybe_fuzz()
        return await self._reader.readexactly(n)

    async def read(self, n: int = -1) -> bytes:
        await self._maybe_fuzz()
        return await self._reader.read(n)

    async def readline(self) -> bytes:
        await self._maybe_fuzz()
        return await self._reader.readline()

    def __getattr__(self, name):
        return getattr(self._reader, name)


def fuzz_streams(
    reader: asyncio.StreamReader,
    writer: asyncio.StreamWriter,
    cfg: FuzzConnConfig | None = None,
    seed: int | None = None,
) -> tuple[FuzzedReader, FuzzedWriter]:
    cfg = cfg or FuzzConnConfig()
    state = _FuzzState(cfg, writer, random.Random(seed))
    return FuzzedReader(reader, state), FuzzedWriter(writer, state)
