"""Multiplexed message connection.

Reference: p2p/conn/connection.go:81-157 — N logical channels (byte IDs
with priorities and bounded send queues) multiplexed onto one encrypted
stream. A send task drains channel queues packet-by-packet, picking the
channel with the lowest sent-bytes/priority ratio (connection.go:693-719
sendPacketMsg "least ratio" scheduling); a recv task reassembles PacketMsg
chunks per channel and hands complete messages to the owning reactor.
Ping/pong keepalive (connection.go:429-520) and token-bucket rate limiting
via libs/flowrate (connection.go:44-45).

Wire: varint-length-delimited protobuf Packet envelopes
(proto/tendermint/p2p/conn.proto shape): oneof ping=1 / pong=2 /
msg=3{channel_id=1, eof=2, data=3}.

Wire-plane accounting (framework extension): every connection keeps
per-channel byte/message/packet counters for both directions, send-queue
depth high-water marks, send-routine stall time (rate-limit sleeps +
blocked socket writes), and a ping-RTT EWMA — surfaced via status(), the
net_telemetry RPC route, and (through the owning Switch's P2PMetrics)
bounded-cardinality Prometheus series. The flowrate monitors are ALWAYS
updated, throttling or not: rate_limit=0 keeps them non-throttling, so
accounting never depends on rate limiting being enabled.
"""

from __future__ import annotations

import asyncio
import time
from dataclasses import dataclass
from typing import Awaitable, Callable, Optional

from cometbft_tpu.libs import linkmodel
from cometbft_tpu.libs import log as cmtlog
from cometbft_tpu.libs.flowrate import Monitor
from cometbft_tpu.libs.service import TaskRunner
from cometbft_tpu.utils.protobuf import Reader, Writer, decode_uvarint, encode_uvarint


@dataclass
class MConnConfig:
    send_rate: int = 5_120_000  # bytes/sec (config.go DefaultP2PConfig)
    recv_rate: int = 5_120_000
    max_packet_msg_payload_size: int = 1024
    flush_throttle: float = 0.1  # connection.go:39 (100ms)
    ping_interval: float = 30.0
    pong_timeout: float = 45.0
    send_timeout: float = 10.0  # connection.go defaultSendTimeout


@dataclass
class ChannelDescriptor:
    id: int
    priority: int = 1
    send_queue_capacity: int = 64
    recv_message_capacity: int = 1 << 22  # 4 MB


class _Channel:
    def __init__(self, desc: ChannelDescriptor, max_payload: int):
        self.desc = desc
        self.max_payload = max_payload
        self.send_queue: asyncio.Queue[bytes] = asyncio.Queue(desc.send_queue_capacity)
        self.sending: bytes = b""  # partially-sent message
        self.sent_pos = 0
        self.recently_sent = 0  # decayed sent-bytes counter for scheduling
        self.recving = bytearray()
        # wire accounting (monotonic counters; bytes are WIRE bytes — the
        # encoded packet envelope, so per-channel sums match the flowrate
        # monitor totals and the actual conn-seam traffic)
        self.send_bytes = 0
        self.send_msgs = 0
        self.send_packets = 0
        self.recv_bytes = 0
        self.recv_msgs = 0
        self.recv_packets = 0
        self.queue_hwm = 0  # send-queue depth high-water mark

    def note_queued(self) -> None:
        depth = self.send_queue.qsize()
        if depth > self.queue_hwm:
            self.queue_hwm = depth

    def has_data(self) -> bool:
        return bool(self.sending) or not self.send_queue.empty()

    def next_packet(self) -> tuple[bytes, bool]:
        """Pop the next <=max_payload chunk + eof flag."""
        if not self.sending:
            self.sending = self.send_queue.get_nowait()
            self.sent_pos = 0
        chunk = self.sending[self.sent_pos : self.sent_pos + self.max_payload]
        self.sent_pos += len(chunk)
        eof = self.sent_pos >= len(self.sending)
        if eof:
            self.sending = b""
            self.sent_pos = 0
            self.send_msgs += 1
        self.send_packets += 1
        self.recently_sent += len(chunk)
        return chunk, eof


class MConnection:
    """One per peer. on_receive(chan_id, msg_bytes) is awaited on the recv
    task; keep it fast (reactors should queue internally)."""

    def __init__(
        self,
        conn,  # SecretConnection (or any object with write/read_msg-like API)
        channels: list[ChannelDescriptor],
        on_receive: Callable[[int, bytes], Awaitable[None]],
        on_error: Callable[[Exception], Awaitable[None]],
        config: MConnConfig | None = None,
        logger: cmtlog.Logger | None = None,
        metrics=None,  # libs.metrics.P2PMetrics | None
        peer_label: str = "",  # pre-capped metrics label for this peer
        peer_id: str = "",  # node id, keys the clock-skew table
    ):
        self.config = config or MConnConfig()
        self._conn = conn
        self._channels = {
            d.id: _Channel(d, self.config.max_packet_msg_payload_size) for d in channels
        }
        self._on_receive = on_receive
        self._on_error = on_error
        self.logger = logger or cmtlog.nop()
        self._send_wake = asyncio.Event()
        self._pong_pending = False
        self._pong_received = asyncio.Event()
        self._send_monitor = Monitor(self.config.send_rate)
        self._recv_monitor = Monitor(self.config.recv_rate)
        self._tasks = TaskRunner("mconn")
        self._stopped = False  # no new sends / no more error callbacks
        self._torn_down = False  # tasks cancelled + socket closed
        self.metrics = metrics
        self.peer_label = peer_label
        # send-routine stall accounting: seconds the routine spent NOT
        # idle-parked — asleep on the rate limiter or blocked in a socket
        # write (TCP backpressure); the "is the wire the bottleneck"
        # number for this peer
        self._stall_rate_limit_s = 0.0
        self._stall_write_s = 0.0
        # ping RTT EWMA (alpha 0.2) + last sample; feeds the process-wide
        # p2p link model for net_telemetry
        self._ping_rtt_s = 0.0
        self._ping_rtt_last_s = 0.0
        self._ping_samples = 0
        # clock-skew sampling: the last pong's remote wall stamp, consumed
        # by the ping routine against its own wall t0 + rtt/2
        self.peer_id = peer_id
        self._last_pong_wall_ns = 0

    # ----------------------------------------------------------- lifecycle

    def start(self) -> None:
        self._tasks.spawn(self._send_routine(), name="mconn-send")
        self._tasks.spawn(self._recv_routine(), name="mconn-recv")
        self._tasks.spawn(self._ping_routine(), name="mconn-ping")

    async def stop(self) -> None:
        """Idempotent teardown. _error() marks the conn stopped but must NOT
        skip this cleanup: the owning Peer always calls stop() afterwards to
        cancel tasks and close the socket."""
        self._stopped = True
        if self._torn_down:
            return
        self._torn_down = True
        await self._tasks.cancel_all()
        self._conn.close()

    # ---------------------------------------------------------------- send

    async def send(self, chan_id: int, msg: bytes) -> bool:
        """Queue msg on the channel; blocks when the queue is full, but only
        up to send_timeout (connection.go:287 Send + defaultSendTimeout) so a
        caller never hangs on a dead peer's full queue."""
        ch = self._channels.get(chan_id)
        if ch is None or self._stopped:
            return False
        try:
            await asyncio.wait_for(ch.send_queue.put(msg), self.config.send_timeout)
        except asyncio.TimeoutError:
            return False
        ch.note_queued()
        self._send_wake.set()
        return True

    def try_send(self, chan_id: int, msg: bytes) -> bool:
        """Non-blocking send; False when the queue is full
        (connection.go:311 TrySend)."""
        ch = self._channels.get(chan_id)
        if ch is None or self._stopped:
            return False
        try:
            ch.send_queue.put_nowait(msg)
        except asyncio.QueueFull:
            return False
        ch.note_queued()
        self._send_wake.set()
        return True

    def _pick_channel(self) -> Optional[_Channel]:
        """Least recently_sent/priority ratio among channels with data
        (connection.go:693-719)."""
        best, best_ratio = None, None
        for ch in self._channels.values():
            if not ch.has_data():
                continue
            ratio = ch.recently_sent / max(ch.desc.priority, 1)
            if best_ratio is None or ratio < best_ratio:
                best, best_ratio = ch, ratio
        return best

    async def _send_routine(self) -> None:
        try:
            while True:
                ch = self._pick_channel()
                if ch is None and not self._pong_pending:
                    # idle: park on the wake event (no polling; try_send /
                    # send / ping-receipt set it)
                    self._send_wake.clear()
                    if self._pick_channel() is None and not self._pong_pending:
                        await self._send_wake.wait()
                    continue
                batch = bytearray()
                if self._pong_pending:
                    # stamp our wall clock into the pong so the pinger can
                    # estimate clock skew from the RTT midpoint
                    batch += _encode_packet_pong(time.time_ns())
                    self._pong_pending = False
                # coalesce a few packets per flush (the reference's
                # 100ms flush throttle analog — we flush per loop, batching
                # whatever is ready)
                n_packets = 0
                flushed: dict[int, tuple[int, int]] = {}  # cid -> (bytes, msgs)
                while ch is not None and n_packets < 16:
                    chunk, eof = ch.next_packet()
                    pkt = _encode_packet_msg(ch.desc.id, eof, chunk)
                    ch.send_bytes += len(pkt)
                    b, m = flushed.get(ch.desc.id, (0, 0))
                    flushed[ch.desc.id] = (b + len(pkt), m + (1 if eof else 0))
                    batch += pkt
                    n_packets += 1
                    ch = self._pick_channel()
                if batch:
                    # ALWAYS update the monitor (rate_limit=0 keeps it
                    # non-throttling): accounting must not depend on
                    # throttling being enabled
                    delay = self._send_monitor.update(len(batch))
                    if delay > 0:
                        self._stall_rate_limit_s += delay
                        await asyncio.sleep(delay)
                    t0 = time.monotonic()
                    await self._conn.write(bytes(batch))
                    self._stall_write_s += time.monotonic() - t0
                    self._flush_metrics(flushed, send=True)
                # decay scheduling counters
                for c in self._channels.values():
                    c.recently_sent = int(c.recently_sent * 0.8)
        except asyncio.CancelledError:
            raise
        except Exception as e:  # noqa: BLE001
            await self._error(e)

    def _flush_metrics(self, per_chan: dict, send: bool) -> None:
        """Hand aggregated per-channel (bytes, msgs) deltas to the owning
        switch's P2PMetrics (bounded-cardinality peer labels live there).
        Metrics failures must never error a connection."""
        m = self.metrics
        if m is None or not per_chan:
            return
        try:
            m.record_conn_traffic(self.peer_label, per_chan, send=send)
        except Exception:  # noqa: BLE001
            pass

    # ---------------------------------------------------------------- recv

    async def _recv_routine(self) -> None:
        # metric deltas accumulate here and flush on message boundaries
        # (or every 32 packets mid-message) — the recv hot loop must not
        # pay two locked counter updates per 1 KB packet when the send
        # side batches up to 16 packets per flush
        pending: dict[int, tuple[int, int]] = {}
        pending_packets = 0
        try:
            while True:
                packet, wire_len = await self._read_packet()
                # ALWAYS update (accounting without throttling — see send);
                # wire_len includes the varint length prefix, matching the
                # sender's encoded-packet accounting byte for byte
                delay = self._recv_monitor.update(wire_len)
                if delay > 0:
                    await asyncio.sleep(delay)
                kind, chan_id, eof, data, pong_wall = _decode_packet(packet)
                if kind == 1:  # ping
                    self._pong_pending = True
                    self._send_wake.set()
                elif kind == 2:  # pong
                    # an extended pong carries the responder's wall clock
                    # for the skew estimator (0 from old senders)
                    self._last_pong_wall_ns = pong_wall
                    self._pong_received.set()
                elif kind == 3:
                    ch = self._channels.get(chan_id)
                    if ch is None:
                        raise ValueError(f"unknown channel {chan_id:#x}")
                    ch.recv_bytes += wire_len
                    ch.recv_packets += 1
                    ch.recving += data
                    if len(ch.recving) > ch.desc.recv_message_capacity:
                        raise ValueError(
                            f"recv message exceeds capacity on channel {chan_id:#x}"
                        )
                    b, m = pending.get(chan_id, (0, 0))
                    pending[chan_id] = (b + wire_len, m + (1 if eof else 0))
                    pending_packets += 1
                    if eof:
                        ch.recv_msgs += 1
                        msg = bytes(ch.recving)
                        ch.recving.clear()
                        self._flush_metrics(pending, send=False)
                        pending = {}
                        pending_packets = 0
                        await self._on_receive(chan_id, msg)
                    elif pending_packets >= 32:
                        self._flush_metrics(pending, send=False)
                        pending = {}
                        pending_packets = 0
        except asyncio.CancelledError:
            raise
        except Exception as e:  # noqa: BLE001
            await self._error(e)

    async def _read_packet(self) -> tuple[bytes, int]:
        """Read one varint-delimited packet from the secret connection.
        Returns (body, wire_len) where wire_len includes the length
        prefix — the recv accounting must match the sender's
        encoded-packet byte counts, not undercount by the varint."""
        # read varint length byte-by-byte (<=5 bytes for our sizes)
        hdr = b""
        while True:
            b = await self._conn.readexactly(1)
            hdr += b
            if not b[0] & 0x80:
                break
            if len(hdr) > 5:
                raise ValueError("packet length varint too long")
        n, _ = decode_uvarint(hdr)
        if n > self.config.max_packet_msg_payload_size + 64:
            raise ValueError(f"packet too large: {n}")
        return await self._conn.readexactly(n), len(hdr) + n

    async def _ping_routine(self) -> None:
        """Keepalive + dead-peer detection: a ping that is not answered
        within pong_timeout errors the connection (connection.go:429-520
        pongTimeoutCh)."""
        while True:
            await asyncio.sleep(self.config.ping_interval)
            try:
                self._pong_received.clear()
                self._last_pong_wall_ns = 0
                ping = _encode_packet_ping()
                self._send_monitor.update(len(ping))  # keepalives count too
                t0 = time.monotonic()
                t0_wall = time.time_ns()
                await self._conn.write(ping)
                try:
                    await asyncio.wait_for(
                        self._pong_received.wait(), self.config.pong_timeout
                    )
                except asyncio.TimeoutError:
                    raise ConnectionError("pong timeout") from None
                rtt = time.monotonic() - t0
                self._note_ping_rtt(rtt)
                if self._last_pong_wall_ns and self.peer_id:
                    # RTT-midpoint skew sample: the responder stamped its
                    # wall clock; ours at the midpoint is t0 + rtt/2
                    linkmodel.skew().observe_ping(
                        self.peer_id, self._last_pong_wall_ns,
                        t0_wall + int(rtt * 5e8), rtt)
            except asyncio.CancelledError:
                raise
            except Exception as e:  # noqa: BLE001
                await self._error(e)
                return

    def _note_ping_rtt(self, rtt: float) -> None:
        """Ping->pong round trip: EWMA per peer + the process-wide p2p
        link model (net_telemetry's aggregate view). The pong rode the
        send routine's batching, so this is an upper bound on the raw
        link RTT — which is the honest number for protocol planning: a
        vote pays the same queueing."""
        self._ping_rtt_last_s = rtt
        self._ping_samples += 1
        self._ping_rtt_s = (rtt if self._ping_samples == 1
                            else self._ping_rtt_s + 0.2 * (rtt - self._ping_rtt_s))
        linkmodel.p2p().observe_rtt(rtt)
        m = self.metrics
        if m is not None:
            try:
                m.peer_ping_rtt.labels(self.peer_label or "other").set(rtt)
            except Exception:  # noqa: BLE001
                pass

    async def _error(self, e: Exception) -> None:
        if self._stopped:
            return
        self._stopped = True
        try:
            await self._on_error(e)
        except Exception:  # noqa: BLE001 - error path must not raise
            pass

    # ---------------------------------------------------------------- misc

    def status(self) -> dict:
        """Connection status incl. the wire-plane accounting: monitor
        totals/averages, per-channel byte/msg/packet counters both ways,
        queue depth + high-water, send-routine stall split, ping RTT EWMA.
        net_info / net_telemetry serve this per peer."""
        return {
            "send_rate": self._send_monitor.rate(),
            "recv_rate": self._recv_monitor.rate(),
            "send": self._send_monitor.stats(),
            "recv": self._recv_monitor.stats(),
            "send_stall_seconds": round(
                self._stall_rate_limit_s + self._stall_write_s, 6),
            "send_stall_split_seconds": {
                "rate_limit": round(self._stall_rate_limit_s, 6),
                "socket_write": round(self._stall_write_s, 6),
            },
            "ping_rtt_ms": round(self._ping_rtt_s * 1e3, 3),
            "ping_rtt_last_ms": round(self._ping_rtt_last_s * 1e3, 3),
            "ping_samples": self._ping_samples,
            "channels": {
                f"{cid:#x}": {
                    "queued": ch.send_queue.qsize(),
                    "queue_hwm": ch.queue_hwm,
                    "recently_sent": ch.recently_sent,
                    "send_bytes": ch.send_bytes,
                    "send_msgs": ch.send_msgs,
                    "send_packets": ch.send_packets,
                    "recv_bytes": ch.recv_bytes,
                    "recv_msgs": ch.recv_msgs,
                    "recv_packets": ch.recv_packets,
                }
                for cid, ch in self._channels.items()
            },
        }


# ------------------------------------------------------------- packet codec


def _encode_packet_ping() -> bytes:
    body = Writer().message(1, b"", always=True).output()
    return encode_uvarint(len(body)) + body


def _encode_packet_pong(wall_ns: int = 0) -> bytes:
    """Pong, optionally carrying the responder's wall clock (uvarint
    field 1 of the pong submessage). Forward-compatible: old decoders
    skip the submessage content of fields 1/2, so an extended pong reads
    as a plain pong to them."""
    inner = Writer().uvarint(1, wall_ns).output() if wall_ns else b""
    body = Writer().message(2, inner, always=True).output()
    return encode_uvarint(len(body)) + body


def _encode_packet_msg(chan_id: int, eof: bool, data: bytes) -> bytes:
    inner = Writer().uvarint(1, chan_id).bool(2, eof).bytes(3, data).output()
    body = Writer().message(3, inner, always=True).output()
    return encode_uvarint(len(body)) + body


def _decode_packet(body: bytes) -> tuple[int, int, bool, bytes, int]:
    """Return (kind, chan_id, eof, data, pong_wall_ns); kind 1=ping
    2=pong 3=msg. pong_wall_ns is the responder clock an extended pong
    carried (0 for a plain pong or any other packet kind)."""
    r = Reader(body)
    kind = chan_id = 0
    eof = False
    data = b""
    pong_wall_ns = 0
    while not r.at_end():
        f, w = r.read_tag()
        if f == 1:
            r.skip(w)
            kind = f
        elif f == 2:
            kind = f
            mr = r.read_message()
            while not mr.at_end():
                mf, mw = mr.read_tag()
                if mf == 1:
                    pong_wall_ns = mr.read_uvarint()
                else:
                    mr.skip(mw)
        elif f == 3:
            kind = 3
            mr = r.read_message()
            while not mr.at_end():
                mf, mw = mr.read_tag()
                if mf == 1:
                    chan_id = mr.read_uvarint()
                elif mf == 2:
                    eof = mr.read_uvarint() != 0
                elif mf == 3:
                    data = mr.read_bytes()
                else:
                    mr.skip(mw)
        else:
            r.skip(w)
    if kind == 0:
        raise ValueError("empty packet")
    return kind, chan_id, eof, data, pong_wall_ns
