"""Authenticated encrypted connection (the p2p wire security layer).

Reference: p2p/conn/secret_connection.go:34-49 — a Station-to-Station
handshake: X25519 ephemeral ECDH -> transcript-bound KDF -> two
ChaCha20-Poly1305 session keys (one per direction) + a challenge that each
side signs with its long-lived ed25519 node key, proving identity. Data
flows in fixed-size sealed frames (1024 data bytes + 4-byte length header)
with 96-bit little-endian counter nonces, one counter per direction
(secret_connection.go:57-60,224-292).

The wire follows the reference exactly (secret_connection.go:71-175):
varint-delimited google.protobuf.BytesValue carries each side's ephemeral
pubkey; session keys come from HKDF-SHA256 over the raw DH secret (info
"TENDERMINT_SECRET_CONNECTION_KEY_AND_CHALLENGE_GEN", lower-key party
receives with the first 32 bytes); the sign-me challenge is extracted
from a Merlin transcript ("TENDERMINT_SECRET_CONNECTION_TRANSCRIPT_HASH"
binding the sorted ephemeral keys and the DH secret — the same Merlin
implementation as the sr25519 stack, byte-checked against merlin
vectors); authentication exchanges a varint-delimited
tendermint.p2p.AuthSigMessage over the now-encrypted channel. Sealed
1028-byte frames with 96-bit little-endian counter nonces.
"""

from __future__ import annotations

import asyncio
import hashlib
import hmac
import struct

try:
    from cryptography.exceptions import InvalidTag
    from cryptography.hazmat.primitives.asymmetric.x25519 import (
        X25519PrivateKey,
        X25519PublicKey,
    )
    from cryptography.hazmat.primitives.ciphers.aead import ChaCha20Poly1305

    _HAVE_OPENSSL = True
except ImportError:  # degraded: pure-Python X25519 + AEAD (crypto/fallback)
    from cometbft_tpu.crypto.fallback import ChaCha20Poly1305, InvalidTag

    _HAVE_OPENSSL = False

from cometbft_tpu.crypto import ed25519

DATA_LEN_SIZE = 4
DATA_MAX_SIZE = 1024
TOTAL_FRAME_SIZE = DATA_MAX_SIZE + DATA_LEN_SIZE  # 1028 (connection.go:57)
AEAD_TAG_SIZE = 16
SEALED_FRAME_SIZE = TOTAL_FRAME_SIZE + AEAD_TAG_SIZE
NONCE_SIZE = 12

_HANDSHAKE_TIMEOUT = 10.0


class ErrHandshake(Exception):
    pass


def _hkdf(secret: bytes, info: bytes, length: int) -> bytes:
    """HKDF-SHA256 (RFC 5869), extract with empty salt + expand."""
    prk = hmac.new(b"\x00" * 32, secret, hashlib.sha256).digest()
    out = b""
    block = b""
    counter = 1
    while len(out) < length:
        block = hmac.new(prk, block + info + bytes([counter]), hashlib.sha256).digest()
        out += block
        counter += 1
    return out[:length]


def derive_secrets(dh_secret: bytes, loc_is_least: bool) -> tuple[bytes, bytes]:
    """secret_connection.go:335-364 deriveSecrets: HKDF-SHA256 over the raw
    DH secret. The party with the lexicographically smaller ephemeral
    pubkey receives with the first key; the other side mirrors."""
    okm = _hkdf(dh_secret, b"TENDERMINT_SECRET_CONNECTION_KEY_AND_CHALLENGE_GEN", 96)
    if loc_is_least:
        recv_key, send_key = okm[0:32], okm[32:64]
    else:
        send_key, recv_key = okm[0:32], okm[32:64]
    return recv_key, send_key


def handshake_challenge(lo_eph: bytes, hi_eph: bytes, dh_secret: bytes) -> bytes:
    """The 32-byte sign-me challenge (secret_connection.go:111-135): a
    Merlin transcript binding both ephemeral keys (sorted) and the DH
    secret."""
    from cometbft_tpu.crypto.sr25519_math import Transcript

    t = Transcript(b"TENDERMINT_SECRET_CONNECTION_TRANSCRIPT_HASH")
    t.append_message(b"EPHEMERAL_LOWER_PUBLIC_KEY", lo_eph)
    t.append_message(b"EPHEMERAL_UPPER_PUBLIC_KEY", hi_eph)
    t.append_message(b"DH_SECRET", dh_secret)
    return t.challenge_bytes(b"SECRET_CONNECTION_MAC", 32)


class _NonceCounter:
    """96-bit little-endian counter nonce (secret_connection.go:57-60)."""

    __slots__ = ("_n",)

    def __init__(self) -> None:
        self._n = 0

    def next(self) -> bytes:
        n = self._n
        self._n += 1
        if self._n >= 1 << 64:
            # the reference rekeys long before this; we hard-fail
            raise OverflowError("nonce counter exhausted")
        return struct.pack("<4xQ", n)


class SecretConnection:
    """Wraps an (asyncio.StreamReader, StreamWriter) pair."""

    def __init__(
        self,
        reader: asyncio.StreamReader,
        writer: asyncio.StreamWriter,
        send_aead: ChaCha20Poly1305,
        recv_aead: ChaCha20Poly1305,
        remote_pubkey: ed25519.PubKey,
    ):
        self._reader = reader
        self._writer = writer
        self._send_aead = send_aead
        self._recv_aead = recv_aead
        self._send_nonce = _NonceCounter()
        self._recv_nonce = _NonceCounter()
        self.remote_pubkey = remote_pubkey
        self._recv_buf = b""
        self._send_lock = asyncio.Lock()

    @property
    def remote_host(self) -> str:
        """The remote SOCKET host — unforgeable, unlike any address the
        peer self-reports; the PEX address book keys its hashed-bucket
        source attribution on this."""
        try:
            peername = self._writer.get_extra_info("peername")
            return peername[0] if peername else ""
        except Exception:  # noqa: BLE001 - telemetry, never raises
            return ""

    # -------------------------------------------------------- handshake

    @classmethod
    async def make(
        cls,
        reader: asyncio.StreamReader,
        writer: asyncio.StreamWriter,
        priv_key: ed25519.PrivKey,
    ) -> "SecretConnection":
        """MakeSecretConnection (secret_connection.go:71-130)."""
        from cometbft_tpu.utils import protobuf as pb

        if _HAVE_OPENSSL:
            eph_priv = X25519PrivateKey.generate()
            eph_pub = eph_priv.public_key().public_bytes_raw()
        else:
            import secrets as _secrets

            from cometbft_tpu.crypto import fallback as _fb

            eph_seed = _secrets.token_bytes(32)
            eph_priv = None
            eph_pub = _fb.x25519(eph_seed, _fb.X25519_BASEPOINT)

        # 1. concurrent ephemeral pubkey exchange as varint-delimited
        #    google.protobuf.BytesValue (secret_connection.go shareEphPubKey)
        bv = pb.Writer().bytes(1, eph_pub).output()
        writer.write(pb.marshal_delimited(bv))
        await writer.drain()
        rem_eph_pub = await asyncio.wait_for(
            _read_bytes_value(reader), _HANDSHAKE_TIMEOUT
        )
        if len(rem_eph_pub) != 32:
            raise ErrHandshake("bad ephemeral pubkey length")

        # 2. DH; session keys via HKDF on the raw DH secret; the sign-me
        #    challenge from the Merlin transcript (secret_connection.go:
        #    111-135)
        if eph_priv is not None:
            dh_secret = eph_priv.exchange(
                X25519PublicKey.from_public_bytes(rem_eph_pub))
        else:
            dh_secret = _fb.x25519(eph_seed, rem_eph_pub)
        loc_is_least = eph_pub < rem_eph_pub
        lo, hi = sorted((eph_pub, rem_eph_pub))
        recv_key, send_key = derive_secrets(dh_secret, loc_is_least)
        challenge = handshake_challenge(lo, hi, dh_secret)
        conn = cls(
            reader,
            writer,
            ChaCha20Poly1305(send_key),
            ChaCha20Poly1305(recv_key),
            remote_pubkey=None,  # set below
        )

        # 3. authenticate: varint-delimited tendermint.p2p.AuthSigMessage
        #    {pub_key=1 (crypto.PublicKey oneof ed25519=1), sig=2} over the
        #    now-encrypted channel (secret_connection.go:155-175)
        sig = priv_key.sign(challenge)
        pk = pb.Writer().bytes(1, priv_key.pub_key().bytes_(), always=True)
        auth_msg = (pb.Writer()
                    .message(1, pk.output(), always=True)
                    .bytes(2, sig).output())
        await conn.write(pb.marshal_delimited(auth_msg))
        auth = await asyncio.wait_for(
            conn.read_delimited(1 << 20), _HANDSHAKE_TIMEOUT)
        rem_pub_bytes, rem_sig = _parse_auth_sig(auth)
        rem_pub = ed25519.PubKey(rem_pub_bytes)
        if not rem_pub.verify_signature(challenge, rem_sig):
            raise ErrHandshake("challenge verification failed")
        conn.remote_pubkey = rem_pub
        return conn

    # ------------------------------------------------------------ frames

    async def write(self, data: bytes) -> int:
        """Chunk into sealed frames (secret_connection.go:224-262). Empty
        writes send nothing (an empty frame would read as EOF on the far
        side)."""
        n = len(data)
        if n == 0:
            return 0
        async with self._send_lock:
            frames = bytearray()
            for off in range(0, len(data), DATA_MAX_SIZE):
                chunk = data[off : off + DATA_MAX_SIZE]
                frame = struct.pack("<I", len(chunk)) + chunk
                frame += b"\x00" * (TOTAL_FRAME_SIZE - len(frame))
                frames += self._send_aead.encrypt(self._send_nonce.next(), bytes(frame), None)
            self._writer.write(bytes(frames))
            await self._writer.drain()
        return n

    async def _read_frame(self) -> bytes:
        sealed = await self._reader.readexactly(SEALED_FRAME_SIZE)
        try:
            frame = self._recv_aead.decrypt(self._recv_nonce.next(), sealed, None)
        except InvalidTag as e:
            raise ErrHandshake("frame decryption failed") from e
        (n,) = struct.unpack_from("<I", frame)
        if n > DATA_MAX_SIZE:
            raise ErrHandshake("frame length header exceeds max")
        return frame[DATA_LEN_SIZE : DATA_LEN_SIZE + n]

    async def read(self, n: int) -> bytes:
        """Read up to n plaintext bytes (one buffered frame at a time)."""
        if not self._recv_buf:
            self._recv_buf = await self._read_frame()
        out, self._recv_buf = self._recv_buf[:n], self._recv_buf[n:]
        return out

    async def readexactly(self, n: int) -> bytes:
        out = bytearray()
        while len(out) < n:
            chunk = await self.read(n - len(out))
            if not chunk:
                raise asyncio.IncompleteReadError(bytes(out), n)
            out += chunk
        return bytes(out)

    # ------------------------------------------ varint-delimited msgs
    # (libs/protoio framing — what the reference speaks over the secret
    # channel for AuthSigMessage and the NodeInfo handshake)

    async def write_msg(self, msg: bytes) -> None:
        from cometbft_tpu.utils.protobuf import marshal_delimited

        await self.write(marshal_delimited(msg))

    async def read_delimited(self, max_size: int = 1 << 22) -> bytes:
        from cometbft_tpu.abci.proto_codec import read_delimited_async

        try:
            return await read_delimited_async(self, max_size=max_size)
        except ValueError as e:
            raise ErrHandshake(str(e)) from e

    async def read_msg(self, max_size: int = 1 << 22) -> bytes:
        return await self.read_delimited(max_size)

    def close(self) -> None:
        try:
            self._writer.close()
        except Exception:  # noqa: BLE001 - best-effort close
            pass


async def _read_bytes_value(reader: asyncio.StreamReader) -> bytes:
    """One varint-delimited google.protobuf.BytesValue {value=1: bytes}
    from the raw stream (the pre-encryption ephemeral-key exchange)."""
    from cometbft_tpu.abci.proto_codec import read_delimited_async
    from cometbft_tpu.utils import protobuf as pb

    try:
        body = await read_delimited_async(reader, max_size=64)
    except ValueError as e:
        raise ErrHandshake(str(e)) from e
    r = pb.Reader(body)
    val = b""
    while not r.at_end():
        f, w = r.read_tag()
        if f == 1:
            val = r.read_bytes()
        else:
            r.skip(w)
    return val


def _parse_auth_sig(data: bytes) -> tuple[bytes, bytes]:
    """tendermint.p2p.AuthSigMessage -> (ed25519 pubkey bytes, signature).
    Only the ed25519 oneof arm is accepted (the framework's node identity
    key type, as in the reference's default)."""
    from cometbft_tpu.utils import protobuf as pb

    r = pb.Reader(data)
    pub, sig = b"", b""
    while not r.at_end():
        f, w = r.read_tag()
        if f == 1:
            pk = pb.Reader(r.read_bytes())
            while not pk.at_end():
                kf, kw = pk.read_tag()
                if kf == 1:  # crypto.PublicKey oneof: ed25519
                    pub = pk.read_bytes()
                else:
                    pk.skip(kw)
        elif f == 2:
            sig = r.read_bytes()
        else:
            r.skip(w)
    if len(pub) != 32 or len(sig) != 64:
        raise ErrHandshake("bad auth message")
    return pub, sig
