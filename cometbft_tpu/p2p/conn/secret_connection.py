"""Authenticated encrypted connection (the p2p wire security layer).

Reference: p2p/conn/secret_connection.go:34-49 — a Station-to-Station
handshake: X25519 ephemeral ECDH -> transcript-bound KDF -> two
ChaCha20-Poly1305 session keys (one per direction) + a challenge that each
side signs with its long-lived ed25519 node key, proving identity. Data
flows in fixed-size sealed frames (1024 data bytes + 4-byte length header)
with 96-bit little-endian counter nonces, one counter per direction
(secret_connection.go:57-60,224-292).

Design deltas from the reference (capability-preserving, documented):
- the transcript is HMAC-SHA256-based HKDF over a SHA-256 transcript hash
  rather than a Merlin/STROBE transcript — same binding (both ephemeral
  pubkeys, sorted, plus the DH secret feed the KDF), standard primitives.
- handshake messages are length-prefixed raw frames, not proto envelopes.

Frames after the handshake are byte-compatible in *shape* with the
reference (sealed 1028-byte chunks), so the flow-control numbers in
MConnection carry over.
"""

from __future__ import annotations

import asyncio
import hashlib
import hmac
import struct

from cryptography.exceptions import InvalidTag
from cryptography.hazmat.primitives.asymmetric.x25519 import (
    X25519PrivateKey,
    X25519PublicKey,
)
from cryptography.hazmat.primitives.ciphers.aead import ChaCha20Poly1305

from cometbft_tpu.crypto import ed25519

DATA_LEN_SIZE = 4
DATA_MAX_SIZE = 1024
TOTAL_FRAME_SIZE = DATA_MAX_SIZE + DATA_LEN_SIZE  # 1028 (connection.go:57)
AEAD_TAG_SIZE = 16
SEALED_FRAME_SIZE = TOTAL_FRAME_SIZE + AEAD_TAG_SIZE
NONCE_SIZE = 12

_HANDSHAKE_TIMEOUT = 10.0


class ErrHandshake(Exception):
    pass


def _hkdf(secret: bytes, info: bytes, length: int) -> bytes:
    """HKDF-SHA256 (RFC 5869), extract with empty salt + expand."""
    prk = hmac.new(b"\x00" * 32, secret, hashlib.sha256).digest()
    out = b""
    block = b""
    counter = 1
    while len(out) < length:
        block = hmac.new(prk, block + info + bytes([counter]), hashlib.sha256).digest()
        out += block
        counter += 1
    return out[:length]


def derive_secrets(dh_secret: bytes, loc_is_least: bool) -> tuple[bytes, bytes, bytes]:
    """secret_connection.go:224-258 deriveSecretAndChallenge: expand the DH
    secret into recv_key, send_key, challenge. The party with the
    lexicographically smaller ephemeral pubkey receives with the first key;
    the other side mirrors."""
    okm = _hkdf(dh_secret, b"TENDERMINT_SECRET_CONNECTION_KEY_AND_CHALLENGE_GEN", 96)
    if loc_is_least:
        recv_key, send_key = okm[0:32], okm[32:64]
    else:
        send_key, recv_key = okm[0:32], okm[32:64]
    challenge = okm[64:96]
    return recv_key, send_key, challenge


class _NonceCounter:
    """96-bit little-endian counter nonce (secret_connection.go:57-60)."""

    __slots__ = ("_n",)

    def __init__(self) -> None:
        self._n = 0

    def next(self) -> bytes:
        n = self._n
        self._n += 1
        if self._n >= 1 << 64:
            # the reference rekeys long before this; we hard-fail
            raise OverflowError("nonce counter exhausted")
        return struct.pack("<4xQ", n)


class SecretConnection:
    """Wraps an (asyncio.StreamReader, StreamWriter) pair."""

    def __init__(
        self,
        reader: asyncio.StreamReader,
        writer: asyncio.StreamWriter,
        send_aead: ChaCha20Poly1305,
        recv_aead: ChaCha20Poly1305,
        remote_pubkey: ed25519.PubKey,
    ):
        self._reader = reader
        self._writer = writer
        self._send_aead = send_aead
        self._recv_aead = recv_aead
        self._send_nonce = _NonceCounter()
        self._recv_nonce = _NonceCounter()
        self.remote_pubkey = remote_pubkey
        self._recv_buf = b""
        self._send_lock = asyncio.Lock()

    # -------------------------------------------------------- handshake

    @classmethod
    async def make(
        cls,
        reader: asyncio.StreamReader,
        writer: asyncio.StreamWriter,
        priv_key: ed25519.PrivKey,
    ) -> "SecretConnection":
        """MakeSecretConnection (secret_connection.go:71-130)."""
        eph_priv = X25519PrivateKey.generate()
        eph_pub = eph_priv.public_key().public_bytes_raw()

        # 1. concurrent ephemeral pubkey exchange (go: cmtasync.Parallel)
        writer.write(struct.pack(">I", len(eph_pub)) + eph_pub)
        await writer.drain()
        rem_eph_pub = await asyncio.wait_for(
            _read_prefixed(reader), _HANDSHAKE_TIMEOUT
        )
        if len(rem_eph_pub) != 32:
            raise ErrHandshake("bad ephemeral pubkey length")

        # 2. DH + transcript-ordered key derivation
        dh_secret = eph_priv.exchange(X25519PublicKey.from_public_bytes(rem_eph_pub))
        loc_is_least = eph_pub < rem_eph_pub
        lo, hi = sorted((eph_pub, rem_eph_pub))
        transcript = hashlib.sha256(b"SECRET_CONNECTION" + lo + hi).digest()
        recv_key, send_key, challenge = derive_secrets(
            _hkdf(dh_secret + transcript, b"DH_TRANSCRIPT_BIND", 32), loc_is_least
        )
        conn = cls(
            reader,
            writer,
            ChaCha20Poly1305(send_key),
            ChaCha20Poly1305(recv_key),
            remote_pubkey=None,  # set below
        )

        # 3. authenticate: exchange (pubkey, sig(challenge)) over the
        #    now-encrypted channel (secret_connection.go:113-127)
        sig = priv_key.sign(challenge)
        await conn.write_msg(priv_key.pub_key().bytes_() + sig)
        auth = await asyncio.wait_for(conn.read_msg(), _HANDSHAKE_TIMEOUT)
        if len(auth) != 32 + 64:
            raise ErrHandshake("bad auth message length")
        rem_pub = ed25519.PubKey(auth[:32])
        if not rem_pub.verify_signature(challenge, auth[32:]):
            raise ErrHandshake("challenge verification failed")
        conn.remote_pubkey = rem_pub
        return conn

    # ------------------------------------------------------------ frames

    async def write(self, data: bytes) -> int:
        """Chunk into sealed frames (secret_connection.go:224-262). Empty
        writes send nothing (an empty frame would read as EOF on the far
        side)."""
        n = len(data)
        if n == 0:
            return 0
        async with self._send_lock:
            frames = bytearray()
            for off in range(0, len(data), DATA_MAX_SIZE):
                chunk = data[off : off + DATA_MAX_SIZE]
                frame = struct.pack("<I", len(chunk)) + chunk
                frame += b"\x00" * (TOTAL_FRAME_SIZE - len(frame))
                frames += self._send_aead.encrypt(self._send_nonce.next(), bytes(frame), None)
            self._writer.write(bytes(frames))
            await self._writer.drain()
        return n

    async def _read_frame(self) -> bytes:
        sealed = await self._reader.readexactly(SEALED_FRAME_SIZE)
        try:
            frame = self._recv_aead.decrypt(self._recv_nonce.next(), sealed, None)
        except InvalidTag as e:
            raise ErrHandshake("frame decryption failed") from e
        (n,) = struct.unpack_from("<I", frame)
        if n > DATA_MAX_SIZE:
            raise ErrHandshake("frame length header exceeds max")
        return frame[DATA_LEN_SIZE : DATA_LEN_SIZE + n]

    async def read(self, n: int) -> bytes:
        """Read up to n plaintext bytes (one buffered frame at a time)."""
        if not self._recv_buf:
            self._recv_buf = await self._read_frame()
        out, self._recv_buf = self._recv_buf[:n], self._recv_buf[n:]
        return out

    async def readexactly(self, n: int) -> bytes:
        out = bytearray()
        while len(out) < n:
            chunk = await self.read(n - len(out))
            if not chunk:
                raise asyncio.IncompleteReadError(bytes(out), n)
            out += chunk
        return bytes(out)

    # ---------------------------------------------- length-prefixed msgs

    async def write_msg(self, msg: bytes) -> None:
        await self.write(struct.pack(">I", len(msg)) + msg)

    async def read_msg(self, max_size: int = 1 << 22) -> bytes:
        hdr = await self.readexactly(4)
        (n,) = struct.unpack(">I", hdr)
        if n > max_size:
            raise ErrHandshake(f"message size {n} exceeds max {max_size}")
        return await self.readexactly(n)

    def close(self) -> None:
        try:
            self._writer.close()
        except Exception:  # noqa: BLE001 - best-effort close
            pass


async def _read_prefixed(reader: asyncio.StreamReader) -> bytes:
    hdr = await reader.readexactly(4)
    (n,) = struct.unpack(">I", hdr)
    if n > 64:
        raise ErrHandshake("oversized handshake message")
    return await reader.readexactly(n)
