"""TCP transport: listen/dial + connection upgrade.

Reference: p2p/transport.go — the MultiplexTransport accepts/dials raw TCP,
then "upgrades": SecretConnection handshake (authenticates the remote
ed25519 key), NodeInfo exchange, and compatibility/identity checks. The
upgraded bundle goes to the Switch to become a Peer.
"""

from __future__ import annotations

import asyncio
from dataclasses import dataclass

from cometbft_tpu.libs import chaos
from cometbft_tpu.libs import log as cmtlog
from cometbft_tpu.p2p import netchaos
from cometbft_tpu.p2p.conn.secret_connection import SecretConnection
from cometbft_tpu.p2p.key import NodeKey, node_id_from_pubkey
from cometbft_tpu.p2p.node_info import NodeInfo

HANDSHAKE_TIMEOUT = 10.0


class ErrRejected(Exception):
    """Connection rejected during upgrade (transport.go ErrRejected)."""


@dataclass
class UpgradedConn:
    conn: SecretConnection
    node_info: NodeInfo
    outbound: bool


def parse_addr(addr: str) -> tuple[str, str, int]:
    """'id@host:port' -> (id, host, port); id may be empty."""
    node_id = ""
    if "@" in addr:
        node_id, addr = addr.split("@", 1)
    host, _, port = addr.rpartition(":")
    return node_id, host or "127.0.0.1", int(port)


class Transport:
    def __init__(
        self,
        node_key: NodeKey,
        node_info: NodeInfo,
        logger: cmtlog.Logger | None = None,
        fuzz_config=None,
    ):
        self.node_key = node_key
        self.node_info = node_info
        self.logger = logger or cmtlog.nop()
        # optional (node_id) -> bool ban probe, wired by the Switch: a
        # banned peer is refused at the handshake, so its dialer sees a
        # clean dial failure instead of an add-then-drop conn churn
        self.is_banned = None
        self._server: asyncio.Server | None = None
        self._accept_queue: asyncio.Queue[UpgradedConn] = asyncio.Queue(64)
        # in-flight inbound upgrades: server.close() only stops LISTENING;
        # handlers mid-handshake must be cancelled at close or they leak
        self._inbound_tasks: set[asyncio.Task] = set()
        # p2p.FuzzConnConfig | None: wrap every raw conn in the fault
        # injector before upgrade (transport.go:221-223 TestFuzz)
        self.fuzz_config = fuzz_config

    # ------------------------------------------------------------- listen

    async def listen(self, laddr: str) -> str:
        """Start the TCP listener; returns the bound 'host:port'."""
        _, host, port = parse_addr(laddr)
        self._server = await asyncio.start_server(self._handle_inbound, host, port)
        sock = self._server.sockets[0]
        bound = sock.getsockname()
        addr = f"{bound[0]}:{bound[1]}"
        self.node_info.listen_addr = addr
        self.logger.info("p2p listening", addr=addr)
        return addr

    async def _handle_inbound(
        self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter
    ) -> None:
        task = asyncio.current_task()
        if task is not None:
            self._inbound_tasks.add(task)
            task.add_done_callback(self._inbound_tasks.discard)
        try:
            chaos.fire("net.accept")
            up = await asyncio.wait_for(
                self._upgrade(reader, writer, outbound=False, expect_id=""),
                HANDSHAKE_TIMEOUT,
            )
        except asyncio.CancelledError:  # transport closing
            writer.close()
            raise
        except Exception as e:  # noqa: BLE001 - a bad dialer must not kill the listener
            self.logger.info("inbound upgrade failed", err=str(e))
            writer.close()
            return
        try:
            await self._accept_queue.put(up)
        except asyncio.CancelledError:  # cancelled while the queue was full
            up.conn.close()
            raise

    async def accept(self) -> UpgradedConn:
        """Next fully-upgraded inbound connection (transport.go Accept).
        Upgrade failures are logged in _handle_inbound, never surfaced here."""
        return await self._accept_queue.get()

    # --------------------------------------------------------------- dial

    async def dial(self, addr: str) -> UpgradedConn:
        """Dial 'id@host:port' and upgrade (transport.go Dial)."""
        expect_id, host, port = parse_addr(addr)
        chaos.fire("net.dial")
        if expect_id and netchaos.dial_blocked(self.node_key.id(), expect_id):
            raise ErrRejected(f"partitioned from {expect_id[:10]} (net chaos)")
        reader, writer = await asyncio.open_connection(host, port)
        try:
            return await asyncio.wait_for(
                self._upgrade(reader, writer, outbound=True, expect_id=expect_id),
                HANDSHAKE_TIMEOUT,
            )
        except Exception:
            writer.close()
            raise

    # ------------------------------------------------------------ upgrade

    async def _upgrade(
        self,
        reader: asyncio.StreamReader,
        writer: asyncio.StreamWriter,
        outbound: bool,
        expect_id: str,
    ) -> UpgradedConn:
        if self.fuzz_config is not None:
            from cometbft_tpu.p2p.fuzz import fuzz_streams

            reader, writer = fuzz_streams(reader, writer, self.fuzz_config)
        chaos.fire("net.handshake")
        sconn = await SecretConnection.make(reader, writer, self.node_key.priv_key)
        authed_id = node_id_from_pubkey(sconn.remote_pubkey)
        if netchaos.dial_blocked(self.node_key.id(), authed_id):
            raise ErrRejected(
                f"partitioned from {authed_id[:10]} (net chaos)")
        if self.is_banned is not None and self.is_banned(authed_id):
            raise ErrRejected(f"peer {authed_id[:10]} is banned")
        if expect_id and authed_id != expect_id:
            raise ErrRejected(
                f"dialed {expect_id[:10]} but authenticated as {authed_id[:10]}"
            )
        # NodeInfo exchange over the encrypted channel (transport.go:455)
        await sconn.write_msg(self.node_info.encode())
        their_info = NodeInfo.decode(await sconn.read_msg(max_size=10240))
        their_info.validate()
        if their_info.node_id != authed_id:
            raise ErrRejected("node info id does not match authenticated key")
        if their_info.node_id == self.node_info.node_id:
            raise ErrRejected("self connection")
        self.node_info.compatible_with(their_info)
        return UpgradedConn(conn=sconn, node_info=their_info, outbound=outbound)

    def close(self) -> None:
        if self._server is not None:
            self._server.close()
        for t in list(self._inbound_tasks):
            t.cancel()
        # upgraded conns parked in the accept queue would otherwise leak
        # their sockets once nothing will ever accept() them
        while True:
            try:
                self._accept_queue.get_nowait().conn.close()
            except asyncio.QueueEmpty:
                break
