"""Multi-chip scaling of signature verification.

Two planes (parallel/mesh.py):

- `sharded_verify_batch` — the SPMD shard_map data plane: one program
  over a 1-D "sig" mesh, fastest for one healthy batch over N healthy
  chips (the bench scaling probe), fragile to any single device fault.
- `VerifyMesh` — the fault-tolerant production plane the VerifyScheduler
  routes through: per-chip fault domains (one DeviceSupervisor/
  CircuitBreaker per chip), class-aware placement, shrink/grow
  re-sharding with in-flight shard redispatch, and an all-chips-dead
  fallback onto the single-chip TPU->XLA->CPU ladder.

The reference's only scaling dimension is signatures-per-verification-
call (SURVEY.md §5.7); here the batch ("sig") axis is the scaling
dimension — verification is embarrassingly parallel, so every chip
verifies its slice of lanes independently.
"""

from cometbft_tpu.parallel.mesh import (  # noqa: F401
    VerifyMesh,
    batch_mesh,
    shard_verify_kernel,
    sharded_verify_batch,
)
