"""Multi-chip scaling of signature mega-batches.

The reference's only scaling dimension is signatures-per-verification-call
(SURVEY.md §5.7): validator-set size (cap 10k) x commits in flight
(blocksync pipelines up to 600 heights). Here a mega-batch is sharded over a
1-D `jax.sharding.Mesh` along the batch ("sig") axis with shard_map — each
chip verifies its slice of lanes independently (verification is
embarrassingly parallel; the only collective is the implicit result
gather). ICI carries the shards; DCN is irrelevant at <=10k-sig batches.
"""

from cometbft_tpu.parallel.mesh import (  # noqa: F401
    batch_mesh,
    shard_verify_kernel,
    sharded_verify_batch,
)
