"""Device-mesh sharding for the verification kernels.

One mesh axis ("sig") over all chips; every kernel input is staged batch-
minor so sharding is a single PartitionSpec on the lane axis. shard_map
runs the per-chip program; XLA inserts the (trivial) collectives. This is
the ICI data plane that replaces nothing in the reference — the Go engine
has no multi-device compute at all (SURVEY.md §2.3) — and is the path to
>1-chip commit-verification throughput.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from cometbft_tpu.ops import ed25519_kernel as K

SIG_AXIS = "sig"


def batch_mesh(devices: list | None = None) -> Mesh:
    """1-D mesh over the given (or all) devices, axis name 'sig'."""
    if devices is None:
        devices = jax.devices()
    return Mesh(np.array(devices), axis_names=(SIG_AXIS,))


@functools.lru_cache(maxsize=8)
def shard_verify_kernel(mesh: Mesh):
    """jit-compiled shard_map of the verify program over `mesh`. The lane
    (batch) axis must divide the mesh size; bucket padding guarantees
    power-of-two batches. Inputs follow ed25519_kernel.verify_math:
    4x A-coords (20, B) int32, then r/s/k packed words (8, B) uint32."""
    spec_tail = P(None, SIG_AXIS)
    in_specs = (spec_tail,) * 7
    out_specs = P(SIG_AXIS)
    # jax.shard_map graduated from jax.experimental in newer releases;
    # support both so the mesh path runs on whatever jax the host bakes in
    shard_map = getattr(jax, "shard_map", None)
    if shard_map is None:
        from jax.experimental.shard_map import shard_map
    fn = shard_map(
        K.verify_math, mesh=mesh, in_specs=in_specs, out_specs=out_specs
    )
    return jax.jit(fn)


def _mesh_bucket(n: int, n_dev: int) -> int:
    b = K.bucket_size(n)
    if b % n_dev:
        b = ((b + n_dev - 1) // n_dev) * n_dev
    return b


def sharded_verify_batch(
    pubs: list[bytes],
    msgs: list[bytes],
    sigs: list[bytes],
    mesh: Mesh | None = None,
    cache: K.PubKeyCache | None = None,
) -> tuple[bool, list[bool]]:
    """Multi-chip analog of ops.ed25519_kernel.verify_batch: same host glue
    (structural checks, SHA-512 challenges, bucket padding — shared via
    stage_batch), with the device batch sharded over the mesh's 'sig'
    axis."""
    n = len(sigs)
    if n == 0:
        return True, []
    if mesh is None:
        mesh = batch_mesh()
    n_dev = mesh.devices.size
    cache = cache or K._default_cache

    b = _mesh_bucket(n, n_dev)
    pre_ok, safe_pubs, r_words, s_words, k_words = K.stage_batch(pubs, msgs, sigs, b)

    tail = NamedSharding(mesh, P(None, SIG_AXIS))
    put = functools.partial(jax.device_put, device=tail)
    # stable cache key: device ids, not id(mesh) (addresses get reused)
    mesh_key = "mesh-" + ",".join(str(d.id) for d in mesh.devices.flat)
    ok_a, a_dev = cache.stage(safe_pubs, b, put=put, put_key=mesh_key)
    fn = shard_verify_kernel(mesh)
    mask_dev = fn(
        *a_dev,
        jax.device_put(r_words, tail),
        jax.device_put(s_words, tail),
        jax.device_put(k_words, tail),
    )
    mask = np.asarray(mask_dev)[:n] & pre_ok & ok_a
    return bool(mask.all()), mask.tolist()
