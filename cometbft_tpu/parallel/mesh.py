"""Device-mesh sharding for the verification kernels.

One mesh axis ("sig") over all chips; every kernel input is batch-major so
sharding is a single PartitionSpec("sig") on dim 0. shard_map runs the
per-chip program; XLA inserts the (trivial) collectives. This is the ICI
data plane that replaces nothing in the reference — the Go engine has no
multi-device compute at all (SURVEY.md §2.3) — and is the path to >1-chip
commit-verification throughput.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from cometbft_tpu.ops import curve
from cometbft_tpu.ops import ed25519_kernel as K
from cometbft_tpu.ops import limbs as L

SIG_AXIS = "sig"


def batch_mesh(devices: list | None = None) -> Mesh:
    """1-D mesh over the given (or all) devices, axis name 'sig'."""
    if devices is None:
        devices = jax.devices()
    return Mesh(np.array(devices), axis_names=(SIG_AXIS,))


def _per_chip_verify(ax, ay, az, at, ok_a, y_r, sign_r, s_bits, k_bits):
    """The single-chip verify program, run on each mesh shard. Identical
    math to ops.ed25519_kernel._verify_kernel."""
    ok_r, r = curve.decompress_zip215(y_r, sign_r)
    neg_a = curve.neg(curve.Point(ax, ay, az, at))
    sb_ka = curve.straus_base_and_point(s_bits, k_bits, neg_a)
    diff = curve.add(sb_ka, curve.neg(r))
    valid = curve.is_identity(curve.mul_by_cofactor(diff))
    return valid & ok_a & ok_r


@functools.lru_cache(maxsize=8)
def shard_verify_kernel(mesh: Mesh):
    """jit-compiled shard_map of the verify program over `mesh`. Batch dim
    must divide the mesh size; ed25519_kernel's bucket padding guarantees
    power-of-two batches."""
    # batch axis is trailing for limb/bit arrays (limb-axis-first layout),
    # leading for the per-lane flags
    spec_tail = P(None, SIG_AXIS)
    spec_flat = P(SIG_AXIS)
    in_specs = (
        spec_tail,  # ax (20, B)
        spec_tail,  # ay
        spec_tail,  # az
        spec_tail,  # at
        spec_flat,  # ok_a (B,)
        spec_tail,  # y_r (20, B)
        spec_flat,  # sign_r (B,)
        spec_tail,  # s_bits (253, B)
        spec_tail,  # k_bits (253, B)
    )
    out_specs = spec_flat
    fn = jax.shard_map(
        _per_chip_verify, mesh=mesh, in_specs=in_specs, out_specs=out_specs
    )
    return jax.jit(fn)


def sharded_verify_batch(
    pubs: list[bytes],
    msgs: list[bytes],
    sigs: list[bytes],
    mesh: Mesh | None = None,
) -> tuple[bool, list[bool]]:
    """Multi-chip analog of ops.ed25519_kernel.verify_batch: same host glue
    (structural checks, SHA-512 challenges, bucket padding), with the device
    batch sharded over the mesh's 'sig' axis."""
    n = len(sigs)
    if n == 0:
        return True, []
    if mesh is None:
        mesh = batch_mesh()
    n_dev = mesh.devices.size

    import hashlib

    from cometbft_tpu.crypto import ed25519_math as oracle

    pre_ok = np.ones(n, dtype=bool)
    s_vals = [0] * n
    for i, (pub, sig) in enumerate(zip(pubs, sigs)):
        if len(pub) != 32 or len(sig) != 64:
            pre_ok[i] = False
            continue
        s = int.from_bytes(sig[32:], "little")
        if s >= oracle.L:
            pre_ok[i] = False
            continue
        s_vals[i] = s

    safe_pubs = [p if pre_ok[i] else b"\x01" + b"\x00" * 31 for i, p in enumerate(pubs)]
    safe_rs = [sigs[i][:32] if pre_ok[i] else b"\x01" + b"\x00" * 31 for i in range(n)]
    ks = []
    for i, (pub, msg, sig) in enumerate(zip(safe_pubs, msgs, sigs)):
        if not pre_ok[i]:
            ks.append(0)
            continue
        h = hashlib.sha512()
        h.update(sig[:32])
        h.update(pub)
        h.update(msg)
        ks.append(int.from_bytes(h.digest(), "little") % oracle.L)

    # bucket to a multiple of the device count (power-of-two covers it when
    # n_dev is a power of two; otherwise round up explicitly)
    b = K.bucket_size(n)
    if b % n_dev:
        b = ((b + n_dev - 1) // n_dev) * n_dev
    pad = b - n

    ok_a, a_coords = K._default_cache.lookup_or_decompress(safe_pubs)
    r_enc = np.frombuffer(b"".join(safe_rs), dtype=np.uint8).reshape(n, 32)
    y_r, sign_r = L.encodings_to_point_inputs(r_enc)
    s_bits = L.scalars_to_bits(s_vals)
    k_bits = L.scalars_to_bits(ks)

    if pad:
        id_y = np.zeros((pad, L.NLIMBS), dtype=np.int32)
        id_y[:, 0] = 1
        id_coords = np.zeros((pad, 4, L.NLIMBS), dtype=np.int32)
        id_coords[:, 1, 0] = 1
        id_coords[:, 2, 0] = 1
        a_coords = np.concatenate([a_coords, id_coords])
        ok_a = np.concatenate([ok_a, np.ones(pad, dtype=bool)])
        y_r = np.concatenate([y_r, id_y])
        sign_r = np.concatenate([sign_r, np.zeros(pad, dtype=np.int32)])
        zbits = np.zeros((pad, L.SCALAR_BITS), dtype=np.int32)
        s_bits = np.concatenate([s_bits, zbits])
        k_bits = np.concatenate([k_bits, zbits])

    fn = shard_verify_kernel(mesh)
    tail = NamedSharding(mesh, P(None, SIG_AXIS))
    flat = NamedSharding(mesh, P(SIG_AXIS))
    host_args = (
        (np.ascontiguousarray(a_coords[:, 0].T), tail),
        (np.ascontiguousarray(a_coords[:, 1].T), tail),
        (np.ascontiguousarray(a_coords[:, 2].T), tail),
        (np.ascontiguousarray(a_coords[:, 3].T), tail),
        (ok_a, flat),
        (np.ascontiguousarray(y_r.T), tail),
        (sign_r, flat),
        (np.ascontiguousarray(s_bits.T), tail),
        (np.ascontiguousarray(k_bits.T), tail),
    )
    args = [jax.device_put(jnp.asarray(a), sh) for a, sh in host_args]
    mask_dev = fn(*args)
    mask = np.asarray(mask_dev)[:n] & pre_ok
    return bool(mask.all()), mask.tolist()
