"""Multi-chip verify mesh with per-chip fault domains.

Two layers live here:

1. The original shard_map data plane (batch_mesh / shard_verify_kernel /
   sharded_verify_batch): one SPMD program over a 1-D "sig" mesh. It is
   the fastest way to run ONE healthy batch over N healthy chips — and
   exactly as fragile as that sentence implies: a single device fault
   fails the whole sharded dispatch.

2. VerifyMesh — the fault-tolerant production plane. Every chip is its
   own FAULT DOMAIN with a dedicated PR 2 DeviceSupervisor/CircuitBreaker
   (registry names "mesh.devN", so the node's supervision knobs apply).
   A batch is split into per-chip shards, each dispatched as an
   independent single-device program under its chip's supervisor:

     evict       a chip whose breaker opens drops out of placement; the
                 mesh re-shards over the survivors
     redispatch  a shard in flight when its chip dies is re-dispatched
                 across the surviving chips — no verify future is ever
                 lost to a device fault
     re-probe    an open breaker whose cooldown elapsed re-enters
                 placement as the half-open probe; success readmits the
                 chip, failure re-opens it (hysteresis: transient faults
                 retry in place and never evict)
     degrade     only an ALL-chips-dead mesh falls back to the existing
                 single-chip TPU->XLA->CPU ladder (ops/ed25519_kernel /
                 ops/sr25519_kernel), which carries its own supervisor

   Placement is class-aware (the VerifyScheduler passes its batch class):
   consensus batches pin to the least-loaded chip (one dispatch, lowest
   latency — a vote flush must not pay an 8-way scatter/gather), while
   sync/mempool batches spread across all live chips for throughput.

   Chaos sites "ed25519.dispatch.devN" / "sr25519.dispatch.devN"
   (libs/chaos.py) fire inside each shard dispatch next to the plain
   scheme site, so a CBFT_CHAOS schedule can kill or flap exactly one
   fault domain deterministically.

Compile economics: each (chip, bucket) pair compiles its own executable
(the persistent compilation cache dedupes across processes). Shard
planning therefore keeps every shard on the shared bucket ladder — the
compiled-shape count is bounded by ladder-length x mesh-size, not by
traffic.
"""

from __future__ import annotations

import functools
import threading
import time as _time

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from cometbft_tpu.libs import trace as _trace
from cometbft_tpu.ops import ed25519_kernel as K

SIG_AXIS = "sig"

# placement policies (config: crypto.mesh_placement)
CLASS_AWARE = "class_aware"  # consensus pinned, sync/mempool spread
SPREAD = "spread"            # every batch spread over the live mesh
PINNED = "pinned"            # every batch on the least-loaded chip
PLACEMENTS = (CLASS_AWARE, SPREAD, PINNED)

# a spread shard below this many rows pads more than it parallelizes
MIN_SHARD_ROWS = K.MIN_BUCKET

# pinning exists for LATENCY (one dispatch for a vote flush); a batch
# bigger than this spreads even under a pin policy — the scheduler's
# rider budget scales with the live mesh size, and funneling a
# mesh-sized coalesced batch onto one chip would pay N x the per-chip
# latency pinning was meant to avoid (plus a one-off compile for a shard
# shape no single-chip path ever traces)
PIN_MAX_ROWS = 2048

# spread shards are capped too: every shard stays on the power-of-two
# end of the bucket ladder, so each chip compiles at most the 9 small
# ladder shapes instead of one giant program per mega-commit size —
# chips take multiple shards round-robin (a 100k-row commit becomes ~49
# pipelined 2048-lane shards, not 8 one-off 14336-lane executables)
MAX_SHARD_ROWS = 2048


def host_mesh_env(base_env: dict, n_devices: int) -> dict:
    """Subprocess env for an n-device CPU host mesh: JAX_PLATFORMS=cpu
    before any jax import, the axon TPU plugin stripped (it self-registers
    from PYTHONPATH, binds the real chip to whichever process initializes
    jax first, and ignores late env changes), and the host platform forced
    to n_devices. THE one copy of the axon-stripping recipe — bench's mesh
    child and the e2e chip perturbations both spawn through it."""
    import os as _os

    env = dict(base_env)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = _os.pathsep.join(
        p for p in env.get("PYTHONPATH", "").split(_os.pathsep)
        if p and "axon" not in p
    )
    for k in list(env):
        if "AXON" in k:
            del env[k]
    flags = [
        f for f in env.get("XLA_FLAGS", "").split()
        if "xla_force_host_platform_device_count" not in f
    ]
    flags.append(f"--xla_force_host_platform_device_count={n_devices}")
    env["XLA_FLAGS"] = " ".join(flags)
    env.setdefault("JAX_NUM_CPU_DEVICES", str(n_devices))
    return env


def batch_mesh(devices: list | None = None) -> Mesh:
    """1-D mesh over the given (or all) devices, axis name 'sig'."""
    if devices is None:
        devices = jax.devices()
    return Mesh(np.array(devices), axis_names=(SIG_AXIS,))


@functools.lru_cache(maxsize=8)
def shard_verify_kernel(mesh: Mesh):
    """jit-compiled shard_map of the verify program over `mesh`. The lane
    (batch) axis must divide the mesh size; bucket padding guarantees
    power-of-two batches. Inputs follow ed25519_kernel.verify_math:
    4x A-coords (20, B) int32, then r/s/k packed words (8, B) uint32."""
    spec_tail = P(None, SIG_AXIS)
    in_specs = (spec_tail,) * 7
    out_specs = P(SIG_AXIS)
    # jax.shard_map graduated from jax.experimental in newer releases;
    # support both so the mesh path runs on whatever jax the host bakes in
    shard_map = getattr(jax, "shard_map", None)
    if shard_map is None:
        from jax.experimental.shard_map import shard_map
    fn = shard_map(
        K.verify_math, mesh=mesh, in_specs=in_specs, out_specs=out_specs
    )
    return jax.jit(fn)


def _mesh_bucket(n: int, n_dev: int) -> int:
    b = K.bucket_size(n)
    if b % n_dev:
        b = ((b + n_dev - 1) // n_dev) * n_dev
    return b


def sharded_verify_batch(
    pubs: list[bytes],
    msgs: list[bytes],
    sigs: list[bytes],
    mesh: Mesh | None = None,
    cache: K.PubKeyCache | None = None,
) -> tuple[bool, list[bool]]:
    """Multi-chip analog of ops.ed25519_kernel.verify_batch: same host glue
    (structural checks, SHA-512 challenges, bucket padding — shared via
    stage_batch), with the device batch sharded over the mesh's 'sig'
    axis. SPMD, all-chips-healthy path (the bench scaling probe);
    VerifyMesh is the fault-tolerant production plane."""
    n = len(sigs)
    if n == 0:
        return True, []
    if mesh is None:
        mesh = batch_mesh()
    n_dev = mesh.devices.size
    cache = cache or K._default_cache

    b = _mesh_bucket(n, n_dev)
    pre_ok, safe_pubs, r_words, s_words, k_words = K.stage_batch(pubs, msgs, sigs, b)

    tail = NamedSharding(mesh, P(None, SIG_AXIS))
    put = functools.partial(jax.device_put, device=tail)
    # stable cache key: device ids, not id(mesh) (addresses get reused)
    mesh_key = "mesh-" + ",".join(str(d.id) for d in mesh.devices.flat)
    ok_a, a_dev = cache.stage(safe_pubs, b, put=put, put_key=mesh_key)
    fn = shard_verify_kernel(mesh)
    mask_dev = fn(
        *a_dev,
        jax.device_put(r_words, tail),
        jax.device_put(s_words, tail),
        jax.device_put(k_words, tail),
    )
    mask = np.asarray(mask_dev)[:n] & pre_ok & ok_a
    return bool(mask.all()), mask.tolist()


# ---------------------------------------------------------------------------
# VerifyMesh — per-chip fault domains
# ---------------------------------------------------------------------------


def _mesh_metrics():
    """Lazy process-global MeshMetrics; never raises (metrics must not
    break verification)."""
    try:
        from cometbft_tpu.libs import metrics as m

        return m.mesh_metrics()
    except Exception:  # noqa: BLE001
        return None


class _Chip:
    """One fault domain: a device plus its dedicated supervisor/breaker
    and the load counters placement reads."""

    __slots__ = ("index", "device", "name", "inflight_lanes", "lanes_total",
                 "shards_total")

    def __init__(self, index: int, device):
        self.index = index
        self.device = device
        self.name = f"mesh.dev{index}"
        self.inflight_lanes = 0
        self.lanes_total = 0
        self.shards_total = 0

    @property
    def supervisor(self):
        from cometbft_tpu.ops import dispatch

        return dispatch.supervisor(self.name)


class VerifyMesh:
    """The elastic multi-chip verify plane: shards bucket-ladder batches
    (ed25519 AND sr25519) across all devices, each chip its own fault
    domain. See the module docstring for the shrink/grow/redispatch
    semantics."""

    def __init__(self, devices: list | None = None,
                 placement: str = CLASS_AWARE):
        if devices is None:
            devices = jax.devices()
        if placement not in PLACEMENTS:
            raise ValueError(
                f"unknown mesh placement {placement!r} (choices: {PLACEMENTS})")
        self.chips = [_Chip(i, d) for i, d in enumerate(devices)]
        self.placement = placement
        # pubkey staging strategy: a real accelerator mesh keeps the
        # decompressed valset device-resident per chip (digest cache +
        # device-side gather — wire bytes dominate there); a forced-host
        # CPU mesh (tests, the bench child) stages coordinates host-side
        # and device_puts them directly, because every extra per-device
        # jit (gather, upload checksum) costs a compile per chip and the
        # "wire" is a memcpy
        self._device_cache = bool(devices) and devices[0].platform != "cpu"
        if self._device_cache:
            # the default device-slot budget (8) was sized for ONE chip;
            # an N-chip mesh keys entries per chip (put_key devN) and
            # per bucket, so scale the FIFO or every batch re-pays the
            # checksummed coordinate upload the cache exists to avoid
            try:
                K._default_cache.device_slots = max(
                    K._default_cache.device_slots, 4 * len(devices))
                from cometbft_tpu.ops import sr25519_kernel as SRK

                SRK._default_cache.device_slots = max(
                    SRK._default_cache.device_slots, 4 * len(devices))
            except Exception:  # noqa: BLE001 - cache sizing is advisory
                pass
        self._lock = threading.Lock()
        self._pool = None
        # eviction/readmission accounting: last observed per-chip
        # breaker-open state (state-based, so a half-open probe in flight
        # is not prematurely counted readmitted)
        self._was_open = [False] * len(self.chips)
        self.evictions = 0
        self.readmissions = 0
        self.redispatches = 0
        self.fallbacks = 0
        self.batches = 0
        self.rows_total = 0

    # ------------------------------------------------------------ plumbing

    def _executor(self):
        if self._pool is None:
            import concurrent.futures

            self._pool = concurrent.futures.ThreadPoolExecutor(
                max_workers=max(2, len(self.chips)),
                thread_name_prefix="mesh-verify")
        return self._pool

    @staticmethod
    def _scheme_ops(scheme: str) -> dict:
        # kernels: the *_ok variants are the SAME compiled programs the
        # single-chip path traces, so a mesh chip's first shard is a
        # compilation-cache hit, not a fresh per-device compile
        if scheme == "ed25519":
            from cometbft_tpu.crypto import ed25519_math as _oracle

            return {
                "stage": K.stage_batch,
                "kernel": K._verify_kernel_ok,
                "cache": lambda: K._default_cache,
                "verify_fn": _oracle.verify_zip215,
                "fallback_async": K.verify_batch_async,
            }
        if scheme == "sr25519":
            from cometbft_tpu.crypto import sr25519_math as _srm
            from cometbft_tpu.ops import sr25519_kernel as SRK

            return {
                "stage": lambda p, m, s, b, out=None: SRK.stage_rows_sr(
                    p, m, s, b, out=out),
                "kernel": SRK._verify_kernel_ok,
                "cache": lambda: SRK._default_cache,
                "verify_fn": _srm.verify,
                "fallback_async": SRK.verify_batch_async,
            }
        if scheme == "bls12381":
            from cometbft_tpu.ops import bls_kernel as BLSK

            return {
                # pairing kernels stage/dispatch through their own piece
                # pipeline — the mesh delegates the whole shard to it
                # (per-chip placement via the committed device of the
                # staged block) instead of the rw/sw/kw word contract
                "shard_verify": BLSK.mesh_shard_verify,
                "verify_fn": BLSK.oracle_verify,
                "fallback_async": BLSK.verify_batch_async,
            }
        raise ValueError(f"mesh has no verify program for scheme {scheme!r}")

    @staticmethod
    def _host_coords(cache, pubs: list[bytes],
                     bucket: int) -> tuple[np.ndarray, tuple]:
        """Host-staged A-coordinates: decompress through the scheme
        cache's host level, identity-pad + transpose via the kernel's
        shared pad_coords_batch_minor, ready for a per-chip device_put.
        The direct-path twin of ed25519_kernel._stage_gather."""
        ok_a, coords = cache.lookup_or_decompress(pubs)
        return ok_a, K.pad_coords_batch_minor(coords, bucket)

    # ------------------------------------------------------------ liveness

    def live_chips(self) -> list[_Chip]:
        """Chips whose breaker currently admits shards (peek: an OPEN
        breaker past its cooldown is included — dispatching to it IS the
        half-open re-probe that can readmit the chip). Also the
        eviction/readmission accounting site and the mesh gauges'
        publish point."""
        from cometbft_tpu.ops import dispatch as D

        live: list[_Chip] = []
        mm = _mesh_metrics()
        with self._lock:
            for chip in self.chips:
                br = chip.supervisor.breaker
                state = br.state
                is_open = state == D.OPEN
                if is_open and not self._was_open[chip.index]:
                    self.evictions += 1
                    _trace.event("mesh.evict", cat="device",
                                 device=chip.index)
                    if mm is not None:
                        try:
                            mm.mesh_evictions_total.inc()
                        except Exception:  # noqa: BLE001
                            pass
                elif self._was_open[chip.index] and not is_open:
                    self.readmissions += 1
                    _trace.event("mesh.readmit", cat="device",
                                 device=chip.index)
                    # re-seed ONLY this fault domain's reduced-send
                    # replicas: a healed chip must not serve validator
                    # tables staged before its fault, and its mesh-mates'
                    # resident sets stay untouched
                    try:
                        from cometbft_tpu.ops import residency

                        residency.invalidate_device(chip.index)
                    except Exception:  # noqa: BLE001 - never block healing
                        pass
                    if mm is not None:
                        try:
                            mm.mesh_readmissions_total.inc()
                        except Exception:  # noqa: BLE001
                            pass
                self._was_open[chip.index] = is_open
                if mm is not None:
                    try:
                        mm.mesh_breaker_state.labels(str(chip.index)).set(
                            {D.CLOSED: 0, D.HALF_OPEN: 1, D.OPEN: 2}[state])
                    except Exception:  # noqa: BLE001
                        pass
                if br.peek():
                    live.append(chip)
        if mm is not None:
            try:
                mm.verify_mesh_size.set(len(live))
                mm.mesh_devices.set(len(self.chips))
            except Exception:  # noqa: BLE001
                pass
        return live

    def live_size(self) -> int:
        return len(self.live_chips())

    def live_size_hint(self) -> int:
        """Lock-light live count for hot-path budget math (no
        eviction/readmission accounting, no gauge publishes — the
        dispatch path runs the full live_chips() scan anyway)."""
        return sum(1 for c in self.chips if c.supervisor.breaker.peek())

    # ----------------------------------------------------------- placement

    def _plan(self, m: int, klass: str,
              chips: list[_Chip]) -> list[tuple[_Chip, int, int]]:
        """Split m rows into contiguous per-chip shards. Consensus (and
        the "pinned" policy) pins the whole group to the least-loaded
        chip; everything else spreads across the live mesh, never
        creating a shard smaller than MIN_SHARD_ROWS."""
        by_load = sorted(
            chips, key=lambda c: (c.inflight_lanes, c.lanes_total, c.index))
        pin = (self.placement == PINNED or (
            self.placement == CLASS_AWARE and klass == "consensus")
        ) and m <= PIN_MAX_ROWS
        if pin or m < 2 * MIN_SHARD_ROWS or len(chips) == 1:
            return [(by_load[0], 0, m)]
        n_shards = max(1, min(len(chips), m // MIN_SHARD_ROWS))
        # shard-size cap: chips take multiple ladder-sized shards
        # round-robin instead of one giant per-chip program
        n_shards = max(n_shards, -(-m // MAX_SHARD_ROWS))
        targets = [by_load[i % len(by_load)] for i in range(n_shards)]
        out: list[tuple[_Chip, int, int]] = []
        base, rem = divmod(m, n_shards)
        lo = 0
        for i, chip in enumerate(targets):
            hi = lo + base + (1 if i < rem else 0)
            if hi > lo:
                out.append((chip, lo, hi))
            lo = hi
        return out

    # ------------------------------------------------------------ dispatch

    def _shard_op(self, ops: dict, scheme: str, chip: _Chip,
                  pubs: list, msgs: list, sigs: list):
        """One chip's shard: stage host-side, place on the chip, run the
        scheme's verify program, fetch the mask. Runs under the chip's
        supervisor (transient retry in place; failures feed its breaker).
        Returns (mask (n,), eligible (n,)).

        Known gap vs the single-chip plane: shards reuse the exact
        _verify_kernel_ok executables (a compilation-cache hit per chip)
        and therefore do NOT carry the staged-word transfer checksum of
        _integrity_parts — the host-oracle recheck still catches
        reject-direction corruption, but an accept-direction h2d bit
        flip is undetected on this path. Folding the checksum in means a
        distinct per-chip program (one executable instantiation per chip
        per shape, tens of seconds each); do it when the mesh runs over
        a real tunnel-attached pod."""
        from cometbft_tpu.libs import chaos
        from cometbft_tpu.libs import linkmodel as _linkmodel
        from cometbft_tpu.ops.dispatch import KERNEL_DISPATCH_LOCK

        chaos.fire(f"{scheme}.dispatch")
        chaos.fire(f"{scheme}.dispatch.dev{chip.index}")
        n = len(sigs)
        b = K.bucket_size(n)
        shard_verify = ops.get("shard_verify")
        if shard_verify is not None:
            # scheme-owned shard path (bls12381): the kernel stages,
            # places on this chip and fetches; the mesh keeps fault-
            # domain accounting and placement
            with _trace.span(f"{scheme}.dispatch", cat="compute",
                             lanes=b, device=chip.index):
                mask, eligible = shard_verify(chip.device, pubs, msgs, sigs)
            K._count_device_batch(scheme, b)
            mm = _mesh_metrics()
            if mm is not None:
                try:
                    mm.mesh_shard_lanes.labels(str(chip.index)).inc(b)
                except Exception:  # noqa: BLE001
                    pass
            with self._lock:
                chip.lanes_total += b
                chip.shards_total += 1
            return mask, eligible
        with _trace.span(f"{scheme}.stage", cat="stage", sig_rows=n,
                         lanes=b, device=chip.index):
            pre_ok, safe_pubs, rw, sw, kw = ops["stage"](pubs, msgs, sigs, b)
        host_arrs = None
        send_path, staging_tx = "full", 0
        # the scheme cache serializes itself (PubKeyCache._tlock): shard
        # workers, scheduler drains, and blocksync stagers all share it
        with _trace.span(f"{scheme}.stage_pubkeys", cat="transfer",
                         lanes=b, device=chip.index):
            if self._device_cache:
                # per-chip reduced-send replica: put_key carries the
                # fault-domain index, so each chip holds its own
                # resident validator table (residency.invalidate_device
                # drops exactly one replica on readmission)
                ok_a, a_dev, send_path, staging_tx = K._stage_gather(
                    ops["cache"](), safe_pubs, b,
                    put_key=f"dev{chip.index}", device=chip.device)
            else:
                ok_a, host_arrs = self._host_coords(
                    ops["cache"](), safe_pubs, b)
        # per-fault-domain in-flight gate: each chip holds its own two
        # slots, so shard N's h2d overlaps shard N-1's compute ON THE
        # SAME CHIP while a third shard queues — and a chip degraded to
        # single-buffer (chaos / device trouble) serializes only its own
        # fault domain, never its mesh siblings
        from cometbft_tpu.ops import dispatch as _dispatchmod

        with _trace.span(f"{scheme}.slot", cat="queue", lanes=b,
                         device=chip.index):
            rel = _dispatchmod.doublebuffer(f"dev{chip.index}").acquire()
        try:
            with _trace.span(f"{scheme}.h2d", cat="transfer", lanes=b,
                             device=chip.index) as sp:
                t0 = _time.perf_counter()
                rwd = jax.device_put(rw, chip.device)
                swd = jax.device_put(sw, chip.device)
                kwd = jax.device_put(kw, chip.device)
                nbytes = rw.nbytes + sw.nbytes + kw.nbytes
                if host_arrs is not None:
                    a_dev = tuple(
                        jax.device_put(a, chip.device) for a in host_arrs)
                    nbytes += sum(a.nbytes for a in host_arrs)
                jax.block_until_ready((rwd, swd, kwd) + tuple(a_dev))
                _linkmodel.tunnel().observe_transfer(
                    nbytes, _time.perf_counter() - t0)
                sp.add_bytes(tx=nbytes)
            try:
                from cometbft_tpu.ops import residency as _residency

                _residency.record_send(send_path, staging_tx + nbytes, sigs=n)
            except Exception:  # noqa: BLE001 - accounting must not break shards
                pass
            with _trace.span(f"{scheme}.dispatch", cat="compute", lanes=b,
                             device=chip.index):
                with KERNEL_DISPATCH_LOCK:
                    mask_dev, _allok = ops["kernel"](*a_dev, rwd, swd, kwd)
        finally:
            rel()
        with _trace.span(f"{scheme}.d2h", cat="fetch",
                         device=chip.index) as sp:
            mask = np.asarray(mask_dev)
            sp.add_bytes(rx=mask.nbytes)
        K._count_device_batch(scheme, b)
        mm = _mesh_metrics()
        if mm is not None:
            try:
                mm.mesh_shard_lanes.labels(str(chip.index)).inc(b)
            except Exception:  # noqa: BLE001
                pass
        with self._lock:
            chip.lanes_total += b
            chip.shards_total += 1
        eligible = pre_ok & ok_a
        return mask[:n] & eligible, eligible

    def _submit_round(self, ops: dict, scheme: str, rows: tuple,
                      idx: np.ndarray, klass: str, chips: list[_Chip]):
        """Shard idx's rows over `chips` and submit every shard to the
        mesh pool. Returns [(chip, sub_idx, future)]."""
        pubs, msgs, sigs = rows
        submitted = []
        for chip, lo, hi in self._plan(len(idx), klass, chips):
            sub_idx = idx[lo:hi]
            sub_pubs = [pubs[i] for i in sub_idx]
            sub_msgs = [msgs[i] for i in sub_idx]
            sub_sigs = [sigs[i] for i in sub_idx]
            with self._lock:
                chip.inflight_lanes += K.bucket_size(len(sub_idx))
            fut = self._executor().submit(
                _trace.wrap_ctx(chip.supervisor.run),
                functools.partial(self._shard_op, ops, scheme, chip,
                                  sub_pubs, sub_msgs, sub_sigs))
            submitted.append((chip, sub_idx, fut))
        return submitted

    @staticmethod
    def _remap_groups(groups, idx: np.ndarray):
        """Translate full-batch recheck-group bounds onto the fallback
        sub-batch (idx is ascending): each producer keeps its own
        host-oracle recheck budget even on the degraded path."""
        if not groups:
            return None
        out = []
        for a, b in groups:
            lo = int(np.searchsorted(idx, a))
            hi = int(np.searchsorted(idx, b))
            if hi > lo:
                out.append((lo, hi))
        return out or None

    def _fallback(self, ops: dict, scheme: str, rows: tuple,
                  idx: np.ndarray, mask: np.ndarray,
                  eligible: np.ndarray, recheck_groups=None) -> None:
        """All fault domains dead: those rows ride the existing
        single-chip TPU->XLA->CPU ladder (which applies its own
        host-oracle recheck, under the producers' remapped per-group
        budgets — the rows are marked ineligible so the mesh-level
        recheck never double-spends a budget on them)."""
        self.fallbacks += 1
        mm = _mesh_metrics()
        if mm is not None:
            try:
                mm.mesh_fallback_total.inc()
            except Exception:  # noqa: BLE001
                pass
        _trace.event("mesh.fallback", cat="device", scheme=scheme,
                     rows=len(idx))
        try:
            from cometbft_tpu.libs import log as _log

            _log.default().error(
                "verify mesh has no live fault domains; degrading to the "
                "single-chip ladder", scheme=scheme, rows=str(len(idx)))
        except Exception:  # noqa: BLE001
            pass
        pubs, msgs, sigs = rows
        kwargs = {}
        sub_groups = self._remap_groups(recheck_groups, idx)
        if sub_groups is not None and scheme == "ed25519":
            # sr25519's async path has no recheck_groups parameter (its
            # single-chip recheck is budgeted whole-batch)
            kwargs["recheck_groups"] = sub_groups
        fb_mask = ops["fallback_async"](
            [pubs[i] for i in idx], [msgs[i] for i in idx],
            [sigs[i] for i in idx], **kwargs)()
        mask[idx] = fb_mask
        eligible[idx] = False

    def verify_async(self, scheme: str, pubs: list[bytes], msgs: list[bytes],
                     sigs: list[bytes], klass: str = "sync",
                     recheck_groups: list[tuple[int, int]] | None = None):
        """Shard + dispatch across the live mesh without blocking; returns
        a thunk materializing the (N,) bool mask. A shard whose chip dies
        mid-flight is re-dispatched over the survivors inside the thunk —
        the caller's futures always resolve."""
        n = len(sigs)
        assert len(pubs) == n and len(msgs) == n
        ops = self._scheme_ops(scheme)
        if n == 0:
            return lambda: np.zeros(0, dtype=bool)
        rows = (list(pubs), list(msgs), list(sigs))
        idx = np.arange(n)
        chips = self.live_chips()
        pending = (self._submit_round(ops, scheme, rows, idx, klass, chips)
                   if chips else [])

        def thunk() -> np.ndarray:
            return self._join(ops, scheme, rows, n, idx, pending, klass,
                              recheck_groups)

        return thunk

    def verify(self, scheme: str, pubs, msgs, sigs, klass: str = "sync",
               recheck_groups=None) -> np.ndarray:
        return self.verify_async(
            scheme, pubs, msgs, sigs, klass, recheck_groups)()

    def _join(self, ops: dict, scheme: str, rows: tuple, n: int,
              idx0: np.ndarray, pending: list, klass: str,
              recheck_groups) -> np.ndarray:
        from cometbft_tpu.ops import dispatch as D

        mask = np.zeros(n, dtype=bool)
        eligible = np.zeros(n, dtype=bool)
        mm = _mesh_metrics()
        if not pending:  # mesh was already fully dead at submit time
            self._fallback(ops, scheme, rows, idx0, mask, eligible,
                           recheck_groups=recheck_groups)
        rounds = 0
        # each failed round opens at least one consecutive-failure notch
        # on some breaker, so this bound is generous, not load-bearing
        max_rounds = 4 * len(self.chips) + 2
        while pending:
            failed_idx: list[np.ndarray] = []
            reasons: list[str] = []
            for chip, sub_idx, fut in pending:
                try:
                    m, el = fut.result(timeout=D.watchdog_timeout())
                    mask[sub_idx] = m
                    eligible[sub_idx] = el
                except (D.DeviceUnavailable, D.DeviceOpFailed) as exc:
                    cause = exc.__cause__ or exc
                    reason = ("unavailable"
                              if isinstance(exc, D.DeviceUnavailable)
                              else D.classify_failure(cause))
                    failed_idx.append(sub_idx)
                    reasons.append(reason)
                except Exception as exc:  # noqa: BLE001 - watchdog etc.
                    # same watchdog-abandonment semantics as the single-
                    # chip plane (supervised_device_thunk._acquire): the
                    # wedged worker keeps its pool slot until jax gives
                    # up, and if the op later resolves inside
                    # supervisor.run it re-records — the breaker sees a
                    # hung chip slightly twice rather than not at all
                    chip.supervisor.record_op_failure(exc)
                    failed_idx.append(sub_idx)
                    reasons.append("timeout")
                finally:
                    with self._lock:
                        chip.inflight_lanes -= K.bucket_size(len(sub_idx))
            pending = []
            if not failed_idx:
                break
            retry_idx = np.concatenate(failed_idx)
            with self._lock:
                self.redispatches += len(failed_idx)
            for reason in reasons:
                _trace.event("mesh.redispatch", cat="device", scheme=scheme,
                             reason=reason)
                if mm is not None:
                    try:
                        mm.mesh_redispatch_total.labels(reason).inc()
                    except Exception:  # noqa: BLE001
                        pass
            rounds += 1
            chips = self.live_chips()
            if not chips or rounds > max_rounds:
                self._fallback(ops, scheme, rows, retry_idx, mask, eligible,
                               recheck_groups=recheck_groups)
                break
            pending = self._submit_round(
                ops, scheme, rows, retry_idx, klass, chips)
        with self._lock:
            self.batches += 1
            self.rows_total += n
        # refresh liveness accounting NOW: a successful half-open probe in
        # this batch just re-closed its breaker, and the readmission (and
        # the mesh-size gauge) must be visible before the next flush
        self.live_chips()
        info = (ops["verify_fn"], scheme, recheck_groups)
        pubs, msgs, sigs = rows
        return K.apply_recheck(mask, eligible, (pubs, msgs, sigs), info)

    # -------------------------------------------------------------- health

    def health(self) -> dict:
        """The crypto_health `mesh` section: live size, per-chip breaker
        state, eviction/readmission/redispatch churn, fallback count."""
        from cometbft_tpu.ops import dispatch as D

        chips = {}
        live = 0
        for chip in self.chips:
            sup = chip.supervisor
            alive = sup.breaker.peek()
            live += bool(alive)
            chips[str(chip.index)] = {
                "state": sup.breaker.state,
                "live": bool(alive),
                "inflight_lanes": chip.inflight_lanes,
                "lanes_total": chip.lanes_total,
                "shards_total": chip.shards_total,
                "failures": sup.failures,
                "successes": sup.successes,
            }
        with self._lock:
            return {
                "devices": len(self.chips),
                "live": live,
                "placement": self.placement,
                "evictions": self.evictions,
                "readmissions": self.readmissions,
                "redispatched_batches": self.redispatches,
                "fallbacks": self.fallbacks,
                "batches": self.batches,
                "rows_total": self.rows_total,
                "chips": chips,
            }


# ---------------------------------------------------------------------------
# process-global mesh singleton + knobs (configured from config.crypto at
# node boot; tests poke configure()/reset() directly)
# ---------------------------------------------------------------------------

_cfg = {
    "enabled": True,
    # below this many devices the mesh adds dispatch overhead without
    # adding a second fault domain — the single-chip path already exists
    "min_devices": 2,
    "placement": CLASS_AWARE,
}

_mesh_lock = threading.Lock()
_mesh: VerifyMesh | None = None


def configure(enabled: bool | None = None, min_devices: int | None = None,
              placement: str | None = None) -> None:
    """Apply config.crypto mesh knobs. The live mesh picks up a placement
    change in place; device-set changes need reset() (a process sees one
    device topology for its lifetime)."""
    global _mesh
    with _mesh_lock:
        if enabled is not None:
            _cfg["enabled"] = bool(enabled)
        if min_devices is not None:
            if min_devices < 1:
                raise ValueError("mesh_min_devices must be >= 1")
            _cfg["min_devices"] = int(min_devices)
        if placement is not None:
            if placement not in PLACEMENTS:
                raise ValueError(
                    f"unknown mesh placement {placement!r} "
                    f"(choices: {PLACEMENTS})")
            _cfg["placement"] = placement
            if _mesh is not None:
                _mesh.placement = placement


def get() -> VerifyMesh:
    """The process-global VerifyMesh over every visible device (built
    lazily — health snapshots must not force device discovery)."""
    global _mesh
    if _mesh is None:
        with _mesh_lock:
            if _mesh is None:
                _mesh = VerifyMesh(placement=_cfg["placement"])
    return _mesh


def _set_for_testing(mesh: VerifyMesh | None) -> None:
    """Install a specific mesh instance (tests build meshes over device
    subsets to bound per-device compile cost)."""
    global _mesh
    with _mesh_lock:
        _mesh = mesh


def reset() -> None:
    """Forget the mesh (tests; per-chip supervisors live in the
    ops/dispatch registry and are cleared by reset_supervision)."""
    _set_for_testing(None)


def active() -> VerifyMesh | None:
    """The mesh the scheduler should route through, or None (disabled or
    too few devices). Builds the mesh on first use — DISPATCH paths only.
    An all-chips-dead mesh is still ACTIVE — its internal fallback IS the
    degradation ladder; only topology/config turn the mesh off."""
    if not _cfg["enabled"]:
        return None
    m = get()
    if len(m.chips) < _cfg["min_devices"]:
        return None
    return m


def peek_active() -> VerifyMesh | None:
    """active() without building: telemetry and planning paths (health
    snapshots, rider-budget math) must not force device discovery or
    register per-chip supervisors."""
    if not _cfg["enabled"] or _mesh is None:
        return None
    if len(_mesh.chips) < _cfg["min_devices"]:
        return None
    return _mesh


def enabled() -> bool:
    return _cfg["enabled"]


def health_snapshot() -> dict:
    """The crypto_health `mesh` section. Reports config even before the
    mesh is built (building it is cheap but creates per-chip supervisors;
    a health poll must not mutate the supervision registry)."""
    out = {
        "enabled": _cfg["enabled"],
        "min_devices": _cfg["min_devices"],
        "placement": _cfg["placement"],
        "built": _mesh is not None,
    }
    if _mesh is not None:
        out.update(_mesh.health())
        out["active"] = active() is not None
    return out
