"""Key-value backend interface (reference: cometbft-db's DB interface, used
by store/store.go, state/store.go, indexers, evidence pool, light store).

Two backends: MemDB (tests, light stores) and SQLiteDB (durable, the
default node backend — sqlite is this stack's goleveldb: embedded,
crash-safe, zero-install). Iteration is ordered by raw key bytes, matching
the reference's iterator contract.
"""

from __future__ import annotations

import sqlite3
import threading
from typing import Iterator


class KVStore:
    def get(self, key: bytes) -> bytes | None: ...

    def set(self, key: bytes, value: bytes) -> None: ...

    def delete(self, key: bytes) -> None: ...

    def has(self, key: bytes) -> bool:
        return self.get(key) is not None

    def iterate(self, start: bytes = b"", end: bytes | None = None) -> Iterator[tuple[bytes, bytes]]:
        """Ordered iteration over [start, end)."""
        ...

    def batch_set(self, pairs: list[tuple[bytes, bytes | None]]) -> None:
        """Atomic write batch; value None = delete."""
        for k, v in pairs:
            if v is None:
                self.delete(k)
            else:
                self.set(k, v)

    def close(self) -> None: ...


class MemDB(KVStore):
    def __init__(self):
        self._data: dict[bytes, bytes] = {}
        self._lock = threading.Lock()

    def get(self, key: bytes) -> bytes | None:
        with self._lock:
            return self._data.get(key)

    def set(self, key: bytes, value: bytes) -> None:
        with self._lock:
            self._data[bytes(key)] = bytes(value)

    def delete(self, key: bytes) -> None:
        with self._lock:
            self._data.pop(key, None)

    def iterate(self, start: bytes = b"", end: bytes | None = None):
        with self._lock:
            keys = sorted(k for k in self._data if k >= start and (end is None or k < end))
        for k in keys:
            v = self.get(k)
            if v is not None:
                yield k, v

    def close(self) -> None:
        pass


class SQLiteDB(KVStore):
    """One table of (key BLOB PRIMARY KEY, value BLOB); WAL mode for
    concurrent readers + crash safety."""

    def __init__(self, path: str):
        self.path = path
        self._local = threading.local()
        conn = self._conn()
        conn.execute("PRAGMA journal_mode=WAL")
        conn.execute("PRAGMA synchronous=NORMAL")
        conn.execute("CREATE TABLE IF NOT EXISTS kv (k BLOB PRIMARY KEY, v BLOB NOT NULL)")
        conn.commit()

    def _conn(self) -> sqlite3.Connection:
        conn = getattr(self._local, "conn", None)
        if conn is None:
            conn = sqlite3.connect(self.path, timeout=30)
            self._local.conn = conn
        return conn

    def get(self, key: bytes) -> bytes | None:
        row = self._conn().execute("SELECT v FROM kv WHERE k = ?", (key,)).fetchone()
        return row[0] if row else None

    def set(self, key: bytes, value: bytes) -> None:
        c = self._conn()
        c.execute("INSERT OR REPLACE INTO kv (k, v) VALUES (?, ?)", (key, value))
        c.commit()

    def delete(self, key: bytes) -> None:
        c = self._conn()
        c.execute("DELETE FROM kv WHERE k = ?", (key,))
        c.commit()

    def iterate(self, start: bytes = b"", end: bytes | None = None):
        c = self._conn()
        if end is None:
            cur = c.execute("SELECT k, v FROM kv WHERE k >= ? ORDER BY k", (start,))
        else:
            cur = c.execute(
                "SELECT k, v FROM kv WHERE k >= ? AND k < ? ORDER BY k", (start, end)
            )
        yield from cur

    def batch_set(self, pairs: list[tuple[bytes, bytes | None]]) -> None:
        c = self._conn()
        with c:  # transaction
            for k, v in pairs:
                if v is None:
                    c.execute("DELETE FROM kv WHERE k = ?", (k,))
                else:
                    c.execute("INSERT OR REPLACE INTO kv (k, v) VALUES (?, ?)", (k, v))

    def close(self) -> None:
        conn = getattr(self._local, "conn", None)
        if conn is not None:
            conn.close()
            self._local.conn = None


def open_db(backend: str, path: str | None = None) -> KVStore:
    if backend == "memdb":
        return MemDB()
    if backend == "sqlite":
        if not path:
            raise ValueError("sqlite backend requires a path")
        return SQLiteDB(path)
    raise ValueError(f"unknown db backend {backend!r}")
