"""Key-value backend interface (reference: cometbft-db's DB interface, used
by store/store.go, state/store.go, indexers, evidence pool, light store).

Two backends: MemDB (tests, light stores) and SQLiteDB (durable, the
default node backend — sqlite is this stack's goleveldb: embedded,
crash-safe, zero-install). Iteration is ordered by raw key bytes, matching
the reference's iterator contract.

Storage-fault plane hardening:
  - every SQLiteDB write runs in an EXPLICIT transaction (a torn batch
    can only ever roll back, never half-apply),
  - the sqlite `synchronous` pragma is a knob (`storage.synchronous`,
    NORMAL|FULL) applied to EVERY minted connection — the original code
    set it on the first thread's connection only, silently leaving other
    threads on the sqlite default,
  - close() closes every connection the store ever minted, whichever
    thread minted it (thread-local conns used to leak on close),
  - CRCStore wraps the block/state DBs with per-value CRC32 guards: a
    flipped disk bit surfaces as a typed ErrCorruptValue naming the key
    and the repair path, never as a silently mis-parsed record,
  - SQLiteDB ops ride the `db.write`/`db.read` disk-chaos seams
    (libs/diskchaos) and feed the db-write-latency storage metrics.
"""

from __future__ import annotations

import sqlite3
import threading
import time
import zlib
from typing import Iterator

from cometbft_tpu.libs import diskchaos

SYNCHRONOUS_MODES = ("NORMAL", "FULL")


class ErrCorruptValue(Exception):
    """A CRC-guarded record failed its checksum: the stored bytes rotted
    on disk (or an injected bitrot fault fired). Named repair path: stop
    the node, `cometbft rollback` past the damaged height or re-sync the
    store from peers; `storage.checksum = false` disables the guard."""

    def __init__(self, key: bytes, detail: str):
        super().__init__(
            f"corrupt value for key {key.hex()}: {detail} — the record "
            f"failed its CRC32 guard (storage.checksum). Repair: "
            f"`cometbft rollback` past the damaged height or re-sync "
            f"this store from peers; the bytes on disk are not "
            f"trustworthy. (A store written BEFORE the guard existed "
            f"fails this way on every key — set `storage.checksum = "
            f"false` for pre-guard data, or re-sync onto a fresh home.)")
        self.key = key


class KVStore:
    def get(self, key: bytes) -> bytes | None: ...

    def set(self, key: bytes, value: bytes) -> None: ...

    def delete(self, key: bytes) -> None: ...

    def has(self, key: bytes) -> bool:
        return self.get(key) is not None

    def iterate(self, start: bytes = b"", end: bytes | None = None) -> Iterator[tuple[bytes, bytes]]:
        """Ordered iteration over [start, end)."""
        ...

    def batch_set(self, pairs: list[tuple[bytes, bytes | None]]) -> None:
        """Atomic write batch; value None = delete."""
        for k, v in pairs:
            if v is None:
                self.delete(k)
            else:
                self.set(k, v)

    def close(self) -> None: ...


class MemDB(KVStore):
    def __init__(self):
        self._data: dict[bytes, bytes] = {}
        self._lock = threading.Lock()

    def get(self, key: bytes) -> bytes | None:
        with self._lock:
            return self._data.get(key)

    def set(self, key: bytes, value: bytes) -> None:
        with self._lock:
            self._data[bytes(key)] = bytes(value)

    def delete(self, key: bytes) -> None:
        with self._lock:
            self._data.pop(key, None)

    def iterate(self, start: bytes = b"", end: bytes | None = None):
        with self._lock:
            keys = sorted(k for k in self._data if k >= start and (end is None or k < end))
        for k in keys:
            v = self.get(k)
            if v is not None:
                yield k, v

    def close(self) -> None:
        pass


def _observe_db_write(t0: float) -> None:
    from cometbft_tpu.libs import metrics as cmtmetrics

    cmtmetrics.storage_metrics().observe_db_write(time.perf_counter() - t0)


class SQLiteDB(KVStore):
    """One table of (key BLOB PRIMARY KEY, value BLOB); WAL mode for
    concurrent readers + crash safety. `synchronous` (NORMAL|FULL) is a
    per-connection pragma: NORMAL fsyncs the sqlite WAL at checkpoints
    (a power cut can lose the tail of recently-committed transactions,
    never corrupt), FULL fsyncs every commit (nothing acked is ever
    lost). The privval sign-state does NOT live here — the one
    FULL-grade-always write goes through privval/file_pv.py's
    durable atomic write."""

    def __init__(self, path: str, synchronous: str = "NORMAL"):
        if synchronous not in SYNCHRONOUS_MODES:
            raise ValueError(
                f"unknown sqlite synchronous mode {synchronous!r} "
                f"(expected one of {SYNCHRONOUS_MODES})")
        self.path = path
        self.synchronous = synchronous
        self._local = threading.local()
        self._conns_lock = threading.Lock()
        self._conns: list[sqlite3.Connection] = []
        conn = self._conn()
        conn.execute("CREATE TABLE IF NOT EXISTS kv (k BLOB PRIMARY KEY, v BLOB NOT NULL)")
        conn.commit()

    def _conn(self) -> sqlite3.Connection:
        conn = getattr(self._local, "conn", None)
        if conn is None:
            # a use after close() mints a fresh connection (reopen
            # semantics): tests and the inspect/rollback CLIs routinely
            # read a store after the node released it
            # check_same_thread=False so close() may close conns minted
            # by OTHER threads; each conn is still only ever USED by its
            # minting thread (the thread-local), which is the actual
            # sqlite3 safety requirement
            conn = sqlite3.connect(self.path, timeout=30,
                                   check_same_thread=False)
            # pragmas are PER CONNECTION (journal_mode persists in the
            # file, synchronous does not): every minted conn gets both
            conn.execute("PRAGMA journal_mode=WAL")
            conn.execute(f"PRAGMA synchronous={self.synchronous}")
            self._local.conn = conn
            with self._conns_lock:
                self._conns.append(conn)
        return conn

    def get(self, key: bytes) -> bytes | None:
        row = self._conn().execute("SELECT v FROM kv WHERE k = ?", (key,)).fetchone()
        if row is None:
            return None
        return diskchaos.fault_read("db.read", row[0])

    def set(self, key: bytes, value: bytes) -> None:
        diskchaos.fault_op("db.write")
        t0 = time.perf_counter()
        c = self._conn()
        with c:  # explicit transaction: commit or roll back, never a tear
            c.execute("INSERT OR REPLACE INTO kv (k, v) VALUES (?, ?)", (key, value))
        _observe_db_write(t0)

    def delete(self, key: bytes) -> None:
        diskchaos.fault_op("db.write")
        t0 = time.perf_counter()
        c = self._conn()
        with c:
            c.execute("DELETE FROM kv WHERE k = ?", (key,))
        _observe_db_write(t0)

    def iterate(self, start: bytes = b"", end: bytes | None = None):
        c = self._conn()
        if end is None:
            cur = c.execute("SELECT k, v FROM kv WHERE k >= ? ORDER BY k", (start,))
        else:
            cur = c.execute(
                "SELECT k, v FROM kv WHERE k >= ? AND k < ? ORDER BY k", (start, end)
            )
        yield from cur

    def batch_set(self, pairs: list[tuple[bytes, bytes | None]]) -> None:
        t0 = time.perf_counter()
        c = self._conn()
        with c:  # transaction
            for i, (k, v) in enumerate(pairs):
                if i == len(pairs) // 2:
                    # the torn-batch fault point, deliberately INSIDE the
                    # open transaction (set/delete fire the seam before
                    # theirs): an ENOSPC or death here half-applies the
                    # statements — commit-or-rollback must make the torn
                    # half invisible, never expose half the pairs
                    diskchaos.fault_op("db.write")
                if v is None:
                    c.execute("DELETE FROM kv WHERE k = ?", (k,))
                else:
                    c.execute("INSERT OR REPLACE INTO kv (k, v) VALUES (?, ?)", (k, v))
        _observe_db_write(t0)

    def close(self) -> None:
        """Close EVERY connection this store minted, whichever thread
        minted it. Safe because each conn's minting thread only touches
        it between operations (and a closed node has stopped issuing
        them); sqlite3 allows the cross-thread close itself via
        check_same_thread=False. A later use reopens (fresh conn)."""
        with self._conns_lock:
            conns, self._conns = list(self._conns), []
        for conn in conns:
            try:
                conn.close()
            except sqlite3.Error:
                pass
        self._local = threading.local()


_CRC_TAG = b"\x01"  # value-format version byte for CRC-guarded records


class CRCStore(KVStore):
    """CRC32 guard over an inner store: set() wraps values as
    [0x01 | payload | crc32(payload)]; get() verifies and unwraps,
    raising ErrCorruptValue on any mismatch. This is the block/state
    record guard the storage plane promises: a rotted bit becomes a
    typed, actionable halt — never an accepted block or a mis-parsed
    header."""

    def __init__(self, inner: KVStore):
        self.inner = inner

    @staticmethod
    def _wrap(value: bytes) -> bytes:
        return _CRC_TAG + value + (zlib.crc32(value) & 0xFFFFFFFF).to_bytes(4, "big")

    @staticmethod
    def _unwrap(key: bytes, raw: bytes) -> bytes:
        if len(raw) < 5 or raw[:1] != _CRC_TAG:
            # a rotted TAG byte lands here, not in the crc branch: both
            # are detections and both must count
            CRCStore._count_corruption()
            raise ErrCorruptValue(
                key, f"missing CRC envelope (len {len(raw)}, "
                     f"tag {raw[:1].hex() if raw else 'empty'})")
        payload, want = raw[1:-4], int.from_bytes(raw[-4:], "big")
        got = zlib.crc32(payload) & 0xFFFFFFFF
        if got != want:
            CRCStore._count_corruption()
            raise ErrCorruptValue(
                key, f"crc32 {got:08x} != stored {want:08x}")
        return payload

    @staticmethod
    def _count_corruption() -> None:
        from cometbft_tpu.libs import metrics as cmtmetrics

        cmtmetrics.storage_metrics().corruption_detected.inc()

    def get(self, key: bytes) -> bytes | None:
        raw = self.inner.get(key)
        return None if raw is None else self._unwrap(key, raw)

    def set(self, key: bytes, value: bytes) -> None:
        self.inner.set(key, self._wrap(value))

    def delete(self, key: bytes) -> None:
        self.inner.delete(key)

    def iterate(self, start: bytes = b"", end: bytes | None = None):
        for k, raw in self.inner.iterate(start, end):
            yield k, self._unwrap(k, raw)

    def batch_set(self, pairs: list[tuple[bytes, bytes | None]]) -> None:
        self.inner.batch_set(
            [(k, None if v is None else self._wrap(v)) for k, v in pairs])

    def close(self) -> None:
        self.inner.close()


def open_db(backend: str, path: str | None = None,
            synchronous: str = "NORMAL", checksum: bool = False) -> KVStore:
    if backend == "memdb":
        db: KVStore = MemDB()
    elif backend == "sqlite":
        if not path:
            raise ValueError("sqlite backend requires a path")
        db = SQLiteDB(path, synchronous=synchronous)
    else:
        raise ValueError(f"unknown db backend {backend!r}")
    return CRCStore(db) if checksum else db
