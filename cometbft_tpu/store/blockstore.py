"""BlockStore — block persistence (reference: store/store.go:38-460).

Key layout (height big-endian for ordered iteration/pruning):
  H:<height>          -> block meta (block_id proto + header proto + sizes)
  P:<height>:<index>  -> block part proto
  C:<height>          -> commit proto (the block's LastCommit, height-1 sigs)
  SC:<height>         -> "seen commit" for the block itself
  EC:<height>         -> extended commit (vote extensions, latest height)
  BH:<hash>           -> height (hash -> height index)
  base / height       -> store bounds
"""

from __future__ import annotations

import threading
from dataclasses import dataclass

from cometbft_tpu.libs import diskchaos
from cometbft_tpu.store.db import KVStore
from cometbft_tpu.types.basic import BlockID
from cometbft_tpu.types.block import Block, Header
from cometbft_tpu.types.commit import Commit, ExtendedCommit
from cometbft_tpu.types.part_set import Part, PartSet
from cometbft_tpu.utils import protobuf as pb


def _hkey(prefix: bytes, height: int) -> bytes:
    return prefix + height.to_bytes(8, "big")


@dataclass
class BlockMeta:
    """store/types.go BlockMeta."""

    block_id: BlockID
    block_size: int
    header: Header
    num_txs: int

    def to_proto(self) -> bytes:
        w = pb.Writer()
        w.message(1, self.block_id.to_proto(), always=True)
        w.varint_i64(2, self.block_size)
        w.message(3, self.header.to_proto(), always=True)
        w.varint_i64(4, self.num_txs)
        return w.output()

    @classmethod
    def from_proto(cls, data: bytes) -> "BlockMeta":
        r = pb.Reader(data)
        block_id, size, header, num_txs = BlockID(), 0, Header(), 0
        while not r.at_end():
            f, w = r.read_tag()
            if f == 1:
                block_id = BlockID.from_proto(r.read_bytes())
            elif f == 2:
                size = r.read_varint_i64()
            elif f == 3:
                header = Header.from_proto(r.read_bytes())
            elif f == 4:
                num_txs = r.read_varint_i64()
            else:
                r.skip(w)
        return cls(block_id=block_id, block_size=size, header=header, num_txs=num_txs)


class BlockStore:
    def __init__(self, db: KVStore):
        self.db = db
        self._lock = threading.RLock()
        self._base = int.from_bytes(db.get(b"base") or b"\x00" * 8, "big")
        self._height = int.from_bytes(db.get(b"height") or b"\x00" * 8, "big")

    # ------------------------------------------------------------- bounds

    def base(self) -> int:
        with self._lock:
            return self._base

    def height(self) -> int:
        with self._lock:
            return self._height

    def size(self) -> int:
        with self._lock:
            return 0 if self._height == 0 else self._height - self._base + 1

    # -------------------------------------------------------------- save

    def save_block(self, block: Block, part_set: PartSet, seen_commit: Commit) -> None:
        """store/store.go:401-417 SaveBlock."""
        self._save_block_parts(block, part_set, seen_commit, None)

    def save_block_with_extended_commit(
        self, block: Block, part_set: PartSet, seen_extended_commit: ExtendedCommit
    ) -> None:
        """store/store.go:418-440: keeps vote extensions for the latest
        height (needed to rebuild LastCommit for PrepareProposal)."""
        self._save_block_parts(
            block, part_set, seen_extended_commit.to_commit(), seen_extended_commit
        )

    def _save_block_parts(
        self,
        block: Block,
        part_set: PartSet,
        seen_commit: Commit,
        extended: ExtendedCommit | None,
    ) -> None:
        if block is None or not part_set.is_complete():
            raise ValueError("BlockStore can only save complete block part sets")
        # the block-store disk seam: an injected ENOSPC/EIO here must
        # surface BEFORE any pair lands (the batch below is one
        # transaction either way)
        diskchaos.fault_op("blockstore.save")
        height = block.header.height
        with self._lock:
            if self._height > 0 and height != self._height + 1:
                raise ValueError(
                    f"BlockStore can only save contiguous blocks: wanted {self._height + 1}, got {height}"
                )
            block_id = BlockID(hash=block.hash(), part_set_header=part_set.header())
            meta = BlockMeta(
                block_id=block_id,
                block_size=sum(len(p.bytes_) for p in part_set.parts if p),
                header=block.header,
                num_txs=len(block.data.txs),
            )
            pairs: list[tuple[bytes, bytes | None]] = [
                (_hkey(b"H:", height), meta.to_proto()),
                (b"BH:" + block_id.hash, height.to_bytes(8, "big")),
            ]
            for i in range(part_set.total):
                part = part_set.get_part(i)
                pairs.append((_hkey(b"P:", height) + i.to_bytes(4, "big"), part.to_proto()))
            if block.last_commit is not None:
                pairs.append((_hkey(b"C:", height - 1), block.last_commit.to_proto()))
            pairs.append((_hkey(b"SC:", height), seen_commit.to_proto()))
            if extended is not None:
                pairs.append((_hkey(b"EC:", height), _extended_to_proto(extended)))
            new_base = self._base or height
            pairs.append((b"base", new_base.to_bytes(8, "big")))
            pairs.append((b"height", height.to_bytes(8, "big")))
            self.db.batch_set(pairs)
            self._base, self._height = new_base, height

    # -------------------------------------------------------------- load

    def load_block_meta(self, height: int) -> BlockMeta | None:
        raw = self.db.get(_hkey(b"H:", height))
        return BlockMeta.from_proto(raw) if raw is not None else None

    def load_block(self, height: int) -> Block | None:
        meta = self.load_block_meta(height)
        if meta is None:
            return None
        chunks = []
        for i in range(meta.block_id.part_set_header.total):
            raw = self.db.get(_hkey(b"P:", height) + i.to_bytes(4, "big"))
            if raw is None:
                return None
            chunks.append(Part.from_proto(raw).bytes_)
        return Block.from_proto(b"".join(chunks))

    def load_block_by_hash(self, h: bytes) -> Block | None:
        raw = self.db.get(b"BH:" + h)
        if raw is None:
            return None
        return self.load_block(int.from_bytes(raw, "big"))

    def load_block_part(self, height: int, index: int) -> Part | None:
        raw = self.db.get(_hkey(b"P:", height) + index.to_bytes(4, "big"))
        return Part.from_proto(raw) if raw is not None else None

    def load_block_commit(self, height: int) -> Commit | None:
        """The canonical commit for `height` (stored with block height+1)."""
        raw = self.db.get(_hkey(b"C:", height))
        return Commit.from_proto(raw) if raw is not None else None

    def load_seen_commit(self, height: int) -> Commit | None:
        raw = self.db.get(_hkey(b"SC:", height))
        return Commit.from_proto(raw) if raw is not None else None

    def load_block_extended_commit(self, height: int) -> ExtendedCommit | None:
        raw = self.db.get(_hkey(b"EC:", height))
        return _extended_from_proto(raw) if raw is not None else None

    # ------------------------------------------------------------- prune

    def save_seen_commit(self, height: int, commit: Commit) -> None:
        """store/store.go SaveSeenCommit — the statesync bootstrap hook:
        consensus reconstructs LastCommit from it at the restored height."""
        self.db.set(_hkey(b"SC:", height), commit.to_proto())

    def delete_latest_block(self) -> None:
        """store/store.go DeleteLatestBlock — the rollback tool's hook."""
        with self._lock:
            height = self._height
            if height == 0:
                raise ValueError("block store is empty")
            meta = self.load_block_meta(height)
            pairs: list[tuple[bytes, bytes | None]] = [
                (_hkey(b"H:", height), None),
                (_hkey(b"SC:", height), None),
                (_hkey(b"EC:", height), None),
                (_hkey(b"C:", height - 1), None),
            ]
            if meta is not None:
                pairs.append((b"BH:" + meta.block_id.hash, None))
                for i in range(10_000):
                    k = _hkey(b"P:", height) + i.to_bytes(4, "big")
                    if self.db.get(k) is None:
                        break
                    pairs.append((k, None))
            pairs.append((b"height", (height - 1).to_bytes(8, "big")))
            self.db.batch_set(pairs)
            self._height = height - 1

    def prune_blocks(self, retain_height: int) -> int:
        """store/store.go:301-383: delete blocks below retain_height,
        keeping hash indices consistent. Returns number pruned."""
        with self._lock:
            if retain_height <= self._base:
                return 0
            if retain_height > self._height:
                raise ValueError("cannot prune beyond the latest height")
            pruned = 0
            pairs: list[tuple[bytes, bytes | None]] = []
            for h in range(self._base, retain_height):
                meta = self.load_block_meta(h)
                if meta is None:
                    continue
                pairs.append((_hkey(b"H:", h), None))
                pairs.append((b"BH:" + meta.block_id.hash, None))
                for i in range(meta.block_id.part_set_header.total):
                    pairs.append((_hkey(b"P:", h) + i.to_bytes(4, "big"), None))
                pairs.append((_hkey(b"C:", h - 1), None))
                pairs.append((_hkey(b"SC:", h), None))
                pairs.append((_hkey(b"EC:", h), None))
                pruned += 1
            pairs.append((b"base", retain_height.to_bytes(8, "big")))
            self.db.batch_set(pairs)
            self._base = retain_height
            return pruned


def _extended_to_proto(ec: ExtendedCommit) -> bytes:
    from cometbft_tpu.types.commit import ExtendedCommitSig

    w = pb.Writer()
    w.varint_i64(1, ec.height)
    w.varint_i64(2, ec.round_)
    w.message(3, ec.block_id.to_proto(), always=True)
    for es in ec.extended_signatures:
        sw = pb.Writer()
        sw.message(1, es.commit_sig.to_proto(), always=True)
        sw.bytes(2, es.extension)
        sw.bytes(3, es.extension_signature)
        w.message(4, sw.output(), always=True)
    return w.output()


def _extended_from_proto(data: bytes) -> ExtendedCommit:
    from cometbft_tpu.types.commit import CommitSig, ExtendedCommitSig

    r = pb.Reader(data)
    height = round_ = 0
    block_id = BlockID()
    esigs: list[ExtendedCommitSig] = []
    while not r.at_end():
        f, w = r.read_tag()
        if f == 1:
            height = r.read_varint_i64()
        elif f == 2:
            round_ = r.read_varint_i64()
        elif f == 3:
            block_id = BlockID.from_proto(r.read_bytes())
        elif f == 4:
            sr = r.read_message()
            cs, ext, extsig = CommitSig.absent(), b"", b""
            while not sr.at_end():
                sf, sw = sr.read_tag()
                if sf == 1:
                    cs = CommitSig.from_proto(sr.read_bytes())
                elif sf == 2:
                    ext = sr.read_bytes()
                elif sf == 3:
                    extsig = sr.read_bytes()
                else:
                    sr.skip(sw)
            esigs.append(ExtendedCommitSig(commit_sig=cs, extension=ext, extension_signature=extsig))
        else:
            r.skip(w)
    return ExtendedCommit(height=height, round_=round_, block_id=block_id, extended_signatures=esigs)
