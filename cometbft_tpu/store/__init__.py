"""Persistence: KV backends + block storage.

Reference: store/store.go over cometbft-db. db.py defines the backend
interface with in-memory and SQLite implementations; blockstore.py persists
block meta/parts/commits keyed by height (SURVEY.md §2.1 row Store).
"""

from cometbft_tpu.store.db import KVStore, MemDB, SQLiteDB, open_db  # noqa: F401
from cometbft_tpu.store.blockstore import BlockStore  # noqa: F401
