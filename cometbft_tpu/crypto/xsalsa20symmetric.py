"""XSalsa20-Poly1305 symmetric encryption (NaCl secretbox format).

Reference: crypto/xsalsa20symmetric/symmetric.go:26-60 — EncryptSymmetric/
DecryptSymmetric over golang.org/x/crypto/nacl/secretbox, used for
passphrase-encrypted key export (secret = sha256(bcrypt(passphrase)) in the
callers). Wire format: nonce(24) || poly1305 tag(16) || ciphertext.

The Salsa20 core and HSalsa20 are implemented from the Salsa20
specification (checked against the eSTREAM vectors); Poly1305 uses the
`cryptography` package's constant-time primitive, keyed per the secretbox
construction (the first 32 keystream bytes of block 0)."""

from __future__ import annotations

import os
import struct

NONCE_LEN = 24
SECRET_LEN = 32
TAG_LEN = 16

_SIGMA = (0x61707865, 0x3320646E, 0x79622D32, 0x6B206574)
_M32 = 0xFFFFFFFF


def _rotl32(v: int, n: int) -> int:
    return ((v << n) | (v >> (32 - n))) & _M32


def _salsa20_rounds(st: list[int]) -> list[int]:
    x = list(st)
    for _ in range(10):  # 20 rounds = 10 double-rounds
        # column round
        x[4] ^= _rotl32((x[0] + x[12]) & _M32, 7)
        x[8] ^= _rotl32((x[4] + x[0]) & _M32, 9)
        x[12] ^= _rotl32((x[8] + x[4]) & _M32, 13)
        x[0] ^= _rotl32((x[12] + x[8]) & _M32, 18)
        x[9] ^= _rotl32((x[5] + x[1]) & _M32, 7)
        x[13] ^= _rotl32((x[9] + x[5]) & _M32, 9)
        x[1] ^= _rotl32((x[13] + x[9]) & _M32, 13)
        x[5] ^= _rotl32((x[1] + x[13]) & _M32, 18)
        x[14] ^= _rotl32((x[10] + x[6]) & _M32, 7)
        x[2] ^= _rotl32((x[14] + x[10]) & _M32, 9)
        x[6] ^= _rotl32((x[2] + x[14]) & _M32, 13)
        x[10] ^= _rotl32((x[6] + x[2]) & _M32, 18)
        x[3] ^= _rotl32((x[15] + x[11]) & _M32, 7)
        x[7] ^= _rotl32((x[3] + x[15]) & _M32, 9)
        x[11] ^= _rotl32((x[7] + x[3]) & _M32, 13)
        x[15] ^= _rotl32((x[11] + x[7]) & _M32, 18)
        # row round
        x[1] ^= _rotl32((x[0] + x[3]) & _M32, 7)
        x[2] ^= _rotl32((x[1] + x[0]) & _M32, 9)
        x[3] ^= _rotl32((x[2] + x[1]) & _M32, 13)
        x[0] ^= _rotl32((x[3] + x[2]) & _M32, 18)
        x[6] ^= _rotl32((x[5] + x[4]) & _M32, 7)
        x[7] ^= _rotl32((x[6] + x[5]) & _M32, 9)
        x[4] ^= _rotl32((x[7] + x[6]) & _M32, 13)
        x[5] ^= _rotl32((x[4] + x[7]) & _M32, 18)
        x[11] ^= _rotl32((x[10] + x[9]) & _M32, 7)
        x[8] ^= _rotl32((x[11] + x[10]) & _M32, 9)
        x[9] ^= _rotl32((x[8] + x[11]) & _M32, 13)
        x[10] ^= _rotl32((x[9] + x[8]) & _M32, 18)
        x[12] ^= _rotl32((x[15] + x[14]) & _M32, 7)
        x[13] ^= _rotl32((x[12] + x[15]) & _M32, 9)
        x[14] ^= _rotl32((x[13] + x[12]) & _M32, 13)
        x[15] ^= _rotl32((x[14] + x[13]) & _M32, 18)
    return x


def _salsa20_block(key: bytes, nonce8: bytes, counter: int) -> bytes:
    k = struct.unpack("<8L", key)
    n = struct.unpack("<2L", nonce8)
    st = [
        _SIGMA[0], k[0], k[1], k[2],
        k[3], _SIGMA[1], n[0], n[1],
        counter & _M32, (counter >> 32) & _M32, _SIGMA[2], k[4],
        k[5], k[6], k[7], _SIGMA[3],
    ]
    x = _salsa20_rounds(st)
    return struct.pack("<16L", *((a + b) & _M32 for a, b in zip(x, st)))


def hsalsa20(key: bytes, nonce16: bytes) -> bytes:
    """32-byte subkey: Salsa20 rounds WITHOUT feed-forward; output words
    0, 5, 10, 15, 6, 7, 8, 9 (the NaCl XSalsa20 derivation)."""
    k = struct.unpack("<8L", key)
    n = struct.unpack("<4L", nonce16)
    st = [
        _SIGMA[0], k[0], k[1], k[2],
        k[3], _SIGMA[1], n[0], n[1],
        n[2], n[3], _SIGMA[2], k[4],
        k[5], k[6], k[7], _SIGMA[3],
    ]
    x = _salsa20_rounds(st)
    return struct.pack("<8L", *(x[i] for i in (0, 5, 10, 15, 6, 7, 8, 9)))


def _xsalsa20_xor(key: bytes, nonce24: bytes, data: bytes) -> tuple[bytes, bytes]:
    """-> (poly1305 one-time key, data ^ keystream[32:]) — the secretbox
    layout: keystream block 0's first 32 bytes key the MAC, the message
    starts at offset 32."""
    subkey = hsalsa20(key, nonce24[:16])
    nonce8 = nonce24[16:]
    block0 = _salsa20_block(subkey, nonce8, 0)
    poly_key = block0[:32]
    stream = bytearray(block0[32:])
    counter = 1
    while len(stream) < len(data):
        stream.extend(_salsa20_block(subkey, nonce8, counter))
        counter += 1
    out = bytes(d ^ stream[i] for i, d in enumerate(data))
    return poly_key, out


def _poly1305(key32: bytes, msg: bytes) -> bytes:
    try:
        from cryptography.hazmat.primitives import poly1305
    except ImportError:  # degraded: pure-Python MAC (crypto/fallback.py)
        from cometbft_tpu.crypto.fallback import poly1305_mac

        return poly1305_mac(key32, msg)

    p = poly1305.Poly1305(key32)
    p.update(msg)
    return p.finalize()


def encrypt_symmetric(plaintext: bytes, secret: bytes) -> bytes:
    """nonce(24) || tag(16) || ciphertext — symmetric.go EncryptSymmetric
    (the nonce is random; the secret must be 32 bytes, e.g.
    sha256(bcrypt(passphrase)))."""
    if len(secret) != SECRET_LEN:
        raise ValueError(f"secret must be {SECRET_LEN} bytes, got {len(secret)}")
    nonce = os.urandom(NONCE_LEN)
    poly_key, ct = _xsalsa20_xor(secret, nonce, plaintext)
    tag = _poly1305(poly_key, ct)
    return nonce + tag + ct


def decrypt_symmetric(ciphertext: bytes, secret: bytes) -> bytes:
    """Raises ValueError on truncation or authentication failure
    (symmetric.go DecryptSymmetric error cases)."""
    if len(secret) != SECRET_LEN:
        raise ValueError(f"secret must be {SECRET_LEN} bytes, got {len(secret)}")
    if len(ciphertext) <= NONCE_LEN + TAG_LEN:
        raise ValueError("xsalsa20symmetric: ciphertext is too short")
    nonce = ciphertext[:NONCE_LEN]
    tag = ciphertext[NONCE_LEN:NONCE_LEN + TAG_LEN]
    ct = ciphertext[NONCE_LEN + TAG_LEN:]
    poly_key, pt = _xsalsa20_xor(secret, nonce, ct)
    import hmac as _hmac

    if not _hmac.compare_digest(_poly1305(poly_key, ct), tag):
        raise ValueError("xsalsa20symmetric: ciphertext decryption failed")
    return pt
