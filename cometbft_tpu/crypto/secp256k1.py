"""secp256k1 keys (reference: crypto/secp256k1/secp256k1.go).

ECDSA over secp256k1, OpenSSL-backed (`cryptography`). Wire formats mirror
the reference: 33-byte compressed pubkeys, 64-byte R||S signatures with S
canonicalized to the lower half-order (secp256k1.go:180-190 — malleability
guard), and Bitcoin-style addresses RIPEMD160(SHA256(pubkey))
(secp256k1.go:23-41).

No batch path: secp256k1 has no safe batch verification (crypto/batch
excludes it, batch.go:26-32), so commits containing secp256k1 validators
fall back to per-signature verification — same behavior as the reference.
"""

from __future__ import annotations

import hashlib
import secrets as _secrets

try:
    from cryptography.exceptions import InvalidSignature
    from cryptography.hazmat.primitives.asymmetric import ec
    from cryptography.hazmat.primitives.asymmetric.utils import (
        decode_dss_signature,
        encode_dss_signature,
    )
    from cryptography.hazmat.primitives import hashes

    _HAVE_OPENSSL = True
except ImportError:  # degraded: pure-Python ECDSA (crypto/fallback.py)
    _HAVE_OPENSSL = False

from cometbft_tpu import crypto
from cometbft_tpu.crypto import fallback as _fb

KEY_TYPE = "secp256k1"
PUB_KEY_SIZE = 33
PRIV_KEY_SIZE = 32
SIGNATURE_SIZE = 64

# curve order (SEC2): canonical signatures use s <= N/2
N = 0xFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFEBAAEDCE6AF48A03BBFD25E8CD0364141
_HALF_N = N // 2


class PubKey(crypto.PubKey):
    __slots__ = ("_bytes", "_openssl")

    def __init__(self, data: bytes):
        if len(data) != PUB_KEY_SIZE:
            raise crypto.ErrInvalidKey(f"secp256k1 pubkey must be {PUB_KEY_SIZE} bytes")
        self._bytes = bytes(data)
        self._openssl: ec.EllipticCurvePublicKey | None = None

    def address(self) -> bytes:
        """secp256k1.go:23-41: RIPEMD160(SHA256(compressed pubkey))."""
        sha = hashlib.sha256(self._bytes).digest()
        return hashlib.new("ripemd160", sha).digest()

    def bytes_(self) -> bytes:
        return self._bytes

    def type_(self) -> str:
        return KEY_TYPE

    def verify_signature(self, msg: bytes, sig: bytes) -> bool:
        """64-byte R||S; rejects non-canonical S (upper half-order),
        matching secp256k1.go:192-210 VerifyBytes."""
        if len(sig) != SIGNATURE_SIZE:
            return False
        if type(msg) is not bytes:
            msg = bytes(msg)  # shared-prefix factored rows (prefixrows)
        r = int.from_bytes(sig[:32], "big")
        s = int.from_bytes(sig[32:], "big")
        if not (0 < r < N and 0 < s <= _HALF_N):
            return False
        if not _HAVE_OPENSSL:
            return _fb.secp_verify(self._bytes, msg, r, s)
        try:
            if self._openssl is None:
                self._openssl = ec.EllipticCurvePublicKey.from_encoded_point(
                    ec.SECP256K1(), self._bytes)
            self._openssl.verify(
                encode_dss_signature(r, s), msg, ec.ECDSA(hashes.SHA256()))
            return True
        except (InvalidSignature, ValueError):
            return False

    def __repr__(self) -> str:
        return f"PubKeySecp256k1{{{self._bytes.hex().upper()}}}"


class PrivKey(crypto.PrivKey):
    __slots__ = ("_bytes", "_openssl", "_pub")

    def __init__(self, data: bytes):
        if len(data) != PRIV_KEY_SIZE:
            raise crypto.ErrInvalidKey("secp256k1 privkey must be 32 bytes")
        self._bytes = bytes(data)
        d = int.from_bytes(data, "big")
        if not 0 < d < N:
            raise crypto.ErrInvalidKey("secp256k1 privkey out of range")
        if _HAVE_OPENSSL:
            self._openssl = ec.derive_private_key(d, ec.SECP256K1())
            from cryptography.hazmat.primitives.serialization import (
                Encoding,
                PublicFormat,
            )

            pub = self._openssl.public_key().public_bytes(
                Encoding.X962, PublicFormat.CompressedPoint)
        else:
            self._openssl = None
            pub = _fb.secp_pub_from_priv(d)
        self._pub = PubKey(pub)

    def bytes_(self) -> bytes:
        return self._bytes

    def sign(self, msg: bytes) -> bytes:
        """64-byte R||S with low-S canonicalization (secp256k1.go:160-178)."""
        if self._openssl is None:
            r, s = _fb.secp_sign(int.from_bytes(self._bytes, "big"), msg)
        else:
            der = self._openssl.sign(msg, ec.ECDSA(hashes.SHA256()))
            r, s = decode_dss_signature(der)
        if s > _HALF_N:
            s = N - s
        return r.to_bytes(32, "big") + s.to_bytes(32, "big")

    def pub_key(self) -> PubKey:
        return self._pub

    def type_(self) -> str:
        return KEY_TYPE


def gen_priv_key() -> PrivKey:
    while True:
        d = _secrets.token_bytes(PRIV_KEY_SIZE)
        if 0 < int.from_bytes(d, "big") < N:
            return PrivKey(d)


def gen_priv_key_from_secret(secret: bytes) -> PrivKey:
    """Deterministic key: SHA256(secret) clamped into range (testing only)."""
    d = int.from_bytes(hashlib.sha256(secret).digest(), "big") % (N - 1) + 1
    return PrivKey(d.to_bytes(32, "big"))
