"""OpenPGP ASCII armor (RFC 4880 §6) — key-export framing.

Reference: crypto/armor/armor.go:24-60 (EncodeArmor/DecodeArmor over
golang.org/x/crypto/openpgp/armor). Implemented here directly from the
RFC: BEGIN/END lines, optional "Key: Value" headers, blank line, base64
body wrapped at 64 columns, and the "=" + base64(CRC-24/OpenPGP) checksum
line (poly 0x1864CFB, init 0xB704CE)."""

from __future__ import annotations

import base64

_CRC24_INIT = 0xB704CE
_CRC24_POLY = 0x1864CFB
_WRAP = 64  # go's armor writer wraps at 64 columns


def _crc24(data: bytes) -> int:
    crc = _CRC24_INIT
    for b in data:
        crc ^= b << 16
        for _ in range(8):
            crc <<= 1
            if crc & 0x1000000:
                crc ^= _CRC24_POLY
    return crc & 0xFFFFFF


def encode_armor(block_type: str, headers: dict[str, str], data: bytes) -> str:
    lines = [f"-----BEGIN {block_type}-----"]
    for k in sorted(headers):
        lines.append(f"{k}: {headers[k]}")
    lines.append("")
    b64 = base64.b64encode(data).decode()
    lines.extend(b64[i:i + _WRAP] for i in range(0, len(b64), _WRAP))
    if not data:
        lines.append("")  # empty payload still carries a body slot
    crc = _crc24(data).to_bytes(3, "big")
    lines.append("=" + base64.b64encode(crc).decode())
    lines.append(f"-----END {block_type}-----")
    return "\n".join(lines) + "\n"


class ArmorError(ValueError):
    pass


def decode_armor(armor_str: str) -> tuple[str, dict[str, str], bytes]:
    """-> (block type, headers, data). Raises ArmorError on framing or
    checksum violations."""
    lines = [ln.rstrip("\r") for ln in armor_str.strip().split("\n")]
    if not lines or not lines[0].startswith("-----BEGIN ") \
            or not lines[0].endswith("-----"):
        raise ArmorError("missing BEGIN line")
    block_type = lines[0][len("-----BEGIN "):-len("-----")]
    end = f"-----END {block_type}-----"
    if lines[-1] != end:
        raise ArmorError(f"missing {end!r}")
    body = lines[1:-1]
    headers: dict[str, str] = {}
    i = 0
    while i < len(body) and body[i]:
        if ":" not in body[i]:
            break  # headerless armor: body starts immediately
        k, _, v = body[i].partition(":")
        headers[k.strip()] = v.strip()
        i += 1
    if i < len(body) and not body[i]:
        i += 1  # the blank separator
    b64_lines = []
    crc_line = None
    for ln in body[i:]:
        if ln.startswith("="):
            crc_line = ln
            break
        b64_lines.append(ln)
    try:
        data = base64.b64decode("".join(b64_lines), validate=True)
    except Exception as e:  # noqa: BLE001
        raise ArmorError(f"bad base64 body: {e}") from e
    if crc_line is not None:
        try:
            want = int.from_bytes(base64.b64decode(crc_line[1:], validate=True), "big")
        except Exception as e:  # noqa: BLE001
            raise ArmorError(f"bad checksum line: {e}") from e
        if _crc24(data) != want:
            raise ArmorError("CRC-24 checksum mismatch")
    return block_type, headers, data
