"""sr25519 (schnorrkel) host-side oracle: ristretto255 group, Merlin
transcripts (STROBE-128 over Keccak-f[1600]), signing context, sign/verify.

Reference: crypto/sr25519/{privkey,pubkey,batch}.go, which delegate to
curve25519-voi's primitives/sr25519 with an empty signing context. The
protocol re-implemented here from the public schnorrkel/merlin/STROBE
specifications:

  sign:  t = SigningContext("")          (merlin transcript "SigningContext"
                                          + appended context bytes)
         t.append_message("sign-bytes", msg)
         t.proto_name("Schnorr-sig"); append pk, R
         k = t.challenge_scalar("sign:c")   (64-byte wide reduction mod L)
         s = k*secret + r  mod L
         signature = R_ristretto(32) || s(32) with bit 255 SET (the
         schnorrkel "v0.1.1 format" marker, cleared before use)

  verify: recompute k, accept iff  [4](sB - kA - R) == identity  — the
         cofactor-4 coset check IS ristretto equality (two edwards points
         encode to the same ristretto string iff they differ by E[4]).

Field/curve arithmetic reuses the ed25519 oracle (same edwards25519 curve
under the ristretto quotient).

COMPATIBILITY NOTE: byte-for-byte schnorrkel interop is validated against
the ristretto255 draft test vectors (generator multiples) and
self-consistency (sign<->verify, tamper rejection, torsion-offset
acceptance); no external schnorrkel implementation exists in this image to
cross-check transcript bytes end-to-end.
"""

from __future__ import annotations

import hashlib
import os
import secrets as _secrets

from cometbft_tpu.crypto import ed25519_math as ed

P = ed.P
L = ed.L
D = ed.D


# ---------------------------------------------------------------------------
# Keccak-f[1600]
# ---------------------------------------------------------------------------

_KECCAK_RC = [
    0x0000000000000001, 0x0000000000008082, 0x800000000000808A, 0x8000000080008000,
    0x000000000000808B, 0x0000000080000001, 0x8000000080008081, 0x8000000000008009,
    0x000000000000008A, 0x0000000000000088, 0x0000000080008009, 0x000000008000000A,
    0x000000008000808B, 0x800000000000008B, 0x8000000000008089, 0x8000000000008003,
    0x8000000000008002, 0x8000000000000080, 0x000000000000800A, 0x800000008000000A,
    0x8000000080008081, 0x8000000000008080, 0x0000000080000001, 0x8000000080008008,
]
_ROTC = [
    [0, 36, 3, 41, 18],
    [1, 44, 10, 45, 2],
    [62, 6, 43, 15, 61],
    [28, 55, 25, 21, 56],
    [27, 20, 39, 8, 14],
]
_M64 = (1 << 64) - 1


def _rotl(x: int, n: int) -> int:
    n %= 64
    return ((x << n) | (x >> (64 - n))) & _M64


def keccak_f1600(state: bytearray) -> None:
    """In-place permutation of the 200-byte state."""
    a = [[int.from_bytes(state[8 * (x + 5 * y): 8 * (x + 5 * y) + 8], "little")
          for y in range(5)] for x in range(5)]
    for rc in _KECCAK_RC:
        # theta
        c = [a[x][0] ^ a[x][1] ^ a[x][2] ^ a[x][3] ^ a[x][4] for x in range(5)]
        d = [c[(x - 1) % 5] ^ _rotl(c[(x + 1) % 5], 1) for x in range(5)]
        for x in range(5):
            for y in range(5):
                a[x][y] ^= d[x]
        # rho + pi
        b = [[0] * 5 for _ in range(5)]
        for x in range(5):
            for y in range(5):
                b[y][(2 * x + 3 * y) % 5] = _rotl(a[x][y], _ROTC[x][y])
        # chi
        for x in range(5):
            for y in range(5):
                a[x][y] = b[x][y] ^ ((~b[(x + 1) % 5][y]) & b[(x + 2) % 5][y] & _M64)
        # iota
        a[0][0] ^= rc
    for x in range(5):
        for y in range(5):
            state[8 * (x + 5 * y): 8 * (x + 5 * y) + 8] = a[x][y].to_bytes(8, "little")


# ---------------------------------------------------------------------------
# STROBE-128 (the subset merlin uses: meta-AD, AD, PRF), per the STROBE v1.0.2
# spec and merlin's strobe128.rs.
# ---------------------------------------------------------------------------

_STROBE_R = 166  # 1600/8 - (2*128)/8 - 2

_FLAG_I = 1
_FLAG_A = 1 << 1
_FLAG_C = 1 << 2
_FLAG_T = 1 << 3
_FLAG_M = 1 << 4
_FLAG_K = 1 << 5


def _load_native_strobe():
    """ctypes handle to native/strobe.c, or None (pure-Python fallback).
    Byte-equivalence with the Python implementation is asserted by
    tests/test_sr25519.py."""
    from cometbft_tpu import native

    return native.load("strobe")


_NATIVE = _load_native_strobe()


class _NativeStrobe128:
    """Same surface as Strobe128, state in a packed 203-byte C buffer."""

    __slots__ = ("_buf",)

    def __init__(self, protocol_label: bytes):
        import ctypes

        self._buf = ctypes.create_string_buffer(203)
        _NATIVE.strobe_new(self._buf, protocol_label, len(protocol_label))

    def meta_ad(self, data: bytes, more: bool) -> None:
        _NATIVE.strobe_meta_ad(self._buf, data, len(data), int(more))

    def ad(self, data: bytes, more: bool) -> None:
        _NATIVE.strobe_ad(self._buf, data, len(data), int(more))

    def prf(self, n: int, more: bool = False) -> bytes:
        import ctypes

        out = ctypes.create_string_buffer(n)
        _NATIVE.strobe_prf(self._buf, out, n, int(more))
        return out.raw

    def key(self, data: bytes, more: bool = False) -> None:
        _NATIVE.strobe_key(self._buf, data, len(data), int(more))


class Strobe128:
    def __new__(cls, protocol_label: bytes = b""):
        # default arg keeps copy.deepcopy (Transcript.clone in the pure-
        # Python fallback) working: deepcopy reconstructs via __new__(cls)
        if cls is Strobe128 and _NATIVE is not None:
            return _NativeStrobe128(protocol_label)
        return super().__new__(cls)

    def __init__(self, protocol_label: bytes):
        self.state = bytearray(200)
        seed = b"\x01" + bytes([_STROBE_R + 2]) + b"\x01\x00\x01\x60" + b"STROBEv1.0.2"
        self.state[: len(seed)] = seed
        keccak_f1600(self.state)
        self.pos = 0
        self.pos_begin = 0
        self.cur_flags = 0
        self.meta_ad(protocol_label, False)

    # --- duplex plumbing (merlin strobe128.rs)

    def _run_f(self) -> None:
        self.state[self.pos] ^= self.pos_begin
        self.state[self.pos + 1] ^= 0x04
        self.state[_STROBE_R + 1] ^= 0x80
        keccak_f1600(self.state)
        self.pos = 0
        self.pos_begin = 0

    def _absorb(self, data: bytes) -> None:
        for byte in data:
            self.state[self.pos] ^= byte
            self.pos += 1
            if self.pos == _STROBE_R:
                self._run_f()

    def _squeeze(self, n: int) -> bytes:
        out = bytearray()
        for _ in range(n):
            out.append(self.state[self.pos])
            self.state[self.pos] = 0
            self.pos += 1
            if self.pos == _STROBE_R:
                self._run_f()
        return bytes(out)

    def _begin_op(self, flags: int, more: bool) -> None:
        if more:
            assert self.cur_flags == flags, "STROBE: inconsistent `more` flags"
            return
        assert not (flags & _FLAG_T), "STROBE: T flag not implemented (no transport)"
        old_begin = self.pos_begin
        self.pos_begin = self.pos + 1
        self.cur_flags = flags
        self._absorb(bytes([old_begin, flags]))
        force_f = bool(flags & (_FLAG_C | _FLAG_K))
        if force_f and self.pos != 0:
            self._run_f()

    # --- merlin's three ops

    def meta_ad(self, data: bytes, more: bool) -> None:
        self._begin_op(_FLAG_M | _FLAG_A, more)
        self._absorb(data)

    def ad(self, data: bytes, more: bool) -> None:
        self._begin_op(_FLAG_A, more)
        self._absorb(data)

    def prf(self, n: int, more: bool = False) -> bytes:
        self._begin_op(_FLAG_I | _FLAG_A | _FLAG_C, more)
        return self._squeeze(n)

    def key(self, data: bytes, more: bool = False) -> None:
        self._begin_op(_FLAG_A | _FLAG_C, more)
        # KEY overwrites (duplex override), per strobe128.rs overwrite
        for byte in data:
            self.state[self.pos] = byte
            self.pos += 1
            if self.pos == _STROBE_R:
                self._run_f()


class BatchStrobe128:
    """N STROBE-128 sponges advancing in lockstep — the batch-axis analog
    of Strobe128 for transcripts whose OP SEQUENCE is identical across
    rows (every verification challenge of a commit runs the same Merlin
    ops; only the absorbed bytes differ per row). State is an (N, 200)
    uint8 array whose (N, 25)-uint64 view advances under ONE batched
    Keccak-f[1600] permutation (ops/hashvec.py: native SIMD when
    available, else the numpy batch rung). pos/pos_begin/cur_flags stay
    scalars because the op sequence — and therefore every duplex
    position — is shared by construction.

    Bit-for-bit equal to Strobe128 on every row (tests/test_hashvec.py
    fuzzes arbitrary op sequences against the serial class)."""

    __slots__ = ("n", "state", "pos", "pos_begin", "cur_flags")

    def __init__(self, n: int, protocol_label: bytes):
        import numpy as np

        self.n = n
        self.state = np.zeros((n, 200), dtype=np.uint8)
        seed = (b"\x01" + bytes([_STROBE_R + 2]) + b"\x01\x00\x01\x60"
                + b"STROBEv1.0.2")
        self.state[:, :len(seed)] = np.frombuffer(seed, dtype=np.uint8)
        self._perm()
        self.pos = 0
        self.pos_begin = 0
        self.cur_flags = 0
        self.meta_ad(protocol_label, False)

    @classmethod
    def from_snapshot(cls, n: int, snap: tuple) -> "BatchStrobe128":
        """Broadcast a single-row snapshot (shared transcript prefix) to
        N lockstep rows."""
        import numpy as np

        bs = cls.__new__(cls)
        state_row, bs.pos, bs.pos_begin, bs.cur_flags = snap
        bs.n = n
        bs.state = np.broadcast_to(state_row, (n, 200)).copy()
        return bs

    def snapshot(self) -> tuple:
        """Row-0 state + duplex position (rows are identical until
        row-dependent data is absorbed)."""
        return (self.state[0].copy(), self.pos, self.pos_begin,
                self.cur_flags)

    # --- duplex plumbing (mirrors Strobe128 exactly)

    def _perm(self) -> None:
        from cometbft_tpu.ops import hashvec

        hashvec.keccak_f1600_many(self.state.view("<u8"))

    def _run_f(self) -> None:
        self.state[:, self.pos] ^= self.pos_begin
        self.state[:, self.pos + 1] ^= 0x04
        self.state[:, _STROBE_R + 1] ^= 0x80
        self._perm()
        self.pos = 0
        self.pos_begin = 0

    def _chunks(self, m: int):
        """Yield (offset, count) absorb/squeeze spans between permutation
        boundaries — the batched replacement for the per-byte loop."""
        off = 0
        while off < m:
            c = min(_STROBE_R - self.pos, m - off)
            yield off, c
            self.pos += c
            off += c
            if self.pos == _STROBE_R:
                self._run_f()

    def _as_rows(self, data):
        """bytes (broadcast to all rows) or (N, m) uint8 array."""
        import numpy as np

        if isinstance(data, (bytes, bytearray)):
            return np.frombuffer(bytes(data), dtype=np.uint8)[None, :], len(data)
        assert data.shape[0] == self.n and data.dtype == np.uint8
        return data, data.shape[1]

    def _absorb(self, data) -> None:
        rows, m = self._as_rows(data)
        for off, c in self._chunks(m):
            self.state[:, self.pos:self.pos + c] ^= rows[:, off:off + c]

    def _begin_op(self, flags: int, more: bool) -> None:
        if more:
            assert self.cur_flags == flags, "STROBE: inconsistent `more` flags"
            return
        assert not (flags & _FLAG_T), "STROBE: T flag not implemented"
        old_begin = self.pos_begin
        self.pos_begin = self.pos + 1
        self.cur_flags = flags
        self._absorb(bytes([old_begin, flags]))
        if (flags & (_FLAG_C | _FLAG_K)) and self.pos != 0:
            self._run_f()

    # --- merlin's ops

    def meta_ad(self, data, more: bool) -> None:
        self._begin_op(_FLAG_M | _FLAG_A, more)
        self._absorb(data)

    def ad(self, data, more: bool) -> None:
        self._begin_op(_FLAG_A, more)
        self._absorb(data)

    def prf(self, n: int, more: bool = False):
        import numpy as np

        self._begin_op(_FLAG_I | _FLAG_A | _FLAG_C, more)
        out = np.empty((self.n, n), dtype=np.uint8)
        for off, c in self._chunks(n):
            out[:, off:off + c] = self.state[:, self.pos:self.pos + c]
            self.state[:, self.pos:self.pos + c] = 0
        return out

    def key(self, data, more: bool = False) -> None:
        self._begin_op(_FLAG_A | _FLAG_C, more)
        rows, m = self._as_rows(data)
        for off, c in self._chunks(m):
            self.state[:, self.pos:self.pos + c] = rows[:, off:off + c]


class Transcript:
    """merlin::Transcript."""

    MERLIN_LABEL = b"Merlin v1.0"

    def __init__(self, label: bytes):
        self.strobe = Strobe128(self.MERLIN_LABEL)
        self.append_message(b"dom-sep", label)

    def append_message(self, label: bytes, message: bytes) -> None:
        self.strobe.meta_ad(label, False)
        self.strobe.meta_ad(len(message).to_bytes(4, "little"), True)
        self.strobe.ad(message, False)

    def append_u64(self, label: bytes, v: int) -> None:
        self.append_message(label, v.to_bytes(8, "little"))

    def challenge_bytes(self, label: bytes, n: int) -> bytes:
        self.strobe.meta_ad(label, False)
        self.strobe.meta_ad(n.to_bytes(4, "little"), True)
        return self.strobe.prf(n)

    def clone(self) -> "Transcript":
        import copy

        t = Transcript.__new__(Transcript)
        t.strobe = copy.deepcopy(self.strobe)
        return t

    # --- schnorrkel extensions (schnorrkel/src/context.rs)

    def proto_name(self, label: bytes) -> None:
        self.append_message(b"proto-name", label)

    def append_point(self, label: bytes, point_bytes: bytes) -> None:
        self.append_message(label, point_bytes)

    def challenge_scalar(self, label: bytes) -> int:
        return int.from_bytes(self.challenge_bytes(label, 64), "little") % L

    def witness_scalar(self, label: bytes, nonce_seed: bytes) -> int:
        """schnorrkel witness_scalar: fork the transcript via STROBE rekey
        with the nonce seed + RNG. Deterministic-with-randomness in
        schnorrkel; deterministic here (witness hygiene does not affect
        verifier compat)."""
        import copy

        s = copy.deepcopy(self.strobe)
        s.meta_ad(b"", False)
        s.meta_ad(label, True)
        s.key(nonce_seed, False)
        s.key(_secrets.token_bytes(32), False)
        s.meta_ad((64).to_bytes(4, "little"), False)
        return int.from_bytes(s.prf(64), "little") % L


# ---------------------------------------------------------------------------
# ristretto255 encode/decode over the ed25519 oracle's extended coordinates
# ---------------------------------------------------------------------------

SQRT_M1 = pow(2, (P - 1) // 4, P)


def _sqrt_ratio_m1(u: int, v: int) -> tuple[bool, int]:
    """(was_square, sqrt(u/v) or sqrt(i*u/v)), nonnegative root
    (ristretto255 spec SQRT_RATIO_M1)."""
    v3 = v * v % P * v % P
    v7 = v3 * v3 % P * v % P
    r = u * v3 % P * pow(u * v7 % P, (P - 5) // 8, P) % P
    check = v * r % P * r % P
    correct = check == u % P
    flipped = check == (-u) % P
    flipped_i = check == (-u) % P * SQRT_M1 % P
    if flipped or flipped_i:
        r = r * SQRT_M1 % P
    was_square = correct or flipped
    if r % 2 == 1:  # CT_ABS: take the nonnegative (even) root
        r = (-r) % P
    return was_square, r


# invsqrt(a - d), a = -1: the nonnegative root of 1/(a-d)
INVSQRT_A_MINUS_D = _sqrt_ratio_m1(1, (-1 - D) % P)[1]


def ristretto_decode(b: bytes) -> tuple[int, int, int, int] | None:
    """32 bytes -> extended point, or None (spec DECODE)."""
    if len(b) != 32:
        return None
    s = int.from_bytes(b, "little")
    if s >= P or s % 2 == 1:  # canonical and nonnegative
        return None
    ss = s * s % P
    u1 = (1 - ss) % P
    u2 = (1 + ss) % P
    u2_sqr = u2 * u2 % P
    v = (-(D * u1 % P * u1) - u2_sqr) % P
    was_square, invsqrt = _sqrt_ratio_m1(1, v * u2_sqr % P)
    den_x = invsqrt * u2 % P
    den_y = invsqrt * den_x % P * v % P
    x = 2 * s % P * den_x % P
    if x % 2 == 1:
        x = (-x) % P
    y = u1 * den_y % P
    t = x * y % P
    if not was_square or t % 2 == 1 or y == 0:
        return None
    return (x, y, 1, t)


def ristretto_encode(pt: tuple[int, int, int, int]) -> bytes:
    """Extended point -> canonical 32 bytes (spec ENCODE)."""
    x0, y0, z0, t0 = pt
    u1 = (z0 + y0) * (z0 - y0) % P
    u2 = x0 * y0 % P
    _, invsqrt = _sqrt_ratio_m1(1, u1 * u2 % P * u2 % P)
    den1 = invsqrt * u1 % P
    den2 = invsqrt * u2 % P
    z_inv = den1 * den2 % P * t0 % P
    ix0 = x0 * SQRT_M1 % P
    iy0 = y0 * SQRT_M1 % P
    enchanted_denominator = den1 * INVSQRT_A_MINUS_D % P
    rotate = (t0 * z_inv % P) % 2 == 1
    if rotate:
        x, y = iy0, ix0
        den_inv = enchanted_denominator
    else:
        x, y = x0, y0
        den_inv = den2
    if (x * z_inv % P) % 2 == 1:
        y = (-y) % P
    s = (z0 - y) * den_inv % P
    if s % 2 == 1:
        s = (-s) % P
    return s.to_bytes(32, "little")


def ristretto_basepoint_table():
    return ed.B_POINT


# ---------------------------------------------------------------------------
# schnorrkel keys + sign/verify (signing context = b"" as the reference,
# privkey.go:17 signingCtx = sr25519.NewSigningContext([]byte{}))
# ---------------------------------------------------------------------------

SIGNING_CTX = b"substrate"  # NOTE: reference uses empty ctx; see make_transcript


def make_signing_transcript(msg: bytes, ctx: bytes = b"") -> Transcript:
    """sr25519.NewSigningContext(ctx).NewTranscriptBytes(msg)
    (schnorrkel signing_context(ctx).bytes(msg))."""
    t = Transcript(b"SigningContext")
    t.append_message(b"", ctx)
    t.append_message(b"sign-bytes", msg)
    return t


def expand_ed25519(mini: bytes) -> tuple[int, bytes]:
    """MiniSecretKey.ExpandEd25519: scalar = clamp(sha512(mini)[:32]) >> 3
    ('divided by cofactor' — schnorrkel keeps the ed25519 bit layout
    compatible), nonce = sha512(mini)[32:]."""
    h = hashlib.sha512(mini).digest()
    key = bytearray(h[:32])
    key[0] &= 248
    key[31] &= 63
    key[31] |= 64
    scalar = int.from_bytes(bytes(key), "little") >> 3
    return scalar % L, h[32:]


def keypair_from_mini(mini: bytes) -> tuple[int, bytes, bytes]:
    """-> (secret scalar, nonce, public ristretto bytes)."""
    scalar, nonce = expand_ed25519(mini)
    pub = ristretto_encode(ed.scalar_mult(scalar, ed.B_POINT))
    return scalar, nonce, pub


def sign(mini_or_pair, msg: bytes) -> bytes:
    """64-byte schnorrkel signature: R(32) || s(32) with bit 255 set."""
    if isinstance(mini_or_pair, bytes):
        scalar, nonce, pub = keypair_from_mini(mini_or_pair)
    else:
        scalar, nonce, pub = mini_or_pair
    t = make_signing_transcript(msg)
    t.proto_name(b"Schnorr-sig")
    t.append_point(b"sign:pk", pub)
    r = t.witness_scalar(b"signing", nonce)
    r_point = ed.scalar_mult(r, ed.B_POINT)
    r_bytes = ristretto_encode(r_point)
    t.append_point(b"sign:R", r_bytes)
    k = t.challenge_scalar(b"sign:c")
    s = (k * scalar + r) % L
    sig = bytearray(r_bytes + s.to_bytes(32, "little"))
    sig[63] |= 128  # schnorrkel "not-ed25519" marker
    return bytes(sig)


def parse_signature(sig: bytes) -> tuple[bytes, int] | None:
    """-> (R bytes, s) or None. The marker bit must be set (schnorrkel
    rejects unmarked signatures) and s must be canonical."""
    if len(sig) != 64 or not sig[63] & 128:
        return None
    s_bytes = bytearray(sig[32:])
    s_bytes[31] &= 127
    s = int.from_bytes(bytes(s_bytes), "little")
    if s >= L:
        return None
    return sig[:32], s


def compute_challenge(pub: bytes, r_bytes: bytes, msg: bytes) -> int:
    t = make_signing_transcript(msg)
    t.proto_name(b"Schnorr-sig")
    t.append_point(b"sign:pk", pub)
    t.append_point(b"sign:R", r_bytes)
    return t.challenge_scalar(b"sign:c")


# shared transcript prefix per message length: everything up to (and
# including) the "sign-bytes" length header is row-independent, so it runs
# once on a 1-row batch sponge and broadcasts (bounded cache; commit
# sign-bytes lengths are few per chain)
_PREFIX_CACHE: dict[int, tuple] = {}


def _signing_prefix(mlen: int) -> tuple:
    snap = _PREFIX_CACHE.get(mlen)
    if snap is None:
        bs = BatchStrobe128(1, Transcript.MERLIN_LABEL)
        for label, msg in ((b"dom-sep", b"SigningContext"), (b"", b"")):
            bs.meta_ad(label, False)
            bs.meta_ad(len(msg).to_bytes(4, "little"), True)
            bs.ad(msg, False)
        bs.meta_ad(b"sign-bytes", False)
        bs.meta_ad(mlen.to_bytes(4, "little"), True)
        snap = bs.snapshot()
        if len(_PREFIX_CACHE) >= 256:
            _PREFIX_CACHE.pop(next(iter(_PREFIX_CACHE)))
        _PREFIX_CACHE[mlen] = snap
    return snap


def _batch_challenge_digests(pub_rows, r_rows, msg_rows):
    """(N, 32)/(N, 32)/(N, mlen) uint8 rows -> (N, 64) uint8 challenge
    bytes: the whole Merlin verification transcript advanced in lockstep,
    two batched permutations per row instead of a per-row sponge."""
    n = pub_rows.shape[0]
    bs = BatchStrobe128.from_snapshot(n, _signing_prefix(msg_rows.shape[1]))
    bs.ad(msg_rows, False)
    for label, msg in ((b"proto-name", b"Schnorr-sig"),):
        bs.meta_ad(label, False)
        bs.meta_ad(len(msg).to_bytes(4, "little"), True)
        bs.ad(msg, False)
    for label, rows in ((b"sign:pk", pub_rows), (b"sign:R", r_rows)):
        bs.meta_ad(label, False)
        bs.meta_ad((32).to_bytes(4, "little"), True)
        bs.ad(rows, False)
    bs.meta_ad(b"sign:c", False)
    bs.meta_ad((64).to_bytes(4, "little"), True)
    return bs.prf(64)


def batch_challenge_words(
    pubs: list[bytes], r_list: list[bytes], msgs: list[bytes]
):
    """All N verification challenges as packed (N, 8) uint32 device words
    (k mod L, little-endian) — the staging fast path. Rows group by
    message length; each group of VEC_MIN_ROWS+ advances under the batch
    STROBE transcript (one permutation call per duplex boundary for the
    WHOLE group); ragged stragglers fall back to the serial rung
    (native strobe.c batch, else per-row Python). Bit-for-bit equal to
    compute_challenge on every row."""
    import numpy as np

    n = len(pubs)
    r_rows = (np.frombuffer(b"".join(r_list), dtype=np.uint8).reshape(n, 32)
              if n else np.zeros((0, 32), dtype=np.uint8))
    return batch_challenge_words_rows(pubs, r_rows, msgs)


def batch_challenge_words_rows(pubs: list[bytes], r_rows, msgs: list[bytes]):
    """Array-native batch_challenge_words: R as the staged (N, 32) uint8
    signature halves (no per-row bytes round trip — sr25519_kernel's
    staging path feeds signature rows straight in)."""
    import numpy as np

    from cometbft_tpu.ops import hashvec

    n = len(pubs)
    out = np.zeros((n, 8), dtype=np.uint32)
    if n == 0:
        return out
    by_len: dict[int, list[int]] = {}
    for i, m in enumerate(msgs):
        by_len.setdefault(len(m), []).append(i)
    for mlen, idxs in by_len.items():
        if (len(idxs) < hashvec.VEC_MIN_ROWS
                or os.environ.get("CBFT_HASHVEC") == "serial"):
            ks = _serial_compute_challenges(
                [pubs[i] for i in idxs], [r_rows[i].tobytes() for i in idxs],
                [msgs[i] for i in idxs])
            blob = b"".join(k.to_bytes(32, "little") for k in ks)
            out[np.asarray(idxs, dtype=np.intp)] = np.frombuffer(
                blob, dtype=np.uint8).reshape(len(idxs), 32).view("<u4")
            continue
        sel = np.asarray(idxs, dtype=np.intp)
        pub_rows = np.frombuffer(
            b"".join(pubs[i] for i in idxs), dtype=np.uint8).reshape(-1, 32)
        msg_rows = np.frombuffer(
            b"".join(msgs[i] for i in idxs), dtype=np.uint8).reshape(-1, mlen)
        digests = _batch_challenge_digests(
            pub_rows, np.ascontiguousarray(r_rows[sel]), msg_rows)
        out[sel] = hashvec.reduce512_mod_l(digests)
    return out


def batch_compute_challenges(
    pubs: list[bytes], r_list: list[bytes], msgs: list[bytes]
) -> list[int]:
    """All N verification challenges as ints. Routed through the batch
    STROBE transcript (batch_challenge_words) for uniform-length groups;
    serial rung otherwise. Equivalence with compute_challenge is asserted
    by tests/test_sr25519.py and tests/test_hashvec.py."""
    n = len(pubs)
    if n == 0:
        return []
    blob = batch_challenge_words(pubs, r_list, msgs).tobytes()
    return [int.from_bytes(blob[32 * i: 32 * i + 32], "little")
            for i in range(n)]


def _serial_compute_challenges(
    pubs: list[bytes], r_list: list[bytes], msgs: list[bytes]
) -> list[int]:
    """The serial rung: one native call for the whole batch (strobe.c
    sr25519_batch_challenge — the whole Merlin transcript per row runs in
    C, so the per-row cost is keccak-bound, not ctypes-bound), else the
    per-row Python path."""
    n = len(pubs)
    if n == 0:
        return []
    if _NATIVE is None or not hasattr(_NATIVE, "sr25519_batch_challenge"):
        return [compute_challenge(p, r, m)
                for p, r, m in zip(pubs, r_list, msgs)]
    import ctypes

    import numpy as np

    msg_buf = b"".join(msgs)
    offs = np.zeros(n + 1, dtype=np.int64)
    np.cumsum([len(m) for m in msgs], out=offs[1:])
    pub_buf = b"".join(pubs)
    r_buf = b"".join(r_list)
    out = ctypes.create_string_buffer(64 * n)
    offs_p = offs.ctypes.data_as(ctypes.POINTER(ctypes.c_int64))

    def run_range(start: int, count: int) -> None:
        # ctypes releases the GIL for the duration of the C call, so
        # chunks keccak in parallel on real cores
        _NATIVE.sr25519_batch_challenge(
            pub_buf[32 * start:], r_buf[32 * start:], msg_buf,
            ctypes.cast(ctypes.byref(offs_p.contents, 8 * start),
                        ctypes.POINTER(ctypes.c_int64)),
            count, ctypes.cast(ctypes.byref(out, 64 * start),
                               ctypes.POINTER(ctypes.c_char)))

    workers = min(4, max(1, n // 512))
    if workers > 1:
        import concurrent.futures

        step = (n + workers - 1) // workers
        with concurrent.futures.ThreadPoolExecutor(workers) as ex:
            list(ex.map(lambda s: run_range(s, min(step, n - s)),
                        range(0, n, step)))
    else:
        run_range(0, n)
    raw = out.raw
    return [int.from_bytes(raw[64 * i: 64 * i + 64], "little") % L
            for i in range(n)]


def verify(pub: bytes, msg: bytes, sig: bytes) -> bool:
    parsed = parse_signature(sig)
    if parsed is None:
        return False
    r_bytes, s = parsed
    a_pt = ristretto_decode(pub)
    r_pt = ristretto_decode(r_bytes)
    if a_pt is None or r_pt is None:
        return False
    k = compute_challenge(pub, r_bytes, msg)
    # [4](sB - kA - R) == O  <=>  ristretto equality sB - kA == R
    sb = ed.scalar_mult(s, ed.B_POINT)
    ka = ed.scalar_mult(k, a_pt)
    diff = ed.point_add(sb, ed.point_neg(ka))
    diff = ed.point_add(diff, ed.point_neg(r_pt))
    quad = ed.point_double(ed.point_double(diff))
    return ed.is_identity(quad)


def gen_mini() -> bytes:
    return _secrets.token_bytes(32)
